"""Versioned, integrity-checked serialization of solver state.

Bundle layout (one directory per bundle)::

    <dir>/
      MANIFEST.json            # written LAST, atomically — the commit point
      <name>.npy               # one file per array, each written tmp+rename
      structure.pkl            # pickled host structures (symbolic + plan)

``MANIFEST.json`` carries ``{format, version, kind, meta, arrays}`` where
``arrays[name]`` records the file name, byte length and sha256 digest of
every artifact.  A bundle is readable iff the manifest parses, the
version is known, and every artifact matches its digest — anything else
raises a structured :class:`CheckpointError` subclass instead of handing
back garbage factors.  Because the manifest is replaced last and every
artifact is written to a temp name first, an interrupted writer always
leaves either the previous consistent bundle or no manifest at all
(crash consistency by construction — the same tmp+rename discipline the
obs tracer uses for its artifacts).

Versioning rule (docs/RELIABILITY.md): readers accept exactly the
versions they know how to decode; ``version`` bumps on any layout or
semantic change, and unknown versions raise
:class:`CheckpointVersionError` rather than guessing.

Int-width / precision portability: every array is stored with its exact
dtype (``.npy`` self-describes), so a bundle saved under
``SLU_TPU_INT64=0`` loads bit-identically under ``SLU_TPU_INT64=1`` and
vice versa — the plan's index maps are int64 on every config, and the
factors' dtype travels in the meta block (f32/f64/c128 and the df64
path's recombined f64 factors all round-trip bitwise;
tests/test_persist.py pins this).
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import pickle

import numpy as np

from superlu_dist_tpu.utils.errors import (
    CheckpointCorruptError, CheckpointError, CheckpointVersionError)

FORMAT = "slu-tpu-persist"
FORMAT_VERSION = 1
MANIFEST = "MANIFEST.json"


# ---------------------------------------------------------------------------
# bundle primitives
# ---------------------------------------------------------------------------

def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _atomic_write(path: str, data: bytes) -> None:
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _npy_bytes(arr: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.save(buf, np.ascontiguousarray(arr), allow_pickle=False)
    return buf.getvalue()


def write_array(dirpath: str, name: str, arr: np.ndarray,
                entries: dict, skip_existing: bool = False) -> None:
    """Write one ``.npy`` artifact (tmp+rename) and record it in the
    manifest's ``entries`` dict.  ``skip_existing`` lets an advancing
    checkpoint reuse immutable artifacts already on disk (the digest in
    ``entries`` must then come from the previous manifest entry)."""
    fname = f"{name}.npy"
    path = os.path.join(dirpath, fname)
    if skip_existing and name in entries and os.path.exists(path):
        return
    data = _npy_bytes(arr)
    _atomic_write(path, data)
    entries[name] = {"file": fname, "bytes": len(data),
                     "sha256": _sha256(data),
                     "dtype": str(arr.dtype), "shape": list(arr.shape)}


def write_blob(dirpath: str, name: str, data: bytes, entries: dict) -> None:
    path = os.path.join(dirpath, name)
    _atomic_write(path, data)
    entries[name] = {"file": name, "bytes": len(data),
                     "sha256": _sha256(data)}


def write_manifest(dirpath: str, kind: str, meta: dict,
                   entries: dict) -> str:
    doc = {"format": FORMAT, "version": FORMAT_VERSION, "kind": kind,
           "meta": meta, "arrays": entries}
    _atomic_write(os.path.join(dirpath, MANIFEST),
                  json.dumps(doc, sort_keys=True).encode())
    return dirpath


def write_bundle(dirpath: str, kind: str, meta: dict,
                 arrays: dict, blobs: dict | None = None) -> str:
    """Write a whole bundle: every array, every blob, then the manifest
    (the commit point).  Returns ``dirpath``."""
    os.makedirs(dirpath, exist_ok=True)
    entries: dict = {}
    for name, arr in arrays.items():
        write_array(dirpath, name, np.asarray(arr), entries)
    for name, data in (blobs or {}).items():
        write_blob(dirpath, name, data, entries)
    return write_manifest(dirpath, kind, meta, entries)


def read_manifest(dirpath: str, kind: str | None = None) -> dict:
    mpath = os.path.join(dirpath, MANIFEST)
    if not os.path.isdir(dirpath) or not os.path.exists(mpath):
        raise CheckpointError(
            f"no persisted bundle at {dirpath!r} (missing {MANIFEST} — "
            "either the path is wrong or a writer died before its first "
            "commit point)")
    try:
        doc = json.loads(open(mpath, "rb").read().decode())
    except Exception as e:
        raise CheckpointCorruptError(
            f"unreadable manifest {mpath!r}: {type(e).__name__}: {e}")
    if doc.get("format") != FORMAT:
        raise CheckpointError(
            f"{mpath!r} is not a {FORMAT} bundle (format="
            f"{doc.get('format')!r})")
    if doc.get("version") != FORMAT_VERSION:
        raise CheckpointVersionError(
            f"bundle version {doc.get('version')!r} at {dirpath!r} is not "
            f"readable by this build (expected {FORMAT_VERSION}) — see the "
            "versioning rules in docs/RELIABILITY.md")
    if kind is not None and doc.get("kind") != kind:
        raise CheckpointError(
            f"bundle at {dirpath!r} is kind={doc.get('kind')!r}, "
            f"expected {kind!r}")
    return doc


def _read_artifact(dirpath: str, name: str, ent: dict) -> bytes:
    path = os.path.join(dirpath, ent["file"])
    try:
        data = open(path, "rb").read()
    except OSError as e:
        raise CheckpointCorruptError(
            f"artifact {name!r} missing/unreadable at {path!r}: {e}")
    if len(data) != ent["bytes"]:
        raise CheckpointCorruptError(
            f"artifact {name!r} at {path!r} is truncated: "
            f"{len(data)} bytes on disk vs {ent['bytes']} in the manifest")
    if _sha256(data) != ent["sha256"]:
        raise CheckpointCorruptError(
            f"artifact {name!r} at {path!r} failed its sha256 digest "
            "check — the bundle is corrupt (refusing to return garbage "
            "factors)")
    return data


def read_array(dirpath: str, name: str, doc: dict) -> np.ndarray:
    ent = doc["arrays"].get(name)
    if ent is None:
        raise CheckpointCorruptError(
            f"manifest at {dirpath!r} has no artifact named {name!r}")
    data = _read_artifact(dirpath, name, ent)
    try:
        return np.load(io.BytesIO(data), allow_pickle=False)
    except Exception as e:
        raise CheckpointCorruptError(
            f"artifact {name!r} at {dirpath!r} is not a valid .npy "
            f"payload: {type(e).__name__}: {e}")


def read_blob(dirpath: str, name: str, doc: dict) -> bytes:
    ent = doc["arrays"].get(name)
    if ent is None:
        raise CheckpointCorruptError(
            f"manifest at {dirpath!r} has no artifact named {name!r}")
    return _read_artifact(dirpath, name, ent)


def read_bundle(dirpath: str, kind: str | None = None):
    """Read and fully verify a bundle.  Returns ``(doc, arrays)`` where
    ``arrays`` maps each ``.npy`` artifact name to its ndarray (blobs are
    left to :func:`read_blob` — callers decide whether to unpickle)."""
    doc = read_manifest(dirpath, kind=kind)
    arrays = {name: read_array(dirpath, name, doc)
              for name, ent in doc["arrays"].items()
              if ent["file"].endswith(".npy")}
    return doc, arrays


# ---------------------------------------------------------------------------
# identity fingerprints
# ---------------------------------------------------------------------------

def plan_fingerprint(plan) -> str:
    """Structural identity of a FactorPlan: the dispatch-group geometry,
    batch membership, pool layout and assembly maps.  Two plans with the
    same fingerprint run the identical kernel/dispatch sequence, which is
    the precondition for splicing a checkpointed frontier into a fresh
    run (resume) — the schedule knobs, bucket geometry and amalgamation
    all fold into these arrays, so they need no separate encoding."""
    h = hashlib.sha256()
    h.update(f"n={plan.n};pool={plan.pool_size};"
             f"sched={plan.schedule};groups={len(plan.groups)};".encode())
    for grp in plan.groups:
        h.update(np.int64([grp.level, grp.m, grp.w, grp.u,
                           grp.batch]).tobytes())
        h.update(np.ascontiguousarray(grp.sns, dtype=np.int64).tobytes())
        h.update(np.ascontiguousarray(grp.ws, dtype=np.int64).tobytes())
        h.update(np.ascontiguousarray(grp.off, dtype=np.int64).tobytes())
        h.update(np.ascontiguousarray(grp.a_src, dtype=np.int64).tobytes())
        for cs in grp.children:
            h.update(np.int64([cs.ub]).tobytes())
            h.update(np.ascontiguousarray(cs.child_off,
                                          dtype=np.int64).tobytes())
    return h.hexdigest()


def pattern_digest(indptr, indices) -> str:
    """Identity of a symmetrized-permuted sparsity pattern: sha256 over
    the CSR structure arrays (widths canonicalized to int64, so the
    digest is int-width portable like the bundles themselves).  This is
    the refactor pipeline's pattern key (``drivers/gssvx.refactor``):
    two handles/bundles with equal digests were analyzed on the SAME
    structure and may share symbolic + plan + compiled programs, paying
    only the numeric phase — drift raises ``PatternMismatchError``
    instead of silently re-running symbolic."""
    h = hashlib.sha256()
    h.update(np.ascontiguousarray(indptr, dtype=np.int64).tobytes())
    h.update(np.ascontiguousarray(indices, dtype=np.int64).tobytes())
    return h.hexdigest()


def dtype_str(dtype) -> str:
    """Canonical dtype name, tolerating extension dtypes (bfloat16)
    numpy's constructor rejects."""
    try:
        return str(np.dtype(dtype))
    except TypeError:
        return str(dtype)


def values_digest(pattern_values, dtype, thresh, gemm_prec: str = "") -> str:
    """Identity of the NUMERIC inputs a frontier was computed from: the
    structurally-permuted value array, factor dtype, GESP threshold and
    the GEMM-precision ladder tier (``gemm_prec``; "" = unspecified —
    callers on the driver path pass the resolved tier, since a bf16
    frontier spliced under highest arithmetic is exactly the stale-
    arithmetic splice this digest exists to refuse).  A resume against
    different values is refused via CheckpointMismatchError."""
    h = hashlib.sha256()
    v = np.ascontiguousarray(np.asarray(pattern_values))
    h.update(str(v.dtype).encode())
    h.update(v.tobytes())
    h.update(dtype_str(dtype).encode())
    h.update(np.float64(float(np.real(thresh))).tobytes())
    if gemm_prec:
        # appended only when specified, so tier-less callers (tests,
        # tooling) keep their historical digests
        h.update(f";gemm={gemm_prec}".encode())
    return h.hexdigest()


def front_digest(arr) -> str:
    """sha256 of one front panel's canonical ``.npy`` payload — the SAME
    digest ``save_lu`` records in a bundle manifest, computable from a
    live (device-resident) panel stack via one D2H pull.  This is the
    unit the serving tier's factor-integrity scrubber compares
    (serve/server.py ``scrub_now``): byte-for-byte, so any bit flip in
    the resident factors — not just NaN-producing ones — mismatches."""
    return _sha256(_npy_bytes(np.asarray(arr)))


def front_digests(fronts) -> list:
    """Per-front ``(sha256_L, sha256_U)`` digests of a live handle's
    panel stacks, in group order — the construction-time ground truth
    for scrubbing a handle that was never persisted."""
    return [(front_digest(lp), front_digest(up)) for lp, up in fronts]


def bundle_front_digests(dirpath: str) -> list:
    """Per-front ``(sha256_L, sha256_U)`` digests straight from a
    persisted LU bundle's manifest — no array reads, no digest work:
    the DURABLE ground truth a scrubber verifies resident factors
    against (a corrupted manifest already fails ``read_manifest``)."""
    doc = read_manifest(dirpath, kind="lu_handle")
    ent = doc["arrays"]
    out = []
    for g in range(int(doc["meta"]["n_groups"])):
        try:
            out.append((ent[f"front_{g:05d}_l"]["sha256"],
                        ent[f"front_{g:05d}_u"]["sha256"]))
        except KeyError:
            raise CheckpointCorruptError(
                f"bundle at {dirpath!r} is missing the manifest entry "
                f"for front group {g} — cannot establish a scrub "
                "baseline")
    return out


# ---------------------------------------------------------------------------
# LU handle save / load
# ---------------------------------------------------------------------------

def _host_fronts(numeric):
    return [(np.asarray(lp), np.asarray(up)) for lp, up in numeric.fronts]


def save_lu(lu, dirpath: str) -> str:
    """Persist a factored :class:`LUFactorization` handle.

    Saved: the scaling/permutation transforms, the symbolic fact + plan
    (one digest-checked pickle blob — they are already the structures
    the distributed tier ships over ``bcast_obj``), and every numeric
    front as its own digest-checked ``.npy`` pair.  NOT saved: the
    original matrix ``a`` (refinement needs a fresh one anyway — pass it
    to ``gssvx(Fact.FACTORED, a, b, lu=loaded)``) and the volatile
    device-side caches, which rebuild lazily.
    """
    if lu.numeric is None:
        raise CheckpointError("save_lu requires a factored handle "
                              "(lu.numeric is None — run the "
                              "factorization first)")
    numeric = lu.numeric
    fronts = _host_fronts(numeric)
    os.makedirs(dirpath, exist_ok=True)
    entries: dict = {}
    arrays = {"dr": lu.dr, "dc": lu.dc, "r1": lu.r1, "c1": lu.c1,
              "row_order": lu.row_order}
    if lu.col_order is not None:
        arrays["col_order"] = lu.col_order
    if lu.a_sym_indptr is not None:
        arrays["a_sym_indptr"] = lu.a_sym_indptr
        arrays["a_sym_indices"] = lu.a_sym_indices
    for name, arr in arrays.items():
        write_array(dirpath, name, np.asarray(arr), entries)
    for g, (lp, up) in enumerate(fronts):
        write_array(dirpath, f"front_{g:05d}_l", lp, entries)
        write_array(dirpath, f"front_{g:05d}_u", up, entries)
    blob = pickle.dumps((lu.sf, lu.plan),
                        protocol=pickle.HIGHEST_PROTOCOL)
    write_blob(dirpath, "structure.pkl", blob, entries)
    meta = {
        "n": int(lu.n),
        "equed": lu.equed,
        "anorm": float(lu.anorm),
        "factor_dtype": str(numeric.dtype),
        "tiny_pivots": int(numeric.tiny_pivots),
        "finite": bool(numeric.finite),
        "info_col": int(numeric.info_col),
        "n_groups": len(fronts),
        "plan_fingerprint": plan_fingerprint(lu.plan),
        "has_col_order": lu.col_order is not None,
        "has_sym_pattern": lu.a_sym_indptr is not None,
        # which GEMM-precision ladder tier the persisted factors were
        # computed at — a reloaded handle must not claim a higher tier
        # than it ran (the escalation rung and SolveReport read this)
        "gemm_precision": getattr(numeric, "gemm_prec", "highest"),
    }
    if lu.a_sym_indptr is not None:
        # pattern-keyed plan sharing (docs/RELIABILITY.md): bundles with
        # equal digests were analyzed on the same structure — a refactor
        # or a same-pattern sibling may reuse this bundle's symbolic +
        # plan + compiled programs wholesale, paying only numeric
        meta["pattern_digest"] = pattern_digest(lu.a_sym_indptr,
                                                lu.a_sym_indices)
    return write_manifest(dirpath, "lu_handle", meta, entries)


def lu_meta(dirpath: str) -> dict:
    """Manifest meta block of a persisted LU handle — a cheap peek (no
    array reads, no digest work) so a serving process can size queues
    and validate n/dtype before paying the full load (serve/server.py's
    from_bundle path).  Adds a computed ``nbytes`` key (the sum of
    every artifact's manifest byte length) so the fleet's handle cache
    (serve/handlecache.py) can budget residency BEFORE paying the
    load."""
    doc = read_manifest(dirpath, kind="lu_handle")
    meta = dict(doc["meta"])
    meta["nbytes"] = sum(int(e.get("bytes", 0))
                         for e in doc["arrays"].values())
    return meta


def load_lu(dirpath: str):
    """Load a persisted handle: verify every digest, rebuild the
    :class:`LUFactorization` with host-resident factors, and return it
    ready to solve (no refactorization; ``lu.a`` is None — supply the
    matrix when refinement is wanted)."""
    from superlu_dist_tpu.drivers.gssvx import LUFactorization
    from superlu_dist_tpu.numeric.factor import NumericFactorization
    from superlu_dist_tpu.utils.options import Options

    doc = read_manifest(dirpath, kind="lu_handle")
    meta = doc["meta"]
    try:
        sf, plan = pickle.loads(read_blob(dirpath, "structure.pkl", doc))
    except CheckpointError:
        raise
    except Exception as e:
        raise CheckpointCorruptError(
            f"structure blob at {dirpath!r} failed to unpickle: "
            f"{type(e).__name__}: {e}")
    if plan_fingerprint(plan) != meta["plan_fingerprint"]:
        raise CheckpointCorruptError(
            f"structure blob at {dirpath!r} does not match the "
            "manifest's plan fingerprint")
    n_groups = int(meta["n_groups"])
    if n_groups != len(plan.groups):
        raise CheckpointCorruptError(
            f"bundle at {dirpath!r} has {n_groups} front pairs for a "
            f"{len(plan.groups)}-group plan")
    fronts = [(read_array(dirpath, f"front_{g:05d}_l", doc),
               read_array(dirpath, f"front_{g:05d}_u", doc))
              for g in range(n_groups)]
    dtype = meta["factor_dtype"]
    numeric = NumericFactorization(
        plan=plan, fronts=fronts, tiny_pivots=int(meta["tiny_pivots"]),
        dtype=np.dtype(dtype), finite=bool(meta["finite"]),
        info_col=int(meta["info_col"]),
        gemm_prec=str(meta.get("gemm_precision", "highest")))
    arr = lambda name: read_array(dirpath, name, doc)   # noqa: E731
    return LUFactorization(
        n=int(meta["n"]), options=Options(), equed=meta["equed"],
        dr=arr("dr"), dc=arr("dc"), r1=arr("r1"), c1=arr("c1"),
        row_order=arr("row_order"),
        col_order=arr("col_order") if meta.get("has_col_order") else None,
        sf=sf, plan=plan, numeric=numeric, anorm=float(meta["anorm"]),
        a=None,
        a_sym_indptr=(arr("a_sym_indptr")
                      if meta.get("has_sym_pattern") else None),
        a_sym_indices=(arr("a_sym_indices")
                       if meta.get("has_sym_pattern") else None))
