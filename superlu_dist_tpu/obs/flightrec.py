"""Flight recorder — an always-on-able bounded ring of recent telemetry.

The file tracer (obs/trace.py) is opt-in and writes continuously; a
production run that DIES needs something cheaper that is simply *there*
when the postmortem starts.  This is that device: a fixed-size
``collections.deque`` of span tuples — no I/O, no formatting, bounded
memory — that dumps ONE JSON artifact (the last N events, the active
phase stack per thread, the compile census so far, a wall-clock anchor
for cross-rank alignment, and the metrics snapshot when enabled) on:

* ``NumericBreakdownError`` / ``CollectiveMismatchError`` construction
  (hooked in ``utils/errors.py`` — every rank that raises dumps);
* the bench watchdog firing (``bench.py`` dumps before ``os._exit``);
* ``SIGTERM`` (armed by the env path / ``install(..., arm_signals=True)``);
* any explicit ``dump(reason)`` call.

Integration: the recorder implements the tracer protocol (``span`` /
``complete`` / ``flush`` / ``close``), so ``obs.trace.get_tracer``
composes it with the file tracer (or runs it alone) and EVERY existing
instrumentation site — phase timers, dispatch spans, comm legs,
sentinel events — feeds the ring with zero new hot-path code.  Unlike
the file tracer it sets ``profiling = False``: the streamed executor
must NOT serialize its async dispatch for the ring (kernel spans need
per-group blocking; dispatch/phase/comm spans don't), which is what
keeps the overhead negligible enough to fly always-on.

Disabled path: with ``SLU_TPU_FLIGHTREC`` unset, ``get_flightrec()``
returns the ``NULL_FLIGHTREC`` singleton — no deque, no clock, no
signal handler (``scripts/check_trace_overhead.py`` enforces it).

``SLU_TPU_FLIGHTREC`` values: a path-looking value names the dump
artifact (``%p`` expands to the pid — REQUIRED for multi-rank runs so
ranks don't clobber each other); any other truthy value enables the
recorder with the default ``flightrec-%p.json`` in the working
directory.  ``SLU_TPU_FLIGHTREC_DEPTH`` sizes the ring (default 512).
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time

# safe one-way dependency: trace.py imports this module only lazily
# (inside get_tracer), never at module load
from superlu_dist_tpu.obs.trace import NULL_SPAN
from superlu_dist_tpu.utils.lockwatch import make_lock


class NullFlightRecorder:
    """Disabled recorder: every operation is a constant-time no-op."""

    __slots__ = ()
    enabled = False
    profiling = False
    path = None          # tracer-protocol attr: no trace artifact
    dump_path = None

    def span(self, name, cat="phase", **attrs):
        return NULL_SPAN

    def complete(self, name, cat, t0, dur, **attrs):
        pass

    def event(self, name, cat="event", **attrs):
        pass

    def dump(self, reason, detail="", extra=None):
        return None

    def flush(self):
        pass

    def close(self):
        pass


NULL_FLIGHTREC = NullFlightRecorder()


class _FlightSpan:
    """One open span recorded into the ring on exit (and onto the
    per-thread phase stack while open)."""

    __slots__ = ("_fr", "name", "cat", "args", "_t0")

    def __init__(self, fr, name, cat, args):
        self._fr = fr
        self.name = name
        self.cat = cat
        self.args = args or None

    def set(self, **attrs):
        self.args = dict(self.args or ())
        self.args.update(attrs)
        return self

    def __enter__(self):
        self._fr._push(self.name, self.cat)
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter_ns()
        self._fr._pop()
        self._fr._append(self._t0, t1 - self._t0, self.name, self.cat,
                         self.args)
        return False


class FlightRecorder:
    """Enabled recorder: a bounded deque of (ts_us, dur_us, name, cat,
    args) tuples plus per-thread open-span stacks."""

    enabled = True
    profiling = False      # never force per-kernel blocking (see module doc)
    path = None            # tracer-protocol attr: no trace artifact

    def __init__(self, dump_path: str | None = None, depth: int | None = None):
        from superlu_dist_tpu.utils.options import env_int
        if depth is None:
            depth = env_int("SLU_TPU_FLIGHTREC_DEPTH")
        depth = max(int(depth), 16)
        if not dump_path:
            dump_path = "flightrec-%p.json"
        self.dump_path = dump_path.replace("%p", str(os.getpid()))
        self.depth = depth
        self._ring = collections.deque(maxlen=depth)
        self._total = 0
        self._lock = make_lock("FlightRecorder._lock")
        self._stacks: dict[int, list] = {}
        # wall-clock anchor: monotonic span timestamps become absolute
        # times via unix ≈ anchor_unix + (ts_ns − anchor_perf_ns)/1e9 —
        # the cross-rank alignment key (each rank dumps its own pair)
        self._wall0 = time.time()
        self._epoch_ns = time.perf_counter_ns()
        self.dumps = 0

    # ---- ring internals -------------------------------------------------
    def _append(self, t0_ns, dur_ns, name, cat, args):
        rec = (round((t0_ns - self._epoch_ns) / 1e3, 3),
               round(dur_ns / 1e3, 3), name, cat, args)
        with self._lock:
            self._ring.append(rec)
            self._total += 1

    def _push(self, name, cat):
        ident = threading.get_ident()
        stack = self._stacks.get(ident)
        if stack is None:
            stack = self._stacks[ident] = []
        stack.append((name, cat))

    def _pop(self):
        stack = self._stacks.get(threading.get_ident())
        if stack:
            stack.pop()

    # ---- tracer protocol ------------------------------------------------
    def span(self, name, cat="phase", **attrs):
        return _FlightSpan(self, name, cat, attrs)

    def complete(self, name, cat, t0, dur, **attrs):
        """t0: time.perf_counter() seconds; dur: seconds (the
        obs.trace.Tracer.complete convention)."""
        self._append(int(t0 * 1e9), int(dur * 1e9), name, cat,
                     attrs or None)

    def event(self, name, cat="event", **attrs):
        """Point-in-time record (zero duration, stamped now)."""
        self._append(time.perf_counter_ns(), 0, name, cat, attrs or None)

    def flush(self):
        pass

    def close(self):
        pass

    # ---- the postmortem -------------------------------------------------
    def dump(self, reason: str, detail: str = "", extra: dict | None = None):
        """Write the postmortem artifact (atomic: temp + rename) and
        return its path.  Never raises — a failing dump must not mask
        the error being dumped for."""
        try:
            with self._lock:
                events = [{"ts": r[0], "dur": r[1], "name": r[2],
                           "cat": r[3],
                           **({"args": r[4]} if r[4] else {})}
                          for r in self._ring]
                stacks = {str(tid): list(stack)
                          for tid, stack in self._stacks.items() if stack}
                total = self._total
            doc = {
                "reason": str(reason),
                "detail": str(detail)[:2000],
                "pid": os.getpid(),
                "seq": self.dumps,
                "anchor": {"unix_time": self._wall0,
                           "perf_ns": self._epoch_ns},
                "dumped_unix": time.time(),
                "depth": self.depth,
                "total_events": total,
                "dropped_events": max(total - len(events), 0),
                "phase_stack": stacks,
                "events": events,
            }
            try:
                from superlu_dist_tpu.obs.compilestats import COMPILE_STATS
                doc["compile"] = COMPILE_STATS.block(top=16)
            except Exception:
                pass
            try:
                from superlu_dist_tpu.obs.metrics import get_metrics
                m = get_metrics()
                if m.enabled:
                    doc["metrics"] = m.snapshot()
            except Exception:
                pass
            try:
                # crash-consistency cross-reference: the checkpoint this
                # process flushed most recently (persist/checkpoint.py) —
                # a postmortem reader goes straight from the dump to the
                # resumable frontier
                from superlu_dist_tpu.persist.checkpoint import (
                    last_checkpoint)
                ck = last_checkpoint()
                if ck:
                    doc["checkpoint"] = ck
            except Exception:
                pass
            if extra:
                doc["extra"] = extra
            parent = os.path.dirname(os.path.abspath(self.dump_path))
            os.makedirs(parent, exist_ok=True)
            tmp = self.dump_path + f".tmp{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(doc, f, default=str)
            os.replace(tmp, self.dump_path)
            self.dumps += 1
            return self.dump_path
        except Exception:
            return None


# ---- process-global recorder ------------------------------------------------

_flightrec = None
_init_lock = make_lock("obs.flightrec._init_lock")
_FLAG_FALSE = ("", "0", "false", "no", "off")


def _looks_like_path(value: str) -> bool:
    return (os.sep in value or "/" in value or value.endswith(".json"))


def _arm_sigterm(fr: FlightRecorder) -> None:
    """On SIGTERM: flush any active factor checkpoint FIRST (so the dump
    below can reference the frontier it left behind), dump the ring,
    then defer to the previous disposition — a previously-installed
    Python handler is CHAINED (it still runs), SIG_IGN is respected
    (the process chose to ignore SIGTERM; hijacking that into a kill
    would change semantics), and only the default disposition re-raises
    the fatal signal.  Only possible from the main thread; silently
    skipped elsewhere."""
    try:
        import signal
        prev = signal.getsignal(signal.SIGTERM)

        def handler(signum, frame):
            try:
                from superlu_dist_tpu.persist.checkpoint import flush_active
                flush_active("SIGTERM")
            except Exception:
                pass
            fr.dump("SIGTERM")
            if callable(prev):
                prev(signum, frame)
            elif prev is signal.SIG_IGN:
                return
            else:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

        signal.signal(signal.SIGTERM, handler)
    except (ValueError, OSError, RuntimeError):
        pass


def get_flightrec():
    """The process recorder: a ``FlightRecorder`` when
    ``SLU_TPU_FLIGHTREC`` is truthy, else ``NULL_FLIGHTREC``.  Read
    once, on first use."""
    global _flightrec
    fr = _flightrec
    if fr is None:
        with _init_lock:
            if _flightrec is None:
                from superlu_dist_tpu.utils.options import env_str
                raw = env_str("SLU_TPU_FLIGHTREC").strip()
                if raw.lower() in _FLAG_FALSE:
                    _flightrec = NULL_FLIGHTREC
                else:
                    _flightrec = FlightRecorder(
                        raw if _looks_like_path(raw) else None)
                    # the dump the call graph reaches runs in the
                    # DEFERRED signal handler, not under this lock
                    _arm_sigterm(_flightrec)  # slulint: disable=SLU109
            fr = _flightrec
    return fr


def install(fr, arm_signals: bool = False):
    """Install ``fr`` as the process recorder; returns the previous one.
    Call BEFORE the first ``obs.trace.get_tracer()`` use (or follow with
    ``trace._reset()``) so the tracer composition picks it up."""
    global _flightrec
    prev = _flightrec
    _flightrec = fr
    if arm_signals and fr is not None and fr.enabled:
        _arm_sigterm(fr)
    return prev


def _reset():
    """Re-read ``SLU_TPU_FLIGHTREC`` on next use (test hygiene)."""
    global _flightrec
    _flightrec = None


def on_error(exc) -> str | None:
    """Structured-error hook (called from utils/errors.py constructors):
    dump the postmortem when the recorder is live.  A ticket-scoped
    error carrying ``ticket_stages`` (the TicketContext per-stage
    timings, obs/slo.py) gets them attached under ``extra`` — the dump
    names the stage that ate the budget.  Never raises."""
    try:
        fr = get_flightrec()
        if not fr.enabled:
            return None
        extra = None
        stages = getattr(exc, "ticket_stages", None)
        if stages:
            extra = {"ticket_stages": dict(stages)}
            trace_id = getattr(exc, "trace_id", None)
            if trace_id:
                extra["trace_id"] = trace_id
        return fr.dump(type(exc).__name__, detail=str(exc), extra=extra)
    except Exception:
        return None
