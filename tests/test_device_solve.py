"""DeviceSolver coverage on the CPU backend.

The device triangular solve (solve/device.py, the pdgstrs analog,
SRC/pdgstrs.c:838) normally only runs on accelerators; constructing it
directly here keeps it under CI on the CPU backend so regressions surface
before real TPU hardware (the reference's analog: GPU-vs-CPU path diff
tests, SURVEY.md §4).
"""

import numpy as np
import pytest

from superlu_dist_tpu.drivers.gssvx import gssvx
from superlu_dist_tpu.models.gallery import (
    poisson2d, random_sparse, convection_diffusion_2d)
from superlu_dist_tpu.solve.device import DeviceSolver
from superlu_dist_tpu.solve.trisolve import lu_solve
from superlu_dist_tpu.utils.options import Options, IterRefine


def _factor(a, **opt_kw):
    opts = Options(iter_refine=IterRefine.NOREFINE, **opt_kw)
    n = a.n_rows
    b = np.ones(n, dtype=a.data.dtype)
    x, lu, stats, info = gssvx(opts, a, b)
    assert info == 0
    return lu


@pytest.mark.parametrize("nrhs", [1, 3, 1024])
@pytest.mark.parametrize("diag_inv", [False, True])
def test_device_solver_matches_host(nrhs, diag_inv):
    a = poisson2d(9)
    lu = _factor(a)
    rng = np.random.default_rng(5)
    d = rng.standard_normal((a.n_rows, nrhs))
    d = d[:, 0] if nrhs == 1 else d
    ds = DeviceSolver(lu.numeric, diag_inv=diag_inv)
    got = ds.solve(d)
    want = lu_solve(lu.numeric, d)
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-11)
    # nrhs-padding honesty (the executed-vs-structural fix): the padded
    # width is the bucketed one and executed flops cover structural
    st = ds.last_solve_stats
    from superlu_dist_tpu.solve.plan import bucket_nrhs
    assert st["nrhs"] == nrhs
    assert st["padded_nrhs"] == bucket_nrhs(nrhs,
                                            ds.splan.nrhs_bucket_set)
    assert st["executed_flops"] >= st["solve_flops"] > 0


def test_device_solver_chunked_past_bucket_cap(monkeypatch):
    """nrhs past SLU_TPU_SOLVE_NRHS_MAX column-chunks (the bounded
    compile set): results reassemble exactly against the host solve."""
    monkeypatch.setenv("SLU_TPU_SOLVE_NRHS_MAX", "32")
    a = poisson2d(9)
    lu = _factor(a)
    d = np.random.default_rng(6).standard_normal((a.n_rows, 70))
    ds = DeviceSolver(lu.numeric)
    got = ds.solve(d)
    assert ds.last_solve_stats["chunks"] == 3          # 32 + 32 + 6->8
    assert ds.last_solve_stats["padded_nrhs"] == 32 + 32 + 8
    want = lu_solve(lu.numeric, d)
    np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-11)


@pytest.mark.parametrize("dtype", ["float32", "float64", "complex128",
                                   "df64"])
def test_device_solver_dtype_matrix(dtype):
    """Device-vs-host agreement across the factor dtype tiers: f32
    (the TPU default), f64, c128 (the z-twin), and the emulated-double
    df64 path (whose recombined f64 factors are host-resident — the
    solver consumes them as-is)."""
    a = poisson2d(8)
    if dtype == "complex128":
        vals = a.data + 1j * np.random.default_rng(4).standard_normal(a.nnz)
        a = type(a)(a.n_rows, a.n_cols, a.indptr, a.indices, vals)
        lu = _factor(a)
    elif dtype == "df64":
        lu = _factor(a, factor_dtype="df64")
    else:
        lu = _factor(a, factor_dtype=dtype)
    rng = np.random.default_rng(9)
    d = rng.standard_normal((a.n_rows, 3))
    if dtype == "complex128":
        d = d + 1j * rng.standard_normal(d.shape)
    got = DeviceSolver(lu.numeric).solve(d)
    want = lu_solve(lu.numeric, d)
    tol = dict(rtol=2e-4, atol=1e-6) if dtype == "float32" \
        else dict(rtol=1e-9, atol=1e-11)
    np.testing.assert_allclose(got, want, **tol)
    # transpose path through the same factors
    from superlu_dist_tpu.solve.trisolve import lu_solve_trans
    conj = dtype == "complex128"
    got_t = DeviceSolver(lu.numeric).solve_trans(d, conj=conj)
    want_t = lu_solve_trans(lu.numeric, d, conj=conj)
    np.testing.assert_allclose(got_t, want_t, **tol)


def test_diag_inv_through_driver():
    """Options.diag_inv (reference DiagInv, util.c:397-401) end-to-end."""
    a = poisson2d(10)
    n = a.n_rows
    xt = np.random.default_rng(2).standard_normal(n)
    b = a.matvec(xt)
    x, lu, stats, info = gssvx(Options(diag_inv=True), a, b)
    assert info == 0
    lu.solve_path = "device"   # force the device path on the CPU backend
    lu.dev_solver = None
    x2 = lu.solve_factored(b)
    assert lu.dev_solver.diag_inv
    np.testing.assert_allclose(x2, x, rtol=1e-7, atol=1e-9)


@pytest.mark.slow
def test_device_solver_padded_buckets():
    # irregular sizes force fronts with padded widths/batches
    a = random_sparse(73, density=0.06, seed=3)
    lu = _factor(a, min_bucket=8, bucket_growth=1.5, relax=4, max_supernode=12)
    rng = np.random.default_rng(7)
    d = rng.standard_normal((a.n_rows, 2))
    got = DeviceSolver(lu.numeric).solve(d)
    want = lu_solve(lu.numeric, d)
    np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-11)


@pytest.mark.slow
def test_device_solver_through_driver_path():
    # full driver solve (permutations + scalings) with the device path
    # forced on the CPU backend
    a = convection_diffusion_2d(10)
    n = a.n_rows
    xtrue = np.random.default_rng(0).standard_normal(n)
    b = a.matvec(xtrue)
    x, lu, stats, info = gssvx(Options(), a, b)
    assert info == 0
    lu.solve_path = "device"
    lu.dev_solver = None
    x_dev = lu.solve_factored(b)
    np.testing.assert_allclose(x_dev, x, rtol=1e-7, atol=1e-9)


def test_device_solver_complex():
    """c128 factors through the device solve path (the pzgstrs z-twin
    capability, SRC/pzgstrs.c) — CPU backend here, same kernels on TPU."""
    from superlu_dist_tpu.models.gallery import random_sparse
    a = random_sparse(48, density=0.08, seed=11)
    vals = a.data + 1j * np.random.default_rng(4).standard_normal(a.nnz)
    ac = type(a)(a.n_rows, a.n_cols, a.indptr, a.indices, vals)
    lu = _factor(ac)
    rng = np.random.default_rng(8)
    d = rng.standard_normal((ac.n_rows, 2)) + 1j * rng.standard_normal(
        (ac.n_rows, 2))
    got = DeviceSolver(lu.numeric).solve(d)
    want = lu_solve(lu.numeric, d)
    np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-11)


@pytest.mark.slow
def test_fused_and_streamed_solve_agree():
    """fused=True (one program per sweep) must equal the per-group
    dispatch path bit-for-bit."""
    a = poisson2d(11)
    lu = _factor(a)
    rng = np.random.default_rng(9)
    d = rng.standard_normal((a.n_rows, 2))
    x_stream = DeviceSolver(lu.numeric, fused=False).solve(d)
    x_fused = DeviceSolver(lu.numeric, fused=True).solve(d)
    np.testing.assert_array_equal(x_fused, x_stream)
    want = lu_solve(lu.numeric, d)
    np.testing.assert_allclose(x_fused, want, rtol=1e-9, atol=1e-11)


@pytest.mark.parametrize("conj", [False, True])
def test_device_solve_trans_matches_host(conj):
    """Device transpose sweeps (UT then LT, the trans_t path) vs the host
    lu_solve_trans — real and complex."""
    from superlu_dist_tpu.solve.trisolve import lu_solve_trans
    from superlu_dist_tpu.models.gallery import random_sparse
    a = random_sparse(60, density=0.08, seed=13)
    if conj:
        vals = a.data + 1j * np.random.default_rng(3).standard_normal(a.nnz)
        a = type(a)(a.n_rows, a.n_cols, a.indptr, a.indices, vals)
    lu = _factor(a)
    rng = np.random.default_rng(17)
    d = rng.standard_normal((a.n_rows, 2))
    if conj:
        d = d + 1j * rng.standard_normal(d.shape)
    got = DeviceSolver(lu.numeric).solve_trans(d, conj=conj)
    want = lu_solve_trans(lu.numeric, d, conj=conj)
    np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-10)


def test_trans_through_driver_device_path():
    """Full AᵀX=B driver solve with the device path forced on CPU."""
    from superlu_dist_tpu.utils.options import Trans
    from superlu_dist_tpu.models.gallery import convection_diffusion_2d
    a = convection_diffusion_2d(9)
    n = a.n_rows
    xt = np.random.default_rng(4).standard_normal(n)
    b = a.transpose().matvec(xt)
    x, lu, stats, info = gssvx(Options(trans=Trans.TRANS), a, b)
    assert info == 0
    lu.solve_path = "device"
    lu.dev_solver = None
    x_dev = lu.solve_factored_trans(b)
    r = np.linalg.norm(b - a.transpose().matvec(x_dev)) / np.linalg.norm(b)
    assert r < 1e-8, r


@pytest.mark.slow
def test_trans_streamed_matches_fused():
    from superlu_dist_tpu.solve.trisolve import lu_solve_trans
    a = poisson2d(10)
    lu = _factor(a)
    d = np.random.default_rng(21).standard_normal((a.n_rows, 2))
    got_f = DeviceSolver(lu.numeric, fused=True).solve_trans(d)
    got_s = DeviceSolver(lu.numeric, fused=False).solve_trans(d)
    np.testing.assert_array_equal(got_f, got_s)
    want = lu_solve_trans(lu.numeric, d)
    np.testing.assert_allclose(got_f, want, rtol=1e-9, atol=1e-11)


@pytest.mark.slow
def test_wide_rhs_batch():
    """nrhs well past the bucket boundary (the reference sweeps nrhs and
    its solve batches Linv GEMMs for large nrhs — SURVEY.md §7 hard-part
    5); both solver paths, 40 columns."""
    a = poisson2d(10)
    lu = _factor(a)
    rng = np.random.default_rng(31)
    d = rng.standard_normal((a.n_rows, 40))
    got = DeviceSolver(lu.numeric).solve(d)
    want = lu_solve(lu.numeric, d)
    assert got.shape == want.shape == (a.n_rows, 40)
    np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-11)
