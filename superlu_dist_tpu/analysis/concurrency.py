"""Concurrency lattice for the slulint thread-safety rules (SLU108-110).

The PR 8-10 serving/reliability era grew a real thread population —
heartbeat daemon, ``SolveServer`` dispatcher, background scrubber — and
their correctness rests on the same disciplined shared-state access the
reference trusts its process grid and atomics with (PAPER.md L0/L8).
This module resolves the raw lock/blocking facts the dataflow pass
collects (``Summary.acquires_raw`` / ``blocking_raw``) into a
project-wide model the three concurrency rules share:

* **class tables** — per class: which ``self.X`` attributes are locks /
  conditions / events / threads (recognized by their constructor:
  ``threading.Lock()``, ``Condition(...)``, the instrumented
  ``utils.lockwatch.make_lock(...)`` twins), with a ``Condition(lock)``
  aliased onto the lock it wraps so both guard ONE identity;
* **module tables** — module-level lock globals (``_REG_LOCK = ...``);
* **thread sides** — ``threading.Thread(target=...)`` targets resolved
  through the call graph, plus their transitive same-class callees:
  the set of methods that execute on a background thread;
* **lock-context methods** — methods whose every in-class call site is
  under a guard (or whose name carries the ``*_locked`` convention):
  their bodies are effectively guarded even without their own ``with``;
* **the global lock-acquisition graph** — edge ``A -> B`` whenever B is
  acquired (directly, or transitively through a resolved call) while A
  is held, each edge carrying its witness sites.  SLU109 reports its
  cycles; the runtime twin (``utils/lockwatch.py``,
  ``SLU_TPU_VERIFY_LOCKS=1``) checks the same graph on live executions.

Everything stays false-negative-leaning (the slulint contract): an
unresolvable thread target, lock identity, or call edge is dropped, not
guessed.
"""

from __future__ import annotations

import ast

from superlu_dist_tpu.analysis.core import dotted_name
from superlu_dist_tpu.analysis.dataflow import _MUTATOR_METHODS

#: constructor-name tail -> lock kind ("lock" and "cond" attrs guard
#: shared state; "event" attrs are their own synchronization)
LOCK_CTORS = {
    "Lock": "lock", "RLock": "lock", "Semaphore": "lock",
    "BoundedSemaphore": "lock", "make_lock": "lock", "make_rlock": "lock",
    "Condition": "cond", "make_condition": "cond",
    "Event": "event", "make_event": "event",
}


def lock_ctor_kind(call: ast.AST):
    if not isinstance(call, ast.Call):
        return None
    return LOCK_CTORS.get(dotted_name(call.func).rsplit(".", 1)[-1])


def _is_thread_ctor(call: ast.AST) -> bool:
    return isinstance(call, ast.Call) and \
        dotted_name(call.func).rsplit(".", 1)[-1] == "Thread"


def _kw(call: ast.Call, name: str):
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _site(path: str, line: int) -> str:
    return f"{path}:{line}"


class ClassModel:
    """Lock / event / thread attribute tables for one class."""

    def __init__(self, qname: str):
        self.qname = qname
        self.lock_attrs: dict = {}      # attr -> "lock" | "cond"
        self.event_attrs: set = set()
        self.cond_alias: dict = {}      # cond attr -> wrapped lock attr
        self.thread_attrs: dict = {}    # attr -> (target qname|None,
                                        #          daemon, path, line)
        self.thread_entries: dict = {}  # target qname -> (path, line)
        self.thread_side: set = set()   # qnames running on a thread
        self.methods: dict = {}         # name -> qname
        self.joined_attrs: set = set()  # thread attrs .join()ed somewhere

    def guard_attrs(self) -> set:
        return set(self.lock_attrs)

    def lock_id(self, attr: str) -> str:
        """Canonical lock identity: a Condition wrapping a lock shares
        the wrapped lock's identity (one mutex underneath)."""
        return f"{self.qname}.{self.cond_alias.get(attr, attr)}"


class Model:
    """The resolved project-wide concurrency model (built once per
    Project and cached on it — every rule shares one instance)."""

    def __init__(self, proj):
        self.proj = proj
        self.classes: dict[str, ClassModel] = {}
        self.module_locks: dict = {}    # module -> {var: kind}
        self.lock_context: set = set()  # method qnames effectively guarded
        # transitive lock acquisitions per function:
        # qname -> {lock_id: (site, via-description)}
        self.t_acquires: dict = {}
        # the global lock graph: (a, b) -> (site_of_b_acquire, via)
        self.edges: dict = {}
        self._build()

    # ------------------------------------------------------------------
    def class_for(self, fi) -> ClassModel | None:
        """The owning ClassModel for a function (methods and their
        nested defs both resolve to the enclosing class)."""
        cur = fi
        while cur is not None:
            if cur.cls is not None:
                return self.classes.get(cur.cls)
            cur = self.proj.functions.get(cur.parent) if cur.parent \
                else None
        return None

    # ------------------------------------------------------------------
    def _build(self) -> None:
        proj = self.proj
        for cq in proj.classes:
            self.classes[cq] = ClassModel(cq)
        for cq, ci in proj.classes.items():
            self.classes[cq].methods = dict(ci.methods)
        for mod in proj.modules.values():
            self._scan_module_locks(mod)
        for fi in proj.functions.values():
            if fi.cls is not None:
                self._scan_class_method(self.classes[fi.cls], fi)
        self._resolve_thread_sides()
        self._compute_lock_contexts()
        self._compute_acquires()
        self._build_edges()

    def _scan_module_locks(self, mod) -> None:
        table = {}
        for st in mod.tree.body:
            if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                    and isinstance(st.targets[0], ast.Name):
                kind = lock_ctor_kind(st.value)
                if kind in ("lock", "cond"):
                    table[st.targets[0].id] = kind
        if table:
            self.module_locks[mod.name] = table

    def _scan_class_method(self, cm: ClassModel, fi) -> None:
        from superlu_dist_tpu.analysis.callgraph import (_class_member,
                                                         _lookup_name)
        mod = self.proj.modules.get(fi.module)

        def resolve_target(expr):
            if isinstance(expr, ast.Attribute) \
                    and isinstance(expr.value, ast.Name) \
                    and expr.value.id == "self":
                return _class_member(self.proj, cm.qname, expr.attr)
            name = dotted_name(expr)
            if name and mod is not None:
                return _lookup_name(self.proj, mod, fi, name)
            return None

        for node in ast.walk(fi.node):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if not (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        continue
                    kind = lock_ctor_kind(node.value)
                    if kind in ("lock", "cond"):
                        cm.lock_attrs[tgt.attr] = kind
                        if kind == "cond":
                            # Condition(self._lock) — and the
                            # make_condition(name, self._lock) twin —
                            # share the wrapped lock's identity
                            cands = list(node.value.args) + \
                                [kw.value for kw in node.value.keywords]
                            for arg in cands:
                                if isinstance(arg, ast.Attribute) \
                                        and isinstance(arg.value,
                                                       ast.Name) \
                                        and arg.value.id == "self":
                                    cm.cond_alias[tgt.attr] = arg.attr
                                    break
                    elif kind == "event":
                        cm.event_attrs.add(tgt.attr)
                    elif _is_thread_ctor(node.value):
                        target = _kw(node.value, "target")
                        tq = resolve_target(target) if target is not None \
                            else None
                        daemon = _kw(node.value, "daemon")
                        cm.thread_attrs[tgt.attr] = (
                            tq,
                            bool(getattr(daemon, "value", False)),
                            fi.path, node.lineno)
                        if tq:
                            cm.thread_entries[tq] = (fi.path, node.lineno)
            elif isinstance(node, ast.Call):
                if _is_thread_ctor(node):
                    target = _kw(node, "target")
                    tq = resolve_target(target) if target is not None \
                        else None
                    if tq:
                        cm.thread_entries.setdefault(
                            tq, (fi.path, node.lineno))
                fn = node.func
                if isinstance(fn, ast.Attribute) and fn.attr == "join" \
                        and isinstance(fn.value, ast.Attribute) \
                        and isinstance(fn.value.value, ast.Name) \
                        and fn.value.value.id == "self":
                    cm.joined_attrs.add(fn.value.attr)

    def _resolve_thread_sides(self) -> None:
        """BFS from each class's thread entries over resolved call edges,
        restricted to functions lexically inside the class (only they
        can touch ``self.*`` state)."""
        for cm in self.classes.values():
            if not cm.thread_entries:
                continue
            seen = set()
            work = [q for q in cm.thread_entries if q in
                    self.proj.functions]
            prefix = cm.qname + "."
            while work:
                q = work.pop()
                if q in seen or not q.startswith(prefix):
                    continue
                seen.add(q)
                fi = self.proj.functions.get(q)
                if fi is None:
                    continue
                work.extend(fi.calls)
                work.extend(fi.children.values())
            cm.thread_side = seen

    def _compute_lock_contexts(self) -> None:
        """Methods whose every in-class call site sits under a guard (or
        under another lock-context method) are effectively guarded —
        the ``_take_batch`` / ``*_locked`` caller-holds-the-lock idiom."""
        # seed: the naming convention is an explicit assertion
        for q in self.proj.functions:
            if q.rsplit(".", 1)[-1].endswith("_locked"):
                self.lock_context.add(q)
        # call sites of class methods: qname -> [(caller, guarded)]
        sites: dict = {}
        for fi in self.proj.functions.values():
            cm = self.class_for(fi)
            for node, locks in self._held_spans(cm, fi):
                if not isinstance(node, ast.Call):
                    continue
                target = self.proj.call_target(fi.path, node)
                tfi = self.proj.functions.get(target)
                if tfi is not None and tfi.cls is not None:
                    sites.setdefault(target, []).append(
                        (fi.qname, bool(locks)))
        changed = True
        while changed:
            changed = False
            for q, callers in sites.items():
                if q in self.lock_context:
                    continue
                if callers and all(g or c in self.lock_context
                                   for c, g in callers):
                    self.lock_context.add(q)
                    changed = True

    def _held_spans(self, cm: ClassModel | None, fi):
        """[(node, held-lock-ids)] for every node in `fi`'s own body
        (nested defs excluded — they run in their own context)."""
        out = []

        def walk(node, held):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                if isinstance(child, (ast.With, ast.AsyncWith)):
                    acquired = list(held)
                    for item in child.items:
                        lid = self._lock_identity(cm, fi,
                                                  item.context_expr)
                        if lid is not None:
                            acquired = acquired + [lid]
                    out.append((child, list(held)))
                    walk(child, acquired)
                    continue
                out.append((child, list(held)))
                walk(child, held)

        walk(fi.node, [])
        return out

    def _lock_identity(self, cm: ClassModel | None, fi, ctx):
        """Canonical id for a with-ed lock expression, or None."""
        if isinstance(ctx, ast.Attribute) and isinstance(ctx.value,
                                                         ast.Name) \
                and ctx.value.id == "self" and cm is not None \
                and ctx.attr in cm.lock_attrs:
            return cm.lock_id(ctx.attr)
        if isinstance(ctx, ast.Name):
            table = self.module_locks.get(fi.module, {})
            if ctx.id in table:
                return f"{fi.module}.{ctx.id}"
        return None

    # ------------------------------------------------------------------
    def _compute_acquires(self) -> None:
        """Transitive lock acquisitions per function (fixpoint over call
        edges): what does calling this function acquire, directly or
        through its callees?"""
        proj = self.proj
        acq: dict = {}
        for q, fi in proj.functions.items():
            cm = self.class_for(fi)
            s = proj.summaries.get(q)
            direct = {}
            for scope, text, line in (s.acquires_raw if s else ()):
                if scope == "self" and cm is not None \
                        and text in cm.lock_attrs:
                    direct[cm.lock_id(text)] = (
                        _site(fi.path, line), f"`with self.{text}`")
                elif scope == "name":
                    table = self.module_locks.get(fi.module, {})
                    if text in table:
                        direct[f"{fi.module}.{text}"] = (
                            _site(fi.path, line), f"`with {text}`")
            acq[q] = direct
        changed = True
        while changed:
            changed = False
            for q, fi in proj.functions.items():
                mine = acq[q]
                for callee in fi.calls:
                    cq = self._callable_fn(callee)
                    for lid, (site, via) in acq.get(cq, {}).items():
                        if lid not in mine:
                            mine[lid] = (site, f"via `{cq}` ({via})")
                            changed = True
        self.t_acquires = acq

    def _callable_fn(self, qname: str) -> str:
        """Calling a class calls its __init__ (the flight-recorder-dump-
        at-construction errors make this edge matter)."""
        if qname in self.proj.classes:
            ci = self.proj.classes[qname]
            return ci.methods.get("__init__", qname)
        return qname

    def _build_edges(self) -> None:
        """The global lock graph: while A is held, acquiring B (by a
        nested ``with`` or through a resolved call) adds edge A -> B."""
        for q, fi in self.proj.functions.items():
            cm = self.class_for(fi)
            for node, held in self._held_spans(cm, fi):
                if not held:
                    continue
                inner = {}
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        lid = self._lock_identity(cm, fi,
                                                  item.context_expr)
                        if lid is not None:
                            inner[lid] = (_site(fi.path, node.lineno),
                                          "nested `with`")
                elif isinstance(node, ast.Call):
                    target = self.proj.call_target(fi.path, node)
                    if target:
                        cq = self._callable_fn(target)
                        inner = {
                            lid: (_site(fi.path, node.lineno),
                                  f"call to `{cq.rsplit('.', 1)[-1]}` "
                                  f"({via})")
                            for lid, (site, via) in
                            self.t_acquires.get(cq, {}).items()}
                if not inner:
                    continue
                for a in held:
                    for b, wit in inner.items():
                        if a != b and (a, b) not in self.edges:
                            self.edges[(a, b)] = wit

    def cycles(self):
        """Minimal lock-order cycles in the global graph: pairs (and
        longer cycles) of edges that can deadlock.  Returns a list of
        [(a, b, site, via), ...] cycles, each reported once."""
        adj: dict = {}
        for (a, b) in self.edges:
            adj.setdefault(a, set()).add(b)
        out = []
        seen_cycles = set()
        for start in sorted(adj):
            # DFS back to start
            stack = [(start, [start])]
            while stack:
                node, path = stack.pop()
                for nxt in sorted(adj.get(node, ())):
                    if nxt == start and len(path) > 1:
                        key = frozenset(path)
                        if key in seen_cycles:
                            continue
                        seen_cycles.add(key)
                        cyc = []
                        hops = path + [start]
                        for i in range(len(hops) - 1):
                            a, b = hops[i], hops[i + 1]
                            site, via = self.edges[(a, b)]
                            cyc.append((a, b, site, via))
                        out.append(cyc)
                    elif nxt not in path and len(path) < 6:
                        stack.append((nxt, path + [nxt]))
        return out


def get_model(project) -> Model:
    """The per-project model, built once and cached on the Project."""
    model = getattr(project, "_concurrency_model", None)
    if model is None:
        model = Model(project)
        project._concurrency_model = model
    return model


# ---------------------------------------------------------------------------
# shared access-classification helpers (SLU108 and SLU110 both need
# "which self attributes does this method read/write")
# ---------------------------------------------------------------------------

def attr_accesses(fi):
    """[(attr, is_write, node)] for every ``self.X`` touch lexically in
    `fi`'s body (nested defs excluded — they carry their own Summary and
    thread context).  Writes: plain/aug assignment, subscript stores,
    and calls of known container mutators (``self.q.append(...)``)."""
    out = []
    stack = list(ast.iter_child_nodes(fi.node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and node.value.id == "self":
            out.append((node.attr,
                        isinstance(node.ctx, (ast.Store, ast.Del)), node))
        elif isinstance(node, ast.Subscript) and \
                isinstance(node.ctx, ast.Store) and \
                isinstance(node.value, ast.Attribute) and \
                isinstance(node.value.value, ast.Name) and \
                node.value.value.id == "self":
            out.append((node.value.attr, True, node))
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATOR_METHODS and \
                isinstance(node.func.value, ast.Attribute) and \
                isinstance(node.func.value.value, ast.Name) and \
                node.func.value.value.id == "self":
            out.append((node.func.value.attr, True, node))
        stack.extend(ast.iter_child_nodes(node))
    return out


def attr_reads_transitive(model: Model, cm: ClassModel, entry: str) -> set:
    """Attributes READ by `entry` and its transitive same-class callees
    (the dependency set of a thread target, for SLU110's started-before-
    assigned check)."""
    proj = model.proj
    seen, reads = set(), set()
    work = [entry]
    prefix = cm.qname + "."
    while work:
        q = work.pop()
        if q in seen or not q.startswith(prefix):
            continue
        seen.add(q)
        fi = proj.functions.get(q)
        if fi is None:
            continue
        for attr, is_write, _ in attr_accesses(fi):
            if not is_write:
                reads.add(attr)
        work.extend(fi.calls)
        work.extend(fi.children.values())
    return reads
