from superlu_dist_tpu.solve.trisolve import lu_solve
