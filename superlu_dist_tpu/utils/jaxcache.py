"""Persistent XLA compile-cache policy, in one place.

Every driver/bench/measurement entry point points jax at a repo-local
cache (gitignored) so kernels compile once per machine — through the
remote-compile TPU tunnel a single kernel costs ~8-40 s, so cache reuse
is the difference between a bench that finishes and one that hits its
watchdog (BASELINE.md round-2/3 compile-wall history).

The cache directory is scoped by a MACHINE FINGERPRINT: XLA:CPU AOT
executables embed host ISA features, and loading an entry compiled on a
different machine is at best a "machine features don't match ... SIGILL"
warning and at worst a deterministic hang — a thread dies inside the
loaded executable and the in-process collective rendezvous of a
multi-device run sleeps forever (observed 6/6 on cross-machine entries
vs 2/2 green cold compiles, round 4).  Scoping the directory by
fingerprint makes every entry point immune to foreign entries while
keeping same-machine warm starts: a different box simply reads a
different directory.

Two layers (the BENCH_r05 hardening — the "machine features don't
match ... could lead to SIGILL" warning survived the first fingerprint
because it hashed only the FIRST core's cpuinfo flags line, and
heterogeneous-core hosts / migrated VMs expose different feature sets
on later cores):

* the fingerprint hashes the FULL host-feature set — every distinct
  flags/Features line across all cores plus family/model/stepping/
  microcode — so a host whose features drift reads a different
  directory by construction;
* ``enable_compile_cache`` additionally stamps the chosen directory
  with the RAW feature text (`.host_features`) and verifies it on
  every enable: a mismatch (an unhashed axis drifted, or a collision)
  re-scopes to a feature-exact subdirectory instead of loading the
  poisoned entries, and bumps :func:`isa_mismatch_count` — bench.py
  emits that counter per row and asserts it stays 0.
"""

import os

_FP_CACHE = None
_ISA_MISMATCHES = 0


def host_features() -> str:
    """The raw (machine ISA, jax toolchain) feature text the cache
    directory is keyed by — the exact axes on which the cpu_aot loader
    declares entries incompatible, plus the serialization-format and
    platform-flavor axes.  Unmemoized on purpose: the fingerprint memo
    (`_FP_CACHE`) is the single cache, so clearing it (tests, forks)
    re-reads the live host state."""
    import platform

    bits = [platform.machine()]
    platforms = os.environ.get("JAX_PLATFORMS", "")
    try:
        import jax
        import jaxlib
        bits += [jax.__version__, jaxlib.__version__]
        # the EFFECTIVE platform selection: in-process
        # jax.config.update("jax_platforms", "cpu") overrides the env
        # (the session env pins axon globally, so env alone cannot
        # distinguish a CPU-pinned worker from a TPU bench)
        platforms = getattr(jax.config, "jax_platforms", None) or platforms
    except Exception:
        pass
    # Platform FLAVOR: a process with the TPU/axon plugin active writes
    # XLA:CPU host executables with different codegen preferences (e.g.
    # +prefer-no-scatter) than a pure-CPU process on the SAME machine +
    # jaxlib.  A CPU-only run that disk-loads such an entry while a peer
    # rank compiles fresh executes a DIFFERENT collective schedule —
    # observed as gloo "preamble.length <= op.nbytes" aborts in the
    # 2-process mesh tests (r5).  Scope the cache by the axes that
    # select the flavor so the flavors never share a directory.
    bits += [str(platforms), os.environ.get("XLA_FLAGS", "")]
    try:
        # EVERY distinct value per key, not just the first core's: the
        # codegen host-feature probe may run on any core, and
        # heterogeneous-core machines (or migrated VMs) expose
        # different flags per core — the BENCH_r05 SIGILL-warning tail
        seen = set()
        with open("/proc/cpuinfo") as fh:
            for line in fh:
                key = line.split(":", 1)[0].strip()
                if key in ("model name", "flags", "Features",
                           "cpu family", "model", "stepping",
                           "microcode"):
                    ln = line.strip()
                    if ln not in seen:
                        seen.add(ln)
                        bits.append(ln)
    except OSError:
        bits.append(platform.processor() or "unknown-cpu")
    return "|".join(bits)


def machine_fingerprint() -> str:
    """Short stable tag for (machine ISA, jax toolchain) — the sha256 of
    :func:`host_features`.  Deterministic within a machine+install,
    distinct across the machines that produced the round-4
    poisoned-cache hangs."""
    global _FP_CACHE
    if _FP_CACHE is not None:
        return _FP_CACHE
    import hashlib
    _FP_CACHE = hashlib.sha256(host_features().encode()).hexdigest()[:10]
    return _FP_CACHE


def isa_mismatch_count() -> int:
    """How many times enable_compile_cache found a cache directory whose
    host-feature stamp disagreed with this host (each one re-scoped to a
    fresh feature-exact directory instead of loading the entries).  The
    bench emits this per row and asserts 0 — nonzero means an
    ISA-compatibility axis escaped the fingerprint hash."""
    return _ISA_MISMATCHES


def _stamp_host_features(cache_dir: str) -> str:
    """Verify/write the `.host_features` stamp for ``cache_dir``.
    Returns the directory to actually use: ``cache_dir`` when the stamp
    matches (or was just written), else a feature-exact subdirectory —
    entries compiled under different host features are never loaded.
    Never raises (cache-is-an-optimization contract)."""
    global _ISA_MISMATCHES
    import hashlib
    feats = host_features()
    try:
        os.makedirs(cache_dir, exist_ok=True)
        stamp = os.path.join(cache_dir, ".host_features")
        if os.path.exists(stamp):
            with open(stamp) as fh:
                if fh.read() != feats:
                    _ISA_MISMATCHES += 1
                    sub = hashlib.sha256(feats.encode()).hexdigest()[:10]
                    cache_dir = os.path.join(cache_dir, f"isa-{sub}")
                    os.makedirs(cache_dir, exist_ok=True)
                    stamp = os.path.join(cache_dir, ".host_features")
                    if not os.path.exists(stamp):
                        with open(stamp, "w") as fh:
                            fh.write(feats)
        else:
            with open(stamp, "w") as fh:
                fh.write(feats)
    except OSError:
        pass
    return cache_dir


def cache_dir_for_machine(base: str | None = None) -> str:
    """The machine-scoped persistent cache directory
    (`.cache/jax-mach-<fingerprint>` under the repo by default)."""
    if base is None:
        base = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), ".cache")
    return os.path.join(base, f"jax-mach-{machine_fingerprint()}")


def warm_marker_path(name: str, base_dir: str) -> str:
    """Path of a fingerprint-suffixed warm-cache marker under
    `<base_dir>/.hw_done/`.  One constructor for every reader/writer:
    the marker vouches for entries in THIS machine's cache dir, so its
    name carries the same fingerprint (a marker from another box or
    toolchain never matches)."""
    return os.path.join(base_dir, ".hw_done",
                        f"{name}.{machine_fingerprint()}")


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def bucket_warm_marker(digest: str, base_dir: str | None = None) -> str:
    """Warm-cache marker path for one CLOSED bucket set (the mega
    executor's compiled-program identity, FactorPlan.bucket_set_digest).
    The persistent cache is thereby keyed by the BUCKET SET rather than
    the matrix: the marker vouches that every program of that set is
    resident in this machine's cache dir, so a serving fleet (or a
    persist.from_bundle cold start) whose plans map onto the same
    buckets compiles nothing — `compile_seconds ≈ 0` on the second run
    of ANY matrix whose buckets are already resident."""
    return warm_marker_path(f"bucketset.{digest}",
                            base_dir or _repo_root())


def bucket_set_warm(digest: str, base_dir: str | None = None) -> bool:
    """True when scripts/warm_compile_cache.py (or a completed mega
    prebake) has marked this bucket set's programs resident."""
    return os.path.exists(bucket_warm_marker(digest, base_dir))


def mark_bucket_set_warm(digest: str, base_dir: str | None = None) -> str:
    """Record a prebaked bucket set (never raises — markers are an
    optimization, exactly like the cache they vouch for)."""
    path = bucket_warm_marker(digest, base_dir)
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        open(path, "a").close()
    except OSError:
        pass
    return path


def enable_compile_cache(cache_dir: str | None = None) -> None:
    """Point jax at the persistent compile cache (default: the repo's
    machine-scoped `.cache/jax-mach-<fp>`).  Caches every entry
    regardless of size/compile time.  Never raises — the cache is an
    optimization, not a failure reason.  Call any time before (or
    after) backend init; only subsequent compiles are affected."""
    import jax
    if cache_dir is None:
        cache_dir = cache_dir_for_machine()
    cache_dir = _stamp_host_features(cache_dir)
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass
    # compile-census boundary (obs/compilestats.py): build records can
    # now attribute disk hit/miss by entry-count delta in this dir
    try:
        from superlu_dist_tpu.obs.compilestats import COMPILE_STATS
        COMPILE_STATS.note_cache_dir(cache_dir)
    except Exception:
        pass


def disable_compile_cache() -> None:
    """Turn the persistent compile cache OFF (jax falls back to purely
    in-memory compilation).  The throwaway-cache pattern
    (__graft_entry__.dryrun_multichip) needs this when the caller had no
    cache configured: leaving the temp directory active after its rmtree
    would let a later same-process compile silently resurrect it and
    write/reload XLA:CPU AOT entries — the exact entry class the
    throwaway opted out of.  Never raises (same contract as enable)."""
    import jax
    try:
        jax.config.update("jax_compilation_cache_dir", None)
    except Exception:
        pass


def entry_count(cache_dir: str | None = None) -> int | None:
    """Number of entries in the persistent compile cache directory
    (None when no dir is configured or it does not exist yet).  The
    compile census uses the delta across a build to tell a disk hit
    (no new entry) from a fresh compile (entry written)."""
    if cache_dir is None:
        cache_dir = current_cache_dir()
    if not cache_dir:
        return None
    try:
        return len(os.listdir(cache_dir))
    except OSError:
        return None


def current_cache_dir() -> str | None:
    """The cache dir jax is currently configured with (None if unset).

    Read via attribute access first: on current jax, ``config.read()``
    raises AttributeError for flags that have a context manager (this
    one does), which silently reported None here and defeated the
    dryrun's restore-the-caller's-cache contract."""
    import jax
    try:
        return jax.config.jax_compilation_cache_dir
    except Exception:
        try:
            return jax.config.read("jax_compilation_cache_dir")
        except Exception:
            return None
