"""Request-scoped ticket tracing + the SLO layer (obs/slo.py):
TicketContext propagation through the serving fleet (one trace id per
ticket surviving kill -9 re-routes, with a ``reroute`` stage recorded),
contiguous stage algebra (stage durations sum to the end-to-end request
span), mergeable latency histograms (associativity), the burn-rate SLO
evaluator, and the ``trace_merge`` clock-anchor join."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from superlu_dist_tpu.drivers.gssvx import gssvx
from superlu_dist_tpu.models.gallery import poisson2d
from superlu_dist_tpu.obs import slo, trace
from superlu_dist_tpu.obs.trace import Tracer
from superlu_dist_tpu.persist.serial import save_lu
from superlu_dist_tpu.serve import FleetRouter, SolveServer
from superlu_dist_tpu.utils.options import IterRefine, Options

pytestmark = pytest.mark.fleet

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

KEYS = ("m0", "m1")
_NX = {"m0": 6, "m1": 7}


def _factor(a):
    x, lu, stats, info = gssvx(
        Options(iter_refine=IterRefine.NOREFINE), a, np.ones(a.n_rows))
    assert info == 0
    return lu


@pytest.fixture(scope="module")
def bundles(tmp_path_factory):
    root = tmp_path_factory.mktemp("ticket_trace_bundles")
    paths, mats = {}, {}
    for key in KEYS:
        a = poisson2d(_NX[key])
        d = str(root / key)
        save_lu(_factor(a), d)
        paths[key] = d
        mats[key] = a
    return paths, mats


@pytest.fixture()
def tracer(tmp_path):
    """An installed in-process file tracer; restored afterwards."""
    t = Tracer(str(tmp_path / "trace.json"))
    prev = trace.install(t)
    try:
        yield t
    finally:
        trace.install(prev)


def _events(tracer):
    tracer.flush()
    return json.load(open(tracer.path))["traceEvents"]


# ---------------------------------------------------------------------------
# ticket context propagation
# ---------------------------------------------------------------------------

def test_kill9_reroute_keeps_one_trace_id(bundles, tracer, monkeypatch):
    """A ticket re-routed off a killed replica keeps its trace id end
    to end and its request span records a ``reroute`` stage."""
    paths, mats = bundles
    monkeypatch.setenv("SLU_TPU_CHAOS", "kill_replica=1@batch=1")
    fleet = FleetRouter(paths, n_replicas=2, kind="thread")
    try:
        rng = np.random.default_rng(0)
        tickets = []
        for j in range(6):
            key = KEYS[j % 2]
            b = mats[key].matvec(rng.standard_normal(mats[key].n_rows))
            tickets.append(fleet.submit(key, b))
        xs = [t.result(120) for t in tickets]
        st = fleet.stats()
        assert st["failovers"] >= 1 and st["errors"] == 0
    finally:
        fleet.close()
        monkeypatch.delenv("SLU_TPU_CHAOS", raising=False)
    for x in xs:
        assert np.isfinite(np.asarray(x)).all()
    events = _events(tracer)
    requests = [e for e in events if e["name"] == "fleet-request"]
    assert len(requests) == 6
    tids = [e["args"]["trace_id"] for e in requests]
    assert len(set(tids)) == 6      # one id per ticket, never recycled
    rerouted = [e for e in requests
                if "reroute" in e["args"]["stages_ms"]]
    assert rerouted, "no request span recorded a reroute stage"
    # the re-routed ticket's stage spans carry the SAME trace id, and
    # its journey still covers route + serve around the reroute
    tid = rerouted[0]["args"]["trace_id"]
    stages = {e["name"] for e in events
              if e["cat"] == "request"
              and e.get("args", {}).get("trace_id") == tid
              and e["name"] != "fleet-request"}
    assert {"route", "reroute", "serve"} <= stages
    # the thread replica handed the ctx to its server as the parent:
    # server-side request spans join the SAME trace ids
    server_reqs = [e for e in events if e["name"] == "request"]
    assert server_reqs
    assert {e["args"]["trace_id"] for e in server_reqs} <= set(tids)


def test_server_stages_sum_to_request_latency(tracer):
    """Contiguous stage algebra: per-stage durations sum to the
    enclosing request span within 5% (the ISSUE acceptance bound)."""
    a = poisson2d(8)
    lu = _factor(a)
    rng = np.random.default_rng(1)
    with SolveServer(lu, max_wait_s=0.0) as srv:
        tickets = [srv.submit(a.matvec(rng.standard_normal(a.n_rows)))
                   for _ in range(5)]
        srv.flush()
        for t in tickets:
            assert np.isfinite(np.asarray(t.result(60.0))).all()
    requests = [e for e in _events(tracer) if e["name"] == "request"]
    assert len(requests) == 5
    for e in requests:
        total_ms = e["dur"] / 1e3
        stage_ms = sum(e["args"]["stages_ms"].values())
        slack = max(0.05 * total_ms, 0.01)   # 10us float/rounding floor
        assert abs(stage_ms - total_ms) <= slack, \
            f"stages {stage_ms:.3f}ms vs span {total_ms:.3f}ms: {e['args']}"


def test_deadline_error_carries_stage_timings(tracer):
    """A deadline miss surfaces the TicketContext stage split on the
    error itself (the flight-dump attachment satellite)."""
    from superlu_dist_tpu.utils.errors import ServeDeadlineError
    a = poisson2d(6)
    lu = _factor(a)
    srv = SolveServer(lu, max_wait_s=5.0, deadline_s=0.05, start=False)
    t = srv.submit(np.ones(a.n_rows))
    time.sleep(0.08)
    with pytest.raises(ServeDeadlineError) as ei:
        t.result(1.0)
    srv.close()
    assert ei.value.ticket_stages is not None
    assert "queue_wait" in ei.value.ticket_stages
    assert ei.value.trace_id


# ---------------------------------------------------------------------------
# latency accounter + SLO
# ---------------------------------------------------------------------------

def _random_accounter(seed, n=200):
    acct = slo.LatencyAccounter()
    rng = np.random.default_rng(seed)
    for _ in range(n):
        acct.observe(int(rng.integers(1, 1200)),
                     float(rng.lognormal(-6.0, 2.0)),
                     klass=("serve", "fleet")[int(rng.integers(2))])
    return acct


def test_histogram_merge_is_associative():
    """(A + B) + C == A + (B + C): fixed-layout snapshots merge by
    elementwise addition, so replica -> router -> export groupings all
    agree."""
    snaps = [_random_accounter(s).snapshot() for s in (1, 2, 3)]
    left = slo.LatencyAccounter()
    left.merge_snapshot(snaps[0])
    left.merge_snapshot(snaps[1])
    left.merge_snapshot(snaps[2])
    bc = slo.LatencyAccounter()
    bc.merge_snapshot(snaps[1])
    bc.merge_snapshot(snaps[2])
    right = slo.LatencyAccounter()
    right.merge_snapshot(snaps[0])
    right.merge_snapshot(bc.snapshot())
    assert left.snapshot() == right.snapshot()
    # and the merged totals are exact
    total = sum(s["count"] for s in left.summary().values())
    assert total == 600


def test_quantiles_and_nrhs_buckets():
    acct = slo.LatencyAccounter()
    for ms in range(1, 101):                 # 1..100 ms, uniform
        acct.observe(1, ms / 1e3)
    p50 = acct.quantile(0.50, nrhs=1)
    p99 = acct.quantile(0.99, nrhs=1)
    assert p50 is not None and p99 is not None
    assert 20.0 <= p50 <= 100.0 and p99 >= p50
    assert acct.quantile(0.5, nrhs=3) == acct.quantile(0.5, nrhs=1)
    assert slo.nrhs_bucket(1) == 1
    assert slo.nrhs_bucket(7) == 1
    assert slo.nrhs_bucket(8) == 8
    assert slo.nrhs_bucket(4096) == 1024


def test_slo_evaluator_burn_rate():
    """Burn accounting: all-fast traffic is ok; all-slow traffic burns
    the budget at 1/budget; the window is the delta between calls."""
    ev = slo.SLOEvaluator(p99_ms=10.0, budget=0.01)
    assert ev.armed
    acct = slo.LatencyAccounter()
    for _ in range(100):
        acct.observe(1, 0.001)               # 1 ms — well under target
    state = ev.evaluate(acct)
    key = "serve|1"
    assert state[key]["ok"] and state[key]["burn"] == 0.0
    for _ in range(100):
        acct.observe(1, 0.5)                 # 500 ms — way over
    state = ev.evaluate(acct)                # window = the slow 100 only
    assert state[key]["count"] == 100
    assert not state[key]["ok"]
    assert state[key]["burn"] == pytest.approx(100.0)


def test_ticket_context_stage_algebra():
    t0 = 100.0
    ctx = slo.TicketContext("t1", t0)
    ctx.stage("queue_wait", t0, 0.010)
    ctx.stage("dispatch", t0 + 0.010, 0.002)
    ctx.stage("device", t0 + 0.012, 0.050)
    ctx.stage("device", t0 + 0.062, 0.008)   # repeated stages sum
    ctx.stage("empty", t0, 0.0)              # zero-length dropped
    ms = ctx.stages_ms()
    assert ms == {"queue_wait": 10.0, "dispatch": 2.0, "device": 58.0}
    child = slo.TicketContext("t2", t0 + 1.0, parent=ctx)
    assert child.trace_id == ctx.trace_id
    assert slo.parent_ref("") is None
    assert slo.parent_ref("abc").trace_id == "abc"


# ---------------------------------------------------------------------------
# trace_merge: the clock-anchor join
# ---------------------------------------------------------------------------

def test_trace_merge_round_trip(tmp_path):
    """Two artifacts from tracers with different epochs merge onto one
    wall clock: spans keep their names/args, and the later tracer's
    spans land later on the merged axis."""
    p1, p2 = str(tmp_path / "a-%p.json"), str(tmp_path / "b-%p.json")
    t1 = Tracer(p1)
    t1.complete("early", "request", time.perf_counter(), 0.001,
                trace_id="x1")
    t1.close()
    time.sleep(0.05)
    t2 = Tracer(p2)
    t2.complete("late", "request", time.perf_counter(), 0.001,
                trace_id="x1")
    t2.close()
    out = str(tmp_path / "merged.json")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "trace_merge.py"),
         "-o", out, t1.path, t2.path],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, cwd=REPO)
    assert r.returncode == 0, r.stderr.decode()
    doc = json.load(open(out))
    events = doc["traceEvents"]
    n1 = len(json.load(open(t1.path))["traceEvents"])
    n2 = len(json.load(open(t2.path))["traceEvents"])
    assert len(events) == n1 + n2
    by_name = {e["name"]: e for e in events if e["cat"] == "request"}
    assert by_name["early"]["args"]["trace_id"] == "x1"
    # the second tracer's epoch is ~50ms after the first's: its spans
    # must be shifted right by about that much on the merged clock
    delta_us = by_name["late"]["ts"] - by_name["early"]["ts"]
    assert 20e3 <= delta_us <= 10e6, delta_us
    assert doc["otherData"]["base_unix_time"] > 0
