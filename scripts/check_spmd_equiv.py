#!/usr/bin/env python
"""SPMD-equivalence gate (#20): the shard_map SPMD tier must be a
bitwise twin of the lockstep reference on the 8-virtual-device CPU mesh.

What it pins, on the gallery trio (poisson/hilbert/arrowhead):

* factor: SpmdFactorExecutor L/U and tiny-pivot count bit-identical to
  the single-device lockstep executors (fused and stream);
* solve: SpmdSolver x (and the transpose sweep) bit-identical to the
  lockstep DeviceSolver on the same factors;
* A/B reference: the demoted TreeComm host-lockstep driver (pgssvx,
  single rank) still produces the SAME bits as the single-process gssvx
  driver — the recovery-fallback chain SPMD results are gated against;
* compile discipline: ONE compiled factor program regardless of n
  (the program count must not grow with matrix size), with 100%
  donation coverage on declared-dead inputs and 0 sharding findings
  (SLU119 replication included) under the runtime auditors.

Exit 0 = pass.  One gate of scripts/ci_gates.sh; tens of seconds on
CPU.  Gate contract (shared with check_schedule_equiv.py and friends):
any regression raises/asserts, which exits non-zero with the
diagnostic on stderr.
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
# runtime auditors ON for every program this gate builds
os.environ["SLU_TPU_VERIFY_PROGRAMS"] = "1"
os.environ["SLU_TPU_VERIFY_SHARDING"] = "1"

import numpy as np  # noqa: E402


def _analyzed(a):
    from superlu_dist_tpu.numeric.plan import build_plan
    from superlu_dist_tpu.ordering.dispatch import get_perm_c
    from superlu_dist_tpu.sparse.formats import symmetrize_pattern
    from superlu_dist_tpu.symbolic.symbfact import symbolic_factorize
    from superlu_dist_tpu.utils.options import Options

    sym = symmetrize_pattern(a)
    col_order = get_perm_c(Options(), a, sym)
    sf = symbolic_factorize(sym, col_order)
    return (build_plan(sf, schedule="dataflow"), sym.data[sf.value_perm],
            a.norm_max())


def check(name, a, mesh):
    from superlu_dist_tpu.numeric.factor import (get_executor,
                                                 numeric_factorize)
    from superlu_dist_tpu.obs.compilestats import COMPILE_STATS
    from superlu_dist_tpu.parallel.spmd import (SpmdFactorExecutor,
                                                SpmdSolver)
    from superlu_dist_tpu.solve.device import DeviceSolver

    plan, vals, anorm = _analyzed(a)
    ex = get_executor(plan, "float64", executor="spmd", mesh=mesh)
    assert isinstance(ex, SpmdFactorExecutor), (
        f"{name}: spmd request downgraded to {type(ex).__name__}")
    assert ex.n_kernels == 1, (
        f"{name}: {ex.n_kernels} factor programs — the SPMD tier must "
        "compile ONE per factor, independent of n")
    mark = COMPILE_STATS.marker()
    fs = numeric_factorize(plan, vals, anorm, executor="spmd", mesh=mesh)
    built = [r for r in COMPILE_STATS.records[mark:]
             if r.site == "spmd.factor"]
    assert len(built) == 1, (
        f"{name}: {len(built)} spmd.factor compile records (want 1)")
    for lockstep in ("fused", "stream"):
        f0 = numeric_factorize(plan, vals, anorm, executor=lockstep)
        assert f0.tiny_pivots == fs.tiny_pivots, (name, lockstep)
        for (l0, u0), (l1, u1) in zip(f0.fronts, fs.fronts):
            assert (np.array_equal(np.asarray(l0), np.asarray(l1))
                    and np.array_equal(np.asarray(u0), np.asarray(u1))), (
                f"{name}: SPMD L/U differ from lockstep {lockstep} "
                "(bitwise)")
    rng = np.random.default_rng(11)
    rhs = rng.standard_normal((plan.n, 3))
    f0 = numeric_factorize(plan, vals, anorm, executor="fused")
    s0, s1 = DeviceSolver(f0), SpmdSolver(fs, mesh)
    assert np.array_equal(s0.solve(rhs), s1.solve(rhs)), (
        f"{name}: SPMD solve differs from lockstep DeviceSolver")
    assert np.array_equal(s0.solve_trans(rhs), s1.solve_trans(rhs)), (
        f"{name}: SPMD transpose solve differs from lockstep")
    print(f"[spmd-equiv] {name}: OK (1 factor program, n={plan.n}, "
          f"L/U/x bitwise vs fused+stream lockstep)")


def check_treecomm_reference(a):
    """The demoted TreeComm tier stays a valid A/B reference: its x is
    bit-identical to the single-process gssvx driver's."""
    from superlu_dist_tpu.drivers.gssvx import gssvx
    from superlu_dist_tpu.parallel.dist import distribute_rows
    from superlu_dist_tpu.parallel.pgssvx import pgssvx
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    from superlu_dist_tpu.utils.options import Options

    b = np.random.default_rng(5).standard_normal(a.n_rows)
    x0, _, _, info0 = gssvx(Options(), a, b.copy())
    name = f"/slu_spmd_gate_{os.getpid()}"
    with TreeComm(name, 1, 0, max_len=2048, create=True) as tc:
        x1, info1 = pgssvx(tc, Options(), distribute_rows(a, 1)[0],
                           b.copy())
    assert info0 == 0 and info1 == 0, (info0, info1)
    assert np.array_equal(np.asarray(x0).ravel(),
                          np.asarray(x1).ravel()), (
        "TreeComm A/B reference drifted from the lockstep gssvx driver")
    print("[spmd-equiv] TreeComm A/B reference: OK (x bitwise vs gssvx)")


def check_auditors_clean():
    from superlu_dist_tpu.obs.compilestats import COMPILE_STATS
    from superlu_dist_tpu.utils import programaudit

    sh = programaudit.get_sharding_auditor()
    assert sh is not None, "sharding auditor never armed"
    slu119 = [f for f in sh.findings if f.rule == "SLU119"]
    assert not sh.findings, (
        f"sharding findings on mesh programs ({len(slu119)} SLU119): "
        f"{sh.findings}")
    blk = COMPILE_STATS.audit_block()
    assert blk["programs"] >= 1 and blk["programs_sharding_audited"] >= 1
    assert blk["donation_coverage_pct"] == 100.0, (
        f"donation coverage {blk['donation_coverage_pct']}% (want 100%)")
    print(f"[spmd-equiv] auditors: OK ({blk['programs']} programs, "
          f"{blk['programs_sharding_audited']} sharding-audited, "
          f"donation {blk['donation_coverage_pct']}%, 0 findings)")


def main():
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    assert len(jax.devices()) >= 8, (
        f"need the 8-virtual-device mesh, got {len(jax.devices())}")
    from superlu_dist_tpu.models.gallery import (hilbert, poisson2d,
                                                 rank_deficient_arrowhead)
    from superlu_dist_tpu.parallel.grid import gridinit

    mesh = gridinit(1, 8).mesh
    check("poisson2d(16)", poisson2d(16), mesh)
    check("poisson2d(24)", poisson2d(24), mesh)   # program count flat in n
    check("hilbert(48)", hilbert(48), mesh)
    check("rank_deficient_arrowhead(40)", rank_deficient_arrowhead(40),
          mesh)
    check_treecomm_reference(poisson2d(16))
    check_auditors_clean()
    print("[spmd-equiv] all checks passed")


if __name__ == "__main__":
    main()
