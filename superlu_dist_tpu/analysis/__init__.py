"""slulint — project-native static analysis (docs/ANALYSIS.md).

Rules:
  SLU101 collective-consistency   (rules_collective.py, interprocedural)
  SLU102 trace-purity             (rules_trace.py)
  SLU103 index-width discipline   (rules_index.py, flow-based)
  SLU104 env-knob registry        (rules_env.py)
  SLU105 jit-cache-key hygiene    (rules_trace.py, call-graph-aware)
  SLU107 jit-key shape diversity  (rules_trace.py)
  SLU108 shared-mutable access    (rules_shared.py)
  SLU109 lock-order discipline    (rules_lockorder.py)
  SLU110 thread lifecycle         (rules_lifecycle.py)
  SLU113 dispatch-loop host sync  (rules_program.py, device lattice)
  SLU106 runtime lockstep verify  (parallel/treecomm.py +
                                   numeric/stream.py retrace sentinel,
                                   env SLU_TPU_VERIFY_COLLECTIVES=1)
  SLU109 runtime lock verify      (utils/lockwatch.py,
                                   env SLU_TPU_VERIFY_LOCKS=1)
  SLU115-SLU118 precision flow    (rules_precision.py, width lattice;
                                   runtime twin utils/programaudit.py
                                   under SLU_TPU_VERIFY_DTYPES=1)
  SLU120 mesh/spec hygiene        (rules_sharding.py, meshreg-backed)
  SLU122 dispatch-loop transfers  (rules_sharding.py, device lattice)
  SLU111/SLU112/SLU114 IR audit   (program.py + rules_program.py over
                                   closed jaxprs; runtime twin
                                   utils/programaudit.py under
                                   SLU_TPU_VERIFY_PROGRAMS=1 — donation
                                   coverage, baked-const blowup, SPMD
                                   collective lockstep)
  SLU119/SLU121 sharding audit    (rules_sharding.py over closed
                                   jaxprs; runtime twin
                                   utils/programaudit.py under
                                   SLU_TPU_VERIFY_SHARDING=1 /
                                   SLU_TPU_MEM_BUDGET_BYTES — implicit
                                   replication blowup, static
                                   peak-memory model)

Engine: every scan first builds a package-wide call graph
(callgraph.py) and per-function dataflow summaries over the
{i32, rank, env, device} taint lattice (dataflow.py); rules consume
both.  Scan results are cached content-hash-keyed (cache.py,
.slulint-cache.json) so an unchanged tree rescans sub-second; the CLI
emits text, JSON or SARIF 2.1.0 (sarif.py).

CLI: ``python -m superlu_dist_tpu.analysis`` (scripts/slulint.py is the
same entry; scripts/ci_gates.sh is the consolidated CI entry point).
"""

from superlu_dist_tpu.analysis.core import (Finding, Rule, analyze_paths,
                                            analyze_source, analyze_sources,
                                            default_rules, read_sources)

__all__ = ["Finding", "Rule", "analyze_paths", "analyze_source",
           "analyze_sources", "default_rules", "read_sources"]
