"""slulint v5 precision-flow rules — SLU115-SLU118.

GESP's correctness story (static pivoting + iterative refinement) rests
on arithmetic precision being exactly what the escalation ladder
believes it is: since the ladder landed, every Schur GEMM can run at
bf16/default/f32/highest, df64 accuracy depends on optimization_barrier-
fenced error-free transforms XLA is free to destroy, and acceptance
gates compare against float literals that silently encode a dtype.
These rules audit DTYPE FLOW — the hazard class the recursive blocked
TRSM/TRMM literature calls out, where accumulation precision (not
layout) decides portability (arXiv:2504.13821).

Two rules run over SOURCE via the v2 dataflow lattice's new precision
component (``dataflow.TAINT_F64``/``TAINT_F32``/``TAINT_EFT``):

SLU115 — implicit downcast.  An ``.astype`` that narrows float width
(f64→f32→bf16) on a value-carrying array in ``numeric/``/``solve/``/
``refine/`` silently discards mantissa bits the BERR gate will charge to
"the matrix" three rungs later.  The sanctioned tier boundary is
``ops/dense.gemm`` (path-exempt: ops/ is outside the rule's scope) and
the df64 split/merge helpers; everything else is flagged, with the
witness chain from the cast site to the consuming GEMM/TRSM when the
cast value demonstrably feeds one.

SLU117 — EFT purity.  df64 hi/lo pair components (results of the
ops/df64.py error-free transforms) carry compensation terms whose bit
patterns only mean something under the EFT algebra: a raw ``+``/``-``/
``*`` on one outside ``ops/df64.py`` re-associates the compensation and
silently degrades df64 to f32.  Second half: the EFT kernels themselves
(two_sum/quick_two_sum/two_prod/_split) must fence every intermediate
with ``optimization_barrier`` — an unfenced transform is exactly what
XLA's reassociation freedoms destroy.

One rule is lexical:

SLU118 — tolerance hygiene.  Float comparison literals in the tolerance
band (1e-18, 1e-5] — ``berr < 1e-6`` style, and ``rtol=``/``atol=``
kwargs — encode a dtype assumption no reader can audit.  Thresholds must
derive from the central dtype-aware model (``utils/tols.py``:
eps(dtype)×factor with provenance).  Perf ratios (0.05), underflow
guards (1e-300) and demo drivers under ``examples/`` are out of band or
out of scope by design.

SLU116 runs over BOTH source and jaxprs:

SLU116 — accumulation dtype.  Source half: a ``jnp``/``lax`` matmul/
dot/einsum/tensordot/dot_general/segment_sum in ``numeric/``/``solve/``
without ``preferred_element_type`` leaves the accumulator at the
backend's whim — on TPU a bf16-input GEMM then accumulates at bf16
(the bug the BERR gate catches three rungs late; ``ops/dense.gemm``
pins every tier and is the sanctioned route).  Jaxpr half
(:func:`audit_accumulation`, plus :func:`audit_narrowing` for SLU115):
every ``dot_general`` in a traced program must produce a float width
≥ the widest operand (and ≥ 32 when any operand is 16-bit); narrowing
``convert_element_type`` eqns on non-scalar values are flagged unless
every transitive consumer (through shape-transparent ops) is a
wide-accumulating dot_general — the shape ``gemm``'s bf16 tier
legitimately emits.  Both halves are duck-typed over jaxpr objects
(no jax import — unit-testable on stubs) and power the
``SLU_TPU_VERIFY_DTYPES=1`` runtime twin (utils/programaudit.py).
"""

from __future__ import annotations

import ast

from superlu_dist_tpu.analysis.core import (Finding, Rule, _norm_parts,
                                            dotted_name)
from superlu_dist_tpu.analysis.dataflow import (TAINT_EFT, FnFlow,
                                                float_width_node,
                                                taint_width)
from superlu_dist_tpu.analysis.program import ProgramSpec, iter_eqns

RULE_IMPLICIT_DOWNCAST = "SLU115"
RULE_ACCUM_DTYPE = "SLU116"
RULE_EFT_PURITY = "SLU117"
RULE_TOL_LITERAL = "SLU118"

#: calls that consume a narrowed value into MXU-bound linear algebra —
#: the witness targets of SLU115's cast→consumer chain
_PREC_CONSUMERS = frozenset({
    "matmul", "dot", "einsum", "tensordot", "dot_general",
    "solve_triangular", "gemm", "trsm", "segment_sum"})

#: private taint kind threading a cast site key through the dataflow
#: (never leaves _NarrowFlow: summarize() runs plain FnFlow)
_TK_NARROW = "_narrow115"


# --------------------------------------------------------------------------
# SLU115 — implicit downcast (source half)
# --------------------------------------------------------------------------

class _NarrowFlow(FnFlow):
    """FnFlow that records narrowing ``.astype`` sites and the first
    GEMM/TRSM-ish consumer each narrowed value reaches."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        # (line, col) -> {node, w_from, w_to, consumer}
        self.casts: dict = {}

    def _call_taint_base(self, node: ast.Call) -> dict:
        t = super()._call_taint_base(node)
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr == "astype" \
                and node.args:
            w_to = float_width_node(node.args[0])
            if w_to is not None:
                w_from = taint_width(self.taint(fn.value))
                # 16-bit targets are always a downcast of a compute
                # dtype; 32-bit targets only flag on a KNOWN f64 source
                # (false-negative-leaning: plain-f32 code stays quiet)
                if w_to == 16 or (w_from is not None and w_to < w_from):
                    key = (node.lineno, node.col_offset)
                    self.casts.setdefault(
                        key, {"node": node, "w_from": w_from,
                              "w_to": w_to, "consumer": None})
                    t = dict(t)
                    t[_TK_NARROW] = key
        return t

    def visit_stmt(self, st) -> None:
        for node in ast.walk(st):
            if not isinstance(node, ast.Call):
                continue
            tail = dotted_name(node.func).rsplit(".", 1)[-1]
            if tail not in _PREC_CONSUMERS:
                continue
            args = list(node.args) + [kw.value for kw in node.keywords]
            for arg in args:
                key = self.taint(arg).get(_TK_NARROW)
                info = self.casts.get(key) if key is not None else None
                if info is not None and info["consumer"] is None:
                    info["consumer"] = (tail, node.lineno)


class ImplicitDowncastRule(Rule):
    rule_id = RULE_IMPLICIT_DOWNCAST
    title = "implicit-float-downcast"
    hint = ("route reduced-precision arithmetic through the sanctioned "
            "tier boundary (ops/dense.gemm pins the accumulator per "
            "ladder tier) or the df64 split helpers; a bare narrowing "
            ".astype silently discards mantissa bits the BERR gate "
            "charges to the matrix")
    package_dirs = ("numeric", "solve", "refine")

    def check(self, tree, source, path, project=None):
        if project is None:
            return []
        out = []
        for qname, fi in project.functions.items():
            if fi.path != path:
                continue
            flow = _NarrowFlow.for_function(project, fi)
            flow.run()
            for key in sorted(flow.casts):
                info = flow.casts[key]
                w_from = info["w_from"]
                src = f"f{w_from}" if w_from else "a compute-width value"
                msg = (f"implicit downcast: `.astype` narrows {src} to "
                       f"f{info['w_to']} on a value-carrying array")
                if info["consumer"] is not None:
                    tail, line = info["consumer"]
                    msg += (f" — witness chain: cast at line "
                            f"{info['node'].lineno} -> consumed by "
                            f"`{tail}` at line {line}")
                out.append(self.finding(path, info["node"], msg))
        return out


# --------------------------------------------------------------------------
# SLU116 — accumulation dtype (source half)
# --------------------------------------------------------------------------

_ACCUM_CALLS = frozenset({"matmul", "dot", "einsum", "tensordot",
                          "dot_general", "segment_sum"})
_JAX_ROOTS = frozenset({"jnp", "jax", "lax"})


class AccumulationDtypeRule(Rule):
    rule_id = RULE_ACCUM_DTYPE
    title = "unpinned-accumulation-dtype"
    hint = ("pin the accumulator: pass preferred_element_type (>= the "
            "widest operand float width) or route through ops/dense.gemm "
            "— without it a reduced-input GEMM accumulates at the "
            "backend's whim (bf16 on the MXU), the "
            "bf16-GEMM-without-f32-accumulation bug")
    package_dirs = ("numeric", "solve")

    def check(self, tree, source, path, project=None):
        out = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            tail = name.rsplit(".", 1)[-1]
            if tail not in _ACCUM_CALLS:
                continue
            root = name.split(".", 1)[0]
            if root not in _JAX_ROOTS:
                continue          # host numpy reductions keep f64 anyway
            if any(kw.arg == "preferred_element_type"
                   for kw in node.keywords):
                continue
            out.append(self.finding(
                path, node,
                f"`{name}` without preferred_element_type — the "
                "accumulation dtype is whatever the backend picks, not "
                "what the ladder promised"))
        return out


# --------------------------------------------------------------------------
# SLU117 — EFT purity
# --------------------------------------------------------------------------

_RAW_OPS = (ast.Add, ast.Sub, ast.Mult)
_EFT_KERNEL_NAMES = frozenset({"two_sum", "quick_two_sum", "two_prod"})
_BARRIER_TAILS = frozenset({"_bar", "optimization_barrier"})


class _EftFlow(FnFlow):
    """FnFlow flagging raw +/-/* on df64 pair components."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.hits: dict = {}     # (line, col) -> (node, provenance)

    def visit_stmt(self, st) -> None:
        for node in ast.walk(st):
            if not isinstance(node, ast.BinOp) \
                    or not isinstance(node.op, _RAW_OPS):
                continue
            for side in (node.left, node.right):
                prov = self.taint(side).get(TAINT_EFT)
                if prov is not None:
                    key = (node.lineno, node.col_offset)
                    self.hits.setdefault(key, (node, prov))
                    break


def _is_eft_kernel(fn) -> bool:
    return fn.name in _EFT_KERNEL_NAMES or fn.name.startswith("_split")


def _fence_findings(rule, path, fn) -> list:
    """BinOps in an EFT kernel body with no optimization_barrier call
    ancestor — the sequences XLA's reassociation freedoms destroy."""
    fenced: set = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and dotted_name(node.func).rsplit(
                ".", 1)[-1] in _BARRIER_TAILS:
            for sub in ast.walk(node):
                if isinstance(sub, ast.BinOp):
                    fenced.add(id(sub))
    out = []
    for node in ast.walk(fn):
        if isinstance(node, ast.BinOp) and isinstance(node.op, _RAW_OPS) \
                and id(node) not in fenced:
            out.append(rule.finding(
                path, node,
                f"unfenced error-free transform in `{fn.name}`: this "
                "+/-/* has no optimization_barrier ancestor, so XLA may "
                "re-associate or fuse it and zero the compensation term",
                hint="wrap every EFT intermediate in "
                     "jax.lax.optimization_barrier (the ops/df64.py "
                     "`_bar` discipline)"))
    return out


class EFTPurityRule(Rule):
    rule_id = RULE_EFT_PURITY
    title = "eft-purity"
    hint = ("df64 hi/lo components only mean something under the "
            "ops/df64.py primitive algebra — use df64_add/df64_mul/... "
            "(or merge with df64_to_f64 first); raw arithmetic "
            "re-associates the compensation term and degrades df64 to "
            "f32")
    package_dirs = None

    def check(self, tree, source, path, project=None):
        parts = _norm_parts(path)
        in_df64 = parts[-1] == "df64.py" and "ops" in parts
        out = []
        # half B — fencing of the EFT kernels themselves (runs
        # everywhere, ops/df64.py very much included)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and _is_eft_kernel(node):
                out.extend(_fence_findings(self, path, node))
        # half A — raw arithmetic on pair components (ops/df64.py is the
        # sanctioned algebra and composes primitives from raw ops)
        if in_df64 or project is None:
            return out
        for qname, fi in project.functions.items():
            if fi.path != path or _is_eft_kernel(fi.node):
                continue
            flow = _EftFlow.for_function(project, fi)
            flow.run()
            for key in sorted(flow.hits):
                node, prov = flow.hits[key]
                out.append(self.finding(
                    path, node,
                    f"raw arithmetic on a df64 pair component ({prov}) "
                    "outside ops/df64.py"))
        return out


# --------------------------------------------------------------------------
# SLU118 — tolerance hygiene
# --------------------------------------------------------------------------

# the tolerance band: narrower than any perf ratio (0.05, 1e-3), wider
# than underflow guards (1e-300).  Named so the rule never flags itself.
_TOL_BAND_LO = 1e-18
_TOL_BAND_HI = 1e-5

_RELATIONAL = (ast.Lt, ast.LtE, ast.Gt, ast.GtE)


def _float_lit(node):
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        node = node.operand
    if isinstance(node, ast.Constant) and isinstance(node.value, float):
        return node.value
    return None


def _in_band(v) -> bool:
    return _TOL_BAND_LO < abs(v) <= _TOL_BAND_HI


class ToleranceLiteralRule(Rule):
    rule_id = RULE_TOL_LITERAL
    title = "ad-hoc-tolerance-literal"
    hint = ("derive the threshold from utils/tols.py (eps(dtype)*factor "
            "with provenance): tols.tol(dtype, 2**k, why=...) / "
            "tols.berr_target(dtype) / the named gate tolerances — a "
            "bare 1e-N encodes a dtype assumption no reader can audit")
    package_dirs = None

    def applies(self, path: str) -> bool:
        # demo drivers mirror the reference's printed residual checks
        return "examples" not in _norm_parts(path)

    def check(self, tree, source, path, project=None):
        out = []
        seen: set = set()

        def flag(lit_node, v, where):
            key = (lit_node.lineno, lit_node.col_offset)
            if key in seen:
                return
            seen.add(key)
            out.append(self.finding(
                path, lit_node,
                f"float tolerance literal {v!r} in {where} — thresholds "
                "in the band (1e-18, 1e-5] must come from the central "
                "dtype-aware model"))

        for node in ast.walk(tree):
            if isinstance(node, ast.Compare) and any(
                    isinstance(op, _RELATIONAL) for op in node.ops):
                for sub in ast.walk(node):
                    v = _float_lit(sub)
                    if v is not None and _in_band(v):
                        flag(sub, v, "a comparison")
                        if isinstance(sub, ast.UnaryOp):
                            # the walk will visit the inner Constant
                            # too — one literal, one finding
                            seen.add((sub.operand.lineno,
                                      sub.operand.col_offset))
            elif isinstance(node, ast.Call):
                for kw in node.keywords:
                    if kw.arg not in ("rtol", "atol"):
                        continue
                    v = _float_lit(kw.value)
                    if v is not None and _in_band(v):
                        flag(kw.value, v, f"an {kw.arg}= keyword")
        return out


# --------------------------------------------------------------------------
# jaxpr half (SLU115/SLU116 over traced programs) — duck-typed, no jax
# --------------------------------------------------------------------------

#: float widths by dtype NAME (complex -> component width; float8
#: handled by prefix below)
_DTYPE_WIDTHS = {"float64": 64, "complex128": 64,
                 "float32": 32, "complex64": 32,
                 "bfloat16": 16, "float16": 16}

#: shape-only plumbing a narrowed value may pass through on its way to
#: the consuming dot_general (jnp.matmul emits broadcasts/transposes
#: around the MXU op) — deliberately NO arithmetic primitives
_TRANSPARENT_PRIMS = frozenset({
    "broadcast_in_dim", "reshape", "transpose", "squeeze", "slice",
    "dynamic_slice", "expand_dims", "concatenate", "pad", "copy",
    "rev", "optimization_barrier", "stop_gradient"})


def dtype_width(dtype) -> int | None:
    """Float width in bits of an aval dtype (None for non-floats)."""
    name = str(getattr(dtype, "name", dtype))
    w = _DTYPE_WIDTHS.get(name)
    if w is None and name.startswith("float8"):
        return 8
    return w


def _prim_name(eqn) -> str:
    return getattr(eqn.primitive, "name", str(eqn.primitive))


def _var_width(v) -> int | None:
    return dtype_width(getattr(getattr(v, "aval", None), "dtype", None))


def _program_finding(rule: str, spec: ProgramSpec, message: str,
                     hint: str) -> Finding:
    return Finding(rule, f"<program:{spec.site}[{spec.label}]>", 0, 1,
                   message, hint)


def _consumer_map(eqns) -> dict:
    """id(var) -> [consuming eqns].  Keyed by object identity: jaxpr
    vars are unique objects shared between a producer's outvars and its
    consumers' invars (and Literals need not be hashable)."""
    out: dict = {}
    for eqn in eqns:
        for v in getattr(eqn, "invars", ()):
            out.setdefault(id(v), []).append(eqn)
    return out


def _sanctioned_narrow(eqn, consumers) -> bool:
    """True when every transitive consumer of a narrowing convert
    (through shape-transparent ops) is a dot_general accumulating at
    width >= 32 — the shape ops/dense.gemm's bf16 tier emits (inputs
    cast to bf16, product pinned to f32).  Zero visible consumers (the
    value escapes this jaxpr) also passes: false-negative-leaning."""
    work = [id(v) for v in eqn.outvars]
    seen: set = set()
    while work:
        k = work.pop()
        if k in seen:
            continue
        seen.add(k)
        for c in consumers.get(k, ()):
            name = _prim_name(c)
            if name in _TRANSPARENT_PRIMS:
                work.extend(id(v) for v in c.outvars)
            elif name == "dot_general":
                w = _var_width(c.outvars[0])
                if w is None or w < 32:
                    return False
            else:
                return False
    return True


def audit_narrowing(spec: ProgramSpec):
    """SLU115 over a traced program: narrowing ``convert_element_type``
    eqns on non-scalar values outside the sanctioned GEMM input pattern.
    Returns ``(findings, {n_converts, n_narrowing})``."""
    eqns = list(iter_eqns(spec.jaxpr))
    consumers = _consumer_map(eqns)
    findings = []
    n_converts = n_narrow = 0
    for eqn in eqns:
        if _prim_name(eqn) != "convert_element_type":
            continue
        n_converts += 1
        iv, ov = eqn.invars[0], eqn.outvars[0]
        w_in, w_out = _var_width(iv), _var_width(ov)
        if w_in is None or w_out is None or w_out >= w_in:
            continue
        if not getattr(getattr(iv, "aval", None), "shape", ()):
            continue             # scalars are not value-carrying arrays
        n_narrow += 1
        if _sanctioned_narrow(eqn, consumers):
            continue
        findings.append(_program_finding(
            RULE_IMPLICIT_DOWNCAST, spec,
            f"narrowing convert f{w_in}->f{w_out} on shape "
            f"{tuple(getattr(iv.aval, 'shape', ()))} whose consumers are "
            "not wide-accumulating dot_generals — the program silently "
            "discards mantissa bits the ladder never sanctioned",
            "narrow only as GEMM INPUT with the accumulator pinned >= "
            "f32 (the ops/dense.gemm bf16-tier shape), or keep the "
            "value at its compute width"))
    return findings, {"n_converts": n_converts, "n_narrowing": n_narrow}


def audit_accumulation(spec: ProgramSpec):
    """SLU116 over a traced program: every ``dot_general`` must produce
    a float width >= the widest float operand, and >= 32 whenever any
    operand is narrower than 32 bits (16-bit MXU inputs must accumulate
    at f32).  Returns ``(findings, {n_dot_generals})``."""
    findings = []
    n_dots = 0
    for eqn in iter_eqns(spec.jaxpr):
        if _prim_name(eqn) != "dot_general":
            continue
        n_dots += 1
        ws = [w for w in (_var_width(v)
                          for v in getattr(eqn, "invars", ()))
              if w is not None]
        if not ws:
            continue
        required = max(ws)
        if min(ws) < 32:
            required = max(required, 32)
        w_out = _var_width(eqn.outvars[0])
        if w_out is not None and w_out < required:
            findings.append(_program_finding(
                RULE_ACCUM_DTYPE, spec,
                f"dot_general accumulates at f{w_out} with operand "
                f"widths {sorted(set(ws))} — required >= f{required}: "
                "the bf16-GEMM-without-f32-accumulation bug, caught "
                "before the program runs instead of by a BERR gate "
                "three rungs later",
                "pin preferred_element_type to the accumulator dtype "
                "(ops/dense.gemm does this on every ladder tier)"))
    return findings, {"n_dot_generals": n_dots}
