"""Factorization plan: mapping supernodes onto level-batched padded fronts.

This is the TPU-native analog of the reference's *distribution* phase
(pddistribute, SRC/pddistribute.c:322): where the reference builds
dLocalLU_t index structures plus MPI send/recv schedules, we precompute —
entirely on the host, once per sparsity pattern — the gather/scatter maps
that let the numeric factorization run as a short sequence of XLA ops per
(level, bucket) group:

  assemble:   F[slot] += A entries            (host-built index triples)
              F[slot] += children's Schur     (extend-add, device-computed
                                               indices from per-child
                                               relative-position vectors —
                                               the dscatter.c:111 analog)
  factor:     batched partial LU (ops.dense)  (the pdgstrf hot loop)
  write-back: pool[off[slot]] = Schur block   (strided, device-computed)

Dispatch groups are formed by an earliest-ready DATAFLOW scheduler by
default (the reference's elimination-tree task parallelism + pipelined
look-ahead, SRC/pdgstrf.c:624-697): ready supernodes sharing a (m, w, u)
bucket shape pack into maximal batches across elimination levels, bounded
by the SLU_TPU_SCHED_WINDOW look-ahead so pool liveness stays bounded.
SLU_TPU_SCHEDULE=level restores strict level lockstep; both schedules
produce bitwise-identical factors (docs/PERFORMANCE.md).

Fronts are square (symmetrized pattern): index set = supernode columns +
below-diagonal rows, padded to bucket sizes (W for the pivot block, M = W+U
total).  Children's Schur blocks live in a device pool as zero-padded U×U
blocks whose offsets come from a size-class free-list allocator simulated
at plan time — pool memory is the live tree frontier (the multifrontal
"update stack"), not the sum over all supernodes.  Host-side index volume
is O(nnz(A) + nnz(L)): per-entry extend-add maps are never materialized
(they are broadcast-computed on device), which is what lets plans scale to
n ~ 10^6 (BASELINE.md config 4).

Like the reference's SamePattern path, a plan is reusable across numeric
refactorizations with the same sparsity pattern.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from superlu_dist_tpu.symbolic.symbfact import SymbolicFact


@dataclasses.dataclass
class ChildSet:
    """Children of one group's fronts, bucketed by child U size.

    The extend-add kernel gathers each child's padded ub×ub Schur block from
    the pool and scatter-adds it into the parent front at positions
    rel[c,i]·M + rel[c,j]; rel == M is the sentinel for padding (maps past
    the front, dropped)."""

    ub: int                 # child U bucket (block is ub*ub in the pool)
    child_off: np.ndarray   # (C,) pool offset of each child block
    child_slot: np.ndarray  # (C,) parent slot in this group
    rel: np.ndarray         # (C, ub) child row -> parent front position


@dataclasses.dataclass
class Group:
    """One (level, bucket) batch of fronts."""

    level: int
    m: int                  # padded front size
    w: int                  # padded pivot width
    u: int                  # padded Schur size (m - w); 0 => no write-back
    batch: int              # number of real fronts
    sns: np.ndarray         # supernode ids, slot order
    ws: np.ndarray          # (batch,) real pivot widths (identity padding)
    off: np.ndarray         # (batch,) pool offset of each front's Schur
                            # block (pool_size => no write-back for slot)
    # assembly of original matrix entries
    a_slot: np.ndarray
    a_flat: np.ndarray
    a_src: np.ndarray
    children: list          # list[ChildSet]


@dataclasses.dataclass
class FactorPlan:
    n: int
    sf: SymbolicFact
    pattern_indptr: np.ndarray     # permuted symmetrized pattern (CSR)
    pattern_indices: np.ndarray
    groups: list                   # Groups in dispatch (topological) order
    pool_size: int                 # peak live Schur-pool entries
    sn_group: np.ndarray           # (ns,) group index of each supernode
    sn_slot: np.ndarray            # (ns,) slot within its group
    flops: float
    front_bytes: int               # total padded front storage (per dtype unit)
    schedule: str = "level"        # "level" | "dataflow" (build_plan)
    sched_window: int = 0          # dataflow look-ahead window (levels)
    n_level_groups: int = 0        # groups a pure level schedule yields
    critical_path: int = 0         # longest chain of dependent groups
    closed: bool = False           # shape-key set closed onto ladder rungs
    bucket_set: tuple = ()         # sorted distinct (W, U) keys over groups

    @property
    def n_levels(self) -> int:
        return int(self.sf.sn_level.max()) + 1 if len(self.sf.sn_level) else 0

    def bucket_set_digest(self) -> str:
        """Stable short digest of the (W, U) shape-key set (plus the
        closure flag): the identity of the compiled-program set the mega
        executor needs for this plan.  The fleet warm-start tier keys
        its prebaked-cache markers on it (utils/jaxcache.py,
        scripts/warm_compile_cache.py) and the bench row records it —
        two matrices with equal digests share one compiled kernel set
        (up to dtype and the derived batch/index rungs)."""
        import hashlib
        blob = repr((bool(self.closed), tuple(self.bucket_set)))
        return hashlib.sha256(blob.encode()).hexdigest()[:12]

    @property
    def mean_occupancy(self) -> float:
        """Mean real fronts per dispatch group — the batching quality the
        dataflow scheduler optimizes (level lockstep leaves deep-tree
        tails at occupancy ~1)."""
        return (self.sf.n_supernodes / len(self.groups)
                if self.groups else 0.0)

    def bytes_moved(self, itemsize: int = 8) -> int:
        """Irregular gather/scatter traffic of one factorization at this
        plan, in bytes — the data-movement honesty twin of the flop
        padding factor (and the number the Pallas fused kernels exist to
        shrink: they keep the front batch VMEM-resident instead of
        round-tripping HBM per index).  Counted per moved element as its
        accesses on the ``.at[]`` path:

        * A-entry assembly: one avals read + a front read-modify-write
          per structural entry (3 accesses);
        * extend-add: one pool read + a front read-modify-write per
          child Schur element (3 accesses, real child count × ub²);
        * Schur write-back: one front read + one pool write per u²
          element of every real front (2 accesses).

        ``itemsize`` defaults to 8 (f64); callers that know the factor
        dtype pass its itemsize for exact bytes.
        """
        elems = 0
        for g in self.groups:
            elems += 3 * len(g.a_src)
            elems += 3 * sum(len(cs.child_off) * cs.ub * cs.ub
                             for cs in g.children)
            elems += 2 * g.batch * g.u * g.u
        return int(elems) * int(itemsize)

    def schedule_stats(self, itemsize: int = 8) -> dict:
        """Schedule telemetry block shared by Stats.report, the trace
        span (numeric.factor.numeric_factorize) and the bench JSON row:
        dispatch-group count before/after aggregation, mean batch
        occupancy, shape-padding factor (executed/structural flops, batch
        padding excluded), the dependent-group critical-path length and
        the irregular gather/scatter traffic (``bytes_moved``)."""
        from superlu_dist_tpu.symbolic.symbfact import _front_flops
        executed = float(sum(g.batch * _front_flops(g.w, g.u)
                             for g in self.groups))
        return {
            "schedule": self.schedule,
            "n_groups": len(self.groups),
            "n_level_groups": self.n_level_groups,
            "occupancy": round(self.mean_occupancy, 2),
            "padding_factor": round(executed / max(self.flops, 1.0), 4),
            "critical_path": self.critical_path,
            "bytes_moved": self.bytes_moved(itemsize),
        }

    def __getstate__(self):
        """Drop the volatile executor cache (factor.make_factor_fn hangs
        compiled closures on the plan — `_factor_fns`).  A plan that has
        already factored once would otherwise be unpicklable, which the
        distributed tier's skeleton broadcast hits on every Fact-reuse
        refactorization (the root's plan is warm by then)."""
        state = dict(self.__dict__)
        state.pop("_factor_fns", None)
        return state

    def check_index_width(self):
        """Flat pool offsets must fit the active jax integer width.
        Beyond 2^31 entries (n≳600k at f32) the int64 index maps need
        jax_enable_x64 — the XSDK_INDEX_SIZE=64 build analog
        (superlu_defs.h:85-88); without it jax silently downcasts them
        to int32 and scatters wrap.  Called by every executor."""
        import jax
        if self.pool_size >= 2 ** 31 and not jax.config.jax_enable_x64:
            raise ValueError(
                f"pool_size {self.pool_size} exceeds int32 index range; "
                "enable jax_enable_x64 (the XSDK_INDEX_SIZE=64 analog) — "
                "without it jax silently downcasts the int64 index maps")


# ---------------------------------------------------------------------------
# The canonical bucket ladder — ONE source of truth for every pad-to-rung
# rounding in the project.  Historically the plan's front buckets
# (_bucket_sizes) and the streamed executor's array padding
# (stream._bucket_len) rounded with different rungs/growth, so schedule
# alignment and kernel caching could disagree about what "the same shape"
# means; both now sit on this recurrence (and the solve plan's nrhs rungs
# follow the same closed-set discipline, solve/plan.nrhs_buckets).
# Defaults come from the knob registry: SLU_TPU_BUCKET_BASE / _GROWTH.
# ---------------------------------------------------------------------------

def ladder_rungs(lo: int, growth: float):
    """Infinite generator of ladder rungs: ``lo``, then
    ``max(prev + step, ceil(prev * growth / step) * step)`` with step = 8
    (multiple-of-8 rungs) above the base, step = 1 below it.  growth=2
    from lo=8 reproduces the streamed executor's historical pow-2 rungs;
    growth=1.5 from a plan ``min_bucket`` reproduces _bucket_sizes'."""
    step = 8 if lo >= 8 else 1
    s = int(lo)
    while True:
        yield s
        s = max(s + step, int(np.ceil(s * growth / step) * step))


def bucket_rung(n: int, lo: int | None = None,
                growth: float | None = None) -> int:
    """Smallest ladder rung >= n.  ``lo``/``growth`` default to the
    registered SLU_TPU_BUCKET_BASE / SLU_TPU_BUCKET_GROWTH knobs —
    the n-independent canonical ladder the closure pass rounds onto."""
    from superlu_dist_tpu.utils.options import env_float, env_int
    if lo is None:
        lo = env_int("SLU_TPU_BUCKET_BASE")
    if growth is None:
        growth = env_float("SLU_TPU_BUCKET_GROWTH")
    for s in ladder_rungs(int(lo), max(float(growth), 1.01)):
        if s >= n:
            return s


def _bucket_sizes(max_needed: int, min_bucket: int, growth: float):
    """Front-size rungs for one plan: the shared ladder's rungs below
    ``max_needed`` plus one tight top rung hugging the largest front
    (the legacy open-ladder behavior; a CLOSED plan re-rounds every key
    onto canonical ladder rungs afterwards — _close_shape_keys)."""
    sizes = []
    for s in ladder_rungs(min_bucket, growth):
        if s >= max_needed:
            break
        sizes.append(s)
    sizes.append(int(np.ceil(max_needed / 8.0) * 8) if max_needed > min_bucket
                 else min_bucket)
    return np.unique(np.array(sizes, dtype=np.int64))


def _align_shape_keys(sn_W, sn_U, tol: float):
    """Schedule-aware shape-key coalescing (the interleaved-batching
    enabler, arXiv:1909.04539).  SHARED MACHINERY: the solve-side
    scheduler (solve/plan.py) runs this a second time on top of the
    factor keys — keep the signature/semantics stable for both callers.
    Greedily merge (W, U) bucket keys —
    promoting the smaller key's members to the merged (max W, max U)
    padding — while the merged members' executed flops stay within
    `tol`x the ORIGINAL constituent flops (the amalgamation budget
    discipline, symbfact.amalgamate_supernodes: chained merges never
    compound past tol).  Fine bucket rungs (growth ~1.05 leaves
    same-width cells 5% apart in U) otherwise scatter the supernodes
    over so many distinct shapes that no scheduler can batch them:
    the bench matrix at n=32768 has 83 distinct keys over 101 level
    cells.  Runs BEFORE the schedule branch so level and dataflow see
    identical per-supernode padding — the bitwise level/dataflow
    equivalence rests on it (padding is NOT arithmetic-neutral: a wider
    GEMM K retiles the real partial-sum reduction).

    Returns (sn_W, sn_U) with coalesced assignments; tol <= 1 disables.
    """
    from superlu_dist_tpu.symbolic.symbfact import _front_flops
    if not tol or tol <= 1.0 or len(sn_W) == 0:
        return sn_W, sn_U
    pairs = np.stack([sn_W, sn_U], axis=1)
    keys, inv, cnt = np.unique(pairs, axis=0, return_inverse=True,
                               return_counts=True)
    k = len(keys)
    W = keys[:, 0].astype(np.int64).copy()
    U = keys[:, 1].astype(np.int64).copy()
    n_mem = cnt.astype(np.int64).copy()
    base = n_mem * _front_flops(W, U)     # original constituent flops
    rep = np.arange(k)
    alive = np.ones(k, dtype=bool)
    while alive.sum() > 1:
        ai = np.flatnonzero(alive)
        Wm = np.maximum.outer(W[ai], W[ai])
        Um = np.maximum.outer(U[ai], U[ai])
        tot = n_mem[ai][:, None] + n_mem[ai][None, :]
        ratio = tot * _front_flops(Wm, Um) / (base[ai][:, None]
                                              + base[ai][None, :])
        np.fill_diagonal(ratio, np.inf)
        i, j = np.unravel_index(np.argmin(ratio), ratio.shape)
        if ratio[i, j] > tol:
            break
        a, b = int(ai[i]), int(ai[j])
        a, b = min(a, b), max(a, b)       # deterministic representative
        W[a], U[a] = max(W[a], W[b]), max(U[a], U[b])
        n_mem[a] += n_mem[b]
        base[a] += base[b]
        alive[b] = False
        rep[b] = a
    # path-compress representatives, then map supernodes through
    for i in range(k):
        r = i
        while rep[r] != r:
            r = rep[r]
        rep[i] = r
    return W[rep[inv]], U[rep[inv]]


def _close_shape_keys(sn_W, sn_U, max_keys: int):
    """The global shape-key CLOSURE pass (the mega-executor prerequisite,
    arXiv:2406.10511's one-engine-every-front-shape discipline): map the
    aligned (W, U) key set onto at most ``max_keys`` keys whose values
    are canonical ladder rungs (bucket_rung), so the compiled-program
    count is bounded by ``max_keys`` INDEPENDENT of matrix size and two
    matrices of the same size class land on the same compiled set.

    Unlike _align_shape_keys (a flop-budgeted OPTIMIZATION), closure is
    a hard bound: merges proceed cheapest-flop-ratio-first until the
    count target is met, and every surviving key is rounded up to ladder
    rungs — the padding cost is the price of the closed compile set
    (docs/PERFORMANCE.md quantifies it).  Like alignment it runs BEFORE
    the schedule branch, so level and dataflow pad identically and the
    bitwise schedule-equivalence guarantee carries over to closed plans.

    Returns (sn_W, sn_U) with closed assignments.
    """
    from superlu_dist_tpu.symbolic.symbfact import _front_flops
    if len(sn_W) == 0:
        return sn_W, sn_U
    rung = np.vectorize(bucket_rung, otypes=[np.int64])
    pairs = np.stack([rung(np.maximum(sn_W, 1)),
                      np.where(sn_U > 0, rung(np.maximum(sn_U, 1)), 0)],
                     axis=1)
    keys, inv, cnt = np.unique(pairs, axis=0, return_inverse=True,
                               return_counts=True)
    k = len(keys)
    W = keys[:, 0].astype(np.int64).copy()
    U = keys[:, 1].astype(np.int64).copy()
    n_mem = cnt.astype(np.int64).copy()
    base = n_mem * _front_flops(W, U)
    rep = np.arange(k)
    alive = np.ones(k, dtype=bool)
    while alive.sum() > max(int(max_keys), 1):
        ai = np.flatnonzero(alive)
        # merged key = rung-rounded (max W, max U): the ratio accounts
        # the TRUE padded flops of the canonical merged rung
        Wm = rung(np.maximum.outer(W[ai], W[ai]))
        Um = np.maximum.outer(U[ai], U[ai])
        Um = np.where(Um > 0, rung(np.maximum(Um, 1)), 0)
        tot = n_mem[ai][:, None] + n_mem[ai][None, :]
        ratio = tot * _front_flops(Wm, Um) / (base[ai][:, None]
                                              + base[ai][None, :])
        np.fill_diagonal(ratio, np.inf)
        i, j = np.unravel_index(np.argmin(ratio), ratio.shape)
        a, b = int(ai[i]), int(ai[j])
        a, b = min(a, b), max(a, b)
        W[a] = int(Wm[i, j])
        U[a] = int(Um[i, j])
        n_mem[a] += n_mem[b]
        base[a] += base[b]
        alive[b] = False
        rep[b] = a
    for i in range(k):
        r = i
        while rep[r] != r:
            r = rep[r]
        rep[i] = r
    return W[rep[inv]], U[rep[inv]]


def _level_batches(sf: SymbolicFact, sn_W, sn_U) -> list:
    """The classic level-lockstep partition: one batch per distinct
    (elimination level, W, U) triple, level-ascending then shape-key
    ascending.  Returns [(level, sns ndarray), ...] in dispatch order."""
    ns = sf.n_supernodes
    key_order = np.lexsort((sn_U, sn_W, sf.sn_level))
    out = []
    i = 0
    while i < ns:
        s0 = key_order[i]
        lvl, W, U = int(sf.sn_level[s0]), int(sn_W[s0]), int(sn_U[s0])
        j = i
        members = []
        while (j < ns and sf.sn_level[key_order[j]] == lvl
               and sn_W[key_order[j]] == W and sn_U[key_order[j]] == U):
            members.append(key_order[j])
            j += 1
        out.append((lvl, np.array(members, dtype=np.int64)))
        i = j
    return out


def _dataflow_batches(sf: SymbolicFact, sn_W, sn_U, window: int) -> list:
    """Earliest-ready dataflow schedule (the reference's elimination-tree
    task parallelism + look-ahead, SRC/pdgstrf.c:624-697, recast for
    batched dispatch; arXiv:2406.10511 medium-granularity dataflow,
    arXiv:1909.04539 interleaved small-problem batching).  SHARED
    MACHINERY: solve/plan.py schedules the triangular sweeps through
    this same function (and _level_batches) — the etree dependency is
    identical on both sides, so a change here changes BOTH dispatch
    sequences.

    A supernode is READY once every child that extend-adds into its
    front has been dispatched in an earlier batch (the Schur-scatter
    dependency = the supernode etree, symbfact.dispatch_dependencies).
    A (key, level) cell — the unit the level scheduler dispatches — is
    CLOSED once all its members are ready.  Each step dispatches, among
    shape keys with undispatched members at the oldest incomplete level
    `base`, the key whose closed cells inside the look-ahead window
    [base, base + window) hold the most members, as ONE batch (window
    <= 0 means unbounded).  Merging whole closed cells (never a ready
    subset of a cell) guarantees the group count is <= the level
    partition's — eager partial dispatch would FRAGMENT cells the level
    schedule batches together — while cross-level cells of the same key
    collapse whenever readiness allows.  Progress is guaranteed: every
    base-level cell is closed, so some key is always dispatchable.

    window=1 degenerates to the level partition (only base-level cells
    are eligible).  Batch membership only changes WHEN a front is
    factored, never the arithmetic within it, so any schedule produced
    here yields bitwise-identical L/U to the level partition
    (tests/test_schedule.py pins this).

    Returns [(wave, sns ndarray), ...]; wave = base at emission time is
    monotonically non-decreasing, so the stream executor's
    granularity="level" groupby stays contiguous.
    """
    from superlu_dist_tpu.symbolic.symbfact import dispatch_dependencies
    ns = sf.n_supernodes
    if ns == 0:
        return []
    lvl = sf.sn_level
    par = sf.sn_parent
    n_levels = int(lvl.max()) + 1
    pending = dispatch_dependencies(par)    # undispatched children per sn
    level_left = np.bincount(lvl, minlength=n_levels)
    # per (key, level) cell: undispatched member count and the ready
    # members; bucketing by level keeps each step O(keys * window)
    keys = [(int(sn_W[s]), int(sn_U[s])) for s in range(ns)]
    remaining: dict = {}
    ready: dict = {}
    for s in range(ns):
        cell = remaining.setdefault(keys[s], {})
        cell[int(lvl[s])] = cell.get(int(lvl[s]), 0) + 1
    for s in np.flatnonzero(pending == 0):
        s = int(s)
        ready.setdefault(keys[s], {}).setdefault(int(lvl[s]), []).append(s)
    out = []
    left = ns
    base = 0
    while left:
        while base < n_levels and level_left[base] == 0:
            base += 1
        limit = base + window if window >= 1 else n_levels
        best_key, best_cnt = None, 0
        for key, by_lvl in ready.items():
            if not by_lvl.get(base):
                continue        # keys absent at base defer and accumulate
            cnt = sum(len(m) for l, m in by_lvl.items()
                      if l < limit and len(m) == remaining[key][l])
            if cnt > best_cnt or (cnt == best_cnt and key < best_key):
                best_key, best_cnt = key, cnt
        assert best_cnt > 0, "scheduler stalled (cyclic dependency?)"
        by_lvl = ready[best_key]
        members = []
        for l in sorted(l for l, m in by_lvl.items()
                        if l < limit and len(m) == remaining[best_key][l]):
            members.extend(by_lvl.pop(l))
            del remaining[best_key][l]
        if not by_lvl:
            del ready[best_key]
        # slot order sorted by supernode id: batch membership is greedy
        # but the per-front arithmetic ordering stays schedule-invariant
        members.sort()
        out.append((base, np.array(members, dtype=np.int64)))
        left -= len(members)
        for s in members:
            level_left[lvl[s]] -= 1
            p = int(par[s])
            if p >= 0:
                pending[p] -= 1
                if pending[p] == 0:
                    ready.setdefault(keys[p], {}).setdefault(
                        int(lvl[p]), []).append(p)
    return out


def build_plan(sf: SymbolicFact, min_bucket: int = 8,
               growth: float = 1.5, schedule: str | None = None,
               window: int | None = None,
               align: float | None = None,
               closed: bool | None = None,
               max_keys: int | None = None) -> FactorPlan:
    """Precompute all index maps.  Pure numpy; cost is O(nnz(A) + nnz(L)).

    schedule selects the dispatch-group former: "dataflow" (default via
    SLU_TPU_SCHEDULE) packs ready supernodes into maximal same-shape
    batches across elimination levels (_dataflow_batches); "level" keeps
    the strict level-lockstep partition for A/B.  window is the dataflow
    look-ahead span in levels (SLU_TPU_SCHED_WINDOW; 1 = level order,
    0 = unbounded).  align is the shape-key coalescing flop tolerance
    (SLU_TPU_SCHED_ALIGN; <= 1 disables), applied before the schedule
    branch so both schedules pad every supernode identically.  Both
    schedules produce bitwise-identical factors — only dispatch count
    and batch occupancy differ.

    closed (SLU_TPU_BUCKET_CLOSED) additionally runs the shape-key
    CLOSURE pass (_close_shape_keys): the (W, U) key set is merged onto
    at most ``max_keys`` (SLU_TPU_BUCKET_KEYS) canonical ladder rungs,
    bounding the compiled-program count independent of matrix size —
    the mega-executor (numeric/mega.py) contract."""
    from superlu_dist_tpu.utils.options import (env_flag, env_float,
                                                env_int, env_str)
    if schedule is None:
        schedule = env_str("SLU_TPU_SCHEDULE")
    if schedule not in ("level", "dataflow"):
        raise ValueError(f"SLU_TPU_SCHEDULE must be 'level' or 'dataflow', "
                         f"got {schedule!r}")
    if window is None:
        window = env_int("SLU_TPU_SCHED_WINDOW")
    if align is None:
        align = env_float("SLU_TPU_SCHED_ALIGN")
    if closed is None:
        closed = env_flag("SLU_TPU_BUCKET_CLOSED")
    if max_keys is None:
        max_keys = env_int("SLU_TPU_BUCKET_KEYS")
    n = sf.n
    ns = sf.n_supernodes
    indptr, indices = sf.pattern_indptr, sf.pattern_indices

    widths = np.diff(sf.sn_start).astype(np.int64)
    us = np.array([len(r) for r in sf.sn_rows], dtype=np.int64)

    w_sizes = _bucket_sizes(int(widths.max(initial=1)), min_bucket, growth)
    u_sizes = _bucket_sizes(int(us.max(initial=1)), min_bucket, growth)

    sn_W = w_sizes[np.searchsorted(w_sizes, np.maximum(widths, 1))]
    sn_U = np.where(us == 0, 0,
                    u_sizes[np.searchsorted(u_sizes, np.maximum(us, 1))])
    sn_W, sn_U = _align_shape_keys(sn_W, sn_U, float(align))
    if closed:
        sn_W, sn_U = _close_shape_keys(sn_W, sn_U, int(max_keys))

    if schedule == "dataflow":
        batches = _dataflow_batches(sf, sn_W, sn_U, int(window))
        n_level_groups = len(_level_batches(sf, sn_W, sn_U))
    else:
        batches = _level_batches(sf, sn_W, sn_U)
        n_level_groups = len(batches)

    groups: list[Group] = []
    sn_group = np.empty(ns, dtype=np.int64)
    sn_slot = np.empty(ns, dtype=np.int64)
    for lvl, sns in batches:
        s0 = int(sns[0])
        W, U = int(sn_W[s0]), int(sn_U[s0])
        for slot, s in enumerate(sns):
            sn_group[s] = len(groups)
            sn_slot[s] = slot
        groups.append(Group(level=int(lvl), m=W + U, w=W, u=U,
                            batch=len(sns), sns=sns, ws=widths[sns],
                            off=None, a_slot=None, a_flat=None, a_src=None,
                            children=[]))

    # position helpers: global index x within the front of supernode s.
    # The vectorized form answers ALL (s, x) queries with one searchsorted
    # over segment-offset keys (sn_rows are sorted within each supernode and
    # supernode ids ascend, so s·(n+1)+row is globally sorted) — the
    # per-supernode Python-call version was the plan-build hot spot at
    # n ~ 1e6 (VERDICT r1 weak #4 class).
    first = sf.sn_start[:-1]
    last = sf.sn_start[1:] - 1
    rows_ptr = np.zeros(ns + 1, dtype=np.int64)
    np.cumsum(us, out=rows_ptr[1:])
    rows_concat = (np.concatenate(sf.sn_rows) if ns
                   else np.empty(0, dtype=np.int64))
    first64 = np.ascontiguousarray(first, dtype=np.int64)
    last64 = np.ascontiguousarray(last, dtype=np.int64)
    snW64 = np.ascontiguousarray(sn_W, dtype=np.int64)
    _fallback_keys = []          # built once, only if the native lib is out

    def positions_vec(s_arr: np.ndarray, x_arr: np.ndarray) -> np.ndarray:
        from superlu_dist_tpu import native
        out = native.positions(s_arr, x_arr, first64, last64, snW64,
                               rows_ptr, rows_concat)
        if out is not None:
            return out
        inpiv = x_arr <= last[s_arr]
        pos = np.where(inpiv, x_arr - first[s_arr], 0)
        below = ~inpiv
        if below.any():
            sb = s_arr[below]
            if not _fallback_keys:
                _fallback_keys.append(
                    np.repeat(np.arange(ns, dtype=np.int64), us) * (n + 1)
                    + rows_concat)
            idx = np.searchsorted(_fallback_keys[0],
                                  sb * (n + 1) + x_arr[below])
            pos[below] = sn_W[sb] + (idx - rows_ptr[sb])
        return pos

    # --- A-entry assembly maps (fully vectorized) -------------------------
    rows_all = np.repeat(np.arange(n), np.diff(indptr)).astype(np.int64)
    cols_all = indices.astype(np.int64)
    owner = sf.col_to_sn[np.minimum(rows_all, cols_all)]
    group_m = np.array([g.m for g in groups], dtype=np.int64)
    pi_all = positions_vec(owner, rows_all)
    pj_all = positions_vec(owner, cols_all)
    flat_all = pi_all * group_m[sn_group[owner]] + pj_all
    slot_all = sn_slot[owner]
    g_of_entry = sn_group[owner]
    by_group = np.argsort(g_of_entry, kind="stable")
    gbounds = np.searchsorted(g_of_entry[by_group],
                              np.arange(len(groups) + 1))
    ga_slot = [slot_all[by_group[gbounds[g]:gbounds[g + 1]]]
               for g in range(len(groups))]
    ga_flat = [flat_all[by_group[gbounds[g]:gbounds[g + 1]]]
               for g in range(len(groups))]
    ga_src = [by_group[gbounds[g]:gbounds[g + 1]]
              for g in range(len(groups))]

    # positions of every supernode's rows within its PARENT front (the
    # extend-add targets), one vectorized query for all children at once
    parent_rep = np.repeat(np.where(sf.sn_parent >= 0, sf.sn_parent, 0), us)
    rel_all = (positions_vec(parent_rep, rows_concat)
               if len(rows_concat) else rows_concat)

    # --- pool allocation (size-class free lists) --------------------------
    # Simulated in group execution order: a group's extend-add consumes its
    # children's blocks (freed), then its own Schur blocks are written
    # (allocated) — the multifrontal update-stack discipline, batched.
    free: dict[int, list] = {}
    top = 0

    def alloc(size: int) -> int:
        nonlocal top
        lst = free.get(size)
        if lst:
            return lst.pop()
        off = top
        top += size
        return off

    sn_off = np.empty(ns, dtype=np.int64)
    # children of each group, bucketed by child U size
    grp_children: list[dict[int, list]] = [dict() for _ in groups]
    for g, grp in enumerate(groups):
        # free children blocks (they are fully consumed by this group)
        for ub, lst in grp_children[g].items():
            for (c, _) in lst:
                free.setdefault(ub * ub, []).append(sn_off[c])
        # allocate this group's blocks and register with parents
        for slot, s in enumerate(grp.sns):
            if us[s] == 0:
                sn_off[s] = -1
                continue
            ub = int(sn_U[s])
            sn_off[s] = alloc(ub * ub)
            p = int(sf.sn_parent[s])
            assert p >= 0
            gp = int(sn_group[p])
            assert gp > g, "parent group must execute after child"
            grp_children[gp].setdefault(ub, []).append((s, p))

    pool_size = int(top)

    front_bytes = 0
    for g, grp in enumerate(groups):
        grp.a_slot, grp.a_flat, grp.a_src = ga_slot[g], ga_flat[g], ga_src[g]
        grp.off = np.where(us[grp.sns] > 0, sn_off[grp.sns], pool_size)
        for ub, lst in sorted(grp_children[g].items()):
            # child-id order, not dispatch order: the scatter-add rows a
            # parent front accumulates must be sequenced identically
            # under every schedule or the bitwise level/dataflow
            # equivalence guarantee breaks on ties
            lst.sort()
            C = len(lst)
            cs = np.fromiter((c for c, _ in lst), dtype=np.int64, count=C)
            ps = np.fromiter((p for _, p in lst), dtype=np.int64, count=C)
            child_off = sn_off[cs]
            child_slot = sn_slot[ps]
            rel = np.full((C, ub), grp.m, dtype=np.int64)   # sentinel = M
            # scatter each child's precomputed parent-positions into row k
            kidx = np.repeat(np.arange(C), us[cs])
            cidx = np.concatenate([np.arange(us[c]) for c in cs]) \
                if C else np.empty(0, dtype=np.int64)
            src = np.concatenate([rel_all[rows_ptr[c]:rows_ptr[c + 1]]
                                  for c in cs]) \
                if C else np.empty(0, dtype=np.int64)
            rel[kidx, cidx] = src
            grp.children.append(ChildSet(ub=ub, child_off=child_off,
                                         child_slot=child_slot, rel=rel))
        front_bytes += grp.batch * grp.m * grp.m

    # dependent-group critical path: the longest chain of groups where a
    # later group consumes a member's child from an earlier one — the
    # serial depth of the schedule (level lockstep: == n_levels)
    pdepth = np.zeros(ns, dtype=np.int64)
    critical_path = 0
    for grp in groups:
        d = int(pdepth[grp.sns].max(initial=0)) + 1
        critical_path = max(critical_path, d)
        pg = sf.sn_parent[grp.sns]
        valid = pg >= 0
        if valid.any():
            np.maximum.at(pdepth, pg[valid], d)

    return FactorPlan(n=n, sf=sf, pattern_indptr=indptr,
                      pattern_indices=indices, groups=groups,
                      pool_size=pool_size, sn_group=sn_group, sn_slot=sn_slot,
                      flops=sf.flops, front_bytes=front_bytes,
                      schedule=schedule, sched_window=int(window),
                      n_level_groups=n_level_groups,
                      critical_path=critical_path,
                      closed=bool(closed),
                      bucket_set=tuple(sorted({(g.w, g.u) for g in groups})))
