from superlu_dist_tpu.parallel.grid import ProcessGrid, gridinit
