#!/usr/bin/env python
"""Precision-safety CI gate: the throughput ladder never delivers a
failing X, and the Pallas fused path is bitwise-equal to ``.at[]``.

Phase A — BERR gate / escalation (docs/PERFORMANCE.md throughput
ladder): the bf16 GEMM tier on an ill-conditioned gallery matrix
(hilbert) must either pass the componentwise-BERR gate outright or
ESCALATE through the gemm-precision rung — the solve must come back
``converged`` with berr <= target and the ladder actions recorded in
the SolveReport.  Run twice: with iterative refinement (the default
path) and with IterRefine.NOREFINE (opting out of IR must not opt out
of the gate).

Phase B — Pallas equivalence: a full factorization of the bench-class
matrix under ``SLU_TPU_PALLAS=interpret`` must be BITWISE-identical to
the ``.at[]`` lowering on the same plan, per executor — the contract
that lets every older equivalence gate (schedule-equiv, solve-equiv,
compile-budget) carry over to the fused path unchanged.

Gate contract (scripts/ci_gates.sh): exit 0 = pass, exit 1 = any
violation, diagnostics on stdout/stderr, runs under the shared
per-gate timeout.
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


def fail(msg: str):
    print(f"FAIL: {msg}", file=sys.stderr)
    raise SystemExit(1)


def phase_a() -> None:
    from superlu_dist_tpu.drivers.gssvx import gssvx
    from superlu_dist_tpu.models.gallery import hilbert
    from superlu_dist_tpu.utils.options import IterRefine, Options
    from superlu_dist_tpu.utils import tols

    a = hilbert(8)
    b = a.matvec(np.ones(a.n_rows))
    for label, opts in (
            ("refine", Options(gemm_prec="bf16", factor_dtype="float32")),
            ("norefine", Options(gemm_prec="bf16", factor_dtype="float32",
                                 iter_refine=IterRefine.NOREFINE))):
        x, lu, stats, info = gssvx(opts, a, b)
        rep = stats.solve_report
        if info != 0:
            fail(f"phase A [{label}]: info={info}")
        if not np.all(np.isfinite(np.asarray(x))):
            fail(f"phase A [{label}]: non-finite X delivered")
        if rep.berr is None or rep.target is None:
            fail(f"phase A [{label}]: no BERR gate was applied "
                 f"({rep.summary()})")
        # the delivered gate must BE the central model's target — a
        # driver that minted its own threshold would bypass utils/tols
        want_target = float(tols.berr_target(np.float64))
        if float(rep.target) != want_target:
            fail(f"phase A [{label}]: gate target {rep.target!r} is not "
                 f"tols.berr_target(float64) = {want_target!r} — the "
                 "driver drifted off the central tolerance model")
        if not rep.converged or rep.berr > rep.target:
            fail(f"phase A [{label}]: delivered berr {rep.berr:.3e} "
                 f"misses the gate {rep.target:.3e} and was still "
                 f"reported — {rep.summary()}")
        if not rep.rungs:
            fail(f"phase A [{label}]: bf16 on hilbert(8) met the f64 "
                 "gate without any ladder action — the gate matrix is "
                 "no longer exercising escalation; pick a harder one")
        print(f"  phase A [{label}]: berr {rep.berr:.3e} <= "
              f"{rep.target:.3e} via "
              f"{[f'{r.name}[{r.detail}]' for r in rep.rungs]} "
              f"(tier {rep.gemm_precision}, dtype {rep.factor_dtype})")


def phase_b() -> None:
    from superlu_dist_tpu.drivers.gssvx import analyze
    from superlu_dist_tpu.models.gallery import poisson3d
    from superlu_dist_tpu.numeric.factor import numeric_factorize
    from superlu_dist_tpu.utils.options import Options

    a = poisson3d(10)
    lu, bvals, _ = analyze(Options(), a)
    plan, anorm = lu.plan, lu.anorm

    def run(executor):
        num = numeric_factorize(plan, bvals, anorm, dtype="float32",
                                executor=executor)
        return [(np.asarray(lp), np.asarray(up)) for lp, up in num.fronts]

    for executor in ("fused", "stream", "mega"):
        os.environ.pop("SLU_TPU_PALLAS", None)
        base = run(executor)
        os.environ["SLU_TPU_PALLAS"] = "interpret"
        try:
            pal = run(executor)
        finally:
            os.environ.pop("SLU_TPU_PALLAS", None)
        for g, ((bl, bu), (ql, qu)) in enumerate(zip(base, pal)):
            if not ((bl == ql).all() and (bu == qu).all()):
                fail(f"phase B: executor {executor} group {g} differs "
                     "between SLU_TPU_PALLAS=interpret and the .at[] "
                     "lowering — the bitwise contract is broken")
        print(f"  phase B: {executor} Pallas==.at[] bitwise over "
              f"{len(base)} groups")


def main() -> int:
    print("== precision-safety gate ==")
    phase_a()
    phase_b()
    print("precision-safety: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
