"""slulint v4 program rules — SLU111-SLU114.

Three rules run over TRACED PROGRAMS (closed jaxprs, via
``analysis/program.py`` and the ``SLU_TPU_VERIFY_PROGRAMS=1`` runtime
twin in ``utils/programaudit.py``):

SLU111 — donation/aliasing audit.  A large array input that the call
site treats as DEAD after the call but does not donate forces XLA to
allocate a fresh output buffer next to the still-live input — the
Schur-pool/panel-stack pattern that doubles peak device memory exactly
where it hurts (the pool IS the memory wall, numeric/plan.pool_size).
The submitter declares its dead argnums (liveness is a caller fact the
jaxpr cannot know); donation flags come off the traced program.  Also
reports donation coverage % per program (donated bytes over
declared-dead bytes).

SLU112 — baked-constant blowup.  Consts embedded in a program above a
size threshold are the per-matrix-capture pattern: a closure-captured
index map or panel stack makes the compiled program IDENTIFY the matrix,
so the PR 11 bucket-set warm start can never hit across matrices (and
the constant is duplicated into every executable that bakes it).  Big
data belongs in ARGUMENTS; the capturing call site is named via the
existing callgraph when the auditor can find it.

SLU114 — SPMD collective lockstep.  For programs containing collectives:
every collective's axis names must exist on the mesh (or be bound by a
nested shard_map), and every branching primitive's branches must execute
the IDENTICAL collective (op, axes) sequence — under shard_map a traced
predicate can differ per shard, so branch-divergent collectives are the
in-program analog of ranks entering different TreeComm collectives.
This is the static complement of runtime SLU106, ahead of the ROADMAP
item 1 shard_map rewrite.

One rule runs over SOURCE (part of the slulint CLI rule set):

SLU113 — host round-trip in the dispatch loop.  Extends SLU102 beyond
jit bodies: ``float()``/``int()``/``bool()``/``.item()``/``np.asarray``
on a DEVICE value — or an ``if``/``while`` test on one — inside a
per-group dispatch loop blocks the async dispatch stream once per group
(the silent serializer of the streamed executors).  Found via the v2
dataflow lattice's new ``device`` taint: results of jnp ops and of
calling jitted programs (jit-factory results tracked through the call
graph).  ``jax.device_get`` / ``jax.block_until_ready`` are the
sanctioned EXPLICIT syncs and clear the taint — making the transfer
visible is exactly the fix.
"""

from __future__ import annotations

import ast

from superlu_dist_tpu.analysis.core import Finding, Rule, dotted_name
from superlu_dist_tpu.analysis.dataflow import TAINT_DEVICE, FnFlow
from superlu_dist_tpu.analysis.program import (ProgramSpec, aval_bytes,
                                               bound_axis_names,
                                               branch_divergences,
                                               collective_sequence,
                                               const_bytes, eqn_axes,
                                               iter_eqns, COLLECTIVE_PRIMS)

RULE_DONATION = "SLU111"
RULE_BAKED_CONST = "SLU112"
RULE_HOST_ROUNDTRIP = "SLU113"
RULE_COLLECTIVE_LOCKSTEP = "SLU114"


def _program_finding(rule: str, spec: ProgramSpec, message: str,
                     hint: str) -> Finding:
    # program findings anchor at a pseudo-path: there is no source line
    # for a jaxpr, but the (site, label) pair identifies the build site
    return Finding(rule, f"<program:{spec.site}[{spec.label}]>", 0, 1,
                   message, hint)


# --------------------------------------------------------------------------
# SLU111 — donation/aliasing
# --------------------------------------------------------------------------

def audit_donation(spec: ProgramSpec, min_bytes: int):
    """Findings for declared-dead inputs >= min_bytes not donated, plus
    {donated_bytes, dead_bytes, donation_coverage_pct}."""
    avals = spec.in_avals
    donated = set(spec.donated)
    dead = set(spec.dead)
    donated_bytes = sum(aval_bytes(avals[i]) for i in donated
                        if i < len(avals))
    dead_bytes = sum(aval_bytes(avals[i]) for i in dead if i < len(avals))
    findings = []
    for i in sorted(dead - donated):
        if i >= len(avals):
            continue
        nb = aval_bytes(avals[i])
        if nb < min_bytes:
            continue
        findings.append(_program_finding(
            RULE_DONATION, spec,
            f"argument {i} ({getattr(avals[i], 'str_short', lambda: avals[i])()}"
            f", {nb} bytes) is dead after the call but NOT donated — XLA "
            "must materialize the output beside the still-live input, "
            "doubling this buffer's peak footprint",
            "donate dead large inputs (jax.jit(..., donate_argnums=...)) "
            "so XLA writes in place — the Schur pool discipline of "
            "stream._kernel"))
    denom = max(donated_bytes + sum(
        aval_bytes(avals[i]) for i in sorted(dead - donated)
        if i < len(avals)), 1)
    coverage = 100.0 if not dead else round(100.0 * donated_bytes / denom, 2)
    return findings, {"donated_bytes": int(donated_bytes),
                      "dead_bytes": int(dead_bytes),
                      "donation_coverage_pct": coverage}


# --------------------------------------------------------------------------
# SLU112 — baked constants
# --------------------------------------------------------------------------

def audit_baked_consts(spec: ProgramSpec, max_bytes: int):
    """Findings for consts >= max_bytes, plus {baked_const_bytes,
    n_consts}."""
    consts = list(getattr(spec.jaxpr, "consts", ()))
    total = sum(const_bytes(c) for c in consts)
    findings = []
    for c in consts:
        nb = const_bytes(c)
        if nb < max_bytes:
            continue
        shape = getattr(c, "shape", ())
        dtype = getattr(c, "dtype", "?")
        findings.append(_program_finding(
            RULE_BAKED_CONST, spec,
            f"constant {tuple(shape)}:{dtype} ({nb} bytes) is BAKED into "
            "the program — a closure-captured per-matrix array makes the "
            "compiled program identify the matrix, defeating the "
            "bucket-set warm start (and duplicating the data into every "
            "executable that bakes it)",
            "pass large arrays as ARGUMENTS instead of closing over them "
            "(the make_factor_fn/_level_fn fix): program shapes may "
            "encode buckets, program CONSTANTS must not encode matrices"))
    return findings, {"baked_const_bytes": int(total),
                      "n_consts": len(consts)}


# --------------------------------------------------------------------------
# SLU114 — SPMD collective lockstep
# --------------------------------------------------------------------------

def audit_collective_lockstep(spec: ProgramSpec):
    seq = collective_sequence(spec.jaxpr)
    if not seq and not any(
            getattr(e.primitive, "name", "") in COLLECTIVE_PRIMS
            for e in iter_eqns(spec.jaxpr)):
        return []
    findings = []
    # (a) axis-name consistency against the mesh (+ nested binders)
    valid = set(spec.mesh_axes) | bound_axis_names(spec.jaxpr)
    if valid:
        for eqn in iter_eqns(spec.jaxpr):
            name = getattr(eqn.primitive, "name", "")
            if name not in COLLECTIVE_PRIMS:
                continue
            bad = [a for a in eqn_axes(eqn) if a not in valid]
            if bad:
                findings.append(_program_finding(
                    RULE_COLLECTIVE_LOCKSTEP, spec,
                    f"collective `{name}` reduces over axis "
                    f"{','.join(map(repr, bad))} which is bound by "
                    f"neither the mesh ({sorted(valid)}) nor a nested "
                    "shard_map — the program cannot run lockstep on the "
                    "mesh it was built for",
                    "collectives must name axes of the mesh the program "
                    "is mapped over"))
    # (b) identical collective sequence on every branch of every
    # branching primitive (the static shard-divergence witness)
    for eqn, seqs in branch_divergences(spec.jaxpr):
        name = getattr(eqn.primitive, "name", "cond")
        rendered = "; ".join(
            f"branch {i}: {[f'{p}@{list(a)}' for p, a in s] or 'none'}"
            for i, s in enumerate(seqs))
        findings.append(_program_finding(
            RULE_COLLECTIVE_LOCKSTEP, spec,
            f"`{name}` branches execute DIVERGENT collective sequences "
            f"({rendered}) — under shard_map the predicate can differ "
            "per shard, so some shards enter a collective their peers "
            "never reach (the in-program SLU106 deadlock)",
            "hoist collectives out of data-dependent branches, or make "
            "every branch run the identical collective sequence"))
    return findings


# --------------------------------------------------------------------------
# SLU113 — host round-trips in dispatch loops (source rule)
# --------------------------------------------------------------------------

_COERCIONS = frozenset({"float", "int", "bool"})
_NP_MATERIALIZERS = frozenset({
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "onp.asarray", "onp.array"})


class _DispatchFlow(FnFlow):
    """FnFlow with the SLU113 in-loop coercion scan attached."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.hits: dict = {}     # (line, col) -> (anchor node, message)

    def _device(self, expr) -> str | None:
        t = self.taint(expr)
        return t.get(TAINT_DEVICE)

    def _scan_expr(self, expr) -> None:
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            prov = None
            what = None
            if name in _COERCIONS and node.args:
                prov = self._device(node.args[0])
                what = f"`{name}()`"
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "item":
                prov = self._device(node.func.value)
                what = "`.item()`"
            elif name in _NP_MATERIALIZERS and node.args:
                prov = self._device(node.args[0])
                what = f"`{name}`"
            if prov is not None:
                self._hit(node, what, prov)

    def _hit(self, node, what, prov) -> None:
        key = (node.lineno, node.col_offset)
        if key not in self.hits:
            self.hits[key] = (node, f"{what} on a device value ({prov}) "
                              "inside the dispatch loop — a blocking "
                              "host round-trip once per group, "
                              "serializing the async dispatch stream")

    def visit_stmt(self, st) -> None:
        if self.loop_depth == 0:
            return
        if isinstance(st, (ast.If, ast.While)):
            prov = self._device(st.test)
            if prov is not None:
                self._hit(st.test, "bool-coercion of the branch test",
                          prov)
            self._scan_expr(st.test)
            return
        if isinstance(st, (ast.For, ast.AsyncFor)):
            self._scan_expr(st.iter)
            return
        if isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                self._scan_expr(item.context_expr)
            return
        if isinstance(st, ast.Try):
            return
        self._scan_expr(st)


class HostRoundTripRule(Rule):
    rule_id = RULE_HOST_ROUNDTRIP
    title = "host-round-trip-in-dispatch-loop"
    hint = ("keep the dispatch loop async: batch the value with the "
            "stream and materialize AFTER the loop, or make the sync "
            "explicit with jax.device_get / jax.block_until_ready "
            "(explicit syncs are exempt — visibility is the point)")
    package_dirs = ("numeric", "solve")

    def check(self, tree, source, path, project=None):
        if project is None:
            return []
        out = []
        for qname, fi in project.functions.items():
            if fi.path != path:
                continue
            flow = _DispatchFlow.for_function(project, fi)
            flow.run()
            for key in sorted(flow.hits):
                node, msg = flow.hits[key]
                out.append(self.finding(path, node, msg))
        return out
