"""Multi-process iterative refinement over block-row distributed A.

Capability analog of pdgsrfs + pdgsmv (SRC/pdgsrfs.c:120, pdgsmv.c:234):
the reference computes the residual r = b − A·x with each rank holding a
block of rows (NRformat_loc) and exchanging the needed x entries, then
solves the correction on the distributed factors.  Here each process owns
a `DistributedCSR` block row; the x exchange that the reference does with
per-rank index lists becomes one tree all-reduce of the zero-padded
block vectors (parallel/treecomm.py — the same collective engine the
reference builds from its Bc/Rd trees), and the correction solve runs on
the factor-owning root and is tree-broadcast back.

This is the host multi-process tier of the refinement stack; on an
accelerator the single-process DeviceSpMV path (drivers/gssvx.py) is
used instead.  Every rank calls `pgsrfs` collectively and receives the
full refined solution.  The per-iteration collective sequence
(allreduce residual -> allreduce denominator -> [bcast dx]) must stay
identical on every rank — the convergence test uses the allreduced
berr, never per-rank values, so all ranks break the loop together;
SLU_TPU_VERIFY_COLLECTIVES=1 (runtime SLU106, docs/ANALYSIS.md) checks
exactly this lockstep at runtime and names divergent call sites
instead of deadlocking.
"""

from __future__ import annotations

import numpy as np

from superlu_dist_tpu.parallel.dist import DistributedCSR
from superlu_dist_tpu.parallel.treecomm import TreeComm
from superlu_dist_tpu.refine.ir import ITMAX, componentwise_berr


def _pad_full(local: np.ndarray, fst_row: int, n: int) -> np.ndarray:
    out = np.zeros(n, dtype=np.result_type(local, np.float64))
    out[fst_row:fst_row + len(local)] = local
    return out


def pgsrfs(tc: TreeComm, a_loc: DistributedCSR, b_loc: np.ndarray,
           x0: np.ndarray | None, solve_fn, itmax: int = ITMAX,
           root: int = 0, trans=None,
           collective_solve: bool = False,
           stats_out: dict | None = None) -> np.ndarray:
    """Collectively refine op(A)·x = b (single RHS; op per `trans` —
    NOTRANS/TRANS/CONJ like pdgssvx's trans dispatch; complex payloads
    ride the f64 tree as re/im passes via TreeComm.*_any).

    tc       — this rank's TreeComm attachment.
    a_loc    — this rank's block rows of A (global column indices).
    b_loc    — this rank's block of b.
    x0       — initial solution (significant on the root; may be None on
               the others).
    solve_fn — correction solver dx = op(A)⁻¹ r; significant on the root
               only (the factor owner — the reference's analog is that
               every rank participates in pdgstrs, here the factors live
               with the root process).  With collective_solve=True, the
               factors live SHARDED across all ranks' devices (the mesh
               tier) and solve_fn is an SPMD program every rank must
               enter: all ranks call it on the same replicated residual
               and the dx broadcast is skipped — this IS the reference's
               shape, where pdgstrs runs on the whole grid inside
               pdgsrfs (SRC/pdgsrfs.c:205).
    stats_out — optional dict filled with {"iters", "berr", "berrs"}:
               the iteration count and componentwise backward-error
               history (every rank gets the same values — they are
               computed from allreduced quantities).

    Returns the full refined x on every rank.
    """
    from superlu_dist_tpu.utils.options import Trans
    if trans is None:
        trans = Trans.NOTRANS
    n = a_loc.n
    eps = float(np.finfo(np.float64).eps)
    cplx = np.iscomplexobj(a_loc.data) or np.iscomplexobj(b_loc)
    wdtype = np.complex128 if cplx else np.float64

    # x lives replicated (root broadcasts), like pdgsrfs's x updates
    x = (np.zeros(n, dtype=wdtype) if x0 is None
         else np.asarray(x0, dtype=wdtype))
    x = tc.bcast_any(x, root=root)

    # global nnz for the shared BERR underflow guard (refine/ir.py's
    # componentwise_berr — the safe1·safmin bump, NOT a den>0 -> 1.0
    # rewrite, which understates berr on tiny denominators)
    cnt = np.zeros(1)
    cnt[0] = float(a_loc.nnz_loc)
    nnz_glob = int(tc.allreduce_sum_any(cnt, root=root)[0])

    berrs = []
    lstres = np.inf
    for _ in range(itmax):
        # r = b − op(A)·x as one all-reduce of per-rank contributions
        # (the pdgsmv exchange analog).  NOTRANS: block rows are disjoint
        # slots; TRANS/CONJ: block rows of A are block columns of op(A),
        # so every rank contributes a full-length partial sum.
        if trans == Trans.NOTRANS:
            r_c = _pad_full(b_loc - a_loc.matvec_local(x),
                            a_loc.fst_row, n)
            den_c = _pad_full(a_loc.abs_matvec_local(np.abs(x))
                              + np.abs(b_loc), a_loc.fst_row, n)
        else:
            conj = trans == Trans.CONJ
            r_c = (_pad_full(b_loc, a_loc.fst_row, n)
                   - a_loc.matvec_trans_local(x, conj=conj))
            den_c = (a_loc.abs_matvec_trans_local(np.abs(x))
                     + _pad_full(np.abs(b_loc), a_loc.fst_row, n))
        r = tc.allreduce_sum_any(r_c, root=root)
        # componentwise backward error denominator |op(A)|·|x| + |b|
        den = tc.allreduce_sum_any(den_c, root=root)
        berr = componentwise_berr(r, den, nnz_glob, np.float64)
        berrs.append(berr)
        if berr <= eps or berr >= lstres / 2.0:
            break
        lstres = berr
        if collective_solve:
            # mesh tier: every rank enters the SPMD correction solve with
            # the identical allreduced residual; results are replicated
            dx = np.asarray(solve_fn(r), dtype=wdtype)
        else:
            # correction on the factor owner, broadcast to all
            dx = np.zeros(n, dtype=wdtype)
            if tc.rank == root:
                dx = np.asarray(solve_fn(r), dtype=wdtype)
            dx = tc.bcast_any(dx, root=root)
        x = x + dx
    if stats_out is not None:
        stats_out["iters"] = len(berrs)
        stats_out["berr"] = berrs[-1] if berrs else None
        stats_out["berrs"] = berrs
    return x
