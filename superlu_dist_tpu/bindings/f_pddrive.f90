! Fortran example driver — capability analog of the reference's
! FORTRAN/f_pddrive.f90 + f_5x5.f90: solve a small sparse system through
! the handle-based Fortran interface (superlu_mod.f90 -> slu_tpu.h C API).
!
! The 5x5 test system is the same shape the reference's f_5x5 example
! uses: an unsymmetric pattern with a known solution of all ones.
!
! Build (needs gfortran; the CI skips when absent):
!   python -m superlu_dist_tpu.bindings.build          # libslu_tpu.so
!   gfortran -o f_pddrive superlu_mod.f90 f_pddrive.f90 \
!       -L. -lslu_tpu $(python3-config --embed --ldflags)
!   ./f_pddrive

program f_pddrive
  use superlu_tpu
  use iso_c_binding
  implicit none

  integer(c_int64_t), parameter :: n = 5, nnz = 12, nrhs = 1
  integer(c_int64_t) :: indptr(n + 1), indices(nnz)
  real(c_double) :: values(nnz), b(n), x(n)
  real(c_double) :: err
  integer(c_int) :: info
  integer :: i

  ! CSR of the 5x5 example matrix (rows: diagonal plus off-diagonals)
  indptr  = [0_c_int64_t, 3_c_int64_t, 5_c_int64_t, 8_c_int64_t, &
             10_c_int64_t, 12_c_int64_t]
  indices = [0_c_int64_t, 2_c_int64_t, 4_c_int64_t, &
             1_c_int64_t, 3_c_int64_t, &
             0_c_int64_t, 2_c_int64_t, 4_c_int64_t, &
             1_c_int64_t, 3_c_int64_t, &
             0_c_int64_t, 4_c_int64_t]
  values  = [19.0d0, 21.0d0, 21.0d0, &
             12.0d0, 12.0d0, &
             12.0d0, 16.0d0, 12.0d0, &
             5.0d0, 18.0d0, &
             12.0d0, 18.0d0]

  ! b = A * ones  =>  expected x = ones
  b = 0.0d0
  do i = 1, int(n)
     block
       integer :: k
       do k = int(indptr(i)) + 1, int(indptr(i + 1))
          b(i) = b(i) + values(k)
       end do
     end block
  end do

  info = slu_tpu_init(c_char_"cpu" // c_null_char)
  if (info /= 0) stop "slu_tpu_init failed"

  info = slu_tpu_solve(n, nnz, indptr, indices, values, b, x, nrhs)
  if (info /= 0) stop "slu_tpu_solve failed"

  err = maxval(abs(x - 1.0d0))
  print "(a, es10.3)", "f_pddrive: ||x - ones||_inf = ", err
  if (err > 1.0d-10) stop "accuracy check FAILED"

  ! ---- full-surface path: options + factor-once / solve-twice reuse ----
  ! (the reference f_pddrive3-style sequence: FACTORED re-solve, then a
  ! SamePattern_SameRowPerm refactorization with new values)
  call full_surface_sequence()

  print *, "f_pddrive: PASS"
  call slu_tpu_finalize()

contains

  subroutine full_surface_sequence()
    integer(c_int64_t) :: opt, handle
    real(c_double) :: b2(n, 2), x2(n, 2), values2(nnz), stat_val
    character(kind=c_char) :: buf(32)
    integer :: j, k2
    integer(c_int) :: rc

    rc = slu_tpu_options_create(opt)
    if (rc /= 0) stop "options_create failed"
    rc = slu_tpu_options_set(opt, c_char_"ColPerm" // c_null_char, &
                             c_char_"COLAMD" // c_null_char)
    if (rc /= 0) stop "options_set ColPerm failed"
    rc = slu_tpu_options_set(opt, c_char_"IterRefine" // c_null_char, &
                             c_char_"SLU_DOUBLE" // c_null_char)
    if (rc /= 0) stop "options_set IterRefine failed"
    rc = slu_tpu_options_get(opt, c_char_"ColPerm" // c_null_char, buf, &
                             32_c_int64_t)
    if (rc /= 0) stop "options_get failed"

    ! factor once under the options handle
    rc = slu_tpu_factor_opts(opt, n, nnz, indptr, indices, values, handle)
    if (rc /= 0) stop "factor_opts failed"

    ! solve 1: two right-hand sides, FACTORED tier
    do j = 1, 2
       do k2 = 1, int(n)
          b2(k2, j) = real(j, c_double) * b(k2)
       end do
    end do
    rc = slu_tpu_solve_factored_opts(handle, 0_c_int64_t, n, b2, n, &
                                     x2, n, 2_c_int64_t)
    if (rc /= 0) stop "solve_factored_opts failed"
    if (maxval(abs(x2(:, 1) - 1.0d0)) > 1.0d-10) stop "reuse solve 1 FAILED"
    if (maxval(abs(x2(:, 2) - 2.0d0)) > 1.0d-10) stop "reuse solve 2 FAILED"

    ! refactor with scaled values (same pattern, tier 2 =
    ! SamePattern_SameRowPerm), then solve again through the same handle
    values2 = 2.0d0 * values
    rc = slu_tpu_refactor(handle, nnz, values2, 2_c_int64_t)
    if (rc /= 0) stop "refactor failed"
    rc = slu_tpu_solve_factored_opts(handle, 0_c_int64_t, n, b2, n, &
                                     x2, n, 2_c_int64_t)
    if (rc /= 0) stop "post-refactor solve failed"
    if (maxval(abs(x2(:, 1) - 0.5d0)) > 1.0d-10) stop "refactor solve FAILED"

    ! statistics surface
    rc = slu_tpu_stat_get(handle, c_char_"FACT" // c_null_char, stat_val)
    if (rc /= 0 .or. stat_val < 0.0d0) stop "stat_get FACT failed"
    rc = slu_tpu_stat_get(handle, c_char_"NNZ_L" // c_null_char, stat_val)
    if (rc /= 0 .or. stat_val < real(n, c_double)) stop "stat_get NNZ_L failed"

    rc = slu_tpu_free_handle(handle)
    if (rc /= 0) stop "free_handle failed"
    rc = slu_tpu_options_free(opt)
    if (rc /= 0) stop "options_free failed"
    print *, "f_pddrive: full-surface reuse sequence OK"
  end subroutine full_surface_sequence
end program f_pddrive
