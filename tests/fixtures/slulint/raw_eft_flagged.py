"""SLU117 true-positive fixture (EFT purity): raw +/-/* on the hi/lo
components a two_sum/df64 primitive returned, outside ops/df64.py —
exactly the reassociation-bait the optimization_barrier fences exist to
prevent; and a fixture-local two_sum whose compensation arithmetic is
not fenced at all."""
from superlu_dist_tpu.ops.df64 import df64_add, two_sum


def leak(xh, xl, yh, yl):
    sh, sl = df64_add(xh, xl, yh, yl)
    return sh + sl                         # flagged: raw add on pair


def drift(a, b):
    hi, lo = two_sum(a, b)
    return hi * 2.0 - lo                   # flagged: raw mul and sub


def quick_two_sum(a, b):                   # unfenced EFT kernel
    s = a + b                              # flagged: no barrier
    return s, b - (s - a)                  # flagged: both subtractions
