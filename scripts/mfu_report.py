#!/usr/bin/env python
"""Summarize tuning rows + kernel-shape traces into an MFU report.

Inputs: tune_results.jsonl (one JSON row per bench config) and
tune_results.err (stderr log containing `# lvl=... m=... w=... u=...`
kernel-trace lines emitted by bench.py when SLU_TPU_PROFILE=1 — the
reference's dgemm_mnk.dat analog, SRC/pdgstrf.c:380-387).

Prints: ranked result table, dispatch-vs-compute split, and the top
kernel-time sinks — the "top-3 MFU thieves" evidence VERDICT r2 #9 asks
for.  Pure text processing; safe to run anywhere.
"""

import json
import re
import sys


def main():
    import os
    # live session logs are gitignored; fall back to the committed
    # docs/ snapshot of the latest hardware session when absent
    out = sys.argv[1] if len(sys.argv) > 1 else "tune_results.jsonl"
    err = sys.argv[2] if len(sys.argv) > 2 else "tune_results.err"
    if len(sys.argv) <= 1 and not os.path.exists(out):
        out, err = "docs/tune_results_r3.jsonl", "docs/tune_results_r3.err"

    rows = []
    try:
        for line in open(out):
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                pass
    except FileNotFoundError:
        pass

    tpu = [r for r in rows if r.get("value") is not None
           and r.get("backend") not in (None, "cpu")]
    tpu.sort(key=lambda r: -r["value"])
    print("== TPU rows (ranked by factor GFLOP/s) ==")
    for r in tpu:
        disp = r.get("dispatch_seconds")
        fs = r.get("factor_seconds", 0.0) or 0.0
        dshare = (f" dispatch {100 * disp / fs:4.0f}%"
                  if disp is not None and fs else "")
        print(f"{r['value']:8.1f} GF/s  mfu {r.get('mfu_pct', 0):5.2f}%  "
              f"pad {r.get('padding_factor', '?'):>4}  "
              f"{r.get('granularity', '?'):<6} "
              f"kern {r.get('n_kernels', '?'):>3}{dshare}  "
              f"resid {r.get('residual', float('nan')):.1e}  "
              f"{r['metric']}"
              + (f"  [{','.join(str(b) for b in r['blocking'])}]"
                 if r.get("blocking") else ""))

    # kernel trace lines: "# lvl=3  B=16  m=512  w=256  u=256  12.34 ms  567.8 GF/s"
    pat = re.compile(
        r"# lvl=\s*(\d+)\s+B=\s*(\d+)\s+m=\s*(\d+)\s+w=\s*(\d+)\s+"
        r"u=\s*(\d+)\s+([\d.]+) ms\s+([\d.]+) GF/s")
    kernels = []
    try:
        for line in open(err):
            m = pat.search(line)
            if m:
                lvl, B, mm, w, u = (int(m.group(i)) for i in range(1, 6))
                ms, gfs = float(m.group(6)), float(m.group(7))
                kernels.append((ms, gfs, lvl, B, mm, w, u))
    except FileNotFoundError:
        pass
    if kernels:
        total = sum(k[0] for k in kernels)
        print(f"\n== kernel trace: {len(kernels)} entries, "
              f"{total:.1f} ms profiled ==")
        print("top sinks (ms, GF/s, lvl, batch, m, w, u, % of profiled):")
        for ms, gfs, lvl, B, mm, w, u in sorted(kernels)[::-1][:12]:
            print(f"  {ms:8.2f} ms {gfs:8.1f} GF/s  lvl={lvl:<3d} B={B:<5d} "
                  f"m={mm:<5d} w={w:<5d} u={u:<5d}  {100 * ms / total:4.1f}%")


if __name__ == "__main__":
    main()
