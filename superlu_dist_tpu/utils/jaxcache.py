"""Persistent XLA compile-cache policy, in one place.

Every driver/bench/measurement entry point points jax at the repo-local
cache (`.cache/jax`, gitignored) so kernels compile once per machine —
through the remote-compile TPU tunnel a single kernel costs ~8-40 s, so
cache reuse is the difference between a bench that finishes and one
that hits its watchdog (BASELINE.md round-2/3 compile-wall history).
"""

import os


def enable_compile_cache(cache_dir: str | None = None) -> None:
    """Point jax at the persistent compile cache (default: the repo's
    `.cache/jax`, resolved relative to this package).  Caches every
    entry regardless of size/compile time.  Never raises — the cache is
    an optimization, not a failure reason.  Call any time before (or
    after) backend init; only subsequent compiles are affected."""
    import jax
    if cache_dir is None:
        cache_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), ".cache", "jax")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass
