#!/usr/bin/env python
"""slulint entry point — identical to `python -m superlu_dist_tpu.analysis`.

Kept as a script so the gate (run_slulint.sh), editors, and pre-commit
hooks have a stable path that works from any cwd.  See docs/ANALYSIS.md
for the rule catalog (SLU101-SLU105), suppressions, and the baseline
workflow.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from superlu_dist_tpu.analysis.__main__ import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
