"""Distributed analysis (ParSymbFact tier, parallel/panalysis.py).

The reference validates its parallel symbolic by factoring the same
systems through both analysis paths (psymbfact vs symbfact) and
checking the solves; we do the same — the skeleton a 4-process
panalyze produces must factor and solve to the same residual class as
the serial analysis.  Unit tests pin the two core invariants the
psymbfact shape rests on: projected coarse separators really separate
(no cross-part edge survives), and the bordered symbolic with an empty
border reproduces the serial supernodal fill.
"""

import multiprocessing as mp
import os

import numpy as np
import pytest

from superlu_dist_tpu import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library unavailable")


# ---------------------------------------------------------------------------
# unit: coarse bisection produces a true vertex separation
# ---------------------------------------------------------------------------

def test_coarse_bisect_separates():
    from superlu_dist_tpu.models.gallery import poisson2d
    from superlu_dist_tpu.parallel.panalysis import _coarse_bisect
    from superlu_dist_tpu.sparse.formats import symmetrize_pattern

    a = symmetrize_pattern(poisson2d(20))
    n = a.n_rows
    for nparts in (2, 4, 3):
        labels, nsep, part_anc = _coarse_bisect(
            n, a.indptr, a.indices, np.ones(n), nparts)
        assert labels.min() >= -nsep and labels.max() < nparts
        assert set(part_anc) == set(range(nparts))
        # every part's label region is bounded by its ancestor chain
        for p, anc in part_anc.items():
            assert all(0 <= s < nsep for s in anc)
        # every vertex labeled; no edge joins two different parts
        rows = np.repeat(np.arange(n), np.diff(a.indptr))
        lr, lc = labels[rows], labels[a.indices]
        cross = (lr >= 0) & (lc >= 0) & (lr != lc)
        assert not cross.any(), "separator failed to separate parts"
        # parts are reasonably balanced (weighted bisection)
        sizes = [(labels == p).sum() for p in range(nparts)]
        assert sum(sizes) + (labels < 0).sum() == n


def test_coarse_bisect_odd_ranks_heavier_component_gets_more_ranks():
    """Disconnected coarse graph + odd rank count: the heavier component
    must take the LARGER rank half (ranks[half:]) — the historical slice
    order handed it the smaller one, inverting the weight balance for
    non-power-of-2 rank counts (ADVICE round 5)."""
    from superlu_dist_tpu.parallel.panalysis import _coarse_bisect
    from superlu_dist_tpu.sparse.formats import coo_to_csr

    # two disconnected paths: heavy (10 vertices, contains vertex 0, so
    # BFS from nodes[0] finds it first) and light (3 vertices)
    heavy, light = np.arange(10), np.arange(10, 13)
    n = 13
    r = np.concatenate([heavy[:-1], heavy[1:], light[:-1], light[1:]])
    c = np.concatenate([heavy[1:], heavy[:-1], light[1:], light[:-1]])
    g = coo_to_csr(n, n, r, c, np.zeros(len(r)))
    for nparts in (3, 5):
        labels, _nsep, part_anc = _coarse_bisect(
            n, g.indptr, g.indices, np.ones(n), nparts)
        heavy_parts = {int(p) for p in labels[heavy] if p >= 0}
        light_parts = {int(p) for p in labels[light] if p >= 0}
        assert heavy_parts.isdisjoint(light_parts)
        # the heavy component's rank share strictly exceeds the light's
        assert len(heavy_parts) > len(light_parts), (
            nparts, heavy_parts, light_parts)
        assert set(part_anc) == set(range(nparts))


def test_cross_part_edge_raises_collectively_on_all_ranks():
    """The cross-part-edge invariant in _part_symbolic must fail via the
    allreduce-flag + collective SuperLUError pattern: EVERY rank raises
    (a bare assert would fire on a rank subset and strand the peers in
    the gather collectives — and vanish under python -O)."""
    import multiprocessing as _mp

    from superlu_dist_tpu.parallel.panalysis import _part_symbolic
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    from superlu_dist_tpu.utils.errors import SuperLUError
    from superlu_dist_tpu.utils.options import Options

    n, P = 8, 2
    # labels: vertices 0-3 -> part 0, 4-7 -> part 1; NO separator.  A
    # direct edge (1, 5) crosses the parts — only rank 0 observes it.
    lab = np.array([0, 0, 0, 0, 1, 1, 1, 1], dtype=np.int64)

    def run(rank, q):
        with TreeComm(name, P, rank, max_len=1 << 14,
                      create=False) as tc:
            if rank == 0:
                pr = np.array([0, 1, 1], dtype=np.int64)
                pc = np.array([1, 0, 5], dtype=np.int64)   # 1-5 crosses
            else:
                pr = np.array([4, 5], dtype=np.int64)
                pc = np.array([5, 4], dtype=np.int64)
            pv = np.ones(len(pr), dtype=np.float64)
            try:
                _part_symbolic(tc, n, P, lab, pr, pc, pv, Options(),
                               np.float64)
                q.put((rank, "no-error"))
            except SuperLUError:
                q.put((rank, "superlu-error"))

    name = f"/slu_xedge_{os.getpid()}"
    owner = TreeComm(name, P, 0, max_len=1 << 14, create=True)
    try:
        ctx = _mp.get_context("fork")
        q = ctx.Queue()
        proc = ctx.Process(target=run, args=(1, q))
        proc.start()
        try:
            pr = np.array([0, 1, 1], dtype=np.int64)
            pc = np.array([1, 0, 5], dtype=np.int64)
            pv = np.ones(len(pr), dtype=np.float64)
            with pytest.raises(SuperLUError):
                _part_symbolic(owner, n, P, lab, pr, pc, pv, Options(),
                               np.float64)
        finally:
            rank, outcome = q.get(timeout=120)
            proc.join(timeout=60)
        assert outcome == "superlu-error", outcome
    finally:
        if proc.is_alive():
            proc.kill()
        owner.close()


# ---------------------------------------------------------------------------
# unit: bordered symbolic, empty border == serial supernodal fill
# ---------------------------------------------------------------------------

def test_bordered_symbolic_matches_serial():
    from superlu_dist_tpu.models.gallery import poisson2d
    from superlu_dist_tpu.parallel.panalysis import _bordered_symbolic
    from superlu_dist_tpu.sparse.formats import symmetrize_pattern
    from superlu_dist_tpu.symbolic.symbfact import symbolic_factorize

    a = symmetrize_pattern(poisson2d(12))
    n = a.n_rows
    order = np.arange(n)
    sf = symbolic_factorize(a, order, relax=8, max_supernode=64,
                            amalg_tol=0)
    post, sn_start, sn_rows, sn_parent, parent_cols = _bordered_symbolic(
        n, n, a.indptr, a.indices, relax=8, max_supernode=64)
    widths = np.diff(sn_start)
    us = np.array([len(r) for r in sn_rows])
    nnz = int(np.sum(widths * (widths + 1) // 2) + np.sum(widths * us))
    assert nnz == sf.nnz_L, (nnz, sf.nnz_L)
    assert len(post) == n and sn_start[-1] == n


def test_python_builder_matches_native():
    """The shared pure-python supernode builder (the non-native path of
    _bordered_symbolic) agrees with the native twin on fill size."""
    from superlu_dist_tpu.models.gallery import poisson2d
    from superlu_dist_tpu.ordering.etree import etree_symmetric
    from superlu_dist_tpu.sparse.formats import symmetrize_pattern
    from superlu_dist_tpu.symbolic.symbfact import build_supernodes_py

    a = symmetrize_pattern(poisson2d(10))
    n = a.n_rows
    parent = native.etree(n, a.indptr, a.indices)
    if parent is None:
        parent = etree_symmetric(n, a.indptr, a.indices)
    # natural order need not postorder this etree (subtrees may be
    # non-contiguous) — strict=False must survive it, like the bordered
    # caller's partially-ordered boundary regime
    sn_start, c2s, sn_rows, sn_parent = build_supernodes_py(
        n, a.indptr, a.indices, parent, 8, 64, strict=False)
    w = np.diff(sn_start)
    us = np.array([len(r) for r in sn_rows])
    nnz = int(np.sum(w * (w + 1) // 2) + np.sum(w * us))
    nat = native.symbolic(n, a.indptr, a.indices, parent, 8, 64)
    if nat is not None:
        nw = np.diff(nat[0])
        nus = np.diff(nat[4])
        nat_nnz = int(np.sum(nw * (nw + 1) // 2) + np.sum(nw * nus))
        assert nnz == nat_nnz, (nnz, nat_nnz)


# ---------------------------------------------------------------------------
# integration: 4 OS processes, skeleton factors + solves correctly
# ---------------------------------------------------------------------------

def _worker(name, n_ranks, rank, build, opts_kw, q):
    from superlu_dist_tpu.parallel.dist import distribute_rows
    from superlu_dist_tpu.parallel.panalysis import panalyze
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    from superlu_dist_tpu.utils.options import Options
    a = build()
    parts = distribute_rows(a, n_ranks)
    with TreeComm(name, n_ranks, rank, max_len=1 << 16,
                  create=False) as tc:
        lu, bvals = panalyze(tc, Options(**opts_kw), parts[rank])
    q.put((rank, lu is not None and bvals is not None))


def _run_panalyze(build, opts_kw, n_ranks=4):
    from superlu_dist_tpu.parallel.dist import distribute_rows
    from superlu_dist_tpu.parallel.panalysis import panalyze
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    from superlu_dist_tpu.utils.options import Options

    name = f"/slu_panl_{os.getpid()}"
    a = build()
    parts = distribute_rows(a, n_ranks)
    owner = TreeComm(name, n_ranks, 0, max_len=1 << 16, create=True)
    ctx = mp.get_context("fork")
    q = ctx.Queue()
    procs = [ctx.Process(target=_worker,
                         args=(name, n_ranks, r, build, opts_kw, q))
             for r in range(1, n_ranks)]
    try:
        for p in procs:
            p.start()
        lu, bvals = panalyze(owner, Options(**opts_kw), parts[0])
        for _ in procs:
            rank, ok = q.get(timeout=120)
            assert ok, f"rank {rank} returned no skeleton"
        for p in procs:
            p.join(timeout=60)
        return a, lu, bvals
    finally:
        for p in procs:
            if p.is_alive():
                p.kill()
        owner.close()


def _check_solves(a, lu, bvals, tol=1e-8):
    from superlu_dist_tpu.drivers.gssvx import factorize_numeric
    n = a.n_rows
    info = factorize_numeric(lu, bvals)
    assert info == 0
    rng = np.random.default_rng(7)
    xt = rng.standard_normal(n).astype(np.asarray(a.data).dtype)
    b = a.matvec(xt)
    x = lu.solve_factored(b)
    resid = np.linalg.norm(b - a.matvec(x)) / np.linalg.norm(b)
    assert resid < tol, resid
    # the skeleton must also report a sane structure
    assert lu.sf.nnz_L >= a.nnz
    assert lu.plan is not None


def _build_poisson():
    from superlu_dist_tpu.models.gallery import poisson2d
    return poisson2d(24)


def _build_convdiff():
    from superlu_dist_tpu.models.gallery import convection_diffusion_2d
    return convection_diffusion_2d(20)


def _build_helmholtz():
    from superlu_dist_tpu.models.gallery import helmholtz_2d
    return helmholtz_2d(18)


@pytest.mark.slow
def test_panalyze_poisson_norowperm():
    from superlu_dist_tpu.utils.options import RowPerm
    a, lu, bvals = _run_panalyze(
        _build_poisson, dict(row_perm=RowPerm.NOROWPERM))
    _check_solves(a, lu, bvals)


@pytest.mark.slow
def test_panalyze_convdiff_mc64():
    # unsymmetric pattern + the serial-on-root MC64 matching branch
    a, lu, bvals = _run_panalyze(_build_convdiff, {})
    _check_solves(a, lu, bvals)


@pytest.mark.slow
def test_panalyze_complex():
    a, lu, bvals = _run_panalyze(_build_helmholtz, {})
    _check_solves(a, lu, bvals, tol=1e-6)


class _LoneTree:
    """Single-rank stand-in for TreeComm: allreduce is identity."""
    n_ranks = 1
    rank = 0

    def allreduce_sum_any(self, arr, root=0):
        return arr


def test_trim_separators_thins_slab():
    """A 3-wide separator slab on a path graph peels to one layer, the
    result still separates the parts, and the trimmed vertices join
    their adjacent parts."""
    from superlu_dist_tpu.parallel.panalysis import _trim_separators

    n = 20
    # path 0-1-...-19; slab = {9,10,11}; parts 0:[0..8], 1:[12..19]
    lab = np.array([0] * 9 + [-1, -1, -1] + [1] * 8, dtype=np.int64)
    sr = np.repeat(np.arange(n), 2)[1:-1]
    sc = np.empty_like(sr)
    sc[0::2] = sr[0::2] + 1
    sc[1::2] = sr[1::2] - 1
    out = _trim_separators(_LoneTree(), lab.copy(), sr, sc, 0, n,
                           {0: [0], 1: [0]}, 2)
    assert (out < 0).sum() == 1, out          # slab thinned to 1 vertex
    # still a separator: no edge joins part 0 and part 1
    cross = (out[sr] >= 0) & (out[sc] >= 0) & (out[sr] != out[sc])
    assert not cross.any()
    # outer layers went to their adjacent parts
    assert out[9] == 0 and out[11] == 1
