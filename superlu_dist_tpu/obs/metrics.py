"""Serving-grade metrics registry — labeled counters / gauges / histograms.

The tracer (obs/trace.py) answers "where did THIS run's time go"; a
serving fleet needs the orthogonal question answered continuously:
counters and distributions that accumulate across thousands of solves
and export to a scraper.  This module is that substrate: a
zero-dependency registry of labeled counters, gauges, and fixed-bucket
histograms with JSON and Prometheus-text exports plus a
``Stats.reduce``-style cross-rank aggregation over a TreeComm.

Wired producers: ``parallel/treecomm.py`` (per-op collective calls /
bytes / seconds, fault-injection retries), the escalation ladder
(``drivers/gssvx.py`` — rung transitions), the retrace sentinel
(``numeric/stream.py``), and the dispatch scheduler telemetry
(``drivers/gssvx.factorize_numeric``).

Disabled path (the NULL_TRACER discipline): with ``SLU_TPU_METRICS``
unset, ``get_metrics()`` returns the module-level ``NULL_METRICS``
singleton whose every method is a constant-time no-op — no dict entry,
no label tuple, no lock.  Producers that sit on hot paths latch
``m if m.enabled else None`` once and pay a single ``is None`` test per
event (see TreeComm).  ``scripts/check_trace_overhead.py`` enforces
this in CI.

``SLU_TPU_METRICS`` values: ``1`` (or any truthy non-path) enables the
registry; a path-looking value (contains a separator or ends in
``.json`` / ``.prom`` / ``.txt``) additionally dumps the export there
at process exit (``%p`` expands to the pid).  ``.json`` → JSON export,
anything else → Prometheus text.
"""

from __future__ import annotations

import atexit
import json
import os
import threading

import numpy as np

from superlu_dist_tpu.utils.lockwatch import make_lock

#: Histogram bucket upper bounds (seconds-flavored log decades); the
#: implicit +Inf bucket is always last.
HIST_BUCKETS = (1e-6, 1e-5, 1e-4, 1e-3, 1e-2, 1e-1, 1.0, 10.0, 100.0)

_FLAG_FALSE = ("", "0", "false", "no", "off")


class NullMetrics:
    """Disabled registry: every operation is a constant-time no-op."""

    __slots__ = ()
    enabled = False

    def inc(self, name, value=1.0, **labels):
        pass

    def set(self, name, value, **labels):
        pass

    def observe(self, name, value, **labels):
        pass

    def snapshot(self):
        return {}

    def merge_snapshot(self, snap, base=None):
        pass

    def dump_now(self):
        return False

    def to_json(self):
        return "{}"

    def to_prometheus(self):
        return ""

    def reduce(self, comm):
        return {}


NULL_METRICS = NullMetrics()


def _series(name: str, labels: dict) -> tuple:
    return (name, tuple(sorted((str(k), str(v)) for k, v in labels.items())))


def _parse_fmt(key: str) -> tuple:
    """Invert ``_fmt``: ``name{k="v",...}`` back to the series tuple —
    the snapshot-merge path (a snapshot's keys are _fmt strings)."""
    import re
    m = re.match(r'^([^{]+)\{(.*)\}$', key)
    if m is None:
        return (key, ())
    labels = tuple(re.findall(r'([\w.]+)="([^"]*)"', m.group(2)))
    return (m.group(1), labels)


def _fmt(series: tuple) -> str:
    name, labels = series
    if not labels:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return f"{name}{{{inner}}}"


class Metrics:
    """Enabled registry.  Thread-safe; label sets are free-form (each
    distinct (name, labels) pair is one series)."""

    enabled = True

    def __init__(self):
        self._lock = make_lock("Metrics._lock")
        self._counters: dict[tuple, float] = {}
        self._gauges: dict[tuple, float] = {}
        # histogram: [count, sum, min, max, per-bucket counts]
        self._hists: dict[tuple, list] = {}
        # armed by get_metrics() when SLU_TPU_METRICS names a path —
        # dump_now() refreshes the export mid-run (slu_top's feed)
        self.export_path: str | None = None

    def dump_now(self) -> bool:
        """Refresh the on-disk export immediately (atomic temp+rename,
        same artifact the atexit dump writes).  True when a path is
        armed; no-op False otherwise."""
        if not self.export_path:
            return False
        _dump(self, self.export_path)
        return True

    # ---- producers -----------------------------------------------------
    def inc(self, name, value=1.0, **labels):
        key = _series(name, labels)
        with self._lock:
            self._counters[key] = self._counters.get(key, 0.0) + float(value)

    def set(self, name, value, **labels):
        key = _series(name, labels)
        with self._lock:
            self._gauges[key] = float(value)

    def observe(self, name, value, **labels):
        key = _series(name, labels)
        value = float(value)
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = [0, 0.0, float("inf"),
                                        float("-inf"),
                                        [0] * (len(HIST_BUCKETS) + 1)]
            h[0] += 1
            h[1] += value
            h[2] = min(h[2], value)
            h[3] = max(h[3], value)
            for i, ub in enumerate(HIST_BUCKETS):
                if value <= ub:
                    h[4][i] += 1
                    break
            else:
                h[4][-1] += 1

    # ---- exports -------------------------------------------------------
    def snapshot(self) -> dict:
        """{"counters": {...}, "gauges": {...}, "histograms": {...}} with
        human-readable ``name{label="v"}`` keys."""
        with self._lock:
            return {
                "counters": {_fmt(k): v for k, v in self._counters.items()},
                "gauges": {_fmt(k): v for k, v in self._gauges.items()},
                "histograms": {
                    _fmt(k): {"count": h[0], "sum": h[1],
                              "min": (None if h[0] == 0 else h[2]),
                              "max": (None if h[0] == 0 else h[3]),
                              "buckets": list(h[4])}
                    for k, h in self._hists.items()},
            }

    def merge_snapshot(self, snap: dict, base: dict | None = None):
        """Fold another registry's ``snapshot()`` into this one —
        the fleet-wide aggregation path (a process replica's child
        registry dies with the process; the router absorbs its
        snapshots at heartbeat/teardown so ``to_prometheus()`` covers
        the whole fleet).

        ``base`` is the previously absorbed snapshot from the SAME
        source: counters and histogram counts/sums/buckets merge as the
        DELTA vs base (so repeated heartbeat absorption never double
        counts), gauges and min/max merge absolutely (last/extreme
        writer wins)."""
        if not snap:
            return
        base = base or {}
        bc = base.get("counters", {})
        bh = base.get("histograms", {})
        with self._lock:
            for key, v in snap.get("counters", {}).items():
                d = float(v) - float(bc.get(key, 0.0))
                if d:
                    sk = _parse_fmt(key)
                    self._counters[sk] = self._counters.get(sk, 0.0) + d
            for key, v in snap.get("gauges", {}).items():
                self._gauges[_parse_fmt(key)] = float(v)
            for key, sh in snap.get("histograms", {}).items():
                prev = bh.get(key) or {"count": 0, "sum": 0.0,
                                       "buckets": [0] * (len(sh["buckets"]))}
                sk = _parse_fmt(key)
                h = self._hists.get(sk)
                if h is None:
                    h = self._hists[sk] = [0, 0.0, float("inf"),
                                           float("-inf"),
                                           [0] * (len(HIST_BUCKETS) + 1)]
                h[0] += int(sh["count"]) - int(prev["count"])
                h[1] += float(sh["sum"]) - float(prev["sum"])
                if sh.get("min") is not None:
                    h[2] = min(h[2], float(sh["min"]))
                if sh.get("max") is not None:
                    h[3] = max(h[3], float(sh["max"]))
                for i in range(min(len(sh["buckets"]), len(h[4]))):
                    h[4][i] += int(sh["buckets"][i]) - int(
                        prev["buckets"][i] if i < len(prev["buckets"])
                        else 0)

    def to_json(self) -> str:
        return json.dumps(self.snapshot(), sort_keys=True)

    def to_prometheus(self) -> str:
        """Prometheus text exposition (type comments + counter/gauge
        samples, histograms as _bucket/_sum/_count)."""
        lines = []
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = {k: [h[0], h[1], list(h[4])]
                     for k, h in self._hists.items()}
        for name in sorted({k[0] for k in counters}):
            lines.append(f"# TYPE {name} counter")
            for k in sorted(k for k in counters if k[0] == name):
                lines.append(f"{_fmt(k)} {counters[k]:g}")
        for name in sorted({k[0] for k in gauges}):
            lines.append(f"# TYPE {name} gauge")
            for k in sorted(k for k in gauges if k[0] == name):
                lines.append(f"{_fmt(k)} {gauges[k]:g}")
        for name in sorted({k[0] for k in hists}):
            lines.append(f"# TYPE {name} histogram")
            for k in sorted(k for k in hists if k[0] == name):
                count, total, buckets = hists[k]
                labels = dict(k[1])
                acc = 0
                for ub, b in zip(tuple(HIST_BUCKETS) + ("+Inf",),
                                 buckets):
                    acc += b
                    lk = _series(name + "_bucket",
                                 {**labels, "le": str(ub)})
                    lines.append(f"{_fmt(lk)} {acc}")
                lines.append(
                    f"{_fmt(_series(name + '_sum', labels))} {total:g}")
                lines.append(
                    f"{_fmt(_series(name + '_count', labels))} {count}")
        return "\n".join(lines) + ("\n" if lines else "")

    # ---- cross-rank aggregation ---------------------------------------
    def _flat(self) -> dict:
        """Scalar view for the collective reduction: counters and gauges
        as-is, histograms flattened to _count/_sum."""
        with self._lock:
            out = {("counter",) + k: v for k, v in self._counters.items()}
            out.update({("gauge",) + k: v
                        for k, v in self._gauges.items()})
            for k, h in self._hists.items():
                out[("hist_count",) + k] = float(h[0])
                out[("hist_sum",) + k] = float(h[1])
        return out

    def reduce(self, comm) -> dict:
        """Cross-rank metric aggregation (the Stats.reduce discipline —
        COLLECTIVE: every rank must call at the same point, with the
        registry enabled on every rank).  Series sets may differ per
        rank: the key union is agreed via one bcast_obj per rank (every
        rank participates in every broadcast), then one matrix
        sum-allreduce carries the aligned values, from which per-series
        sum/min/max/avg over ranks are exact."""
        local = self._flat()
        keys = sorted(local)
        all_keys = set(keys)
        for r in range(comm.n_ranks):
            got = comm.bcast_obj(keys if comm.rank == r else None, root=r)
            all_keys.update(got)
        ordered = sorted(all_keys)
        vec = np.asarray([local.get(k, 0.0) for k in ordered],
                         dtype=np.float64)
        mat = np.zeros((comm.n_ranks, max(vec.size, 1)))
        mat[comm.rank, :vec.size] = vec
        mat = np.asarray(comm.allreduce_sum_any(mat)).reshape(
            comm.n_ranks, -1)
        out = {}
        for j, k in enumerate(ordered):
            col = mat[:, j]
            kind, name, labels = k
            out[f"{kind}:{_fmt((name, labels))}"] = {
                "sum": float(col.sum()), "min": float(col.min()),
                "max": float(col.max()), "avg": float(col.mean())}
        return out


# ---- process-global registry ----------------------------------------------

_metrics = None
_init_lock = make_lock("obs.metrics._init_lock")


def _looks_like_path(value: str) -> bool:
    return (os.sep in value or "/" in value
            or value.endswith((".json", ".prom", ".txt")))


def _dump(metrics: Metrics, path: str) -> None:
    path = path.replace("%p", str(os.getpid()))
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    tmp = path + f".tmp{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(metrics.to_json() if path.endswith(".json")
                else metrics.to_prometheus())
    os.replace(tmp, path)


def get_metrics():
    """The process registry: a ``Metrics`` when ``SLU_TPU_METRICS`` is
    truthy, else the ``NULL_METRICS`` singleton.  Read once, on first
    use (tests reconfigure via ``install``/``_reset``)."""
    global _metrics
    m = _metrics
    if m is None:
        with _init_lock:
            if _metrics is None:
                from superlu_dist_tpu.utils.options import env_str
                raw = env_str("SLU_TPU_METRICS").strip()
                if raw.lower() in _FLAG_FALSE:
                    _metrics = NULL_METRICS
                else:
                    _metrics = Metrics()
                    if _looks_like_path(raw):
                        _metrics.export_path = raw
                        atexit.register(_dump, _metrics, raw)
            m = _metrics
    return m


def install(metrics):
    """Install ``metrics`` as the process registry (programmatic enable
    for tests and embedding callers); returns the previous one.
    NOTE: producers that latched the previous registry at construction
    (TreeComm) keep it — install before building them."""
    global _metrics
    prev = _metrics
    _metrics = metrics
    return prev


def _reset():
    """Re-read ``SLU_TPU_METRICS`` on next use (test hygiene)."""
    global _metrics
    _metrics = None
