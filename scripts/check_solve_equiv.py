#!/usr/bin/env python
"""Solve-equivalence gate: the device batched solve must agree with the
host supernodal solve, and the solve-plan machinery must never change
the answer.

Three tiers on the (downsized) bench matrix family:

1. **fused vs streamed, BITWISE** — one jitted program per sweep vs one
   kernel per sweep batch runs the identical arithmetic, so
   np.array_equal (no tolerance) must hold, per solve schedule.
2. **schedules agree** — dataflow / level / factor sweep schedules (and
   a promoted-key alignment pass) solve through the SAME factors; batch
   membership may reorder the lsum scatter-adds, so these compare at a
   tight f64 tolerance (≤ 64·eps·cond-ish; 1e-11 componentwise here),
   not bitwise — the solve twin of check_schedule_equiv.py's contract,
   with the reordering caveat documented in docs/SERVING.md.
3. **device vs host** — the serving path against the scipy-grade host
   loop at nrhs ∈ {1, 5, 130} (130 crosses a geometric nrhs bucket and
   exercises padding columns), including the transpose sweep.  The
   nrhs-padding telemetry must also report honestly: executed >=
   structural, and padded_nrhs equal to the chunked bucket total.

Exit 0 = pass.  One gate of scripts/ci_gates.sh (the consolidated CI
entry point); a few seconds on CPU.  Gate contract (shared with the
other gates): any regression — a bitwise mismatch between fused and
streamed, a cross-schedule drift past tolerance, a device-vs-host
disagreement, a padding under-report — raises/asserts, which exits
non-zero with the diagnostic on stderr.
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402
from superlu_dist_tpu.utils import tols  # noqa: E402


def _factored(a):
    from superlu_dist_tpu.drivers.gssvx import gssvx
    from superlu_dist_tpu.utils.options import IterRefine, Options

    opts = Options(iter_refine=IterRefine.NOREFINE)
    x, lu, stats, info = gssvx(opts, a, np.ones(a.n_rows))
    assert info == 0, f"factorization failed: info={info}"
    return lu


def check(name, a):
    from superlu_dist_tpu.solve.device import DeviceSolver
    from superlu_dist_tpu.solve.plan import build_solve_plan, chunk_nrhs
    from superlu_dist_tpu.solve.trisolve import lu_solve, lu_solve_trans

    lu = _factored(a)
    n = a.n_rows
    rng = np.random.default_rng(7)
    for nrhs in (1, 5, 130):
        d = rng.standard_normal((n, nrhs))
        d = d[:, 0] if nrhs == 1 else d
        want = lu_solve(lu.numeric, d)
        ref = None
        for sched in ("dataflow", "level", "factor"):
            s_str = DeviceSolver(lu.numeric, fused=False, schedule=sched)
            s_fus = DeviceSolver(lu.numeric, fused=True, schedule=sched)
            x_str = s_str.solve(d)
            x_fus = s_fus.solve(d)
            # tier 1: identical arithmetic => identical bits
            assert np.array_equal(x_str, x_fus), (
                f"{name}: fused vs streamed differ BITWISE "
                f"(schedule={sched}, nrhs={nrhs})")
            # tier 2: schedules agree to f64 tightness
            if ref is None:
                ref = x_str
            else:
                np.testing.assert_allclose(
                    x_str, ref, rtol=tols.SCHEDULE_DRIFT_RTOL,
                    atol=tols.SCHEDULE_DRIFT_ATOL,
                    err_msg=f"{name}: schedule {sched} drifted past "
                            f"tolerance at nrhs={nrhs}")
            # tier 3: device vs host
            np.testing.assert_allclose(
                x_str, want, rtol=tols.DEVICE_VS_HOST_RTOL,
                atol=tols.DEVICE_VS_HOST_ATOL,
                err_msg=f"{name}: device ({sched}) vs host solve "
                        f"disagree at nrhs={nrhs}")
            # padding honesty: executed covers structural, padded nrhs
            # is exactly the chunked bucket total
            st = s_str.last_solve_stats
            assert st["executed_flops"] >= st["solve_flops"] > 0, st
            kb = sum(b for _, _, b in chunk_nrhs(
                nrhs, s_str.splan.nrhs_bucket_set))
            assert st["padded_nrhs"] == kb and st["nrhs"] == nrhs, st
        # transpose sweep through the dataflow schedule
        want_t = lu_solve_trans(lu.numeric, d)
        got_t = DeviceSolver(lu.numeric, schedule="dataflow").solve_trans(d)
        np.testing.assert_allclose(
            got_t, want_t, rtol=tols.DEVICE_VS_HOST_RTOL,
            atol=tols.DEVICE_VS_HOST_ATOL,
            err_msg=f"{name}: transpose device vs host at nrhs={nrhs}")
    sp = build_solve_plan(lu.plan, schedule="dataflow", window=0)
    assert len(sp.groups) <= sp.n_factor_groups, (
        f"{name}: dataflow solve plan produced MORE groups "
        f"({len(sp.groups)} > {sp.n_factor_groups})")
    print(f"[solve-equiv] {name}: OK (factor groups "
          f"{sp.n_factor_groups} -> solve groups {len(sp.groups)}, "
          f"occupancy {sp.mean_occupancy:.2f})")


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    from superlu_dist_tpu.models.gallery import poisson2d, random_sparse

    check("poisson2d-12", poisson2d(12))
    check("random-120", random_sparse(120, density=0.05, seed=3))
    print("[solve-equiv] all checks passed")


if __name__ == "__main__":
    main()
