"""Dataflow-aggregated scheduling (numeric/plan.py).

The scheduler contract under test is the one the reference's
elimination-tree pipeline rests on (SRC/pdgstrf.c:624-697): batch
membership only changes WHEN a front is factored, never the arithmetic
within it, so the dataflow schedule must produce bitwise-identical L/U
to the level-lockstep schedule — on both executors — while strictly
reducing dispatch-group count on schedules with mergeable cells.
"""

import multiprocessing as mp
import os

import numpy as np
import pytest

from superlu_dist_tpu import native

pytestmark = pytest.mark.schedule


def _analyzed(a, **symb_kw):
    from superlu_dist_tpu.ordering.dispatch import get_perm_c
    from superlu_dist_tpu.sparse.formats import symmetrize_pattern
    from superlu_dist_tpu.symbolic.symbfact import symbolic_factorize
    from superlu_dist_tpu.utils.options import Options

    sym = symmetrize_pattern(a)
    col_order = get_perm_c(Options(), a, sym)
    sf = symbolic_factorize(sym, col_order, **symb_kw)
    return sf, sym.data[sf.value_perm], a.norm_max()


def _real_blocks(plan, fact, s, wr, ur):
    """The unpadded (real) L and U sub-blocks of supernode s: pivot-block
    rows [0, wr), below-diagonal rows [W, W + ur) of the padded front."""
    g, slot = int(plan.sn_group[s]), int(plan.sn_slot[s])
    grp = plan.groups[g]
    lp = np.asarray(fact.fronts[g][0][slot])
    up = np.asarray(fact.fronts[g][1][slot])
    L = np.concatenate([lp[:wr, :wr], lp[grp.w:grp.w + ur, :wr]])
    return L, up[:wr, :ur]


def _assert_bitwise(sf, plan_a, fact_a, plan_b, fact_b):
    widths = np.diff(sf.sn_start)
    us = np.array([len(r) for r in sf.sn_rows])
    for s in range(sf.n_supernodes):
        La, Ua = _real_blocks(plan_a, fact_a, s, int(widths[s]), int(us[s]))
        Lb, Ub = _real_blocks(plan_b, fact_b, s, int(widths[s]), int(us[s]))
        assert np.array_equal(La, Lb), f"L mismatch at supernode {s}"
        assert np.array_equal(Ua, Ub), f"U mismatch at supernode {s}"


@pytest.mark.parametrize("case", ["poisson", "hilbert", "arrowhead"])
@pytest.mark.parametrize("executor", ["fused", "stream"])
def test_bitwise_equivalence_level_vs_dataflow(case, executor):
    """Same symbolic structure, level vs dataflow plans: the factored
    L/U real blocks must match BITWISE (np.array_equal, no tolerance)
    on both executors — the scheduler only reorders dispatch, never
    front arithmetic.  Gallery coverage includes the ill-conditioned
    (hilbert) and structurally singular (rank_deficient_arrowhead,
    ReplaceTinyPivot path) cases."""
    from superlu_dist_tpu.models.gallery import (
        hilbert, poisson2d, rank_deficient_arrowhead)
    from superlu_dist_tpu.numeric.factor import numeric_factorize
    from superlu_dist_tpu.numeric.plan import build_plan

    a = {"poisson": lambda: poisson2d(16),
         "hilbert": lambda: hilbert(48),
         "arrowhead": lambda: rank_deficient_arrowhead(40)}[case]()
    sf, vals, anorm = _analyzed(a)
    plan_l = build_plan(sf, schedule="level")
    plan_d = build_plan(sf, schedule="dataflow")
    assert plan_l.schedule == "level" and plan_d.schedule == "dataflow"
    f_l = numeric_factorize(plan_l, vals, anorm, executor=executor)
    f_d = numeric_factorize(plan_d, vals, anorm, executor=executor)
    assert f_l.tiny_pivots == f_d.tiny_pivots
    _assert_bitwise(sf, plan_l, f_l, plan_d, f_d)


def test_window_one_degenerates_to_level_partition():
    """SLU_TPU_SCHED_WINDOW=1 restricts eligibility to the oldest
    incomplete level, whose cells are always fully ready — the dataflow
    partition must then equal the level partition exactly (same member
    sets, same per-group shapes)."""
    from superlu_dist_tpu.models.gallery import poisson2d
    from superlu_dist_tpu.numeric.plan import build_plan

    sf, _, _ = _analyzed(poisson2d(20))
    plan_l = build_plan(sf, schedule="level")
    plan_1 = build_plan(sf, schedule="dataflow", window=1)
    part_l = {frozenset(g.sns.tolist()): (g.m, g.w, g.u)
              for g in plan_l.groups}
    part_1 = {frozenset(g.sns.tolist()): (g.m, g.w, g.u)
              for g in plan_1.groups}
    assert part_l == part_1
    assert len(plan_1.groups) == plan_1.n_level_groups


def _deep_tree_sf(depth=8, k_width=12):
    """Synthetic deep-tree SymbolicFact with independent same-shape
    roots at EVERY level — the deep-tail regime where level lockstep
    yields singleton batches.  For l in 1..depth: a width-1 chain
    x_{l,0}..x_{l,l-1} (shape key (8, 8)) topped by a width-`k_width`
    root K_l (key (16, 0)).  The K_l are pairwise independent yet sit at
    levels 1..depth, so only a cross-level scheduler can batch them."""
    from superlu_dist_tpu.sparse.formats import coo_to_csr
    from superlu_dist_tpu.symbolic.symbfact import _finish

    sn_widths, sn_rows_first, sn_parent, sn_level = [], [], [], []
    col = 0
    first_cols = []       # first column of each supernode
    for l in range(1, depth + 1):
        chain = []
        for j in range(l):
            sn_widths.append(1)
            first_cols.append(col)
            sn_level.append(j)
            chain.append(len(sn_widths) - 1)
            col += 1
        k_id = len(sn_widths)
        sn_widths.append(k_width)
        first_cols.append(col)
        sn_level.append(l)
        col += k_width
        for j, s in enumerate(chain):
            sn_parent.append(s + 1 if j + 1 < l else k_id)
        sn_parent.append(-1)          # K_l is a root
    n = col
    ns = len(sn_widths)
    sn_start = np.zeros(ns + 1, dtype=np.int64)
    np.cumsum(sn_widths, out=sn_start[1:])
    col_to_sn = np.repeat(np.arange(ns), sn_widths)
    sn_parent = np.array(sn_parent, dtype=np.int64)
    sn_level = np.array(sn_level, dtype=np.int64)
    sn_rows = [np.array([sn_start[p]], dtype=np.int64) if p >= 0
               else np.empty(0, dtype=np.int64)
               for p in sn_parent]
    us = np.array([len(r) for r in sn_rows], dtype=np.int64)
    # pattern: SPD-ish diagonal plus the child->parent couplings
    r = list(range(n))
    c = list(range(n))
    v = [4.0] * n
    for s, p in enumerate(sn_parent):
        if p >= 0:
            i, j = int(sn_start[s]), int(sn_start[p])
            r += [i, j]
            c += [j, i]
            v += [-1.0, -1.0]
    pat = coo_to_csr(n, n, np.array(r), np.array(c),
                     np.array(v, dtype=np.float64))
    sf = _finish(n, np.arange(n), np.full(n, -1, dtype=np.int64), sn_start,
                 col_to_sn, sn_rows, sn_parent, sn_level, us,
                 pat.indptr, pat.indices, None)
    return sf, np.asarray(pat.data), 6.0


def test_occupancy_strictly_improves_on_deep_tree():
    """On the synthetic deep tree the dataflow scheduler batches the
    independent per-level roots that level lockstep dispatches one by
    one: strictly fewer groups, strictly higher mean occupancy, and the
    factors stay bitwise identical."""
    from superlu_dist_tpu.numeric.factor import numeric_factorize
    from superlu_dist_tpu.numeric.plan import build_plan

    sf, vals, anorm = _deep_tree_sf(depth=8)
    plan_l = build_plan(sf, schedule="level", align=0)
    plan_d = build_plan(sf, schedule="dataflow", window=8, align=0)
    assert len(plan_d.groups) < len(plan_l.groups)
    assert plan_d.mean_occupancy > plan_l.mean_occupancy
    assert plan_d.n_level_groups == len(plan_l.groups)
    f_l = numeric_factorize(plan_l, vals, anorm, executor="fused")
    f_d = numeric_factorize(plan_d, vals, anorm, executor="fused")
    _assert_bitwise(sf, plan_l, f_l, plan_d, f_d)


def test_dataflow_never_exceeds_level_group_count():
    """The closed-cell policy merges whole (key, level) cells only, so
    the dataflow group count is bounded by the level partition's on any
    structure and at any window."""
    from superlu_dist_tpu.models.gallery import poisson2d, random_sparse
    from superlu_dist_tpu.numeric.plan import build_plan

    for a in (poisson2d(24), random_sparse(300, density=0.02, seed=3)):
        sf, _, _ = _analyzed(a)
        n_level = len(build_plan(sf, schedule="level").groups)
        for w in (0, 1, 2, 4, 16):
            plan = build_plan(sf, schedule="dataflow", window=w)
            assert len(plan.groups) <= n_level, (w, len(plan.groups))


def test_schedule_topological_and_telemetry():
    """Every schedule keeps children in strictly earlier groups than
    their parents (the pool free-list and the solve sweeps rest on it),
    waves are monotone for the level-granularity executor, and the
    telemetry block carries the documented fields."""
    from superlu_dist_tpu.models.gallery import poisson2d
    from superlu_dist_tpu.numeric.plan import build_plan

    sf, _, _ = _analyzed(poisson2d(20))
    for schedule in ("level", "dataflow"):
        plan = build_plan(sf, schedule=schedule)
        for s in range(sf.n_supernodes):
            p = int(sf.sn_parent[s])
            if p >= 0:
                assert plan.sn_group[p] > plan.sn_group[s]
        waves = [g.level for g in plan.groups]
        assert waves == sorted(waves)
        stats = plan.schedule_stats()
        assert stats["schedule"] == schedule
        assert set(stats) == {"schedule", "n_groups", "n_level_groups",
                              "occupancy", "padding_factor",
                              "critical_path", "bytes_moved"}
        assert stats["critical_path"] >= 1
        assert stats["bytes_moved"] > 0
        assert stats["n_groups"] == len(plan.groups)


def test_env_knobs_drive_build_plan(monkeypatch):
    from superlu_dist_tpu.models.gallery import poisson2d
    from superlu_dist_tpu.numeric.plan import build_plan

    sf, _, _ = _analyzed(poisson2d(12))
    monkeypatch.setenv("SLU_TPU_SCHEDULE", "level")
    assert build_plan(sf).schedule == "level"
    monkeypatch.setenv("SLU_TPU_SCHEDULE", "dataflow")
    monkeypatch.setenv("SLU_TPU_SCHED_WINDOW", "3")
    plan = build_plan(sf)
    assert plan.schedule == "dataflow" and plan.sched_window == 3
    monkeypatch.setenv("SLU_TPU_SCHEDULE", "bogus")
    with pytest.raises(ValueError):
        build_plan(sf)


def test_shape_alignment_budget():
    """Shape-key coalescing must respect its flop budget: total executed
    (shape-padded) flops stay within tol of the unaligned schedule's,
    and tol<=1 disables the pass entirely."""
    from superlu_dist_tpu.models.gallery import poisson3d
    from superlu_dist_tpu.numeric.plan import build_plan
    from superlu_dist_tpu.symbolic.symbfact import _front_flops

    sf, _, _ = _analyzed(poisson3d(10))

    def executed(plan):
        return float(sum(g.batch * _front_flops(g.w, g.u)
                         for g in plan.groups))

    base = build_plan(sf, schedule="level", align=0)
    for tol in (1.1, 1.3):
        aligned = build_plan(sf, schedule="level", align=tol)
        assert executed(aligned) <= tol * executed(base) * (1 + 1e-12)
        assert len(aligned.groups) <= len(base.groups)
    assert len(build_plan(sf, schedule="level", align=1.0).groups) \
        == len(base.groups)


def test_driver_stats_carry_schedule_block():
    """The driver path (analyze + factorize_numeric) surfaces the
    schedule telemetry on Stats and in the PStatPrint-analog report."""
    import superlu_dist_tpu as slu
    from superlu_dist_tpu.models.gallery import poisson2d

    a = poisson2d(12)
    b = a.matvec(np.ones(a.n_rows))
    x, lu, stats, info = slu.gssvx(slu.Options(), a, b)
    assert info == 0
    assert stats.sched["schedule"] in ("level", "dataflow")
    assert stats.sched["n_groups"] == len(lu.plan.groups)
    assert stats.sched["n_level_groups"] >= stats.sched["n_groups"]
    assert "schedule" in stats.report()


def test_schedule_trace_span(tmp_path, monkeypatch):
    """With tracing armed, the factorization emits a schedule span
    carrying the telemetry attributes."""
    import json

    from superlu_dist_tpu.models.gallery import poisson2d
    from superlu_dist_tpu.numeric.factor import numeric_factorize
    from superlu_dist_tpu.numeric.plan import build_plan
    from superlu_dist_tpu.obs import trace as trace_mod

    sf, vals, anorm = _analyzed(poisson2d(10))
    plan = build_plan(sf, schedule="dataflow")
    path = tmp_path / "sched_trace.json"
    monkeypatch.setenv("SLU_TPU_TRACE", str(path))
    trace_mod._reset()
    try:
        numeric_factorize(plan, vals, anorm, executor="fused")
        trace_mod.get_tracer().close()
    finally:
        trace_mod._reset()
    events = json.loads(path.read_text())
    if isinstance(events, dict):
        events = events.get("traceEvents", [])
    sched = [e for e in events if e.get("name") == "schedule"]
    assert sched, "schedule span missing from trace"
    args = sched[0].get("args", {})
    assert args.get("schedule") == "dataflow"
    assert args.get("n_groups") == len(plan.groups)
    assert "occupancy" in args and "critical_path" in args


# ---------------------------------------------------------------------------
# 2-rank: the broadcast skeleton's schedule stays collective-clean
# ---------------------------------------------------------------------------

def _verify_worker(name, n_ranks, rank, part, b_loc, q):
    import jax
    jax.config.update("jax_platforms", "cpu")
    from superlu_dist_tpu.parallel.pgssvx import pgssvx
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    from superlu_dist_tpu.utils.options import Options
    with TreeComm(name, n_ranks, rank, max_len=2048, create=False) as tc:
        x, info = pgssvx(tc, Options(), part, b_loc)
        q.put((rank, info, x))


@pytest.mark.skipif(not native.available(),
                    reason="native library unavailable")
def test_two_rank_dataflow_collective_clean(monkeypatch):
    """A 2-rank pgssvx solve on a dataflow-scheduled plan under
    SLU_TPU_VERIFY_COLLECTIVES=1: the lockstep verifier (runtime SLU106)
    digests every collective across ranks, so any schedule divergence
    between the ranks' dispatch sequences would raise
    CollectiveMismatchError instead of finishing."""
    from superlu_dist_tpu.models.gallery import poisson2d
    from superlu_dist_tpu.parallel.dist import distribute_rows
    from superlu_dist_tpu.parallel.pgssvx import pgssvx
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    from superlu_dist_tpu.utils.options import Options

    monkeypatch.setenv("SLU_TPU_VERIFY_COLLECTIVES", "1")
    monkeypatch.setenv("SLU_TPU_SCHEDULE", "dataflow")
    a = poisson2d(12)
    n = a.n_rows
    xtrue = np.random.default_rng(5).standard_normal(n)
    b = a.matvec(xtrue)
    parts = distribute_rows(a, 2)
    b_blocks = [b[p.fst_row:p.fst_row + p.m_loc] for p in parts]
    name = f"/slu_sched_{os.getpid()}"
    owner = TreeComm(name, 2, 0, max_len=2048, create=True)
    try:
        ctx = mp.get_context("fork")
        q = ctx.Queue()
        proc = ctx.Process(target=_verify_worker,
                           args=(name, 2, 1, parts[1], b_blocks[1], q))
        proc.start()
        x, info = pgssvx(owner, Options(), parts[0], b_blocks[0])
        assert info == 0
        rank, info1, x1 = q.get(timeout=300)
        proc.join(timeout=300)
        assert proc.exitcode == 0 and info1 == 0
        np.testing.assert_allclose(x1, x, rtol=0, atol=1e-12)
    finally:
        owner.close(unlink=True)
    resid = float(np.linalg.norm(b - a.matvec(x)) / np.linalg.norm(b))
    assert resid < 1e-12, resid
