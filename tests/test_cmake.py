"""Standalone CMake build flow (reference CMakeLists.txt analog).

Kept in its own module: the ctypes fast-path availability mark that
gates test_native.py must NOT gate this — a broken direct build is
exactly when the CMake flow matters.
"""

import pytest
def test_cmake_build_and_ctest(tmp_path):
    """The standalone CMake flow (reference CMakeLists.txt analog) must
    configure, build the native targets, and pass ctest."""
    import os
    import shutil
    import subprocess
    cmake = shutil.which("cmake")
    if cmake is None:
        pytest.skip("no cmake in this image")
    root = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
    gen = ["-G", "Ninja"] if shutil.which("ninja") else []
    r = subprocess.run([cmake, "-B", str(tmp_path), "-S", root] + gen,
                       capture_output=True, timeout=300)
    assert r.returncode == 0, r.stdout.decode() + r.stderr.decode()
    r = subprocess.run([cmake, "--build", str(tmp_path), "-j", "2"],
                       capture_output=True, timeout=900)
    assert r.returncode == 0, r.stdout.decode() + r.stderr.decode()
    r = subprocess.run(["ctest", "--test-dir", str(tmp_path),
                        "--output-on-failure"],
                       capture_output=True, timeout=900)
    assert r.returncode == 0, r.stdout.decode() + r.stderr.decode()



import pytest  # noqa: E402

# slow tier: multi-process / native-build / at-scale — fast CI runs -m "not slow"
pytestmark = pytest.mark.slow
