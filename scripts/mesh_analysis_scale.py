#!/usr/bin/env python
"""A/B the distributed-factors tier's analysis distribution (VERDICT r4 #3).

Round-4 behavior (replicated): EVERY rank assembles the global matrix and
runs the identical EQUIL→ROWPERM→COLPERM→SYMBFACT→plan analysis —
O(nnz(A)+nnz(L)) host memory and analysis work per process, the wall the
reference's parallel symbolic was built to break (SRC/psymbfact.c:228-242).

Round-5 behavior (root+bcast): rank 0 analyzes once and broadcasts the
analyzed skeleton over the shared-memory tree (parallel/pgssvx.py
_pgssvx_mesh) — non-root ranks never hold the global graph or do analysis
work.

Each mode runs in FRESH forked processes (VmHWM is a process-lifetime
high-water mark), 4 ranks, poisson3d(MAS_NX) (default 48 → n=110,592;
MAS_NX=100 → n=1e6).  Writes docs/mesh_analysis_4proc_n{n}.json with
per-rank analysis wall time and peak host memory for both modes.
"""

import json
import multiprocessing as mp
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import REPO  # noqa: E402

sys.path.insert(0, REPO)


def _mem_mb(key):
    with open("/proc/self/status") as fh:
        for line in fh:
            if line.startswith(key):
                return int(line.split()[1]) / 1024.0
    return float("nan")


def _rank_body(mode, name, nranks, rank, part, q):
    from superlu_dist_tpu.drivers.gssvx import analyze
    from superlu_dist_tpu.parallel.pgssvx import gather_distributed
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    from superlu_dist_tpu.utils.options import Options

    with TreeComm(name, nranks, rank, max_len=1 << 20,
                  create=rank == 0) as tc:
        # barrier-ish: everyone attached before timing starts
        tc.allreduce_sum_any(np.ones(1))
        base_mb = _mem_mb("VmRSS")     # interpreter+imports baseline —
        t0 = time.perf_counter()       # the analysis delta is the signal
        if mode == "replicated":
            a_all = gather_distributed(tc, part, all_ranks=True)
            lu, bvals, _ = analyze(Options(), a_all)
        elif mode == "parsymb":
            # the ParSymbFact tier: ordering + symbolic work partition
            # across the ranks themselves (parallel/panalysis.py)
            from superlu_dist_tpu.parallel.panalysis import panalyze
            lu, bvals = panalyze(tc, Options(), part)
        else:
            # the production tier-1 path itself (one implementation)
            from superlu_dist_tpu.parallel.pgssvx import (
                root_analyze_bcast)
            from superlu_dist_tpu.utils.stats import Stats
            lu, bvals = root_analyze_bcast(tc, Options(), part, Stats())
        t = time.perf_counter() - t0
        assert lu.plan is not None and len(bvals) > 0
        q.put({"rank": rank, "mode": mode, "analysis_seconds": round(t, 3),
               "nnz_L": int(lu.sf.nnz_L),          # ordering quality:
               "struct_flops": float(lu.sf.flops),  # parsymb vs serial ND
               "vm_rss_mb": round(_mem_mb("VmRSS"), 1),
               "vm_hwm_mb": round(_mem_mb("VmHWM"), 1),
               "baseline_mb": round(base_mb, 1),
               "analysis_hwm_delta_mb": round(_mem_mb("VmHWM") - base_mb, 1),
               "n_groups": len(lu.plan.groups)})


def _run_mode(mode, parts, nranks):
    name = f"/slu_mas_{os.getpid()}_{mode}"
    ctx = mp.get_context("fork")
    q = ctx.Queue()
    procs = [ctx.Process(target=_rank_body,
                         args=(mode, name, nranks, r, parts[r], q))
             for r in range(nranks)]
    # rank 0 creates the segment; its constructor must win the race —
    # start it first and give it a head start (TreeComm rendezvous
    # contract)
    procs[0].start()
    time.sleep(1.0)
    for p in procs[1:]:
        p.start()
    rows = []
    try:
        import queue
        # n=1M replicated mode = 4 concurrent full analyses contending
        # for this box's ONE core — allow hours (MAS_DEADLINE_S to tune)
        deadline = time.monotonic() + float(
            os.environ.get("MAS_DEADLINE_S", "14400"))
        while len(rows) < nranks:
            try:
                rows.append(q.get(timeout=5))
                continue
            except queue.Empty:
                pass
            dead = [p.pid for p in procs if p.exitcode not in (None, 0)]
            if dead:
                raise RuntimeError(
                    f"rank process(es) {dead} died before reporting")
            if time.monotonic() > deadline:
                raise TimeoutError("measurement ranks still running at "
                                   "the MAS_DEADLINE_S deadline")
    finally:
        for p in procs:
            p.join(timeout=60)
            if p.is_alive():
                p.terminate()
        import glob
        for leftover in glob.glob(f"/dev/shm/*{name.strip('/')}*"):
            try:
                os.unlink(leftover)
            except OSError:
                pass
    return sorted(rows, key=lambda r: r["rank"])


def main():
    from superlu_dist_tpu.models.gallery import poisson3d
    from superlu_dist_tpu.parallel.dist import distribute_rows

    nx = int(os.environ.get("MAS_NX", "48"))
    a = poisson3d(nx)
    n = a.n_rows
    nranks = 4
    parts = distribute_rows(a, nranks)
    del a

    out = {"n": n, "nnz": int(sum(p.nnz_loc for p in parts)),
           "nranks": nranks}
    # partial re-runs (MAS_MODES) merge into the existing artifact so a
    # modes subset never clobbers previously measured sections
    path = os.path.join(REPO, "docs", f"mesh_analysis_4proc_n{n}.json")
    if os.path.exists(path):
        with open(path) as fh:
            prior = json.load(fh)
        for k in ("replicated", "root_bcast", "parsymb"):
            if k in prior:
                out[k] = prior[k]
    modes = tuple(os.environ.get(
        "MAS_MODES", "replicated,root_bcast,parsymb").split(","))
    run_id = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    for mode in modes:
        t0 = time.perf_counter()
        rows = _run_mode(mode, parts, nranks)
        out[mode] = {"ranks": rows, "run_id": run_id,
                     "wall_seconds": round(time.perf_counter() - t0, 3)}
        print(f"[{mode}] wall={out[mode]['wall_seconds']}s  " +
              "  ".join(f"r{r['rank']}:{r['analysis_seconds']}s/"
                        f"{r['vm_hwm_mb']:.0f}MB" for r in rows),
              flush=True)

    def same_run(*ks):
        # cross-section ratios are only honest when both operands were
        # measured in the SAME run (same load, same code) — a partial
        # MAS_MODES rerun must not mix a fresh section with a stale one
        ids = {out[k].get("run_id") for k in ks if k in out}
        return (all(k in out for k in ks)
                and len(ids) == 1 and None not in ids)

    for k in ("parsymb_root_time_ratio", "parsymb_root_hwm_delta_ratio",
              "nonroot_time_ratio", "nonroot_hwm_ratio",
              "nonroot_hwm_delta_ratio", "wall_ratio"):
        out.pop(k, None)
    if same_run("parsymb", "root_bcast"):
        # what the distributed analysis buys OVER the root+bcast tier:
        # the root stops doing the whole ordering+symbolic itself
        ps = out["parsymb"]["ranks"]
        bc0 = out["root_bcast"]["ranks"]
        out["parsymb_root_time_ratio"] = round(
            bc0[0]["analysis_seconds"]
            / max(ps[0]["analysis_seconds"], 1e-9), 2)
        out["parsymb_root_hwm_delta_ratio"] = round(
            bc0[0].get("analysis_hwm_delta_mb", float("nan"))
            / max(ps[0].get("analysis_hwm_delta_mb", 1e-9), 1e-9), 2)
    if same_run("replicated", "root_bcast"):
        rep = out["replicated"]["ranks"]
        bc = out["root_bcast"]["ranks"]
        out["nonroot_time_ratio"] = round(
            np.mean([r["analysis_seconds"] for r in rep[1:]])
            / max(np.mean([r["analysis_seconds"] for r in bc[1:]]),
                  1e-9), 2)
        out["nonroot_hwm_ratio"] = round(
            np.mean([r["vm_hwm_mb"] for r in rep[1:]])
            / np.mean([r["vm_hwm_mb"] for r in bc[1:]]), 2)
        out["nonroot_hwm_delta_ratio"] = round(
            np.mean([r["analysis_hwm_delta_mb"] for r in rep[1:]])
            / max(np.mean([r["analysis_hwm_delta_mb"] for r in bc[1:]]),
                  1e-9), 2)
        # the barrier wall time: in replicated mode 4 analyses contend
        # for the core; in bcast mode one analysis + one O(nnz) transfer
        out["wall_ratio"] = round(out["replicated"]["wall_seconds"]
                                  / out["root_bcast"]["wall_seconds"], 2)
    with open(path, "w") as fh:
        json.dump(out, fh, indent=1)
    print("wrote", path)
    print(json.dumps({k: out[k] for k in
                      ("nonroot_time_ratio", "nonroot_hwm_ratio",
                       "wall_ratio") if k in out}))


if __name__ == "__main__":
    main()
