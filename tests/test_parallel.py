"""Multi-device sharded factorization on the virtual 8-device CPU mesh.

Mirrors the reference's strategy of oversubscribing MPI ranks on one box
(.travis_tests.sh) to test multi-process behavior; here the "ranks" are
XLA virtual devices in a jax.sharding.Mesh.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from superlu_dist_tpu.models.gallery import poisson2d
from superlu_dist_tpu.sparse.formats import symmetrize_pattern
from superlu_dist_tpu.utils.options import Options
from superlu_dist_tpu.ordering.dispatch import get_perm_c
from superlu_dist_tpu.symbolic.symbfact import symbolic_factorize
from superlu_dist_tpu.numeric.plan import build_plan
from superlu_dist_tpu.numeric.factor import make_factor_fn
from superlu_dist_tpu.parallel.grid import gridinit


def _plan(n_grid=12):
    a = poisson2d(n_grid)
    opts = Options()
    sym = symmetrize_pattern(a)
    col_order = get_perm_c(opts, a, sym)
    sf = symbolic_factorize(sym, col_order, relax=opts.relax,
                            max_supernode=opts.max_supernode)
    plan = build_plan(sf)
    avals = sym.data[sf.value_perm]
    thresh = np.sqrt(np.finfo(np.float64).eps) * a.norm_max()
    return plan, avals, thresh


def test_eight_devices_visible():
    assert len(jax.devices()) == 8


@pytest.mark.parametrize("shape", [(4, 2), (2, 2), (8, 1)])
@pytest.mark.slow
def test_sharded_factor_matches_single_device(shape):
    plan, avals, thresh = _plan()
    single = make_factor_fn(plan, "float64")
    ref_fronts, ref_tiny = single(jnp.asarray(avals),
                                  jnp.asarray(thresh))
    grid = gridinit(*shape)
    fn = make_factor_fn(plan, "float64", mesh=grid.mesh)
    fronts, tiny = fn(jnp.asarray(avals), jnp.asarray(thresh))
    assert int(tiny) == int(ref_tiny)
    for (lp, up), (rlp, rup) in zip(fronts, ref_fronts):
        np.testing.assert_allclose(np.asarray(lp), np.asarray(rlp),
                                   rtol=1e-12, atol=1e-12)
        np.testing.assert_allclose(np.asarray(up), np.asarray(rup),
                                   rtol=1e-12, atol=1e-12)


@pytest.mark.slow
def test_stream_matches_fused():
    plan, avals, thresh = _plan()
    fused = make_factor_fn(plan, "float64")
    rf, rt = fused(jnp.asarray(avals), jnp.asarray(thresh))
    from superlu_dist_tpu.numeric.stream import StreamExecutor
    ex = StreamExecutor(plan, "float64")
    gf, gt = ex(jnp.asarray(avals), jnp.asarray(thresh))
    assert int(gt) == int(rt)
    for (lp, up), (rlp, rup) in zip(gf, rf):
        np.testing.assert_array_equal(np.asarray(lp), np.asarray(rlp))
        np.testing.assert_array_equal(np.asarray(up), np.asarray(rup))


@pytest.mark.parametrize("shape", [(4, 2), (8, 1)])
@pytest.mark.slow
def test_sharded_stream_matches_single(shape):
    """The real-TPU executor must shard (VERDICT r1 gap #3): streamed
    per-bucket kernels under a mesh == single-device stream, bit-equal."""
    plan, avals, thresh = _plan()
    from superlu_dist_tpu.numeric.stream import StreamExecutor
    single = StreamExecutor(plan, "float64")
    rf, rt = single(jnp.asarray(avals), jnp.asarray(thresh))
    grid = gridinit(*shape)
    ex = StreamExecutor(plan, "float64", mesh=grid.mesh)
    gf, gt = ex(jnp.asarray(avals), jnp.asarray(thresh))
    assert int(gt) == int(rt)
    for (lp, up), (rlp, rup) in zip(gf, rf):
        np.testing.assert_allclose(np.asarray(lp), np.asarray(rlp),
                                   rtol=1e-12, atol=1e-12)
        np.testing.assert_allclose(np.asarray(up), np.asarray(rup),
                                   rtol=1e-12, atol=1e-12)


@pytest.mark.slow
def test_gssvx_with_grid_matches_serial():
    """The driver accepts a ProcessGrid (pdgssvx's gridinfo_t argument):
    full pipeline sharded over the mesh == single-device result."""
    from superlu_dist_tpu.drivers.gssvx import gssvx
    from superlu_dist_tpu.utils.options import Options
    a = poisson2d(11)
    xt = np.random.default_rng(6).standard_normal(a.n_rows)
    b = a.matvec(xt)
    x0, _, _, info0 = gssvx(Options(), a, b)
    grid = gridinit(4, 2)
    x1, lu1, stats1, info1 = gssvx(Options(), a, b, grid=grid)
    assert info0 == info1 == 0
    np.testing.assert_allclose(x1, x0, rtol=1e-12, atol=1e-12)
    np.testing.assert_allclose(x1, xt, rtol=1e-8, atol=1e-8)


@pytest.mark.slow
def test_device_solve_on_sharded_factors():
    """The pdgstrs analog must work when the factors live sharded on the
    mesh (solve after a multi-chip factorization, no host round-trip)."""
    from superlu_dist_tpu.numeric.stream import StreamExecutor
    from superlu_dist_tpu.numeric.factor import NumericFactorization
    from superlu_dist_tpu.solve.device import DeviceSolver
    from superlu_dist_tpu.solve.trisolve import lu_solve
    plan, avals, thresh = _plan(10)
    grid = gridinit(4, 2)
    ex = StreamExecutor(plan, "float64", mesh=grid.mesh)
    fronts, tiny = ex(jnp.asarray(avals), jnp.asarray(thresh))
    fact = NumericFactorization(plan=plan, fronts=list(fronts),
                                tiny_pivots=int(tiny),
                                dtype=jnp.dtype("float64"))
    rng = np.random.default_rng(0)
    d = rng.standard_normal((plan.n, 2))
    got = DeviceSolver(fact).solve(d)
    want = lu_solve(fact, d)
    np.testing.assert_allclose(got, want, rtol=1e-10, atol=1e-12)


def test_graft_dryrun():
    import importlib.util
    import os
    path = os.path.join(os.path.dirname(__file__), "..", "__graft_entry__.py")
    spec = importlib.util.spec_from_file_location("__graft_entry__", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.dryrun_multichip(8)


@pytest.mark.slow
def test_pool_partitioned_stream_matches_replicated():
    """Sharding the Schur pool itself across the mesh (the n≈1M memory
    path: ~27 GB pool > one chip's HBM) must be bit-equal to the
    replicated-pool stream."""
    from superlu_dist_tpu.numeric.stream import StreamExecutor
    plan, avals, thresh = _plan()
    ref = StreamExecutor(plan, "float64")(jnp.asarray(avals),
                                          jnp.asarray(thresh))
    grid = gridinit(4, 2)
    ex = StreamExecutor(plan, "float64", mesh=grid.mesh,
                        pool_partition=True)
    got = ex(jnp.asarray(avals), jnp.asarray(thresh))
    assert int(got[1]) == int(ref[1])
    for (lp, up), (rlp, rup) in zip(got[0], ref[0]):
        np.testing.assert_allclose(np.asarray(lp), np.asarray(rlp),
                                   rtol=1e-12, atol=1e-12)
        np.testing.assert_allclose(np.asarray(up), np.asarray(rup),
                                   rtol=1e-12, atol=1e-12)


def test_pool_partitioned_fused_matches_replicated():
    from superlu_dist_tpu.numeric.factor import make_factor_fn
    plan, avals, thresh = _plan()
    ref = make_factor_fn(plan, "float64")(jnp.asarray(avals),
                                          jnp.asarray(thresh))
    grid = gridinit(8, 1)
    fn = make_factor_fn(plan, "float64", mesh=grid.mesh,
                        pool_partition=True)
    got = fn(jnp.asarray(avals), jnp.asarray(thresh))
    assert int(got[1]) == int(ref[1])
    for (lp, up), (rlp, rup) in zip(got[0], ref[0]):
        np.testing.assert_allclose(np.asarray(lp), np.asarray(rlp),
                                   rtol=1e-12, atol=1e-12)
        np.testing.assert_allclose(np.asarray(up), np.asarray(rup),
                                   rtol=1e-12, atol=1e-12)


@pytest.mark.slow
def test_gssvx_pool_partition_option():
    """Options.pool_partition reaches the executor through the driver."""
    from superlu_dist_tpu.drivers.gssvx import gssvx
    from superlu_dist_tpu.utils.options import Options
    a = poisson2d(10)
    xt = np.random.default_rng(1).standard_normal(a.n_rows)
    b = a.matvec(xt)
    x0, _, _, _ = gssvx(Options(), a, b)
    grid = gridinit(4, 2)
    x1, lu, stats, info = gssvx(Options(pool_partition=True), a, b,
                                grid=grid)
    assert info == 0
    np.testing.assert_allclose(x1, x0, rtol=1e-12, atol=1e-12)


@pytest.mark.slow
def test_level_granularity_matches_group():
    """granularity="level" (one dispatch per elimination level) must be
    bit-equal to the per-group stream, plain and mesh-sharded."""
    from superlu_dist_tpu.numeric.stream import StreamExecutor
    plan, avals, thresh = _plan()
    ref = StreamExecutor(plan, "float64")(jnp.asarray(avals),
                                          jnp.asarray(thresh))
    lev = StreamExecutor(plan, "float64", granularity="level")(
        jnp.asarray(avals), jnp.asarray(thresh))
    assert int(lev[1]) == int(ref[1])
    for (lp, up), (rlp, rup) in zip(lev[0], ref[0]):
        np.testing.assert_array_equal(np.asarray(lp), np.asarray(rlp))
        np.testing.assert_array_equal(np.asarray(up), np.asarray(rup))
    grid = gridinit(4, 2)
    lev_m = StreamExecutor(plan, "float64", mesh=grid.mesh,
                           granularity="level")(
        jnp.asarray(avals), jnp.asarray(thresh))
    assert int(lev_m[1]) == int(ref[1])
    for (lp, up), (rlp, rup) in zip(lev_m[0], ref[0]):
        np.testing.assert_allclose(np.asarray(lp), np.asarray(rlp),
                                   rtol=1e-12, atol=1e-12)
        np.testing.assert_allclose(np.asarray(up), np.asarray(rup),
                                   rtol=1e-12, atol=1e-12)


def test_offload_with_pool_partition():
    """The round-3 config-4 recipe: host-offloaded factor panels + the
    Schur pool sharded across the mesh, together, must match the plain
    stream bit-for-bit."""
    from superlu_dist_tpu.numeric.stream import StreamExecutor
    plan, avals, thresh = _plan()
    ref = StreamExecutor(plan, "float64")(jnp.asarray(avals),
                                          jnp.asarray(thresh))
    grid = gridinit(4, 2)
    ex = StreamExecutor(plan, "float64", mesh=grid.mesh,
                        pool_partition=True, offload="host")
    assert ex.offload == "host"           # the mode actually engaged
    got = ex(jnp.asarray(avals), jnp.asarray(thresh))
    assert int(got[1]) == int(ref[1])
    for (lp, up), (rlp, rup) in zip(got[0], ref[0]):
        # offload guarantees host-resident results; correctness is the
        # numeric equality below (device-residency internals are covered
        # by the executor's own offload path)
        assert isinstance(lp, np.ndarray)
        np.testing.assert_allclose(lp, np.asarray(rlp),
                                   rtol=1e-12, atol=1e-12)
        np.testing.assert_allclose(up, np.asarray(rup),
                                   rtol=1e-12, atol=1e-12)


@pytest.mark.slow
def test_host_share_split_matches_plain():
    """The CPU-share split (SLU_TPU_HOST_FLOPS — the reference's
    gemm_division_cpu_gpu + N_GEMM threshold, SRC/util.c:1271-1360):
    leading small levels run on the host CPU device with one pool handoff.
    On the CPU backend the handoff is same-device, but the full routing /
    handoff / mixed-front finalize path executes and must be bit-equal to
    the unsplit stream, at both granularities."""
    from superlu_dist_tpu.numeric.stream import StreamExecutor
    from superlu_dist_tpu.numeric.stream import _bucket_len
    from superlu_dist_tpu.symbolic.symbfact import _front_flops

    # fine supernodes (no amalgamation) give the real shape: many cheap
    # leaf levels below a few big ancestor levels
    a = poisson2d(16)
    sym = symmetrize_pattern(a)
    col_order = get_perm_c(Options(), a, sym)
    sf = symbolic_factorize(sym, col_order, relax=4, max_supernode=16,
                            amalg_tol=0.0)
    plan = build_plan(sf)
    avals = sym.data[sf.value_perm]
    thresh = np.sqrt(np.finfo(np.float64).eps) * a.norm_max()

    ref = StreamExecutor(plan, "float64", host_flops=0)(
        jnp.asarray(avals), jnp.asarray(thresh))
    # threshold above the leaf level's cost but below the costliest level,
    # so the split engages AND leaves trailing levels on the device
    lv_cost = {}
    for g in plan.groups:
        fl = _bucket_len(g.batch, 1) * _front_flops(g.w, g.u)
        lv_cost[g.level] = max(lv_cost.get(g.level, 0), fl)
    costs = [lv_cost[lv] for lv in sorted(lv_cost)]
    cut = max(costs)
    assert costs[0] < cut, "plan must have a cheap leaf level"
    for gran in ("group", "level"):
        ex = StreamExecutor(plan, "float64", granularity=gran,
                            host_flops=cut)
        assert ex.host_levels > 0, "threshold must engage on this plan"
        assert ex.host_levels < len({g.level for g in plan.groups}), \
            "split must leave trailing levels on the device"
        out = ex(jnp.asarray(avals), jnp.asarray(thresh))
        assert int(out[1]) == int(ref[1])
        for (lp, up), (rlp, rup) in zip(out[0], ref[0]):
            np.testing.assert_array_equal(np.asarray(lp), np.asarray(rlp))
            np.testing.assert_array_equal(np.asarray(up), np.asarray(rup))
    # host-share combined with offload="host": the lag window must not
    # reach into the host prefix (it would block on host compute and
    # corrupt the comm split); result still bit-equal, all fronts numpy
    exc = StreamExecutor(plan, "float64", offload="host", host_flops=cut)
    assert exc.host_levels > 0
    outc = exc(jnp.asarray(avals), jnp.asarray(thresh))
    assert int(outc[1]) == int(ref[1])
    assert all(isinstance(lp, np.ndarray) for lp, _ in outc[0])
    for (lp, up), (rlp, rup) in zip(outc[0], ref[0]):
        np.testing.assert_array_equal(np.asarray(lp), np.asarray(rlp))
        np.testing.assert_array_equal(np.asarray(up), np.asarray(rup))

    # a mesh-sharded executor ignores the host share (everything stays on
    # the mesh)
    grid = gridinit(4, 2)
    exm = StreamExecutor(plan, "float64", mesh=grid.mesh, host_flops=1e7)
    assert exm.host_levels == 0
