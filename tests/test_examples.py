"""The example drivers double as integration tests — the reference's own
discipline (SURVEY.md §4: EXAMPLE drivers fabricate xtrue and check the
solve, .travis_tests.sh runs them as CI).  Each must exit 0."""

import os
import subprocess
import sys

import pytest

EXAMPLES = ["pddrive.py", "pddrive1.py", "pddrive2.py", "pddrive3.py",
            "pddrive4.py", "pzdrive.py", "pzdrive1.py", "pzdrive2.py",
            "pzdrive3.py", "pzdrive4.py", "pddrive_ABglobal.py",
            "pddrive_dist.py", "pddrive_df64.py", "pddrive_grid.py",
            "pddrive_refactor.py"]
ROOT = os.path.join(os.path.dirname(__file__), "..")


@pytest.mark.parametrize("script", EXAMPLES)
def test_example_runs_clean(script):
    env = dict(os.environ)
    # examples run in a fresh interpreter: pin the CPU backend the same
    # way the conftest does (the session's accelerator plugin would
    # otherwise grab a tunnel the CI environment may not have)
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "examples", script),
         "--backend", "cpu"],
        capture_output=True, timeout=600, env=env, cwd=ROOT)
    assert r.returncode == 0, r.stdout.decode() + r.stderr.decode()
    assert b"residual" in r.stdout


import pytest  # noqa: E402

# slow tier: multi-process / native-build / at-scale — fast CI runs -m "not slow"
pytestmark = pytest.mark.slow
