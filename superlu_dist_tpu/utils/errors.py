"""Error model.

The reference reports errors via ``info`` codes (<0: the -info-th argument
was invalid, via pxerr_dist; >0: U(i,i) is exactly singular, pdgstrf.c:234-241)
or aborts (ABORT, util_dist.h:27-34).  We use exceptions for argument errors
and return ``info`` from drivers for singularity, matching pdgssvx semantics.
"""


def _flight_dump(exc) -> None:
    """Flight-recorder postmortem hook (obs/flightrec.py): the
    structured breakdown/mismatch errors dump the telemetry ring at
    CONSTRUCTION time, so the evidence lands on disk on every rank even
    when the exception later dies inside a watchdog ``os._exit``, a
    worker process, or an over-broad caller ``except``.  Must never
    interfere with raising the error itself."""
    try:
        from superlu_dist_tpu.obs.flightrec import on_error
        exc.flightrec_dump = on_error(exc)   # path, or None when off
    except Exception:
        exc.flightrec_dump = None


class SuperLUError(Exception):
    """Invalid argument / internal error (analog of pxerr_dist + ABORT)."""


class SingularMatrixError(SuperLUError):
    """U(i,i) exactly singular and ReplaceTinyPivot disabled (info > 0)."""

    def __init__(self, k: int):
        self.info = k + 1   # reference convention: 1-based first zero pivot
        super().__init__(f"Factorization failed: U({k},{k}) is exactly zero "
                         f"(info={self.info})")


class NumericBreakdownError(SuperLUError):
    """A non-finite value (NaN/Inf) appeared in the computed factors or the
    solution while ReplaceTinyPivot was active — overflow or NaN input, not
    plain singularity (which SingularMatrixError covers).  Tripped by the
    isfinite sentinels in the numeric layer so a breakdown surfaces at the
    offending supernode instead of propagating NaN through the remainder of
    the factorization (the structured replacement for the reference's ABORT,
    util_dist.h:27-34)."""

    def __init__(self, supernode: int = -1, col: int = -1, where: str = ""):
        self.supernode = int(supernode)   # first contaminated supernode
        self.col = int(col)               # its first global column (0-based)
        self.where = where                # which stage tripped the sentinel
        loc = (f" at supernode {supernode} (column {col})"
               if supernode >= 0 else "")
        stage = f" during {where}" if where else ""
        super().__init__(
            f"non-finite values detected{stage}{loc}; the system is "
            "numerically broken down (overflow or NaN input)")
        _flight_dump(self)


class DeadlineExceededError(SuperLUError):
    """The cooperative deadline (``Options.deadline_s`` /
    ``SLU_TPU_DEADLINE_S``) expired between dispatch groups.  The factor
    loop writes a checkpoint of the completed-group frontier FIRST (when
    checkpointing is armed), so the work done before cancellation is
    durable — ``checkpoint_path`` names it and ``gssvx(resume_from=...)``
    restarts from it.  On the multi-rank path the expiry decision is an
    allreduced flag, so every rank raises this together instead of one
    rank abandoning its peers inside a collective (the SLU101/SLU106
    discipline: cancellation must never become a deadlock)."""

    def __init__(self, deadline_s: float, elapsed_s: float, where: str = "",
                 checkpoint_path: str | None = None,
                 expired_ranks: int = 0):
        self.deadline_s = float(deadline_s)
        self.elapsed_s = float(elapsed_s)
        self.where = where
        self.checkpoint_path = checkpoint_path
        self.expired_ranks = int(expired_ranks)   # 0 = single-rank check
        stage = f" during {where}" if where else ""
        ck = (f"; frontier checkpointed at {checkpoint_path}"
              if checkpoint_path else "")
        ranks = (f" ({expired_ranks} rank(s) over budget)"
                 if expired_ranks else "")
        super().__init__(
            f"cooperative deadline of {deadline_s:.3f}s exceeded"
            f"{stage} after {elapsed_s:.3f}s{ranks}{ck}")
        _flight_dump(self)


class CheckpointError(SuperLUError):
    """A persisted bundle (LU handle or factor checkpoint) is unusable:
    missing manifest, structural mismatch, or an unreadable artifact.
    Subclasses distinguish the failure families so callers can decide
    between 'refactor from scratch' and 'operator error'."""


class CheckpointCorruptError(CheckpointError):
    """Integrity failure: a per-array digest mismatch or a truncated
    array file.  Raised instead of returning garbage factors — the
    whole point of the manifest (persist/serial.py)."""


class CheckpointVersionError(CheckpointError):
    """The bundle's format version is not one this build can read
    (persist.FORMAT_VERSION — the versioning rule is documented in
    docs/RELIABILITY.md)."""


class CheckpointMismatchError(CheckpointError):
    """The checkpoint is internally consistent but belongs to a
    DIFFERENT factorization: plan fingerprint, value digest, dtype or
    threshold differ from the run trying to resume.  Resuming would
    silently splice incompatible frontiers, so this is a hard error."""


class PatternMismatchError(SuperLUError):
    """A values-only refactorization (``drivers/gssvx.refactor``) was
    handed a matrix whose sparsity pattern, shape, or permutation
    identity differs from the one the handle's symbolic structure was
    built on.  Silently re-running the symbolic phase here would break
    the refactor contract (zero symbolic cost, zero recompile, plan and
    compiled programs reused by identity), so drift is a hard, typed
    error: re-analyze with ``Fact=DOFACT`` to factor the new pattern.
    ``expected_digest``/``got_digest`` carry the sha256 pattern digests
    (persist.serial.pattern_digest — the same identity bundles record)
    when both sides could compute one.  Dumps a flight-recorder
    postmortem at construction."""

    def __init__(self, reason: str, expected_digest: str = "",
                 got_digest: str = "", n: int = -1, nnz: int = -1):
        self.reason = reason
        self.expected_digest = expected_digest
        self.got_digest = got_digest
        self.n = int(n)
        self.nnz = int(nnz)
        dg = (f" (handle pattern {expected_digest[:12]}, "
              f"got {got_digest[:12]})"
              if expected_digest and got_digest else "")
        super().__init__(
            f"refactor refused: {reason}{dg} — a values-only refactor "
            "requires the exact sparsity pattern the handle was analyzed "
            "on; factor the new pattern with Fact=DOFACT instead")
        _flight_dump(self)


class CommTimeoutError(SuperLUError):
    """A bounded-wait collective leg (``SLU_TPU_COMM_TIMEOUT_S``) kept
    timing out on a peer whose process is still ALIVE, and the retry
    budget (``SLU_TPU_COMM_RETRIES`` > 0) ran out.  This is the
    slow-not-dead verdict: the failure detector refused to declare the
    peer failed (its pid answers ``kill(pid, 0)``), so the caller gets a
    timeout, not a :class:`RankFailureError` — retrying later, raising
    the timeout, or widening the budget are all sound.  With the default
    unlimited retries (``SLU_TPU_COMM_RETRIES=0``) this error never
    fires: live-but-slow peers are waited out indefinitely."""

    def __init__(self, op: str, stuck_rank: int, timeout_s: float,
                 retries: int, seq: int = -1, site: str = ""):
        self.op = op
        self.stuck_rank = int(stuck_rank)
        self.timeout_s = float(timeout_s)
        self.retries = int(retries)
        self.seq = int(seq)
        self.site = site
        where = f" at {site}" if site else ""
        super().__init__(
            f"collective {op} (seq {seq}){where} timed out {retries}x "
            f"({timeout_s:.3f}s each) waiting on live rank {stuck_rank} "
            "— peer is slow, not dead (SLU_TPU_COMM_RETRIES exhausted)")
        _flight_dump(self)


class RankFailureError(SuperLUError):
    """The failure detector declared peer rank(s) DEAD: a bounded-wait
    collective leg timed out (``SLU_TPU_COMM_TIMEOUT_S``), the detector
    found the stuck peer's pid gone (``kill(pid, 0)`` → ESRCH — liveness
    is polled on the process itself, so death is detected even when the
    heartbeat thread died with it), and the survivors converged on the
    same dead set through the ``.ftx`` agreement board (a wait-free
    bulletin domain that excludes the dead rank by construction — no
    survivor ever blocks on it).  Every surviving rank raises this error
    naming the dead rank(s), the op it was inside, the collective
    sequence number and the call site — the ULFM revoke→agree shape: a
    dead rank is a structured, recoverable event, not a fleet-killing
    hang (``Options.ft`` = "shrink"/"respawn" in parallel/recover.py
    resumes the solve on the survivors from the last checkpoint
    frontier)."""

    def __init__(self, dead_ranks, op: str = "", seq: int = -1,
                 site: str = "", rank: int = -1, n_ranks: int = 0,
                 epoch: int = 0):
        self.dead_ranks = sorted(int(r) for r in dead_ranks)
        self.op = op
        self.seq = int(seq)
        self.site = site
        self.rank = int(rank)
        self.n_ranks = int(n_ranks)
        self.epoch = int(epoch)
        where = f" at {site}" if site else ""
        inside = f" during {op} (seq {seq})" if op else ""
        super().__init__(
            f"rank(s) {','.join(map(str, self.dead_ranks))} of "
            f"{n_ranks} declared dead{inside}{where} (epoch {epoch}, "
            f"observed from rank {rank}); survivors agreed via the .ftx "
            "board — recover with Options.ft='shrink'/'respawn' or treat "
            "as fatal (ft='abort')")
        _flight_dump(self)


class ServerClosedError(SuperLUError):
    """A ``SolveServer`` request could not be served because the server
    closed: either ``submit()`` was called after ``close()`` (the request
    was never enqueued), or the request was still queued/undelivered when
    the server shut down — ``close()`` delivers this to every undelivered
    ticket deterministically, so a waiter can never hang on a server that
    no longer exists (serve/server.py)."""


class ServeOverloadError(SuperLUError):
    """Admission control shed this request: accepting its columns would
    push the pending queue past ``SLU_TPU_SERVE_QUEUE_MAX``, or the
    server is in drain mode and rejects new work.  Raised AT SUBMIT —
    the request never queues, so an overload degrades into fast
    structured rejections instead of an unbounded queue whose every
    entry eventually misses its deadline (docs/SERVING.md failure-domain
    matrix).  Retry with backoff, route to another replica, or widen the
    cap."""

    def __init__(self, columns: int, pending_cols: int, queue_max: int,
                 reason: str = "queue_full"):
        self.columns = int(columns)
        self.pending_cols = int(pending_cols)
        self.queue_max = int(queue_max)
        self.reason = reason
        why = ("server is draining (finishing in-flight work, rejecting "
               "new requests)" if reason == "draining" else
               f"queue holds {pending_cols} columns of a "
               f"{queue_max}-column cap")
        super().__init__(
            f"solve request ({columns} column(s)) shed by admission "
            f"control: {why}")


class ServeDeadlineError(SuperLUError):
    """The request's serving deadline (``SLU_TPU_SERVE_DEADLINE_MS``)
    expired while its columns were still queued — the dispatcher (or the
    waiting ticket itself, when the dispatcher is stalled) expired it
    instead of serving an answer the caller has already abandoned.
    Expired work is removed from the queue, so a backlog of dead
    requests cannot starve live ones.

    ``stages`` carries the ticket's per-stage timings (TicketContext
    ``stages_ms()``, obs/slo.py) when request tracing is on, so the
    flight-recorder postmortem names the stage that ate the budget.
    The error is constructed UNDER server locks (the expiry paths), so
    it performs no postmortem I/O at construction — callers invoke
    :meth:`flight_postmortem` once outside the locks (the SLU109 hold
    discipline)."""

    def __init__(self, deadline_s: float, waited_s: float, columns: int,
                 stages: dict | None = None):
        self.deadline_s = float(deadline_s)
        self.waited_s = float(waited_s)
        self.columns = int(columns)
        self.ticket_stages = dict(stages) if stages else None
        self.flightrec_dump = None
        super().__init__(
            f"solve request ({columns} column(s)) missed its "
            f"{deadline_s:.3f}s serving deadline after {waited_s:.3f}s "
            "in queue (shed, not served)")

    def flight_postmortem(self):
        """Dump the flight-recorder postmortem (with the ticket's stage
        timings attached) — call OUTSIDE any server/router lock."""
        _flight_dump(self)
        return self.flightrec_dump


class ServePoisonedError(SuperLUError):
    """THIS request poisoned (or was poisoned inside) a serving
    micro-batch: its column(s) produced non-finite results, or the batch
    solve raised ``NumericBreakdownError`` and bisection pinned the
    blame on them.  The healthy neighbors of the same micro-batch were
    isolated and served bit-identically to an unpoisoned run — one bad
    right-hand side costs only its own ticket (serve/server.py,
    ``_isolate``).  ``columns`` are request-relative 0-based column
    indices.  Dumps a flight-recorder postmortem at construction (the
    poison scatter path constructs it outside the server lock);
    ``stages`` attaches the ticket's per-stage timings (TicketContext
    ``stages_ms()``, obs/slo.py) so the postmortem carries the span
    chain."""

    def __init__(self, columns, batch_columns: int = 0, where: str = "",
                 stages: dict | None = None):
        self.columns = sorted(int(c) for c in columns)
        self.batch_columns = int(batch_columns)
        self.where = where
        self.ticket_stages = dict(stages) if stages else None
        stage = f" during {where}" if where else ""
        batch = (f" of a {batch_columns}-column micro-batch"
                 if batch_columns else "")
        super().__init__(
            f"request column(s) {','.join(map(str, self.columns))} "
            f"poisoned the solve{stage}{batch}: non-finite results "
            "isolated to this ticket (healthy neighbors were re-served "
            "unaffected)")
        _flight_dump(self)


class FactorCorruptError(SuperLUError):
    """The factor-integrity scrubber (``SLU_TPU_SERVE_SCRUB_S``)
    re-hashed the handle's resident panel stacks and found front
    group(s) whose sha256 digest no longer matches the persist-bundle
    (or construction-time) ground truth — silent data corruption in the
    factors.  The handle is QUARANTINED: every queued and future request
    fails with this error instead of being served garbage X, until
    ``server.swap()`` installs a fresh handle.  Dumps a flight-recorder
    postmortem at construction (``dump=False`` for the per-submit
    re-raises of an already-reported quarantine)."""

    def __init__(self, groups, source: str = "", dump: bool = True):
        self.groups = sorted(int(g) for g in groups)
        self.source = source
        src = f" (digest baseline: {source})" if source else ""
        super().__init__(
            f"factor integrity scrub failed: front group(s) "
            f"{','.join(map(str, self.groups))} no longer match their "
            f"sha256 digests{src} — handle quarantined; swap in a fresh "
            "factorization (server.swap) instead of serving corrupt X")
        if dump:
            _flight_dump(self)
        else:
            self.flightrec_dump = None


class ReplicaFailureError(SuperLUError):
    """The fleet router (serve/fleet.py) declared a serving replica
    FAILED: its process died (``pid_alive`` — the same kill(pid,0) +
    zombie verdict the PR 8 rank failure detector uses, generalized to
    replica processes), its worker crashed, or a factor-integrity
    quarantine made it unroutable.  Every ticket the replica had
    accepted but not yet delivered is RE-ROUTED to a healthy replica
    under the same idempotent retry token, so a client observes
    bitwise-identical X, never this error — unless zero healthy
    replicas remain, in which case the undelivered tickets are handed
    this error instead of a hang (the zero-loss failover contract,
    docs/SERVING.md fleet chapter).  Dumps a flight-recorder postmortem
    at construction naming the dead replica and the re-routed ticket
    set."""

    def __init__(self, replica: int, tickets, cause: str = "",
                 pid: int = -1, kind: str = "replica"):
        self.replica = int(replica)
        self.tickets = sorted(int(t) for t in tickets)
        self.cause = cause
        self.pid = int(pid)
        self.kind = kind
        why = f" ({cause})" if cause else ""
        who = f" pid {pid}" if pid > 0 else ""
        super().__init__(
            f"fleet {kind} {replica}{who} declared failed{why}; "
            f"{len(self.tickets)} undelivered ticket(s) "
            f"{self.tickets} re-routed to healthy replicas under their "
            "idempotent retry tokens (zero-loss failover — clients see "
            "identical X, not this error, while healthy replicas "
            "remain)")
        _flight_dump(self)


class DeployRollbackError(SuperLUError):
    """A rolling deploy (``FleetRouter.deploy``) was ROLLED BACK: the
    new bundle failed its load/scrub integrity verification or a canary
    batch's quality gate (non-finite X, or componentwise berr past the
    gate) on some replica, so every replica already swapped was
    restored to the previous bundle and the fleet keeps serving the old
    factors.  ``stage`` names the failing check (``load`` / ``scrub`` /
    ``canary``), ``replica`` the replica it failed on, ``rolled_back``
    the replicas that were restored.  Dumps a flight-recorder
    postmortem at construction."""

    def __init__(self, key, bundle: str, stage: str, replica: int = -1,
                 rolled_back=(), cause: str = ""):
        self.key = key
        self.bundle = str(bundle)
        self.stage = stage
        self.replica = int(replica)
        self.rolled_back = sorted(int(r) for r in rolled_back)
        self.cause = cause
        at = f" on replica {replica}" if replica >= 0 else ""
        why = f": {cause}" if cause else ""
        back = (f"; replica(s) {self.rolled_back} restored to the "
                "previous bundle" if self.rolled_back else
                "; no replica had swapped yet")
        super().__init__(
            f"rolling deploy of bundle {self.bundle!r} for handle "
            f"{key!r} rolled back at the {stage} check{at}{why}{back} "
            "— the fleet keeps serving the previous factors "
            "(docs/SERVING.md fleet chapter)")
        _flight_dump(self)


class RefactorRollbackError(SuperLUError):
    """A refactorization was ROLLED BACK: the shadow factorization over
    the new values broke down (NaN/Inf, singular), missed its BERR
    canary gate, or — on the fleet verb (``FleetRouter.refactor``) — a
    replica failed its per-replica canary mid-roll, so every replica
    already swapped to the refactored bundle was restored and the
    previous consistent handle keeps serving.  ``stage`` names the
    failing check (``factor`` / ``canary`` / ``deploy``), ``replica``
    the replica it failed on (-1 for the handle-level pipeline),
    ``rolled_back`` the replicas restored, ``berr``/``berr_target`` the
    measured vs required canary backward error when the gate fired.
    Dumps a flight-recorder postmortem at construction."""

    def __init__(self, key, stage: str, replica: int = -1,
                 rolled_back=(), cause: str = "", berr: float = -1.0,
                 berr_target: float = -1.0):
        self.key = key
        self.stage = stage
        self.replica = int(replica)
        self.rolled_back = sorted(int(r) for r in rolled_back)
        self.cause = cause
        self.berr = float(berr)
        self.berr_target = float(berr_target)
        at = f" on replica {replica}" if replica >= 0 else ""
        why = f": {cause}" if cause else ""
        gate = (f" (berr {berr:.3e} > gate {berr_target:.3e})"
                if berr >= 0.0 and berr_target >= 0.0 else "")
        back = (f"; replica(s) {self.rolled_back} restored to the "
                "previous factors" if self.rolled_back else "")
        super().__init__(
            f"refactor of handle {key!r} rolled back at the {stage} "
            f"check{at}{why}{gate}{back} — the previous consistent "
            "factorization keeps serving (docs/SERVING.md fleet-refactor "
            "verb)")
        _flight_dump(self)


class LockOrderError(SuperLUError):
    """Lock-verify mode (``SLU_TPU_VERIFY_LOCKS=1``, slulint's runtime
    rule SLU109 twin — ``utils/lockwatch.py``) detected a lock-order
    inversion: this thread is about to acquire ``inner`` while holding
    ``outer``, but the global order graph already records ``inner`` held
    while ``outer`` was acquired (at ``inverse_site``).  Two threads
    entering that cycle from different ends freeze forever; with
    verification on, the acquisition raises HERE — before blocking —
    naming both acquisition sites (the SLU106 deadlock-to-diagnosis
    conversion, for threads instead of ranks).  Dumps a flight-recorder
    postmortem at construction."""

    def __init__(self, outer: str, inner: str, site: str,
                 inverse_site: str):
        self.outer = outer
        self.inner = inner
        self.site = site
        self.inverse_site = inverse_site
        super().__init__(
            f"lock-order inversion (SLU109 runtime): acquiring "
            f"`{inner}` while holding `{outer}` at {site}, but the "
            f"inverse order `{inner}` -> `{outer}` was recorded at "
            f"{inverse_site} — two threads entering this cycle from "
            "different ends deadlock (this acquisition raised instead "
            "of blocking; SLU_TPU_VERIFY_LOCKS=1)")
        _flight_dump(self)


class ProgramAuditError(SuperLUError):
    """Program-audit mode (``SLU_TPU_VERIFY_PROGRAMS=1``, slulint's
    v4 IR rules SLU111/SLU112/SLU114 — ``utils/programaudit.py``)
    rejected a jitted program at construction/AOT-stage time: a
    declared-dead large input is not donated (peak-memory doubling,
    SLU111), a per-matrix constant is baked into the program
    (warm-start defeat, SLU112), or an SPMD program's branches execute
    divergent collective sequences / name axes off the mesh (in-program
    deadlock, SLU114).  Raised BEFORE the program ever runs — the same
    verify-before-it-OOMs/deadlocks conversion SLU106/SLU109 apply at
    runtime, moved to program-construction time.  ``findings`` holds the
    slulint Finding records (rule id + program label + offending
    eqn/arg); dumps a flight-recorder postmortem at construction."""

    def __init__(self, site: str, program: str, findings):
        self.site = site
        self.program = program
        self.findings = list(findings)
        self.rules = sorted({f.rule for f in self.findings})
        lines = "; ".join(f"{f.rule}: {f.message}" for f in self.findings)
        super().__init__(
            f"program audit failed for {site}[{program}] "
            f"({', '.join(self.rules)}): {lines} "
            "(SLU_TPU_VERIFY_PROGRAMS=1 — docs/ANALYSIS.md catalogs the "
            "program rules)")
        _flight_dump(self)


class PrecisionAuditError(SuperLUError):
    """Precision-audit mode (``SLU_TPU_VERIFY_DTYPES=1``, slulint's v5
    precision rules SLU115/SLU116 — ``utils/programaudit.py``) rejected
    a jitted program at construction/AOT-stage time: a narrowing
    ``convert_element_type`` discards mantissa bits outside the
    sanctioned GEMM-input pattern (SLU115), or a ``dot_general``
    accumulates narrower than its widest operand / narrower than f32 on
    16-bit inputs (SLU116) — the arithmetic running at a precision the
    escalation ladder never sanctioned, caught BEFORE the program runs
    instead of by a BERR gate three rungs later.  ``findings`` holds the
    slulint Finding records; dumps a flight-recorder postmortem at
    construction."""

    def __init__(self, site: str, program: str, findings):
        self.site = site
        self.program = program
        self.findings = list(findings)
        self.rules = sorted({f.rule for f in self.findings})
        lines = "; ".join(f"{f.rule}: {f.message}" for f in self.findings)
        super().__init__(
            f"precision audit failed for {site}[{program}] "
            f"({', '.join(self.rules)}): {lines} "
            "(SLU_TPU_VERIFY_DTYPES=1 — docs/ANALYSIS.md catalogs the "
            "precision rules)")
        _flight_dump(self)


class ShardingAuditError(SuperLUError):
    """Sharding-audit mode (``SLU_TPU_VERIFY_SHARDING=1``, slulint's v6
    sharding rules — ``utils/programaudit.py``) rejected a jitted
    program at construction/AOT-stage time: a gathering collective
    materializes whole-buffer cross-shard traffic, or an explicit
    constraint resolves a large buffer to a fully-replicated layout on a
    non-trivial mesh (SLU119, ``analysis/rules_sharding.py``) — the
    implicit-replication blowup that turns a pod-slice port into an OOM,
    caught BEFORE the program runs.  ``findings`` holds the slulint
    Finding records; dumps a flight-recorder postmortem at
    construction."""

    def __init__(self, site: str, program: str, findings):
        self.site = site
        self.program = program
        self.findings = list(findings)
        self.rules = sorted({f.rule for f in self.findings})
        lines = "; ".join(f"{f.rule}: {f.message}" for f in self.findings)
        super().__init__(
            f"sharding audit failed for {site}[{program}] "
            f"({', '.join(self.rules)}): {lines} "
            "(SLU_TPU_VERIFY_SHARDING=1 — docs/ANALYSIS.md catalogs the "
            "sharding rules)")
        _flight_dump(self)


class MemoryBudgetError(ShardingAuditError):
    """The SLU121 static peak-memory model priced a program above
    ``SLU_TPU_MEM_BUDGET_BYTES``: the liveness walk's high-water
    live-byte estimate (args + baked consts + intermediates,
    free-after-last-use) does not fit the declared per-device budget, so
    the submit raises HERE — at program construction, naming the program
    (for the mega executor: the offending bucket rung) and its largest
    live buffers — instead of the first real MXU run dying in an opaque
    device OOM.  A subclass of :class:`ShardingAuditError` so one
    ``except`` covers the whole v6 audit family; ``peak_bytes`` /
    ``budget_bytes`` carry the verdict."""

    def __init__(self, site: str, program: str, findings,
                 peak_bytes: int = 0, budget_bytes: int = 0):
        self.peak_bytes = int(peak_bytes)
        self.budget_bytes = int(budget_bytes)
        super().__init__(site=site, program=program, findings=findings)


class CollectiveMismatchError(SuperLUError):
    """Lockstep-verify mode (SLU_TPU_VERIFY_COLLECTIVES=1, slulint's
    runtime rule SLU106) detected ranks entering DIFFERENT collectives:
    the digest exchange that precedes every TreeComm collective came back
    with divergent (call-site, op, payload shape/dtype, sequence) records.
    Without verification this is the classic silent distributed deadlock —
    each rank blocks forever inside a collective its peers never entered;
    with it, every rank raises this error naming the divergent call sites
    (the MUST-style conversion of a hang into a diagnosis).

    ``records`` holds one dict per rank: {rank, seq, op, shape, dtype,
    root, site}."""

    def __init__(self, records, rank: int = -1):
        self.records = list(records)
        self.rank = int(rank)
        by_site = {}
        for r in self.records:
            key = (r.get("site", "?"), r.get("op", "?"),
                   tuple(r.get("shape", ())), str(r.get("dtype", "?")))
            by_site.setdefault(key, []).append(r.get("rank"))
        parts = []
        for (site, op, shape, dtype), ranks in sorted(by_site.items()):
            rs = ",".join(str(x) for x in ranks)
            parts.append(f"rank(s) {rs}: {op}{list(shape)}:{dtype} "
                         f"at {site}")
        super().__init__(
            "collective lockstep mismatch (SLU106): ranks entered "
            "divergent collectives — " + "; ".join(parts)
            + " — every rank must reach the same TreeComm collective "
              "sequence (this would have deadlocked without "
              "SLU_TPU_VERIFY_COLLECTIVES)")
        _flight_dump(self)
