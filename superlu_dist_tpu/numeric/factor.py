"""Level-batched multifrontal numeric factorization on the accelerator.

The execution analog of pdgstrf (SRC/pdgstrf.c:243) — but where the
reference runs an MPI look-ahead pipeline of per-panel BLAS calls, this
walks the elimination-tree levels bottom-up and, per (level, bucket) group,
issues assembly gathers, one batched dense partial LU (ops.dense), and a
strided Schur write-back.  All arrays stay resident on the device; the
update pool plays the role of the reference's bigU/bigV GEMM buffers
(pdgstrf.c:770-884) and the device-computed extend-add indices the role of
the dscatter_l/u index arithmetic (SRC/dscatter.c:111-290).

Four executors share the same per-group step (`group_step`):
  * make_factor_fn — the whole factorization traced into ONE jittable XLA
    program (best for moderate plans);
  * stream.StreamExecutor — one small jitted kernel per shape key, groups
    streamed through asynchronously (best on real TPU where giant programs
    compile slowly);
  * mega.MegaExecutor — shape-closed bucketed programs, O(1) compile
    count across matrices (and, since the SPMD tier, under a mesh);
  * parallel.spmd.SpmdFactorExecutor — the shard_map tier: the whole
    factorization as ONE SPMD program over the mesh, slots block-cyclic
    over the devices and the collectives in-program ops XLA can overlap
    with compute (the pdgstrf look-ahead shape).
"""

from __future__ import annotations

import dataclasses

import numpy as np
import jax
import jax.numpy as jnp

from superlu_dist_tpu.numeric.plan import FactorPlan
from superlu_dist_tpu.obs.trace import get_tracer
from superlu_dist_tpu.ops.dense import group_partial_factor


def extend_add_set(f, pool, m, ub, child_off, child_slot, rel):
    """One child-set's extend-add: gather each child's padded ub×ub Schur
    block from the pool and scatter-add it into the parent fronts at
    rel[c,i]·m + rel[c,j] (rel == m is the OOB sentinel).  SHARED
    MACHINERY: ``group_step`` unrolls a Python loop of these per group
    (one call per ChildSet), and the mega executor (numeric/mega.py)
    lax.scan's the SAME function over uniform padded child tables with a
    TRACED ``ub`` — keep it shape-polymorphic in (C, UB) and exact in
    the per-child gather indices (off + i·ub + j), which is what makes
    the two executors bitwise-identical."""
    c, ubmax = rel.shape
    ii = jnp.arange(ubmax)
    # per-child 2-D gather: row stride is the child's REAL ub (a python
    # int here, the per-set bucket in the mega scan), so entries past a
    # child's real block read out of its pool slab — always paired with
    # an OOB rel sentinel, hence dropped below
    src = (child_off[:, None, None] + ii[None, :, None] * ub
           + ii[None, None, :]).reshape(c, ubmax * ubmax)
    vals = pool.at[src].get(mode="fill", fill_value=0)
    ri, rj = rel[:, :, None], rel[:, None, :]
    # any sentinel (rel == m) in the pair must push the flat index OOB —
    # a mixed pair's ri*m + rj would land in-bounds at (ri+1, 0)
    dst = jnp.where((ri >= m) | (rj >= m), m * m,
                    ri * m + rj).reshape(c, ubmax * ubmax)
    return f.at[(child_slot[:, None], dst)].add(vals, mode="drop")


def group_step(dims, avals, pool, thresh, a_slot, a_flat, a_src, ws, off,
               children, front_sharding=None, pivot_sharding=None,
               replicated=None, pivot="blocked", gemm_prec="highest",
               pallas="off", write_back=True):
    """One (level, bucket) group: assemble + factor + write back.

    dims = (batch, m, w, u) static; `children` is either a list of
    (ub, child_off, child_slot, rel) with device arrays (the fused and
    streamed executors — one unrolled extend-add per set), or a 4-tuple
    of STACKED tables (child_off (S,C), child_slot (S,C), child_ub (S,),
    rel (S,C,UB)) which the mega executor folds in with ONE lax.scan —
    same per-set arithmetic, program size independent of the set count.
    Index padding convention (used by the streamed executor): scatter
    slots == batch and gather sources past the array end are
    dropped/filled — all index arithmetic keeps OOB entries OOB (rel
    sentinel == m maps past m*m).

    ``gemm_prec`` is the caller-resolved GEMM-precision ladder tier and
    ``pallas`` the resolved fused-kernel mode (numeric/pallas_kernels):
    both are baked into the cached jitted factories' keys, never read
    from env here (slulint SLU102/SLU105).  The Pallas path is bitwise-
    identical to the ``.at[]`` lowering, so every executor-equivalence
    contract is mode-independent — including under a mesh, where the
    SPMD tier runs it per-shard inside shard_map (interpret mode on CPU
    meshes, native on TPU; see parallel/spmd.py).

    ``write_back=False`` (the SPMD per-shard path) skips the pool
    scatter and returns the raw (batch, u*u) Schur values in the pool's
    position instead (None when u == 0): inside shard_map each device
    factors only its slot partition, so the full-order pool write is
    replayed by the caller AFTER the all-gather — keeping the exact
    scatter sequence (and hence bitwise factors) of the write_back=True
    lowering every other executor runs.
    """
    batch, m, w, u = dims
    dt = pool.dtype
    wsc = jax.lax.with_sharding_constraint
    use_pallas = pallas in ("on", "interpret")

    f = jnp.zeros((batch, m * m), dtype=dt)
    if replicated is not None:
        f = wsc(f, replicated)
    # identity columns for pivot-block padding (cols ws..w), computed on
    # device so padded batch slots (ws == 0) become identity fronts
    k = jnp.arange(m)
    diag_mask = (k[None, :] >= ws[:, None]) & (k[None, :] < w)
    f = f.at[:, k * m + k].add(diag_mask.astype(dt))
    if a_src.shape[0]:
        f2 = None
        if use_pallas:
            from superlu_dist_tpu.numeric.pallas_kernels import (
                assemble_avals_pallas)
            f2 = assemble_avals_pallas(f, avals, a_slot, a_flat, a_src,
                                       mode=pallas)
        if f2 is not None:
            f = f2
        else:
            vals = avals.at[a_src].get(mode="fill", fill_value=0)
            f = f.at[(a_slot, a_flat)].add(vals, mode="drop")
    if isinstance(children, tuple):
        # stacked child tables (mega executor): scan the shared per-set
        # extend-add — the sets fold into f in the same sequence the
        # Python loop below runs them, so the factors stay bitwise equal
        # (the per-set ub is TRACED here, so this branch keeps the .at[]
        # lowering under every pallas mode)
        c_off, c_slot, c_ub, c_rel = children
        if c_off.shape[0]:
            def body(fc, xs):
                co, cs, ub, r = xs
                return extend_add_set(fc, pool, m, ub, co, cs, r), None
            f, _ = jax.lax.scan(body, f, (c_off, c_slot, c_ub, c_rel))
    else:
        for (ub, child_off, child_slot, rel) in children:
            f2 = None
            if use_pallas:
                from superlu_dist_tpu.numeric.pallas_kernels import (
                    extend_add_set_pallas)
                f2 = extend_add_set_pallas(f, pool, m, ub, child_off,
                                           child_slot, rel, mode=pallas)
            if f2 is not None:
                f = f2
            else:
                f = extend_add_set(f, pool, m, ub, child_off, child_slot,
                                   rel)
    f = f.reshape(batch, m, m)
    if front_sharding is not None:
        f = wsc(f, front_sharding)
    lpanel, upanel, schur, counts = group_partial_factor(
        f, thresh, w, front_sharding=front_sharding,
        pivot_sharding=pivot_sharding, pivot=pivot, gemm_prec=gemm_prec)
    # counts is (batch, w) per-column tiny flags; identity-padding columns
    # (col >= ws, incl. whole padded batch slots with ws == 0) are unit
    # pivots — don't let a thresh > 1 count them as tiny
    tiny = jnp.sum(jnp.where(jnp.arange(w)[None, :] < ws[:, None], counts, 0))
    if u > 0:
        vals = schur.reshape(batch, u * u)
        if replicated is not None:
            vals = wsc(vals, replicated)
        if not write_back:
            return (lpanel, upanel), vals, tiny
        dst = off[:, None] + jnp.arange(u * u)         # off==pool_size drops
        pool = pool.at[dst].set(vals, mode="drop")
    elif not write_back:
        return (lpanel, upanel), None, tiny
    return (lpanel, upanel), pool, tiny


def pool_spec(mesh, pool_partition: bool):
    """The Schur pool's sharding: replicated, or 1-D over ALL mesh devices
    (pool_partition — per-chip pool memory divides by the device count).
    Single definition shared by both executors; returns None without a
    mesh."""
    if mesh is None:
        return None
    from jax.sharding import NamedSharding, PartitionSpec as P
    return NamedSharding(
        mesh, P(tuple(mesh.axis_names)) if pool_partition else P(None))


def _group_arrays(grp):
    children = [(cs.ub, jnp.asarray(cs.child_off), jnp.asarray(cs.child_slot),
                 jnp.asarray(cs.rel)) for cs in grp.children]
    return (jnp.asarray(grp.a_slot), jnp.asarray(grp.a_flat),
            jnp.asarray(grp.a_src), jnp.asarray(grp.ws),
            jnp.asarray(grp.off), children)


@dataclasses.dataclass
class NumericFactorization:
    """LU factors as packed front batches (the dLUstruct_t analog,
    superlu_ddefs.h:186-191)."""

    plan: FactorPlan
    fronts: list              # per group: (lpanel (B,M,w), upanel (B,w,u))
                              # — packed L (diag block over L21) and U12;
                              # the eliminated A22 is never stored (its
                              # Schur update lives transiently in the pool)
    tiny_pivots: int
    dtype: object
    finite: bool = True       # False => an exact zero pivot propagated
                              # (only possible with replace_tiny=False)
    info_col: int = -1        # first zero-pivot column (0-based, final
                              # labeling) when not finite — the reference's
                              # info>0 = first i with U(i,i)==0
                              # (pdgstrf.c:1920-1924, Allreduce MIN)
    host_fronts: list = None  # lazily pulled numpy copies for the host solve
    resumed_groups: int = 0   # dispatch groups restored from a durable
                              # checkpoint frontier instead of recomputed
                              # (persist/checkpoint.py; 0 = fresh run)
    gemm_prec: str = "highest"  # GEMM-precision ladder tier the Schur
                              # updates ran at (ops/dense.gemm_precision)
                              # — recorded so the BERR gate / escalation
                              # rung and the SolveReport can name the
                              # tier the delivered answer rests on

    @property
    def on_host(self) -> bool:
        """True when the factors ALL live in host memory (the executor
        streamed them off-device — offload mode — or we run on the CPU
        backend).  A host-share split (stream.py SLU_TPU_HOST_FLOPS)
        leaves only the leading leaf panels as numpy — that is a
        device-resident factorization and must keep the device solve."""
        return bool(self.fronts) and all(
            isinstance(lp, np.ndarray) for lp, _ in self.fronts)

    def pull_to_host(self):
        """Transfer factors to host once (the dSolveInit analog,
        SRC/pdutil.c:690 — solve-side setup cached across solves)."""
        if self.host_fronts is None:
            self.host_fronts = [(np.asarray(lp), np.asarray(up))
                                for lp, up in self.fronts]
        return self.host_fronts


def make_factor_fn(plan: FactorPlan, dtype="float64", mesh=None,
                   pool_partition: bool = False, gemm_prec=None,
                   pallas=None):
    """Build the whole numeric factorization as ONE jittable function.

    Returns fn(avals, thresh) -> (fronts_tuple, tiny_count).  The plan's
    index maps are passed as PROGRAM ARGUMENTS (latched on the returned
    wrapper), not closed over: a closure-captured device array becomes a
    CONSTANT of the jaxpr, so the compiled program identifies the matrix
    — the per-matrix-capture pattern slulint SLU112 polices, which
    defeats cross-matrix program reuse and duplicates the maps into the
    executable.  If `mesh` is a jax.sharding.Mesh with axes ("snode", "panel"),
    the dense factor math is sharded batch-over-"snode" and
    columns-over-"panel" — the 2D block-cyclic layout analog (SURVEY.md
    §2.4) — while every irregular scatter/gather is pinned replicated
    (XLA's SPMD partitioner miscompiles scatter/gather with sharded minor
    dims, jax 0.9.0; they are bandwidth-trivial next to the GEMMs).

    pool_partition=True shards the Schur update pool itself across ALL
    mesh devices (1-D, so the partitioner handles it — verified equal to
    the replicated result on a virtual mesh).  This divides the pool's
    HBM footprint by the device count — the path to the n≈1M problem
    class, whose ~27 GB pool exceeds one chip (the reference's analog:
    no rank holds the whole factor, SURVEY.md §5 scaling) — at the cost
    of extra collectives per extend-add.
    """
    dtype = jnp.dtype(dtype)
    plan.check_index_width()
    sharding = pivot_sharding = replicated = None
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        sharding = NamedSharding(mesh, P("snode", None, "panel"))
        pivot_sharding = NamedSharding(mesh, P("snode", None, None))
        pool_sharding = pool_spec(mesh, pool_partition)
        replicated = NamedSharding(mesh, P(None, None))
    arrays = [_group_arrays(grp) for grp in plan.groups]
    # flatten the index maps into one static-layout argument list: the
    # per-group child counts (ubs) are program STRUCTURE, the arrays are
    # program INPUTS — so the jaxpr carries no per-matrix constants
    # (slulint SLU112) and dead-input/donation accounting sees them
    flat_args = []
    child_meta = []
    for (a_slot, a_flat, a_src, ws, off, children) in arrays:
        flat_args.extend((a_slot, a_flat, a_src, ws, off))
        child_meta.append(tuple(ub for ub, _, _, _ in children))
        for (_, child_off, child_slot, rel) in children:
            flat_args.extend((child_off, child_slot, rel))
    flat_args = tuple(flat_args)
    # SLU_TPU_PIVOT_KERNEL / SLU_TPU_GEMM_PREC / SLU_TPU_PALLAS resolved
    # HERE, in the uncached factory, and closed over as constants —
    # get_executor keys the fused executor on them, and the traced body
    # must not read env (slulint SLU102/SLU105).  Mesh runs no longer
    # pin Pallas off: the resolved mode rides through (auto still means
    # off on CPU backends, interpret/on must be asked for explicitly).
    from superlu_dist_tpu.numeric.pallas_kernels import pallas_mode
    from superlu_dist_tpu.ops.dense import gemm_precision, pivot_kernel
    pivot = pivot_kernel()
    gemm_prec = gemm_precision(gemm_prec)
    pallas = pallas_mode(pallas)

    def fn(avals, thresh, *flat):
        avals = avals.astype(dtype)
        pool = jnp.zeros(plan.pool_size, dtype=dtype)
        if mesh is not None:
            pool = jax.lax.with_sharding_constraint(pool, pool_sharding)
        fronts = []
        tiny = jnp.zeros((), jnp.int32)
        i = 0
        for grp, ubs in zip(plan.groups, child_meta):
            a_slot, a_flat, a_src, ws, off = flat[i:i + 5]
            i += 5
            children = []
            for ub in ubs:
                children.append((ub, flat[i], flat[i + 1], flat[i + 2]))
                i += 3
            packed, pool, t = group_step(
                (grp.batch, grp.m, grp.w, grp.u), avals, pool, thresh,
                a_slot, a_flat, a_src, ws, off, children,
                front_sharding=sharding, pivot_sharding=pivot_sharding,
                replicated=replicated, pivot=pivot, gemm_prec=gemm_prec,
                pallas=pallas)
            if mesh is not None:
                pool = jax.lax.with_sharding_constraint(pool, pool_sharding)
            fronts.append(packed)
            tiny = tiny + t
        return tuple(fronts), tiny

    jfn = jax.jit(fn)
    # the fused path keeps real batch sizes (no pow-2 pad); shape padding
    # is already inside _front_flops' padded (w, u) dims
    from superlu_dist_tpu.symbolic.symbfact import _front_flops
    executed = float(sum(g.batch * _front_flops(g.w, g.u)
                         for g in plan.groups))

    built = []

    def traced(avals, thresh):
        """Kernel-shape telemetry for the one-program executor: the whole
        factorization is a single dispatch, so it records one issue span
        plus one aggregate kernel span (blocking only when a profiling
        tracer is on — the warm disabled path returns the async jitted
        call untouched).  The FIRST call additionally lands in the
        compile census: jit compiles synchronously inside it, so its
        wall time IS the build cost of the fused program."""
        tracer = get_tracer()
        cold = not built
        if not (tracer.enabled or cold):
            return jfn(avals, thresh, *flat_args)
        import time

        from superlu_dist_tpu.obs.compilestats import COMPILE_STATS
        if cold:
            # program audit (SLU_TPU_VERIFY_PROGRAMS=1): one abstract
            # trace before the program first runs — no dead args (the
            # caller may retain avals; the maps live on the executor)
            from superlu_dist_tpu.utils.programaudit import maybe_audit
            maybe_audit(
                "make_factor_fn",
                f"fused g{len(plan.groups)} {str(dtype)} {gemm_prec}", jfn,
                (avals, thresh, *flat_args),
                mesh_axes=tuple(mesh.axis_names) if mesh is not None
                else ())
        t0 = time.perf_counter()
        out = jfn(avals, thresh, *flat_args)
        t_issue = time.perf_counter() - t0
        if cold:
            built.append(True)
            # same label the audit notes use (gemm_prec included), so the
            # census join that attaches peak_bytes_est to this row holds
            COMPILE_STATS.record(
                "make_factor_fn",
                f"fused g{len(plan.groups)} {str(dtype)} {gemm_prec}",
                t0, t_issue, n_args=2)
        if not tracer.enabled:
            return out
        tracer.complete("issue fused", "dispatch", t0, t_issue,
                        groups=len(plan.groups))
        if tracer.profiling:
            jax.block_until_ready(out[0])
            tracer.complete("factor-fused", "kernel", t0,
                            time.perf_counter() - t0,
                            n_groups=len(plan.groups), aggregate=True,
                            executed_flops=executed,
                            structural_flops=float(plan.flops),
                            padding=round(executed / max(float(plan.flops),
                                                         1.0), 4))
        return out

    return traced


def get_executor(plan: FactorPlan, dtype="float64", executor: str = "auto",
                 mesh=None, pool_partition: bool = False, gemm_prec=None):
    """Executor for a plan, cached on the plan (SamePattern reuse tier).

    executor: "fused" (one XLA program — fast dispatch, compile grows with
    plan size), "stream" (per-bucket kernels — compile count is bounded,
    right for real TPU where program compile is expensive), "mega"
    (bucketed shape-closed programs, O(1) compile count), "spmd" (the
    shard_map tier, parallel/spmd.py: ONE compiled program per factor
    with the collectives as in-program ops), or "auto".  Auto picks
    spmd on a single-process mesh (unless SLU_TPU_SPMD=0 or the pool is
    partitioned), stream on multi-process meshes and accelerators, and
    fused on single-controller CPU.  A mesh spanning processes keeps
    stream for the same reason real TPU does: the fused whole-program
    jit's compile time grows with the plan (an n≈1e5 SPMD program took
    >60 min on XLA:CPU), while the streamed kernels' compile count is
    bounded by distinct shape keys.  mesh shards every executor over
    ("snode", "panel"); pool_partition shards the Schur pool across all
    mesh devices (see make_factor_fn).
    """
    if executor not in ("auto", "fused", "stream", "mega", "spmd"):
        raise ValueError(f"executor must be auto|fused|stream|mega|spmd, "
                         f"got {executor!r}")
    multiproc = mesh is not None and jax.process_count() > 1
    if executor == "auto":
        from superlu_dist_tpu.parallel.spmd import spmd_mode
        if (mesh is not None and not multiproc and not pool_partition
                and spmd_mode()):
            executor = "spmd"
        else:
            executor = ("fused" if jax.default_backend() == "cpu"
                        and not multiproc else "stream")
    if executor == "spmd" and (mesh is None or multiproc or pool_partition):
        # the shard_map tier is single-controller over a local mesh and
        # replays the full-order pool on every device (its bitwise
        # contract) — no mesh, a multi-process mesh, or a partitioned
        # pool keep the streamed GSPMD kernels
        executor = "stream"
    cache = getattr(plan, "_factor_fns", None)
    if cache is None:
        cache = plan._factor_fns = {}
    from superlu_dist_tpu.numeric.pallas_kernels import pallas_mode
    from superlu_dist_tpu.ops.dense import gemm_precision, pivot_kernel
    from superlu_dist_tpu.utils.options import env_float
    # every executor bakes the GEMM-precision tier and the Pallas mode
    # into its compiled programs, so both are part of its identity (the
    # escalation rung's refactor-at-the-next-tier relies on getting a
    # FRESH executor); the fused executor additionally bakes the
    # pivot-kernel choice, which StreamExecutor re-reads per call
    # (stream._kernel / _level_fns key on it)
    gemm_prec = gemm_precision(gemm_prec)
    pallas = pallas_mode()
    key = (str(jnp.dtype(dtype)), executor, mesh, bool(pool_partition),
           gemm_prec, pallas,
           pivot_kernel() if executor == "fused" else None,
           # StreamExecutor latches the host-share threshold at
           # construction — a changed SLU_TPU_HOST_FLOPS needs a new one
           env_float("SLU_TPU_HOST_FLOPS")
           if executor == "stream" else None)
    fn = cache.get(key)
    if fn is None:
        if executor == "stream":
            from superlu_dist_tpu.numeric.stream import StreamExecutor
            fn = StreamExecutor(plan, dtype, mesh=mesh,
                                pool_partition=pool_partition,
                                gemm_prec=gemm_prec, pallas=pallas)
        elif executor == "mega":
            from superlu_dist_tpu.numeric.mega import MegaExecutor
            fn = MegaExecutor(plan, dtype, mesh=mesh,
                              pool_partition=pool_partition,
                              gemm_prec=gemm_prec, pallas=pallas)
        elif executor == "spmd":
            from superlu_dist_tpu.parallel.spmd import SpmdFactorExecutor
            fn = SpmdFactorExecutor(plan, dtype, mesh,
                                    gemm_prec=gemm_prec, pallas=pallas)
        else:
            fn = make_factor_fn(plan, dtype, mesh=mesh,
                                pool_partition=pool_partition,
                                gemm_prec=gemm_prec, pallas=pallas)
        cache[key] = fn
    return fn


def numeric_factorize(plan: FactorPlan, pattern_values: np.ndarray,
                      anorm: float, dtype="float64",
                      replace_tiny: bool = True,
                      executor: str = "auto",
                      mesh=None,
                      pool_partition: bool = False,
                      check_finite: bool = True,
                      ckpt_dir: str | None = None,
                      ckpt_every: int = 0,
                      resume_from: str | None = None,
                      deadline=None,
                      gemm_prec: str | None = None) -> NumericFactorization:
    """Factor with values aligned to plan.pattern_indices.

    anorm: ‖A‖ for the GESP tiny-pivot threshold sqrt(eps)·‖A‖
    (reference pdgstrf2.c:218: thresh = eps·‖A‖; we use the sqrt variant of
    ReplaceTinyPivot so f32 factors retain half their digits).
    With replace_tiny=False an exact zero pivot propagates inf/nan; the
    result is flagged non-finite (the reference's info>0 singularity path,
    pdgstrf.c:234-241).

    check_finite arms the non-finite sentinel: with ReplaceTinyPivot
    active a NaN/Inf in the factors means overflow or NaN input (never
    expected singularity), so the cheap isfinite reductions below trip a
    structured NumericBreakdownError naming the offending supernode
    instead of letting NaN propagate through every later front.

    Crash consistency (persist/, docs/RELIABILITY.md): ``ckpt_every`` /
    ``ckpt_dir`` arm a FactorCheckpointer flushing the completed-group
    frontier every K groups (and on breakdown/deadline/SIGTERM);
    ``resume_from`` loads a checkpoint, verifies its plan fingerprint
    AND value digest against THIS call's inputs, and restarts the
    stream from the durable frontier — bitwise-identical factors to an
    uninterrupted run.  ``deadline`` is a utils.deadline.Deadline
    polled between dispatch groups.  Checkpointing/resume have group
    boundaries only on the streamed executor, so arming them forces
    ``executor="stream"``.
    """
    dtype = jnp.dtype(dtype)
    real_dtype = jnp.dtype(dtype).type(0).real.dtype
    eps = jnp.finfo(real_dtype).eps
    # GEMM-precision ladder tier (ops/dense.gemm_precision): resolved
    # ONCE here so the executor, the checkpoint identity and the result
    # record all agree on the arithmetic this factorization ran
    from superlu_dist_tpu.ops.dense import gemm_precision
    gemm_prec = gemm_precision(gemm_prec)
    tracer = get_tracer()
    if tracer.enabled:
        # schedule telemetry span: what the dispatch stream below is
        # shaped like (groups before/after aggregation, occupancy,
        # padding, critical path) — the same block Stats.report prints
        import time
        tracer.complete("schedule", "phase", time.perf_counter(), 0.0,
                        **plan.schedule_stats(itemsize=dtype.itemsize))
    thresh = jnp.asarray(
        np.sqrt(float(eps)) * max(anorm, 1e-300) if replace_tiny else 0.0,
        dtype=real_dtype)
    # failure-domain chaos injection (testing/chaos.py, SLU_TPU_CHAOS):
    # the NaN poke rewrites the values BEFORE the checkpointer latches
    # its value digest, so a frontier computed from poisoned values can
    # never be resumed against clean ones
    from superlu_dist_tpu.testing.chaos import get_chaos
    chaos = get_chaos()
    if chaos is not None:
        pattern_values = chaos.poke_nan(plan, pattern_values)
    ckpt = None
    want_ckpt = bool(ckpt_dir) or ckpt_every > 0
    if want_ckpt or resume_from:
        # checkpoints need per-group boundaries: the streamed and mega
        # executors have them, the fused and spmd whole-program jits
        # do not
        if executor in ("auto", "fused", "spmd"):
            executor = "stream"
    if want_ckpt:
        from superlu_dist_tpu.persist.checkpoint import FactorCheckpointer
        # the GEMM tier is part of the frontier's numeric identity: a
        # bf16 frontier spliced under highest arithmetic would silently
        # break the bitwise-resume guarantee
        ckpt = FactorCheckpointer(ckpt_dir or ".slu_ckpt", plan,
                                  pattern_values, thresh, dtype,
                                  every=int(ckpt_every),
                                  gemm_prec=gemm_prec)
    resume = None
    if resume_from:
        from superlu_dist_tpu.persist.checkpoint import load_checkpoint
        resume = load_checkpoint(resume_from, plan=plan,
                                 pattern_values=pattern_values,
                                 thresh=thresh, dtype=dtype,
                                 gemm_prec=gemm_prec)
    avals = jnp.asarray(pattern_values, dtype=dtype)
    fn = get_executor(plan, dtype, executor, mesh=mesh,
                      pool_partition=pool_partition, gemm_prec=gemm_prec)
    if hasattr(fn, "check_finite"):
        # streamed executor: also sentinel each offloaded group as it
        # lands on the host (early abort — see stream._emit_front),
        # plus the crash-consistency hooks (one-shot resume state)
        fn.check_finite = bool(check_finite and replace_tiny)
        fn.checkpoint = ckpt
        fn.resume = resume
        fn.deadline = deadline
        fn.chaos = chaos
    elif deadline is not None:
        # fused executor: one dispatch, so the only boundaries are
        # before/after the whole program
        deadline.poll(where="fused factorization")
    try:
        fronts_out, tiny_total = fn(avals, thresh)
    except BaseException:
        if ckpt is not None:
            # keep the flushed frontier on disk but deregister — a later
            # factorization's SIGTERM flush must not resurrect stale refs
            ckpt.complete(cleanup=False)
        raise
    finally:
        if hasattr(fn, "check_finite"):
            # the hooks are per-call state; a reused executor must not
            # carry them into the next factorization
            fn.checkpoint = fn.resume = fn.deadline = fn.chaos = None
    fronts_out = list(fronts_out)
    finite = True
    info_col = -1
    if not replace_tiny:
        finite, info_col = localize_singularity(plan, fronts_out)
    elif check_finite and not fronts_finite(fronts_out):
        from superlu_dist_tpu.utils.errors import NumericBreakdownError
        sn, col = localize_nonfinite(plan, fronts_out)
        ck_path = None
        if ckpt is not None:
            ck_path = ckpt.flush_latest("numeric-breakdown")
            ckpt.complete(cleanup=False)
        err = NumericBreakdownError(supernode=sn, col=col,
                                    where="numeric factorization")
        err.checkpoint_path = ck_path
        raise err
    if ckpt is not None:
        # completed: the durable artifact of a finished factorization is
        # the saved handle (persist.save_lu), not a stale frontier
        ckpt.complete(cleanup=True)
    return NumericFactorization(plan=plan, fronts=fronts_out,
                                tiny_pivots=int(tiny_total), dtype=dtype,
                                finite=finite, info_col=info_col,
                                resumed_groups=(resume.k if resume is not None
                                                else 0),
                                gemm_prec=gemm_prec)


def fronts_finite(fronts) -> bool:
    """Cheap isfinite sentinel over factored panels: one all-reduce per
    group, device-resident panels reduced device-side (a few scalar
    transfers — O(panel bytes) reads, trivial next to the factorization's
    O(n·w²) flops)."""
    flags = []
    for lp, up in fronts:
        if isinstance(lp, np.ndarray):
            if not (np.isfinite(lp).all() and np.isfinite(up).all()):
                return False
        else:
            flags.append(jnp.isfinite(lp).all() & jnp.isfinite(up).all())
    if flags:
        return bool(np.all(jax.device_get(flags)))
    return True


def localize_nonfinite(plan: FactorPlan, fronts):
    """Earliest contaminated supernode over all fronts: returns
    (supernode, first global column), or (-1, -1) if everything is finite.
    The localization mirrors localize_singularity's per-SLOT attribution —
    an unrelated subtree batched in the same group must not be blamed."""
    sn_start = plan.sf.sn_start
    best_sn, best_col = -1, -1
    for grp, (lp, up) in zip(plan.groups, fronts):
        lph = np.asarray(lp)
        nf = ~np.isfinite(lph.reshape(lph.shape[0], -1)).all(axis=1)
        nf |= ~np.isfinite(np.asarray(up).reshape(
            lph.shape[0], -1)).all(axis=1)
        if nf.any():
            sns = np.asarray(grp.sns)[np.nonzero(nf)[0]]
            sn = int(sns[np.argmin(sn_start[sns])])
            col = int(sn_start[sn])
            if best_col < 0 or col < best_col:
                best_sn, best_col = sn, col
    return best_sn, best_col


def localize_singularity(plan: FactorPlan, fronts):
    """Zero-pivot detection + localization over factored fronts.

    A zero or non-finite U diagonal in a real (non-padding) column; the
    earliest such global column is the reference's info>0
    first-zero-pivot index (pdgstrf.c:1920-1924).  A zero pivot in the
    LAST column of a front divides nothing during factorization, so an
    isfinite scan alone would miss it.  Returns (finite, info_col)."""
    bad_cols = []
    sn_start = plan.sf.sn_start
    for grp, (lp, up) in zip(plan.groups, fronts):
        lph = np.asarray(lp)
        diag = np.diagonal(lph[:, :grp.w, :grp.w], axis1=1, axis2=2)
        bad = (diag == 0) | ~np.isfinite(diag)
        bad &= np.arange(grp.w)[None, :] < np.asarray(grp.ws)[:, None]
        if bad.any():
            slots, cols = np.nonzero(bad)
            bad_cols.append(int((sn_start[grp.sns[slots]] + cols).min()))
        else:
            # off-diagonal-only contamination: attribute per SLOT, not
            # per group — an unrelated subtree batched in the same
            # group must not shift min(bad_cols) below the true pivot
            # (contamination only flows to ancestors, whose columns
            # are larger than the zero pivot's)
            nf = ~np.isfinite(lph.reshape(lph.shape[0], -1)).all(axis=1)
            nf |= ~np.isfinite(np.asarray(up).reshape(
                lph.shape[0], -1)).all(axis=1)
            if nf.any():
                bad_cols.append(int(sn_start[grp.sns[nf]].min()))
    if bad_cols:
        return False, min(bad_cols)
    return True, -1


def factor_flops(plan: FactorPlan) -> float:
    """Flop count for stats (the ops[FACT] analog, SRC/util.c:513)."""
    return plan.flops


def query_space(numeric: NumericFactorization) -> dict:
    """Memory held by the factorization — the dQuerySpace_dist analog
    (SRC/dmemory_dist.c:73): packed-front (L+U) bytes plus the transient
    Schur update pool (the reference's 'expansions'/buffer gauges)."""
    itemsize = np.dtype(numeric.dtype).itemsize
    front_b = sum(int(np.prod(lp.shape)) + int(np.prod(up.shape))
                  for lp, up in numeric.fronts) * itemsize
    pool_b = int(numeric.plan.pool_size) * itemsize
    return {"for_lu_bytes": front_b, "pool_bytes": pool_b,
            "total_bytes": front_b + pool_b}
