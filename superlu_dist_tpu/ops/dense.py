"""Dense supernodal kernels — the TPU offload boundary.

This layer replaces the reference's BLAS seam (CBLAS fallback / vendor BLAS
/ cuBLAS, SURVEY.md L1): the panel factorization dger/dtrsm loop
(pdgstrf2_trsm, SRC/pdgstrf2.c:140-318), the U-row triangular solves
(pdgstrs2_omp, :771), and the Schur-complement GEMM
(dSchCompUdt-2Ddynamic.c:566) all become one *batched partial factorization
of padded dense fronts*, vmapped over a level's worth of supernodes and
compiled by XLA onto the MXU.

Everything is static-shape: fronts are padded to bucket sizes (M total, W
pivot columns), with identity columns in the pivot-block padding so the
unpivoted LU passes through them untouched.  Tiny pivots are replaced by
±sqrt(eps)·‖A‖ exactly like the reference's GESP (pdgstrf2.c:218-232,
option ReplaceTinyPivot), and counted.

Layout of a factored front F (M×M, pivot width W, real sizes w ≤ W,
u ≤ M−W):
    F[:W, :W]   packed LU of the diagonal block (unit-lower L11 + U11)
    F[W:, :W]   L21 = A21·U11⁻¹   (real data in rows W..W+u)
    F[:W, W:]   U12 = L11⁻¹·A12
    F[W:, W:]   Schur complement S = A22 − L21·U12 (scattered to the pool)
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.scipy.linalg import solve_triangular

_UNROLL = 16   # panel width factored by the unrolled column loop

# MXU pass count for the f32 Schur GEMMs: HIGHEST = 6-pass bf16 (full f32
# products, ~1/6 of bf16 peak), HIGH = 3-pass (~f32-mantissa-19), DEFAULT =
# single-pass bf16.  f32 factors feed f64 iterative refinement, which
# tolerates reduced factor precision at the cost of extra IR sweeps — the
# HIGH tier doubles the MXU flop ceiling and is worth sweeping on hardware
# (SLU_TPU_PRECISION=high bench run).
_PRECISION_TIERS = {"default": lax.Precision.DEFAULT,
                    "high": lax.Precision.HIGH,
                    "highest": lax.Precision.HIGHEST}


@functools.lru_cache(maxsize=None)
def _precision():
    """Resolved lazily at first kernel build (not import) so a typo'd env
    var fails the matmul path with a pointed error instead of making the
    whole package unimportable for host-only work."""
    name = os.environ.get("SLU_TPU_PRECISION", "highest").strip().lower()
    if name not in _PRECISION_TIERS:
        raise ValueError(f"SLU_TPU_PRECISION={name!r} — expected one of "
                         f"{sorted(_PRECISION_TIERS)}")
    return _PRECISION_TIERS[name]


def _fix_pivot(piv, thresh):
    """GESP tiny-pivot replacement: piv -> phase(piv)·thresh if |piv|<thresh."""
    ap = jnp.abs(piv)
    safe = jnp.where(ap == 0, jnp.ones_like(ap), ap)
    unit = jnp.where(ap == 0, jnp.ones_like(piv), piv / safe.astype(piv.dtype))
    tiny = ap < thresh
    return jnp.where(tiny, unit * thresh.astype(piv.dtype), piv), tiny.astype(jnp.int32)


def _lu_masked(a, thresh):
    """Unpivoted LU of a small block — scatter-free masked formulation.

    Each step is masked selects + a full-matrix rank-1 update + `where`
    masks: no scatter/dynamic-update ops at all.  That matters twice on
    TPU: (a) masked dense updates vectorize on the VPU where scatters
    serialize, and (b) XLA's SPMD partitioner miscompiles vmapped
    scatter-updates whose minor dim gets sharded (observed jax 0.9.0), so
    the factorization core must stay scatter-free to be mesh-shardable.
    The ~3× extra flops of full-width updates are negligible next to the
    Schur GEMMs.

    Row/column/pivot extraction uses elementwise masked reductions rather
    than one-hot dot products: a dot_general here would route through the
    MXU at default precision (bf16 inputs on TPU), truncating the pivot row
    and the pivot value itself every elimination step.

    Returns (packed LU, tiny: (k,) int32 per-column tiny-pivot flags) —
    per-column so callers can mask out identity-padding columns.
    """
    k = a.shape[0]
    idx = jnp.arange(k)

    def step(i, carry):
        a, flags = carry
        sel = idx == i
        e = sel.astype(a.dtype)
        row_i = jnp.sum(a * e[:, None], axis=0)    # row i
        col_i = jnp.sum(a * e[None, :], axis=1)    # column i
        piv_raw = jnp.sum(row_i * e)
        piv, tiny = _fix_pivot(piv_raw, thresh)
        below = (idx > i)
        l = jnp.where(below, col_i / piv, jnp.zeros_like(col_i))
        u = jnp.where(below, row_i, jnp.zeros_like(row_i))   # cols > i
        a = a - l[:, None] * u[None, :]
        # write multipliers + fixed pivot into column i
        new_col = jnp.where(below, l, col_i) + (piv - piv_raw) * e
        cur_col = jnp.sum(a * e[None, :], axis=1)
        a = a + (new_col - cur_col)[:, None] * e[None, :]
        return a, flags + tiny * sel.astype(jnp.int32)

    return jax.lax.fori_loop(0, k, step, (a, jnp.zeros(k, jnp.int32)))


def lu_nopivot(a, thresh):
    """Blocked-recursive unpivoted LU with tiny-pivot replacement.

    Static shapes throughout; the trailing update is a single GEMM per
    recursion level, which is where XLA maps onto the MXU.

    Returns (packed LU, tiny: (n,) int32 per-column tiny-pivot flags).
    """
    n = a.shape[0]
    if n <= _UNROLL:
        return _lu_masked(a, thresh)
    h = max(_UNROLL, (n // 2 + _UNROLL - 1) // _UNROLL * _UNROLL)
    h = min(h, n - 1)
    a11, a12 = a[:h, :h], a[:h, h:]
    a21, a22 = a[h:, :h], a[h:, h:]
    f11, c1 = lu_nopivot(a11, thresh)
    u12 = solve_triangular(f11, a12, lower=True, unit_diagonal=True)
    l21 = solve_triangular(f11, a21.T, trans=1, lower=False).T
    s = a22 - jnp.matmul(l21, u12, precision=_precision())
    f22, c2 = lu_nopivot(s, thresh)
    top = jnp.concatenate([f11, u12], axis=1)
    bot = jnp.concatenate([l21, f22], axis=1)
    return jnp.concatenate([top, bot], axis=0), jnp.concatenate([c1, c2])


def partial_front_factor(f, thresh, w):
    """Factor the leading w columns of one front; see module docstring."""
    m = f.shape[0]
    f11, count = lu_nopivot(f[:w, :w], thresh)
    if w == m:
        return f11, count
    u12 = solve_triangular(f11, f[:w, w:], lower=True, unit_diagonal=True)
    l21 = solve_triangular(f11, f[w:, :w].T, trans=1, lower=False).T
    s = f[w:, w:] - jnp.matmul(l21, u12, precision=_precision())
    top = jnp.concatenate([f11, u12], axis=1)
    bot = jnp.concatenate([l21, s], axis=1)
    return jnp.concatenate([top, bot], axis=0), count


def group_partial_factor(fronts, thresh, w, front_sharding=None,
                         pivot_sharding=None):
    """Partial factorization of a batch of fronts with explicit shardings.

    Group-level formulation of partial_front_factor: the pivot-block LU is
    latency-bound (unrolled column loop) and runs replicated along the
    "panel" mesh axis (pivot_sharding), while the trailing triangular
    solves and the Schur GEMM — where the flops are (reference
    dSchCompUdt-2Ddynamic.c:566) — are pure batched matmuls that partition
    cleanly over the 2D mesh (front_sharding).  Note: the scatter-style
    pivot loop must NOT be sharded along its last dim — XLA's SPMD
    partitioner miscompiles vmapped scatter-updates with a sharded minor
    dimension (observed on jax 0.9.0), and splitting a tiny LU across
    chips would be latency-dominated anyway.

    Returns (lpanel (B,m,w), upanel (B,w,u), schur (B,u,u), tiny (B,w)).
    lpanel stacks the packed diagonal block (L11 unit-lower + U11) over
    L21; upanel is U12.  The Schur block is returned separately — the
    caller scatters it into the update pool and then drops it, so the
    stored factors are only the n_L + n_U panels the solves read (the
    reference likewise keeps L in Lnzval_bc_ptr and U in Unzval_br_ptr and
    never stores the eliminated A22, superlu_ddefs.h:97-183).
    """
    from jax.lax import with_sharding_constraint as wsc
    m = fronts.shape[-1]
    b = fronts.shape[0]
    f11_in = fronts[:, :w, :w]
    if pivot_sharding is not None:
        f11_in = wsc(f11_in, pivot_sharding)
    f11, tiny = jax.vmap(lambda x: lu_nopivot(x, thresh))(f11_in)
    if w == m:
        if pivot_sharding is not None:
            f11 = wsc(f11, pivot_sharding)
        u = 0
        return f11, jnp.zeros((b, w, u), fronts.dtype), \
            jnp.zeros((b, u, u), fronts.dtype), tiny
    a12 = fronts[:, :w, w:]
    a21 = fronts[:, w:, :w]
    a22 = fronts[:, w:, w:]
    u12 = jax.vmap(lambda l, b_: solve_triangular(l, b_, lower=True,
                                                  unit_diagonal=True))(f11, a12)
    l21 = jax.vmap(lambda u_, b_: solve_triangular(u_, b_.T, trans=1,
                                                   lower=False).T)(f11, a21)
    s = a22 - jnp.matmul(l21, u12, precision=_precision())
    if front_sharding is not None:
        s = wsc(s, front_sharding)
    lpanel = jnp.concatenate([f11, l21], axis=1)
    if front_sharding is not None:
        lpanel = wsc(lpanel, front_sharding)
    return lpanel, u12, s, tiny


@functools.lru_cache(maxsize=None)
def make_front_kernel(m: int, w: int, dtype: str):
    """Jitted batched front factorization for bucket shape (M=m, W=w).

    Returns fn(F: (B, m, m), thresh) -> (F_packed: (B, m, m), tiny: int32).
    Cached per (m, w, dtype); batch size participates in jit's own cache.
    """

    def kernel(fronts, thresh):
        outs, counts = jax.vmap(lambda f: partial_front_factor(f, thresh, w))(fronts)
        return outs, jnp.sum(counts)

    return jax.jit(kernel)
