#!/usr/bin/env python
"""Perf-regression CI gate: micro-bench vs the bench-history baseline.

Runs the bench at a small, CI-affordable size (``PERF_GATE_NX``,
default 8 → n=512, CPU backend, ~seconds warm) and compares its factor
GFLOP/s against the MEDIAN of prior same-configuration rows in the
bench-history DB (scripts/bench_history.py).  Noise-tolerant by design:

* SELF-SEEDING — with no (or too few, < ``PERF_GATE_MIN_SAMPLES``)
  comparable history rows the gate appends the fresh row and passes, so
  the first CI run on a new machine is green and every later run has a
  baseline;
* the failure threshold is ``value < (1 - PERF_GATE_TOL) * median``
  (default tol 0.5 — CI machines are noisy; a real regression from a
  bad change is far larger than scheduler jitter);
* a failing row is still appended, flagged ``gate_fail`` so it never
  poisons the baseline median;
* compile-time creep is reported (WARN) when ``compile_seconds``
  exceeds (1 + 2·tol)·median, but does not fail the gate — cold/warm
  cache state legitimately swings it.

Usage:  check_perf_regress.py [--row FILE] [--history PATH]
  --row      compare an existing bench JSON row instead of running the
             micro-bench (used by the tests; FILE may be '-')
  --history  override the DB path (default: SLU_TPU_BENCH_HISTORY or
             .cache/bench_history.jsonl)

Gate contract (scripts/ci_gates.sh): exit 0 = pass/seeded, exit 1 =
regression or no measurement, diagnostics on stdout/stderr, runs under
the shared per-gate timeout.
"""

import json
import os
import statistics
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from superlu_dist_tpu.utils.options import env_float, env_int  # noqa: E402
from bench_history import (                                    # noqa: E402
    append_row, history_path, load_history, row_key)

#: history rows consulted for the baseline (most recent first)
BASELINE_WINDOW = 8


def fail(msg: str) -> "None":
    print(f"FAIL: {msg}", file=sys.stderr)
    raise SystemExit(1)


def run_micro_bench(nx: int) -> dict:
    """One bench row at gate size, pinned to the CPU backend (the gate
    must not depend on accelerator availability) with a bounded budget."""
    env = dict(os.environ,
               BENCH_NX=str(nx), BENCH_REPS="2", BENCH_NO_PROBE="1",
               BENCH_FORCE_CPU="1", BENCH_DEADLINE_S="240",
               JAX_PLATFORMS="cpu")
    # the gate measures the default configuration — a sweep knob left in
    # the CI environment would silently fork the history key
    env.pop("SLU_TPU_TRACE", None)
    r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                       env=env, cwd=REPO, stdout=subprocess.PIPE,
                       stderr=subprocess.PIPE)
    if r.returncode != 0:
        sys.stderr.write(r.stderr.decode())
        fail(f"micro-bench failed (rc={r.returncode})")
    lines = [ln for ln in r.stdout.decode().strip().splitlines()
             if ln.strip()]
    if not lines:
        fail("micro-bench produced no JSON row")
    return json.loads(lines[-1])


def main(argv) -> int:
    row_file = None
    hist_path = None
    it = iter(argv)
    for a in it:
        if a == "--row":
            row_file = next(it, None)
        elif a == "--history":
            hist_path = next(it, None)
        else:
            print(__doc__, file=sys.stderr)
            return 2
    hist_path = hist_path or history_path()
    tol = env_float("PERF_GATE_TOL")
    min_samples = env_int("PERF_GATE_MIN_SAMPLES")

    if row_file:
        text = (sys.stdin.read() if row_file == "-"
                else open(row_file).read())
        lines = [ln for ln in text.strip().splitlines() if ln.strip()]
        row = json.loads(lines[-1])
    else:
        row = run_micro_bench(env_int("PERF_GATE_NX"))

    if row.get("value") is None:
        fail(f"bench row carries no measurement (phase="
             f"{row.get('phase')!r}, timeout={row.get('timeout')})")
    key = row_key(row)
    value = float(row["value"])

    prior = [h for h in load_history(hist_path)
             if h.get("history_key", row_key(h)) == key
             and h.get("value") is not None and not h.get("gate_fail")]
    if len(prior) < min_samples:
        append_row(row, hist_path)
        print(f"perf gate: SEEDED history ({len(prior)} -> "
              f"{len(prior) + 1} rows for [{key}]; enforcement starts at "
              f"{min_samples}) — value {value:.2f} GF/s")
        return 0

    window = prior[-BASELINE_WINDOW:]
    base = statistics.median(float(h["value"]) for h in window)
    floor = (1.0 - tol) * base
    ok = value >= floor
    append_row(row, hist_path, gate_fail=not ok)

    # compile-time creep: informational only (cache state swings it)
    comp = row.get("compile_seconds")
    comps = [float(h["compile_seconds"]) for h in window
             if h.get("compile_seconds")]
    if comp and comps:
        cbase = statistics.median(comps)
        if cbase > 0 and float(comp) > (1.0 + 2.0 * tol) * cbase:
            print(f"perf gate: WARN compile_seconds {comp:.2f}s vs "
                  f"median {cbase:.2f}s (cold cache?)")

    verdict = "OK" if ok else "REGRESSION"
    print(f"perf gate: {verdict} value {value:.2f} GF/s vs median "
          f"{base:.2f} over {len(window)} rows (floor {floor:.2f}, "
          f"tol {tol:.0%}) [{key}]")
    if not ok:
        print(f"FAIL: factor throughput regressed below the noise floor "
              f"— {value:.2f} < {floor:.2f} GF/s; inspect "
              f"'{sys.executable} scripts/bench_history.py list' and the "
              "compile census in the bench row", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
