"""Program-level IR audit — slulint v4's jaxpr tier.

slulint v1-v3 analyze Python SOURCE, but the artifacts that actually run
are jaxprs/HLO: the failure modes of compiled programs — un-donated
device buffers doubling peak memory, per-matrix constants baked into a
program that was supposed to be bucket-closed, shard-divergent
collective sequences that deadlock an SPMD mesh — are invisible to AST
rules.  This module walks CLOSED JAXPRS of the actual jitted programs
(stream/mega factor kernels, the fused ``make_factor_fn`` program, the
``solve/device.py`` sweep kernels, any ``shard_map``-wrapped program)
and checks them against the SLU111/SLU112/SLU114 rules in
``rules_program.py`` — the "verify the SCHEDULED program, not the
source" discipline of the dataflow-scheduling literature
(arXiv:2406.10511, arXiv:2506.05793) and the same statically-before-it-
deadlocks/OOMs bet SLU106/SLU109 already won at runtime.

Layering: this module is the only analysis file that touches jax, and
only LAZILY (inside :func:`trace_spec`) — the slulint CLI never imports
it, so source scans stay jax-free.  The rule functions themselves
(rules_program.py) are duck-typed over jaxpr objects and import no jax
either, so they are unit-testable on stubs.

The runtime twin lives in ``utils/programaudit.py``
(``SLU_TPU_VERIFY_PROGRAMS=1``): executors submit each program once at
construction/AOT-stage time and a finding raises a structured
``ProgramAuditError`` before the program ever runs.
"""

from __future__ import annotations

import dataclasses

#: jaxpr primitives that move data BETWEEN shards.  ``psum`` appears as
#: ``psum2`` inside shard_map since jax 0.4.31; ``pbroadcast`` is
#: excluded deliberately — shard_map inserts it as replication
#: BOOKKEEPING around ordinary math, so counting it would make every
#: branch look collective-bearing.
COLLECTIVE_PRIMS = frozenset({
    "psum", "psum2", "pmax", "pmin", "ppermute", "pshuffle", "all_gather",
    "all_to_all", "reduce_scatter", "psum_scatter",
})

#: control-flow primitives whose branch sub-jaxprs execute ALTERNATIVELY
#: (every other sub-jaxpr — scan/while/pjit/closed_call bodies — executes
#: unconditionally and is walked inline)
BRANCHING_PRIMS = frozenset({"cond", "switch"})


@dataclasses.dataclass
class ProgramSpec:
    """One traced program plus the call-site facts the rules need.

    ``donated`` are the argument positions jit will alias/overwrite;
    ``dead`` are the positions the CALL SITE treats as dead after the
    call (the submitter knows its own liveness — the jaxpr cannot).
    A dead-but-not-donated large input is exactly the SLU111 bug."""

    label: str                 # program identity, e.g. "lu b8 m24 w8 u16"
    site: str                  # build site, e.g. "stream._kernel"
    jaxpr: object              # jax.core.ClosedJaxpr (duck-typed)
    donated: tuple = ()        # argnums jit donates
    dead: tuple = ()           # argnums the call site discards after use
    mesh_axes: tuple = ()      # mesh axis names the program runs under

    @property
    def in_avals(self):
        return tuple(self.jaxpr.in_avals)


# --------------------------------------------------------------------------
# duck-typed jaxpr walking (no jax import — works on test stubs)
# --------------------------------------------------------------------------

def aval_bytes(aval) -> int:
    """Size of one input/output aval in bytes (0 when unknown)."""
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    return n * int(getattr(dtype, "itemsize", 0) or 0)


def const_bytes(const) -> int:
    """Bytes held by one baked constant (jax array, numpy array or
    scalar)."""
    nb = getattr(const, "nbytes", None)
    if nb is not None:
        return int(nb)
    return 0


def open_jaxpr(j):
    """The open jaxpr of a ClosedJaxpr, or ``j`` itself if already open."""
    inner = getattr(j, "jaxpr", None)
    return inner if inner is not None and hasattr(inner, "eqns") else j


def sub_jaxprs(eqn, branches_too: bool = True):
    """Sub-jaxprs referenced by one equation's params (scan/while/pjit
    bodies, cond branches...).  ``branches_too=False`` skips params named
    'branches' so callers can treat alternative execution specially."""
    for name, v in getattr(eqn, "params", {}).items():
        if not branches_too and name == "branches":
            continue
        vs = v if isinstance(v, (list, tuple)) else (v,)
        for s in vs:
            s = open_jaxpr(s)
            if hasattr(s, "eqns"):
                yield s


def iter_eqns(jaxpr):
    """Every equation, recursively through all sub-jaxprs (branches
    included)."""
    stack = [open_jaxpr(jaxpr)]
    while stack:
        j = stack.pop()
        for eqn in j.eqns:
            yield eqn
            stack.extend(sub_jaxprs(eqn))


def eqn_axes(eqn) -> tuple:
    """Mesh axis NAMES a collective equation reduces/permutes over
    (positional integer axes are filtered out)."""
    params = getattr(eqn, "params", {})
    axes = params.get("axes", params.get("axis_name", ()))
    if not isinstance(axes, (list, tuple)):
        axes = (axes,)
    return tuple(a for a in axes if isinstance(a, str))


def collective_sequence(jaxpr) -> list:
    """The ordered (primitive, axes) sequence of collectives a program
    executes, sub-jaxprs inlined IN ORDER.  For branching primitives the
    first branch's sequence is inlined (branch DISAGREEMENT is SLU114's
    separate check — for a lockstep-clean program all branches agree, so
    any branch represents the sequence)."""
    out = []
    j = open_jaxpr(jaxpr)
    for eqn in j.eqns:
        name = getattr(eqn.primitive, "name", str(eqn.primitive))
        if name in COLLECTIVE_PRIMS:
            out.append((name, eqn_axes(eqn)))
            continue
        if name in BRANCHING_PRIMS:
            branches = [open_jaxpr(b)
                        for b in eqn.params.get("branches", ())]
            if branches:
                out.extend(collective_sequence(branches[0]))
            continue
        for s in sub_jaxprs(eqn):
            out.extend(collective_sequence(s))
    return out


def branch_divergences(jaxpr) -> list:
    """Branching equations whose branches execute DIFFERENT collective
    sequences — the static shard-divergence witness: under shard_map a
    traced predicate can differ per shard, so a collective present in
    one branch and absent (or reordered) in another is the in-program
    analog of ranks entering different TreeComm collectives (runtime
    SLU106).  Returns [(eqn, [per-branch sequences])]."""
    out = []
    for eqn in iter_eqns(jaxpr):
        name = getattr(eqn.primitive, "name", str(eqn.primitive))
        if name not in BRANCHING_PRIMS:
            continue
        seqs = [collective_sequence(b)
                for b in eqn.params.get("branches", ())]
        if seqs and any(s != seqs[0] for s in seqs[1:]):
            out.append((eqn, seqs))
    return out


def bound_axis_names(jaxpr) -> set:
    """Axis names bound INSIDE the program by nested shard_map/pmap
    equations (valid targets for collectives even when the outer mesh
    contributes none)."""
    names: set = set()
    for eqn in iter_eqns(jaxpr):
        params = getattr(eqn, "params", {})
        mesh = params.get("mesh")
        if mesh is not None:
            names.update(str(a) for a in getattr(mesh, "axis_names", ()))
        an = params.get("axis_name")
        if isinstance(an, str) and getattr(
                eqn.primitive, "name", "") not in COLLECTIVE_PRIMS:
            names.add(an)
    return names


# --------------------------------------------------------------------------
# tracing (the ONLY place this module touches jax — lazily)
# --------------------------------------------------------------------------

def _shape_structs(args):
    """Per-argument ShapeDtypeStruct PYTREES mirroring ``args`` (the
    fused solve programs take lists/tuples of arrays)."""
    import numpy as np
    import jax
    from jax.tree_util import tree_map

    def to_sds(leaf):
        if isinstance(leaf, jax.ShapeDtypeStruct):
            return leaf
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype)
        a = np.asarray(leaf)
        return jax.ShapeDtypeStruct(a.shape, a.dtype)

    return tuple(tree_map(to_sds, a) for a in args)


def _flat_argnums(sds, argnums) -> tuple:
    """Translate TOP-LEVEL argument positions into flat invar positions
    of the traced program (pytree args span several invars)."""
    from jax.tree_util import tree_leaves
    counts = [len(tree_leaves(a)) for a in sds]
    starts = [0]
    for c in counts[:-1]:
        starts.append(starts[-1] + c)
    out = []
    for i in argnums:
        if i < len(counts):
            out.extend(range(starts[i], starts[i] + counts[i]))
    return tuple(out)


def _auto_donated(traced) -> tuple:
    """Donated argnums read off jax.stages.Traced.args_info (flat
    positional programs: leaf order == argnum order)."""
    try:
        from jax.tree_util import tree_leaves
        leaves = tree_leaves(traced.args_info,
                             is_leaf=lambda x: hasattr(x, "donated"))
        return tuple(i for i, l in enumerate(leaves)
                     if getattr(l, "donated", False))
    except Exception:
        return ()


def trace_spec(fn, args, *, label: str, site: str, dead=(),
               donated=None, mesh_axes=()) -> ProgramSpec:
    """Trace ``fn`` abstractly (ShapeDtypeStructs — no device work, no
    compile) and package the closed jaxpr with the call-site facts.

    ``fn`` is usually a ``jax.jit`` object: its ``.trace`` (jax >=
    0.4.31) yields the closed jaxpr AND the per-arg donation flags, so
    donation never has to be restated at the submit site.  Plain
    callables fall back to ``jax.make_jaxpr`` (donated=()).
    """
    import jax
    sds = _shape_structs(args)
    closed = None
    if donated is None:
        auto = ()
    else:
        auto = _flat_argnums(sds, tuple(donated))
    if hasattr(fn, "trace"):
        traced = fn.trace(*sds)
        closed = traced.jaxpr
        if donated is None:
            auto = _auto_donated(traced)
    if closed is None:
        closed = jax.make_jaxpr(fn)(*sds)
    return ProgramSpec(label=label, site=site, jaxpr=closed,
                       donated=tuple(auto), dead=_flat_argnums(sds, dead),
                       mesh_axes=tuple(mesh_axes))


def audit_spec(spec: ProgramSpec, donate_min_bytes: int,
               const_max_bytes: int):
    """Run the SLU111/SLU112/SLU114 program rules over one spec.

    Returns ``(findings, stats)`` — findings are
    :class:`~superlu_dist_tpu.analysis.core.Finding` records anchored at
    ``<program:label>``; stats carry the per-program donation coverage
    and baked-const byte totals the compile census and bench row report.
    """
    from superlu_dist_tpu.analysis import rules_program as rp
    findings = []
    f1, don_stats = rp.audit_donation(spec, donate_min_bytes)
    f2, const_stats = rp.audit_baked_consts(spec, const_max_bytes)
    f3 = rp.audit_collective_lockstep(spec)
    findings = f1 + f2 + f3
    stats = {"label": spec.label, "site": spec.site,
             "findings": len(findings)}
    stats.update(don_stats)
    stats.update(const_stats)
    return findings, stats


def audit_sharding(spec: ProgramSpec, reshard_min_bytes: int,
                   budget_bytes: int = 0):
    """Run the v6 sharding/memory rules (SLU119 implicit replication/
    reshard blowup / SLU121 static peak-memory model) over one spec —
    the jaxpr half of the ``SLU_TPU_VERIFY_SHARDING=1`` /
    ``SLU_TPU_MEM_BUDGET_BYTES`` runtime twin (utils/programaudit.py).

    Returns ``(findings, stats)`` like :func:`audit_spec`; stats carry
    ``peak_bytes_est``/``replicated_bytes`` — the census memory column.
    """
    from superlu_dist_tpu.analysis import rules_sharding as rs
    f1, reshard_stats = rs.audit_resharding(spec, reshard_min_bytes)
    f2, mem_stats = rs.audit_peak_memory(spec, budget_bytes)
    findings = f1 + f2
    stats = {"label": spec.label, "site": spec.site,
             "findings": len(findings)}
    stats.update(reshard_stats)
    stats.update(mem_stats)
    return findings, stats


def audit_dtypes(spec: ProgramSpec):
    """Run the v5 precision rules (SLU115 narrowing converts / SLU116
    accumulation dtypes) over one spec — the jaxpr half of the
    ``SLU_TPU_VERIFY_DTYPES=1`` runtime twin (utils/programaudit.py).

    Returns ``(findings, stats)`` like :func:`audit_spec`; stats carry
    the convert/dot_general census the precision audit notes report.
    """
    from superlu_dist_tpu.analysis import rules_precision as rp
    f1, narrow_stats = rp.audit_narrowing(spec)
    f2, accum_stats = rp.audit_accumulation(spec)
    findings = f1 + f2
    stats = {"label": spec.label, "site": spec.site,
             "findings": len(findings)}
    stats.update(narrow_stats)
    stats.update(accum_stats)
    return findings, stats
