#!/usr/bin/env python
"""Multi-process driver (pgssvx, the pdgssvx-with-NR_loc analog) at the
driver bench size: block-row distributed A and b across 4 real
processes, shared-memory tree-collective gather to the factoring root,
distributed refinement back out (parallel/pgsrfs.py) — the capability
the reference exercises with `mpiexec -n 4 pdtest` on one box
(SURVEY.md §4, .travis_tests.sh).

Writes docs/pgssvx_4proc_n{n}.json.  Env: PGS_NX (default 48).
"""

import json
import multiprocessing as mp
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import REPO, cpu_session  # noqa: E402


def _worker(name, n_ranks, rank, part, b_loc, q):
    import jax
    jax.config.update("jax_platforms", "cpu")
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    from superlu_dist_tpu.parallel.pgssvx import pgssvx
    from superlu_dist_tpu.utils.options import Options
    with TreeComm(name, n_ranks, rank, max_len=1 << 20,
                  create=False) as tc:
        x, info = pgssvx(tc, Options(), part, b_loc)
        q.put((rank, info,
               float(np.linalg.norm(x)) if x is not None else None))


def main():
    cpu_session()
    import superlu_dist_tpu as slu
    from superlu_dist_tpu.models.gallery import poisson3d
    from superlu_dist_tpu.parallel.dist import distribute_rows
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    from superlu_dist_tpu.parallel.pgssvx import pgssvx

    nx = int(os.environ.get("PGS_NX", "48"))
    a = poisson3d(nx)
    n = a.n_rows
    xtrue = np.random.default_rng(2).standard_normal(n)
    b = a.matvec(xtrue)

    nranks = 4
    parts = distribute_rows(a, nranks)
    b_blocks = [b[p.fst_row:p.fst_row + p.m_loc] for p in parts]

    name = f"/slu_pgs_{os.getpid()}"
    t0 = time.perf_counter()
    procs = []
    owner = TreeComm(name, nranks, 0, max_len=1 << 20, create=True)
    try:
        ctx = mp.get_context("fork")
        q = ctx.Queue()
        procs += [ctx.Process(target=_worker,
                              args=(name, nranks, r, parts[r], b_blocks[r],
                                    q))
                  for r in range(1, nranks)]
        for p in procs:
            p.start()
        x, info = pgssvx(owner, slu.Options(), parts[0], b_blocks[0])
        t_total = time.perf_counter() - t0
        others = [q.get(timeout=1800) for _ in procs]
        for p in procs:
            p.join(timeout=60)
    finally:
        for p in procs:
            if p.is_alive():
                p.terminate()
                p.join(timeout=30)
        owner.close(unlink=True)
    assert info == 0 and all(i == 0 for _, i, _ in others), \
        (info, others)
    resid = float(np.linalg.norm(b - a.matvec(x)) / np.linalg.norm(b))
    err = float(np.max(np.abs(x - xtrue)) / np.max(np.abs(x)))
    rec = {"driver": "pgssvx", "processes": nranks, "n": n,
           "matrix": f"poisson3d nx={nx}", "total_seconds": round(t_total, 1),
           "residual": resid, "xtrue_inf_error": err, "info": info,
           "backend": "cpu, 4 host processes over shm tree collectives"}
    with open(os.path.join(REPO, "docs", f"pgssvx_4proc_n{n}.json"),
              "w") as f:
        json.dump(rec, f, indent=1)
    print(json.dumps(rec), flush=True)
    from superlu_dist_tpu.utils import tols
    assert resid < tols.RESID_GATE_TIGHT, resid


if __name__ == "__main__":
    main()
