"""Device-resident supernodal triangular solves.

Analog of pdgstrs (SRC/pdgstrs.c:838) + the lsum kernels
(SRC/pdgstrs_lsum.c:413,1360): forward solve L·y = d walking the supernode
tree bottom-up, backward solve U·x = y walking it back down.  Where the
reference runs an MPI event loop over per-supernode broadcast/reduce trees
with OpenMP-task lsum updates, here each sweep batch is one batched
kernel: gather RHS segments, a (recursively blocked) triangular solve on
the MXU, and a scatter-add of the L21·y (resp. U12·x) contributions — the
lsum vector lives in device HBM, playing the role of the reference's
distributed lsum buffers.

Sweep batches come from a :class:`~superlu_dist_tpu.solve.plan.SolvePlan`
(solve/plan.py): the PR 5 dataflow machinery regroups supernodes across
elimination levels into maximal same-shape batches, with a second
shape-key alignment pass on top of the factor keys.  Batches that
coincide with a factor group alias its front arrays (zero copy); merged
batches gather — and, for promoted keys, identity/zero-pad — a fresh
panel stack once at solver construction.

Many-RHS support is first-class: request widths map onto a CLOSED nrhs
bucket set (power-of-two rungs then bounded geometric growth,
solve/plan.py) and anything past the cap is column-chunked, so one
serving process compiles at most |buckets| kernel variants per sweep
shape no matter what traffic arrives.  Large supernode diagonal blocks
solve via recursive blocked TRSM (``SLU_TPU_SOLVE_TRSM_LEAF``): the
recursion turns all but the leaf triangles into batched GEMMs the MXU
can run at rate (arXiv:2504.13821's recursive TRSM, batched).

Factors never leave the device (the reference's analog: factors stay in
each rank's memory between pdgstrf and pdgstrs); only the right-hand side
(n·nrhs) crosses the host boundary.  Like the factorization executors, one
kernel compiles per distinct (batch, m, w, u, nrhs-bucket) shape and is
cached persistently.
"""

from __future__ import annotations

import functools
import time

import numpy as np
import jax
import jax.numpy as jnp

from superlu_dist_tpu.numeric.factor import NumericFactorization
from superlu_dist_tpu.obs.compilestats import COMPILE_STATS
from superlu_dist_tpu.obs.trace import get_tracer
from superlu_dist_tpu.solve.plan import SolvePlan, build_solve_plan, chunk_nrhs


def _audit_sweep(label: str, kern, args, dead) -> None:
    """Submit one sweep program to the runtime IR auditor
    (SLU_TPU_VERIFY_PROGRAMS=1; allocates nothing when off).  ``dead``
    names the RHS/lsum argnums each sweep consumes — they are donated
    by every kernel factory above, which is what SLU111 verifies."""
    from superlu_dist_tpu.utils.programaudit import maybe_audit
    maybe_audit("solve.device", label, kern, args, dead=dead)


def _sweep_kernel_builds() -> int:
    """Total jitted-closure builds across the solve kernel factories —
    the compile-census marker for one solve's sweeps (a fresh closure's
    first invocation compiles synchronously inside the sweep)."""
    return (_fwd_kernel.cache_info().misses
            + _bwd_kernel.cache_info().misses
            + _fwd_trans_kernel.cache_info().misses
            + _bwd_trans_kernel.cache_info().misses
            + _diag_inv_kernel.cache_info().misses)


def _trsm(a, b, lower, unit, trans, leaf, prec="highest"):
    """Batched triangular solve op(a)·x = b with recursive blocking.

    a is (B, w, w), b is (B, w, k).  At or below ``leaf`` the vmapped
    LAPACK-style solve runs directly; above it the triangle splits in
    half and the off-diagonal block becomes one batched GEMM — the
    recursive blocked TRSM that keeps large diagonal blocks on the MXU
    instead of in a length-w dependent chain (leaf <= 0 disables
    blocking entirely).  ``prec`` is the caller-resolved GEMM-precision
    ladder tier (ops/dense.gemm_precision) the off-diagonal GEMMs run at
    — the solve-side half of the throughput ladder; the leaf triangles
    themselves always solve at full precision.  Conjugation is the
    caller's job (conj the triangle before calling, as the trans sweeps
    already do)."""
    from superlu_dist_tpu.ops.dense import gemm
    w = a.shape[-1]
    if leaf <= 0 or w <= leaf:
        return jax.vmap(lambda m, r: jax.scipy.linalg.solve_triangular(
            m, r, lower=lower, unit_diagonal=unit, trans=trans))(a, b)
    h = w // 2
    a11, a22 = a[:, :h, :h], a[:, h:, h:]
    b1, b2 = b[:, :h], b[:, h:]
    if lower != bool(trans):
        # dependency runs top-down: x1 first, then fold A21·x1 (notrans
        # lower) / A12ᵀ·x1 (trans upper) out of b2
        off = a[:, h:, :h] if lower else jnp.swapaxes(a[:, :h, h:], 1, 2)
        x1 = _trsm(a11, b1, lower, unit, trans, leaf, prec)
        x2 = _trsm(a22, b2 - gemm(off, x1, prec),
                   lower, unit, trans, leaf, prec)
    else:
        # bottom-up: x2 first (notrans upper / trans lower)
        off = a[:, :h, h:] if not lower else jnp.swapaxes(a[:, h:, :h], 1, 2)
        x2 = _trsm(a22, b2, lower, unit, trans, leaf, prec)
        x1 = _trsm(a11, b1 - gemm(off, x2, prec),
                   lower, unit, trans, leaf, prec)
    return jnp.concatenate([x1, x2], axis=1)


def _fwd_body(lpanel, x, lsum, first, rows, ws, w, u, n, use_inv, linv,
              leaf, prec="highest"):
    """x[cols] <- L11⁻¹(x[cols] − lsum[cols]); lsum[rows] += L21·x[cols].

    With use_inv, L11⁻¹ arrives precomputed and the triangular solve
    becomes one batched GEMM (the reference's DiagInv fast path,
    pdgstrs.c:1252-1396: dense X(k) = Linv(k)·b via dgemm)."""
    k = jnp.arange(w)
    # padded pivot columns (k >= ws) would alias the NEXT supernode's
    # entries — clamp them to the dump row n-1 (factor cols/rows there
    # are exactly identity/zero, so the garbage never reaches real x)
    cols = jnp.where(k[None, :] < ws[:, None],
                     first[:, None] + k, n - 1)      # (B, w)
    rhs = (x.at[cols].get(mode="fill", fill_value=0)
           - lsum.at[cols].get(mode="fill", fill_value=0))
    if use_inv:
        # same-dtype preferred_element_type pins are no-ops bitwise —
        # they make the accumulation width explicit (slulint SLU116)
        y = jnp.matmul(linv, rhs, precision=jax.lax.Precision.HIGHEST,
                       preferred_element_type=rhs.dtype)
    else:
        y = _trsm(lpanel[:, :w, :w], rhs, lower=True, unit=True,
                  trans=0, leaf=leaf, prec=prec)
    x = x.at[cols].set(y, mode="drop")
    if u:
        contrib = jnp.matmul(lpanel[:, w:, :], y,
                             precision=jax.lax.Precision.HIGHEST,
                             preferred_element_type=y.dtype)
        lsum = lsum.at[rows].add(contrib, mode="drop")
    return x, lsum


def _bwd_body(lpanel, upanel, x, first, rows, ws, w, u, n, use_inv, uinv,
              leaf, prec="highest"):
    """x[cols] <- U11⁻¹(x[cols] − U12·x[rows])."""
    k = jnp.arange(w)
    cols = jnp.where(k[None, :] < ws[:, None],
                     first[:, None] + k, n - 1)
    rhs = x.at[cols].get(mode="fill", fill_value=0)
    if u:
        xr = x.at[rows].get(mode="fill", fill_value=0)   # (B, u, nrhs)
        rhs = rhs - jnp.matmul(upanel, xr,
                               precision=jax.lax.Precision.HIGHEST,
                               preferred_element_type=xr.dtype)
    if use_inv:
        y = jnp.matmul(uinv, rhs, precision=jax.lax.Precision.HIGHEST,
                       preferred_element_type=rhs.dtype)
    else:
        y = _trsm(lpanel[:, :w, :w], rhs, lower=False, unit=False,
                  trans=0, leaf=leaf, prec=prec)
    return x.at[cols].set(y, mode="drop")


def _fwd_body_trans(lpanel, upanel, x, lsum, first, rows, ws, w, u, n,
                    conj, leaf, prec="highest"):
    """Transpose forward sweep: x[cols] <- U11⁻ᵀ(x[cols] − lsum[cols]);
    lsum[rows] += U12ᵀ·x[cols].  Mᵀ = UᵀLᵀ, so Uᵀ (lower) leads — the
    trans_t path through the same factors (superlu_defs.h:628-657)."""
    k = jnp.arange(w)
    cols = jnp.where(k[None, :] < ws[:, None],
                     first[:, None] + k, n - 1)
    rhs = (x.at[cols].get(mode="fill", fill_value=0)
           - lsum.at[cols].get(mode="fill", fill_value=0))
    u11 = lpanel[:, :w, :w]
    if conj:
        u11 = u11.conj()
    y = _trsm(u11, rhs, lower=False, unit=False, trans=1, leaf=leaf,
              prec=prec)
    x = x.at[cols].set(y, mode="drop")
    if u:
        u12 = upanel.conj() if conj else upanel       # (B, w, u)
        contrib = jnp.matmul(jnp.swapaxes(u12, 1, 2), y,
                             precision=jax.lax.Precision.HIGHEST,
                             preferred_element_type=y.dtype)
        lsum = lsum.at[rows].add(contrib, mode="drop")
    return x, lsum


def _bwd_body_trans(lpanel, x, first, rows, ws, w, u, n, conj, leaf,
                    prec="highest"):
    """Transpose backward sweep: x[cols] <- L11⁻ᵀ(x[cols] − L21ᵀ·x[rows])."""
    k = jnp.arange(w)
    cols = jnp.where(k[None, :] < ws[:, None],
                     first[:, None] + k, n - 1)
    rhs = x.at[cols].get(mode="fill", fill_value=0)
    if u:
        xr = x.at[rows].get(mode="fill", fill_value=0)
        l21 = lpanel[:, w:, :]                         # (B, u_pad, w)
        if conj:
            l21 = l21.conj()
        rhs = rhs - jnp.matmul(jnp.swapaxes(l21, 1, 2), xr,
                               precision=jax.lax.Precision.HIGHEST,
                               preferred_element_type=xr.dtype)
    l11 = lpanel[:, :w, :w]
    if conj:
        l11 = l11.conj()
    y = _trsm(l11, rhs, lower=True, unit=True, trans=1, leaf=leaf,
              prec=prec)
    return x.at[cols].set(y, mode="drop")


@functools.lru_cache(maxsize=None)
def _fwd_kernel(batch, m, w, u, nrhs, n, dtype, use_inv=False, leaf=0,
                prec="highest"):
    def step(lpanel, x, lsum, first, rows, ws, linv=None):
        return _fwd_body(lpanel, x, lsum, first, rows, ws, w, u, n,
                         use_inv, linv, leaf, prec)

    return jax.jit(step, donate_argnums=(1, 2))


@functools.lru_cache(maxsize=None)
def _bwd_kernel(batch, m, w, u, nrhs, n, dtype, use_inv=False, leaf=0,
                prec="highest"):
    def step(lpanel, upanel, x, first, rows, ws, uinv=None):
        return _bwd_body(lpanel, upanel, x, first, rows, ws, w, u, n,
                         use_inv, uinv, leaf, prec)

    return jax.jit(step, donate_argnums=(2,))


@functools.lru_cache(maxsize=None)
def _fwd_trans_kernel(batch, m, w, u, nrhs, n, dtype, conj=False, leaf=0,
                      prec="highest"):
    def step(lpanel, upanel, x, lsum, first, rows, ws):
        return _fwd_body_trans(lpanel, upanel, x, lsum, first, rows, ws,
                               w, u, n, conj, leaf, prec)

    return jax.jit(step, donate_argnums=(2, 3))


@functools.lru_cache(maxsize=None)
def _bwd_trans_kernel(batch, m, w, u, nrhs, n, dtype, conj=False, leaf=0,
                      prec="highest"):
    def step(lpanel, x, first, rows, ws):
        return _bwd_body_trans(lpanel, x, first, rows, ws, w, u, n, conj,
                               leaf, prec)

    return jax.jit(step, donate_argnums=(1,))


@functools.lru_cache(maxsize=None)
def _diag_inv_kernel(w, dtype, leaf=0, prec="highest"):
    """Batched inverses of the packed diagonal blocks — the
    pdCompute_Diag_Inv analog (SRC/pdgstrs.c:647, dtrtri per block)."""

    def inv(lpanel):
        f11 = lpanel[:, :w, :w]
        eye = jnp.broadcast_to(jnp.eye(w, dtype=lpanel.dtype),
                               f11.shape)
        linv = _trsm(f11, eye, lower=True, unit=True, trans=0, leaf=leaf,
                     prec=prec)
        uinv = _trsm(f11, eye, lower=False, unit=False, trans=0,
                     leaf=leaf, prec=prec)
        return linv, uinv

    return jax.jit(inv)


def _pad_panels(lp, up, w0, u0, W, U):
    """Promote one factor group's panel stack from its (w0, u0) padding
    to a merged solve key (W, U): identity on the new pivot diagonal
    (benign under both the unit-lower and the non-unit upper solves —
    padded columns gather from and write to the dump row only), zeros
    everywhere else so padded L21/U12 contributions vanish exactly."""
    piv, l21 = lp[:, :w0, :w0], lp[:, w0:, :]
    dw, du = W - w0, U - u0
    piv = jnp.pad(piv, ((0, 0), (0, dw), (0, dw)))
    if dw:
        idx = jnp.arange(w0, W)
        piv = piv.at[:, idx, idx].set(1)
    l21 = jnp.pad(l21, ((0, 0), (0, du), (0, dw)))
    return (jnp.concatenate([piv, l21], axis=1),
            jnp.pad(up, ((0, 0), (0, dw), (0, du))))


class DeviceSolver:
    """Solve (L·U)x = d on the device, in the factor's permuted labeling.

    The dSOLVEstruct_t analog (superlu_ddefs.h:216-228): the sweep
    schedule (a SolvePlan), per-batch index maps and panel stacks are
    built once and reused across repeated solves (the reference caches
    them behind SolveInitialized, pdgssvx.c:1330-1337).

    fused=True traces each whole sweep (all batches) into ONE jitted XLA
    program per nrhs bucket — one dispatch for the forward solve and one
    for the backward instead of one per sweep batch.  The solve is
    latency-bound (tiny per-level GEMVs — SURVEY.md §7 hard-part 5:
    "tree-based trisolve is tiny-message dominated"), so collapsing the
    dispatch chain is the device analog of the reference's fully
    pipelined event loop.  Compile cost grows with the plan, so "auto"
    fuses only moderate plans.
    """

    def __init__(self, fact: NumericFactorization, diag_inv: bool = False,
                 fused: str | bool = "auto", mesh=None,
                 solve_plan: SolvePlan | None = None,
                 schedule: str | None = None, window: int | None = None,
                 align: float | None = None, trsm_leaf: int | None = None,
                 nrhs_max: int | None = None,
                 nrhs_growth: float | None = None,
                 gemm_prec: str | None = None):
        """mesh: a jax.sharding.Mesh the factors are sharded over.  Needed
        when the mesh spans MULTIPLE PROCESSES (the pdgstrs-over-the-grid
        case): the RHS then uploads replicated over the global mesh and
        the index maps stay numpy (pjit treats identical host arrays as
        replicated global inputs), so every controller runs the same SPMD
        sweeps and reads the replicated result locally.  On such a
        MULTI-PROCESS mesh the sweep schedule is pinned to "factor" —
        re-gathering panel stacks into dataflow sweep batches would
        commit non-addressable shards to one local device (solve/plan.py
        documents the rationale) — so those solves keep the factor
        grouping 1:1.  Single-process mesh solves are NOT pinned: one
        controller addresses every device, so the dataflow solve
        schedule applies, and the shard_map tier (parallel/spmd.SpmdSolver,
        which subclasses this with mesh=None) always uses it."""
        self.fact = fact
        self.diag_inv = diag_inv
        self.mesh = mesh
        plan = fact.plan
        if trsm_leaf is None:
            from superlu_dist_tpu.utils.options import env_int
            trsm_leaf = env_int("SLU_TPU_SOLVE_TRSM_LEAF")
        self.trsm_leaf = int(trsm_leaf)
        # GEMM-precision ladder tier for the blocked-TRSM off-diagonal
        # GEMMs (ops/dense.gemm_precision — the solve-side half of the
        # throughput ladder), resolved in this uncached constructor and
        # part of every sweep-kernel cache key below
        from superlu_dist_tpu.ops.dense import gemm_precision
        self.gemm_prec = gemm_precision(gemm_prec)
        if mesh is not None and jax.process_count() > 1:
            # the factor-schedule pin is a MULTI-PROCESS constraint only
            # (docstring above; solve/plan.py) — single-process meshes
            # keep the dataflow solve schedule like any local solve
            solve_plan = build_solve_plan(plan, schedule="factor",
                                          nrhs_max=nrhs_max,
                                          nrhs_growth=nrhs_growth)
        elif solve_plan is None:
            solve_plan = build_solve_plan(plan, schedule=schedule,
                                          window=window, align=align,
                                          nrhs_max=nrhs_max,
                                          nrhs_growth=nrhs_growth)
        self.splan = solve_plan
        self.last_solve_stats = None
        if fused == "auto":
            fused = len(solve_plan.groups) <= 256
        self.fused = bool(fused)
        self._fused_cache = {}
        self._replicate = None
        sf = plan.sf
        self.n = plan.n
        first = sf.sn_start[:-1]
        self._groups = []
        self._invs_cached = None
        # with a (multi-process) mesh the index arrays must not commit to
        # one local device — numpy args are what pjit accepts uniformly
        _put = (lambda x: np.asarray(x)) if mesh is not None else jnp.asarray
        # a host-share factorization (stream.py SLU_TPU_HOST_FLOPS) leaves
        # the leading leaf panels as numpy: upload those once so the
        # jitted sweeps don't re-transfer them on every solve.  The
        # uploaded list lives on the SOLVER — assigning back to
        # fact.fronts would silently flip fact.on_host and force a
        # later host solve on the same factorization to re-pull everything
        if (any(isinstance(lp, np.ndarray) for lp, _ in fact.fronts)
                and not fact.on_host):
            # stream.py disables host-share under a mesh; enforce that
            # invariant HERE too — jnp.asarray would commit these fronts
            # to one local device and break a multi-process SPMD solve
            assert mesh is None, \
                "host-share fronts cannot meet a multi-process mesh solve"
            src_fronts = [(jnp.asarray(lp), jnp.asarray(up))
                          for lp, up in fact.fronts]
        else:
            src_fronts = fact.fronts
        panels = []
        for sg in solve_plan.groups:
            if sg.reuse >= 0:
                panels.append(src_fronts[sg.reuse])
            else:
                panels.append(self._gather_panels(sg, src_fronts, plan))
            firsts = _put(first[sg.sns])
            rows = np.full((sg.batch, sg.u), self.n, dtype=np.int64)
            for slot, s in enumerate(sg.sns):
                r = sf.sn_rows[s]
                rows[slot, :len(r)] = r
            self._groups.append((sg, firsts, _put(rows), _put(sg.ws)))
        self.fronts = panels

    @staticmethod
    def _gather_panels(sg, src_fronts, plan):
        """Assemble one merged sweep batch's panel stack from the factor
        fronts: per contiguous source-group run one fancy-index gather,
        promoted keys identity/zero-padded, all concatenated in member
        (slot) order.  Runs once at construction, on device."""
        parts_l, parts_u = [], []
        i, B = 0, sg.batch
        while i < B:
            g = int(sg.src_group[i])
            j = i
            while j < B and int(sg.src_group[j]) == g:
                j += 1
            slots = np.ascontiguousarray(sg.src_slot[i:j], dtype=np.int64)
            lp, up = src_fronts[g]
            fg = plan.groups[g]
            if len(slots) == fg.batch and np.array_equal(
                    slots, np.arange(fg.batch)):
                lp, up = jnp.asarray(lp), jnp.asarray(up)   # whole group
            else:
                lp = jnp.asarray(lp)[slots]
                up = jnp.asarray(up)[slots]
            if (fg.w, fg.u) != (sg.w, sg.u):
                lp, up = _pad_panels(lp, up, fg.w, fg.u, sg.w, sg.u)
            parts_l.append(lp)
            parts_u.append(up)
            i = j
        if len(parts_l) == 1:
            return parts_l[0], parts_u[0]
        return (jnp.concatenate(parts_l, axis=0),
                jnp.concatenate(parts_u, axis=0))

    @property
    def _invs(self):
        """Batched diagonal-block inverses (DiagInv), computed lazily on
        the first NON-transpose solve — transpose sweeps never read them,
        so a trans-only solver must not pay the inversion compiles or
        pin the inverse buffers in HBM."""
        if self._invs_cached is None:
            if self.diag_inv:
                self._invs_cached = [
                    _diag_inv_kernel(grp.w, str(jnp.dtype(self.fact.dtype)),
                                     self.trsm_leaf,
                                     self.gemm_prec)(jnp.asarray(lp))
                    for (grp, _, _, _), (lp, _) in zip(self._groups,
                                                       self.fronts)]
            else:
                self._invs_cached = [(None, None)] * len(self._groups)
        return self._invs_cached

    def _fused_fns(self, kb):
        """One jitted program per sweep (all batches) for this nrhs
        bucket.  (jit re-traces on shape/dtype changes anyway; the kb key
        just avoids rebuilding the Python closures.)"""
        fns = self._fused_cache.get(kb)
        if fns is not None:
            return fns
        n1 = self.n + 1
        use_inv = self.diag_inv
        leaf = self.trsm_leaf
        prec = self.gemm_prec
        meta = [(grp.w, grp.u) for grp, _, _, _ in self._groups]

        def fwd(x, lsum, fronts, idx, invs):
            for (w, u), (lp, _), (firsts, rows, ws), (linv, _) in zip(
                    meta, fronts, idx, invs):
                x, lsum = _fwd_body(lp, x, lsum, firsts, rows, ws, w, u,
                                    n1, use_inv, linv, leaf, prec)
            return x, lsum

        def bwd(x, fronts, idx, invs):
            for (w, u), (lp, up), (firsts, rows, ws), (_, uinv) in zip(
                    reversed(meta), reversed(fronts), reversed(idx),
                    reversed(invs)):
                x = _bwd_body(lp, up, x, firsts, rows, ws, w, u, n1,
                              use_inv, uinv, leaf, prec)
            return x

        fns = (jax.jit(fwd, donate_argnums=(0, 1)),
               jax.jit(bwd, donate_argnums=(0,)))
        self._fused_cache[kb] = fns
        return fns

    def _fused_trans_fns(self, kb, conj):
        fns = self._fused_cache.get(("T", kb, conj))
        if fns is not None:
            return fns
        n1 = self.n + 1
        leaf = self.trsm_leaf
        prec = self.gemm_prec
        meta = [(grp.w, grp.u) for grp, _, _, _ in self._groups]

        def fwd(x, lsum, fronts, idx):
            for (w, u), (lp, up), (firsts, rows, ws) in zip(
                    meta, fronts, idx):
                x, lsum = _fwd_body_trans(lp, up, x, lsum, firsts, rows,
                                          ws, w, u, n1, conj, leaf, prec)
            return x, lsum

        def bwd(x, fronts, idx):
            for (w, u), (lp, _), (firsts, rows, ws) in zip(
                    reversed(meta), reversed(fronts), reversed(idx)):
                x = _bwd_body_trans(lp, x, firsts, rows, ws, w, u, n1,
                                    conj, leaf, prec)
            return x

        fns = (jax.jit(fwd, donate_argnums=(0, 1)),
               jax.jit(bwd, donate_argnums=(0,)))
        self._fused_cache[("T", kb, conj)] = fns
        return fns

    def _run_sweeps(self, rhs, sweeps):
        """Shared solve scaffolding: map the request's nrhs onto the
        closed bucket set (column-chunking past the cap), pad each chunk
        into an (n+1, kb) buffer (slot n is the OOB dump row), run
        sweeps(x, lsum, kb) -> x per chunk, then unpad — one copy for
        the plain and transpose paths.  Executed-vs-structural flops
        (shape padding × nrhs padding) are reported on the kernel span
        and latched on ``last_solve_stats`` — the solve path's honesty
        telemetry, matching the factor path's."""
        tracer = get_tracer()
        squeeze = rhs.ndim == 1
        r2 = rhs[:, None] if squeeze else rhs
        k = r2.shape[1]
        chunks = chunk_nrhs(k, self.splan.nrhs_bucket_set)
        kb_total = sum(b for _, _, b in chunks)
        dt = jnp.dtype(self.fact.dtype)
        structural = self.splan.flops_per_rhs * k
        executed = self.splan.executed_flops_per_rhs * kb_total
        stats = {"nrhs": k, "padded_nrhs": kb_total,
                 "chunks": len(chunks),
                 "solve_flops": structural, "executed_flops": executed,
                 "padding_factor": round(executed / max(structural, 1.0),
                                         4)}
        nonfinite_cols: list = []
        out = np.empty((self.n, k), dtype=dt)
        # compile census: new sweep-kernel closures (streamed lru misses
        # or fresh fused programs) mean this call compiles — time the
        # sweep issue and account it per (n, nrhs-bucket, mode)
        builds0 = _sweep_kernel_builds() + len(self._fused_cache)
        t0_build = time.perf_counter()
        d2h_s, d2h_bytes = 0.0, 0
        with tracer.span("device-solve", cat="kernel", n=self.n, nrhs=k,
                         padded_nrhs=kb_total, chunks=len(chunks),
                         fused=self.fused, n_groups=len(self._groups),
                         schedule=self.splan.schedule,
                         solve_flops=structural, executed_flops=executed,
                         padding_factor=stats["padding_factor"],
                         dtype=str(dt)):
            for lo, hi, kb in chunks:
                pad = np.zeros((self.n + 1, kb), dtype=dt)
                pad[:self.n, :hi - lo] = r2[:, lo:hi]
                if self.mesh is not None:
                    # replicated over the global mesh: every process
                    # supplies the same host array, every process can
                    # read the result locally
                    from jax.sharding import (NamedSharding,
                                              PartitionSpec as P)
                    rep = NamedSharding(self.mesh, P(None, None))
                    if self._replicate is None:
                        # cached: a fresh lambda per solve would miss
                        # jax's trace cache on every IR correction solve.
                        # The input re-shard buffer is dead after the
                        # call — donate it so the replication aliases
                        # instead of doubling the (n+1, kb) footprint
                        # per chunk (slulint SLU111)
                        self._replicate = jax.jit(lambda a: a,
                                                  out_shardings=rep,
                                                  donate_argnums=(0,))
                    x = jax.device_put(pad, rep)
                    lsum = jax.device_put(np.zeros_like(pad), rep)
                    x = sweeps(x, lsum, kb)
                    # normalize whatever sharding GSPMD inferred back to
                    # fully replicated so np.asarray below is
                    # process-local
                    x = self._replicate(x)
                else:
                    x = jnp.asarray(pad)
                    lsum = jnp.zeros_like(x)
                    x = sweeps(x, lsum, kb)
                t0 = time.perf_counter()
                res = np.asarray(jax.block_until_ready(x))[:self.n,
                                                           :hi - lo]
                d2h_s += time.perf_counter() - t0
                d2h_bytes += int(res.nbytes)
                # per-column finiteness probe on the sweep output: the
                # serving tier's poisoned-request isolation needs to
                # know WHICH columns broke, not just that one did (one
                # all-reduce pass when healthy, per-column only on the
                # failure path)
                if not np.isfinite(res).all():
                    fin = np.isfinite(res).all(axis=0)
                    nonfinite_cols.extend(
                        int(lo + j) for j in np.nonzero(~fin)[0])
                out[:, lo:hi] = res
            builds = (_sweep_kernel_builds() + len(self._fused_cache)
                      - builds0)
            if builds:
                COMPILE_STATS.record(
                    "solve.device",
                    f"solve n{self.n} nrhs{kb_total} "
                    f"{'fused' if self.fused else 'stream'}",
                    t0_build, time.perf_counter() - t0_build,
                    n_args=6, builds=builds)
            if tracer.enabled:
                # the solution's D2H pull (the only factor-sized data
                # that ever crosses the boundary per solve)
                tracer.complete("solve-d2h", "comm",
                                time.perf_counter() - d2h_s, d2h_s,
                                op="d2h", bytes=d2h_bytes)
        stats["finite"] = not nonfinite_cols
        stats["nonfinite_cols"] = nonfinite_cols
        if nonfinite_cols and tracer.enabled:
            tracer.complete("solve-probe", "verify", time.perf_counter(),
                            0.0, nonfinite=len(nonfinite_cols))
        self.last_solve_stats = stats
        return out[:, 0] if squeeze else out

    def solve_trans(self, rhs: np.ndarray, conj: bool = False) -> np.ndarray:
        """Solve (L·U)ᵀ x = rhs (or (L·U)ᴴ with conj) on the device —
        Mᵀ = Uᵀ·Lᵀ through the same factors (the reference's trans_t,
        superlu_defs.h:628-657; host twin: trisolve.lu_solve_trans).
        Respects the same fused/streamed guard as solve()."""
        fact = self.fact
        n1 = self.n + 1
        dt = jnp.dtype(fact.dtype)
        conj = bool(conj)
        leaf = self.trsm_leaf

        def sweeps(x, lsum, kb):
            if self.fused:
                fwd, bwd = self._fused_trans_fns(kb, conj)
                idx = [(firsts, rows, ws)
                       for _, firsts, rows, ws in self._groups]
                _audit_sweep(f"fusedT-fwd n{self.n} k{kb}", fwd,
                             (x, lsum, self.fronts, idx), dead=(0, 1))
                x, lsum = fwd(x, lsum, self.fronts, idx)
                _audit_sweep(f"fusedT-bwd n{self.n} k{kb}", bwd,
                             (x, self.fronts, idx), dead=(0,))
                return bwd(x, self.fronts, idx)
            # Uᵀ forward, sweep batches ascending
            for (grp, firsts, rows, ws), (lp, up) in zip(
                    self._groups, self.fronts):
                kern = _fwd_trans_kernel(grp.batch, grp.m, grp.w, grp.u,
                                         kb, n1, str(dt), conj, leaf,
                                         self.gemm_prec)
                _audit_sweep(
                    f"fwdT b{grp.batch} m{grp.m} w{grp.w} u{grp.u} "
                    f"k{kb} n{self.n}", kern,
                    (lp, up, x, lsum, firsts, rows, ws), dead=(2, 3))
                x, lsum = kern(lp, up, x, lsum, firsts, rows, ws)
            # Lᵀ backward, descending
            for (grp, firsts, rows, ws), (lp, up) in zip(
                    reversed(self._groups), reversed(self.fronts)):
                kern = _bwd_trans_kernel(grp.batch, grp.m, grp.w, grp.u,
                                         kb, n1, str(dt), conj, leaf,
                                         self.gemm_prec)
                _audit_sweep(
                    f"bwdT b{grp.batch} m{grp.m} w{grp.w} u{grp.u} "
                    f"k{kb} n{self.n}", kern,
                    (lp, x, firsts, rows, ws), dead=(1,))
                x = kern(lp, x, firsts, rows, ws)
            return x

        return self._run_sweeps(rhs, sweeps)

    def solve(self, rhs: np.ndarray) -> np.ndarray:
        """rhs (n,) or (n, k) in permuted labeling -> solution, same shape."""
        fact = self.fact
        n1 = self.n + 1
        dt = jnp.dtype(fact.dtype)
        use_inv = self.diag_inv
        leaf = self.trsm_leaf

        def sweeps(x, lsum, kb):
            if self.fused:
                fwd, bwd = self._fused_fns(kb)
                idx = [(firsts, rows, ws)
                       for _, firsts, rows, ws in self._groups]
                _audit_sweep(f"fused-fwd n{self.n} k{kb}", fwd,
                             (x, lsum, self.fronts, idx, self._invs),
                             dead=(0, 1))
                x, lsum = fwd(x, lsum, self.fronts, idx, self._invs)
                _audit_sweep(f"fused-bwd n{self.n} k{kb}", bwd,
                             (x, self.fronts, idx, self._invs), dead=(0,))
                return bwd(x, self.fronts, idx, self._invs)
            # forward in dispatch order (topological: every descendant's
            # batch precedes its ancestors' under either scheduler)
            for (grp, firsts, rows, ws), (lp, up), (linv, _) in zip(
                    self._groups, self.fronts, self._invs):
                kern = _fwd_kernel(grp.batch, grp.m, grp.w, grp.u, kb, n1,
                                   str(dt), use_inv, leaf, self.gemm_prec)
                args = ((lp, x, lsum, firsts, rows, ws, linv) if use_inv
                        else (lp, x, lsum, firsts, rows, ws))
                _audit_sweep(
                    f"fwd b{grp.batch} m{grp.m} w{grp.w} u{grp.u} "
                    f"k{kb} n{self.n}", kern, args, dead=(1, 2))
                x, lsum = kern(*args)
            # backward, descending
            for (grp, firsts, rows, ws), (lp, up), (_, uinv) in zip(
                    reversed(self._groups), reversed(self.fronts),
                    reversed(self._invs)):
                kern = _bwd_kernel(grp.batch, grp.m, grp.w, grp.u, kb, n1,
                                   str(dt), use_inv, leaf, self.gemm_prec)
                _audit_sweep(
                    f"bwd b{grp.batch} m{grp.m} w{grp.w} u{grp.u} "
                    f"k{kb} n{self.n}", kern,
                    (lp, up, x, firsts, rows, ws, uinv) if use_inv
                    else (lp, up, x, firsts, rows, ws), dead=(2,))
                x = (kern(lp, up, x, firsts, rows, ws, uinv) if use_inv
                     else kern(lp, up, x, firsts, rows, ws))
            return x

        return self._run_sweeps(rhs, sweeps)
