"""Mesh-axis registry — the single source of truth for every mesh axis
name the project shards over (the axis-name analog of the PR 3 env-knob
registry in ``utils/options.py``).

ROADMAP item 1 (the shard_map/pjit SPMD rewrite) multiplies the number
of call sites that spell axis names as string literals; a typo'd axis
(``"pannel"``) is not an error anywhere — jax just treats the dimension
as replicated and the program silently gathers.  Declaring every axis
here lets slulint rule SLU120 (``analysis/rules_sharding.py``) flag any
``shard_map``/``pjit``/``Mesh``/``NamedSharding``/``PartitionSpec``
call site whose literal axis name the registry does not declare — the
same lexical closed-world bet SLU104 won for env knobs.

The registry is import-cheap (no jax): the analysis tier reads it from
rule construction, and ``parallel/grid.py`` builds its mesh from the
canonical names below so the runtime and the lint rule can never
disagree about what an axis is called.
"""

from __future__ import annotations

import dataclasses


class UnknownAxisError(KeyError):
    """A mesh axis name was used that the registry does not declare."""


@dataclasses.dataclass(frozen=True)
class MeshAxis:
    name: str
    help: str


AXIS_REGISTRY: dict[str, MeshAxis] = {}


def register_axis(name: str, help: str) -> None:
    AXIS_REGISTRY[name] = MeshAxis(name, help)


def registered_axes() -> tuple:
    """The declared axis names, sorted — what SLU120 validates literal
    specs against."""
    return tuple(sorted(AXIS_REGISTRY))


def require_axis(name: str) -> str:
    """Validate one axis name at runtime (mesh construction paths);
    returns it unchanged or raises :class:`UnknownAxisError`."""
    if name not in AXIS_REGISTRY:
        raise UnknownAxisError(
            f"mesh axis {name!r} is not declared in utils/meshreg.py "
            f"(declared: {', '.join(registered_axes()) or 'none'}) — "
            "register it there so slulint SLU120 can vet literal specs")
    return name


def _register_all() -> None:
    r = register_axis
    r("snode", "supernode-batch axis: fronts of one dispatch group are "
      "scattered across devices along their batch dimension "
      "(parallel/grid.py process grid rows)")
    r("panel", "intra-front panel axis: the trailing front dimension a "
      "partitioned Schur pool shards over (parallel/grid.py process "
      "grid columns; SLU_TPU_POOL_PARTITION)")


_register_all()
