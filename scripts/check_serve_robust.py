#!/usr/bin/env python
"""Serve-robustness gate: the serving tier's survival kit must contain
blast radii exactly.

Two phases on a small Poisson system (CPU, a few seconds):

1. **Poisoned-column isolation** — one NaN column injected into a
   coalesced 64-column backlog: EXACTLY one ticket errors (with a
   structured ``ServePoisonedError`` naming its request-relative
   column) and every survivor's X is BITWISE identical to an
   uninjected run of the same backlog — per-column independence of the
   batched sweeps, preserved by the isolation path re-serving healthy
   columns at the original batch width.

2. **Overload storm** — a server with a small column cap and armed
   per-request deadlines is hammered by concurrent submitters: the
   shed count must be positive (admission control actually engaged),
   the queue must stay bounded by the cap, every ticket must resolve
   to a result or a structured error (no waiter hangs — the
   submit/close storm regression), and the server must close cleanly.

Exit 0 = pass.  One gate of scripts/ci_gates.sh (the consolidated CI
entry point).  Gate contract (shared with the other gates): any
regression — a second ticket failing, a survivor drifting bitwise, a
hang, an unbounded queue — raises/asserts, which exits non-zero with
the diagnostic on stderr.
"""

import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


def _factored(a):
    from superlu_dist_tpu.drivers.gssvx import gssvx
    from superlu_dist_tpu.utils.options import IterRefine, Options

    x, lu, stats, info = gssvx(Options(iter_refine=IterRefine.NOREFINE),
                               a, np.ones(a.n_rows))
    assert info == 0, f"factorization failed: info={info}"
    return lu


def _serve_backlog(srv, cols, timeout=120):
    tickets = [srv.submit(c) for c in cols]
    srv.start()
    srv.flush()
    out = []
    for t in tickets:
        try:
            out.append(("ok", t.result(timeout)))
        except Exception as e:          # noqa: BLE001
            out.append(("err", e))
    srv.close()
    return out


def check_poison_isolation(a, lu, bs):
    from superlu_dist_tpu.serve import ServePoisonedError, SolveServer

    clean = SolveServer(lu, start=False)
    ref = _serve_backlog(clean, [bs[:, j] for j in range(64)])
    assert all(k == "ok" for k, _ in ref), "clean backlog failed"
    assert clean.stats()["batches"] == 1, (
        f"backlog did not coalesce into one micro-batch "
        f"({clean.stats()['batches']} batches)")

    bp = bs.copy()
    bp[:, 17] = np.nan
    pois = SolveServer(lu, start=False)
    got = _serve_backlog(pois, [bp[:, j] for j in range(64)])
    errs = [j for j, (k, _) in enumerate(got) if k == "err"]
    assert errs == [17], (
        f"exactly ticket 17 must error, got error tickets {errs}")
    err = got[17][1]
    assert isinstance(err, ServePoisonedError), type(err).__name__
    assert err.columns == [0], err.columns
    drifted = [j for j in range(64) if j != 17
               and not np.array_equal(got[j][1], ref[j][1])]
    assert not drifted, (
        f"survivor ticket(s) {drifted} are not bitwise identical to the "
        "uninjected run")
    assert pois.stats()["poisoned_columns"] == 1
    print("  poison-isolation: 1/64 tickets errored, 63 survivors "
          "bitwise identical")


def check_overload_storm(a, lu, bs):
    from superlu_dist_tpu.serve import (ServeDeadlineError,
                                        ServeOverloadError,
                                        ServerClosedError, SolveServer)

    srv = SolveServer(lu, queue_max=16, deadline_s=0.25, max_wait_s=0.001)
    outcomes = []
    lock = threading.Lock()

    def client(seed):
        rng = np.random.default_rng(seed)
        for _ in range(10):
            # a burst of wide requests in flight at once — the storm
            # shape that actually pressures the 16-column cap
            burst = []
            for _ in range(3):
                j = int(rng.integers(0, bs.shape[1] - 4))
                try:
                    burst.append(srv.submit(bs[:, j:j + 4]))
                except ServeOverloadError:
                    with lock:
                        outcomes.append("shed")
                except ServerClosedError:
                    with lock:
                        outcomes.append("closed")
            with lock:
                depth = srv.stats()["queue_depth"]
                assert depth <= 16, f"queue grew past its cap: {depth}"
            for t in burst:
                try:
                    t.result(30)
                    tag = "ok"
                except ServeDeadlineError:
                    tag = "deadline"
                except ServerClosedError:
                    tag = "closed"
                except TimeoutError:
                    tag = "HANG"
                with lock:
                    outcomes.append(tag)

    threads = [threading.Thread(target=client, args=(s,))
               for s in range(8)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
        assert not t.is_alive(), "storm client hung"
    srv.close(timeout=60)
    wall = time.perf_counter() - t0
    st = srv.stats()
    assert "HANG" not in outcomes, "a ticket neither resolved nor erred"
    assert st["shed"] > 0, (
        "the storm never tripped admission control — the gate is not "
        f"exercising overload (outcomes: {outcomes})")
    assert outcomes.count("ok") > 0, "no request was served at all"
    assert st["queue_depth"] == 0, "queue not drained at close"
    print(f"  overload-storm: {outcomes.count('ok')} served, "
          f"{st['shed']} shed, {st['deadline_miss']} deadline misses, "
          f"{wall:.1f}s wall, queue bounded at {srv.queue_max}")


def main():
    from superlu_dist_tpu.models.gallery import poisson2d

    a = poisson2d(10)
    lu = _factored(a)
    rng = np.random.default_rng(3)
    xs = rng.standard_normal((a.n_rows, 64))
    bs = np.stack([a.matvec(xs[:, j]) for j in range(64)], axis=1)

    print("serve-robust gate: poisoned-column isolation")
    check_poison_isolation(a, lu, bs)
    print("serve-robust gate: overload storm")
    check_overload_storm(a, lu, bs)
    print("serve-robust gate: OK")


if __name__ == "__main__":
    main()
