"""COLAMD-class column ordering + AᵀA pattern (MMD_ATA support).

Capability analogs of the reference's colamd (SRC/colamd.c, dispatched for
colperm_t COLAMD) and getata_dist (SRC/get_perm_c.c:164, the AᵀA pattern
behind MMD_ATA) — both fresh implementations, not translations.

The COLAMD idea (as published by Davis/Gilbert/Larimore/Ng): order the
columns of A by approximate minimum degree in AᵀA *without forming AᵀA*.
The rows of A serve as the initial quotient-graph elements; eliminating a
column merges every element containing it into one fill element whose
column set is the union; a column's score is the sum of its live element
sizes — an upper bound on its external degree in AᵀA.  Dense rows are
dropped from the analysis and dense columns ordered last so one dense
stripe cannot poison every score.

The native implementation (slu_host.cpp slu_colamd / slu_ata_pattern) is
the fast path; the Python versions here are the specification and test
oracle (same tie-breaking: smallest column id on equal score).
"""

from __future__ import annotations

import heapq

import numpy as np


def dense_row_threshold(n: int) -> int:
    """Single definition of the colamd dense-row/column heuristic cutoff
    (entries > 10·sqrt(n) ⇒ sidelined).  Used by the Python oracle, the
    MMD_ATA dispatch, and mirrored by the C++ fast path
    (slu_host.cpp slu_colamd / slu_ata_pattern — keep in sync)."""
    return max(16, int(10.0 * np.sqrt(max(n, 1))))


def colamd_order(n_rows: int, n_cols: int, indptr: np.ndarray,
                 indices: np.ndarray) -> np.ndarray:
    """Return order[k] = old index of the k-th pivot column."""
    from superlu_dist_tpu import native
    order = native.colamd(n_rows, n_cols, indptr, indices)
    if order is not None:
        return order
    return _colamd_py(n_rows, n_cols, indptr, indices)


def _colamd_py(n_rows, n_cols, indptr, indices):
    dense_row = dense_row_threshold(n_cols)
    dense_col = dense_row_threshold(n_rows)
    elem_cols = {}                       # element id -> sorted col list
    col_elems = [[] for _ in range(n_cols)]
    for r in range(n_rows):
        cols = sorted(set(int(j) for j in indices[indptr[r]:indptr[r + 1]]))
        if len(cols) > dense_row:
            continue
        elem_cols[r] = cols
        for j in cols:
            col_elems[j].append(r)
    alive = np.ones(n_cols, dtype=bool)
    score = np.zeros(n_cols, dtype=np.int64)
    dense_cols = []

    def col_score(j):
        s = sum(len(elem_cols[e]) - 1 for e in col_elems[j]
                if e in elem_cols)
        return min(max(s, 0), n_cols - 1)

    heap = []
    for j in range(n_cols):
        if len(col_elems[j]) > dense_col:
            alive[j] = False
            dense_cols.append(j)
            continue
        score[j] = col_score(j)
        heap.append((int(score[j]), j))
    heapq.heapify(heap)
    for j in dense_cols:
        for e in col_elems[j]:
            if e in elem_cols and j in elem_cols[e]:
                elem_cols[e].remove(j)
    dense_cols.sort(key=lambda j: (len(col_elems[j]), j))

    order = np.empty(n_cols, dtype=np.int64)
    k = 0
    n_live = n_cols - len(dense_cols)
    while k < n_live:
        while True:
            s, c = heapq.heappop(heap)
            if alive[c] and s == score[c]:
                break
        order[k] = c
        alive[c] = False
        merged = set()
        for e in col_elems[c]:
            if e in elem_cols:
                merged.update(elem_cols[e])
                del elem_cols[e]
        merged.discard(c)
        live = sorted(j for j in merged if alive[j])
        eid = n_rows + k
        elem_cols[eid] = live
        # aggressive absorption (the colamd trick this implementation's
        # first cut missed): an old element whose every LIVE column lies
        # inside the new element is dominated by it — drop it, which
        # tightens the scores AND stops the per-column element lists
        # from accumulating (the 3D-mesh slowdown)
        live_set = set(live)
        tested = set()
        for j in live:
            for e in col_elems[j]:
                if e == eid or e in tested or e not in elem_cols:
                    continue
                tested.add(e)
                if all(not alive[x] or x in live_set
                       for x in elem_cols[e]):
                    del elem_cols[e]
        for j in live:
            col_elems[j] = [e for e in col_elems[j]
                            if e in elem_cols] + [eid]
            score[j] = col_score(j)
            heapq.heappush(heap, (int(score[j]), j))
        k += 1
    for j in dense_cols:
        order[k] = j
        k += 1
    return order


def ata_adjacency(n_rows: int, n_cols: int, indptr: np.ndarray,
                  indices: np.ndarray, dense_row: int = 0):
    """Symmetric adjacency (no diagonal) of AᵀA in CSR form — the
    getata_dist analog.  Every row of A is a clique over its column
    support; rows longer than dense_row (when > 0) are dropped."""
    from superlu_dist_tpu import native
    out = native.ata_pattern(n_rows, n_cols, indptr, indices, dense_row)
    if out is not None:
        return out
    adj = [set() for _ in range(n_cols)]
    for r in range(n_rows):
        cols = list(set(int(j) for j in indices[indptr[r]:indptr[r + 1]]))
        if len(cols) <= 1 or (dense_row > 0 and len(cols) > dense_row):
            continue
        cs = set(cols)
        for j in cols:
            adj[j].update(cs - {j})
    out_ptr = np.zeros(n_cols + 1, dtype=np.int64)
    out_idx = []
    for j in range(n_cols):
        s = sorted(adj[j])
        out_idx.extend(s)
        out_ptr[j + 1] = out_ptr[j] + len(s)
    return out_ptr, np.asarray(out_idx, dtype=np.int64)
