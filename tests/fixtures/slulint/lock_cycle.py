"""SLU109 true-positive fixture: the two methods acquire the same two
locks in opposite orders — two threads entering from different ends
deadlock."""
import threading


class Pair:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self.x = 0
        self.y = 0

    def forward(self):
        with self._a:
            with self._b:
                return self.x + self.y

    def backward(self):
        with self._b:
            with self._a:
                self.x += 1
