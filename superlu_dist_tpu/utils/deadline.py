"""Cooperative deadlines for the factor loop.

``Options.deadline_s`` / ``SLU_TPU_DEADLINE_S`` bound how long a
factorization may run.  The check is COOPERATIVE: the streamed executor
polls between dispatch groups (the natural consistent-state boundary),
writes a checkpoint of the completed frontier first (when checkpointing
is armed), and raises a structured
:class:`~superlu_dist_tpu.utils.errors.DeadlineExceededError` — never a
mid-kernel abort, so the durable state is always a clean group boundary.

Multi-rank discipline (SLU101/SLU106): on the distributed tier every
rank runs the same SPMD group loop, so the polls line up 1:1 across
ranks.  With a ``comm`` (a TreeComm), each poll allreduces an
expired-flag — the DECISION is collective, so either every rank raises
together or none does.  A single rank noticing its local clock and
bailing out alone would strand its peers inside the next collective
(the exact deadlock family SLU_TPU_VERIFY_COLLECTIVES exists to
convert into diagnoses); the flag allreduce makes that impossible by
construction, and runs clean UNDER verification since every rank enters
the identical allreduce sequence.
"""

from __future__ import annotations

import time

import numpy as np

from superlu_dist_tpu.utils.errors import DeadlineExceededError


class Deadline:
    """One factorization's deadline clock.

    ``comm`` (optional, anything with ``allreduce_sum_any``) makes every
    poll collective; ``poll_every`` thins the collective exchanges to
    one per N polls (the LOCAL clock is still read every poll, but a
    lone rank never acts on it — expiry is latched and only honored at
    the next collective exchange).  All ranks must construct with the
    same ``poll_every``.
    """

    def __init__(self, seconds: float, comm=None, poll_every: int = 1):
        self.seconds = float(seconds)
        self.comm = comm
        self.poll_every = max(int(poll_every), 1)
        self.t0 = time.perf_counter()
        self.polls = 0

    def elapsed(self) -> float:
        return time.perf_counter() - self.t0

    def expired_local(self) -> bool:
        return self.elapsed() > self.seconds

    def poll(self, where: str = "", on_expire=None) -> None:
        """Check the deadline at a consistent-state boundary.

        ``on_expire`` runs BEFORE the raise (the checkpoint-flush hook);
        its return value, if truthy, becomes ``checkpoint_path`` on the
        error.  With a comm, the exchange (and therefore the raise) is
        collective — identical on every rank."""
        self.polls += 1
        local = self.expired_local()
        if self.comm is None:
            if not local:
                return
            expired = 1
        else:
            if self.polls % self.poll_every:
                return
            flag = np.zeros(1)
            flag[0] = 1.0 if local else 0.0
            expired = int(self.comm.allreduce_sum_any(flag)[0])
            if expired == 0:
                return
        path = None
        if on_expire is not None:
            try:
                path = on_expire()
            except Exception:
                path = None
        raise DeadlineExceededError(
            deadline_s=self.seconds, elapsed_s=self.elapsed(), where=where,
            checkpoint_path=path, expired_ranks=expired)
