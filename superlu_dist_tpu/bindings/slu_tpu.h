/*
 * C API for the TPU-native SuperLU_DIST framework.
 *
 * Capability analog of the reference's C-callable library API (pdgssvx,
 * SRC/pdgssvx.c:505) and of its handle-based Fortran wrapper layer
 * (FORTRAN/superlu_c2f_dwrap.c:51-327): C and Fortran programs solve
 * sparse A X = B through a solver runtime hosted in an embedded Python
 * interpreter that drives the JAX/XLA compute path.  Factorization
 * handles give the reference's Fact-reuse tiers (FACTORED re-solves).
 *
 * Matrix input: CSR with int64 indices (the XSDK 64-bit index build of the
 * reference), double values.  Right-hand sides and solutions are
 * column-major (Fortran order), n x nrhs.
 *
 * Fortran usage (ISO_C_BINDING): see superlu_mod.f90 next to this header.
 *
 * Link:  cc app.c -lslu_tpu $(python3-config --embed --ldflags)
 *        with libslu_tpu.so built by bindings/build.py.
 *
 * All functions return 0 on success; > 0 mirrors pdgssvx's info (first
 * zero pivot, 1-based); < 0 is a runtime/usage error.
 */

#ifndef SLU_TPU_H
#define SLU_TPU_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* Start the embedded solver runtime (idempotent).  backend may be NULL
 * (session default), "cpu", or "tpu". */
int slu_tpu_init(const char* backend);

/* One-shot expert solve: equilibrate + row-permute + order + factor +
 * solve + refine (the pdgssvx pipeline). */
int slu_tpu_solve(int64_t n, int64_t nnz, const int64_t* indptr,
                  const int64_t* indices, const double* values,
                  const double* b, double* x, int64_t nrhs);

/* Factor once, keep a handle (the dLUstruct_t analog held by the
 * runtime); returns 0 and sets *handle on success. */
int slu_tpu_factor(int64_t n, int64_t nnz, const int64_t* indptr,
                   const int64_t* indices, const double* values,
                   int64_t* handle);

/* Re-solve with an existing factorization (Fact=FACTORED tier). */
int slu_tpu_solve_factored(int64_t handle, int64_t n, const double* b,
                           double* x, int64_t nrhs);

/* Release a factorization handle. */
int slu_tpu_free_handle(int64_t handle);

/* Shut the runtime down.  TERMINAL for the process: CPython extension
 * modules do not survive re-initialization, so any API call after this
 * returns -4.  Only call when done with the solver for good. */
void slu_tpu_finalize(void);

#ifdef __cplusplus
}
#endif

#endif /* SLU_TPU_H */
