#!/usr/bin/env python
"""slu_top — a live console over a metrics export snapshot.

Point ``SLU_TPU_METRICS`` at a ``.json`` path in the serving process
(the registry dumps there at the fleet's observability heartbeat and at
exit), then watch it here::

    SLU_TPU_METRICS=/tmp/slu-metrics.json python serve_something.py &
    python scripts/slu_top.py /tmp/slu-metrics.json

Renders, top-like, once per ``--interval`` seconds (or a single frame
with ``--once``):

* traffic — requests / delivered columns / shed / deadline misses,
  fleet reroutes + failovers + healthy-replica count;
* serving — queue depth, batch fill, queue-wait and request-latency
  histogram means;
* latency — the always-on accounter's p50/p95/p99 gauges per (traffic
  class, nrhs bucket) (``slu_latency_*_ms``, obs/slo.py);
* SLO — per-series burn rate and ok/violating state
  (``slu_slo_burn_rate`` / ``slu_slo_ok``, armed by
  ``SLU_TPU_SLO_P99_MS`` / ``SLU_TPU_SLO_TARGETS``).

Reads ONE file; no sockets, no dependencies — the reader side of the
atomic temp+rename contract ``obs/metrics._dump`` maintains, so a frame
is never torn.  Exit 0 on ctrl-C.
"""

import argparse
import json
import os
import re
import sys
import time

_LABELS = re.compile(r'([\w.]+)="([^"]*)"')


def parse_key(key: str):
    """``name{k="v",...}`` -> (name, {labels})."""
    m = re.match(r"^([^{]+)\{(.*)\}$", key)
    if not m:
        return key, {}
    return m.group(1), dict(_LABELS.findall(m.group(2)))


def pick(table: dict, name: str):
    """All (labels, value) rows of one metric name."""
    out = []
    for key, val in table.items():
        n, labels = parse_key(key)
        if n == name:
            out.append((labels, val))
    return out


def one(table: dict, name: str, default=0.0):
    rows = pick(table, name)
    return rows[0][1] if rows else default


def hist_mean(hists: dict, name: str):
    for key, h in hists.items():
        n, _ = parse_key(key)
        if n == name and h.get("count"):
            return h["sum"] / h["count"], h["count"]
    return None, 0


def render(snap: dict, path: str) -> str:
    c = snap.get("counters", {})
    g = snap.get("gauges", {})
    h = snap.get("histograms", {})
    lines = [f"slu_top — {path} — {time.strftime('%H:%M:%S')}"]

    served = one(c, "slu_serve_requests_total") \
        + one(c, "slu_fleet_requests_total")
    lines.append(
        "traffic   requests {:>10.0f}   shed {:>7.0f}   deadline miss "
        "{:>6.0f}".format(served, one(c, "slu_serve_shed_total"),
                          one(c, "slu_serve_deadline_miss_total")))
    if pick(c, "slu_fleet_requests_total") or \
            pick(g, "slu_fleet_replicas_healthy"):
        lines.append(
            "fleet     healthy  {:>10.0f}   reroutes {:>3.0f}   "
            "failovers {:>9.0f}".format(
                one(g, "slu_fleet_replicas_healthy"),
                one(c, "slu_fleet_reroutes_total"),
                one(c, "slu_fleet_failovers_total")))

    depth = one(g, "slu_serve_queue_depth")
    fill_mean, _ = hist_mean(h, "slu_serve_batch_fill")
    wait_mean, _ = hist_mean(h, "slu_serve_queue_wait_seconds")
    req_mean, req_n = hist_mean(h, "slu_serve_request_seconds")
    lines.append(
        "serving   queue depth {:>7.0f}   batch fill {:>6s}   "
        "queue wait {:>9s}".format(
            depth,
            f"{fill_mean:.2f}" if fill_mean is not None else "-",
            f"{wait_mean * 1e3:.2f} ms" if wait_mean is not None else "-"))
    if req_mean is not None:
        lines.append(f"          request mean {req_mean * 1e3:.3f} ms "
                     f"over {req_n} requests")

    lat = {}
    for q in ("p50", "p95", "p99"):
        for labels, val in pick(g, f"slu_latency_{q}_ms"):
            key = (labels.get("class", "?"), int(labels.get("nrhs", 0)))
            lat.setdefault(key, {})[q] = val
    for labels, val in pick(g, "slu_latency_requests_total"):
        key = (labels.get("class", "?"), int(labels.get("nrhs", 0)))
        lat.setdefault(key, {})["n"] = val
    if lat:
        lines.append("latency   class    nrhs>=      n      p50 ms   "
                     "p95 ms   p99 ms")
        for (klass, nb), s in sorted(lat.items()):
            lines.append(
                "          {:<8s} {:<6d} {:>6.0f}   {:>8s} {:>8s} "
                "{:>8s}".format(
                    klass, nb, s.get("n", 0),
                    *(f"{s[q]:.3f}" if q in s else "-"
                      for q in ("p50", "p95", "p99"))))

    burn = {}
    for labels, val in pick(g, "slu_slo_burn_rate"):
        key = (labels.get("class", "?"), labels.get("nrhs", "?"))
        burn[key] = [val, None]
    for labels, val in pick(g, "slu_slo_ok"):
        key = (labels.get("class", "?"), labels.get("nrhs", "?"))
        burn.setdefault(key, [None, None])[1] = val
    if burn:
        lines.append("slo       class    nrhs>=   burn     state")
        for (klass, nb), (b, ok) in sorted(burn.items()):
            state = ("-" if ok is None
                     else ("ok" if ok else "VIOLATING"))
            lines.append(
                "          {:<8s} {:<8s} {:>6s}   {}".format(
                    klass, str(nb),
                    f"{b:.2f}" if b is not None else "-", state))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="live console over a SLU_TPU_METRICS json export")
    ap.add_argument("path", help="metrics export file "
                                 "(SLU_TPU_METRICS=<path>.json)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh period in seconds (default 2)")
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit")
    args = ap.parse_args(argv)
    while True:
        try:
            with open(args.path) as f:
                snap = json.load(f)
        except FileNotFoundError:
            frame = (f"slu_top — waiting for {args.path} "
                     "(SLU_TPU_METRICS not exporting yet?)")
        except json.JSONDecodeError:
            time.sleep(0.05)    # mid-rename; the next read is whole
            continue
        else:
            frame = render(snap, args.path)
        if args.once:
            print(frame)
            return 0
        os.system("clear" if os.name != "nt" else "cls")
        print(frame)
        try:
            time.sleep(max(args.interval, 0.1))
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
