"""Phase timing / flop statistics.

Analog of ``SuperLUStat_t`` (SRC/util_dist.h:83-96) with the per-phase
``utime[]``/``ops[]`` arrays over the PhaseType enum
(SRC/superlu_enum_consts.h:65-89), and of ``PStatPrint`` (SRC/util.c:484-534)
which reports phase seconds plus factor/solve Mflops — the baseline metric
source (BASELINE.md).
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field

import numpy as np

from superlu_dist_tpu.obs.trace import get_tracer

#: Phases, mirroring the reference's PhaseType (superlu_enum_consts.h:65-89).
PHASES = (
    "EQUIL", "ROWPERM", "COLPERM", "ETREE", "SYMBFACT", "DIST",
    "FACT", "SOLVE", "REFINE",
)

#: Comm-op kinds tracked by CommStats — the PROFlevel≥1 split
#: (the reference's COMM_DIAG/COMM_RIGHT/COMM_DOWN direction split,
#: SRC/util.c:538-630, re-expressed for the tree-collective transport).
COMM_OPS = ("bcast", "reduce", "allreduce", "bcast_bytes")


class CommStats:
    """Per-op communication counters: calls, bytes, seconds.

    Attached to every TreeComm (``tc.comm_stats``); each collective leg
    accounts at the native-call site, so chunked payloads count one call
    per chunk — the message-count analog of the reference's
    ``MSG_COUNT``/``BYTES`` gauges (superlu_defs.h SuperLUStat_t at
    PROFlevel≥1)."""

    __slots__ = ("calls", "bytes", "seconds")

    def __init__(self):
        self.calls = {op: 0 for op in COMM_OPS}
        self.bytes = {op: 0 for op in COMM_OPS}
        self.seconds = {op: 0.0 for op in COMM_OPS}

    def add(self, op: str, nbytes: int, seconds: float):
        if op not in self.calls:          # tolerate future op kinds
            self.calls[op] = 0
            self.bytes[op] = 0
            self.seconds[op] = 0.0
        self.calls[op] += 1
        self.bytes[op] += int(nbytes)
        self.seconds[op] += float(seconds)

    def totals(self) -> dict:
        """{op: {"calls": n, "bytes": b, "seconds": s}} snapshot."""
        return {op: {"calls": self.calls[op], "bytes": self.bytes[op],
                     "seconds": self.seconds[op]}
                for op in self.calls if self.calls[op]}

    def report(self) -> str:
        lines = []
        for op in self.calls:
            if not self.calls[op]:
                continue
            lines.append(
                f"    comm {op:<12s} calls {self.calls[op]:6d}  "
                f"{self.bytes[op] / 1e6:10.3f} MB  "
                f"{self.seconds[op]:8.4f} s")
        return "\n".join(lines)


@dataclass
class RungRecord:
    """One escalation-ladder action (drivers/gssvx.py): what was tried,
    why, and what it bought.  berr values are max-over-RHS componentwise
    backward errors before/after the rung."""

    name: str                     # "residual-precision" | "hiprec-factors"
                                  # | "refactor-rescale"
    detail: str = ""              # e.g. the dtype escalated to
    berr_before: float = float("inf")
    berr_after: float = float("inf")
    seconds: float = 0.0


@dataclass
class SolveReport:
    """What the solve did to earn trust — the rcond/ferr/berr outputs of
    the reference driver (pdgssvx.c's pdgscon + pdgsrfs reporting) plus
    the recovery ladder's actions.  Attached to Stats.solve_report by
    drivers/gssvx.gssvx; callers inspect it to see *what* degraded and
    *why* the answer is still trustworthy."""

    rcond: float | None = None    # Hager–Higham 1-norm estimate (pdgscon)
    ferr: list | None = None      # per-RHS normwise forward-error bounds
    berr: float | None = None     # final max-over-RHS backward error
    berr_history: list = field(default_factory=list)
    rungs: list = field(default_factory=list)     # RungRecord per escalation
    tiny_pivots: int = 0          # ReplaceTinyPivot count for THIS solve
    refine_steps: int = 0
    target: float | None = None   # the berr convergence target applied
    converged: bool = True        # final berr <= target (True w/o refine)
    finite: bool = True           # solution passed the isfinite sentinel
    factor_dtype: str = ""        # dtype of the factors the answer rests on
    gemm_precision: str = ""      # GEMM-precision ladder tier the factors
                                  # the answer rests on ran at (updated by
                                  # the gemm-precision escalation rung —
                                  # ops/dense.GEMM_PREC_LADDER)
    latency_ms: float | None = None  # end-to-end driver solve latency
                                  # (SOLVE + refine + ladder + condest),
                                  # also fed to the always-on obs/slo
                                  # accounter under class "driver"

    def summary(self) -> str:
        parts = [f"factor dtype {self.factor_dtype}" if self.factor_dtype
                 else ""]
        if self.gemm_precision:
            parts.append(f"gemm {self.gemm_precision}")
        if self.rcond is not None:
            parts.append(f"rcond {self.rcond:.3e}")
        if self.berr is not None:
            parts.append(f"berr {self.berr:.3e}")
        if self.ferr:
            parts.append(f"ferr {max(self.ferr):.3e}")
        if self.latency_ms is not None:
            parts.append(f"latency {self.latency_ms:.3f} ms")
        if self.tiny_pivots:
            parts.append(f"{self.tiny_pivots} tiny pivots replaced")
        for r in self.rungs:
            if r.berr_before == float("inf") and \
                    r.berr_after == float("inf"):
                # informational rung (e.g. resume-from-checkpoint): no
                # berr was measured around it
                parts.append(f"rung {r.name}[{r.detail}]")
            else:
                parts.append(f"rung {r.name}[{r.detail}] "
                             f"berr {r.berr_before:.2e}->{r.berr_after:.2e}")
        if not self.finite:
            parts.append("NON-FINITE")
        if not self.converged:
            parts.append("NOT CONVERGED to target")
        return "; ".join(p for p in parts if p)


@dataclass
class Stats:
    utime: dict = field(default_factory=lambda: {p: 0.0 for p in PHASES})
    ops: dict = field(default_factory=lambda: {p: 0.0 for p in PHASES})
    tiny_pivots: int = 0          # reference: stat->TinyPivots (pdgstrf2.c:226)
    refine_steps: int = 0         # reference: stat->RefineSteps
    retraces: int = 0             # unexpected jit recompiles flagged by the
                                  # stream retrace sentinel (runtime SLU106)
    peak_memory_bytes: int = 0
    current_memory_bytes: int = 0
    for_lu_bytes: int = 0         # dQuerySpace_dist analog: packed L+U
    pool_bytes: int = 0           # transient Schur update pool
    solve_report: object = None   # SolveReport of the last driver solve
    comm: dict = field(default_factory=dict)   # CommStats.totals() snapshot
    sched: dict = field(default_factory=dict)  # FactorPlan.schedule_stats()
                                  # of the last factorization (dispatch
                                  # groups before/after aggregation, mean
                                  # batch occupancy, padding factor,
                                  # critical-path length)
    compile: dict = field(default_factory=dict)   # compile-census block
                                  # of the last factorization
                                  # (obs/compilestats.COMPILE_STATS.block:
                                  # builds, seconds, persistent hits,
                                  # top shape-key buckets)
    resume: dict = field(default_factory=dict)    # checkpoint-resume
                                  # telemetry of the last factorization
                                  # (drivers/gssvx.factorize_numeric:
                                  # groups restored / total / bundle path)
    _timer_depth: dict = field(default_factory=dict, repr=False,
                               compare=False)

    @contextlib.contextmanager
    def timer(self, phase: str):
        """TIC/TOC analog (util_dist.h:135-141).

        Reentrancy-safe: drivers time coarse phases that internally call
        sub-steps timing the SAME phase (e.g. an escalation rung's
        factorize_numeric inside the outer REFINE, or symbolic_factorize
        timing ETREE inside SYMBFACT) — only the OUTERMOST enter of a
        phase accumulates, so nested time is never double-counted.
        Every enter still emits a trace span (nesting is exactly what
        the span tracer renders)."""
        depth = self._timer_depth.get(phase, 0)
        self._timer_depth[phase] = depth + 1
        t0 = time.perf_counter()
        sp = get_tracer().span(phase, cat="phase")
        sp.__enter__()
        try:
            yield
        finally:
            sp.__exit__(None, None, None)
            self._timer_depth[phase] = depth
            if depth == 0:
                self.utime[phase] = (self.utime.get(phase, 0.0)
                                     + time.perf_counter() - t0)

    # ---- cross-rank reduction (the sum-over-ranks PStatPrint) -----------
    def _pack(self) -> np.ndarray:
        """Fixed-layout stat vector for the collective reduction: every
        rank packs the same columns in the same order (phase times, phase
        ops, scalar counters, comm counters per COMM_OPS op)."""
        vals = [self.utime.get(p, 0.0) for p in PHASES]
        vals += [self.ops.get(p, 0.0) for p in PHASES]
        vals += [float(self.tiny_pivots), float(self.refine_steps),
                 float(self.peak_memory_bytes)]
        for op in COMM_OPS:
            d = self.comm.get(op, {})
            vals += [float(d.get("calls", 0)), float(d.get("bytes", 0)),
                     float(d.get("seconds", 0.0))]
        return np.asarray(vals, dtype=np.float64)

    def reduce(self, comm) -> "StatsSummary":
        """Cross-rank stat reduction — the PROFlevel PStatPrint the
        reference computes with MPI_Reduce over ranks (SRC/util.c:538-630):
        per-phase min/max/avg plus a load-balance factor (max/avg).

        ``comm`` is anything with ``n_ranks``, ``rank`` and an
        ``allreduce_sum_any(arr)`` collective (a TreeComm in production).
        COLLECTIVE: every rank must call this at the same point.  Each
        rank contributes its packed vector into its own row of an
        (n_ranks, k) matrix; one sum-allreduce gives every rank the full
        per-rank table, from which min/max/avg are exact (the tree
        transport only sums, so gather-then-reduce locally)."""
        vec = self._pack()
        mat = np.zeros((comm.n_ranks, vec.size))
        mat[comm.rank] = vec
        mat = np.asarray(comm.allreduce_sum_any(mat)).reshape(
            comm.n_ranks, vec.size)
        return StatsSummary._from_matrix(mat)

    def attach_comm(self, comm_stats: CommStats):
        """Snapshot a CommStats into this Stats (call BEFORE reduce —
        the reduction itself is comm traffic)."""
        self.comm = comm_stats.totals()
        return self

    def log_memory(self, nbytes: int):
        """Analog of log_memory (SRC/util.c:914): delta-accounting (allocs
        positive, frees negative) with a running peak."""
        self.current_memory_bytes += nbytes
        self.peak_memory_bytes = max(self.peak_memory_bytes, self.current_memory_bytes)

    def observe_memory(self, nbytes: int):
        """Replace the current gauge (the new allocation supersedes the
        previous factorization's) — keeps peak correct when one Stats is
        reused across refactorizations (the SamePattern time-stepping
        pattern)."""
        self.current_memory_bytes = nbytes
        self.peak_memory_bytes = max(self.peak_memory_bytes, nbytes)

    def gflops(self, phase: str) -> float:
        t = self.utime.get(phase, 0.0)
        return (self.ops.get(phase, 0.0) / t / 1e9) if t > 0 else 0.0

    def report(self) -> str:
        """PStatPrint analog (SRC/util.c:484-534): phase times + Mflops."""
        lines = ["**************************************************",
                 "**** Time (seconds) ****"]
        for p in PHASES:
            if self.utime.get(p, 0.0) > 0 or self.ops.get(p, 0.0) > 0:
                lines.append(f"    {p:<10s} time {self.utime.get(p, 0.0):10.4f}")
        for p in ("FACT", "SOLVE"):
            if self.ops.get(p, 0.0) > 0:
                lines.append(
                    f"    {p} flops {self.ops[p]:.6e}\tMflops {self.gflops(p) * 1e3:10.2f}")
        if self.sched:
            # dispatch-schedule telemetry (numeric/plan.py scheduler):
            # group count vs the level-lockstep partition, mean fronts
            # per dispatch, executed/structural padding, serial depth
            s = self.sched
            lines.append(
                f"    schedule {s.get('schedule', '?'):<9s} "
                f"groups {s.get('n_groups', 0):4d} "
                f"(level {s.get('n_level_groups', 0)})  "
                f"occupancy {s.get('occupancy', 0.0):6.2f}  "
                f"padding {s.get('padding_factor', 0.0):5.2f}x  "
                f"critical path {s.get('critical_path', 0)}"
                + (f"  moved {s['bytes_moved'] / 1e6:8.1f} MB"
                   if s.get("bytes_moved") else ""))
        if self.compile and self.compile.get("builds"):
            # compile census (obs/compilestats.py): what the jit builds
            # of the last factorization cost, and which shape-key
            # buckets dominated — the ROADMAP item 3 diagnostic
            c = self.compile
            lines.append(
                f"    compile  builds {c['builds']:4d}  "
                f"{c.get('seconds', 0.0):10.4f} s  "
                f"persistent hits {c.get('persistent_hits', 0)}")
            for row in c.get("census", [])[:3]:
                lines.append(
                    f"      {row['site']:<18s} {row['key']:<26s} "
                    f"x{row['n']:<3d} {row['seconds']:9.4f} s")
        if self.resume:
            # crash-consistency telemetry (persist/): this factorization
            # spliced a durable frontier instead of recomputing it
            lines.append(
                f"    resumed  {self.resume.get('groups', 0)}/"
                f"{self.resume.get('of', 0)} groups from checkpoint "
                f"{self.resume.get('path', '?')}")
        if self.tiny_pivots:
            lines.append(f"    tiny pivots replaced: {self.tiny_pivots}")
        if self.retraces:
            lines.append(f"    UNEXPECTED jit retraces: {self.retraces} "
                         "(cache-key input changed mid-run — SLU106)")
        if self.refine_steps:
            lines.append(f"    refinement steps: {self.refine_steps}")
        if self.solve_report is not None:
            lines.append(f"    solve health: {self.solve_report.summary()}")
        try:
            from superlu_dist_tpu.obs.slo import get_accounter
            lat_lines = get_accounter().report_lines()
        except Exception:
            lat_lines = []
        if lat_lines:
            # the always-on streaming latency histograms (obs/slo.py):
            # per (traffic class, nrhs bucket) quantiles — the serving
            # SLO layer's view, printed wherever Stats is printed
            lines.append("**** Latency (ms, per class / nrhs bucket) ****")
            lines.extend(lat_lines)
        if self.for_lu_bytes:
            # dQuerySpace_dist-style report (SRC/dmemory_dist.c:73)
            lines.append(f"    L\\U storage {self.for_lu_bytes / 1e6:10.2f} MB"
                         f"\tupdate pool {self.pool_bytes / 1e6:10.2f} MB")
        if self.peak_memory_bytes:
            lines.append(
                f"    peak device memory {self.peak_memory_bytes / 1e6:10.2f} MB")
        for op, d in self.comm.items():
            # the PROFlevel≥1 comm split: per-op message count / MB / time
            lines.append(f"    comm {op:<12s} calls {d['calls']:6d}  "
                         f"{d['bytes'] / 1e6:10.3f} MB  "
                         f"{d['seconds']:8.4f} s")
        lines.append("**************************************************")
        return "\n".join(lines)

    def print(self):
        print(self.report())


@dataclass
class RankStat:
    """One quantity reduced over ranks: min/max/avg/total and the
    load-balance factor max/avg (1.0 = perfectly balanced — the
    reference's PROFlevel prints the same factor per comm direction)."""

    min: float
    max: float
    avg: float
    total: float

    @property
    def balance(self) -> float:
        return self.max / self.avg if self.avg > 0 else 1.0

    @classmethod
    def of(cls, col: np.ndarray) -> "RankStat":
        return cls(min=float(col.min()), max=float(col.max()),
                   avg=float(col.mean()), total=float(col.sum()))


@dataclass
class StatsSummary:
    """Cross-rank reduction of Stats (built by Stats.reduce; identical on
    every rank, so callers may branch on it collectively)."""

    n_ranks: int
    utime: dict                   # phase -> RankStat (seconds)
    ops: dict                     # phase -> RankStat (flops)
    tiny_pivots: int              # sum over ranks
    refine_steps: int
    peak_memory_bytes: RankStat
    comm: dict                    # op -> {"calls","bytes": totals,
                                  #        "seconds": RankStat}

    @classmethod
    def _from_matrix(cls, mat: np.ndarray) -> "StatsSummary":
        n_phases = len(PHASES)
        utime = {p: RankStat.of(mat[:, i]) for i, p in enumerate(PHASES)}
        ops = {p: RankStat.of(mat[:, n_phases + i])
               for i, p in enumerate(PHASES)}
        base = 2 * n_phases
        comm = {}
        for j, op in enumerate(COMM_OPS):
            c = base + 3 + 3 * j
            if mat[:, c].sum() > 0:
                comm[op] = {"calls": int(mat[:, c].sum()),
                            "bytes": int(mat[:, c + 1].sum()),
                            "seconds": RankStat.of(mat[:, c + 2])}
        return cls(n_ranks=mat.shape[0], utime=utime, ops=ops,
                   tiny_pivots=int(mat[:, base].sum()),
                   refine_steps=int(mat[:, base + 1].sum()),
                   peak_memory_bytes=RankStat.of(mat[:, base + 2]),
                   comm=comm)

    def balance(self, phase: str) -> float:
        """Load-balance factor max/avg for one phase."""
        return self.utime[phase].balance

    def report(self) -> str:
        """The sum-over-ranks PStatPrint (SRC/util.c:538-630 at
        PROFlevel≥1): per-phase min/max/avg seconds + balance factor."""
        lines = ["**************************************************",
                 f"**** Cross-rank statistics over {self.n_ranks} "
                 "ranks ****",
                 f"    {'phase':<10s} {'min':>10s} {'max':>10s} "
                 f"{'avg':>10s} {'balance':>8s}"]
        for p in PHASES:
            s = self.utime[p]
            if s.max > 0 or self.ops[p].max > 0:
                lines.append(f"    {p:<10s} {s.min:10.4f} {s.max:10.4f} "
                             f"{s.avg:10.4f} {s.balance:8.2f}")
        for p in ("FACT", "SOLVE"):
            o = self.ops[p]
            t = self.utime[p]
            if o.total > 0 and t.max > 0:
                lines.append(f"    {p} flops {o.total:.6e}\t"
                             f"Mflops {o.total / t.max / 1e6:10.2f}")
        if self.tiny_pivots:
            lines.append(f"    tiny pivots replaced: {self.tiny_pivots}")
        if self.refine_steps:
            lines.append(f"    refinement steps: {self.refine_steps}")
        if self.peak_memory_bytes.max > 0:
            m = self.peak_memory_bytes
            lines.append(f"    peak device memory max {m.max / 1e6:.2f} MB"
                         f"  avg {m.avg / 1e6:.2f} MB")
        for op, d in self.comm.items():
            s = d["seconds"]
            lines.append(f"    comm {op:<12s} calls {d['calls']:6d}  "
                         f"{d['bytes'] / 1e6:10.3f} MB  "
                         f"max {s.max:8.4f} s  balance {s.balance:5.2f}")
        lines.append("**************************************************")
        return "\n".join(lines)
