/*
 * Embedded-Python implementation of the slu_tpu C API (see slu_tpu.h).
 *
 * Architecture: like the reference's Fortran wrapper layer
 * (FORTRAN/superlu_c2f_dwrap.c:51-327), this file is a thin marshalling
 * shim over the real solver — there the C library, here the Python package
 * driving JAX/XLA.  The interpreter is initialized once; the bootstrap
 * imports superlu_dist_tpu.bindings.capi_impl, which views the caller's
 * buffers through ctypes (zero-copy in, one copy out into the caller's x)
 * and keeps handle registries of live factorizations and option structs
 * (the reference's factors[] handle array).
 */

#include "slu_tpu.h"

#include <Python.h>
#include <stdio.h>
#include <string.h>

static int g_ready = 0;
static int g_finalized = 0;

static const char* kBootstrap =
    "import superlu_dist_tpu.bindings.capi_impl as _slu_impl\n";

int slu_tpu_init(const char* backend) {
  if (g_ready) return 0;
  if (g_finalized) return -4;   /* CPython extension modules (numpy) do not
                                 * survive re-initialization — finalize is
                                 * terminal for this process */
  if (!Py_IsInitialized()) Py_InitializeEx(0);
  if (backend && backend[0]) {
    char buf[256];
    snprintf(buf, sizeof buf,
             "import jax\n"
             "jax.config.update('jax_platforms', '%s')\n"
             "jax.config.update('jax_enable_x64', True)\n",
             backend);
    if (PyRun_SimpleString(buf) != 0) return -1;
  }
  if (PyRun_SimpleString(kBootstrap) != 0) return -1;
  g_ready = 1;
  return 0;
}

static int ensure_ready(void) {
  if (g_ready) return 0;
  int rc = slu_tpu_init(NULL);
  return rc == 0 ? 0 : (rc < 0 ? rc : -2);
}

static PyObject* get_fn(const char* name) {
  PyObject* mod = PyImport_ImportModule("superlu_dist_tpu.bindings.capi_impl");
  if (!mod) {
    PyErr_Print();
    return NULL;
  }
  PyObject* fn = PyObject_GetAttrString(mod, name);
  Py_DECREF(mod);
  return fn;
}

/* Call an impl function returning a PyObject*, or NULL on failure. */
static PyObject* call_obj(const char* name, const char* fmt, va_list ap) {
  PyObject* fn = get_fn(name);
  if (!fn) return NULL;
  PyObject* args = Py_VaBuildValue(fmt, ap);
  if (!args) {
    Py_DECREF(fn);
    return NULL;
  }
  PyObject* res = PyObject_CallObject(fn, args);
  Py_DECREF(args);
  Py_DECREF(fn);
  if (!res) PyErr_Print();
  return res;
}

static int call_int(const char* name, const char* fmt, ...) {
  int rc = ensure_ready();
  if (rc != 0) return rc;
  va_list ap;
  va_start(ap, fmt);
  PyObject* res = call_obj(name, fmt, ap);
  va_end(ap);
  if (!res) return -2;
  long v = PyLong_AsLong(res);
  Py_DECREF(res);
  return (int)v;
}

/* int status + int64 out-handle, for (info, handle) tuple returns */
static int call_int_handle(const char* name, int64_t* out, const char* fmt,
                           ...) {
  int rc = ensure_ready();
  if (rc != 0) return rc;
  va_list ap;
  va_start(ap, fmt);
  PyObject* res = call_obj(name, fmt, ap);
  va_end(ap);
  if (!res) return -2;
  int info = -2;
  long long h = 0;
  if (PyArg_ParseTuple(res, "iL", &info, &h)) *out = (int64_t)h;
  Py_DECREF(res);
  return info;
}

/* ---- narrow legacy surface (ABI-stable since round 3) ------------------- */

int slu_tpu_solve(int64_t n, int64_t nnz, const int64_t* indptr,
                  const int64_t* indices, const double* values,
                  const double* b, double* x, int64_t nrhs) {
  return call_int("solve", "(LLLLLLLL)", (long long)n, (long long)nnz,
                  (long long)(intptr_t)indptr, (long long)(intptr_t)indices,
                  (long long)(intptr_t)values, (long long)(intptr_t)b,
                  (long long)(intptr_t)x, (long long)nrhs);
}

int slu_tpu_factor(int64_t n, int64_t nnz, const int64_t* indptr,
                   const int64_t* indices, const double* values,
                   int64_t* handle) {
  return call_int_handle("factor", handle, "(LLLLL)", (long long)n,
                         (long long)nnz, (long long)(intptr_t)indptr,
                         (long long)(intptr_t)indices,
                         (long long)(intptr_t)values);
}

int slu_tpu_solve_factored(int64_t handle, int64_t n, const double* b,
                           double* x, int64_t nrhs) {
  return call_int("solve_factored", "(LLLLL)", (long long)handle,
                  (long long)n, (long long)(intptr_t)b,
                  (long long)(intptr_t)x, (long long)nrhs);
}

int slu_tpu_free_handle(int64_t handle) {
  return call_int("free", "(L)", (long long)handle);
}

/* ---- options registry (superlu_c2f_dwrap options block analog) ---------- */

int slu_tpu_options_create(int64_t* opt) {
  int rc = ensure_ready();
  if (rc != 0) return rc;
  int h = call_int("opt_create", "()");
  if (h <= 0) return h < 0 ? h : -2;
  *opt = h;
  return 0;
}

int slu_tpu_options_set(int64_t opt, const char* key, const char* value) {
  return call_int("opt_set", "(Lss)", (long long)opt, key, value);
}

int slu_tpu_options_get(int64_t opt, const char* key, char* buf,
                        int64_t buflen) {
  int rc = ensure_ready();
  if (rc != 0) return rc;
  PyObject* fn = get_fn("opt_get");
  if (!fn) return -2;
  PyObject* res = PyObject_CallFunction(fn, "(Ls)", (long long)opt, key);
  Py_DECREF(fn);
  if (!res) {
    PyErr_Print();
    return -2;
  }
  if (PyLong_Check(res)) {       /* int error code: -3 bad handle,
                                  * -5 unknown key */
    int rc2 = (int)PyLong_AsLong(res);
    Py_DECREF(res);
    return rc2;
  }
  const char* s = PyUnicode_AsUTF8(res);
  if (!s || (int64_t)strlen(s) + 1 > buflen) {
    Py_DECREF(res);
    return -6;
  }
  strcpy(buf, s);
  Py_DECREF(res);
  return 0;
}

int slu_tpu_options_free(int64_t opt) {
  return call_int("opt_free", "(L)", (long long)opt);
}

/* ---- full-surface solve/factor ------------------------------------------ */

int slu_tpu_solve_opts(int64_t opt, int64_t n, int64_t nnz,
                       const int64_t* indptr, const int64_t* indices,
                       const double* values, const double* b, int64_t ldb,
                       double* x, int64_t ldx, int64_t nrhs) {
  return call_int("solve_opts", "(LLLLLLLLLLL)", (long long)opt,
                  (long long)n, (long long)nnz, (long long)(intptr_t)indptr,
                  (long long)(intptr_t)indices, (long long)(intptr_t)values,
                  (long long)(intptr_t)b, (long long)ldb,
                  (long long)(intptr_t)x, (long long)ldx, (long long)nrhs);
}

int slu_tpu_factor_opts(int64_t opt, int64_t n, int64_t nnz,
                        const int64_t* indptr, const int64_t* indices,
                        const double* values, int64_t* handle) {
  return call_int_handle("factor_opts", handle, "(LLLLLL)", (long long)opt,
                         (long long)n, (long long)nnz,
                         (long long)(intptr_t)indptr,
                         (long long)(intptr_t)indices,
                         (long long)(intptr_t)values);
}

int slu_tpu_refactor(int64_t handle, int64_t nnz, const double* values,
                     int64_t tier) {
  return call_int("refactor", "(LLLL)", (long long)handle, (long long)nnz,
                  (long long)(intptr_t)values, (long long)tier);
}

int slu_tpu_solve_factored_opts(int64_t handle, int64_t opt, int64_t n,
                                const double* b, int64_t ldb, double* x,
                                int64_t ldx, int64_t nrhs) {
  return call_int("solve_factored_opts", "(LLLLLLLL)", (long long)handle,
                  (long long)opt, (long long)n, (long long)(intptr_t)b,
                  (long long)ldb, (long long)(intptr_t)x, (long long)ldx,
                  (long long)nrhs);
}

/* ---- statistics (PStatPrint-class observability, SRC/util.c:484-534) ---- */

int slu_tpu_stat_get(int64_t handle, const char* name, double* value) {
  int rc = ensure_ready();
  if (rc != 0) return rc;
  PyObject* fn = get_fn("stat_get");
  if (!fn) return -2;
  PyObject* res = PyObject_CallFunction(fn, "(Ls)", (long long)handle, name);
  Py_DECREF(fn);
  if (!res) {
    PyErr_Print();
    return -2;
  }
  if (PyLong_Check(res)) {       /* int error code: -3 bad handle */
    int rc2 = (int)PyLong_AsLong(res);
    Py_DECREF(res);
    return rc2;
  }
  double v = PyFloat_AsDouble(res);
  Py_DECREF(res);
  if (v != v) return -5;         /* NaN: unknown stat name */
  *value = v;
  return 0;
}

void slu_tpu_finalize(void) {
  if (Py_IsInitialized()) Py_FinalizeEx();
  g_ready = 0;
  g_finalized = 1;   /* terminal: further init/solve calls return -4 */
}
