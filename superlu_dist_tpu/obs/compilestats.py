"""Compile census — per-shape-key accounting of every jit build.

The n=110592 TPU factor died inside ``factor-compile`` after the 1350 s
watchdog (BENCH_r02: 119 kernels / 455 groups) and left no artifact
saying WHICH shape-key buckets ate the time.  This registry is that
artifact's source: every jit build site (``numeric/stream.py`` kernel
factories, the fused ``make_factor_fn`` program, ``solve/device.py``
sweep kernels) records one :class:`CompileRecord` per build — site,
bucket key, build seconds, arg count, and whether the persistent
XLA compile cache (``utils/jaxcache.py``) satisfied it from disk.

Measurement model: ``jax.jit`` compiles synchronously inside the FIRST
invocation for a given signature, so the executors time that first
dispatch (which they already know is a build via their own key caches)
and report it here — no second compile, no AOT staging on the hot path.
The recorded ``seconds`` therefore include trace+lower+compile plus the
(async) issue, which compile dominates by orders of magnitude on any
build that matters.  ``scripts/compile_census.py --live`` provides the
exact trace/lower/compile stage split offline, where double work is
acceptable; records carry the split when a caller measured it.

Persistent-cache attribution: ``jaxcache.enable_compile_cache`` notes
the cache directory here; each record then checks whether the build
appended a new entry file (disk MISS — XLA compiled and wrote) or not
(disk HIT — loaded).  Without a configured cache dir the flag is None.

The registry is always on: compiles are rare (O(#distinct kernels) per
process), so unlike span/metric events there is no per-event hot-path
cost to gate.  Consumers: the ``compile`` trace category
(obs/trace.py), the ``stats.compile`` block in the PStatPrint-analog
report (utils/stats.py via drivers/gssvx.factorize_numeric), the
``compile_seconds`` / ``compile_census`` fields of the bench JSON row,
flight-recorder postmortems (obs/flightrec.py), and
``scripts/compile_census.py``.
"""

from __future__ import annotations

import os
import threading

from superlu_dist_tpu.utils.lockwatch import make_lock
import time
from dataclasses import dataclass


@dataclass
class CompileRecord:
    """One jit build: where, what bucket, how long, and whether the
    persistent compile cache served it from disk."""

    site: str                 # build site, e.g. "stream._kernel"
    key: str                  # bucket key, e.g. "lu b16 m32 w16 u16"
    seconds: float            # first-invocation wall time (see module doc)
    t0: float = 0.0           # time.perf_counter() at build start
    n_args: int = 0           # kernel parameter count
    builds: int = 1           # jit programs built inside this record
    persistent_hit: bool | None = None   # disk-cache hit (None: no cache)
    trace_seconds: float | None = None   # exact stage split when the
    lower_seconds: float | None = None   # caller staged explicitly
    compile_seconds: float | None = None # (scripts/compile_census.py)


class CompileStats:
    """Process-wide compile census (module singleton ``COMPILE_STATS``).

    ``marker()`` + ``block(since=...)`` let callers account a window
    (bench's factor-compile phase, one factorize_numeric call) without
    resetting global state.
    """

    def __init__(self):
        self._lock = make_lock("CompileStats._lock")
        self.records: list[CompileRecord] = []
        self._cache_dir: str | None = None
        self._cache_entries: int | None = None
        # pending-key accounting: executors announce their FULL expected
        # kernel set at construction (they know it from the plan), and
        # record() retires keys as they build — so a watchdog firing
        # mid-compile can name the shape keys still UNCOMPILED (the
        # BENCH_r02 postmortem gap: "died in factor-compile, 119
        # kernels" with no record of which were left)
        self._announced: set = set()
        self._built: set = set()
        # program-audit notes (utils/programaudit.py, SLU_TPU_VERIFY_
        # PROGRAMS=1): per-(site, label) donation-coverage and
        # baked-const-bytes stats — empty dict when auditing never ran
        self._audits: dict = {}

    # ---- persistent-cache boundary (utils/jaxcache.py) -----------------
    def note_cache_dir(self, path: str | None) -> None:
        """jaxcache.enable_compile_cache announces the active persistent
        cache directory; subsequent records attribute disk hit/miss by
        entry-count delta."""
        with self._lock:
            self._cache_dir = path
            self._cache_entries = self._count_entries(path)

    @staticmethod
    def _count_entries(path: str | None) -> int | None:
        if not path:
            return None
        try:
            return len(os.listdir(path))
        except OSError:
            return None          # dir not created yet (first-ever compile)

    # ---- recording -----------------------------------------------------
    def record(self, site: str, key: str, t0: float, seconds: float,
               n_args: int = 0, builds: int = 1,
               trace_seconds: float | None = None,
               lower_seconds: float | None = None,
               compile_seconds: float | None = None) -> CompileRecord:
        """Account one build and emit a ``compile`` trace span (when
        tracing is on).  ``t0`` is the ``time.perf_counter()`` at build
        start so the span lands at the right trace position."""
        hit = None
        with self._lock:
            n = self._count_entries(self._cache_dir)
            if n is not None:
                if self._cache_entries is not None:
                    # no new entry file while a cache dir is live: the
                    # executable came off disk, not out of the compiler
                    hit = n <= self._cache_entries
                self._cache_entries = n
            rec = CompileRecord(site=site, key=key, seconds=float(seconds),
                                t0=float(t0), n_args=int(n_args),
                                builds=int(builds), persistent_hit=hit,
                                trace_seconds=trace_seconds,
                                lower_seconds=lower_seconds,
                                compile_seconds=compile_seconds)
            self.records.append(rec)
            self._built.add((site, key))
            self._announced.discard((site, key))
        from superlu_dist_tpu.obs.trace import get_tracer
        tr = get_tracer()
        if tr.enabled:
            tr.complete(f"compile {site}", "compile", t0, seconds,
                        key=key, n_args=int(n_args), builds=int(builds),
                        persistent_hit=hit)
        return rec

    # ---- pending-key accounting ----------------------------------------
    def announce(self, site: str, keys) -> None:
        """An executor declares the kernel keys it EXPECTS to build
        (before any of them compile).  Keys this process already built
        are not re-announced — a warmed executor re-running the same
        plan leaves nothing pending."""
        with self._lock:
            for key in keys:
                if (site, key) not in self._built:
                    self._announced.add((site, str(key)))

    def pending(self) -> list[dict]:
        """Announced-but-unbuilt kernel keys, sorted — the census delta
        a factor-compile watchdog row emits so the postmortem names the
        offending buckets (bench.py `pending_kernels`)."""
        with self._lock:
            return [{"site": s, "key": k}
                    for s, k in sorted(self._announced)]

    # ---- program-audit notes (slulint v4 runtime twin) -----------------
    def audit_note(self, site: str, key: str, stats: dict) -> None:
        """The program auditor reports one audited program's stats
        (donation coverage %, baked const bytes, finding count)."""
        with self._lock:
            self._audits[(site, key)] = dict(stats)

    def audit_block(self) -> dict:
        """Aggregate program-audit stats for the stats.compile block and
        the bench row: program count, donated/dead byte totals, overall
        donation coverage %, total baked-const bytes, plus the v6
        sharding-twin aggregates (programs_sharding_audited,
        peak_bytes_est = the worst program's static high-water mark,
        replicated_bytes = gathered/replicated traffic across all
        audited programs)."""
        with self._lock:
            audits = [dict(v) for v in self._audits.values()]
            sharding = [dict(v) for (s, k), v in self._audits.items()
                        if k.endswith("#sharding")]
        donated = sum(a.get("donated_bytes", 0) for a in audits)
        dead = sum(a.get("dead_bytes", 0) for a in audits)
        return {
            "programs": len(audits),
            "findings": sum(a.get("findings", 0) for a in audits),
            "donated_bytes": int(donated),
            "dead_bytes": int(dead),
            "donation_coverage_pct": (
                100.0 if dead == 0
                else round(100.0 * donated / dead, 2)),
            "baked_const_bytes": sum(a.get("baked_const_bytes", 0)
                                     for a in audits),
            "programs_sharding_audited": len(sharding),
            "peak_bytes_est": max(
                (a.get("peak_bytes_est", 0) for a in sharding),
                default=0),
            "replicated_bytes": sum(a.get("replicated_bytes", 0)
                                    for a in sharding),
        }

    # ---- querying ------------------------------------------------------
    # Export-path readers snapshot under the lock: a SolveServer
    # dispatcher (or scrubber postmortem) records builds concurrently
    # with a census/flightrec export, and an unlocked slice racing
    # record()/_reset() tears the window (slulint SLU108's discipline,
    # applied to this singleton by hand — it spawns no thread itself).
    def _snap(self, since: int = 0) -> list:
        with self._lock:
            return list(self.records[since:])

    def marker(self) -> int:
        """Opaque position marker for windowed accounting."""
        with self._lock:
            return len(self.records)

    def total_seconds(self, since: int = 0) -> float:
        return float(sum(r.seconds for r in self._snap(since)))

    def census(self, since: int = 0) -> list[dict]:
        """Per-(site, key) aggregation of the records after ``since``,
        sorted by total seconds descending — the "which buckets dominate
        cold-compile" table.  Rows carry ``peak_bytes_est`` (the SLU121
        static high-water estimate) when the sharding twin audited the
        matching program (``key#sharding`` audit note)."""
        agg: dict[tuple, dict] = {}
        for r in self._snap(since):
            row = agg.get((r.site, r.key))
            if row is None:
                row = agg[(r.site, r.key)] = {
                    "site": r.site, "key": r.key, "n": 0, "builds": 0,
                    "seconds": 0.0, "persistent_hits": 0, "n_args": r.n_args}
            row["n"] += 1
            row["builds"] += r.builds
            row["seconds"] += r.seconds
            row["persistent_hits"] += 1 if r.persistent_hit else 0
        with self._lock:
            peaks = {(s, k[:-len("#sharding")]): v.get("peak_bytes_est")
                     for (s, k), v in self._audits.items()
                     if k.endswith("#sharding")}
        out = sorted(agg.values(), key=lambda row: -row["seconds"])
        for row in out:
            row["seconds"] = round(row["seconds"], 4)
            peak = peaks.get((row["site"], row["key"]))
            if peak is not None:
                row["peak_bytes_est"] = int(peak)
        return out

    def block(self, since: int = 0, top: int = 8) -> dict:
        """The ``stats.compile`` block: totals plus the top buckets.

        ``fresh_seconds`` counts only builds the persistent cache did
        NOT serve from disk — the time spent actually COMPILING, which
        a bucket-set-keyed warm start drives to ~0 (``seconds`` keeps
        the first-invocation total: trace + lower + cache load)."""
        recs = self._snap(since)
        audit = self.audit_block()
        return {
            "program_audit": audit if audit["programs"] else None,
            "builds": sum(r.builds for r in recs),
            "seconds": round(sum(r.seconds for r in recs), 4),
            "fresh_seconds": round(sum(r.seconds for r in recs
                                       if not r.persistent_hit), 4),
            "persistent_hits": sum(1 for r in recs if r.persistent_hit),
            "cache_dir": self._cache_dir,
            "census": self.census(since)[:top],
        }

    def _reset(self) -> None:
        """Test hygiene: drop all records and pending announcements (the
        cache-dir note and the built-key set survive — they are
        process-wide facts, like the executors' kernel caches)."""
        with self._lock:
            self.records = []
            self._announced = set()
            self._audits = {}


COMPILE_STATS = CompileStats()


def record_build(site: str, key: str, t0: float, seconds: float,
                 **kw) -> CompileRecord:
    """Module-level convenience for the executors' build sites."""
    return COMPILE_STATS.record(site, key, t0, seconds, **kw)


def timed_build(site: str, key: str, fn, *args, n_args: int = 0, **kwargs):
    """Run ``fn(*args, **kwargs)`` (a first jit invocation) and record
    its wall time as a build.  Returns fn's result."""
    t0 = time.perf_counter()
    out = fn(*args, **kwargs)
    COMPILE_STATS.record(site, key, t0, time.perf_counter() - t0,
                         n_args=n_args)
    return out
