"""Per-backend / per-precision peak-FLOP tables for honest MFU.

The bench's historical MFU denominator was one constant
(``BENCH_PEAK_F32_TFLOPS`` = 49 TFLOP/s, a v5e figure): every CPU row
divided a few GFLOP/s by a TPU peak and printed ``mfu_pct: 0.0`` — a
number that *looks* measured and is pure noise.  This module owns the
denominator instead:

* ``SLU_TPU_PEAK_GFLOPS`` (registered knob) overrides everything — the
  operator's calibrated figure wins;
* TPU backends look up a per-device-kind, per-GEMM-tier table
  (``TPU_PEAK_GFLOPS`` — vendor bf16 figures; the ``f32``/``highest``
  tiers divide by the 3-/6-pass MXU cost, the ``default``/``bf16``
  tiers run at the native single-pass rate);
* the CPU backend (and anything unknown) CALIBRATES: one cached
  micro-GEMM per tier, timed at steady state — a measured machine-local
  peak instead of a borrowed constant.

Every consumer reports the peak's provenance alongside the percentage
(``peak_source``), so an MFU number can always be traced to the
denominator it was computed against.  ``table_peak_gflops`` is the
jax-free accessor for offline tooling (scripts/mfu_report.py) reading
rows recorded on another machine.
"""

from __future__ import annotations

import functools

from superlu_dist_tpu.utils.options import env_float

#: vendor peak dense-matmul throughput in GFLOP/s per TPU device kind
#: (matched by substring against jax's ``device_kind``, first hit wins)
#: at the bf16 native rate; reduced-precision tiers derive from it via
#: the MXU pass counts (default/bf16 = 1 pass, f32 = 3, highest = 6).
TPU_PEAK_GFLOPS = {
    "v6e": 918_000.0,
    "v6": 918_000.0,
    "v5p": 459_000.0,
    "v5e": 197_000.0,
    "v5litepod": 197_000.0,
    "v4": 275_000.0,
    "v3": 123_000.0,
    "v2": 45_000.0,
    # unrecognized TPU kinds fall back to the v5e figure — labeled as
    # such in the source string so nobody mistakes it for a measurement
    "tpu": 197_000.0,
}

#: MXU passes per GEMM tier (ops/dense.GEMM_PREC_LADDER semantics)
TIER_PASSES = {"bf16": 1, "default": 1, "f32": 3, "highest": 6}


def table_peak_gflops(device_kind: str, gemm_precision: str) -> float | None:
    """Tabulated TPU peak for one device kind + GEMM tier, or None when
    the kind matches nothing.  Pure table lookup — no jax import — for
    offline row post-processing (scripts/mfu_report.py)."""
    kind = (device_kind or "").lower()
    passes = TIER_PASSES.get(gemm_precision, 6)
    for key, bf16_peak in TPU_PEAK_GFLOPS.items():
        if key in kind:
            return bf16_peak / passes
    return None


@functools.lru_cache(maxsize=None)
def _calibrate_gflops(tier: str) -> float:
    """Measured matmul peak of THIS process's default backend at one
    GEMM tier: a steady-state timed micro-GEMM through the same
    ``ops.dense.gemm`` wrapper the factor path uses.  Cached per tier —
    one-shot cost (~100 ms) per process."""
    import time

    import jax
    import jax.numpy as jnp

    from superlu_dist_tpu.ops.dense import gemm

    n = 512
    a = jnp.ones((n, n), dtype=jnp.float32)
    fn = jax.jit(lambda x, y: gemm(x, y, tier))
    jax.block_until_ready(fn(a, a))          # compile + warm
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(a, a))
        best = min(best, time.perf_counter() - t0)
    return 2.0 * n ** 3 / max(best, 1e-9) / 1e9


def detect_peak_gflops(gemm_precision: str,
                       backend: str | None = None) -> tuple[float, str]:
    """Resolve the MFU denominator for this process: ``(gflops,
    source)`` where source names the provenance ("env", "table:<kind>",
    or "measured:<backend>").  ``SLU_TPU_PEAK_GFLOPS`` wins when set;
    TPU backends read the vendor table; everything else calibrates."""
    override = env_float("SLU_TPU_PEAK_GFLOPS")
    if override > 0:
        return float(override), "env"
    import jax
    if backend is None:
        backend = jax.default_backend()
    if backend == "tpu":
        try:
            kind = jax.devices()[0].device_kind
        except Exception:
            kind = "tpu"
        peak = table_peak_gflops(kind, gemm_precision)
        if peak is not None:
            return peak, f"table:{kind}"
    return _calibrate_gflops(gemm_precision), f"measured:{backend}"


def mfu_pct(gflops: float, gemm_precision: str,
            backend: str | None = None) -> tuple[float, float, str]:
    """(mfu_pct, peak_gflops, source) for an achieved rate — rounded to
    4 decimals so small-but-real utilizations never print as 0.0 (the
    historical honesty bug this module replaces)."""
    peak, source = detect_peak_gflops(gemm_precision, backend=backend)
    return round(100.0 * gflops / max(peak, 1e-9), 4), peak, source
