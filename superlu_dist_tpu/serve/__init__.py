from superlu_dist_tpu.serve.server import (   # noqa: F401
    SolveServer, SolveTicket)
from superlu_dist_tpu.serve.handlecache import HandleCache  # noqa: F401
from superlu_dist_tpu.serve.fleet import (    # noqa: F401
    FleetRouter, FleetTicket, ProcessReplica, ThreadReplica)
from superlu_dist_tpu.utils.errors import (   # noqa: F401
    DeployRollbackError, FactorCorruptError, PatternMismatchError,
    RefactorRollbackError, ReplicaFailureError, ServeDeadlineError,
    ServeOverloadError, ServePoisonedError, ServerClosedError)
