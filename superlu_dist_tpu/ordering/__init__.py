from superlu_dist_tpu.ordering.etree import etree_symmetric, postorder, tree_levels
from superlu_dist_tpu.ordering.dispatch import get_perm_c
