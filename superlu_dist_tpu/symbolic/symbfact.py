"""Supernodal symbolic factorization.

Capability analog of the reference's serial symbolic factorization
(symbfact, SRC/symbfact.c:80: column DFS, T2 supernode detection, relaxed
supernodes via relax_snode at :224) — redesigned for the TPU numeric phase:

* The pattern is structurally symmetrized first (sparse.formats.
  symmetrize_pattern).  Under static pivoting (GESP — no row exchanges
  during factorization, reference pdgstrf2.c:218) the LU fill of a
  symmetric pattern equals the Cholesky fill of that pattern, so the
  symbolic phase is exact and L and U share one structure (U = Lᵀ pattern),
  halving the bookkeeping.
* Row structures are computed per *supernode*, not per column: for a
  supernode with root (last) column r, the below-diagonal structure equals
  struct(r) — by the etree subset theorem struct(j)\\{parent(j)} ⊆
  struct(parent(j)), applied along the path from any member column to r.
  Bottom-up set unions over the supernode tree give O(fill)-ish work.
* Supernodes = relaxed leaf subtrees (≤ `relax` columns; reference NREL,
  sp_ienv(2)) plus zero-extra-fill chain merges capped at `max_supernode`
  (reference NSUP, sp_ienv(3)).  The merge test — child's row structure
  exactly equals parent's columns ∪ parent's rows, with contiguous column
  ranges — recovers the fundamental supernodes the reference's T2 test
  finds, at supernode granularity.

The output feeds the FactorPlan ("distribution" analog, numeric.plan) that
maps supernodes onto level-batched dense fronts for the MXU.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from superlu_dist_tpu.sparse.formats import SparseCSR, invert_perm
from superlu_dist_tpu.ordering.etree import etree_symmetric, postorder


@dataclasses.dataclass
class SymbolicFact:
    n: int
    perm: np.ndarray          # combined fill-reducing + postorder: new k <- old perm[k]
    parent: np.ndarray        # column etree in final labels
    sn_start: np.ndarray      # (ns+1,) supernode column ranges [start, end)
    col_to_sn: np.ndarray     # (n,)
    sn_rows: list             # per supernode: sorted below-diagonal rows (final labels)
    sn_parent: np.ndarray     # (ns,) parent supernode id or -1
    sn_level: np.ndarray      # (ns,) batching level (leaves 0)
    nnz_L: int                # including the dense diagonal-block lower triangle
    nnz_U: int
    flops: float              # factorization flop estimate
    pattern_indptr: np.ndarray = None    # symmetrized pattern permuted by `perm` (CSR)
    pattern_indices: np.ndarray = None
    value_perm: np.ndarray = None        # gather map: permuted-pattern values
                                         # = sym_pattern.data[value_perm]

    @property
    def n_supernodes(self) -> int:
        return len(self.sn_start) - 1

    def sn_width(self, s: int) -> int:
        return int(self.sn_start[s + 1] - self.sn_start[s])


def symbolic_factorize(sym_pattern: SparseCSR, order: np.ndarray,
                       relax: int = 20, max_supernode: int = 256,
                       stats=None, nthreads: int | None = None,
                       amalg_tol: float | None = None) -> SymbolicFact:
    """Symbolic phase on a symmetrized pattern with a fill-reducing order.

    Returns all structures in the final (order ∘ postorder) labeling.
    When `stats` is given, the etree+postorder step is timed into the ETREE
    phase (the reference times sp_colorder separately from symbfact,
    pdgssvx.c:1044-1073).

    nthreads > 1 (or SLU_TPU_SYMB_THREADS) uses the threaded native
    symbolic — the symbfact_dist capability analog (SRC/psymbfact.c:140):
    identical per-column fill, possibly different supernode chain merges
    at subtree boundaries.

    amalg_tol > 1 enables fill-tolerant supernode amalgamation
    (amalgamate_supernodes); None reads SLU_TPU_AMALG_TOL (default 1.2).
    The reference's zero-fill T2 supernodes leave median widths of ~1 on
    3D-mesh problems — CPU BLAS tolerates skinny panels, the MXU does not,
    so fill-tolerant merging is the TPU-first default.  0 disables.
    """
    import contextlib
    import os

    from superlu_dist_tpu import native

    if nthreads is None:
        from superlu_dist_tpu.utils.options import _env_int
        nthreads = _env_int("SLU_TPU_SYMB_THREADS", 1)
    if amalg_tol is None:
        from superlu_dist_tpu.utils.options import _env_float
        amalg_tol = _env_float("SLU_TPU_AMALG_TOL", 1.2)

    n = sym_pattern.n_rows
    relax = min(relax, max_supernode)

    # ---- permute, etree, postorder, combine --------------------------------
    b0 = sym_pattern.permute(order, order)
    with (stats.timer("ETREE") if stats is not None
          else contextlib.nullcontext()):
        parent0 = native.etree(n, b0.indptr, b0.indices)
        if parent0 is None:
            parent0 = etree_symmetric(n, b0.indptr, b0.indices)
        post = native.postorder(parent0)
        if post is None:
            post = postorder(parent0)
    inv_post = invert_perm(post)
    perm = np.asarray(order, dtype=np.int64)[post]
    old_parents = parent0[post]
    parent = np.where(old_parents >= 0, inv_post[np.clip(old_parents, 0, None)], -1)
    # permute once, carrying entry ids so later refactorizations can align
    # values with a single gather instead of re-permuting (SamePattern reuse)
    tracer = SparseCSR(n, n, sym_pattern.indptr, sym_pattern.indices,
                       np.arange(sym_pattern.nnz, dtype=np.int64))
    b = tracer.permute(perm, perm)
    indptr, indices, value_perm = b.indptr, b.indices, b.data

    # ---- supernode partition + row structures ------------------------------
    nat = native.symbolic(n, indptr, indices, parent, relax, max_supernode,
                          nthreads=nthreads)
    if nat is not None:
        sn_start, col_to_sn, sn_parent, sn_level, rows_ptr, rows_data = nat
        sn_rows = np.split(rows_data, rows_ptr[1:-1])
        us = np.diff(rows_ptr)
        sf = _finish(n, perm, parent, sn_start, col_to_sn, sn_rows,
                     sn_parent, sn_level, us, indptr, indices, value_perm)
        return _amalg_if(sf, amalg_tol, max_supernode)

    # ---- pure-python fallback (shared with the bordered caller) ------------
    sn_start, col_to_sn, sn_rows, sn_parent = build_supernodes_py(
        n, indptr, indices, parent, relax, max_supernode)
    sn_level = np.zeros(len(sn_rows), dtype=np.int64)
    for s in range(len(sn_rows)):
        p = sn_parent[s]
        if p >= 0:
            sn_level[p] = max(sn_level[p], sn_level[s] + 1)
    us = np.array([len(r) for r in sn_rows], dtype=np.int64)
    sf = _finish(n, perm, parent, sn_start, col_to_sn, sn_rows, sn_parent,
                 sn_level, us, indptr, indices, value_perm)
    return _amalg_if(sf, amalg_tol, max_supernode)


def build_supernodes_py(n, indptr, indices, parent, relax, max_supernode,
                        strict: bool = True):
    """Relaxed-leaf supernode partition + bottom-up row structures +
    zero-fill chain merging — the pure-python twin of the native
    symbolic_impl (native/slu_host.cpp:139).  Returns (sn_start,
    col_to_sn, sn_rows, sn_parent); sn_parent is -1 for roots (columns
    whose structure is empty or leaves the n-column range).

    strict asserts relaxed-subtree contiguity, which postordered labels
    guarantee; the bordered caller (parallel/panalysis.py) passes
    strict=False because its trailing boundary columns are only
    partially ordered — their non-contiguous subtrees then degrade to
    singleton starts, exactly like the native walk does."""
    # ---- relaxed leaf supernodes (relax_snode analog) ----------------------
    cnt = np.ones(n, dtype=np.int64)
    for j in range(n):
        p = parent[j]
        if p >= 0:
            cnt[p] += cnt[j]
    is_relaxed_root = (cnt <= relax) & np.where(
        parent >= 0, cnt[np.clip(parent, 0, None)] > relax, True)
    starts = []
    j = 0
    relaxed_roots = np.flatnonzero(is_relaxed_root)
    root_iter = iter(relaxed_roots)
    next_root = next(root_iter, None)
    while j < n:
        if not strict:
            # skip roots whose subtree window we already walked past
            # (non-postordered labels make windows overlap) BEFORE the
            # append: advancing after it re-appended the same j and
            # manufactured a zero-width duplicate supernode
            while (next_root is not None
                   and next_root - cnt[next_root] + 1 < j):
                next_root = next(root_iter, None)
        starts.append(j)
        if next_root is not None and next_root - cnt[next_root] + 1 == j:
            j = int(next_root) + 1
            next_root = next(root_iter, None)
        else:
            if strict:
                assert (next_root is None
                        or j < next_root - cnt[next_root] + 1), \
                    "relaxed subtrees must be contiguous and disjoint"
            j += 1
    starts.append(n)
    first = np.array(starts[:-1], dtype=np.int64)
    last = np.array(starts[1:], dtype=np.int64) - 1
    ns0 = len(first)
    col_to_sn0 = np.repeat(np.arange(ns0), last - first + 1)

    # ---- bottom-up structures + zero-fill chain merging --------------------
    rows_of: list = [None] * ns0
    kids: list[list[int]] = [[] for _ in range(ns0)]
    alive = np.ones(ns0, dtype=bool)
    by_last = {int(l): s for s, l in enumerate(last)}   # live supernode by last col

    for s in range(ns0):
        l = int(last[s])
        pieces = [np.empty(0, dtype=np.int64)]
        for j in range(int(first[s]), l + 1):
            rj = indices[indptr[j]:indptr[j + 1]]
            pieces.append(rj[rj > l].astype(np.int64))
        for g in kids[s]:
            rg = rows_of[g]
            pieces.append(rg[rg > l])
        rows = np.unique(np.concatenate(pieces))
        rows_of[s] = rows
        # chain-merge the supernode ending just before first[s] while the
        # merge adds no fill: rows(c) ≡ cols(s) ∪ rows(s), contiguous cols
        while True:
            c = by_last.get(int(first[s]) - 1)
            if c is None or not alive[c]:
                break
            if int(last[s]) - int(first[c]) + 1 > max_supernode:
                break
            rc = rows_of[c]
            if (len(rc) == 0 or rc[0] != first[s]
                    or len(rc) != (last[s] - first[s] + 1) + len(rows)):
                break
            del by_last[int(last[c])]
            alive[c] = False
            first[s] = first[c]
        if len(rows) and rows[0] < n:
            kids[int(col_to_sn0[rows[0]])].append(s)

    # ---- compact to live supernodes ----------------------------------------
    live = np.flatnonzero(alive)
    ns = len(live)
    sn_start = np.concatenate([first[live], [n]]).astype(np.int64)
    assert np.all(np.diff(sn_start) > 0)
    col_to_sn = np.repeat(np.arange(ns), np.diff(sn_start))
    sn_rows = [rows_of[s] for s in live]
    sn_parent = np.full(ns, -1, dtype=np.int64)
    for s in range(ns):
        if len(sn_rows[s]) and sn_rows[s][0] < n:
            sn_parent[s] = col_to_sn[sn_rows[s][0]]
        assert sn_parent[s] > s or sn_parent[s] == -1
    return sn_start, col_to_sn, sn_rows, sn_parent


def _amalg_if(sf: SymbolicFact, tol, max_width: int) -> SymbolicFact:
    if tol and tol > 1.0 and sf.n_supernodes > 1:
        return amalgamate_supernodes(sf, tol=float(tol), max_width=max_width)
    return sf


def _front_flops(w, u):
    """Dense partial-factorization flops of a front: LU(w) + two
    triangular solves (w²u each) + Schur GEMM (2wu²)."""
    w = np.asarray(w, dtype=float)
    u = np.asarray(u, dtype=float)
    return 2.0 / 3.0 * w ** 3 + 2.0 * w * w * u + 2.0 * w * u * u


def amalgamate_supernodes(sf: SymbolicFact, tol: float = 1.2,
                          max_width: int = 1024, narrow: int = 64,
                          hard_tol: float = 4.0) -> SymbolicFact:
    """Fill-tolerant supernode amalgamation (the classic multifrontal
    relaxation, applied over the whole tree rather than only at leaves as
    the reference's relax_snode does, SRC/symbfact.c:224).

    Greedily merges each supernode p with the column-adjacent supernode c
    ending exactly at p's first column when find(parent(c)) == p — i.e. the
    rightmost descendant path — while the merged front's dense flops stay
    within `tol`× the *original* (pre-amalgamation) flops of its
    constituent supernodes, or within `hard_tol`× when the merged width is
    still ≤ `narrow` (skinny supernodes are MXU-hostile enough that extra
    fill is cheaper than a rank-1-class GEMM).  Testing against original
    constituent flops (not the current pair) keeps chained merges from
    compounding: total structure flops stay ≤ max(tol, hard_tol)× the
    input structure's.  Explicit zeros are stored and factored like any
    front entry; the flop/nnz counts returned are those of the amalgamated
    structure (the reference likewise counts its relaxed-supernode zeros
    in ops[FACT]).

    Motivation (measured, 3D Poisson n=110k, ND order): unamalgamated
    median supernode width is 1 and the bucket-padded executor runs 15.7×
    the structural flops; tol=1.2 yields median width ~150, 10707→587
    supernodes, 325→13 levels, and ~1.7× padding at growth=1.3.
    """
    from superlu_dist_tpu import native
    ns = sf.n_supernodes
    start = sf.sn_start
    us0 = np.array([len(r) for r in sf.sn_rows], dtype=np.int64)
    if native.available():
        # flat marshalling is O(nnz(L)) — only worth it when the native
        # twin will actually consume it
        nat_ptr = np.zeros(ns + 1, dtype=np.int64)
        np.cumsum(us0, out=nat_ptr[1:])
        nat_data = (np.concatenate(sf.sn_rows) if ns
                    else np.empty(0, dtype=np.int64))
        nat = native.amalgamate(sf.n, start, nat_ptr, nat_data, tol,
                                max_width, narrow, hard_tol)
        if nat is not None:
            (sn_start, col_to_sn_new, sn_parent, sn_level, rows_ptr,
             rows_data) = nat
            sn_rows = np.split(rows_data, rows_ptr[1:-1])
            us = np.diff(rows_ptr)
            return _finish(sf.n, sf.perm, sf.parent, sn_start,
                           col_to_sn_new, sn_rows, sn_parent, sn_level, us,
                           sf.pattern_indptr, sf.pattern_indices,
                           sf.value_perm)
    first = start[:-1].copy()
    end = start[1:].copy()              # exclusive end column; fixed
    rows_of = list(sf.sn_rows)
    alive = np.ones(ns, dtype=bool)
    rep = np.arange(ns)
    col_to_sn = sf.col_to_sn
    # original constituent flops per live supernode (the merge budget)
    base = np.asarray(_front_flops(np.diff(start), us0), dtype=float)

    def find(s: int) -> int:
        while rep[s] != s:
            rep[s] = rep[rep[s]]
            s = rep[s]
        return s

    by_end = {int(end[s]): s for s in range(ns)}
    for p in range(ns):
        if not alive[p]:
            continue
        while True:
            c = by_end.get(int(first[p]))
            if c is None:
                break
            c = find(c)
            if not alive[c]:
                break
            rc = rows_of[c]
            if len(rc) == 0 or find(int(col_to_sn[rc[0]])) != p:
                break
            w_c = int(end[c] - first[c])
            w_p = int(end[p] - first[p])
            w_m = w_c + w_p
            if w_m > max_width:
                break
            rp = rows_of[p]
            merged = np.union1d(rc[rc >= end[p]], rp)
            fl_m = float(_front_flops(w_m, len(merged)))
            budget = base[p] + base[c]
            if not (fl_m <= tol * budget
                    or (w_m <= narrow and fl_m <= hard_tol * budget)):
                break
            del by_end[int(first[p])]
            first[p] = first[c]
            rows_of[p] = merged
            alive[c] = False
            rep[c] = p
            base[p] = budget
    live = np.flatnonzero(alive)
    old2new = -np.ones(ns, dtype=np.int64)
    old2new[live] = np.arange(len(live))
    sn_start = np.concatenate([first[live], [sf.n]]).astype(np.int64)
    col_to_sn_new = np.repeat(np.arange(len(live)), np.diff(sn_start))
    sn_rows = [rows_of[s] for s in live]
    sn_parent = np.full(len(live), -1, dtype=np.int64)
    for i in range(len(live)):
        r = sn_rows[i]
        if len(r):
            sn_parent[i] = old2new[find(int(col_to_sn[r[0]]))]
    sn_level = np.zeros(len(live), dtype=np.int64)
    for i in range(len(live)):
        p = sn_parent[i]
        if p >= 0:
            sn_level[p] = max(sn_level[p], sn_level[i] + 1)
    us = np.array([len(r) for r in sn_rows], dtype=np.int64)
    return _finish(sf.n, sf.perm, sf.parent, sn_start, col_to_sn_new,
                   sn_rows, sn_parent, sn_level, us, sf.pattern_indptr,
                   sf.pattern_indices, sf.value_perm)


def supernode_nnz(widths, us) -> tuple:
    """(nnz of the dense diagonal-block triangles, nnz of the rectangular
    panels) for supernode widths w and below-diagonal row counts u.

    Promotes to int64 BEFORE the products: w·u and w·(w+1)/2 wrap int32
    at supernode scale (w=u=50,000 → 2.5·10^9 > 2^31) even though every
    individual width/count fits easily — the int_t accumulator
    discipline (slulint SLU103), regression-tested with int32 inputs in
    tests/test_symbolic.py."""
    w = np.asarray(widths, dtype=np.int64)
    u = np.asarray(us, dtype=np.int64)
    return (int(np.sum(w * (w + 1) // 2)), int(np.sum(w * u)))


def dispatch_dependencies(sn_parent) -> np.ndarray:
    """Per-supernode count of direct dispatch dependencies for the
    dataflow scheduler (numeric/plan.py): supernode s may be dispatched
    once every child that extend-adds a Schur block into s's front has
    been dispatched in an earlier group.  Under the multifrontal
    structure every below-diagonal row of a child lies in an ancestor's
    column range and the Schur scatter targets exactly the PARENT front
    (the dscatter.c:111 analog in plan.ChildSet), so the dependency
    graph over Schur scatter targets is precisely the supernode etree —
    reachability beyond the parent is transitive through it.  Returns
    the in-degree (number of children) of each supernode."""
    sn_parent = np.asarray(sn_parent, dtype=np.int64)
    deps = np.zeros(len(sn_parent), dtype=np.int64)
    has_p = sn_parent >= 0
    np.add.at(deps, sn_parent[has_p], 1)
    return deps


def _finish(n, perm, parent, sn_start, col_to_sn, sn_rows, sn_parent,
            sn_level, us, indptr, indices, value_perm) -> SymbolicFact:
    widths = np.diff(sn_start)
    nnz_tri, nnz_rect = supernode_nnz(widths, us)
    w = np.asarray(widths, dtype=float)
    u = np.asarray(us, dtype=float)
    flops = float(np.sum(2.0 / 3.0 * w ** 3 + 2.0 * w ** 2 * u + 2.0 * w * u ** 2))
    return SymbolicFact(
        n=n, perm=perm, parent=parent, sn_start=sn_start, col_to_sn=col_to_sn,
        sn_rows=sn_rows, sn_parent=sn_parent, sn_level=sn_level,
        nnz_L=nnz_tri + nnz_rect, nnz_U=nnz_tri + nnz_rect, flops=flops,
        pattern_indptr=indptr, pattern_indices=indices, value_perm=value_perm)
