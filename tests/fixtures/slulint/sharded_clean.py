"""SLU119 clean twin of implicit_gather.py: the same shard_map shape,
but the pool stays shard-resident — the body reduces with psum (output
is shard-shaped, deliberately not a gathering primitive) and the result
keeps its P("snode") layout.  ``build(mesh)`` returns
``(jitted_fn, args)``."""
import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P


def build(mesh):
    def scale_pool(pool):
        def body(p):
            norm = jax.lax.psum(jnp.sum(jnp.abs(p)), "snode")
            return p / (norm + 1.0)
        return shard_map(body, mesh=mesh, in_specs=(P("snode"),),
                         out_specs=P("snode"))(pool)

    args = (jnp.zeros((512, 512), jnp.float32),)
    return jax.jit(scale_pool), args
