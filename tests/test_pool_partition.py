"""Partitioned Schur pool at non-toy scale (the n≈1M memory path).

`pool_partition=True` shards the 1-D Schur update pool across ALL mesh
devices, dividing its HBM footprint by the device count — the property
that lets BASELINE config 4 (n≈1M, ~27 GB pool) fit a pod slice when no
single chip can hold it (the reference's analog: no rank holds the whole
factor, SRC/pddistribute.c:322).  Toy-size validation is not enough: this
pins bit-equality with the replicated pool at n ≥ 1e5 on the 8-device
virtual mesh, where the per-device pool share is genuinely smaller than
the whole.  Compile-dominated (~4 min total on the virtual CPU mesh) —
the price of exercising the real SPMD partitioner at scale.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from superlu_dist_tpu.models.gallery import poisson2d
from superlu_dist_tpu.sparse.formats import symmetrize_pattern
from superlu_dist_tpu.utils.options import Options
from superlu_dist_tpu.ordering.dispatch import get_perm_c
from superlu_dist_tpu.symbolic.symbfact import symbolic_factorize
from superlu_dist_tpu.numeric.plan import build_plan
from superlu_dist_tpu.numeric.stream import StreamExecutor
from superlu_dist_tpu.parallel.grid import gridinit


def test_pool_partition_bit_equal_at_1e5():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh (conftest XLA_FLAGS)")
    a = poisson2d(320)                        # n = 102,400
    sym = symmetrize_pattern(a)
    col_order = get_perm_c(Options(), a, sym)
    sf = symbolic_factorize(sym, col_order, relax=128, max_supernode=512)
    plan = build_plan(sf, min_bucket=32, growth=1.3)
    grid = gridinit(4, 2)
    share = -(-plan.pool_size // grid.mesh.size)
    assert share < plan.pool_size             # partitioning is real here

    avals = jnp.asarray(sym.data[sf.value_perm], "float32")
    thresh = jnp.asarray(np.sqrt(np.finfo(np.float32).eps) * a.norm_max(),
                         "float32")
    ex_rep = StreamExecutor(plan, "float32", mesh=grid.mesh)
    rf, rt = ex_rep(avals, thresh)
    jax.block_until_ready(rf)
    ex_part = StreamExecutor(plan, "float32", mesh=grid.mesh,
                             pool_partition=True)
    pf, pt = ex_part(avals, thresh)
    jax.block_until_ready(pf)
    assert int(rt) == 0 and int(pt) == 0
    for (lp, up), (plp, pup) in zip(rf, pf):
        assert np.array_equal(np.asarray(lp), np.asarray(plp))
        assert np.array_equal(np.asarray(up), np.asarray(pup))


import pytest  # noqa: E402

# slow tier: multi-process / native-build / at-scale — fast CI runs -m "not slow"
pytestmark = pytest.mark.slow
