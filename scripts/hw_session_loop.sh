#!/bin/bash
# Re-run the idempotent hardware session until every config has its
# .hw_done marker (tunnel drops mid-compile abort single configs; the
# markers + the persistent compile cache make retries cheap — each pass
# resumes exactly where the last one died).  Bounded passes so a
# persistently-failing config (real OOM, not tunnel weather) cannot eat
# the round; 120 s between passes lets a wedged relay settle.
set -u
cd "$(dirname "$0")/.."
for pass in $(seq 1 "${HW_MAX_PASSES:-20}"); do
  echo "[hw-loop] pass $pass $(date -u +%H:%M:%S)" >&2
  bash scripts/hw_session_r3.sh
  # done when the session script's final marker set is complete: every
  # run/script_once config named in the script has a marker
  missing=0
  for m in nx48_default nx32_default nx32_profile nx32_fused nx32_level \
           nx32_prec_hi nx32_bf16 nx32_host3e7 nx32_amalg0 nx32_amalg15 \
           nx32_ms512 nx32_geo3d nx32_diaginv nx48_diaginv nx48_fused \
           nx48_prec_hi nx48_profile nx24_default nx56 nx64 nx72 nx80 \
           baseline_fixtures df64_cost; do
    [ -e ".hw_done/$m" ] || missing=$((missing + 1))
  done
  if [ "$missing" -eq 0 ]; then
    echo "[hw-loop] all markers present after pass $pass" >&2
    break
  fi
  echo "[hw-loop] $missing configs still missing" >&2
  sleep 120
done
