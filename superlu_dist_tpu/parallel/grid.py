"""2D device grid — the TPU-native analog of the reference's process grid.

The reference creates a Pr×Pc MPI grid with row/column sub-communicators
(superlu_gridinit, SRC/superlu_grid.c:31-189) and maps supernode block
(I, J) to rank (I mod Pr, J mod Pc) (superlu_defs.h:293-318).  On TPU the
grid is a `jax.sharding.Mesh` over the chips: axis "snode" distributes
independent fronts of an elimination-tree level (the task-parallel axis —
the analog of block-cyclic rows), axis "panel" splits each front's columns
(the analog of block-cyclic columns).  XLA inserts the ICI collectives that
the reference issues by hand (Isend/Irecv panels, Allreduce schedules,
pdgstrf.c:1025-1224).

Multi-host runs use the same Mesh spanning all processes' devices —
jax.distributed handles what superlu_gridmap did.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass
class ProcessGrid:
    """gridinfo_t analog (superlu_defs.h:323-349): shape + mesh handle."""

    nprow: int
    npcol: int
    mesh: Mesh

    @property
    def nproc(self) -> int:
        return self.nprow * self.npcol

    def front_sharding(self) -> NamedSharding:
        """Sharding for a (batch, m, m) level group of fronts."""
        return NamedSharding(self.mesh, P("snode", None, "panel"))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P(None))


def gridinit(nprow: int, npcol: int, devices=None) -> ProcessGrid:
    """superlu_gridinit analog (SRC/superlu_grid.c:31): carve an nprow×npcol
    mesh out of the first nprow·npcol devices."""
    if devices is None:
        devices = jax.devices()
    need = nprow * npcol
    if len(devices) < need:
        raise ValueError(
            f"grid {nprow}x{npcol} needs {need} devices, have {len(devices)}")
    dev = np.asarray(devices[:need]).reshape(nprow, npcol)
    # axis names come from the central registry (utils/meshreg.py) so the
    # runtime mesh and slulint SLU120's literal-spec vetting can never
    # disagree about what an axis is called
    from superlu_dist_tpu.utils.meshreg import require_axis
    return ProcessGrid(nprow=nprow, npcol=npcol,
                       mesh=Mesh(dev, axis_names=(require_axis("snode"),
                                                  require_axis("panel"))))


def gridmap(device_ids, nprow: int, npcol: int) -> ProcessGrid:
    """superlu_gridmap analog (SRC/superlu_grid.c:63): build the grid from an
    explicit device-id list (arbitrary subset/order), the way the reference
    lets callers map MPI ranks to grid positions."""
    by_id = {d.id: d for d in jax.devices()}
    try:
        devices = [by_id[int(i)] for i in device_ids]
    except KeyError as e:                       # pragma: no cover
        raise ValueError(f"unknown device id {e}") from e
    return gridinit(nprow, npcol, devices)


def gridinit_multihost(nprow: int, npcol: int,
                       coordinator_address: str | None = None,
                       num_processes: int | None = None,
                       process_id: int | None = None) -> ProcessGrid:
    """Multi-host grid — what superlu_gridinit over a world communicator is
    to the reference.

    Initializes jax.distributed (idempotent) so every host contributes its
    local chips to one global device list, then lays the nprow×npcol mesh
    over jax.devices() — XLA routes mesh collectives over ICI within a
    host/pod slice and DCN across, replacing the reference's MPI
    row/column subcommunicators (superlu_grid.c:137-148).  On a single
    process this degrades to gridinit.
    """
    if num_processes is not None and num_processes > 1:
        if not jax.distributed.is_initialized():
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes, process_id=process_id)
    return gridinit(nprow, npcol, jax.devices())
