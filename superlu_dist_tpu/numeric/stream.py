"""Streamed factorization executor — per-bucket kernels, async dispatch.

The whole-program jit (factor.make_factor_fn) is ideal for moderate plans,
but its HLO grows with the number of (level, bucket) groups; large matrices
produce programs that compile slowly (and the remote-compile path of the
TPU tunnel rejects oversized programs outright).  This executor instead
compiles ONE small kernel per distinct shape key and *streams* the groups
through it in level order, keeping the Schur pool resident on the device
and chaining all dispatches asynchronously (the role of the reference's
pipelined look-ahead + cuBLAS streams, SRC/pdgstrf.c:1100-1348,
dSchCompUdt-cuda.c:123-251).

Shape keys repeat because every host-built index array is padded to a
power-of-2 bucket: out-of-range scatter indices are dropped (mode='drop')
and gathers fill zeros (mode='fill'), so padding entries are no-ops.
Padded batch slots become identity fronts (ws == 0 pads the whole pivot
diagonal; LU of I = I, no tiny pivots).  Compile count is O(#distinct
keys), not O(#groups).
"""

from __future__ import annotations

import functools
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

from superlu_dist_tpu.numeric.plan import FactorPlan
from superlu_dist_tpu.numeric.factor import group_step
from superlu_dist_tpu.obs.compilestats import COMPILE_STATS
from superlu_dist_tpu.obs.metrics import get_metrics
from superlu_dist_tpu.obs.trace import NULL_TRACER, get_tracer
from superlu_dist_tpu.symbolic.symbfact import _front_flops
from superlu_dist_tpu.utils.lockwatch import make_lock
from superlu_dist_tpu.utils.options import env_flag, env_float, env_int

#: Shape keys whose first (compiling) invocation the compile census has
#: already accounted — process-wide, mirroring the lru cache on _kernel.
_CENSUSED_KEYS = set()


# Look-ahead window (the num_lookaheads analog, reference
# SRC/pdgstrf.c:624-697 + sp_ienv case 4).  The reference needs a
# dependency table + look-ahead pipeline because panels wait on MPI
# messages between ranks; here dispatch is async and every kernel is
# serialized on the donated Schur pool, so the only look-ahead that
# matters is how many groups of FACTORED PANELS may stay in flight
# device-side before their D2H offload is forced to complete — deeper =
# more compute/transfer overlap, shallower = less HBM held by panels.
# Env SLU_TPU_OFFLOAD_LAG (default 8), latched per StreamExecutor.


class RetraceSentinel:
    """Runtime recompile watchdog — the dynamic counterpart of slulint's
    SLU105 cache-key rule (part of the SLU106 runtime tier).

    The streamed executor's compile count is bounded by distinct shape
    keys, all built on the FIRST call; a warmed executor re-running the
    same plan must build ZERO new kernels.  Any rebuild after warmup
    means a cache-key input changed mid-run — an env knob
    (SLU_TPU_PIVOT_KERNEL), a mesh identity, a dtype — which is exactly
    the silent recompile axis SLU105 polices statically.  Rebuilds are
    counted process-wide, reported to stderr, surfaced as a `verify`
    trace span, and accumulated into Stats.retraces by the driver
    (drivers/gssvx.factorize_numeric)."""

    def __init__(self):
        self.total = 0            # unexpected rebuilds, process-wide
        self.events = []          # (factory, builds), bounded window
        # module-global sentinel, bumped from whichever thread ran the
        # executor (a SolveServer dispatcher, a user thread, the
        # scrubber's re-serve) — totals must not tear across them
        self._lock = make_lock("stream.RetraceSentinel._lock")

    def record(self, factory: str, builds: int, tracer=None) -> None:
        with self._lock:
            self.total += builds
            self.events = (self.events + [(factory, int(builds))])[-32:]
        print(f"[SLU106] retrace sentinel: {builds} unexpected jit kernel "
              f"build(s) in {factory} after warmup — a cache-key input "
              "(env knob, mesh identity, dtype) changed mid-run; a warmed "
              "executor expects 0 recompiles", file=sys.stderr, flush=True)
        if tracer is not None and tracer.enabled:
            tracer.complete("retrace-sentinel", "verify",
                            time.perf_counter(), 0.0,
                            factory=factory, builds=int(builds))
        m = get_metrics()
        if m.enabled:
            m.inc("slu_retraces_total", float(builds), factory=factory)


RETRACE_SENTINEL = RetraceSentinel()


def _bucket_len(n: int, lo: int = 8, base: float = 2.0) -> int:
    """Next rung of the canonical bucket ladder (plan.bucket_rung — the
    ONE ladder shared with the plan's front bucketing, so schedule
    alignment and kernel caching can never disagree about what "the same
    shape" means).  The defaults reproduce the historical pow-2 rounding;
    base=4 for index arrays whose padding costs only a cheap gather:
    coarser rungs collapse more compile keys."""
    from superlu_dist_tpu.numeric.plan import bucket_rung
    return bucket_rung(max(int(n), 1), lo=lo, growth=base)


def _pad_to(arr: np.ndarray, length: int, fill) -> np.ndarray:
    out = np.full(length, fill, dtype=np.int64)
    out[:len(arr)] = arr
    return out


@functools.lru_cache(maxsize=None)
def _kernel(dims, l_a, child_shapes, pool_size, dtype, mesh,
            pool_partition, pivot, gemm_prec="highest", pallas="off"):
    """Jitted group step for one shape key (optionally mesh-sharded).

    With a mesh, the dense factor math shards batch-over-"snode" and
    columns-over-"panel" exactly like the fused executor (make_factor_fn);
    the irregular gathers/scatters stay replicated (see factor.py notes on
    the SPMD partitioner).  This is the VERDICT-r1 gap #3: the real-TPU
    executor must be shardable where the fused whole-program jit won't
    compile.  pool_partition shards the 1-D Schur pool across all mesh
    devices (see make_factor_fn) — per-chip pool memory divides by the
    device count.
    """
    front_sharding = pivot_sharding = replicated = pool_sharding = None
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        from superlu_dist_tpu.numeric.factor import pool_spec
        front_sharding = NamedSharding(mesh, P("snode", None, "panel"))
        pivot_sharding = NamedSharding(mesh, P("snode", None, None))
        replicated = NamedSharding(mesh, P(None, None))
        pool_sharding = pool_spec(mesh, pool_partition)

    def step(avals, pool, thresh, a_slot, a_flat, a_src, ws, off, *child_arr):
        if pool_sharding is not None:
            pool = jax.lax.with_sharding_constraint(pool, pool_sharding)
        children = [(ub, child_arr[3 * i], child_arr[3 * i + 1],
                     child_arr[3 * i + 2])
                    for i, (ub, _) in enumerate(child_shapes)]
        out, pool, tiny = group_step(dims, avals, pool, thresh,
                                     a_slot, a_flat, a_src, ws, off, children,
                                     front_sharding=front_sharding,
                                     pivot_sharding=pivot_sharding,
                                     replicated=replicated, pivot=pivot,
                                     gemm_prec=gemm_prec, pallas=pallas)
        if pool_sharding is not None:
            pool = jax.lax.with_sharding_constraint(pool, pool_sharding)
        return out, pool, tiny

    # pool is threaded linearly through the group stream — donating it lets
    # XLA scatter in place instead of copying pool_size entries per group
    return jax.jit(step, donate_argnums=(1,))


class StreamExecutor:
    """Callable factorization: (avals, thresh) -> (fronts, tiny_count).

    Reusable across refactorizations with the same plan (SamePattern tier).
    """

    def __init__(self, plan: FactorPlan, dtype="float64", mesh=None,
                 offload: str = "auto", pool_partition: bool = False,
                 granularity: str = "group", host_flops=None,
                 gemm_prec=None, pallas=None):
        """offload: "none" keeps every factored panel on the device;
        "host" streams each group's (lpanel, upanel) to host memory as
        soon as it is produced (copy_to_host_async overlaps the next
        groups' compute), so device memory holds only the Schur pool plus
        the in-flight group — the factor-size wall that limits single-chip
        problem size (a 16 GB v5e holds ~n=50k padded f32 factors;
        streaming lifts that to host-RAM scale, the same reason the
        reference's GPU path keeps factors in host memory and ships only
        panels to the accelerator, dSchCompUdt-cuda.c:194-241).
        "auto" offloads iff the padded factor bytes exceed
        SLU_TPU_FRONT_BYTES_LIMIT (default 6e9) on an accelerator backend.
        """
        plan.check_index_width()
        self.plan = plan
        self.dtype = str(jnp.dtype(dtype))
        self.mesh = mesh
        self.pool_partition = bool(pool_partition and mesh is not None)
        # GEMM-precision tier + Pallas gather/scatter mode, resolved in
        # THIS uncached constructor and latched for the executor's
        # lifetime (they are part of get_executor's cache key, so a
        # changed knob yields a fresh executor — slulint SLU105)
        from superlu_dist_tpu.numeric.pallas_kernels import pallas_mode
        from superlu_dist_tpu.ops.dense import (gemm_precision,
                                                resolve_gemm_tier)
        self.gemm_prec = gemm_precision(gemm_prec)
        # the tier the arithmetic will actually RUN for this dtype
        # (bf16 degrades to default on complex) — kernel spans report
        # THIS, never a tier the math didn't use (slulint v5 satellite)
        self.gemm_prec_resolved = resolve_gemm_tier(self.gemm_prec,
                                                    self.dtype)
        # Pallas rides through under meshes too (interpret-mode on CPU
        # meshes, native on TPU) — the old "pin OFF under mesh"
        # composition debt is cleared; pallas_kernels.py emits the
        # .at[]-fallback only when a kernel genuinely can't partition
        self.pallas = pallas_mode(pallas)
        # granularity="level" traces all bucket groups sharing one
        # schedule wave (Group.level: the elimination level under
        # SLU_TPU_SCHEDULE=level, the monotone dispatch wave under the
        # dataflow scheduler — consecutive either way) into ONE jitted
        # program; group_step calls thread the pool sequentially, so
        # intra-wave dependencies the dataflow packer allows are still
        # honored.  Dispatch count drops from #groups to #waves, at the
        # cost of per-wave (mostly unique) compiles.  "group" keeps the
        # bounded compile count of one kernel per distinct shape key.
        if granularity not in ("group", "level"):
            raise ValueError(f"granularity must be 'group' or 'level', "
                             f"got {granularity!r}")
        self.granularity = granularity
        self._level_fns = {}
        if offload == "auto":
            limit = env_float("SLU_TPU_FRONT_BYTES_LIMIT")
            itemsize = jnp.dtype(dtype).itemsize
            padded = sum(
                _bucket_len(g.batch, 1) * (g.m * g.w + g.w * g.u)
                for g in plan.groups) * itemsize
            offload = ("host" if padded > limit
                       and jax.default_backend() != "cpu" else "none")
        self.offload = offload
        self.last_profile = None   # filled when SLU_TPU_PROFILE is set
        self.last_dispatch_seconds = None   # async-issue time of last call
        # time blocked materializing offloaded panels (D2H waits inside
        # the dispatch loop) — with last_dispatch_seconds this is the
        # PROFlevel comm-split analog (pdgstrf.c:1930-1951): issue /
        # transfer-wait / (the rest =) device compute
        self.last_offload_wait_seconds = None
        self._lag = env_int("SLU_TPU_OFFLOAD_LAG")
        self._tracer = NULL_TRACER   # latched from the global per call
        # non-finite sentinel (set per call by numeric_factorize): when
        # armed, every group materialized on the host mid-stream is
        # isfinite-checked so a breakdown aborts the stream at the
        # offending supernode instead of NaN-ing the remaining levels
        self.check_finite = False
        # crash-consistency hooks (set per call by numeric_factorize,
        # docs/RELIABILITY.md): a persist.checkpoint.FactorCheckpointer
        # noting every completed group, a persist.checkpoint.ResumeState
        # splicing a durable frontier in (consumed one-shot), a
        # utils.deadline.Deadline polled between dispatch groups, and a
        # testing.chaos.ChaosMonkey injector — all None on the
        # production fast path (one `is None` test per group each)
        self.checkpoint = None
        self.resume = None
        self.deadline = None
        self.chaos = None
        # retrace sentinel state (see RetraceSentinel): first call warms
        # the kernel caches; later calls must build nothing new
        self._warmed = False
        self.last_kernel_builds = 0
        self.last_retraces = 0

        # Host-share split (the reference's CPU/GPU work division:
        # gemm_division_cpu_gpu + the N_GEMM flops threshold,
        # SRC/util.c:1271-1360, sp_ienv case 7).  Leading elimination
        # levels whose every group executes fewer than `host_flops` flops
        # run on the host CPU backend — they are dispatch-latency-bound on
        # the accelerator (thousands of tiny leaf LUs cost more in kernel
        # launch + tunnel RPC than in math) — with ONE pool handoff to the
        # device where the large fronts begin.  Disabled by default
        # (host_flops=0); env SLU_TPU_HOST_FLOPS overrides.  Mesh-sharded
        # runs keep everything on the mesh.
        if host_flops is None:
            host_flops = env_float("SLU_TPU_HOST_FLOPS")
        self._host_levels = set()
        self._cpu_dev = None
        if host_flops > 0 and mesh is None:
            try:
                self._cpu_dev = jax.devices("cpu")[0]
            except RuntimeError:
                self._cpu_dev = None
        if self._cpu_dev is not None:
            lv_max = {}
            for g in plan.groups:
                fl = _bucket_len(g.batch, 1) * _front_flops(g.w, g.u)
                lv_max[g.level] = max(lv_max.get(g.level, 0.0), fl)
            for lv in sorted(lv_max):
                if lv_max[lv] < host_flops:
                    self._host_levels.add(lv)
                else:
                    break
        self.host_levels = len(self._host_levels)
        self._n_host_groups = sum(1 for g in plan.groups
                                  if g.level in self._host_levels)

        # executor-resident lengths the call loop reads (the mega
        # subclass pads both to canonical ladder rungs so its programs
        # are matrix-size-independent)
        self._pool_len = plan.pool_size
        self._steps = self._build_steps()
        self._announce_keys()

    def _build_steps(self) -> list:
        """Per-group (key, assembly arrays, child arrays, batch, on_host)
        tuples in dispatch order.  Overridden by the mega executor
        (numeric/mega.py), which packs the same metadata onto
        per-bucket-canonical shapes instead of per-group ones."""
        plan = self.plan
        n_avals = len(plan.pattern_indices)
        steps = []
        for grp in plan.groups:
            on_host = grp.level in self._host_levels
            # host-group index arrays go straight numpy -> cpu device (a
            # jnp.asarray first would bounce them through the accelerator)
            _put = ((lambda x: jax.device_put(x, self._cpu_dev))
                    if on_host else jnp.asarray)
            b = _bucket_len(grp.batch, 1)
            la = _bucket_len(len(grp.a_src), lo=64, base=4.0)
            # batch padding: slot b-? -> identity fronts via ws=0; scatter
            # slots == b are dropped; gather sources past end fill 0
            a = (_pad_to(grp.a_slot, la, b), _pad_to(grp.a_flat, la, 0),
                 _pad_to(grp.a_src, la, n_avals),
                 _pad_to(grp.ws, b, 0), _pad_to(grp.off, b, plan.pool_size))
            child_arrs = []
            child_shapes = []
            for cs in grp.children:
                c = _bucket_len(len(cs.child_off), 1, base=4.0)
                rel = np.full((c, cs.ub), grp.m, dtype=np.int64)
                rel[:len(cs.rel)] = cs.rel
                child_arrs.extend([
                    _put(_pad_to(cs.child_off, c, plan.pool_size)),
                    _put(_pad_to(cs.child_slot, c, b)),
                    _put(rel)])
                child_shapes.append((cs.ub, c))
            key = ((b, grp.m, grp.w, grp.u), la, tuple(child_shapes),
                   plan.pool_size, self.dtype)
            steps.append((key, tuple(_put(x) for x in a),
                          tuple(child_arrs), grp.batch, on_host))
        return steps

    # ---- compile-census integration (obs/compilestats.py) ---------------
    # The executor knows its FULL expected kernel set up front, so it
    # announces the per-key census labels at construction; a watchdog
    # fire mid-compile can then name the keys still PENDING (the
    # BENCH_r02 postmortem gap — 119 kernels, no record of which were
    # left).  Group granularity only: the level-traced programs are
    # per-wave aggregates with no stable per-key identity.

    _census_site = "stream._kernel"

    @staticmethod
    def _census_label(key) -> str:
        (b, m, w, u) = key[0]
        return f"lu b{b} m{m} w{w} u{u}"

    def _announce_keys(self) -> None:
        if self.granularity != "group":
            return
        COMPILE_STATS.announce(
            self._census_site,
            sorted({self._census_label(key)
                    for key, _, _, _, _ in self._steps}))

    def _get_kernel(self, key, pivot, args):
        """The jitted program for one step key.  ``args`` is the exact
        call tuple (for AOT shape derivation in the mega subclass —
        unused here: stream kernels compile inside their first call)."""
        return _kernel(*key, self.mesh, self.pool_partition, pivot,
                       self.gemm_prec, self.pallas)

    def _audit_program(self, site, label, fn, args) -> None:
        """Submit one program to the runtime IR auditor
        (SLU_TPU_VERIFY_PROGRAMS=1; no-op allocating nothing when off).
        Argnum 1 is the Schur pool — threaded linearly through the
        stream, dead after each call and donated by every kernel, which
        is exactly what SLU111 verifies."""
        from superlu_dist_tpu.utils.programaudit import maybe_audit
        maybe_audit(site, label, fn, args, dead=(1,),
                    mesh_axes=(tuple(self.mesh.axis_names)
                               if self.mesh is not None else ()))

    def _census_pending(self, key, pivot) -> bool:
        """True when this step's FIRST invocation will build (and should
        be timed into the census by the call loop)."""
        ck = ("group", key, self.mesh, self.pool_partition, pivot,
              self.gemm_prec, self.pallas)
        return ck not in _CENSUSED_KEYS

    def _census_record(self, key, pivot, t0, n_args) -> None:
        _CENSUSED_KEYS.add(("group", key, self.mesh, self.pool_partition,
                            pivot, self.gemm_prec, self.pallas))
        COMPILE_STATS.record(self._census_site, self._census_label(key),
                             t0, time.perf_counter() - t0, n_args=n_args)

    def _prep_avals(self, avals):
        """Upload/cast the pattern values (mega pads to its rung)."""
        return jnp.asarray(avals, dtype=self.dtype)

    def _ckpt_pool(self, pool):
        """The pool view a checkpoint frontier stores (mega strips its
        rung padding so frontiers stay executor-portable)."""
        return pool

    @property
    def n_kernels(self) -> int:
        if self.granularity == "level":
            return len({g.level for g in self.plan.groups})
        return len({key for key, _, _, _, _ in self._steps})

    @property
    def executed_flops(self) -> float:
        """Flops the device actually runs, bucket+batch padding included
        (plan.flops is the structural count — the reference's ops[FACT]).
        The ratio executed/structural is the padding overhead the MFU
        tuning fights (the reference's analog is its GEMM padding trick,
        dSchCompUdt-2Ddynamic.c:212-237)."""
        return float(sum(_bucket_len(g.batch, 1) * _front_flops(g.w, g.u)
                         for g in self.plan.groups))

    @staticmethod
    def _level_flat(entries) -> tuple:
        """One level's index maps + child tables flattened into the
        program-argument tuple ``_level_fn`` expects (5 assembly arrays
        then 3 per child set, per entry — the layout is static program
        STRUCTURE, the arrays are program INPUTS)."""
        return tuple(x for _, a, child_arrs, _, _ in entries
                     for x in (*a, *child_arrs))

    def _level_fn(self, level, entries):
        """One jitted program running every group of `level`.  The index
        maps are passed as program ARGUMENTS (see _level_flat), not
        closed over: a captured device array becomes a jaxpr CONSTANT,
        so the compiled program identifies the matrix — the per-matrix-
        capture pattern slulint SLU112 polices."""
        from superlu_dist_tpu.ops.dense import pivot_kernel
        pivot = pivot_kernel()    # resolved OUTSIDE the traced body: the
        # choice is the cache key (slulint SLU105); the gemm tier and
        # pallas mode are executor-lifetime constants (latched in the
        # constructor), so (level, pivot) stays a sufficient key here
        gemm_prec, pallas = self.gemm_prec, self.pallas
        fn = self._level_fns.get((level, pivot))
        if fn is not None:
            return fn
        from superlu_dist_tpu.numeric.factor import pool_spec
        psh = (pool_spec(self.mesh, self.pool_partition)
               if self.mesh is not None else None)

        front_sharding = pivot_sharding = replicated = None
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            front_sharding = NamedSharding(self.mesh,
                                           P("snode", None, "panel"))
            pivot_sharding = NamedSharding(self.mesh,
                                           P("snode", None, None))
            replicated = NamedSharding(self.mesh, P(None, None))

        metas = tuple((key, len(child_arrs))
                      for key, _, child_arrs, _, _ in entries)

        def run(avals, pool, thresh, *flat):
            outs = []
            tiny = jnp.zeros((), jnp.int32)
            i = 0
            for key, n_child in metas:
                (dims, l_a, child_shapes, _, _) = key
                a = flat[i:i + 5]
                child_arrs = flat[i + 5:i + 5 + n_child]
                i += 5 + n_child
                if psh is not None:
                    pool = jax.lax.with_sharding_constraint(pool, psh)
                children = [(ub, child_arrs[3 * j], child_arrs[3 * j + 1],
                             child_arrs[3 * j + 2])
                            for j, (ub, _) in enumerate(child_shapes)]
                out, pool, t = group_step(
                    dims, avals, pool, thresh, *a, children,
                    front_sharding=front_sharding,
                    pivot_sharding=pivot_sharding, replicated=replicated,
                    pivot=pivot, gemm_prec=gemm_prec, pallas=pallas)
                outs.append(out)
                tiny = tiny + t
            if psh is not None:
                pool = jax.lax.with_sharding_constraint(pool, psh)
            return outs, pool, tiny

        fn = jax.jit(run, donate_argnums=(1,))
        self._level_fns[(level, pivot)] = fn
        return fn

    def __call__(self, avals, thresh):
        plan = self.plan
        pool = jnp.zeros(self._pool_len, dtype=self.dtype)
        avals = self._prep_avals(avals)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            from superlu_dist_tpu.numeric.factor import pool_spec
            rep = NamedSharding(self.mesh, P(None))
            pool = jax.device_put(pool,
                                  pool_spec(self.mesh, self.pool_partition))
            avals = jax.device_put(avals, rep)
        # kernel-shape trace (the reference's PROFlevel GEMM trace,
        # pdgstrf.c:380-387 -> dgemm_mnk.dat): per-group synchronous timing.
        # NOTE: blocking per group serializes the async dispatch stream, so
        # profiled runs measure per-kernel cost, not end-to-end overlap.
        # The structured span tracer (obs/trace.py, SLU_TPU_TRACE) implies
        # profiling for the same reason: its kernel spans must sum to the
        # factor wall time, which only per-group blocking guarantees.
        self._tracer = tracer = get_tracer()
        # per-kernel blocking timing: file tracing implies it (kernel
        # spans must sum to the FACT wall time); the flight recorder
        # alone does NOT (tracer.profiling False) — its ring must not
        # serialize the async dispatch stream
        from superlu_dist_tpu.utils.options import deprecated_knob_warning
        deprecated_knob_warning(
            "SLU_TPU_PROFILE",
            "set SLU_TPU_TRACE=trace.json instead — the tracer's "
            "kernel spans carry the same per-kernel timings")
        profile = env_flag("SLU_TPU_PROFILE") or tracer.profiling
        if profile:
            self.last_profile = []
        # SLU_TPU_PROGRESS=K: log every K groups/levels issued (async
        # issue order, not completion) — hours-long runs are otherwise
        # silent between plan build and the final block_until_ready
        progress = env_int("SLU_TPU_PROGRESS")
        self._progress = max(progress, 0)
        self._offload_wait = 0.0
        builds0 = self._retrace_begin()
        if self.granularity == "level":
            return self._call_levels(avals, pool, thresh, profile, builds0)
        fronts = []
        tiny = jnp.zeros((), jnp.int32)
        t_issue0 = time.perf_counter()
        from superlu_dist_tpu.ops.dense import pivot_kernel
        pivot = pivot_kernel()
        # host-share prologue: the leading levels' kernels run on the CPU
        # device, so pool/avals/thresh start there; the first device group
        # triggers the one H2D handoff (mirrors the reference keeping the
        # leading blocks' GEMMs on the CPU while the accelerator streams,
        # dSchCompUdt-cuda.c:253-294)
        avals_dev, thresh_dev = avals, thresh
        on_host_now, avals, thresh, pool = self._host_prologue(
            avals, thresh, pool)
        tiny_host = 0
        # checkpoint resume: splice a durable frontier in — the first
        # `start` groups' panels come from the checkpoint and the pool
        # restarts from the saved boundary state, so the remaining
        # groups run the IDENTICAL arithmetic an uninterrupted run
        # would (bitwise; scripts/check_crash_resume.py pins it)
        resume, self.resume = self.resume, None
        start = tiny_resumed = 0
        if resume is not None:
            start, fronts, pool, tiny_resumed = self._apply_resume(
                resume, pool)
        for gi, (key, a, child_arrs, nreal, on_host) in \
                enumerate(self._steps):
            if gi < start:
                continue
            if self.deadline is not None:
                self._deadline_poll("streamed factorization")
            if on_host_now and not on_host:
                tiny_host, pool = self._host_handoff(tiny, pool)
                tiny = jnp.zeros((), jnp.int32)
                avals, thresh = avals_dev, thresh_dev
                on_host_now = False
            kern = self._get_kernel(key, pivot,
                                    (avals, pool, thresh, *a, *child_arrs))
            # compile census: the FIRST invocation per shape key runs the
            # synchronous trace+lower+compile inside the dispatch — time
            # it (no extra blocking; execution stays async).  The mega
            # subclass AOT-builds inside _get_kernel instead and reports
            # the exact trace/lower/compile split there.
            cold = self._census_pending(key, pivot)
            if cold:
                self._audit_program(self._census_site,
                                    self._census_label(key), kern,
                                    (avals, pool, thresh, *a, *child_arrs))
            if self._progress and gi % self._progress == 0:
                print(f"[stream] issuing group {gi}/{len(self._steps)} "
                      f"(+{time.perf_counter() - t_issue0:.1f}s)",
                      file=sys.stderr, flush=True)
            if cold or profile or tracer.enabled:
                t0 = time.perf_counter()
            (lp, up), pool, t = kern(avals, pool, thresh, *a, *child_arrs)
            if cold:
                self._census_record(key, pivot, t0,
                                    n_args=8 + len(child_arrs))
            if tracer.enabled:
                # async-issue span: how long the DISPATCH took (Python +
                # transfer setup), before any blocking — the
                # dispatch-bound-vs-compute-bound split per group
                tracer.complete(f"issue g{gi}", "dispatch", t0,
                                time.perf_counter() - t0, group=gi,
                                level=int(plan.groups[gi].level))
            if profile:
                jax.block_until_ready(lp)
                dt = time.perf_counter() - t0
                (b, m, w, u) = key[0]
                grp = plan.groups[gi]
                gflop = float(_front_flops(w, u)) * grp.batch / 1e9
                self.last_profile.append({
                    "level": grp.level, "batch": b, "m": m, "w": w, "u": u,
                    "host": on_host,
                    "seconds": dt, "gflop": gflop})
                self._trace_kernel(t0, dt, grp.level, b, m, w, u,
                                   grp.batch, on_host)
            self._emit_front(fronts, lp, up, nreal, on_host)
            tiny = tiny + t
            if self.checkpoint is not None:
                # frontier bookkeeping (interval flushes inside note);
                # BEFORE the chaos hook so an injected kill at group gi
                # leaves gi's interval checkpoint durable
                self.checkpoint.note(gi, fronts, self._ckpt_pool(pool),
                                     tiny)
            if self.chaos is not None:
                self.chaos.on_group(gi)
        tiny = tiny + tiny_host + tiny_resumed
        # dispatch-gap instrumentation (the PROFlevel comm-split analog,
        # pdgstrf.c:1930-1951): time spent ISSUING the async stream.  If
        # this approaches the end-to-end factor time, the run is
        # dispatch-bound (Python + transfer overhead), not compute-bound.
        self.last_dispatch_seconds = time.perf_counter() - t_issue0
        self.last_offload_wait_seconds = self._offload_wait
        self._retrace_end(builds0)
        return self._finalize_fronts(fronts), tiny

    def _apply_resume(self, resume, pool):
        """Validate and splice a ResumeState: returns (start, fronts,
        pool, tiny_resumed).  Mesh-sharded and host-share runs have no
        single durable pool boundary to restore into — refused."""
        from superlu_dist_tpu.utils.errors import SuperLUError
        if self.mesh is not None or self._host_levels:
            raise SuperLUError(
                "checkpoint resume is not supported on a mesh-sharded "
                "or host-share factorization — refactor from scratch")
        start = int(resume.k)
        if start > len(self._steps):
            raise SuperLUError(
                f"resume frontier k={start} exceeds this plan's "
                f"{len(self._steps)} groups")
        fronts = [(lp, up) for lp, up in resume.fronts]
        pool = jnp.asarray(resume.pool, dtype=self.dtype)
        if self.checkpoint is not None:
            self.checkpoint.tiny_base = int(resume.tiny)
        return start, fronts, pool, int(resume.tiny)

    def _deadline_poll(self, where: str) -> None:
        """Cooperative deadline check at a group boundary: the latest
        consistent frontier is flushed BEFORE the structured raise, so
        cancellation always leaves a resumable checkpoint behind (and
        on the multi-rank path the poll's flag allreduce makes the
        raise collective — see utils/deadline.py)."""
        ck = self.checkpoint
        self.deadline.poll(
            where=where,
            on_expire=(None if ck is None
                       else (lambda: ck.flush_latest("deadline"))))

    def _retrace_begin(self) -> int:
        """Kernel-build counter snapshot (per granularity's cache)."""
        if self.granularity == "level":
            return len(self._level_fns)
        return _kernel.cache_info().misses

    def _retrace_end(self, before: int) -> None:
        built = self._retrace_begin() - before
        self.last_kernel_builds = built
        self.last_retraces = 0
        if self._warmed and built:
            # a warmed executor re-ran the same plan and still compiled:
            # some cache-key input changed under us (dynamic SLU105)
            self.last_retraces = built
            RETRACE_SENTINEL.record(f"StreamExecutor[{self.granularity}]",
                                    built, self._tracer)
        self._warmed = True

    def _trace_kernel(self, t0, dt, level, b, m, w, u, nreal, host,
                      aggregate=False, executed=None, structural=None):
        """Structured kernel-shape record (the dgemm_mnk.dat analog):
        executed vs structural flops and the padding ratio per dispatch,
        so MFU attribution needs no stderr scraping."""
        tr = self._tracer
        if not tr.enabled:
            return
        if executed is None:
            executed = float(b) * _front_flops(w, u)
        if structural is None:
            structural = float(nreal) * _front_flops(w, u)
        tr.complete(f"lu b{b} m{m} w{w} u{u}", "kernel", t0, dt,
                    level=int(level), batch=int(nreal),
                    padded_batch=int(b), m=int(m), w=int(w), u=int(u),
                    gemm_prec=self.gemm_prec_resolved,
                    host=bool(host), aggregate=bool(aggregate),
                    executed_flops=float(executed),
                    structural_flops=float(structural),
                    padding=round(float(executed)
                                  / max(float(structural), 1.0), 4))

    def _host_prologue(self, avals, thresh, pool):
        """(active, avals, thresh, pool): when the plan opens with
        host-share levels, commit the stream inputs to the cpu device.
        Shared by both granularities so their handoff logic cannot
        diverge."""
        if not (self._steps and self._steps[0][4]):
            return False, avals, thresh, pool
        return (True, jax.device_put(avals, self._cpu_dev),
                jax.device_put(thresh, self._cpu_dev),
                jax.device_put(pool, self._cpu_dev))

    @staticmethod
    def _host_handoff(tiny, pool):
        """End of the host prefix: sync its tiny-pivot count on the cheap
        host stream and move the pool to the accelerator (the ONE H2D
        transfer of the split)."""
        return int(tiny), jax.device_put(np.asarray(pool))

    def _emit_front(self, fronts, lp, up, nreal, on_host=False):
        """Append one group's factored panels; in offload mode start the
        D2H transfer now (it overlaps the following kernels — the
        copy-back stream of the reference's GPU path,
        dSchCompUdt-cuda.c:238-241) and materialize with a lag window so
        the device never holds more than a few groups of panels."""
        if lp.shape[0] != nreal:
            lp, up = lp[:nreal], up[:nreal]
        if on_host:
            # host-share groups: panels already live on the cpu device;
            # keep them async here (a per-group np.asarray would block the
            # host stream) — _finalize_fronts materializes the prefix
            fronts.append((lp, up))
        elif self.offload == "host":
            lp.copy_to_host_async()
            up.copy_to_host_async()
            fronts.append((lp, up))
            i = len(fronts) - 1 - self._lag
            # the lag window must not reach into the host-share prefix:
            # materializing those cpu-device panels here would block on
            # host-stream COMPUTE (not D2H) and corrupt the comm split —
            # _finalize_fronts handles the prefix
            if i >= self._n_host_groups:
                dlp, dup = fronts[i]
                if not isinstance(dlp, np.ndarray):
                    t0 = time.perf_counter()
                    fronts[i] = (np.asarray(dlp), np.asarray(dup))
                    dt = time.perf_counter() - t0
                    self._offload_wait += dt
                    if self._tracer.enabled:
                        self._tracer.complete(
                            f"offload g{i}", "host-offload", t0, dt,
                            group=i, bytes=int(fronts[i][0].nbytes
                                               + fronts[i][1].nbytes))
                    if self.check_finite:
                        self._sentinel_check(i, *fronts[i])
        else:
            fronts.append((lp, up))

    def _sentinel_check(self, gi, lp, up):
        """Trip NumericBreakdownError if group `gi`'s materialized panels
        carry NaN/Inf — the mid-stream half of the non-finite sentinel
        (the end-of-run half lives in factor.numeric_factorize)."""
        if np.isfinite(lp).all() and np.isfinite(up).all():
            return
        from superlu_dist_tpu.utils.errors import NumericBreakdownError
        grp = self.plan.groups[gi]
        sn_start = self.plan.sf.sn_start
        nf = ~np.isfinite(lp.reshape(lp.shape[0], -1)).all(axis=1)
        nf |= ~np.isfinite(up.reshape(lp.shape[0], -1)).all(axis=1)
        sns = np.asarray(grp.sns)[np.nonzero(nf)[0]]
        sn = int(sns[np.argmin(sn_start[sns])])
        # durability before diagnosis: flush the latest consistent
        # frontier FIRST, so the error construction's flight-recorder
        # dump can reference the checkpoint it left behind
        ck_path = (self.checkpoint.flush_latest("numeric-breakdown")
                   if self.checkpoint is not None else None)
        err = NumericBreakdownError(supernode=sn, col=int(sn_start[sn]),
                                    where="streamed factorization")
        err.checkpoint_path = ck_path
        raise err

    def _finalize_fronts(self, fronts):
        if self.offload == "host" or self._n_host_groups:
            # offload mode: everything to numpy.  Host-share only: just
            # the leading host-group prefix (the trailing device fronts
            # stay resident so the device solve keeps working on them).
            fronts = [
                (lp, up) if isinstance(lp, np.ndarray)
                or (self.offload != "host" and i >= self._n_host_groups)
                else (np.asarray(lp), np.asarray(up))
                for i, (lp, up) in enumerate(fronts)]
        return tuple(fronts)

    def _call_levels(self, avals, pool, thresh, profile, builds0=0):
        """Level-granularity execution: one dispatch per elimination
        level (see __init__)."""
        import itertools
        if self.resume is not None:
            from superlu_dist_tpu.utils.errors import SuperLUError
            raise SuperLUError(
                "checkpoint resume requires granularity='group' (the "
                "level-traced programs have no per-group entry points)")
        plan = self.plan
        fronts = []
        tiny = jnp.zeros((), jnp.int32)
        pairs = list(zip(plan.groups, self._steps))
        avals_dev, thresh_dev = avals, thresh
        on_host_now, avals, thresh, pool = self._host_prologue(
            avals, thresh, pool)
        tiny_host = 0
        for level, chunk in itertools.groupby(pairs,
                                              key=lambda p: p[0].level):
            if self.deadline is not None:
                self._deadline_poll("streamed factorization")
            chunk = list(chunk)
            entries = tuple(step for _, step in chunk)
            lv_host = entries[0][4]
            if on_host_now and not lv_host:
                tiny_host, pool = self._host_handoff(tiny, pool)
                tiny = jnp.zeros((), jnp.int32)
                avals, thresh = avals_dev, thresh_dev
                on_host_now = False
            n_fns = len(self._level_fns)
            fn = self._level_fn(level, entries)
            flat = self._level_flat(entries)
            # a fresh jitted program means the next call compiles it —
            # account the build in the compile census (sync compile
            # inside the dispatch, execution stays async)
            cold = len(self._level_fns) > n_fns
            if cold:
                self._audit_program(
                    "stream._level_fn", f"level{level} g{len(entries)}",
                    fn, (avals, pool, thresh, *flat))
            if self._progress:
                print(f"[stream] issuing level {level} "
                      f"({len(entries)} groups)", file=sys.stderr,
                      flush=True)
            tracer = self._tracer
            if cold or profile or tracer.enabled:
                t0 = time.perf_counter()
            outs, pool, t = fn(avals, pool, thresh, *flat)
            if cold:
                COMPILE_STATS.record(
                    "stream._level_fn", f"level{level} g{len(entries)}",
                    t0, time.perf_counter() - t0, n_args=3)
            tiny = tiny + t
            if tracer.enabled:
                tracer.complete(f"issue lvl{level}", "dispatch", t0,
                                time.perf_counter() - t0,
                                level=int(level), groups=len(entries))
            if profile:
                jax.block_until_ready(outs)
                dt = time.perf_counter() - t0
                gflop = sum(float(_front_flops(g.w, g.u)) * g.batch
                            for g, _ in chunk) / 1e9
                # a LEVEL aggregate, not one kernel's shape: m/w/u are
                # maxima over the level's heterogeneous groups
                self.last_profile.append({
                    "level": level, "aggregate": True, "host": lv_host,
                    "batch": sum(g.batch for g, _ in chunk),
                    "m": max(g.m for g, _ in chunk),
                    "w": max(g.w for g, _ in chunk),
                    "u": max(g.u for g, _ in chunk),
                    "seconds": dt, "gflop": gflop})
                self._trace_kernel(
                    t0, dt, level,
                    sum(key[0][0] for key, *_ in entries),
                    max(g.m for g, _ in chunk),
                    max(g.w for g, _ in chunk),
                    max(g.u for g, _ in chunk),
                    sum(g.batch for g, _ in chunk), lv_host,
                    aggregate=True,
                    executed=float(sum(
                        key[0][0] * _front_flops(key[0][2], key[0][3])
                        for key, *_ in entries)),
                    structural=gflop * 1e9)
            for (grp, (_, _, _, nreal, g_host)), (lp, up) in zip(chunk, outs):
                self._emit_front(fronts, lp, up, nreal, g_host)
            if fronts:
                # wave boundary: the pool now corresponds exactly to the
                # frontier len(fronts) — the only consistent checkpoint
                # boundary this granularity has (group-mode resume can
                # still consume it: frontiers are group-aligned)
                if self.checkpoint is not None:
                    self.checkpoint.note(len(fronts) - 1, fronts,
                                         self._ckpt_pool(pool), tiny)
                if self.chaos is not None:
                    self.chaos.on_group(len(fronts) - 1)
        self.last_offload_wait_seconds = self._offload_wait
        self._retrace_end(builds0)
        return self._finalize_fronts(fronts), tiny + tiny_host
