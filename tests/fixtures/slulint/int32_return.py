"""slulint v2 acceptance fixture: int32-ness flowing through returns
and temporaries into accumulators.

PR-3's lexical SLU103 only matched a 32-bit constructor written
directly on the accumulator assignment; both shapes here keep the
constructor out of lexical sight.  The v2 dataflow pass follows the
taint — through ``_alloc``'s return via the call graph, and through the
``tmp`` temporary via the forward pass.  NOT scanned by the CI gate;
tests/test_analysis.py runs both rule tiers over this file.
"""

import numpy as np


def _alloc(n):
    # fine on its own: "indices-width" arrays may be 32-bit — it is the
    # ACCUMULATOR use at the caller that overflows
    return np.zeros(n + 1, dtype=np.int32)


def build_indptr(counts):
    indptr = _alloc(len(counts))        # v2 SLU103: i32 through the return
    np.add.at(indptr, np.arange(len(counts)) + 1, counts)
    return indptr


def build_via_temp(n):
    tmp = np.empty(n + 1, dtype=np.int32)
    indptr = tmp                        # v2 SLU103: i32 through a temporary
    return indptr


def build_promoted(counts):
    tmp = np.asarray(counts, dtype=np.int32)
    indptr = np.cumsum(tmp.astype(np.int64))   # promotion clears the taint
    return indptr
