#!/usr/bin/env python
"""Complex full-reuse tier: same pattern AND similar values — analog of
EXAMPLE/pzdrive3.c (the z-twin of pddrive3; Fact=SamePattern_SameRowPerm
reuses scalings, both permutations, the symbolic analysis and the plan;
only the numeric factorization runs on the new complex values).

    python examples/pzdrive3.py [matrix.cua] [--backend cpu]
"""

import sys
import os

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from examples._common import (pin_cpu_if_requested, load_matrix, make_rhs,
                              report)


def main():
    pin_cpu_if_requested()
    import superlu_dist_tpu as slu

    a, src = load_matrix(complex_=True)
    print(f"matrix: {src}  n={a.n_rows} nnz={a.nnz} dtype={a.data.dtype}")
    xtrue, b = make_rhs(a)
    x, lu, stats, info = slu.gssvx(slu.Options(), a, b)
    assert info == 0

    rng = np.random.default_rng(11)
    a2 = type(a)(a.n_rows, a.n_cols, a.indptr, a.indices,
                 a.data * (1.0 + 0.001 * rng.standard_normal(a.nnz)))
    xtrue2, b2 = make_rhs(a2, seed=3)
    x2, lu2, stats2, info2 = slu.gssvx(
        slu.Options(fact=slu.Fact.SamePattern_SameRowPerm), a2, b2, lu=lu)
    assert info2 == 0
    assert np.array_equal(lu2.row_order, lu.row_order), "row perm reused"
    assert np.array_equal(lu2.col_order, lu.col_order), "col order reused"
    assert lu2.sf is lu.sf and lu2.plan is lu.plan, "symbolic+plan reused"
    resid = report("pzdrive3 (SamePattern_SameRowPerm)", a2, b2, x2,
                   xtrue2, stats2)
    assert resid < 1e-10
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
