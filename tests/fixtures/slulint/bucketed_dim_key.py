"""slulint fixture: SLU107 negative — the same lru_cached jit factory
called with LADDER-ROUNDED dimensions.

The raw sizes route through a bucketing helper (a canonical-ladder
rounding like numeric/plan.bucket_rung / stream._bucket_len) before
they enter the cache key, so shapes repeat and the compiled-program
set stays bounded.  SLU107 must stay quiet here.
"""

import functools

import jax
import jax.numpy as jnp


def _bucket_len(n, lo=8, base=2.0):
    s = lo
    while s < n:
        s = int(s * base)
    return s


@functools.lru_cache(maxsize=None)
def _kern(batch, width):
    def step(x):
        padded = jnp.zeros((batch, width), x.dtype)
        padded = padded.at[:x.shape[0], :x.shape[1]].set(x)
        return jnp.sum(padded, axis=1)

    return jax.jit(step)


def run(chunks):
    outs = []
    for x in chunks:
        # GOOD: both key axes are ladder rungs — shapes repeat
        fn = _kern(_bucket_len(x.shape[0]), _bucket_len(len(x[0])))
        outs.append(fn(jnp.asarray(x)))
    return outs
