"""Multi-process expert driver (pdgssvx-with-NR_loc-input analog):
block-row distributed A and b in four real processes, tree-collective
gather to the factoring root, distributed refinement back out."""

import multiprocessing as mp
import os

import numpy as np
import pytest

from superlu_dist_tpu import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library unavailable")


def _worker(name, n_ranks, rank, part, b_loc, q):
    import jax
    jax.config.update("jax_platforms", "cpu")
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    from superlu_dist_tpu.parallel.pgssvx import pgssvx
    from superlu_dist_tpu.utils.options import Options
    with TreeComm(name, n_ranks, rank, max_len=2048, create=False) as tc:
        x, info = pgssvx(tc, Options(), part, b_loc)
        q.put((rank, info, x))


def test_pgssvx_four_processes():
    import jax
    jax.config.update("jax_platforms", "cpu")
    import superlu_dist_tpu as slu
    from superlu_dist_tpu.models.gallery import convection_diffusion_2d
    from superlu_dist_tpu.parallel.dist import distribute_rows
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    from superlu_dist_tpu.parallel.pgssvx import pgssvx

    a = convection_diffusion_2d(11)
    n = a.n_rows
    xtrue = np.random.default_rng(2).standard_normal(n)
    b = a.matvec(xtrue)

    nranks = 4
    parts = distribute_rows(a, nranks)
    b_blocks = [b[p.fst_row:p.fst_row + p.m_loc] for p in parts]

    name = f"/slu_pgssvx_{os.getpid()}"
    owner = TreeComm(name, nranks, 0, max_len=2048, create=True)
    try:
        ctx = mp.get_context("fork")
        q = ctx.Queue()
        procs = [ctx.Process(target=_worker,
                             args=(name, nranks, r, parts[r],
                                   b_blocks[r], q))
                 for r in range(1, nranks)]
        for p in procs:
            p.start()
        x, info = pgssvx(owner, slu.Options(), parts[0], b_blocks[0])
        assert info == 0
        others = [q.get(timeout=300) for _ in procs]
        for p in procs:
            p.join(timeout=300)
            assert p.exitcode == 0
    finally:
        owner.close(unlink=True)

    # serial reference through the plain driver
    x_ref, _, _, info_ref = slu.gssvx(slu.Options(), a, b)
    assert info_ref == 0
    resid = float(np.linalg.norm(b - a.matvec(x)) / np.linalg.norm(b))
    assert resid < 1e-13, resid
    np.testing.assert_allclose(x, x_ref, rtol=1e-9, atol=1e-11)
    for rank, info_r, xr in others:
        assert info_r == 0
        np.testing.assert_allclose(xr, x, rtol=0, atol=1e-12)
