"""Condition estimation and forward-error bounds on the computed factors.

Analog of ``pdgscon`` (SRC/pdgscon.c:95): estimate the reciprocal condition
number rcond = 1 / (‖A‖₁·‖A⁻¹‖₁) with the Hager–Higham 1-norm estimator
(LAPACK's dlacon/dlacn2 algorithm), using the existing triangular-solve
path as the black-box A⁻¹·v / A⁻ᴴ·v apply — the factors never leave their
resident layout.  Also the ``ferr`` half of the reference's expert-driver
reporting (sequential dgsrfs.f:363-414): a normwise forward-error bound
per right-hand side, estimated through the same machinery.

This is the *detect* half of the GESP repair loop (PAPER.md L4/L8): the
factorization traded pivoting stability for MXU speed; rcond/ferr/berr are
how the driver proves — or disproves — that the answer survived the trade.
"""

from __future__ import annotations

import numpy as np

from superlu_dist_tpu.utils import tols


def onenormest(n: int, apply, apply_adj, dtype=np.float64,
               itmax: int = 5) -> float:
    """Hager–Higham estimate of ‖Op‖₁ for a linear operator given only
    v ↦ Op·v (`apply`) and v ↦ Opᴴ·v (`apply_adj`).

    The dlacon iteration (Higham TOMS 1988): start from the uniform
    vector, follow the subgradient of ‖Op·x‖₁ uphill through adjoint
    applies, stop on repetition or stagnation; finish with the alternating
    lower bound that protects against adversarial cancellation
    (dlacon.f:160-176).  Underestimates by at most a small factor in
    practice; never overestimates the true norm by construction.
    """
    if n == 0:
        return 0.0
    cplx = np.issubdtype(np.dtype(dtype), np.complexfloating)
    x = np.full(n, 1.0 / n, dtype=dtype)
    est = 0.0
    j_old = -1
    for _ in range(itmax):
        y = np.asarray(apply(x))
        cur = float(np.abs(y).sum())
        if cur <= est:      # no growth — keep the best estimate seen
            break
        est = cur
        # subgradient: sign(y) (complex: y/|y|, 1 where y == 0)
        if cplx:
            ay = np.abs(y)
            xi = np.where(ay == 0, 1.0 + 0.0j, y / np.where(ay == 0, 1, ay))
        else:
            xi = np.where(y >= 0, 1.0, -1.0)
        z = np.asarray(apply_adj(xi.astype(dtype)))
        j = int(np.argmax(np.abs(z)))
        if np.abs(z[j]) <= np.real(z @ np.conj(x)) * (
                1 + float(tols.ONENORMEST_SLACK)):
            break           # converged: the subgradient test (dlacon.f:130)
        if j == j_old:
            break           # 2-cycle: e_j would repeat the last iterate
        j_old = j
        x = np.zeros(n, dtype=dtype)
        x[j] = 1.0
    # alternating-vector lower bound (dlacon.f:160-176)
    alt = ((-1.0) ** np.arange(n)) * (1.0 + np.arange(n) / max(n - 1, 1))
    y = np.asarray(apply(alt.astype(dtype)))
    est_alt = 2.0 * float(np.abs(y).sum()) / (3.0 * n)
    return max(est, est_alt)


def scaled_onenorm(a, R: np.ndarray, C: np.ndarray) -> float:
    """‖diag(R)·A·diag(C)‖₁ computed from the ORIGINAL matrix and the
    combined scalings (permutations do not change the 1-norm, so this is
    the norm of the factored matrix M without materializing it)."""
    rows = np.repeat(np.arange(a.n_rows), np.diff(a.indptr))
    colsum = np.zeros(a.n_cols)
    np.add.at(colsum, a.indices, np.abs(a.data) * np.abs(R)[rows])
    return float(np.max(colsum * np.abs(C))) if a.n_cols else 0.0


def condition_estimate(lu) -> float:
    """rcond of the factored (equilibrated/permuted) matrix M — the
    pdgscon analog (SRC/pdgscon.c:95).  Returns 1/(‖M‖₁·est(‖M⁻¹‖₁)),
    0.0 when the factorization is singular/non-finite, 1.0 for n == 0.

    The apply is the existing permuted-domain solve path
    (LUFactorization._solve_permuted), so on an accelerator the estimate
    rides the device solver; the adjoint apply is the transpose solve
    through the same factors (pdgscon's kase=2 branch)."""
    if lu.numeric is None or not lu.numeric.finite:
        return 0.0
    n = lu.n
    if n == 0:
        return 1.0
    anorm = scaled_onenorm(lu.a, lu.R, lu.C) if lu.a is not None else 0.0
    if anorm == 0.0:
        return 0.0
    cplx = np.issubdtype(np.dtype(lu.numeric.dtype), np.complexfloating)
    dtype = np.complex128 if cplx else np.float64

    def apply(v):
        return lu._solve_permuted(np.asarray(v, dtype=dtype))

    def apply_adj(v):
        return lu._solve_permuted_trans(np.asarray(v, dtype=dtype),
                                        conj=cplx)

    try:
        inv_norm = onenormest(n, apply, apply_adj, dtype=dtype)
    except Exception:
        return 0.0              # solve blew up => treat as singular
    if not np.isfinite(inv_norm) or inv_norm == 0.0:
        return 0.0
    return float(min(1.0, 1.0 / (anorm * inv_norm)))


def ferr_estimate(op, b: np.ndarray, x: np.ndarray, solve_fn,
                  solve_trans_fn, residual_dtype=np.float64) -> list:
    """Normwise forward-error bounds per RHS (dgsrfs.f:363-414).

    For each column: ferr_k bounds ‖x_k − x*_k‖∞/‖x_k‖∞ by estimating
    ‖A⁻¹·diag(f)‖∞ with f = |r| + nz·eps·(|A|·|x| + |b|) — the residual
    plus the rounding cloud of computing it — via the 1-norm estimator on
    the adjoint operator (‖B‖∞ = ‖Bᴴ‖₁).  `op` is the (possibly
    transposed) operator the system was solved with; solve_fn/
    solve_trans_fn apply op⁻¹ and op⁻ᴴ through the factors.
    """
    b = np.asarray(b)
    squeeze = b.ndim == 1
    b2 = b[:, None] if squeeze else b
    x2 = np.asarray(x)
    x2 = x2[:, None] if squeeze else x2
    n, nrhs = b2.shape
    eps = float(np.finfo(np.dtype(residual_dtype)).eps)
    nz = max(int(np.diff(op.indptr).max()) if op.n_rows else 0, 1) + 1
    cplx = (np.issubdtype(b2.dtype, np.complexfloating)
            or np.issubdtype(x2.dtype, np.complexfloating))
    dtype = np.complex128 if cplx else np.float64
    ferrs = []
    for k in range(nrhs):
        xk = x2[:, k].astype(dtype)
        rk = b2[:, k].astype(dtype) - op.matvec(xk)
        f = np.abs(rk) + nz * eps * (op.abs_matvec(np.abs(xk))
                                     + np.abs(b2[:, k]))
        xnorm = float(np.max(np.abs(xk))) if n else 0.0
        if xnorm == 0.0 or not np.all(np.isfinite(f)):
            ferrs.append(float("inf"))
            continue

        # ‖A⁻¹ D_f‖∞ = ‖(A⁻¹ D_f)ᴴ‖₁ = ‖D_f A⁻ᴴ‖₁
        def apply(v, f=f):
            return f * np.asarray(solve_trans_fn(np.asarray(v, dtype=dtype)))

        def apply_adj(v, f=f):
            return np.asarray(solve_fn(f * np.asarray(v, dtype=dtype)))

        try:
            est = onenormest(n, apply, apply_adj, dtype=dtype)
        except Exception:
            ferrs.append(float("inf"))
            continue
        ferrs.append(float(min(est / xnorm, 1.0 / eps)))
    return ferrs
