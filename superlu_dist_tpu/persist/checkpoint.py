"""Mid-factorization checkpoints: the durable completed-group frontier.

The streamed factor loop (numeric/stream.py) completes one dispatch
group at a time; everything up to a group boundary is a deterministic
function of (plan, values, threshold, dtype).  A checkpoint therefore
is: the factored ``(lpanel, upanel)`` pairs of the first ``k`` groups
plus the Schur pool AS OF that boundary — resuming re-runs groups
``k..`` with the restored pool and produces BITWISE-identical factors
to an uninterrupted run (scripts/check_crash_resume.py proves it with
a kill -9).

Write policy:

* every ``SLU_TPU_CKPT_EVERY`` completed groups (``Options.ckpt_every``)
  — the durable-interval tier; this blocks the async dispatch stream to
  materialize the pool, which is the price of durability (size the
  interval accordingly);
* on :class:`NumericBreakdownError` / cooperative-deadline expiry — the
  factor loop flushes the latest consistent frontier before raising
  (for a breakdown the frontier may INCLUDE the contaminated group:
  checkpoints promise crash-consistency, not numerical validity, and a
  resume against unchanged inputs deterministically reproduces the
  breakdown — while changed inputs are refused by the value digest);
* on SIGTERM / the bench watchdog — best-effort via
  :func:`flush_active`: if the signal lands mid-dispatch the live pool
  buffer may already be donated to the in-flight kernel, in which case
  the last interval checkpoint stands as the durable frontier.

Front artifacts are immutable once written (``front_00012_l.npy`` never
changes), so an advancing checkpoint only writes the NEW groups plus
the pool and manifest — the manifest replace is the commit point
(persist/serial.py crash-consistency rules).
"""

from __future__ import annotations

import dataclasses
import os
import threading

import numpy as np

from superlu_dist_tpu.persist import serial
from superlu_dist_tpu.utils.lockwatch import make_lock
from superlu_dist_tpu.utils.errors import (
    CheckpointError, CheckpointMismatchError)

# process-wide registry of live checkpointers (for signal/watchdog
# flushes) and the most recently committed checkpoint path (for
# flight-recorder postmortems to reference)
_ACTIVE: list = []
_LAST_PATH: list = []
_REG_LOCK = make_lock("persist.checkpoint._REG_LOCK")


@dataclasses.dataclass
class ResumeState:
    """A loaded checkpoint, ready to splice into the factor loop."""

    k: int                    # completed dispatch groups
    fronts: list              # k (lpanel, upanel) numpy pairs
    pool: np.ndarray          # Schur pool at the frontier
    tiny: int                 # tiny-pivot count over the first k groups
    meta: dict                # the bundle's manifest meta block
    path: str = ""


class FactorCheckpointer:
    """Checkpoint writer bound to ONE factorization's identity.

    Constructed by the driver when ``Options.ckpt_every > 0`` and handed
    to the streamed executor, which calls :meth:`note` after every
    completed group.  ``every=0`` disables interval flushes but keeps
    the breakdown/deadline/signal flush paths armed.
    """

    def __init__(self, dirpath: str, plan, pattern_values, thresh, dtype,
                 every: int = 0, gemm_prec: str = ""):
        self.dirpath = os.path.abspath(dirpath)
        os.makedirs(self.dirpath, exist_ok=True)
        self.every = int(every)
        self.plan = plan
        self.n_groups = len(plan.groups)
        self.plan_fp = serial.plan_fingerprint(plan)
        # gemm_prec joins the numeric identity: a frontier computed at
        # one GEMM tier must not be spliced under another tier's
        # arithmetic (numeric_factorize passes the resolved tier on
        # both the save and the resume side)
        self.values_fp = serial.values_digest(pattern_values, dtype, thresh,
                                              gemm_prec=gemm_prec)
        self.dtype = serial.dtype_str(dtype)
        self._entries: dict = {}      # manifest entries carried across
                                      # flushes (front files are immutable)
        self._host: list = []         # numpy copies of fronts already saved
        self._latest = None           # (gi, fronts, pool, tiny) live refs
        self.tiny_base = 0            # tiny count carried in from a
                                      # resumed frontier (executor sets it)
        self._flushed_k = -1
        self._lock = make_lock("FactorCheckpointer._lock")
        self.last_path = None
        self.flushes = 0
        with _REG_LOCK:
            _ACTIVE.append(self)
        _arm_sigterm_once()

    # ---- executor-facing hooks -----------------------------------------
    def note(self, gi: int, fronts, pool, tiny) -> None:
        """Group ``gi`` just completed.  Cheap: rebinds the live refs;
        flushes only on the interval boundary."""
        self._latest = (gi, fronts, pool, tiny)
        if self.every and (gi + 1) % self.every == 0:
            self.flush(gi + 1, fronts, pool, tiny, reason="interval")

    def flush(self, k: int, fronts, pool, tiny, reason: str) -> str:
        """Commit frontier ``k`` (the first ``k`` groups are durable).
        Blocks until the pool and any device-resident panels are
        materialized.  Returns the bundle path."""
        with self._lock:
            while len(self._host) < k:
                lp, up = fronts[len(self._host)]
                self._host.append((np.asarray(lp), np.asarray(up)))
            pool_np = np.asarray(pool)
            # the flush lock exists to serialize exactly these
            # bundle writes (interval flush vs breakdown/SIGTERM
            # flush racing on one dirpath): the I/O IS the guarded
            # operation, so the SLU109 hold-discipline findings on
            # this block are intended behavior
            for g in range(k):
                lp, up = self._host[g]
                serial.write_array(  # slulint: disable=SLU109
                    self.dirpath, f"front_{g:05d}_l", lp,
                    self._entries, skip_existing=True)
                serial.write_array(  # slulint: disable=SLU109
                    self.dirpath, f"front_{g:05d}_u", up,
                    self._entries, skip_existing=True)
            serial.write_array(self.dirpath, "pool", pool_np,  # slulint: disable=SLU109
                               self._entries)
            meta = {
                "k": int(k),
                "n_groups": self.n_groups,
                "tiny": int(tiny) + self.tiny_base,
                "factor_dtype": self.dtype,
                "plan_fingerprint": self.plan_fp,
                "values_digest": self.values_fp,
                "reason": reason,
            }
            path = serial.write_manifest(  # slulint: disable=SLU109
                self.dirpath, "factor_checkpoint", meta, self._entries)
            self._flushed_k = k
            self.flushes += 1
            self.last_path = path
            with _REG_LOCK:
                _LAST_PATH[:] = [path]
            return path

    def flush_latest(self, reason: str) -> str | None:
        """Best-effort flush of the most recent completed frontier (for
        signal handlers / watchdogs).  Never raises; returns the bundle
        path, the previous durable path if nothing new could be written,
        or None when no frontier exists at all."""
        latest = self._latest
        try:
            if latest is None:
                return self.last_path
            gi, fronts, pool, tiny = latest
            if gi + 1 <= self._flushed_k:
                return self.last_path       # nothing newer than on disk
            return self.flush(gi + 1, fronts, pool, tiny, reason=reason)
        except Exception:
            # e.g. the pool buffer was donated to an in-flight kernel —
            # the last interval checkpoint stands
            return self.last_path

    def complete(self, cleanup: bool = True) -> None:
        """The factorization finished: deregister, and by default remove
        the checkpoint (the durable artifact of a COMPLETED run is the
        saved handle, persist.save_lu — a stale mid-factor frontier
        would only invite resuming work that already finished)."""
        with _REG_LOCK:
            if self in _ACTIVE:
                _ACTIVE.remove(self)
        self._latest = None
        if cleanup and self.last_path:
            import shutil
            shutil.rmtree(self.dirpath, ignore_errors=True)
            with _REG_LOCK:
                if _LAST_PATH and _LAST_PATH[0] == self.last_path:
                    _LAST_PATH[:] = []
            self.last_path = None


# ---------------------------------------------------------------------------
# process-wide flush / query (signal handlers, watchdogs, postmortems)
# ---------------------------------------------------------------------------

def flush_active(reason: str) -> str | None:
    """Flush every live checkpointer's latest frontier (best-effort;
    never raises).  Returns the last committed path, or None."""
    path = None
    with _REG_LOCK:
        active = list(_ACTIVE)
    for ck in active:
        p = ck.flush_latest(reason)
        path = p or path
    return path


def last_checkpoint() -> str | None:
    """Path of the most recently committed checkpoint in this process
    (referenced by flight-recorder dumps), or None."""
    with _REG_LOCK:
        return _LAST_PATH[0] if _LAST_PATH else None


_sigterm_armed = []


def _arm_sigterm_once() -> None:
    """Chain a SIGTERM disposition that flushes active checkpointers
    before delegating to whatever handler was installed previously
    (flight recorder, user code, or the default kill).  Main-thread
    only; silently skipped elsewhere."""
    if _sigterm_armed:
        return
    try:
        import signal
        prev = signal.getsignal(signal.SIGTERM)

        def handler(signum, frame):
            flush_active("SIGTERM")
            if callable(prev):
                prev(signum, frame)
            elif prev is signal.SIG_IGN:
                return          # the process chose to ignore SIGTERM
            else:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

        signal.signal(signal.SIGTERM, handler)
        _sigterm_armed.append(True)
    except (ValueError, OSError, RuntimeError):
        pass


# ---------------------------------------------------------------------------
# loading / resume
# ---------------------------------------------------------------------------

def peek(dirpath: str) -> dict:
    """Manifest meta of a checkpoint without loading its arrays (for
    resume-eligibility checks, e.g. the bench watchdog row)."""
    return serial.read_manifest(dirpath, kind="factor_checkpoint")["meta"]


def load_checkpoint(dirpath: str, plan=None, pattern_values=None,
                    thresh=None, dtype=None,
                    gemm_prec: str = "") -> ResumeState:
    """Load and verify a factor checkpoint.

    With ``plan``/``pattern_values``/``thresh``/``dtype`` (and, on the
    driver path, the resolved ``gemm_prec`` tier) given, the
    checkpoint's identity fingerprints must match — a frontier computed
    from a different schedule or different values must never be spliced
    into this run (:class:`CheckpointMismatchError`).  Every artifact is
    digest-verified on read (corruption/truncation raise
    :class:`CheckpointCorruptError`, never garbage factors)."""
    doc = serial.read_manifest(dirpath, kind="factor_checkpoint")
    meta = doc["meta"]
    k = int(meta["k"])
    if plan is not None:
        fp = serial.plan_fingerprint(plan)
        if fp != meta["plan_fingerprint"]:
            raise CheckpointMismatchError(
                f"checkpoint at {dirpath!r} was written for a different "
                "factorization plan (schedule/bucket/amalgamation knobs "
                "or the sparsity pattern changed) — refactor from "
                "scratch instead of resuming")
        if k > len(plan.groups):
            raise CheckpointError(
                f"checkpoint frontier k={k} exceeds the plan's "
                f"{len(plan.groups)} groups")
    if pattern_values is not None:
        if dtype is None or thresh is None:
            raise CheckpointError(
                "value verification needs dtype and thresh alongside "
                "pattern_values")
        vd = serial.values_digest(pattern_values, dtype, thresh,
                                  gemm_prec=gemm_prec)
        if vd != meta["values_digest"]:
            raise CheckpointMismatchError(
                f"checkpoint at {dirpath!r} was computed from different "
                "numeric values (or dtype/threshold/GEMM-precision "
                "tier) — resuming would splice stale panels; refactor "
                "instead")
    fronts = [(serial.read_array(dirpath, f"front_{g:05d}_l", doc),
               serial.read_array(dirpath, f"front_{g:05d}_u", doc))
              for g in range(k)]
    pool = serial.read_array(dirpath, "pool", doc)
    return ResumeState(k=k, fronts=fronts, pool=pool,
                       tiny=int(meta["tiny"]), meta=meta,
                       path=os.path.abspath(dirpath))
