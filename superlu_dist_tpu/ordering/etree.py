"""Elimination tree and postorder.

Analog of sp_coletree_dist / TreePostorder_dist (SRC/etree.c:222) — but we
compute the etree of a *symmetrized* pattern (see
sparse.formats.symmetrize_pattern), which under static pivoting gives the
exact elimination structure, where the reference uses the column etree of
AᵀA as an upper bound for partial pivoting.

Liu's algorithm with path compression, O(nnz·α).  Pure numpy/python for now;
a C++ accelerator with identical output is planned (SURVEY.md §2.2 item 4).
"""

from __future__ import annotations

import numpy as np


def etree_symmetric(n: int, indptr: np.ndarray, indices: np.ndarray) -> np.ndarray:
    """parent[j] of the elimination tree of a symmetric-pattern CSR/CSC matrix.

    Only entries below the diagonal (j < i when scanning row i) are used, so
    either triangle or the full pattern may be passed.  Roots get parent -1.
    """
    parent = np.full(n, -1, dtype=np.int64)
    ancestor = np.full(n, -1, dtype=np.int64)
    indptr = np.asarray(indptr)
    indices = np.asarray(indices)
    for i in range(n):
        for j in indices[indptr[i]:indptr[i + 1]]:
            j = int(j)
            # walk from j up to the root of its subtree, compressing to i
            while j != -1 and j < i:
                nxt = ancestor[j]
                ancestor[j] = i
                if nxt == -1:
                    parent[j] = i
                    break
                j = int(nxt)
    return parent


def children_lists(parent: np.ndarray):
    """Children adjacency (first_child/next_sibling style, vectorized)."""
    n = len(parent)
    order = np.argsort(parent, kind="stable")
    counts = np.bincount(parent[parent >= 0], minlength=n)
    # skip roots (parent == -1 sorts first)
    nroots = int(np.sum(parent == -1))
    child_ptr = np.zeros(n + 1, dtype=np.int64)
    child_ptr[1:] = np.cumsum(counts)
    child_list = order[nroots:]
    return child_ptr, child_list


def postorder(parent: np.ndarray) -> np.ndarray:
    """Postorder permutation: post[new] = old node id, children before parents.

    Iterative DFS over the children lists (TreePostorder_dist analog).
    """
    n = len(parent)
    child_ptr, child_list = children_lists(parent)
    post = np.empty(n, dtype=np.int64)
    out = 0
    stack = []
    roots = np.flatnonzero(parent == -1)
    # visit roots in natural order; push children reversed so DFS pops
    # the smallest-numbered child first (stable, matches recursive defn)
    for r in roots[::-1]:
        stack.append((int(r), False))
    while stack:
        node, expanded = stack.pop()
        if expanded:
            post[out] = node
            out += 1
            continue
        stack.append((node, True))
        for c in child_list[child_ptr[node]:child_ptr[node + 1]][::-1]:
            stack.append((int(c), False))
    assert out == n
    return post


def tree_levels(parent: np.ndarray) -> np.ndarray:
    """level[j] = height of j in the tree: leaves 0, parent > max(children).

    This is the schedule axis of the TPU numeric phase: all nodes at one
    level are independent and factor as one batch.  It replaces the
    reference's etree-based static schedule (dstatic_schedule.c:46).
    """
    n = len(parent)
    level = np.zeros(n, dtype=np.int64)
    # process in topological order: children before parents.  Any postorder
    # works; node indices are NOT guaranteed topological pre-relabel, so use
    # postorder explicitly.
    for j in postorder(parent):
        p = parent[j]
        if p >= 0:
            level[p] = max(level[p], level[j] + 1)
    return level
