"""SLU119 true-positive fixture (executable): a shard_map program whose
body all-gathers the whole sharded pool onto every shard — the
implicit-replication blowup the jaxpr walk prices.  ``build(mesh)``
returns ``(jitted_fn, args)`` sized so the gathered output crosses the
1 MiB RESHARD_MIN_BYTES threshold (f32[512,512] -> 1 MiB gathered)."""
import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P


def build(mesh):
    def gather_pool(pool):
        def body(p):
            # materializes the WHOLE pool on every shard
            g = jax.lax.all_gather(p, "snode")
            return jnp.sum(g)
        return shard_map(body, mesh=mesh, in_specs=(P("snode"),),
                         out_specs=P(), check_rep=False)(pool)

    args = (jnp.zeros((512, 512), jnp.float32),)
    return jax.jit(gather_pool), args
