#!/usr/bin/env bash
# slulint CI gate: exit 1 on any finding that is neither inline-suppressed
# (# slulint: disable=SLUxxx with a justification) nor grandfathered in
# the committed baseline (.slulint-baseline.json — target state: empty).
#
# Pure host-side AST analysis, no jax import: the whole tree scans in
# ~1-2 s; the 60 s timeout is a hard ceiling far above the <10 s budget
# (a slow scan is itself a regression — rules must stay lexical).
#
# Wired for CI next to the tier-1 command (ROADMAP.md), alongside
# check_nan_guards.sh and check_trace_overhead.py, which follow the same
# contract: non-zero exit on ANY regression, so `&&`-chaining the three
# after pytest gates a change on all of them.
set -euo pipefail
cd "$(dirname "$0")/.."

exec timeout -k 5 60 python -m superlu_dist_tpu.analysis \
  superlu_dist_tpu/ scripts/ bench.py "$@"
