"""Shared setup for the CPU-backend measurement scripts in this
directory (config4_virtual, df64_scale, pgssvx_scale).

Not used by the TPU-session scripts (baseline_fixtures_tpu,
df64_cost_tpu) — those must NOT pin the CPU platform.
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def cpu_session(n_devices: int = 1, x64: bool = True):
    """Pin the CPU platform (with `n_devices` virtual devices), enable
    x64, and point jax at the persistent compile cache.  Must run before
    the first jax operation; any XLA_FLAGS the caller needs go into the
    environment BEFORE this call (backend init snapshots them).
    Returns the configured jax module."""
    sys.path.insert(0, REPO)
    if n_devices > 1 and "host_platform_device_count" not in os.environ.get(
            "XLA_FLAGS", ""):
        # the portable spelling across jax versions (jax_num_cpu_devices
        # is newer than the pinned 0.4.37); XLA snapshots XLA_FLAGS at
        # backend init, which the caller contract says has not happened
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={n_devices}")
    import jax
    jax.config.update("jax_platforms", "cpu")
    if n_devices > 1 and len(jax.devices()) < n_devices:
        raise SystemExit(
            f"cpu_session: wanted {n_devices} virtual cpu devices, got "
            f"{len(jax.devices())} — backend initialized before this call?")
    if x64:
        jax.config.update("jax_enable_x64", True)
    from superlu_dist_tpu.utils.jaxcache import enable_compile_cache
    enable_compile_cache()
    return jax


def raise_collective_timeouts():
    """Raise the XLA:CPU in-process collective rendezvous timeouts (the
    r3 rc=134 lesson: 8-thread all-gathers on big arrays legitimately
    take minutes on one core).  Must run BEFORE cpu_session / backend
    init — XLA snapshots XLA_FLAGS there."""
    import os
    if "collective_call_terminate_timeout" not in os.environ.get(
            "XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_cpu_collective_call_warn_stuck_timeout_seconds=3600"
            + " --xla_cpu_collective_call_terminate_timeout_seconds=14400")


def parse_mesh_spec(spec: str):
    """'1' -> (1, 1, 1); 'RxC' (R*C >= 2) -> (R, C, R*C); else SystemExit."""
    import re
    if spec == "1":
        return 1, 1, 1
    m = re.fullmatch(r"(\d+)x(\d+)", spec)
    if m:
        r, c = int(m.group(1)), int(m.group(2))
        if r * c >= 2:
            return r, c, r * c
    raise SystemExit(f"mesh spec {spec!r}: expected '1' (single device) "
                     "or 'RxC' with R*C >= 2 (e.g. '4x2')")
