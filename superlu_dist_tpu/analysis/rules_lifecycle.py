"""SLU110 — thread lifecycle discipline.

Three shapes around the *edges* of a background thread's life — exactly
where the PR 8-10 daemons (heartbeat, dispatcher, scrubber) can race
construction and interpreter teardown:

* **started-before-dependencies** — a thread started in ``__init__``
  whose target (or a transitive same-class callee, via the call graph)
  reads an attribute first assigned LATER in ``__init__``: the thread
  can observe a half-constructed object (``AttributeError`` at best, a
  stale-state decision at worst);
* **daemon-without-join** — a ``daemon=True`` thread stored on ``self``
  that no method ever ``join()``s: interpreter shutdown races the live
  daemon against module teardown (the canonical fix: a bounded-timeout
  join in ``close()``, after setting the stop event);
* **set-never-waited events** — a ``threading.Event`` that is ``set()``
  but never ``wait()``ed or ``is_set()``-polled in the class: dead
  signaling — a stop flag no one checks is a thread no one stops.

Class-scoped and false-negative-leaning: anonymous fire-and-forget
threads (``threading.Thread(target=..., daemon=True).start()`` without a
``self`` binding — the bench watchdog idiom) are intentionally out of
scope; a thread a class OWNS must have an owned lifecycle.
"""

from __future__ import annotations

import ast

from superlu_dist_tpu.analysis.concurrency import (attr_reads_transitive,
                                                   get_model)
from superlu_dist_tpu.analysis.core import Finding, Rule


class ThreadLifecycleRule(Rule):
    rule_id = "SLU110"
    title = "thread lifecycle discipline"
    hint = ("assign every attribute the target reads before start(); "
            "pair each daemon with a stop event + bounded-timeout join "
            "in close(); delete events nothing waits on")

    def check(self, tree, source, path, project=None):
        if project is None:
            return []
        model = get_model(project)
        out = []
        for cq, cm in model.classes.items():
            fns = [fi for q, fi in project.functions.items()
                   if q.startswith(cq + ".")
                   and model.class_for(fi) is cm]
            if not any(fi.path == path for fi in fns):
                continue
            out.extend(self._daemon_joins(cm, path))
            out.extend(self._init_ordering(model, cm, fns, path))
            out.extend(self._dead_events(cm, fns, path))
        return out

    # ------------------------------------------------------------------
    def _daemon_joins(self, cm, path):
        out = []
        for attr, (tq, daemon, apath, line) in sorted(
                cm.thread_attrs.items()):
            if not daemon or apath != path:
                continue
            if attr in cm.joined_attrs:
                continue
            out.append(Finding(
                self.rule_id, path, line, 1,
                f"daemon thread `self.{attr}` of `{cm.qname}` is never "
                "join()ed — interpreter shutdown races the live daemon "
                "against module teardown",
                "signal the stop event, then `self."
                f"{attr}.join(timeout)` (bounded) in close()"))
        return out

    # ------------------------------------------------------------------
    def _init_ordering(self, model, cm, fns, path):
        init = next((fi for fi in fns if fi.name == "__init__"
                     and fi.cls == cm.qname), None)
        if init is None or init.path != path:
            return []
        # source-ordered attribute assignments and thread starts
        assign_line: dict = {}
        starts = []          # (line, thread attr or None, target qname)
        for node in ast.walk(init.node):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute) \
                            and isinstance(tgt.value, ast.Name) \
                            and tgt.value.id == "self":
                        assign_line.setdefault(tgt.attr, node.lineno)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "start":
                recv = node.func.value
                if isinstance(recv, ast.Attribute) \
                        and isinstance(recv.value, ast.Name) \
                        and recv.value.id == "self" \
                        and recv.attr in cm.thread_attrs:
                    starts.append((node.lineno, recv.attr,
                                   cm.thread_attrs[recv.attr][0], node))
        out = []
        for line, attr, tq, node in starts:
            if not tq:
                continue
            reads = attr_reads_transitive(model, cm, tq)
            late = sorted(a for a in reads
                          if assign_line.get(a, 0) > line)
            if late:
                out.append(Finding(
                    self.rule_id, path, line, node.col_offset + 1,
                    f"thread `self.{attr}` started in __init__ before "
                    f"dependent attribute(s) {', '.join('`self.%s`' % a for a in late)} "
                    f"are assigned — the target "
                    f"(`{tq.rsplit('.', 1)[-1]}`) can observe a "
                    "half-constructed object",
                    "assign everything the target reads before "
                    "start(), or start from a separate start() method"))
        return out

    # ------------------------------------------------------------------
    def _dead_events(self, cm, fns, path):
        if not cm.event_attrs:
            return []
        sets: dict = {}
        used: set = set()
        for fi in fns:
            for node in ast.walk(fi.node):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Attribute)
                        and isinstance(node.func.value.value, ast.Name)
                        and node.func.value.value.id == "self"):
                    continue
                attr = node.func.value.attr
                if attr not in cm.event_attrs:
                    continue
                if node.func.attr == "set":
                    sets.setdefault(attr, (fi.path, node.lineno))
                elif node.func.attr in ("wait", "is_set", "clear"):
                    used.add(attr)
        out = []
        for attr, (apath, line) in sorted(sets.items()):
            if attr in used or apath != path:
                continue
            out.append(Finding(
                self.rule_id, path, line, 1,
                f"event `self.{attr}` of `{cm.qname}` is set() but "
                "never wait()ed or is_set()-polled — dead signaling "
                "(a stop flag no thread checks stops nothing)",
                "make the thread loop poll/wait the event, or delete "
                "it"))
        return out
