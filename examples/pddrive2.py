#!/usr/bin/env python
"""Pattern reuse: same sparsity pattern, new values — analog of
EXAMPLE/pddrive2.c (Fact=SamePattern: ordering and symbolic analysis are
reused; the numeric factorization runs on the new values).

    python examples/pddrive2.py [matrix.rua] [--backend cpu]
"""

import sys
import os

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from examples._common import (pin_cpu_if_requested, load_matrix, make_rhs,
                              report)


def main():
    pin_cpu_if_requested()
    import superlu_dist_tpu as slu

    a, src = load_matrix()
    print(f"matrix: {src}  n={a.n_rows} nnz={a.nnz}")
    xtrue, b = make_rhs(a)
    x, lu, stats, info = slu.gssvx(slu.Options(), a, b)
    assert info == 0

    # perturb values, keep the pattern (dcreate_matrix_perturbed analog)
    rng = np.random.default_rng(7)
    a2 = type(a)(a.n_rows, a.n_cols, a.indptr, a.indices,
                 a.data * (1.0 + 0.01 * rng.standard_normal(a.nnz)))
    xtrue2, b2 = make_rhs(a2, seed=2)
    x2, lu2, stats2, info2 = slu.gssvx(
        slu.Options(fact=slu.Fact.SamePattern), a2, b2, lu=lu)
    assert info2 == 0
    # SamePattern reuses the column ordering (the reference tier,
    # superlu_defs.h:489-510).  Check the invariant itself, not a timing
    # proxy:
    assert np.array_equal(lu2.col_order, lu.col_order), "col order reused"
    # Round-5 widening: the fresh MC64 matching is computed, and when it
    # reproduces the prior row permutation (the common time-stepping
    # case — values drifted mildly), the symbolic + plan are reused too,
    # so SYMBFACT+DIST drop to ~0 while ROWPERM re-ran.  The reference's
    # plain SamePattern re-runs symbfact unconditionally (pdgssvx.c:1034).
    if np.array_equal(lu2.row_order, lu.row_order):
        assert lu2.sf is lu.sf and lu2.plan is lu.plan, \
            "symbolic/plan must be reused when the row perm is unchanged"
        assert stats2.utime["SYMBFACT"] + stats2.utime["DIST"] < \
            max(0.25 * stats.utime["SYMBFACT"], 0.05), "reuse not ~free"
        print("pddrive2: row perm stable -> symbolic+plan reused "
              f"(SYMBFACT+DIST {stats2.utime['SYMBFACT'] + stats2.utime['DIST']:.4f}s)")
    resid = report("pddrive2 (SamePattern)", a2, b2, x2, xtrue2, stats2)
    assert resid < 1e-10
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
