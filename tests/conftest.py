"""Test harness configuration.

Tests run on the CPU backend with an 8-device virtual mesh so multi-chip
sharding is exercised without TPU hardware (the driver separately dry-runs
the multi-chip path), and with x64 enabled so the f64/c128 reference paths
are exact.  Mirrors the reference's strategy of oversubscribing MPI ranks on
one box (SURVEY.md §4, .travis_tests.sh).

Note: the session environment pins JAX_PLATFORMS to the remote TPU (axon)
and its sitecustomize imports jax at interpreter start, so env vars are
already snapshotted — jax.config.update is the only override that works
here.
"""

import os

# stash the session's original platform pin (e.g. "axon") so the opt-in
# hardware tests (test_tpu_hw.py) can restore it in their subprocesses —
# unsetting it entirely would re-enable the silent-CPU-fallback mode the
# pin exists to prevent (see /root/.axon_site/sitecustomize.py)
os.environ.setdefault("SLU_TPU_ORIG_PLATFORMS",
                      os.environ.get("JAX_PLATFORMS", ""))
os.environ["JAX_PLATFORMS"] = "cpu"   # for any subprocesses
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
# the dryrun's n=1e5 pool-partition phase duplicates
# tests/test_pool_partition.py (~4 compile-minutes); run it only in the
# driver's standalone dryrun, not again inside the suite
os.environ.setdefault("SLU_TPU_DRYRUN_BIG", "0")

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
