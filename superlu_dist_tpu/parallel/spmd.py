"""SPMD shard_map tier: one compiled program per factor / solve sweep.

The distributed execution model the reference's pdgstrf look-ahead
pipeline (SRC/pdgstrf.c:624-697) exists to approximate by hand: instead
of a per-rank host dispatch loop whose communication is host-mediated
lockstep (parallel/treecomm.py — kept as the A/B reference and recovery
fallback), the whole numeric factorization is ONE ``shard_map``-wrapped
jitted program over a real ``jax.Mesh`` (axes registered in
utils/meshreg.py), and each triangular-solve sweep bucket is one more.
Panels are sharded BLOCK-CYCLICALLY over the flattened device order —
slot j of a group lives on device ``j % nd`` (the reference's 2-D
block-cyclic process-to-panel map, SURVEY.md §2.4) — and every
extend-add / Schur / lsum exchange is an in-program ``all_gather`` /
``psum`` leg derived from the FactorPlan dataflow schedule, so XLA sees
the communication and can overlap it with the surrounding GEMMs: the
look-ahead window becomes compiler-visible overlap instead of host
lockstep (the ShyLU node-solver decomposition shape, arXiv:2506.05793).

Bitwise contract (the PR 5 pattern, gated by scripts/check_spmd_equiv.py
and tests/test_spmd.py): L, U and X are bitwise-identical to the
lockstep/host path.  Two mechanisms carry it:

* per-slot independence — the batched partial factor and the batched
  GEMMs compute slot s's result from slot s's data alone, so
  re-batching the slots across devices cannot change any slot's bits
  (the same invariant that keeps fused/stream/mega bitwise-equal under
  different batch compositions).  The batched TRSM does NOT have this
  property — XLA:CPU's batched triangular_solve picks a strategy per
  TOTAL batch size, so a slot's bits change when the stack is split —
  which is why SpmdSolver runs the pivot TRSM replicated on the full
  batch (identical HLO + identical operands as the single-device
  sweep) and shards only the contribution GEMMs;
* full-order replay — every scatter whose ORDER matters (the Schur pool
  write, the solve's x/lsum updates) is NOT performed on the local
  shard: the per-slot values are all-gathered, un-permuted back to the
  original slot order (``g[j] = (j % nd)·B_loc + j//nd``), and the
  exact scatter the single-device executors run is replayed redundantly
  on every device.  Identical scatter HLO on identical inputs ==
  identical bits, and the redundant copies keep the pool/x replicated
  without any check_rep machinery (shard_map runs with
  ``check_rep=False``; replication is by construction).

Padding sentinels follow the streamed executor's conventions
(numeric/stream.py): OOB scatter slots == local batch (dropped), OOB
gather sources == array length (filled 0), rel sentinel == m, padded
batch slots are identity fronts (ws == 0).
"""

from __future__ import annotations

import time

import numpy as np
import jax
import jax.numpy as jnp

from superlu_dist_tpu.obs.compilestats import COMPILE_STATS
from superlu_dist_tpu.obs.trace import get_tracer


def spmd_mode(value: str | None = None) -> bool:
    """Resolve SLU_TPU_SPMD: ""/"auto" enables the shard_map tier on
    single-process meshes (where one controller addresses every mesh
    device); "0"/"off" forces the GSPMD stream/fused tier; anything
    else forces it on.  Read OUTSIDE traced code only (slulint
    SLU102)."""
    if value is None:
        from superlu_dist_tpu.utils.options import env_str
        value = env_str("SLU_TPU_SPMD")
    v = str(value).strip().lower()
    if v in ("", "auto"):
        return jax.process_count() == 1
    return v not in ("0", "off", "false", "no")


def _cyclic_layout(batch: int, nd: int):
    """Block-cyclic slot partition over ``nd`` devices.

    Returns (B_loc, B_pad, src, valid, g): position p of the device-major
    padded order (device d = p // B_loc, local l = p % B_loc) holds slot
    ``src[p] = l·nd + d`` when ``valid[p]``; ``g[j]`` is the padded
    position of slot j, so ``take(gathered, g)`` restores slot order."""
    b_loc = max(1, -(-batch // nd))
    b_pad = b_loc * nd
    pos = np.arange(b_pad)
    src = (pos % b_loc) * nd + pos // b_loc
    valid = src < batch
    j = np.arange(batch)
    g = (j % nd) * b_loc + j // nd
    return b_loc, b_pad, src, valid, g


def _partition_rows(owner: np.ndarray, nd: int, pads: list, cols: list):
    """Stable partition of table rows by owning device: row i goes to
    device ``owner[i]``, original order preserved within a device (the
    scatter-add sequence INTO one slot is the bitwise contract).  Each
    column array in ``cols`` is repacked to (nd·C_max, ...) device-major
    with its ``pads`` sentinel filling the tail — sharding the leading
    axis over the mesh hands each device exactly its (C_max, ...)
    block."""
    per_dev = [np.nonzero(owner == d)[0] for d in range(nd)]
    c_max = max((len(ix) for ix in per_dev), default=0)
    out = []
    for col, pad in zip(cols, pads):
        col = np.asarray(col)
        shaped = np.full((nd * c_max,) + col.shape[1:], pad,
                         dtype=col.dtype)
        for d, ix in enumerate(per_dev):
            shaped[d * c_max:d * c_max + len(ix)] = col[ix]
        out.append(shaped)
    return c_max, out


class SpmdFactorExecutor:
    """The whole numeric factorization as ONE shard_map program.

    Per (level, bucket) group, each device assembles and factors only
    its block-cyclic slot partition (``group_step`` with
    ``write_back=False`` — identical per-slot arithmetic to every other
    executor), then the panels and Schur values are all-gathered,
    un-permuted to slot order, and the pool write is replayed in full
    order on every device.  The program count is 1 per factorization
    regardless of n (the compile-budget discipline), and the
    inter-group extend-add dataflow is visible to XLA as
    gather-then-compute it can overlap — the look-ahead window as
    compiler scheduling.

    Same call surface as the fused executor: ``fn(avals, thresh) ->
    (fronts_tuple, tiny)``; no per-group boundaries, so checkpointing
    forces the streamed executor (numeric_factorize).
    """

    def __init__(self, plan, dtype="float64", mesh=None, gemm_prec=None,
                 pallas=None):
        if mesh is None:
            raise ValueError("SpmdFactorExecutor needs a mesh")
        from superlu_dist_tpu.numeric.pallas_kernels import pallas_mode
        from superlu_dist_tpu.ops.dense import gemm_precision, pivot_kernel
        from superlu_dist_tpu.symbolic.symbfact import _front_flops
        plan.check_index_width()
        self.plan = plan
        self.mesh = mesh
        self.dtype = jnp.dtype(dtype)
        self._axes = tuple(mesh.axis_names)
        self.nd = int(np.prod(mesh.devices.shape))
        # env knobs resolved HERE, in the uncached constructor, and baked
        # into the one compiled program (slulint SLU102/SLU105); Pallas
        # rides through per-shard (interpret on CPU meshes, native on TPU)
        self.gemm_prec = gemm_precision(gemm_prec)
        self.pallas = pallas_mode(pallas)
        self._pivot = pivot_kernel()
        self._built = False
        nd = self.nd
        n_avals = len(plan.pattern_indices)

        meta = []          # per group: (B, B_loc, m, w, u, child ubs)
        flat = []          # program inputs, device-major repacked
        specs = []         # matching PartitionSpecs (built programmatically)
        from jax.sharding import PartitionSpec as P
        sh, rep = P(self._axes), P()
        executed = 0.0
        for grp in plan.groups:
            b = grp.batch
            b_loc, b_pad, src, valid, g = _cyclic_layout(b, nd)
            executed += b_pad * _front_flops(grp.w, grp.u)
            # assembly triples partitioned by the owning slot's device;
            # sentinels: slot == b_loc drops, src == len(avals) fills 0
            a_slot = np.asarray(grp.a_slot)
            _, (as_s, af_s, asrc_s) = _partition_rows(
                a_slot % nd, nd, [b_loc, 0, n_avals],
                [a_slot // nd, np.asarray(grp.a_flat),
                 np.asarray(grp.a_src)])
            ws = np.asarray(grp.ws)
            srcc = np.minimum(src, max(b - 1, 0))
            ws_s = np.where(valid, ws[srcc], 0).astype(ws.dtype)
            flat += [jnp.asarray(as_s), jnp.asarray(af_s),
                     jnp.asarray(asrc_s), jnp.asarray(ws_s),
                     jnp.asarray(np.asarray(grp.off)), jnp.asarray(g)]
            specs += [sh, sh, sh, sh, rep, rep]
            ubs = []
            for cs in grp.children:
                child_slot = np.asarray(cs.child_slot)
                _, (co_s, cs_s, rel_s) = _partition_rows(
                    child_slot % nd, nd,
                    [plan.pool_size, b_loc, grp.m],
                    [np.asarray(cs.child_off), child_slot // nd,
                     np.asarray(cs.rel)])
                flat += [jnp.asarray(co_s), jnp.asarray(cs_s),
                         jnp.asarray(rel_s)]
                specs += [sh, sh, sh]
                ubs.append(cs.ub)
            meta.append((b, b_loc, grp.m, grp.w, grp.u, tuple(ubs)))
        self._flat = tuple(flat)
        self.executed_flops = float(executed)

        dtype_ = self.dtype
        axes = self._axes
        pivot, gp, pal = self._pivot, self.gemm_prec, self.pallas
        pool_size = plan.pool_size
        from superlu_dist_tpu.numeric.factor import group_step

        def fn(avals, thresh, *args):
            avals = avals.astype(dtype_)
            # every device holds the full pool and replays every write
            # in full order — replicated by construction, and the
            # extend-add gathers need no communication at all
            pool = jnp.zeros(pool_size, dtype=dtype_)
            fronts = []
            tiny = jnp.zeros((), jnp.int32)
            i = 0
            for (b, b_loc, m, w, u, ubs) in meta:
                a_slot, a_flat, a_src, ws_l, off_full, g = args[i:i + 6]
                i += 6
                children = []
                for ub in ubs:
                    children.append((ub, args[i], args[i + 1], args[i + 2]))
                    i += 3
                # off=None: write_back=False never reaches the pool
                # scatter — the replay below IS the pool write
                packed, schur, t = group_step(
                    (b_loc, m, w, u), avals, pool, thresh, a_slot,
                    a_flat, a_src, ws_l, None, children, pivot=pivot,
                    gemm_prec=gp, pallas=pal, write_back=False)
                lp_l, up_l = packed
                lp = jnp.take(jax.lax.all_gather(lp_l, axes, axis=0,
                                                 tiled=True), g, axis=0)
                up = jnp.take(jax.lax.all_gather(up_l, axes, axis=0,
                                                 tiled=True), g, axis=0)
                if u > 0:
                    sv = jnp.take(jax.lax.all_gather(schur, axes, axis=0,
                                                     tiled=True), g, axis=0)
                    dst = off_full[:, None] + jnp.arange(u * u)
                    pool = pool.at[dst].set(sv, mode="drop")
                fronts.append((lp, up))
                tiny = tiny + t
            return tuple(fronts), jax.lax.psum(tiny, axes)

        from jax.experimental.shard_map import shard_map
        smapped = shard_map(fn, mesh=mesh,
                            in_specs=(rep, rep) + tuple(specs),
                            out_specs=rep, check_rep=False)
        self._jfn = jax.jit(smapped)
        self._label = (f"spmd g{len(plan.groups)} nd{nd} "
                       f"{str(self.dtype)} {self.gemm_prec}")
        # fused-executor telemetry surface (bench.py / drivers read these)
        self.offload = 0.0
        self.granularity = "program"
        self.n_kernels = 1
        self.last_profile = None
        self.last_dispatch_seconds = 0.0

    def __call__(self, avals, thresh):
        tracer = get_tracer()
        cold = not self._built
        if cold:
            from superlu_dist_tpu.utils.programaudit import maybe_audit
            maybe_audit("spmd.factor", self._label, self._jfn,
                        (avals, thresh, *self._flat),
                        mesh_axes=self._axes)
        t0 = time.perf_counter()
        out = self._jfn(avals, thresh, *self._flat)
        t_issue = time.perf_counter() - t0
        self.last_dispatch_seconds = t_issue
        if cold:
            self._built = True
            COMPILE_STATS.record("spmd.factor", self._label, t0, t_issue,
                                 n_args=2)
        if tracer.enabled:
            tracer.complete("issue spmd", "dispatch", t0, t_issue,
                            groups=len(self.plan.groups), n_devices=self.nd)
            if tracer.profiling:
                jax.block_until_ready(out[0])
                tracer.complete("factor-spmd", "kernel", t0,
                                time.perf_counter() - t0,
                                n_groups=len(self.plan.groups),
                                aggregate=True,
                                executed_flops=self.executed_flops,
                                structural_flops=float(self.plan.flops))
        return out


from superlu_dist_tpu.solve.device import DeviceSolver, _trsm


class SpmdSolver(DeviceSolver):
    """Triangular sweeps as one shard_map program per nrhs bucket.

    Subclasses DeviceSolver for its plan/panel machinery — built with
    ``mesh=None`` so the DATAFLOW solve schedule applies (the factor-
    schedule pin is a multi-process constraint only; solve/plan.py) —
    and fuses the forward AND backward sweeps into ONE jitted shard_map
    program per nrhs bucket.  Work split per group (the reference's
    pdgstrs shape — the diagonal solve is latency-bound on the pivot
    owner while the lsum updates carry the flops, SRC/pdgstrs.c):

    * pivot TRSM — runs REPLICATED on the full slot-ordered batch.
      XLA:CPU's batched triangular_solve is not batch-size invariant
      (slot bits change when the stack is split; module docstring), so
      the only way to keep y bitwise-identical to DeviceSolver is to
      issue the exact same full-batch solve on every device.  The pivot
      stack is (B, w, w) — tiny next to the off-diagonal panels — so
      replicating it costs little memory and no communication.
    * contribution GEMMs (L21·y forward, U12·x backward — where the
      flops are) — sharded block-cyclically: each device multiplies
      only its slots' L21/U12 panels (batched matmul IS per-slot
      independent), the per-slot blocks are all-gathered and
      un-permuted, and the x/lsum scatters are replayed in full slot
      order on every device (replicated x — the bitwise contract).

    Padded slots exist only in the sharded arrays: zero L21/U12 (their
    contributions vanish), gather rows pinned to the dump row."""

    def __init__(self, fact, mesh, fused=True, schedule=None,
                 window=None, align=None, trsm_leaf=None, nrhs_max=None,
                 nrhs_growth=None, gemm_prec=None):
        if mesh is None:
            raise ValueError("SpmdSolver needs a mesh")
        super().__init__(fact, diag_inv=False, fused=True, mesh=None,
                         schedule=schedule, window=window, align=align,
                         trsm_leaf=trsm_leaf, nrhs_max=nrhs_max,
                         nrhs_growth=nrhs_growth, gemm_prec=gemm_prec)
        self.spmd_mesh = mesh
        self._axes = tuple(mesh.axis_names)
        self.nd = nd = int(np.prod(mesh.devices.shape))
        from jax.sharding import PartitionSpec as P
        sh, rep = P(self._axes), P()
        sf = fact.plan.sf
        first = sf.sn_start[:-1]
        n = self.n
        dt = jnp.dtype(fact.dtype)
        flat, specs, meta = [], [], []
        for (sg, _, _, _), (lp, up) in zip(self._groups, self.fronts):
            b, m, w, u = sg.batch, lp.shape[1], sg.w, sg.u
            b_loc, b_pad, src, valid, g = _cyclic_layout(b, nd)
            srcc = np.minimum(src, max(b - 1, 0))
            lp, up = jnp.asarray(lp), jnp.asarray(up)
            # replicated pivot stack (full slot order, no padding) for
            # the full-batch TRSM; sharded off-diagonal panels for the
            # contribution GEMMs (pad slots zeroed — no contribution)
            piv = lp[:, :w, :w]
            l21_s, up_s = lp[srcc][:, w:, :], up[srcc]
            if not valid.all():
                mask = jnp.asarray(valid)[:, None, None]
                l21_s = jnp.where(mask, l21_s,
                                  jnp.zeros((m - w, w), dt)[None])
                up_s = jnp.where(mask, up_s, jnp.zeros((w, u), dt)[None])
            firsts = first[sg.sns]
            rows = np.full((b, u), n, dtype=np.int64)
            for slot, s in enumerate(sg.sns):
                r = sf.sn_rows[s]
                rows[slot, :len(r)] = r
            ws = np.asarray(sg.ws)
            # sel: which full-order y row each local GEMM slot reads
            # (pad slots read slot 0 — harmless, zero panels)
            sel = srcc.astype(np.int64)
            rows_l = np.where(valid[:, None], rows[srcc], n)
            flat += [piv, l21_s, up_s, jnp.asarray(sel),
                     jnp.asarray(rows_l), jnp.asarray(firsts),
                     jnp.asarray(ws), jnp.asarray(rows), jnp.asarray(g)]
            specs += [rep, sh, sh, sh, sh, rep, rep, rep, rep]
            meta.append((w, u))
        self._spmd_flat = tuple(flat)
        self._spmd_specs = tuple(specs)
        self._spmd_meta = meta

    def _spmd_program(self, conj=None):
        """Build one fwd+bwd shard_map program (notrans when conj is
        None, else the transpose pair with optional conjugation)."""
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P
        meta = self._spmd_meta
        axes = self._axes
        n1 = self.n + 1
        leaf, prec = self.trsm_leaf, self.gemm_prec
        hp = jax.lax.Precision.HIGHEST

        def sweep(x, lsum, *args):
            per_group = [args[i * 9:(i + 1) * 9] for i in range(len(meta))]
            # forward sweep, groups ascending (L·y = d; Uᵀ leads when
            # transposed).  The TRSM runs on the FULL slot-ordered batch
            # on every device — same HLO, same operands as the
            # single-device _fwd_body, hence the same bits; only the
            # contribution GEMM is sharded (per-slot exact).
            for (w, u), ga in zip(meta, per_group):
                (piv, l21_s, up_s, sel, rows_l, f_f, ws_f, rows_f, g) = ga
                k = jnp.arange(w)
                cols_f = jnp.where(k[None, :] < ws_f[:, None],
                                   f_f[:, None] + k, n1 - 1)
                rhs = (x.at[cols_f].get(mode="fill", fill_value=0)
                       - lsum.at[cols_f].get(mode="fill", fill_value=0))
                if conj is None:
                    y = _trsm(piv, rhs, lower=True, unit=True,
                              trans=0, leaf=leaf, prec=prec)
                    mat = l21_s
                else:
                    u11 = piv.conj() if conj else piv
                    y = _trsm(u11, rhs, lower=False, unit=False, trans=1,
                              leaf=leaf, prec=prec)
                    u12 = up_s.conj() if conj else up_s
                    mat = jnp.swapaxes(u12, 1, 2)
                x = x.at[cols_f].set(y, mode="drop")
                if u:
                    y_l = jnp.take(y, sel, axis=0)
                    contrib = jnp.matmul(mat, y_l, precision=hp,
                                         preferred_element_type=y.dtype)
                    c_f = jnp.take(jax.lax.all_gather(
                        contrib, axes, axis=0, tiled=True), g, axis=0)
                    lsum = lsum.at[rows_f].add(c_f, mode="drop")
            # backward sweep, descending: the correction GEMM reads the
            # replicated x at each device's own row slots, the gathered
            # full-order corrections are subtracted, then the full-batch
            # TRSM replays _bwd_body exactly
            for (w, u), ga in zip(reversed(meta), reversed(per_group)):
                (piv, l21_s, up_s, sel, rows_l, f_f, ws_f, rows_f, g) = ga
                k = jnp.arange(w)
                cols_f = jnp.where(k[None, :] < ws_f[:, None],
                                   f_f[:, None] + k, n1 - 1)
                rhs = x.at[cols_f].get(mode="fill", fill_value=0)
                if u:
                    xr = x.at[rows_l].get(mode="fill", fill_value=0)
                    if conj is None:
                        mat = up_s
                    else:
                        l21 = l21_s.conj() if conj else l21_s
                        mat = jnp.swapaxes(l21, 1, 2)
                    mm = jnp.matmul(mat, xr, precision=hp,
                                    preferred_element_type=xr.dtype)
                    mm_f = jnp.take(jax.lax.all_gather(
                        mm, axes, axis=0, tiled=True), g, axis=0)
                    rhs = rhs - mm_f
                if conj is None:
                    y = _trsm(piv, rhs, lower=False, unit=False,
                              trans=0, leaf=leaf, prec=prec)
                else:
                    l11 = piv.conj() if conj else piv
                    y = _trsm(l11, rhs, lower=True, unit=True, trans=1,
                              leaf=leaf, prec=prec)
                x = x.at[cols_f].set(y, mode="drop")
            return x

        rep = P()
        smapped = shard_map(sweep, mesh=self.spmd_mesh,
                            in_specs=(rep, rep) + self._spmd_specs,
                            out_specs=rep, check_rep=False)
        return jax.jit(smapped, donate_argnums=(0, 1))

    def _spmd_fns(self, kb, conj=None):
        key = ("S", kb, conj)
        fn = self._fused_cache.get(key)
        if fn is None:
            fn = self._fused_cache[key] = self._spmd_program(conj)
        return fn

    def _sweeps_for(self, conj=None):
        def sweeps(x, lsum, kb):
            fn = self._spmd_fns(kb, conj)
            args = (x, lsum, *self._spmd_flat)
            from superlu_dist_tpu.utils.programaudit import maybe_audit
            t = "" if conj is None else ("H" if conj else "T")
            maybe_audit("solve.spmd", f"spmd{t}-sweep n{self.n} k{kb}",
                        fn, args, dead=(0, 1), mesh_axes=self._axes)
            return fn(*args)
        return sweeps

    def solve(self, rhs):
        return self._run_sweeps(rhs, self._sweeps_for(None))

    def solve_trans(self, rhs, conj: bool = False):
        return self._run_sweeps(rhs, self._sweeps_for(bool(conj)))
