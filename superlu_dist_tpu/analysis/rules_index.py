"""SLU103 — index-width discipline.

The GESP analog of the reference's ``int_t`` audit (superlu_defs.h:80-93
/ XSDK_INDEX_SIZE): pattern indices may be 32-bit (``sparse.formats.INT``
— bounded by n), but anything that ACCUMULATES — indptr/offset cumsums,
nnz totals, dimension products — overflows int32 exactly in the n≈10^6
regime the config4 targets run at (nnz(L) > 2^31 long before n does).

Flagged, in symbolic/ sparse/ numeric/ inside the project tree (and
everywhere outside it, e.g. test fixtures):

* ``np.cumsum(..., dtype=D)`` with a possibly-32-bit D (``np.int32``,
  ``"int32"``, ``np.intc``, or the env-selected ``INT`` alias) — a
  running prefix sum is the canonical nnz accumulator;
* array construction (`zeros`/`empty`/`full`/`arange`/`array`/`asarray`)
  or ``.astype`` with a possibly-32-bit dtype assigned to an
  accumulator-named target (indptr / *off* / *ptr* / nnz* / *cnt* /
  count / total);
* arithmetic (`*`, `+`) where an operand is an EXPLICIT int32 cast
  (``np.int32(x)``, ``x.astype(np.int32)``) — products of dimension-like
  quantities must be promoted before they multiply, not after.
"""

from __future__ import annotations

import ast
import re

from superlu_dist_tpu.analysis.core import Rule, dotted_name

_I32_DOTTED = frozenset({"np.int32", "numpy.int32", "np.intc",
                         "numpy.intc", "int32"})
# formats.INT is int32 unless SLU_TPU_INT64 is set — treat it as 32-bit
# for accumulator purposes (the whole point of the alias is that callers
# must not feed it to arithmetic that can exceed 2^31)
_I32_ALIASES = frozenset({"INT"})

_ACCUM_TARGET = re.compile(
    r"(^|_)(indptr|offs?|offsets?|ptr|rows_ptr|nnz\w*|cnt|counts?|total)"
    r"(_|$)|(_ptr|_offs?|_cnt)$")

_ARRAY_CTORS = frozenset({"zeros", "empty", "full", "arange", "array",
                          "asarray", "ones"})


def _is_i32_dtype(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant) and node.value == "int32":
        return True
    name = dotted_name(node)
    return name in _I32_DOTTED or name in _I32_ALIASES


def _dtype_kw(call: ast.Call):
    for kw in call.keywords:
        if kw.arg == "dtype":
            return kw.value
    return None


def _is_explicit_i32_expr(node: ast.AST) -> bool:
    """np.int32(x) or x.astype(np.int32) / x.astype('int32')."""
    if not isinstance(node, ast.Call):
        return False
    if _is_i32_dtype(node.func) and dotted_name(node.func) not in \
            _I32_ALIASES:
        return True
    if isinstance(node.func, ast.Attribute) and node.func.attr == "astype" \
            and node.args and _is_i32_dtype(node.args[0]):
        return True
    return False


class IndexWidthRule(Rule):
    rule_id = "SLU103"
    title = "index-width"
    hint = ("accumulators must be int64 regardless of the pattern-index "
            "width: use formats.counts_to_indptr / symbfact.supernode_nnz "
            "or an explicit dtype=np.int64, and promote operands BEFORE "
            "products (.astype(np.int64) * ...)")
    package_dirs = ("symbolic", "sparse", "numeric")

    def check(self, tree, source, path):
        findings = []
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                self._check_call(node, path, findings)
            elif isinstance(node, ast.Assign):
                self._check_assign(node, path, findings)
            elif isinstance(node, ast.BinOp) \
                    and isinstance(node.op, (ast.Mult, ast.Add)):
                for side in (node.left, node.right):
                    if _is_explicit_i32_expr(side):
                        findings.append(self.finding(
                            path, node,
                            "int32-cast operand in arithmetic — the "
                            "product/sum wraps at 2^31 before any later "
                            "promotion can save it"))
                        break
        return findings

    def _check_call(self, node, path, findings):
        name = dotted_name(node.func)
        if name.endswith("cumsum"):
            dt = _dtype_kw(node)
            if dt is not None and _is_i32_dtype(dt):
                findings.append(self.finding(
                    path, node,
                    f"cumsum with 32-bit dtype `{dotted_name(dt) or 'int32'}`"
                    " — a prefix-sum accumulator overflows at nnz > 2^31"))

    def _check_assign(self, node, path, findings):
        targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if not any(_ACCUM_TARGET.search(t) for t in targets):
            return
        val = node.value
        if not isinstance(val, ast.Call):
            return
        dt = None
        fn = val.func
        if isinstance(fn, ast.Attribute) and fn.attr in _ARRAY_CTORS:
            dt = _dtype_kw(val)
            if dt is None and len(val.args) >= 2 \
                    and fn.attr in ("zeros", "empty", "full", "arange",
                                    "array", "asarray", "ones"):
                dt = val.args[-1] if _is_i32_dtype(val.args[-1]) else None
        elif isinstance(fn, ast.Attribute) and fn.attr == "astype" \
                and val.args:
            dt = val.args[0]
        if dt is not None and _is_i32_dtype(dt):
            findings.append(self.finding(
                path, node.value,
                f"accumulator `{', '.join(targets)}` constructed with a "
                "32-bit dtype — offset/nnz accumulators must be int64"))
