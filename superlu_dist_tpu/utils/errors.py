"""Error model.

The reference reports errors via ``info`` codes (<0: the -info-th argument
was invalid, via pxerr_dist; >0: U(i,i) is exactly singular, pdgstrf.c:234-241)
or aborts (ABORT, util_dist.h:27-34).  We use exceptions for argument errors
and return ``info`` from drivers for singularity, matching pdgssvx semantics.
"""


class SuperLUError(Exception):
    """Invalid argument / internal error (analog of pxerr_dist + ABORT)."""


class SingularMatrixError(SuperLUError):
    """U(i,i) exactly singular and ReplaceTinyPivot disabled (info > 0)."""

    def __init__(self, k: int):
        self.info = k + 1   # reference convention: 1-based first zero pivot
        super().__init__(f"Factorization failed: U({k},{k}) is exactly zero "
                         f"(info={self.info})")
