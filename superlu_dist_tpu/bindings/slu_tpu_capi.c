/*
 * Embedded-Python implementation of the slu_tpu C API (see slu_tpu.h).
 *
 * Architecture: like the reference's Fortran wrapper layer
 * (FORTRAN/superlu_c2f_dwrap.c), this file is a thin marshalling shim over
 * the real solver — there the C library, here the Python package driving
 * JAX/XLA.  The interpreter is initialized once; a bootstrap defines
 * _slu_capi_* helpers that view the caller's buffers through ctypes
 * (zero-copy in, one copy out into the caller's x) and keep a handle
 * registry of live factorizations (the reference's factors[] handle array,
 * superlu_c2f_dwrap.c:51).
 */

#include "slu_tpu.h"

#include <Python.h>
#include <stdio.h>

static int g_ready = 0;
static int g_finalized = 0;

static const char* kBootstrap =
    "import ctypes\n"
    "import numpy as _np\n"
    "import superlu_dist_tpu as _slu\n"
    "from superlu_dist_tpu.sparse.formats import SparseCSR as _CSR\n"
    "_slu_handles = {}\n"
    "_slu_next = [1]\n"
    "def _as(ptr, n, ct):\n"
    "    return _np.ctypeslib.as_array(ctypes.cast(ptr, ctypes.POINTER(ct)), (n,))\n"
    "def _mat(n, nnz, ip, ix, vp):\n"
    "    indptr = _as(ip, n + 1, ctypes.c_int64).copy()\n"
    "    indices = _as(ix, nnz, ctypes.c_int64).copy()\n"
    "    values = _as(vp, nnz, ctypes.c_double).copy()\n"
    "    return _CSR(n, n, indptr, indices, values)\n"
    "def _writeback(xp, x, n, nrhs):\n"
    "    out = _as(xp, n * nrhs, ctypes.c_double)\n"
    "    out[:] = _np.asarray(x).reshape(n, nrhs, order='A').ravel(order='F')\n"
    "def _rhs(bp, n, nrhs):\n"
    "    b = _as(bp, n * nrhs, ctypes.c_double).copy().reshape(n, nrhs, order='F')\n"
    "    return b[:, 0] if nrhs == 1 else b\n"
    "def _slu_capi_solve(n, nnz, ip, ix, vp, bp, xp, nrhs):\n"
    "    a = _mat(n, nnz, ip, ix, vp)\n"
    "    x, lu, stats, info = _slu.gssvx(_slu.Options(), a, _rhs(bp, n, nrhs))\n"
    "    if info == 0:\n"
    "        _writeback(xp, x, n, nrhs)\n"
    "    return int(info)\n"
    "def _slu_capi_factor(n, nnz, ip, ix, vp):\n"
    "    a = _mat(n, nnz, ip, ix, vp)\n"
    "    b0 = _np.zeros(n)\n"
    "    x, lu, stats, info = _slu.gssvx(\n"
    "        _slu.Options(iter_refine=_slu.IterRefine.NOREFINE), a, b0)\n"
    "    if info != 0:\n"
    "        return (int(info), 0)\n"
    "    h = _slu_next[0]; _slu_next[0] += 1\n"
    "    _slu_handles[h] = (a, lu)\n"
    "    return (0, h)\n"
    "def _slu_capi_solve_factored(h, n, bp, xp, nrhs):\n"
    "    if h not in _slu_handles:\n"
    "        return -3\n"
    "    a, lu = _slu_handles[h]\n"
    "    x, lu, stats, info = _slu.gssvx(\n"
    "        _slu.Options(fact=_slu.Fact.FACTORED), a, _rhs(bp, n, nrhs), lu=lu)\n"
    "    if info == 0:\n"
    "        _writeback(xp, x, n, nrhs)\n"
    "    return int(info)\n"
    "def _slu_capi_free(h):\n"
    "    return 0 if _slu_handles.pop(h, None) is not None else -3\n";

int slu_tpu_init(const char* backend) {
  if (g_ready) return 0;
  if (g_finalized) return -4;   /* CPython extension modules (numpy) do not
                                 * survive re-initialization — finalize is
                                 * terminal for this process */
  if (!Py_IsInitialized()) Py_InitializeEx(0);
  if (backend && backend[0]) {
    char buf[256];
    snprintf(buf, sizeof buf,
             "import jax\n"
             "jax.config.update('jax_platforms', '%s')\n"
             "jax.config.update('jax_enable_x64', True)\n",
             backend);
    if (PyRun_SimpleString(buf) != 0) return -1;
  }
  if (PyRun_SimpleString(kBootstrap) != 0) return -1;
  g_ready = 1;
  return 0;
}

static PyObject* get_fn(const char* name) {
  PyObject* main_mod = PyImport_AddModule("__main__"); /* borrowed */
  if (!main_mod) return NULL;
  return PyObject_GetAttrString(main_mod, name);
}

static int call_int(const char* name, const char* fmt, ...) {
  if (!g_ready) {
    int rc = slu_tpu_init(NULL);
    if (rc != 0) return rc < 0 ? rc : -2;
  }
  PyObject* fn = get_fn(name);
  if (!fn) return -2;
  va_list ap;
  va_start(ap, fmt);
  PyObject* args = Py_VaBuildValue(fmt, ap);
  va_end(ap);
  if (!args) {
    Py_DECREF(fn);
    return -2;
  }
  PyObject* res = PyObject_CallObject(fn, args);
  Py_DECREF(args);
  Py_DECREF(fn);
  if (!res) {
    PyErr_Print();
    return -2;
  }
  long rc = PyLong_AsLong(res);
  Py_DECREF(res);
  return (int)rc;
}

int slu_tpu_solve(int64_t n, int64_t nnz, const int64_t* indptr,
                  const int64_t* indices, const double* values,
                  const double* b, double* x, int64_t nrhs) {
  return call_int("_slu_capi_solve", "(LLLLLLLL)", (long long)n,
                  (long long)nnz, (long long)(intptr_t)indptr,
                  (long long)(intptr_t)indices, (long long)(intptr_t)values,
                  (long long)(intptr_t)b, (long long)(intptr_t)x,
                  (long long)nrhs);
}

int slu_tpu_factor(int64_t n, int64_t nnz, const int64_t* indptr,
                   const int64_t* indices, const double* values,
                   int64_t* handle) {
  if (!g_ready) {
    int rc = slu_tpu_init(NULL);
    if (rc != 0) return rc < 0 ? rc : -2;
  }
  PyObject* fn = get_fn("_slu_capi_factor");
  if (!fn) return -2;
  PyObject* res = PyObject_CallFunction(
      fn, "(LLLLL)", (long long)n, (long long)nnz,
      (long long)(intptr_t)indptr, (long long)(intptr_t)indices,
      (long long)(intptr_t)values);
  Py_DECREF(fn);
  if (!res) {
    PyErr_Print();
    return -2;
  }
  int info = -2;
  long long h = 0;
  if (PyArg_ParseTuple(res, "iL", &info, &h)) *handle = (int64_t)h;
  Py_DECREF(res);
  return info;
}

int slu_tpu_solve_factored(int64_t handle, int64_t n, const double* b,
                           double* x, int64_t nrhs) {
  return call_int("_slu_capi_solve_factored", "(LLLLL)", (long long)handle,
                  (long long)n, (long long)(intptr_t)b,
                  (long long)(intptr_t)x, (long long)nrhs);
}

int slu_tpu_free_handle(int64_t handle) {
  return call_int("_slu_capi_free", "(L)", (long long)handle);
}

void slu_tpu_finalize(void) {
  if (Py_IsInitialized()) Py_FinalizeEx();
  g_ready = 0;
  g_finalized = 1;   /* terminal: further init/solve calls return -4 */
}
