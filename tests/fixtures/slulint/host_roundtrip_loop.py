"""slulint fixture: SLU113 host round-trips inside a dispatch loop.

The dispatch loop calls a jitted kernel per group and then coerces the
device result on the host EVERY iteration — a blocking D2H round-trip
per group that serializes the async dispatch stream.  slulint v4's
device taint (dataflow lattice) must flag all three round-trip shapes:
float() coercion, np.asarray materialization, and the bool-coercion of
an `if` test on a device value.
"""

import functools

import jax
import numpy as np


@functools.lru_cache(maxsize=None)
def _kernel(w):
    def step(x):
        return x * 2.0

    return jax.jit(step)


def dispatch(xs):
    out = []
    total = 0.0
    for x in xs:
        kern = _kernel(8)
        y = kern(x)
        total += float(y[0])          # flagged: float() on device value
        host = np.asarray(y)          # flagged: implicit D2H per group
        if y[0] > 0:                  # flagged: bool-coercion of device test
            out.append(host)
    return out, total
