// Native host-analysis kernels for the TPU-native SuperLU_DIST framework.
//
// The reference implements its host analysis in C (SRC/etree.c, symbfact.c,
// mc64ad_dist.c, get_perm_c.c + METIS); the Python twins in this package are
// the specification and test oracle, but cannot reach the n≈1M problem class
// (BASELINE.md config 4).  This library provides drop-in accelerated
// versions behind a ctypes seam (superlu_dist_tpu/native/__init__.py):
//
//   slu_etree      — Liu's elimination-tree algorithm with path compression
//                    (analog of sp_coletree_dist, SRC/etree.c:222)
//   slu_postorder  — iterative DFS postorder (TreePostorder_dist analog)
//   slu_symbolic   — relaxed-supernode partition + bottom-up supernodal row
//                    structures + zero-fill chain merging (analog of
//                    symbfact/relax_snode, SRC/symbfact.c:80,224) — exact
//                    mirror of symbolic/symbfact.py semantics
//   slu_mc64       — maximum-product bipartite matching with LP duals
//                    ("MC64 job=5", analog of SRC/mc64ad_dist.c:121) — exact
//                    mirror of rowperm/matching.py
//   slu_mlnd       — multilevel nested dissection (coarsen → bisect → FM
//                    refine → project) with vertex separators; the
//                    METIS_AT_PLUS_A-quality general-graph ordering
//                    (analog of SRC/get_perm_c.c:90,463-530)
//
// All indices are int64 (the XSDK_INDEX_SIZE=64 configuration of the
// reference, superlu_defs.h:80-93): nnz(L) > 2^31 is reachable at the
// target problem class.
//
// Build: g++ -O3 -shared -fPIC (see native/__init__.py; no external deps).

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <queue>
#include <random>
#include <thread>
#include <utility>
#include <vector>

#if defined(__GLIBC__)
#include <malloc.h>
// The symbolic/ordering phases allocate and free hundreds of thousands of
// row-list vectors totalling ~GBs.  glibc serves large vectors by
// mmap/munmap, so every reuse re-faults its pages — on slow virtualized
// cores that dwarfs the actual merge work.  HeapScope keeps allocations on
// the heap (no mmap, no trim) for the duration of one analysis call, then
// restores the defaults and trims so the process does not retain the
// transient GBs (a load-time global retune would).
struct HeapScope {
  HeapScope() {
    mallopt(M_MMAP_MAX, 0);
    mallopt(M_TRIM_THRESHOLD, -1);
  }
  ~HeapScope() {
    mallopt(M_MMAP_MAX, 65536);
    mallopt(M_TRIM_THRESHOLD, 128 * 1024);
    malloc_trim(0);
  }
};
#else
struct HeapScope {};
#endif

using i64 = int64_t;

extern "C" {

// ---------------------------------------------------------------------------
// Elimination tree (Liu's algorithm, path compression).  Pattern must be
// structurally symmetric; only entries j < i of row i are used.
// ---------------------------------------------------------------------------
void slu_etree(i64 n, const i64* indptr, const i64* indices, i64* parent) {
  std::vector<i64> ancestor(n, -1);
  for (i64 i = 0; i < n; ++i) parent[i] = -1;
  for (i64 i = 0; i < n; ++i) {
    for (i64 p = indptr[i]; p < indptr[i + 1]; ++p) {
      i64 j = indices[p];
      while (j != -1 && j < i) {
        i64 nxt = ancestor[j];
        ancestor[j] = i;
        if (nxt == -1) {
          parent[j] = i;
          break;
        }
        j = nxt;
      }
    }
  }
}

// ---------------------------------------------------------------------------
// Postorder of the forest: children before parents, smallest-numbered child
// first, roots in natural order.  post[k] = node visited k-th.
// ---------------------------------------------------------------------------
void slu_postorder(i64 n, const i64* parent, i64* post) {
  // children lists via counting sort (stable => ascending child ids)
  std::vector<i64> child_cnt(n + 1, 0);
  for (i64 j = 0; j < n; ++j)
    if (parent[j] >= 0) child_cnt[parent[j] + 1]++;
  std::vector<i64> child_ptr(n + 1, 0);
  for (i64 j = 0; j < n; ++j) child_ptr[j + 1] = child_ptr[j] + child_cnt[j + 1];
  std::vector<i64> child_list(child_ptr[n]);
  {
    std::vector<i64> fill(child_ptr.begin(), child_ptr.end() - 1);
    for (i64 j = 0; j < n; ++j)
      if (parent[j] >= 0) child_list[fill[parent[j]]++] = j;
  }
  // iterative DFS; stack entries: (node, next-child cursor)
  i64 out = 0;
  std::vector<std::pair<i64, i64>> stack;
  stack.reserve(64);
  for (i64 r = 0; r < n; ++r) {
    if (parent[r] != -1) continue;
    stack.emplace_back(r, child_ptr[r]);
    while (!stack.empty()) {
      auto& top = stack.back();
      if (top.second < child_ptr[top.first + 1]) {
        i64 c = child_list[top.second++];
        stack.emplace_back(c, child_ptr[c]);
      } else {
        post[out++] = top.first;
        stack.pop_back();
      }
    }
  }
}

// ---------------------------------------------------------------------------
// Supernodal symbolic factorization on a postordered symmetric pattern.
// Mirror of symbolic/symbfact.py: relaxed leaf subtrees (<= relax cols),
// bottom-up per-supernode row structures, zero-fill chain merging capped at
// max_supernode.  Returns ns (supernode count) or -1 on error.
// Outputs (caller-allocated): sn_start (n+1), col_to_sn (n), sn_parent (n),
// sn_level (n), rows_ptr (n+1).  rows_data is malloc'd here (size
// rows_ptr[ns]); caller frees via slu_free_i64.
// ---------------------------------------------------------------------------
static i64 symbolic_impl(i64 n, const i64* indptr, const i64* indices,
                         const i64* parent, i64 relax, i64 max_supernode,
                         i64 nthreads, i64* sn_start, i64* col_to_sn,
                         i64* sn_parent, i64* sn_level, i64* rows_ptr,
                         i64** rows_data) {
  HeapScope heap_scope;
  if (relax > max_supernode) relax = max_supernode;
  // subtree counts (postordered labels: children have smaller ids)
  std::vector<i64> cnt(n, 1);
  for (i64 j = 0; j < n; ++j)
    if (parent[j] >= 0) cnt[parent[j]] += cnt[j];
  // relaxed roots -> contiguous leading partition
  std::vector<i64> first, last;
  first.reserve(n / (relax > 0 ? relax : 1) + 16);
  for (i64 j = 0; j < n;) {
    bool relaxed_root = false;
    // find whether some relaxed root r has its subtree starting at j; the
    // subtree of r covers [r-cnt[r]+1, r]: scan upward from j while counts
    // allow.  Equivalent to python's precomputed flag per node; here walk
    // the chain: r = j + ... cheapest: check each candidate root r >= j with
    // r - cnt[r] + 1 == j and cnt[r] <= relax, take the largest such r.
    // Since subtrees are nested, walk ancestors of j while they start at j.
    i64 r = j;
    i64 best = -1;
    while (r < n && r - cnt[r] + 1 == j) {
      bool is_root = (cnt[r] <= relax) &&
                     (parent[r] < 0 || cnt[parent[r]] > relax);
      if (is_root) best = r;
      if (parent[r] < 0) break;
      r = parent[r];
      if (r - cnt[r] + 1 != j) break;
    }
    first.push_back(j);
    if (best >= 0) {
      relaxed_root = true;
      j = best + 1;
    } else {
      j += 1;
    }
    last.push_back(j - 1);
    (void)relaxed_root;
  }
  i64 ns0 = (i64)first.size();
  std::vector<i64> c2s0(n);
  for (i64 s = 0; s < ns0; ++s)
    for (i64 j = first[s]; j <= last[s]; ++j) c2s0[j] = s;

  std::vector<std::vector<i64>> rows_of(ns0);
  std::vector<std::vector<i64>> kids(ns0);
  std::vector<char> alive(ns0, 1);
  // live supernode by last column
  std::vector<i64> by_last(n, -1);
  for (i64 s = 0; s < ns0; ++s) by_last[last[s]] = s;

  // Row structures via sorted-set unions: every piece (a child's row list,
  // or this supernode's structural entries) is sorted, so fold them with
  // set_union smallest-first instead of sorting the concatenation — the
  // reference's symbolic does the analogous pruned merges column-by-column
  // (symbfact.c:455); at n~1e6 this is the host-analysis hot spot.
  //
  // process_one computes supernode s's rows + chain-merges predecessors
  // within [range_lo, s]; registration of s with its parent is the
  // CALLER's job (serial: immediate; threaded: subtree roots defer to the
  // sequential top phase).  The restriction to range_lo is the only
  // divergence of the threaded result from serial output: chain merges
  // cannot cross a subtree boundary (same class of difference as the
  // reference's parallel symbolic vs serial, psymbfact.c:228-242 — a
  // valid alternative supernode partition over identical fill).
  auto process_one = [&](i64 s, i64 range_lo, std::vector<i64>& buf,
                         std::vector<i64>& acc, std::vector<i64>& tmp) {
    i64 l = last[s];
    // structural piece (small): entries > l from this supernode's columns
    buf.clear();
    for (i64 j = first[s]; j <= l; ++j)
      for (i64 p = indptr[j]; p < indptr[j + 1]; ++p)
        if (indices[p] > l) buf.push_back(indices[p]);
    std::sort(buf.begin(), buf.end());
    buf.erase(std::unique(buf.begin(), buf.end()), buf.end());
    // children pieces: rows > l (sorted views), folded smallest-first
    struct Piece { const i64* lo; const i64* hi; };
    std::vector<Piece> pieces;
    if (!buf.empty()) pieces.push_back({buf.data(), buf.data() + buf.size()});
    for (i64 g : kids[s]) {
      const auto& rg = rows_of[g];
      const i64* lo = std::upper_bound(rg.data(), rg.data() + rg.size(), l);
      if (lo != rg.data() + rg.size()) pieces.push_back({lo, rg.data() + rg.size()});
    }
    std::sort(pieces.begin(), pieces.end(),
              [](const Piece& a, const Piece& b) {
                return a.hi - a.lo < b.hi - b.lo;
              });
    acc.clear();
    for (const auto& pc : pieces) {
      tmp.clear();
      tmp.reserve(acc.size() + (pc.hi - pc.lo));
      std::set_union(acc.begin(), acc.end(), pc.lo, pc.hi,
                     std::back_inserter(tmp));
      acc.swap(tmp);
    }
    // move (not copy): steals acc's buffer, avoiding a second pass over
    // the ~nnz(L)-sized aggregate row volume
    rows_of[s] = std::move(acc);
    acc = std::vector<i64>();
    // chain-merge predecessors while zero fill and within max_supernode
    while (true) {
      if (first[s] == 0) break;
      i64 c = by_last[first[s] - 1];
      if (c < range_lo || !alive[c]) break;
      if (last[s] - first[c] + 1 > max_supernode) break;
      const auto& rc = rows_of[c];
      if (rc.empty() || rc[0] != first[s] ||
          (i64)rc.size() != (last[s] - first[s] + 1) + (i64)rows_of[s].size())
        break;
      by_last[last[c]] = -1;
      alive[c] = 0;
      first[s] = first[c];
    }
  };

  if (nthreads <= 1 || ns0 < 4 * nthreads) {
    std::vector<i64> buf, acc, tmp;
    for (i64 s = 0; s < ns0; ++s) {
      process_one(s, 0, buf, acc, tmp);
      if (!rows_of[s].empty()) kids[c2s0[rows_of[s][0]]].push_back(s);
    }
  } else {
    // ---- threaded bottom-up (the psymbfact subtree-to-worker analog) ----
    // The supernode tree is known upfront: parent supernode of s is the
    // owner of etree-parent(last[s]) (the first below-diagonal row).
    std::vector<i64> p0(ns0, -1), cnt_s(ns0, 1);
    for (i64 s = 0; s < ns0; ++s)
      if (parent[last[s]] >= 0) p0[s] = c2s0[parent[last[s]]];
    for (i64 s = 0; s < ns0; ++s)
      if (p0[s] >= 0) cnt_s[p0[s]] += cnt_s[s];
    // subtree roots: contiguous id ranges [r-cnt_s[r]+1, r] small enough
    // to balance, big enough to amortize a thread
    i64 target = std::max<i64>(64, ns0 / (4 * nthreads));
    std::vector<std::pair<i64, i64>> ranges;   // [lo, r] inclusive
    std::vector<char> in_range(ns0, 0);
    for (i64 r = 0; r < ns0; ++r) {
      bool root = cnt_s[r] <= target &&
                  (p0[r] < 0 || cnt_s[p0[r]] > target);
      if (root && cnt_s[r] >= 16) {
        ranges.emplace_back(r - cnt_s[r] + 1, r);
        for (i64 s = r - cnt_s[r] + 1; s <= r; ++s) in_range[s] = 1;
      }
    }
    std::vector<std::thread> pool;
    std::atomic<size_t> next{0};
    auto worker = [&]() {
      std::vector<i64> buf, acc, tmp;
      while (true) {
        size_t t = next.fetch_add(1);
        if (t >= ranges.size()) break;
        auto [lo, hi] = ranges[t];
        for (i64 s = lo; s <= hi; ++s) {
          process_one(s, lo, buf, acc, tmp);
          // register within the subtree only; roots defer to the top phase
          if (s != hi && !rows_of[s].empty())
            kids[c2s0[rows_of[s][0]]].push_back(s);
        }
      }
    };
    i64 nt = std::min<i64>(nthreads, (i64)ranges.size());
    for (i64 t = 0; t < nt; ++t) pool.emplace_back(worker);
    for (auto& th : pool) th.join();
    // top phase (sequential): register subtree roots, then process the
    // remaining supernodes in ascending order
    for (auto [lo, hi] : ranges)
      if (!rows_of[hi].empty()) kids[c2s0[rows_of[hi][0]]].push_back(hi);
    std::vector<i64> buf, acc, tmp;
    for (i64 s = 0; s < ns0; ++s) {
      if (in_range[s]) continue;
      process_one(s, 0, buf, acc, tmp);
      if (!rows_of[s].empty()) kids[c2s0[rows_of[s][0]]].push_back(s);
    }
  }

  // compact to live supernodes
  i64 ns = 0;
  std::vector<i64> live;
  live.reserve(ns0);
  for (i64 s = 0; s < ns0; ++s)
    if (alive[s]) live.push_back(s);
  ns = (i64)live.size();
  i64 total_rows = 0;
  for (i64 k = 0; k < ns; ++k) {
    sn_start[k] = first[live[k]];
    total_rows += (i64)rows_of[live[k]].size();
  }
  sn_start[ns] = n;
  for (i64 k = 0; k < ns; ++k)
    for (i64 j = sn_start[k]; j < sn_start[k + 1]; ++j) col_to_sn[j] = k;
  i64* rd = (i64*)std::malloc(sizeof(i64) * (total_rows ? total_rows : 1));
  if (!rd) return -1;
  i64 off = 0;
  for (i64 k = 0; k < ns; ++k) {
    rows_ptr[k] = off;
    const auto& r = rows_of[live[k]];
    std::memcpy(rd + off, r.data(), sizeof(i64) * r.size());
    off += (i64)r.size();
  }
  rows_ptr[ns] = off;
  *rows_data = rd;
  for (i64 k = 0; k < ns; ++k) {
    sn_parent[k] = rows_ptr[k] < rows_ptr[k + 1] ? col_to_sn[rd[rows_ptr[k]]] : -1;
    sn_level[k] = 0;
  }
  for (i64 k = 0; k < ns; ++k) {
    i64 p = sn_parent[k];
    if (p >= 0 && sn_level[p] < sn_level[k] + 1) sn_level[p] = sn_level[k] + 1;
  }
  return ns;
}

i64 slu_symbolic(i64 n, const i64* indptr, const i64* indices,
                 const i64* parent, i64 relax, i64 max_supernode,
                 i64* sn_start, i64* col_to_sn, i64* sn_parent,
                 i64* sn_level, i64* rows_ptr, i64** rows_data) {
  return symbolic_impl(n, indptr, indices, parent, relax, max_supernode, 1,
                       sn_start, col_to_sn, sn_parent, sn_level, rows_ptr,
                       rows_data);
}

// Parallel symbolic factorization — capability analog of symbfact_dist
// (SRC/psymbfact.c:140): subtree-to-worker decomposition over the
// supernode tree (known upfront from the etree), threads computing
// independent subtrees' row structures bottom-up, a sequential pass for
// the top separators.  Produces identical fill; supernode chain merges
// cannot cross subtree boundaries, so the partition may differ slightly
// from the serial one (the reference's parallel symbolic likewise
// produces different-but-valid structures).
i64 slu_symbolic_mt(i64 n, const i64* indptr, const i64* indices,
                    const i64* parent, i64 relax, i64 max_supernode,
                    i64 nthreads, i64* sn_start, i64* col_to_sn,
                    i64* sn_parent, i64* sn_level, i64* rows_ptr,
                    i64** rows_data) {
  if (nthreads <= 0)
    nthreads = (i64)std::max(1u, std::thread::hardware_concurrency());
  return symbolic_impl(n, indptr, indices, parent, relax, max_supernode,
                       nthreads, sn_start, col_to_sn, sn_parent, sn_level,
                       rows_ptr, rows_data);
}

void slu_free_i64(i64* p) { std::free(p); }

// ---------------------------------------------------------------------------
// Fill-tolerant supernode amalgamation — native twin of
// symbolic/symbfact.py:amalgamate_supernodes (the TPU-first whole-tree
// extension of the reference's leaf-only relax_snode, SRC/symbfact.c:224).
// Greedy merge of the column-adjacent rightmost descendant path, each merge
// tested against the constituents' ORIGINAL front flops (globally bounded
// growth).  Inputs are a symbolic partition in the slu_symbolic output
// protocol; outputs use the same protocol (o_rows_data malloc'd here, freed
// by the caller via slu_free_i64).  Returns the new supernode count, or -1.
// ---------------------------------------------------------------------------
static double front_flops_d(double w, double u) {
  return 2.0 / 3.0 * w * w * w + 2.0 * w * w * u + 2.0 * w * u * u;
}

i64 slu_amalgamate(i64 n, i64 ns, const i64* sn_start, const i64* rows_ptr,
                   const i64* rows_data, double tol, i64 max_width,
                   i64 narrow, double hard_tol, i64* o_sn_start,
                   i64* o_col_to_sn, i64* o_sn_parent, i64* o_sn_level,
                   i64* o_rows_ptr, i64** o_rows_data) {
  if (n < 0 || ns < 0) return -1;
  HeapScope heap_scope;
  std::vector<i64> first(ns), end(ns);
  std::vector<std::vector<i64>> rows(ns);
  for (i64 s = 0; s < ns; ++s) {
    first[s] = sn_start[s];
    end[s] = sn_start[s + 1];
    rows[s].assign(rows_data + rows_ptr[s], rows_data + rows_ptr[s + 1]);
  }
  std::vector<i64> c2s(n);
  for (i64 s = 0; s < ns; ++s)
    for (i64 j = first[s]; j < end[s]; ++j) c2s[j] = s;
  std::vector<i64> rep(ns);
  for (i64 s = 0; s < ns; ++s) rep[s] = s;
  auto find = [&](i64 s) {
    while (rep[s] != s) { rep[s] = rep[rep[s]]; s = rep[s]; }
    return s;
  };
  std::vector<i64> by_end(n + 1, -1);
  for (i64 s = 0; s < ns; ++s) by_end[end[s]] = s;
  std::vector<double> base(ns);
  for (i64 s = 0; s < ns; ++s)
    base[s] = front_flops_d((double)(end[s] - first[s]),
                            (double)rows[s].size());
  std::vector<char> alive(ns, 1);
  std::vector<i64> merged;
  for (i64 p = 0; p < ns; ++p) {
    if (!alive[p]) continue;
    for (;;) {
      i64 c = by_end[first[p]];
      if (c < 0) break;
      c = find(c);
      if (!alive[c]) break;
      const auto& rc = rows[c];
      if (rc.empty()) break;
      if (find(c2s[rc[0]]) != p) break;
      i64 w_m = (end[c] - first[c]) + (end[p] - first[p]);
      if (w_m > max_width) break;
      const i64* lo = std::lower_bound(rc.data(), rc.data() + rc.size(),
                                       end[p]);
      merged.clear();
      std::set_union(lo, rc.data() + rc.size(), rows[p].begin(),
                     rows[p].end(), std::back_inserter(merged));
      double fl = front_flops_d((double)w_m, (double)merged.size());
      double budget = base[p] + base[c];
      if (!(fl <= tol * budget ||
            (w_m <= narrow && fl <= hard_tol * budget)))
        break;
      by_end[first[p]] = -1;
      first[p] = first[c];
      rows[p].swap(merged);
      alive[c] = 0;
      rep[c] = p;
      base[p] = budget;
    }
  }
  // compact to live supernodes (column order is preserved: live first[]
  // ascend because merges only extend a supernode downward); parents are
  // reconstructed from o_col_to_sn[first row] below, so no old->new map
  // is needed
  i64 k = 0;
  i64 total = 0;
  for (i64 s = 0; s < ns; ++s)
    if (alive[s]) {
      ++k;
      total += (i64)rows[s].size();
    }
  i64* rd = (i64*)std::malloc(sizeof(i64) * (size_t)std::max<i64>(total, 1));
  if (!rd) return -1;
  i64 off = 0, i = 0;
  for (i64 s = 0; s < ns; ++s) {
    if (!alive[s]) continue;
    o_sn_start[i] = first[s];
    o_rows_ptr[i] = off;
    std::copy(rows[s].begin(), rows[s].end(), rd + off);
    off += (i64)rows[s].size();
    for (i64 j = first[s]; j < end[s]; ++j) o_col_to_sn[j] = i;
    ++i;
  }
  o_sn_start[k] = n;
  o_rows_ptr[k] = off;
  *o_rows_data = rd;
  for (i64 s2 = 0; s2 < k; ++s2) {
    o_sn_parent[s2] = o_rows_ptr[s2] < o_rows_ptr[s2 + 1]
                          ? o_col_to_sn[rd[o_rows_ptr[s2]]]
                          : -1;
    o_sn_level[s2] = 0;
  }
  for (i64 s2 = 0; s2 < k; ++s2) {
    i64 p = o_sn_parent[s2];
    if (p >= 0 && o_sn_level[p] < o_sn_level[s2] + 1)
      o_sn_level[p] = o_sn_level[s2] + 1;
  }
  return k;
}

// ---------------------------------------------------------------------------
// Batched front-position queries for plan building: for query q, the
// position of global index x[q] within the front of supernode s[q] —
// pivot columns map to x - first[s], below-diagonal rows to
// W[s] + rank of x in rows(s) (binary search in the supernode's sorted
// row list).  One C pass replaces ~30 numpy whole-array passes.
// ---------------------------------------------------------------------------
void slu_positions(i64 nq, const i64* s_arr, const i64* x_arr,
                   const i64* first, const i64* last, const i64* snW,
                   const i64* rows_ptr, const i64* rows_data, i64* pos) {
  for (i64 q = 0; q < nq; ++q) {
    i64 s = s_arr[q], x = x_arr[q];
    if (x <= last[s]) {
      pos[q] = x - first[s];
    } else {
      const i64* lo = rows_data + rows_ptr[s];
      const i64* hi = rows_data + rows_ptr[s + 1];
      pos[q] = snW[s] + (std::lower_bound(lo, hi, x) - lo);
    }
  }
}

// ---------------------------------------------------------------------------
// MC64 job=5: maximum-product matching + scalings via successive shortest
// augmenting paths with potentials.  Inputs: CSC pattern, |a| values.
// cost[k] = log(colmax_j) - log|a_k| (>= 0, +inf for zeros — excluded).
// Outputs: col_match (col -> row, the row_order), u (col duals), v (row
// duals).  Returns 0 ok, 1 structurally singular.
// ---------------------------------------------------------------------------
int slu_mc64(i64 n, const i64* indptr, const i64* indices,
             const double* absval, i64* col_match_out, double* u, double* v) {
  const double INF = 1e300;
  std::vector<double> cost(indptr[n]);
  std::vector<double> colmax(n, 0.0);
  for (i64 j = 0; j < n; ++j)
    for (i64 k = indptr[j]; k < indptr[j + 1]; ++k)
      colmax[j] = std::max(colmax[j], absval[k]);
  for (i64 j = 0; j < n; ++j) {
    if (colmax[j] == 0.0) return 1;  // empty column
    double lm = std::log(colmax[j]);
    for (i64 k = indptr[j]; k < indptr[j + 1]; ++k)
      cost[k] = absval[k] > 0.0 ? lm - std::log(absval[k]) : INF;
  }
  for (i64 i = 0; i < n; ++i) { u[i] = 0.0; v[i] = 0.0; }
  std::vector<i64> row_match(n, -1), col_match(n, -1);
  // Generation-stamped search state: dist/pred/done are valid for row i
  // only when its stamp equals the current source column j0, and the
  // rows touched this round are collected in `visited`.  Without this,
  // each of the n augmentations pays four O(n) refills/scans — an
  // O(n^2) total that measured ~40 MINUTES at n=1e6 (21 s at n=1e5,
  // the round-5 1M-analysis A/B bottleneck).  Stamped, each round
  // costs O(local search tree): seconds at n=1e6.
  std::vector<double> dist(n);
  std::vector<i64> pred(n);
  std::vector<i64> dstamp(n, -1), done_stamp(n, -1);
  std::vector<i64> visited;
  std::vector<i64> tree_cols;
  std::vector<double> d_col(n);
  using QE = std::pair<double, i64>;
  std::priority_queue<QE, std::vector<QE>, std::greater<QE>> heap;

  for (i64 j0 = 0; j0 < n; ++j0) {
    tree_cols.clear();
    tree_cols.push_back(j0);
    d_col[j0] = 0.0;
    visited.clear();
    while (!heap.empty()) heap.pop();

    auto dget = [&](i64 i) { return dstamp[i] == j0 ? dist[i] : INF; };
    auto relax_col = [&](i64 j, double base) {
      for (i64 k = indptr[j]; k < indptr[j + 1]; ++k) {
        if (cost[k] >= INF) continue;
        i64 i = indices[k];
        if (done_stamp[i] == j0) continue;
        double nd = base + cost[k] - u[j] - v[i];
        if (nd < dget(i) - 1e-30) {
          if (dstamp[i] != j0) {
            dstamp[i] = j0;
            visited.push_back(i);
          }
          dist[i] = nd;
          pred[i] = j;
          heap.emplace(nd, i);
        }
      }
    };
    relax_col(j0, 0.0);
    i64 found = -1;
    double mind = 0.0;
    while (!heap.empty()) {
      auto [d, i] = heap.top();
      heap.pop();
      if (done_stamp[i] == j0 || d > dget(i)) continue;
      done_stamp[i] = j0;
      if (row_match[i] == -1) {
        found = i;
        mind = dist[i];
        break;
      }
      i64 jn = row_match[i];
      tree_cols.push_back(jn);
      d_col[jn] = d;
      relax_col(jn, d);
    }
    if (found == -1) return 1;  // no perfect matching
    for (i64 i : visited)
      if (done_stamp[i] == j0 && dist[i] <= mind) v[i] += dist[i] - mind;
    for (i64 j : tree_cols) u[j] += mind - d_col[j];
    // augment
    i64 i = found;
    while (i != -1) {
      i64 j = pred[i];
      i64 inext = col_match[j];
      row_match[i] = j;
      col_match[j] = i;
      i = inext;
      if (j == j0) break;
    }
  }
  for (i64 j = 0; j < n; ++j) col_match_out[j] = col_match[j];
  // convert duals so caller computes r = exp(v), c = exp(u)/colmax
  return 0;
}

// ---------------------------------------------------------------------------
// Exact-external-degree minimum-degree ordering on a quotient graph with
// element absorption — the MMD capability analog (reference genmmd_dist_,
// SRC/mmd.c, dispatched by get_perm_c.c:463-530).  Exact mirror of
// ordering/minimum_degree.py (same tie-breaking: smallest vertex id on
// equal degree), so the Python implementation remains the test oracle.
// Sets are sorted vectors; element ids are n + elimination step.
// ---------------------------------------------------------------------------
namespace {

using VSet = std::vector<i64>;  // sorted, unique

void vset_subtract(VSet& a, const VSet& b) {
  if (a.empty() || b.empty()) return;
  VSet out;
  out.reserve(a.size());
  std::set_difference(a.begin(), a.end(), b.begin(), b.end(),
                      std::back_inserter(out));
  a.swap(out);
}

void vset_erase(VSet& a, i64 x) {
  auto it = std::lower_bound(a.begin(), a.end(), x);
  if (it != a.end() && *it == x) a.erase(it);
}

void vset_insert(VSet& a, i64 x) {
  auto it = std::lower_bound(a.begin(), a.end(), x);
  if (it == a.end() || *it != x) a.insert(it, x);
}

}  // namespace

void slu_mmd(i64 n, const i64* indptr, const i64* indices, i64* order_out) {
  HeapScope heap_scope;
  std::vector<VSet> adj(n);
  for (i64 i = 0; i < n; ++i)
    for (i64 p = indptr[i]; p < indptr[i + 1]; ++p) {
      i64 j = indices[p];
      if (j != i) {
        adj[i].push_back(j);
        adj[j].push_back(i);
      }
    }
  for (i64 i = 0; i < n; ++i) {
    std::sort(adj[i].begin(), adj[i].end());
    adj[i].erase(std::unique(adj[i].begin(), adj[i].end()), adj[i].end());
  }
  std::vector<VSet> var_elems(n);          // element ids adjacent to var
  std::vector<VSet> elem_vars(n);          // index k <-> element id n+k
  std::vector<char> alive(n, 1);
  std::vector<i64> degree(n);
  using QE = std::pair<i64, i64>;
  std::priority_queue<QE, std::vector<QE>, std::greater<QE>> heap;
  for (i64 v = 0; v < n; ++v) {
    degree[v] = (i64)adj[v].size();
    heap.emplace(degree[v], v);
  }

  // Epoch-stamped scratch instead of sorted-vector unions: external
  // sets/degrees are computed by flat marking scans (same RESULTS,
  // identical tie-breaking — the Python oracle stays bit-exact), which
  // removes the allocation+merge cost that made 3D-mesh elements
  // (O(n^{2/3}) wide) pathological: 654 s -> measured seconds-class at
  // n=110,592.  The per-step pivot element is marked ONCE and every
  // neighbor's adjacency filtered in O(deg) against it.
  std::vector<i64> mark(n, -1);
  i64 epoch = 0;
  auto external_set = [&](i64 v, VSet& out) {
    ++epoch;
    mark[v] = epoch;
    out.clear();
    for (i64 x : adj[v])
      if (mark[x] != epoch) { mark[x] = epoch; out.push_back(x); }
    for (i64 e : var_elems[v])
      for (i64 x : elem_vars[e - n])
        if (mark[x] != epoch) { mark[x] = epoch; out.push_back(x); }
    std::sort(out.begin(), out.end());
  };
  std::vector<i64> in_le(n, -1);            // step stamp: x in pivot elem
  VSet le, scratch;

  for (i64 k = 0; k < n; ++k) {
    i64 v;
    while (true) {
      auto [d, u] = heap.top();
      heap.pop();
      if (alive[u] && d == degree[u]) {
        v = u;
        break;
      }
    }
    order_out[k] = v;
    alive[v] = 0;
    external_set(v, le);
    const VSet absorbed = var_elems[v];     // copy: elements of v, absorbed
    for (i64 e : absorbed) elem_vars[e - n].clear();
    elem_vars[k] = le;
    i64 eid = n + k;
    for (i64 x : le) in_le[x] = k;
    in_le[v] = k;                           // v leaves every adjacency
    for (i64 u : le) {
      // adj[u] minus (le ∪ {v}) in one linear pass (edges now covered
      // by the new element)
      scratch.clear();
      for (i64 x : adj[u])
        if (in_le[x] != k) scratch.push_back(x);
      adj[u].swap(scratch);
      vset_subtract(var_elems[u], absorbed);
      vset_insert(var_elems[u], eid);
      // exact external degree WITHOUT rescanning the new element for
      // every member (the |le|^2 term that dominated on 3D meshes):
      // le \ {u} are pairwise distinct, alive, disjoint from the
      // just-filtered adj[u]; only the OLD elements need the dedup
      // scan, skipping le members (in_le stamp) and u itself
      degree[u] = (i64)le.size() - 1 + (i64)adj[u].size();
      ++epoch;
      mark[u] = epoch;
      for (i64 x : adj[u]) mark[x] = epoch;
      for (i64 e : var_elems[u]) {
        if (e == eid) continue;
        for (i64 x : elem_vars[e - n])
          if (in_le[x] != k && mark[x] != epoch) {
            mark[x] = epoch;
            ++degree[u];
          }
      }
      heap.emplace(degree[u], u);
    }
  }
}

// ---------------------------------------------------------------------------
// COLAMD-class approximate column minimum-degree ordering — capability
// analog of the reference's colamd (SRC/colamd.c, dispatched for
// colperm_t COLAMD, get_perm_c.c:463-530).  Fresh implementation of the
// published algorithm idea: order the columns of A by approximate minimum
// degree in AᵀA *without forming AᵀA* — the rows of A are the initial
// quotient-graph elements, eliminating a column merges every element that
// contains it into one fill element, and a column's score is the sum of
// its live element sizes (an upper bound on its AᵀA external degree).
// Dense rows are dropped from the analysis and dense columns ordered
// last, as colamd does, so one dense stripe cannot poison every score.
// ---------------------------------------------------------------------------
void slu_colamd(i64 n_rows, i64 n_cols, const i64* indptr,
                const i64* indices, i64* order_out) {
  HeapScope heap_scope;
  const i64 dense_row =
      std::max<i64>(16, (i64)(10.0 * std::sqrt((double)n_cols)));
  const i64 dense_col =
      std::max<i64>(16, (i64)(10.0 * std::sqrt((double)std::max<i64>(
                                         n_rows, 1))));
  // elements: ids 0..n_rows-1 are rows of A; n_rows+k is the k-th fill
  // element.  col_elems[j] lists the live elements containing column j.
  std::vector<VSet> elem_cols(n_rows);
  std::vector<VSet> col_elems(n_cols);
  std::vector<char> elem_alive(n_rows, 0);
  for (i64 r = 0; r < n_rows; ++r) {
    VSet& cols = elem_cols[r];
    cols.assign(indices + indptr[r], indices + indptr[r + 1]);
    std::sort(cols.begin(), cols.end());
    cols.erase(std::unique(cols.begin(), cols.end()), cols.end());
    // dense test on the DEDUPED length — the Python oracle dedups first
    if ((i64)cols.size() > dense_row) {
      cols.clear();
      cols.shrink_to_fit();
      continue;  // dense row: excluded from scores
    }
    elem_alive[r] = 1;
    for (i64 j : cols) col_elems[j].push_back(r);
  }
  std::vector<char> col_alive(n_cols, 1);
  std::vector<i64> score(n_cols, 0);
  std::vector<i64> dense_cols;
  using QE = std::pair<i64, i64>;
  std::priority_queue<QE, std::vector<QE>, std::greater<QE>> heap;
  auto col_score = [&](i64 j) {
    i64 s = 0;
    for (i64 e : col_elems[j])
      if (elem_alive[e]) s += (i64)elem_cols[e].size() - 1;
    return std::min<i64>(std::max<i64>(s, 0), n_cols - 1);
  };
  for (i64 j = 0; j < n_cols; ++j) {
    if ((i64)col_elems[j].size() > dense_col) {
      col_alive[j] = 0;
      dense_cols.push_back(j);   // ordered last, by original degree
      continue;
    }
    score[j] = col_score(j);
    heap.emplace(score[j], j);
  }
  // dense columns must not linger inside the elements they touch
  for (i64 j : dense_cols)
    for (i64 e : col_elems[j]) vset_erase(elem_cols[e], j);
  std::sort(dense_cols.begin(), dense_cols.end(), [&](i64 a, i64 b) {
    i64 da = col_elems[a].size(), db = col_elems[b].size();
    return da != db ? da < db : a < b;
  });

  elem_cols.resize(n_rows + n_cols);       // room for fill elements
  elem_alive.resize(n_rows + n_cols, 0);
  std::vector<i64> col_mark(n_cols, -1);   // step stamp: col in new elem
  std::vector<i64> elem_tested(n_rows + n_cols, -1);
  VSet keep;
  i64 k = 0;
  i64 n_live = n_cols - (i64)dense_cols.size();
  while (k < n_live) {
    i64 c;
    while (true) {
      auto [s, j] = heap.top();
      heap.pop();
      if (col_alive[j] && s == score[j]) {
        c = j;
        break;
      }
    }
    order_out[k] = c;
    col_alive[c] = 0;
    // merge every live element containing c into one fill element —
    // concatenate then sort+unique once (a chained set_union pays
    // O(k·|merged|) across k absorbed elements)
    VSet merged;
    for (i64 e : col_elems[c])
      if (elem_alive[e]) {
        merged.insert(merged.end(), elem_cols[e].begin(),
                      elem_cols[e].end());
        elem_alive[e] = 0;
        elem_cols[e].clear();
        elem_cols[e].shrink_to_fit();
      }
    std::sort(merged.begin(), merged.end());
    merged.erase(std::unique(merged.begin(), merged.end()), merged.end());
    vset_erase(merged, c);
    // drop dead columns so element sizes track live structure
    VSet live;
    live.reserve(merged.size());
    for (i64 j : merged)
      if (col_alive[j]) live.push_back(j);
    i64 eid = n_rows + k;
    elem_cols[eid] = live;
    elem_alive[eid] = 1;
    // aggressive absorption (mirror of ordering/colamd.py): an old
    // element whose every LIVE column lies inside the new element is
    // dominated by it — drop it, which tightens the scores AND stops
    // the per-column element lists from accumulating (the 3D-mesh
    // slowdown's root)
    for (i64 x : live) col_mark[x] = k;
    for (i64 j : live) {
      for (i64 e : col_elems[j]) {
        if (e == eid || !elem_alive[e] || elem_tested[e] == k) continue;
        elem_tested[e] = k;
        bool dominated = true;
        for (i64 x : elem_cols[e])
          if (col_alive[x] && col_mark[x] != k) {
            dominated = false;
            break;
          }
        if (dominated) {
          elem_alive[e] = 0;
          elem_cols[e].clear();
          elem_cols[e].shrink_to_fit();
        }
      }
    }
    // score update without rescanning the new element per member (the
    // |live|^2 term — the 3D-mesh pathology): it contributes
    // |live| - 1 to every member identically; only the OLD live
    // elements need the per-column walk.  The compaction keeps only
    // live elements (drops this step's absorbed AND dominated — both
    // dead now), then appends eid.
    const i64 base = (i64)live.size() - 1;
    for (i64 j : live) {
      keep.clear();
      for (i64 e : col_elems[j])
        if (elem_alive[e]) keep.push_back(e);
      keep.push_back(eid);
      col_elems[j].swap(keep);
      i64 s = base;
      for (i64 e : col_elems[j])
        if (e != eid && elem_alive[e]) s += (i64)elem_cols[e].size() - 1;
      score[j] = std::min<i64>(std::max<i64>(s, 0), n_cols - 1);
      heap.emplace(score[j], j);
    }
    ++k;
  }
  for (i64 j : dense_cols) order_out[k++] = j;
}

// ---------------------------------------------------------------------------
// Pattern of AᵀA (getata_dist analog, SRC/get_perm_c.c:164) for the
// MMD_ATA ordering: every row of A is a clique over its column support.
// Emits a symmetric adjacency (no diagonal) in CSR form.  Rows longer
// than dense_row are dropped (one dense row would produce an O(n²)
// clique; colamd applies the same pruning).  Single pass: the adjacency
// is built once and the index array allocated here — caller copies and
// releases it with slu_free_i64 (same protocol as slu_symbolic_mt).
// Returns total adjacency length.
// ---------------------------------------------------------------------------
i64 slu_ata_pattern(i64 n_rows, i64 n_cols, const i64* indptr,
                    const i64* indices, i64 dense_row,
                    i64* out_indptr, i64** out_indices) {
  HeapScope heap_scope;
  // append row-clique contributions, dedup each column amortized (when a
  // list grows past 4x its last compacted size) — linear appends instead
  // of the quadratic repeated set-union a popular column would pay, with
  // peak memory bounded at ~4x the final pattern instead of the raw
  // O(sum row_len^2) of append-everything
  std::vector<VSet> adj(n_cols);
  std::vector<i64> compacted(n_cols, 16);   // size floor before dedup
  auto compact = [&](i64 j) {
    VSet& a = adj[j];
    std::sort(a.begin(), a.end());
    a.erase(std::unique(a.begin(), a.end()), a.end());
    compacted[j] = std::max<i64>((i64)a.size(), 16);
  };
  for (i64 r = 0; r < n_rows; ++r) {
    VSet cols(indices + indptr[r], indices + indptr[r + 1]);
    std::sort(cols.begin(), cols.end());
    cols.erase(std::unique(cols.begin(), cols.end()), cols.end());
    // dense test on the DEDUPED length — matches the Python oracle
    if ((i64)cols.size() <= 1
        || (dense_row > 0 && (i64)cols.size() > dense_row))
      continue;
    for (i64 j : cols) {
      for (i64 u : cols)
        if (u != j) adj[j].push_back(u);
      if ((i64)adj[j].size() > 4 * compacted[j]) compact(j);
    }
  }
  i64 total = 0;
  out_indptr[0] = 0;
  for (i64 j = 0; j < n_cols; ++j) {
    VSet& a = adj[j];
    std::sort(a.begin(), a.end());
    a.erase(std::unique(a.begin(), a.end()), a.end());
    total += (i64)a.size();
    out_indptr[j + 1] = total;
  }
  i64* out = (i64*)std::malloc(std::max<i64>(total, 1) * sizeof(i64));
  for (i64 j = 0; j < n_cols; ++j)
    std::copy(adj[j].begin(), adj[j].end(), out + out_indptr[j]);
  *out_indices = out;
  return total;
}

// ---------------------------------------------------------------------------
// Approximate-weight perfect matching ("AWPM") — capability analog of the
// reference's CombBLAS HWPM path (d_c2cpp_GetHWPM.cpp, dHWPM_CombBLAS.hpp):
// a cheap, parallel-friendly alternative to exact MC64.  Greedy matching on
// weight-sorted edges, then max-cardinality augmentation (BFS alternating
// paths) to make it perfect.  Returns the permutation only (like HWPM — no
// scalings).  0 ok, 1 structurally singular.
// ---------------------------------------------------------------------------
int slu_awpm(i64 n, const i64* indptr, const i64* indices,
             const double* absval, i64* col_match_out) {
  i64 nnz = indptr[n];
  std::vector<i64> col_of(nnz);
  for (i64 j = 0; j < n; ++j)
    for (i64 k = indptr[j]; k < indptr[j + 1]; ++k) col_of[k] = j;
  // only finite positive weights participate (NaN fails `> 0.0` and would
  // otherwise break std::sort's strict-weak-ordering contract)
  std::vector<i64> order;
  order.reserve(nnz);
  for (i64 k = 0; k < nnz; ++k)
    if (absval[k] > 0.0) order.push_back(k);
  std::sort(order.begin(), order.end(),
            [&](i64 a, i64 b) { return absval[a] > absval[b]; });
  std::vector<i64> row_match(n, -1), col_match(n, -1);
  for (i64 k : order) {
    i64 i = indices[k], j = col_of[k];
    if (row_match[i] == -1 && col_match[j] == -1) {
      row_match[i] = j;
      col_match[j] = i;
    }
  }
  // perfect the matching: BFS alternating paths from each unmatched column
  // (explicit zeros are excluded, matching MC64's cost model — a zero
  // diagonal anchor would defeat the purpose of the row permutation)
  std::vector<i64> pred_row(n), queue_;
  std::vector<i64> stamp(n, -1);
  for (i64 j0 = 0; j0 < n; ++j0) {
    if (col_match[j0] != -1) continue;
    queue_.clear();
    queue_.push_back(j0);
    i64 found = -1;
    for (size_t qh = 0; qh < queue_.size() && found == -1; ++qh) {
      i64 j = queue_[qh];
      for (i64 k = indptr[j]; k < indptr[j + 1]; ++k) {
        i64 i = indices[k];
        if (!(absval[k] > 0.0) || stamp[i] == j0) continue;
        stamp[i] = j0;
        pred_row[i] = j;
        if (row_match[i] == -1) {
          found = i;
          break;
        }
        queue_.push_back(row_match[i]);
      }
    }
    if (found == -1) return 1;     // no perfect matching exists
    // backtrack: flip the alternating path (col_match[j] read before the
    // overwrite is the row displaced from j, which continues the path)
    i64 i = found;
    while (true) {
      i64 j = pred_row[i];
      i64 displaced = col_match[j];
      row_match[i] = j;
      col_match[j] = i;
      if (j == j0) break;
      i = displaced;
    }
  }
  for (i64 j = 0; j < n; ++j) col_match_out[j] = col_match[j];
  return 0;
}

// ---------------------------------------------------------------------------
// Multilevel nested dissection.
//
// Recursive: find a vertex separator of the (sub)graph via multilevel edge
// bisection (heavy-edge-matching coarsening, greedy-growing initial
// bisection, boundary-FM refinement) + vertex cover of the cut; order
// part A, part B recursively, separator last.  Leaves (<= leaf_size) are
// ordered by a local exact minimum-degree.
// ---------------------------------------------------------------------------

namespace {

struct Graph {
  i64 n;
  std::vector<i64> xadj, adj;   // CSR, no self loops
  std::vector<i64> vwgt, ewgt;  // vertex / edge weights
};

// Build coarse graph from matching map (cmap: fine vertex -> coarse id).
Graph coarsen(const Graph& g, const std::vector<i64>& cmap, i64 cn) {
  Graph c;
  c.n = cn;
  c.vwgt.assign(cn, 0);
  for (i64 v = 0; v < g.n; ++v) c.vwgt[cmap[v]] += g.vwgt[v];
  // bucket fine edges by coarse source, merge duplicates with a scratch map
  std::vector<std::vector<std::pair<i64, i64>>> nbr(cn);
  for (i64 v = 0; v < g.n; ++v) {
    i64 cv = cmap[v];
    for (i64 p = g.xadj[v]; p < g.xadj[v + 1]; ++p) {
      i64 cu = cmap[g.adj[p]];
      if (cu != cv) nbr[cv].emplace_back(cu, g.ewgt[p]);
    }
  }
  c.xadj.assign(cn + 1, 0);
  std::vector<std::pair<i64, i64>> tmp;
  std::vector<std::vector<std::pair<i64, i64>>> merged(cn);
  for (i64 v = 0; v < cn; ++v) {
    auto& e = nbr[v];
    std::sort(e.begin(), e.end());
    tmp.clear();
    for (auto& [t, w] : e) {
      if (!tmp.empty() && tmp.back().first == t)
        tmp.back().second += w;
      else
        tmp.emplace_back(t, w);
    }
    merged[v] = tmp;
    c.xadj[v + 1] = c.xadj[v] + (i64)tmp.size();
  }
  c.adj.resize(c.xadj[cn]);
  c.ewgt.resize(c.xadj[cn]);
  for (i64 v = 0; v < cn; ++v) {
    i64 o = c.xadj[v];
    for (auto& [t, w] : merged[v]) {
      c.adj[o] = t;
      c.ewgt[o] = w;
      ++o;
    }
  }
  return c;
}

// Heavy-edge matching; returns coarse count, fills cmap.
i64 hem_match(const Graph& g, std::vector<i64>& cmap, std::mt19937_64& rng) {
  std::vector<i64> order(g.n);
  for (i64 i = 0; i < g.n; ++i) order[i] = i;
  std::shuffle(order.begin(), order.end(), rng);
  cmap.assign(g.n, -1);
  i64 cn = 0;
  for (i64 v : order) {
    if (cmap[v] != -1) continue;
    i64 best = -1, bw = -1;
    for (i64 p = g.xadj[v]; p < g.xadj[v + 1]; ++p) {
      i64 u = g.adj[p];
      if (cmap[u] == -1 && g.ewgt[p] > bw) {
        bw = g.ewgt[p];
        best = u;
      }
    }
    cmap[v] = cn;
    if (best != -1) cmap[best] = cn;
    ++cn;
  }
  return cn;
}

// Greedy graph-growing bisection: BFS-grow part 0 from seed to ~half weight.
void grow_bisect(const Graph& g, i64 seed, std::vector<char>& part) {
  i64 total = 0;
  for (i64 v = 0; v < g.n; ++v) total += g.vwgt[v];
  part.assign(g.n, 1);
  i64 w0 = 0;
  std::vector<i64> q{seed};
  std::vector<char> seen(g.n, 0);
  seen[seed] = 1;
  size_t head = 0;
  i64 scan = 0;  // monotone cursor for disconnected-graph pickup
  while (w0 * 2 < total) {
    i64 v;
    if (head < q.size()) {
      v = q[head++];
    } else {
      while (scan < g.n && seen[scan]) ++scan;
      if (scan == g.n) break;
      v = scan;
      seen[v] = 1;
      q.push_back(v);
      ++head;
    }
    part[v] = 0;
    w0 += g.vwgt[v];
    for (i64 p = g.xadj[v]; p < g.xadj[v + 1]; ++p) {
      i64 u = g.adj[p];
      if (!seen[u]) {
        seen[u] = 1;
        q.push_back(u);
      }
    }
  }
}

i64 cut_of(const Graph& g, const std::vector<char>& part) {
  i64 cut = 0;
  for (i64 v = 0; v < g.n; ++v)
    for (i64 p = g.xadj[v]; p < g.xadj[v + 1]; ++p)
      if (part[v] != part[g.adj[p]]) cut += g.ewgt[p];
  return cut / 2;
}

// Boundary FM refinement (simplified): passes of greedy single-vertex moves
// with a tolerance on balance; stops when a pass improves nothing.
void fm_refine(const Graph& g, std::vector<char>& part, double balance_tol) {
  i64 total = 0;
  for (i64 v = 0; v < g.n; ++v) total += g.vwgt[v];
  i64 w[2] = {0, 0};
  for (i64 v = 0; v < g.n; ++v) w[part[v]] += g.vwgt[v];
  i64 maxside = (i64)(total * (0.5 + balance_tol));

  std::vector<i64> gain(g.n);
  auto compute_gain = [&](i64 v) {
    i64 ext = 0, in = 0;
    for (i64 p = g.xadj[v]; p < g.xadj[v + 1]; ++p) {
      if (part[g.adj[p]] != part[v]) ext += g.ewgt[p];
      else in += g.ewgt[p];
    }
    return ext - in;
  };
  for (int pass = 0; pass < 8; ++pass) {
    // collect boundary vertices
    std::vector<i64> cand;
    for (i64 v = 0; v < g.n; ++v) {
      bool boundary = false;
      for (i64 p = g.xadj[v]; p < g.xadj[v + 1] && !boundary; ++p)
        boundary = part[g.adj[p]] != part[v];
      if (boundary) {
        gain[v] = compute_gain(v);
        cand.push_back(v);
      }
    }
    std::sort(cand.begin(), cand.end(),
              [&](i64 a, i64 b) { return gain[a] > gain[b]; });
    i64 moved = 0;
    for (i64 v : cand) {
      i64 from = part[v], to = 1 - from;
      if (w[to] + g.vwgt[v] > maxside) continue;
      i64 gv = compute_gain(v);  // recompute: neighbors may have moved
      if (gv <= 0) continue;
      part[v] = (char)to;
      w[from] -= g.vwgt[v];
      w[to] += g.vwgt[v];
      ++moved;
    }
    if (!moved) break;
  }
}

// Multilevel 2-way partition of g; fills part (0/1 per vertex).
void ml_bisect(const Graph& g0, std::vector<char>& part,
               std::mt19937_64& rng) {
  std::vector<Graph> levels;
  std::vector<std::vector<i64>> cmaps;
  levels.push_back(g0);
  while (levels.back().n > 160) {
    std::vector<i64> cmap;
    const Graph& f = levels.back();
    i64 cn = hem_match(f, cmap, rng);
    if (cn > (i64)(0.95 * f.n)) break;  // coarsening stalled
    Graph c = coarsen(f, cmap, cn);
    cmaps.push_back(std::move(cmap));
    levels.push_back(std::move(c));
  }
  // initial bisection at coarsest: best of a few grow seeds
  const Graph& c = levels.back();
  std::vector<char> best_part, cur;
  i64 best_cut = -1;
  std::uniform_int_distribution<i64> pick(0, c.n - 1);
  for (int t = 0; t < 4; ++t) {
    grow_bisect(c, pick(rng), cur);
    fm_refine(c, cur, 0.05);
    i64 cut = cut_of(c, cur);
    if (best_cut < 0 || cut < best_cut) {
      best_cut = cut;
      best_part = cur;
    }
  }
  part = best_part;
  // project back with FM refinement at each level
  for (i64 l = (i64)cmaps.size() - 1; l >= 0; --l) {
    const Graph& f = levels[l];
    std::vector<char> fpart(f.n);
    for (i64 v = 0; v < f.n; ++v) fpart[v] = part[cmaps[l][v]];
    fm_refine(f, fpart, 0.03);
    part = std::move(fpart);
  }
}

// Exact minimum-degree ordering of a small dense-ish subgraph (leaf).
// nodes: global ids; writes ordered global ids to out.
void leaf_md(const std::vector<i64>& nodes, const i64* indptr,
             const i64* indices, const std::vector<i64>& glob2loc,
             std::vector<i64>& out) {
  i64 k = (i64)nodes.size();
  if (k <= 2) {
    for (i64 v : nodes) out.push_back(v);
    return;
  }
  // local adjacency as bitsets over k (k <= ~256 so this is cheap)
  i64 words = (k + 63) / 64;
  std::vector<uint64_t> adj(k * words, 0);
  auto set_bit = [&](i64 r, i64 c) { adj[r * words + c / 64] |= 1ull << (c % 64); };
  auto test_bit = [&](i64 r, i64 c) {
    return (adj[r * words + c / 64] >> (c % 64)) & 1ull;
  };
  for (i64 li = 0; li < k; ++li) {
    i64 v = nodes[li];
    for (i64 p = indptr[v]; p < indptr[v + 1]; ++p) {
      i64 lj = glob2loc[indices[p]];
      if (lj >= 0 && lj != li) {
        set_bit(li, lj);
        set_bit(lj, li);
      }
    }
  }
  std::vector<char> elim(k, 0);
  std::vector<uint64_t> elim_mask(words, 0);  // bit set => eliminated
  for (i64 step = 0; step < k; ++step) {
    i64 best = -1, bestdeg = k + 1;
    for (i64 v = 0; v < k; ++v) {
      if (elim[v]) continue;
      i64 deg = 0;
      for (i64 w = 0; w < words; ++w) deg += __builtin_popcountll(adj[v * words + w]);
      if (deg < bestdeg) {
        bestdeg = deg;
        best = v;
      }
    }
    elim[best] = 1;
    elim_mask[best / 64] |= 1ull << (best % 64);
    out.push_back(nodes[best]);
    // eliminate: connect neighbors pairwise (union rows), mask out
    // eliminated vertices + self wordwise
    for (i64 u = 0; u < k; ++u) {
      if (elim[u] || !test_bit(u, best)) continue;
      for (i64 w = 0; w < words; ++w)
        adj[u * words + w] = (adj[u * words + w] | adj[best * words + w]) &
                             ~elim_mask[w];
      adj[u * words + u / 64] &= ~(1ull << (u % 64));
    }
  }
}

inline uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ull;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ull;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebull;
  return x ^ (x >> 31);
}

// One nested-dissection task: emits the post-order [A..., B..., sep...]
// into `out`.  `glob2loc` is an n-sized scratch owned by this task's
// thread (every entry it writes is restored to -1 before returning or
// recursing into a spawned sibling).  While depth < spawn_depth the A
// branch runs on a freshly spawned std::thread with its own scratch —
// the subtree-to-thread mapping that makes this the ParMETIS-analog
// parallel ordering (reference get_perm_c_parmetis.c:104,255: separator
// tree computed by 2^q processes).
void mlnd_rec(i64 n, const i64* indptr, const i64* indices, i64 leaf_size,
              std::vector<i64> nodes, uint64_t seed, i64 spawn_depth,
              i64 depth, std::vector<i64>& glob2loc, std::vector<i64>& out) {
  std::mt19937_64 rng(splitmix64(seed));
  if ((i64)nodes.size() <= leaf_size) {
    for (i64 li = 0; li < (i64)nodes.size(); ++li) glob2loc[nodes[li]] = li;
    leaf_md(nodes, indptr, indices, glob2loc, out);
    for (i64 v : nodes) glob2loc[v] = -1;
    return;
  }
  // build local subgraph — scoped so the O(edges) Graph and all bisection
  // scratch are destroyed BEFORE the recursion (a recursion path must hold
  // only its own partition lists, not every ancestor's subgraph, or memory
  // grows to O(E·depth) at the n≈1M target class)
  std::vector<i64> a_part, b_part, sep;
  {
    Graph g;
    g.n = (i64)nodes.size();
    for (i64 li = 0; li < g.n; ++li) glob2loc[nodes[li]] = li;
    g.xadj.assign(g.n + 1, 0);
    for (i64 li = 0; li < g.n; ++li) {
      i64 v = nodes[li];
      i64 deg = 0;
      for (i64 p = indptr[v]; p < indptr[v + 1]; ++p) {
        i64 lj = glob2loc[indices[p]];
        if (lj >= 0 && lj != li) ++deg;
      }
      g.xadj[li + 1] = g.xadj[li] + deg;
    }
    g.adj.resize(g.xadj[g.n]);
    g.ewgt.assign(g.xadj[g.n], 1);
    g.vwgt.assign(g.n, 1);
    for (i64 li = 0; li < g.n; ++li) {
      i64 v = nodes[li], o = g.xadj[li];
      for (i64 p = indptr[v]; p < indptr[v + 1]; ++p) {
        i64 lj = glob2loc[indices[p]];
        if (lj >= 0 && lj != li) g.adj[o++] = lj;
      }
    }
    std::vector<char> part;
    ml_bisect(g, part, rng);
    // vertex separator from the edge cut: greedy cover — move to the
    // separator the endpoint covering the most uncovered cut edges
    std::vector<char> insep(g.n, 0);
    std::vector<i64> cutdeg(g.n, 0);
    for (i64 v = 0; v < g.n; ++v)
      for (i64 p = g.xadj[v]; p < g.xadj[v + 1]; ++p)
        if (part[g.adj[p]] != part[v]) ++cutdeg[v];
    std::vector<i64> by_cut;
    for (i64 v = 0; v < g.n; ++v)
      if (cutdeg[v] > 0) by_cut.push_back(v);
    std::sort(by_cut.begin(), by_cut.end(),
              [&](i64 a, i64 b) { return cutdeg[a] > cutdeg[b]; });
    for (i64 v : by_cut) {
      if (cutdeg[v] <= 0) continue;
      bool uncovered = false;
      for (i64 p = g.xadj[v]; p < g.xadj[v + 1] && !uncovered; ++p) {
        i64 u = g.adj[p];
        uncovered = part[u] != part[v] && !insep[u];
      }
      if (!uncovered) continue;
      insep[v] = 1;
    }
    for (i64 v = 0; v < g.n; ++v) {
      if (insep[v])
        sep.push_back(nodes[v]);
      else if (part[v] == 0)
        a_part.push_back(nodes[v]);
      else
        b_part.push_back(nodes[v]);
    }
    for (i64 li = 0; li < g.n; ++li) glob2loc[nodes[li]] = -1;
  }
  // degenerate split (e.g. clique): local MD on the blob when the
  // bitset cost (k^2/8 bytes) is affordable, natural order otherwise
  if (a_part.empty() || b_part.empty()) {
    std::sort(nodes.begin(), nodes.end());
    if ((i64)nodes.size() <= 2048) {
      for (i64 li = 0; li < (i64)nodes.size(); ++li)
        glob2loc[nodes[li]] = li;
      leaf_md(nodes, indptr, indices, glob2loc, out);
      for (i64 v : nodes) glob2loc[v] = -1;
    } else {
      for (i64 v : nodes) out.push_back(v);
    }
    return;
  }
  nodes.clear();
  nodes.shrink_to_fit();
  uint64_t sa = splitmix64(seed * 2 + 1), sb = splitmix64(seed * 2 + 2);
  if (depth < spawn_depth) {
    std::vector<i64> a_out, b_out;
    std::thread t([&, sa]() {
      std::vector<i64> scratch(n, -1);
      mlnd_rec(n, indptr, indices, leaf_size, std::move(a_part), sa,
               spawn_depth, depth + 1, scratch, a_out);
    });
    mlnd_rec(n, indptr, indices, leaf_size, std::move(b_part), sb,
             spawn_depth, depth + 1, glob2loc, b_out);
    t.join();
    out.insert(out.end(), a_out.begin(), a_out.end());
    out.insert(out.end(), b_out.begin(), b_out.end());
  } else {
    mlnd_rec(n, indptr, indices, leaf_size, std::move(a_part), sa,
             spawn_depth, depth + 1, glob2loc, out);
    mlnd_rec(n, indptr, indices, leaf_size, std::move(b_part), sb,
             spawn_depth, depth + 1, glob2loc, out);
  }
  out.insert(out.end(), sep.begin(), sep.end());
}

}  // namespace

void slu_mlnd_mt(i64 n, const i64* indptr, const i64* indices,
                 i64 leaf_size, uint64_t seed, i64 nthreads,
                 i64* order_out) {
  HeapScope heap_scope;
  // spawn_depth d gives up to 2^d concurrent subtree tasks (plus the
  // separator work in their ancestors) — the subtree-to-process mapping
  // of the reference's parallel ordering (get_perm_c_parmetis.c:255)
  i64 hc = (i64)std::thread::hardware_concurrency();
  if (hc <= 0) hc = 1;
  if (nthreads > hc) nthreads = hc;   // oversubscription only wastes
  if (nthreads < 1) nthreads = 1;     // scratch memory; a huge env value
                                      // must not exhaust pthreads
  i64 spawn_depth = 0;
  while ((1ll << spawn_depth) < nthreads) ++spawn_depth;
  std::vector<i64> all(n);
  for (i64 i = 0; i < n; ++i) all[i] = i;
  std::vector<i64> glob2loc(n, -1);
  std::vector<i64> out;
  out.reserve(n);
  mlnd_rec(n, indptr, indices, leaf_size, std::move(all), seed,
           spawn_depth, 0, glob2loc, out);
  for (i64 i = 0; i < (i64)out.size() && i < n; ++i) order_out[i] = out[i];
}

void slu_mlnd(i64 n, const i64* indptr, const i64* indices, i64 leaf_size,
              uint64_t seed, i64* order_out) {
  slu_mlnd_mt(n, indptr, indices, leaf_size, seed, 1, order_out);
}

// ---------------------------------------------------------------------------
// Async tree broadcast / reduction over shared memory — capability analog
// of the reference's C++11 tree-collective engine (TreeBcast_slu.hpp,
// TreeReduce_slu.hpp, TreeInterface.cpp) that drives the distributed
// triangular solve.  Same topology rule: flat tree up to 8 ranks, binary
// beyond (TreeBcast_slu.hpp:17-29).  The reference's transport is MPI
// point-to-point; the TPU-native host runtime uses a POSIX shared-memory
// segment with per-rank sequence/ack counters — single-node multi-process
// orchestration, while on-device collectives ride XLA/ICI (parallel/grid).
//
// Layout of the segment: header {n_ranks, max_len}, then per rank:
//   seq  (atomic u64): last operation index this rank has published
//   ack  (atomic u64): cumulative count of child reads of this rank's slot
//   buf  (max_len doubles)
// Each collective call site must be reached by every rank in the same
// order (the usual collective contract); op indices are tracked per
// attached handle.
// ---------------------------------------------------------------------------
}  // extern "C"

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

namespace slu_tree {

struct RankSlot {
  std::atomic<uint64_t> seq;
  std::atomic<uint64_t> ack;
  // failure-detector slots (ISSUE 8): hb is a heartbeat epoch bumped by
  // the owner's heartbeat thread; pid is the owning process, polled by
  // peers with kill(pid, 0) so death is detected even when the
  // heartbeat thread died WITH the process.  Both are pure telemetry —
  // the collective protocol never reads them.
  std::atomic<uint64_t> hb;
  std::atomic<int64_t> pid;
};

struct Header {
  i64 n_ranks;
  i64 max_len;
  std::atomic<uint64_t> ready;   // == kReadyMagic once fully initialized
};

constexpr uint64_t kReadyMagic = 0x51b17ee5c0113c7ull;

struct Handle {
  Header* hdr = nullptr;
  RankSlot* slots = nullptr;   // n_ranks
  double* bufs = nullptr;      // n_ranks * max_len
  i64 rank = -1;
  uint64_t op = 0;             // shared across bcast+reduce: every rank
                               // reaches the collectives in the same order
  uint64_t my_reads = 0;       // total reads ever promised on my slot
  size_t map_len = 0;
  void* base = nullptr;
};

inline size_t seg_size(i64 n_ranks, i64 max_len) {
  return sizeof(Header) + (size_t)n_ranks * sizeof(RankSlot)
         + (size_t)n_ranks * (size_t)max_len * sizeof(double);
}

// flat <= 8 ranks (every rank a direct child of the root), binary above —
// expressed on the root-relative virtual rank v = (rank - root) mod n
inline i64 parent_of(i64 v, i64 n) {
  if (v == 0) return -1;
  if (n <= 8) return 0;
  return (v - 1) / 2;
}

inline void children_of(i64 v, i64 n, i64* out, i64* n_out) {
  *n_out = 0;
  if (n <= 8) {
    if (v == 0)
      for (i64 c = 1; c < n; ++c) out[(*n_out)++] = c;
    return;
  }
  for (i64 c = 2 * v + 1; c <= 2 * v + 2 && c < n; ++c)
    out[(*n_out)++] = c;
}

inline void backoff(int& spins) {
  if (++spins < 1024) return;
  ::usleep(50);
}

inline double mono_now() {
  struct timespec ts;
  ::clock_gettime(CLOCK_MONOTONIC, &ts);
  return (double)ts.tv_sec + 1e-9 * (double)ts.tv_nsec;
}

// Bounded spin-wait: a short hot-spin phase, then exponential-backoff
// sleeps with jitter (decorrelates the ranks of a big tree hammering
// the same cache lines) up to a monotonic deadline.  deadline <= 0
// means unbounded — the legacy behavior of the untimed entry points.
struct TimedWait {
  double deadline;
  int spins = 0;
  useconds_t slp = 50;
  uint64_t rng;
  explicit TimedWait(double dl, uint64_t seed)
      : deadline(dl), rng(seed * 2654435769ull + 1) {}
  bool step() {
    if (++spins < 512) return true;
    if (deadline > 0 && mono_now() >= deadline) return false;
    rng = rng * 6364136223846793005ull + 1442695040888963407ull;
    useconds_t j = (useconds_t)((rng >> 33) % (uint64_t)(slp / 2 + 1));
    ::usleep(slp / 2 + j);
    if (slp < 4000) slp <<= 1;
    return true;
  }
};

}  // namespace slu_tree

extern "C" {

void* slu_tree_attach(const char* name, i64 n_ranks, i64 max_len,
                      i64 rank, i64 create) {
  using namespace slu_tree;
  size_t len = seg_size(n_ranks, max_len);
  int fd;
  if (create) {
    // a stale segment from a crashed run still carries ready==magic and
    // old seq/ack values — unlink first and create exclusively, so
    // attachers genuinely wait for THIS creator's initialization
    ::shm_unlink(name);
    fd = ::shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  } else {
    fd = ::shm_open(name, O_RDWR, 0600);
  }
  if (fd < 0) return nullptr;
  if (create) {
    if (::ftruncate(fd, (off_t)len) != 0) {
      ::close(fd);
      return nullptr;
    }
  } else {
    // creator may still be between shm_open and ftruncate: mapping a
    // zero-length segment SIGBUSes on first touch.  Wait (bounded) for
    // the segment to reach full size.
    struct stat st;
    int tries = 0;
    while (::fstat(fd, &st) == 0 && (size_t)st.st_size < len) {
      if (++tries > 100000) {       // ~10 s
        ::close(fd);
        return nullptr;
      }
      ::usleep(100);
    }
  }
  void* base = ::mmap(nullptr, len, PROT_READ | PROT_WRITE, MAP_SHARED,
                      fd, 0);
  ::close(fd);
  if (base == MAP_FAILED) return nullptr;
  auto* h = new Handle;
  h->base = base;
  h->map_len = len;
  h->hdr = (Header*)base;
  h->slots = (RankSlot*)((char*)base + sizeof(Header));
  h->bufs = (double*)((char*)base + sizeof(Header)
                      + (size_t)n_ranks * sizeof(RankSlot));
  h->rank = rank;
  if (create) {
    h->hdr->n_ranks = n_ranks;
    h->hdr->max_len = max_len;
    for (i64 r = 0; r < n_ranks; ++r) {
      h->slots[r].seq.store(0, std::memory_order_relaxed);
      h->slots[r].ack.store(0, std::memory_order_relaxed);
    }
    h->hdr->ready.store(kReadyMagic, std::memory_order_release);
  } else {
    // size alone is not enough: the creator may be preempted between
    // ftruncate and the header stores — wait for the ready flag
    int tries = 0;
    while (h->hdr->ready.load(std::memory_order_acquire) != kReadyMagic) {
      if (++tries > 100000) {       // ~10 s
        ::munmap(base, len);
        delete h;
        return nullptr;
      }
      ::usleep(100);
    }
  }
  return h;
}

void slu_tree_detach(void* vh, const char* name, i64 unlink_seg) {
  using namespace slu_tree;
  auto* h = (Handle*)vh;
  if (!h) return;
  if (h->base) ::munmap(h->base, h->map_len);
  if (unlink_seg) ::shm_unlink(name);
  delete h;
}

// In-process rank handle SHARING the creator's mapping (same virtual
// addresses).  Two uses: threads standing in for ranks, and sanitizer
// runs — TSAN's shadow memory is keyed by virtual address, so races in
// the collective protocol are only visible when all "ranks" touch the
// segment through one mapping.  The returned handle must be detached
// with a null name and unlink_seg=0; it does not own the mapping.
void* slu_tree_attach_shared(void* creator_handle, i64 rank) {
  using namespace slu_tree;
  auto* c = (Handle*)creator_handle;
  if (!c) return nullptr;
  auto* h = new Handle;
  h->hdr = c->hdr;
  h->slots = c->slots;
  h->bufs = c->bufs;
  h->rank = rank;
  h->map_len = 0;
  h->base = nullptr;   // not owned: detach skips munmap
  return h;
}

// Broadcast buf (len doubles) from root to all ranks.  Every rank calls
// with its own buf; non-roots receive into it.  Publish protocol: before
// overwriting my slot I wait until every read promised by my PREVIOUS
// publishes has been acked (cumulative counter), so a slow child can
// still be copying op t while the tree races ahead to t+1 elsewhere.
//
// Timed variant (ISSUE 8 bounded-wait): EVERY wait runs under one
// monotonic deadline with exponential backoff + jitter; all waits
// complete BEFORE any mutation (op bump, memcpy, ack, publish), so a
// timeout is perfectly resumable — the caller consults the failure
// detector and either retries this very op or raises.  Returns 0 on
// success; on timeout, 1 + the rank being waited on, or 1 + n_ranks
// when the stuck party is an unidentified child (cumulative ack drain).
// timeout_s <= 0 waits forever (the legacy untimed behavior).
i64 slu_tree_bcast_tw(void* vh, i64 root, double* buf, i64 len,
                      double timeout_s) {
  using namespace slu_tree;
  auto* h = (Handle*)vh;
  i64 n = h->hdr->n_ranks;
  uint64_t op = h->op + 1;
  if (n == 1) {
    h->op = op;
    return 0;
  }
  root = ((root % n) + n) % n;   // normalize (root=-1 idiom, bad input)
  i64 v = (h->rank - root + n) % n;
  i64 kids[8];
  i64 n_kids = 0;
  children_of(v, n, kids, &n_kids);
  RankSlot& mine = h->slots[h->rank];
  double* my_buf = h->bufs + (size_t)h->rank * h->hdr->max_len;
  double dl = timeout_s > 0 ? mono_now() + timeout_s : 0.0;
  TimedWait w(dl, (uint64_t)h->rank * 0x9e3779b9u + op);
  // ---- wait phase (side-effect free) ---------------------------------
  if (n_kids) {
    while (mine.ack.load(std::memory_order_acquire) < h->my_reads)
      if (!w.step()) return 1 + n;
  }
  i64 p_rank = -1;
  if (v != 0) {
    p_rank = (parent_of(v, n) + root) % n;
    RankSlot& ps = h->slots[p_rank];
    while (ps.seq.load(std::memory_order_acquire) < op)
      if (!w.step()) return 1 + p_rank;
  }
  // ---- commit phase --------------------------------------------------
  h->op = op;
  if (v != 0) {
    RankSlot& ps = h->slots[p_rank];
    std::memcpy(buf, h->bufs + (size_t)p_rank * h->hdr->max_len,
                (size_t)len * sizeof(double));
    ps.ack.fetch_add(1, std::memory_order_acq_rel);
  }
  if (n_kids) {
    std::memcpy(my_buf, buf, (size_t)len * sizeof(double));
    mine.seq.store(op, std::memory_order_release);
    h->my_reads += (uint64_t)n_kids;
  }
  return 0;
}

void slu_tree_bcast(void* vh, i64 root, double* buf, i64 len) {
  slu_tree_bcast_tw(vh, root, buf, len, 0.0);
}

// Sum-reduce buf (len doubles) onto the root: on return the root's buf
// holds the elementwise sum of every rank's input; other ranks' bufs are
// clobbered with their subtree partial.  Timed contract identical to
// slu_tree_bcast_tw: all waits (children present AND my previous
// publishes acked) precede the first mutation — in particular the
// child-partial accumulation into buf — so a timeout never leaves a
// half-summed buffer behind.
i64 slu_tree_reduce_sum_tw(void* vh, i64 root, double* buf, i64 len,
                           double timeout_s) {
  using namespace slu_tree;
  auto* h = (Handle*)vh;
  i64 n = h->hdr->n_ranks;
  uint64_t op = h->op + 1;
  if (n == 1) {
    h->op = op;
    return 0;
  }
  root = ((root % n) + n) % n;   // normalize (root=-1 idiom, bad input)
  i64 v = (h->rank - root + n) % n;
  i64 kids[8];
  i64 n_kids = 0;
  children_of(v, n, kids, &n_kids);
  RankSlot& mine = h->slots[h->rank];
  double* my_buf = h->bufs + (size_t)h->rank * h->hdr->max_len;
  double dl = timeout_s > 0 ? mono_now() + timeout_s : 0.0;
  TimedWait w(dl, (uint64_t)h->rank * 0x9e3779b9u + op);
  // ---- wait phase (side-effect free) ---------------------------------
  for (i64 c = 0; c < n_kids; ++c) {
    i64 c_rank = (kids[c] + root) % n;
    RankSlot& cs = h->slots[c_rank];
    while (cs.seq.load(std::memory_order_acquire) < op)
      if (!w.step()) return 1 + c_rank;
  }
  if (v != 0) {
    while (mine.ack.load(std::memory_order_acquire) < h->my_reads)
      if (!w.step()) return 1 + n;
  }
  // ---- commit phase --------------------------------------------------
  h->op = op;
  for (i64 c = 0; c < n_kids; ++c) {
    i64 c_rank = (kids[c] + root) % n;
    RankSlot& cs = h->slots[c_rank];
    const double* cb = h->bufs + (size_t)c_rank * h->hdr->max_len;
    for (i64 i = 0; i < len; ++i) buf[i] += cb[i];
    cs.ack.fetch_add(1, std::memory_order_acq_rel);
  }
  if (v != 0) {                 // publish subtree partial for my parent
    std::memcpy(my_buf, buf, (size_t)len * sizeof(double));
    mine.seq.store(op, std::memory_order_release);
    h->my_reads += 1;
  }
  return 0;
}

void slu_tree_reduce_sum(void* vh, i64 root, double* buf, i64 len) {
  slu_tree_reduce_sum_tw(vh, root, buf, len, 0.0);
}

// ---------------------------------------------------------------------------
// Failure-detector surface (ISSUE 8).  pid + heartbeat live in the
// RankSlot of the COLLECTIVE domain; the post/peek pair implements the
// wait-free bulletin board of the sibling ".ftx" agreement domain —
// each rank writes only its OWN slot (seqlock versioning via the seq
// counter, which the board domain never uses for collectives), peers
// poll, and nothing ever blocks on a dead rank.
// ---------------------------------------------------------------------------

void slu_tree_set_pid(void* vh, i64 pid) {
  using namespace slu_tree;
  auto* h = (Handle*)vh;
  h->slots[h->rank].pid.store(pid, std::memory_order_release);
}

i64 slu_tree_get_pid(void* vh, i64 rank) {
  using namespace slu_tree;
  auto* h = (Handle*)vh;
  return h->slots[rank].pid.load(std::memory_order_acquire);
}

void slu_tree_heartbeat(void* vh) {
  using namespace slu_tree;
  auto* h = (Handle*)vh;
  h->slots[h->rank].hb.fetch_add(1, std::memory_order_acq_rel);
}

i64 slu_tree_get_heartbeat(void* vh, i64 rank) {
  using namespace slu_tree;
  auto* h = (Handle*)vh;
  return (i64)h->slots[rank].hb.load(std::memory_order_acquire);
}

// The seqlock payload is copied element-wise through relaxed atomic
// u64 accesses (bit patterns of the doubles): a reader's speculative
// copy RACES the writer's store by design — the version check discards
// torn snapshots — but with plain loads that race is undefined
// behavior and a true ThreadSanitizer report (the classic seqlock
// pitfall).  Atomic accesses make the race defined (any value may be
// read; the seq re-check rejects inconsistent ones) and keep the TSan
// gate (scripts/check_tsan_native.sh) meaningful for the REAL protocol
// bugs.  BOARD_LEN is 4 doubles — the per-element cost is noise.
static_assert(sizeof(double) == sizeof(uint64_t), "seqlock payload");

static inline void seqlock_store(double* dst, const double* src, i64 len) {
  auto* d = reinterpret_cast<std::atomic<uint64_t>*>(dst);
  for (i64 i = 0; i < len; ++i) {
    uint64_t bits;
    std::memcpy(&bits, src + i, sizeof bits);
    d[i].store(bits, std::memory_order_relaxed);
  }
}

static inline void seqlock_load(double* dst, const double* src, i64 len) {
  auto* s = reinterpret_cast<const std::atomic<uint64_t>*>(src);
  for (i64 i = 0; i < len; ++i) {
    uint64_t bits = s[i].load(std::memory_order_relaxed);
    std::memcpy(dst + i, &bits, sizeof bits);
  }
}

// Publish len doubles into my board slot.  Odd seq = write in progress,
// even = committed; returns the committed version (>= 2).
i64 slu_tree_post(void* vh, double* buf, i64 len) {
  using namespace slu_tree;
  auto* h = (Handle*)vh;
  RankSlot& mine = h->slots[h->rank];
  double* my_buf = h->bufs + (size_t)h->rank * h->hdr->max_len;
  uint64_t s = mine.seq.load(std::memory_order_relaxed) & ~1ull;
  mine.seq.store(s + 1, std::memory_order_release);
  std::atomic_thread_fence(std::memory_order_release);
  seqlock_store(my_buf, buf, len);
  mine.seq.store(s + 2, std::memory_order_release);
  return (i64)(s + 2);
}

// Read rank's board slot into out.  Returns the committed version read
// (0 = never posted, -1 = could not get a consistent snapshot — e.g.
// the writer died mid-post; callers treat both as "no data").
i64 slu_tree_peek(void* vh, i64 rank, double* out, i64 len) {
  using namespace slu_tree;
  auto* h = (Handle*)vh;
  RankSlot& rs = h->slots[rank];
  const double* rb = h->bufs + (size_t)rank * h->hdr->max_len;
  for (int tries = 0; tries < 200; ++tries) {
    uint64_t s1 = rs.seq.load(std::memory_order_acquire);
    if (s1 == 0) return 0;
    if (s1 & 1) {
      ::usleep(20);
      continue;
    }
    seqlock_load(out, rb, len);
    std::atomic_thread_fence(std::memory_order_acquire);
    if (rs.seq.load(std::memory_order_acquire) == s1) return (i64)s1;
  }
  return -1;
}

}  // extern "C"
