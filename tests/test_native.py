"""Native host-analysis library vs the Python specification.

The C++ kernels (native/slu_host.cpp) must produce bit-identical analysis
results to the Python implementations they accelerate — same etree, same
postorder, same supernode partition/rows, same matching + scalings.  The
Python code is the oracle (the reference's analog: serial vs parallel
symbolic producing identical structures).
"""

import numpy as np
import pytest

from superlu_dist_tpu import native
from superlu_dist_tpu.models.gallery import (
    poisson2d, random_sparse, convection_diffusion_2d)
from superlu_dist_tpu.sparse.formats import SparseCSR, symmetrize_pattern
from superlu_dist_tpu.ordering.etree import etree_symmetric, postorder
from superlu_dist_tpu.ordering.dissection import bfs_nd
from superlu_dist_tpu.symbolic.symbfact import symbolic_factorize

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library unavailable")


def _cases():
    return [
        symmetrize_pattern(poisson2d(15)),
        symmetrize_pattern(random_sparse(150, density=0.04, seed=1)),
        symmetrize_pattern(convection_diffusion_2d(12)),
    ]


def test_etree_and_postorder_match_python():
    for sym in _cases():
        n = sym.n_rows
        pn = native.etree(n, sym.indptr, sym.indices)
        pp = etree_symmetric(n, sym.indptr, sym.indices)
        assert np.array_equal(pn, pp)
        assert np.array_equal(native.postorder(pp), postorder(pp))


@pytest.mark.parametrize("relax,maxs", [(1, 8), (8, 32), (20, 256)])
def test_symbolic_matches_python(relax, maxs, monkeypatch):
    for sym in _cases():
        n = sym.n_rows
        order = np.arange(n)
        # Python-only run (native disabled via env knob)
        monkeypatch.setenv("SLU_TPU_NO_NATIVE", "1")
        native._tried, native._lib = False, None
        sf_py = symbolic_factorize(sym, order, relax=relax, max_supernode=maxs)
        monkeypatch.delenv("SLU_TPU_NO_NATIVE")
        native._tried, native._lib = False, None
        sf_nat = symbolic_factorize(sym, order, relax=relax, max_supernode=maxs)
        assert np.array_equal(sf_py.sn_start, sf_nat.sn_start)
        assert np.array_equal(sf_py.sn_parent, sf_nat.sn_parent)
        assert np.array_equal(sf_py.sn_level, sf_nat.sn_level)
        assert sf_py.nnz_L == sf_nat.nnz_L
        for rp, rn in zip(sf_py.sn_rows, sf_nat.sn_rows):
            assert np.array_equal(rp, rn)


def test_mc64_matches_python():
    from superlu_dist_tpu.rowperm import matching as m
    for seed in range(3):
        a = random_sparse(90, density=0.07, seed=seed)
        import superlu_dist_tpu.native as nat
        csc = a.tocsc()
        cm, u, v = nat.mc64(a.n_rows, csc.indptr, csc.indices,
                            np.abs(csc.data))
        # python path forced
        import os
        os.environ["SLU_TPU_NO_NATIVE"] = "1"
        nat._tried, nat._lib = False, None
        try:
            ro, r, c = m.maximum_product_matching(a)
        finally:
            del os.environ["SLU_TPU_NO_NATIVE"]
            nat._tried, nat._lib = False, None
        assert np.array_equal(cm, ro)
        colmax = np.zeros(a.n_rows)
        cols = np.repeat(np.arange(a.n_rows), np.diff(csc.indptr))
        np.maximum.at(colmax, cols, np.abs(csc.data))
        np.testing.assert_allclose(np.exp(np.clip(v, -700, 700)), r,
                                   rtol=1e-10)
        np.testing.assert_allclose(
            np.exp(np.clip(u - np.log(colmax), -700, 700)), c, rtol=1e-10)


def _per_column_fill(sf):
    """Per-column below-diagonal fill counts — invariant across valid
    supernode partitions of the same (zero-fill-merged) structure."""
    last = sf.sn_start[1:] - 1
    out = np.empty(sf.n, dtype=np.int64)
    for s in range(sf.n_supernodes):
        for j in range(int(sf.sn_start[s]), int(sf.sn_start[s + 1])):
            out[j] = (last[s] - j) + len(sf.sn_rows[s])
    return out


@pytest.mark.parametrize("nthreads", [2, 4])
def test_threaded_symbolic_same_fill(nthreads):
    """The threaded symbolic (symbfact_dist analog) must produce the same
    per-column fill as serial; the supernode partition may differ only by
    boundary chain merges."""
    from superlu_dist_tpu.models.gallery import poisson3d
    for sym in _cases() + [symmetrize_pattern(poisson3d(8))]:
        n = sym.n_rows
        order = np.arange(n)
        ser = symbolic_factorize(sym, order, relax=4, max_supernode=64,
                                 amalg_tol=0)
        par = symbolic_factorize(sym, order, relax=4, max_supernode=64,
                                 nthreads=nthreads, amalg_tol=0)
        assert np.array_equal(_per_column_fill(ser), _per_column_fill(par))
        assert par.nnz_L >= ser.nnz_L   # fewer merges => never less padding


def test_threaded_symbolic_end_to_end():
    """Solve through a threaded-symbolic factorization."""
    from superlu_dist_tpu.drivers.gssvx import gssvx
    from superlu_dist_tpu.utils.options import Options
    from superlu_dist_tpu.models.gallery import poisson2d
    import os
    a = poisson2d(12)
    xt = np.random.default_rng(0).standard_normal(a.n_rows)
    b = a.matvec(xt)
    os.environ["SLU_TPU_SYMB_THREADS"] = "4"
    try:
        x, lu, stats, info = gssvx(Options(), a, b)
    finally:
        del os.environ["SLU_TPU_SYMB_THREADS"]
    assert info == 0
    np.testing.assert_allclose(x, xt, rtol=1e-8, atol=1e-8)


def test_mmd_matches_python():
    """Native exact-MD must match the Python oracle bit-for-bit (same
    algorithm, same tie-breaking)."""
    import os
    from superlu_dist_tpu.ordering import minimum_degree as md_mod
    for sym in _cases():
        n = sym.n_rows
        got = native.mmd(n, sym.indptr, sym.indices)
        os.environ["SLU_TPU_NO_NATIVE"] = "1"
        native._tried, native._lib = False, None
        try:
            want = md_mod.minimum_degree(n, sym.indptr, sym.indices)
        finally:
            del os.environ["SLU_TPU_NO_NATIVE"]
            native._tried, native._lib = False, None
        assert np.array_equal(got, want)


def test_mmd_scales_beyond_python():
    """The native MD must handle sizes the Python sets version cannot."""
    sym = symmetrize_pattern(poisson2d(45))       # n = 2025
    n = sym.n_rows
    order = native.mmd(n, sym.indptr, sym.indices)
    assert sorted(order) == list(range(n))
    sf = symbolic_factorize(sym, order, relax=1, max_supernode=64,
                            amalg_tol=0)
    nat = symbolic_factorize(sym, np.arange(n), relax=1, max_supernode=64,
                             amalg_tol=0)
    assert sf.nnz_L < 0.5 * nat.nnz_L             # real fill reduction


def test_mlnd_is_valid_permutation_and_beats_bfs():
    a = symmetrize_pattern(random_sparse(600, density=0.02, seed=4))
    n = a.n_rows
    order = native.mlnd(n, a.indptr, a.indices)
    assert sorted(order) == list(range(n))

    def fill(o):
        return symbolic_factorize(a, o, relax=1, max_supernode=64,
                                  amalg_tol=0).nnz_L

    # the multilevel ordering must clearly beat the BFS level-set fallback
    assert fill(order) < fill(bfs_nd(n, a.indptr, a.indices))


def test_mlnd_fill_quality_vs_scipy_colamd():
    """VERDICT r1 gate: fill within ~2x of scipy COLAMD on an irregular
    matrix (the reference's METIS_AT_PLUS_A quality bar)."""
    sp = pytest.importorskip("scipy.sparse")
    spl = pytest.importorskip("scipy.sparse.linalg")
    a0 = random_sparse(500, density=0.02, seed=11)
    sym = symmetrize_pattern(a0)
    n = sym.n_rows
    order = native.mlnd(n, sym.indptr, sym.indices)
    sf = symbolic_factorize(sym, order, relax=1, max_supernode=64,
                            amalg_tol=0)
    data = np.where(sym.data == 0, 1e-8, sym.data)
    A = sp.csr_matrix((data, sym.indices, sym.indptr), shape=(n, n)).tocsc()
    lu = spl.splu(A, permc_spec="COLAMD",
                  options=dict(SymmetricMode=False))
    assert sf.nnz_L <= 2.0 * lu.L.nnz, (sf.nnz_L, lu.L.nnz)

