"""slulint — project-native static analysis (docs/ANALYSIS.md).

Rules:
  SLU101 collective-consistency   (rules_collective.py)
  SLU102 trace-purity             (rules_trace.py)
  SLU103 index-width discipline   (rules_index.py)
  SLU104 env-knob registry        (rules_env.py)
  SLU105 jit-cache-key hygiene    (rules_trace.py)

CLI: ``python -m superlu_dist_tpu.analysis`` (scripts/slulint.py is the
same entry; scripts/run_slulint.sh is the CI gate).
"""

from superlu_dist_tpu.analysis.core import (Finding, Rule, analyze_paths,
                                            analyze_source, default_rules)

__all__ = ["Finding", "Rule", "analyze_paths", "analyze_source",
           "default_rules"]
