"""SLU108 — unguarded shared-mutable access.

The serving tier's correctness rests on every cross-thread touch of a
``SolveServer``/detector attribute happening under the owning lock (the
PR 10 submit/close race was exactly one missed case).  The rule encodes
that contract: for every class that spawns a ``threading.Thread``, an
attribute *written on the thread side* (the target method or any of its
transitive same-class callees, resolved through the call graph) must
only be touched on the public-API side under the class's lock.

What counts as guarded (analysis/concurrency.py):

* lexically inside ``with self._lock:`` / ``with self._cond:`` (a
  ``Condition(self._lock)`` aliases onto the lock it wraps — one mutex);
* inside a method whose every in-class call site is under the guard
  (the ``*_locked`` caller-holds-the-lock idiom, verified — the naming
  convention alone is also honored as an explicit assertion).

Exempt: lock/condition/event/thread attributes themselves (events are
their own synchronization), methods, and attributes never written
outside ``__init__`` (immutable-after-construction state needs no
lock).  False-negative-leaning: an unresolvable thread target drops the
class from the scan entirely.
"""

from __future__ import annotations

from superlu_dist_tpu.analysis.concurrency import attr_accesses, get_model
from superlu_dist_tpu.analysis.core import Finding, Rule


class SharedMutableRule(Rule):
    rule_id = "SLU108"
    title = "unguarded shared-mutable access"
    hint = ("guard every cross-thread access with the owning lock "
            "(`with self._lock:`), move it into a *_locked helper called "
            "under the lock, or make the attribute immutable before the "
            "thread starts")

    def check(self, tree, source, path, project=None):
        if project is None:
            return []
        model = get_model(project)
        out = []
        for cq, cm in model.classes.items():
            if not cm.thread_side:
                continue
            fns = [fi for q, fi in project.functions.items()
                   if q.startswith(cq + ".")
                   and model.class_for(fi) is cm]
            if not any(fi.path == path for fi in fns):
                continue
            out.extend(self._check_class(model, cm, fns, path))
        return out

    # ------------------------------------------------------------------
    def _check_class(self, model, cm, fns, path):
        exempt = (cm.guard_attrs() | cm.event_attrs
                  | set(cm.thread_attrs) | set(cm.methods))
        # (attr -> [(fi, node, guarded, is_write)]) split by side
        thread_acc: dict = {}
        public_acc: dict = {}
        for fi in fns:
            if fi.name == "__init__":
                continue
            held_at = {id(n): locks
                       for n, locks in model._held_spans(cm, fi)}
            base = fi.qname in model.lock_context
            side = thread_acc if fi.qname in cm.thread_side \
                else public_acc
            for attr, is_write, node in attr_accesses(fi):
                if attr in exempt:
                    continue
                guarded = base or bool(held_at.get(id(node)))
                side.setdefault(attr, []).append(
                    (fi, node, guarded, is_write))
        out = []
        for attr, taccs in sorted(thread_acc.items()):
            twrites = [a for a in taccs if a[3]]
            if not twrites:
                continue
            pubs = public_acc.get(attr, ())
            if not pubs:
                continue
            wfi, wnode, _, _ = twrites[0]
            witness = (f"`{wfi.qname.rsplit('.', 1)[-1]}` at "
                       f"{wfi.path}:{wnode.lineno}")
            for fi, node, guarded, is_write in pubs:
                if guarded:
                    continue
                verb = "written" if is_write else "read"
                out.append(Finding(
                    self.rule_id, path, node.lineno,
                    node.col_offset + 1,
                    f"`self.{attr}` is {verb} here without the owning "
                    f"lock, but a background thread of `{cm.qname}` "
                    f"writes it ({witness}) — cross-thread data race",
                    self.hint))
            if not all(g for _, _, g, _ in twrites):
                fi, node, _, _ = next(a for a in twrites if not a[2])
                if fi.path == path:
                    out.append(Finding(
                        self.rule_id, path, node.lineno,
                        node.col_offset + 1,
                        f"thread-side write of `self.{attr}` (thread "
                        f"target side of `{cm.qname}`) without the "
                        "owning lock, while the public API also touches "
                        "it — cross-thread data race",
                        self.hint))
        return out
