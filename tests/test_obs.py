"""Observability subsystem (obs/trace.py + compile census + flight
recorder + metrics + comm/kernel telemetry + cross-rank stat
reduction) — the PROFlevel analog.

Covers: span nesting/ordering and both artifact formats (Chrome
trace-event JSON with wall-clock anchor, JSONL sidecar), the
guaranteed-negligible disabled paths (no file / no ring / no registry,
reused no-op singletons), comm counters against a 2-rank TreeComm
exchange with known byte counts, kernel-shape records from both
factorization executors and the device solve, Stats.timer reentrancy,
Stats.reduce min/max/avg + load-balance factors, the compile census
(cold builds recorded with bucket keys + compile trace spans, warm
reruns silent, stats.compile block), flight-recorder postmortems
(bounded ring, dump on provoked NumericBreakdownError and 2-rank
CollectiveMismatchError, tracer composition), the metrics registry
(exports, TreeComm wiring, 2-rank collective reduction, recovery-rung
counters), the bench row's compile/phase acceptance fields, and the
perf-regression gate's seeding/enforcement state machine.
"""

import json
import multiprocessing as mp
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from superlu_dist_tpu import native
from superlu_dist_tpu.obs import trace
from superlu_dist_tpu.utils.stats import (
    COMM_OPS, CommStats, PHASES, Stats, StatsSummary)

pytestmark = pytest.mark.obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _tracer_hygiene(monkeypatch):
    """Every test starts and ends with the env-driven telemetry state
    reset (tracer, flight recorder, and metrics are all latched on
    first use)."""
    from superlu_dist_tpu.obs import flightrec, metrics
    for knob in ("SLU_TPU_TRACE", "SLU_TPU_FLIGHTREC", "SLU_TPU_METRICS"):
        monkeypatch.delenv(knob, raising=False)
    trace._reset()
    flightrec._reset()
    metrics._reset()
    yield
    trace._reset()
    flightrec._reset()
    metrics._reset()


# ---------------------------------------------------------------------------
# span tracer
# ---------------------------------------------------------------------------

def test_span_nesting_and_jsonl(tmp_path):
    t = trace.Tracer(str(tmp_path / "t.json"))
    with t.span("outer", cat="phase", who="test"):
        time.sleep(0.002)
        with t.span("inner", cat="kernel", m=8, w=4):
            time.sleep(0.002)
        with t.span("inner2", cat="comm", bytes=64):
            pass
    t.close()
    rows = [json.loads(line) for line in open(tmp_path / "t.jsonl")]
    # the first record is the wall-clock anchor written at tracer open
    assert [r["name"] for r in rows] == ["clock-anchor", "inner", "inner2",
                                         "outer"]
    assert rows[0]["args"]["unix_time"] > 0
    by = {r["name"]: r for r in rows}
    outer, inner = by["outer"], by["inner"]
    # nesting: children start after and end before the parent
    assert inner["ts"] >= outer["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"]
    assert by["inner2"]["ts"] >= inner["ts"] + inner["dur"]
    # depth reflects nesting at record time
    assert outer["depth"] == 0 and inner["depth"] == 1
    assert inner["args"] == {"m": 8, "w": 4}
    assert outer["args"] == {"who": "test"}


def test_chrome_trace_artifact_valid(tmp_path):
    path = str(tmp_path / "t.json")
    t = trace.Tracer(path)
    with t.span("a", cat="phase"):
        with t.span("b", cat="kernel"):
            pass
    t.complete("c", "comm", time.perf_counter() - 0.5, 0.01, bytes=3)
    t.close()
    doc = json.load(open(path))
    events = doc["traceEvents"]
    assert len(events) == 4          # 3 spans + the wall-clock anchor
    for ev in events:
        assert ev["ph"] == "X"
        for key in ("name", "cat", "ts", "dur", "pid", "tid"):
            assert key in ev
        assert ev["cat"] in trace.CATEGORIES
    # events are sorted: ts monotone per (pid, tid)
    last = {}
    for ev in events:
        key = (ev["pid"], ev["tid"])
        assert ev["ts"] >= last.get(key, float("-inf"))
        last[key] = ev["ts"]


def test_span_set_attaches_midspan_attrs(tmp_path):
    t = trace.Tracer(str(tmp_path / "t.json"))
    with t.span("s", cat="dispatch") as sp:
        sp.set(result_bytes=128)
    t.close()
    rows = [json.loads(line) for line in open(tmp_path / "t.jsonl")]
    assert rows[1]["args"] == {"result_bytes": 128}   # rows[0] = anchor


def test_disabled_path_is_noop(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    t = trace.get_tracer()
    assert t is trace.NULL_TRACER
    assert not t.enabled
    # one reused no-op span object, regardless of args
    assert t.span("a") is t.span("b", cat="kernel", x=1)
    with t.span("a") as sp:
        sp.set(ignored=True)
    t.complete("x", "comm", 0.0, 1.0)
    t.flush()
    t.close()
    assert os.listdir(tmp_path) == []        # nothing written, ever
    # near-zero overhead: a hundred thousand disabled spans in well under
    # a second (they allocate nothing and read no clock)
    t0 = time.perf_counter()
    for _ in range(100_000):
        with t.span("hot", cat="kernel"):
            pass
    assert time.perf_counter() - t0 < 1.0


def test_env_gated_tracer(tmp_path, monkeypatch):
    path = str(tmp_path / "run.json")
    monkeypatch.setenv("SLU_TPU_TRACE", path)
    trace._reset()
    t = trace.get_tracer()
    assert isinstance(t, trace.Tracer) and t.enabled
    with trace.span("gated", cat="phase"):
        pass
    trace._reset()                            # closes + flushes
    doc = json.load(open(path))
    names = [e["name"] for e in doc["traceEvents"]]
    assert names == ["clock-anchor", "gated"]
    assert (tmp_path / "run.jsonl").exists()


def test_install_programmatic(tmp_path):
    t = trace.Tracer(str(tmp_path / "p.json"))
    prev = trace.install(t)
    try:
        assert trace.enabled()
        with trace.span("prog", cat="phase"):
            pass
    finally:
        trace.install(prev)
        t.close()
    rows = [json.loads(line) for line in open(tmp_path / "p.jsonl")]
    assert [r["name"] for r in rows] == ["clock-anchor", "prog"]


# ---------------------------------------------------------------------------
# kernel-shape telemetry (both executors + device solve)
# ---------------------------------------------------------------------------

def _small_plan():
    from superlu_dist_tpu.models.gallery import poisson2d
    from superlu_dist_tpu.numeric.plan import build_plan
    from superlu_dist_tpu.sparse.formats import symmetrize_pattern
    from superlu_dist_tpu.symbolic.symbfact import symbolic_factorize

    a = poisson2d(6)
    sym = symmetrize_pattern(a)
    sf = symbolic_factorize(sym, np.arange(a.n_rows), relax=4,
                            max_supernode=16)
    plan = build_plan(sf)
    return plan, sym.data[sf.value_perm]


def test_stream_executor_kernel_spans(tmp_path):
    import jax.numpy as jnp
    from superlu_dist_tpu.numeric.stream import StreamExecutor

    plan, avals = _small_plan()
    t = trace.Tracer(str(tmp_path / "s.json"))
    prev = trace.install(t)
    try:
        ex = StreamExecutor(plan, "float64")
        ex(jnp.asarray(avals), jnp.asarray(0.0))
    finally:
        trace.install(prev)
        t.close()
    events = json.load(open(tmp_path / "s.json"))["traceEvents"]
    kernels = [e for e in events if e["cat"] == "kernel"]
    dispatch = [e for e in events if e["cat"] == "dispatch"]
    assert len(kernels) == len(plan.groups)
    assert len(dispatch) == len(plan.groups)
    for k in kernels:
        args = k["args"]
        for key in ("level", "batch", "padded_batch", "m", "w", "u",
                    "executed_flops", "structural_flops", "padding"):
            assert key in args, (key, args)
        assert args["executed_flops"] >= args["structural_flops"] > 0
        assert args["padding"] >= 1.0
    # tracing implies the profile record too (no stderr scraping needed,
    # but the legacy consumer keeps working)
    assert len(ex.last_profile) == len(plan.groups)


def test_fused_executor_kernel_span(tmp_path):
    import jax.numpy as jnp
    from superlu_dist_tpu.numeric.factor import make_factor_fn

    plan, avals = _small_plan()
    fn = make_factor_fn(plan, "float64")
    t = trace.Tracer(str(tmp_path / "f.json"))
    prev = trace.install(t)
    try:
        fn(jnp.asarray(avals), jnp.asarray(0.0))
    finally:
        trace.install(prev)
        t.close()
    events = json.load(open(tmp_path / "f.json"))["traceEvents"]
    kernels = [e for e in events if e["cat"] == "kernel"]
    assert len(kernels) == 1 and kernels[0]["name"] == "factor-fused"
    args = kernels[0]["args"]
    assert args["aggregate"] and args["structural_flops"] == plan.flops
    assert any(e["cat"] == "dispatch" for e in events)


def test_device_solve_spans(tmp_path):
    from superlu_dist_tpu.drivers.gssvx import gssvx
    from superlu_dist_tpu.models.gallery import poisson2d
    from superlu_dist_tpu.solve.device import DeviceSolver
    from superlu_dist_tpu.utils.options import IterRefine, Options

    a = poisson2d(7)
    b = np.ones(a.n_rows)
    x, lu, stats, info = gssvx(Options(iter_refine=IterRefine.NOREFINE),
                               a, b)
    assert info == 0
    t = trace.Tracer(str(tmp_path / "d.json"))
    prev = trace.install(t)
    try:
        DeviceSolver(lu.numeric).solve(np.ones(a.n_rows))
    finally:
        trace.install(prev)
        t.close()
    events = json.load(open(tmp_path / "d.json"))["traceEvents"]
    solve = [e for e in events if e["name"] == "device-solve"]
    assert len(solve) == 1 and solve[0]["cat"] == "kernel"
    assert solve[0]["args"]["nrhs"] == 1
    d2h = [e for e in events if e["name"] == "solve-d2h"]
    assert len(d2h) == 1 and d2h[0]["cat"] == "comm"
    assert d2h[0]["args"]["bytes"] > 0


def test_gssvx_emits_phase_spans(tmp_path):
    import superlu_dist_tpu as slu
    from superlu_dist_tpu.models.gallery import poisson2d

    t = trace.Tracer(str(tmp_path / "g.json"))
    prev = trace.install(t)
    try:
        a = poisson2d(6)
        x, lu, stats, info = slu.gssvx(slu.Options(), a,
                                       np.ones(a.n_rows))
        assert info == 0
    finally:
        trace.install(prev)
        t.close()
    events = json.load(open(tmp_path / "g.json"))["traceEvents"]
    phases = {e["name"] for e in events if e["cat"] == "phase"}
    assert {"EQUIL", "ROWPERM", "COLPERM", "SYMBFACT", "DIST", "FACT",
            "SOLVE"} <= phases


# ---------------------------------------------------------------------------
# Stats.timer reentrancy (satellite regression)
# ---------------------------------------------------------------------------

def test_stats_timer_reentrant_same_phase():
    """Nested enters of the SAME phase must not double-count: the outer
    enter owns the accumulation (the old implementation added the inner
    elapsed a second time)."""
    s = Stats()
    with s.timer("FACT"):
        time.sleep(0.05)
        with s.timer("FACT"):
            time.sleep(0.05)
    assert 0.09 <= s.utime["FACT"] < 0.14, s.utime["FACT"]
    assert s._timer_depth["FACT"] == 0


def test_stats_timer_sequential_accumulates():
    s = Stats()
    for _ in range(2):
        with s.timer("SOLVE"):
            time.sleep(0.02)
    assert s.utime["SOLVE"] >= 0.04


def test_stats_timer_reentrant_under_exception():
    s = Stats()
    with pytest.raises(RuntimeError):
        with s.timer("FACT"):
            with s.timer("FACT"):
                raise RuntimeError("boom")
    assert s._timer_depth["FACT"] == 0
    with s.timer("FACT"):        # still usable afterwards
        pass
    assert s.utime["FACT"] > 0


# ---------------------------------------------------------------------------
# cross-rank stat reduction
# ---------------------------------------------------------------------------

class _FakeComm:
    """Two-rank comm stub: rank 0's matrix summed with a preloaded rank-1
    row — exercises the reduce math without the native transport."""

    n_ranks = 2
    rank = 0

    def __init__(self, peer_stats: Stats):
        self._peer_vec = peer_stats._pack()

    def allreduce_sum_any(self, arr, root=0):
        out = np.array(arr, dtype=np.float64)
        out[1] += self._peer_vec
        return out


def test_stats_reduce_min_max_avg_balance():
    s0, s1 = Stats(), Stats()
    s0.utime["FACT"], s1.utime["FACT"] = 1.0, 3.0
    s0.ops["FACT"] = s1.ops["FACT"] = 50.0
    s0.tiny_pivots, s1.tiny_pivots = 2, 3
    s1.comm = {"bcast": {"calls": 4, "bytes": 256, "seconds": 0.5}}
    summary = s0.reduce(_FakeComm(s1))
    assert isinstance(summary, StatsSummary)
    f = summary.utime["FACT"]
    assert f.min == 1.0 and f.max == 3.0 and f.avg == 2.0
    assert abs(f.balance - 1.5) < 1e-12
    assert abs(summary.balance("FACT") - 1.5) < 1e-12
    assert summary.ops["FACT"].total == 100.0
    assert summary.tiny_pivots == 5
    assert summary.comm["bcast"]["calls"] == 4
    assert summary.comm["bcast"]["bytes"] == 256
    rep = summary.report()
    assert "FACT" in rep and "balance" in rep.splitlines()[2]
    # untouched phases don't clutter the report
    assert "EQUIL" not in rep


def test_comm_stats_accounting_and_report():
    cs = CommStats()
    cs.add("bcast", 64, 0.01)
    cs.add("bcast", 64, 0.01)
    cs.add("allreduce", 128, 0.02)
    t = cs.totals()
    assert t["bcast"] == {"calls": 2, "bytes": 128, "seconds": 0.02}
    assert "reduce" not in t                  # zero ops stay out
    assert "bcast" in cs.report()
    s = Stats()
    s.attach_comm(cs)
    assert "comm bcast" in s.report()


# ---------------------------------------------------------------------------
# 2-rank native transport: comm counters with known byte counts + reduce
# ---------------------------------------------------------------------------

def _exchange(tc):
    """The scripted 2-rank exchange: 1 bcast, 1 reduce, 1 allreduce of
    8 float64 each (single chunk at max_len=64)."""
    from superlu_dist_tpu.utils.stats import Stats

    buf = np.arange(8.0) if tc.rank == 0 else np.zeros(8)
    tc.bcast(buf, root=0)
    ok = bool(np.array_equal(buf, np.arange(8.0)))
    buf2 = np.full(8, float(tc.rank + 1))
    tc.reduce_sum(buf2, root=0)
    buf3 = np.ones(8)
    tc.allreduce_sum(buf3, root=0)
    totals = tc.comm_stats.totals()
    st = Stats()
    st.utime["FACT"] = float(tc.rank + 1)
    st.ops["FACT"] = 100.0
    st.tiny_pivots = tc.rank
    st.attach_comm(tc.comm_stats)
    summary = st.reduce(tc)
    return ok, totals, summary


def _obs_rank_worker(name, n_ranks, rank, q):
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    tc = TreeComm(name, n_ranks, rank, max_len=64, create=False)
    try:
        q.put((rank,) + _exchange(tc))
    finally:
        tc.close()


@pytest.mark.skipif(not native.available(),
                    reason="native library unavailable")
def test_comm_counters_and_reduce_two_ranks():
    from superlu_dist_tpu.parallel.treecomm import TreeComm

    name = f"/slu_obs_comm_{os.getpid()}"
    owner = TreeComm(name, 2, 0, max_len=64, create=True)
    try:
        ctx = mp.get_context("spawn")     # no fork of the jax-laden parent
        q = ctx.Queue()
        p = ctx.Process(target=_obs_rank_worker, args=(name, 2, 1, q))
        p.start()
        ok0, totals0, summary0 = _exchange(owner)
        rank1, ok1, totals1, summary1 = q.get(timeout=120)
        p.join(timeout=120)
        assert p.exitcode == 0
    finally:
        owner.close(unlink=True)
    assert ok0 and ok1
    for totals in (totals0, totals1):
        # known byte counts: 8 float64 = 64 bytes per leg
        assert totals["bcast"] == {"calls": 1, "bytes": 64,
                                   "seconds": totals["bcast"]["seconds"]}
        assert totals["reduce"]["calls"] == 1
        assert totals["reduce"]["bytes"] == 64
        # the composite attributes BOTH its legs to "allreduce"
        assert totals["allreduce"]["calls"] == 2
        assert totals["allreduce"]["bytes"] == 128
    # every rank computed the SAME cross-rank summary
    for summary in (summary0, summary1):
        f = summary.utime["FACT"]
        assert f.min == 1.0 and f.max == 2.0 and f.avg == 1.5
        assert abs(f.balance - 2.0 / 1.5) < 1e-12
        assert summary.tiny_pivots == 1
        assert summary.ops["FACT"].total == 200.0
        # comm totals summed over ranks
        assert summary.comm["bcast"]["bytes"] == 128
        assert summary.comm["allreduce"]["bytes"] == 256


# ---------------------------------------------------------------------------
# comm spans from the tree collectives
# ---------------------------------------------------------------------------

@pytest.mark.skipif(not native.available(),
                    reason="native library unavailable")
def test_single_rank_comm_spans(tmp_path):
    from superlu_dist_tpu.parallel.treecomm import TreeComm

    t = trace.Tracer(str(tmp_path / "c.json"))
    prev = trace.install(t)
    try:
        name = f"/slu_obs_span_{os.getpid()}"
        with TreeComm(name, 1, 0, max_len=16, create=True) as tc:
            tc.bcast(np.ones(4))
            tc.allreduce_sum(np.ones(4))
            tc.bcast_bytes(b"hello")
    finally:
        trace.install(prev)
        t.close()
    events = json.load(open(tmp_path / "c.json"))["traceEvents"]
    comm = [e for e in events if e["cat"] == "comm"]
    ops = {e["args"]["op"] for e in comm}
    assert {"bcast", "allreduce", "bcast_bytes"} <= ops
    for e in comm:
        assert e["args"]["bytes"] > 0
        assert e["name"].startswith("tree-")


# ---------------------------------------------------------------------------
# mfu_report: structured-trace parsing + explicit empty-input diagnostic
# ---------------------------------------------------------------------------

def _run_mfu(*args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "mfu_report.py"),
         *args],
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE)


def test_mfu_report_missing_inputs_diagnostic(tmp_path):
    r = _run_mfu(str(tmp_path / "no.jsonl"), str(tmp_path / "no.err"))
    assert r.returncode == 1
    assert b"no trace rows found" in r.stderr


def test_mfu_report_prefers_structured_trace(tmp_path):
    t = trace.Tracer(str(tmp_path / "k.json"))
    t.complete("lu b4 m32 w16 u16", "kernel", 0.0, 0.005, level=2,
               batch=3, padded_batch=4, m=32, w=16, u=16,
               executed_flops=4.0e7, structural_flops=3.0e7, padding=1.33)
    t.close()
    for artifact in ("k.json", "k.jsonl"):
        r = _run_mfu(str(tmp_path / "no.jsonl"), str(tmp_path / artifact))
        assert r.returncode == 0, r.stderr
        out = r.stdout.decode()
        assert "structured trace" in out
        assert "m=32" in out and "lvl=2" in out


def test_mfu_report_legacy_stderr_still_parses(tmp_path):
    err = tmp_path / "legacy.err"
    err.write_text("# lvl=3  B=16  m=512  w=256  u=256  12.34 ms  "
                   "567.8 GF/s\n")
    r = _run_mfu(str(tmp_path / "no.jsonl"), str(err))
    assert r.returncode == 0, r.stderr
    out = r.stdout.decode()
    assert "legacy stderr" in out and "m=512" in out


# ---------------------------------------------------------------------------
# compile census (obs/compilestats.py): cold builds recorded, warm silent
# ---------------------------------------------------------------------------

def test_compile_census_cold_then_warm_stream(tmp_path):
    import jax.numpy as jnp
    from superlu_dist_tpu.numeric import stream as stream_mod
    from superlu_dist_tpu.obs.compilestats import COMPILE_STATS

    plan, avals = _small_plan()
    stream_mod._CENSUSED_KEYS.clear()
    m0 = COMPILE_STATS.marker()
    t = trace.Tracer(str(tmp_path / "c.json"))
    prev = trace.install(t)
    try:
        ex = stream_mod.StreamExecutor(plan, "float64")
        ex(jnp.asarray(avals), jnp.asarray(0.0))
        cold = COMPILE_STATS.marker() - m0
        assert cold > 0
        # warm rerun: every key censused, nothing new recorded
        ex(jnp.asarray(avals), jnp.asarray(0.0))
        assert COMPILE_STATS.marker() - m0 == cold
    finally:
        trace.install(prev)
        t.close()
    # record content: site, bucket key, seconds, param count
    recs = COMPILE_STATS.records[m0:]
    assert all(r.site == "stream._kernel" for r in recs)
    assert all(r.key.startswith("lu b") for r in recs)
    assert all(r.seconds >= 0 and r.n_args >= 8 for r in recs)
    # census aggregation ranks buckets by total seconds
    census = COMPILE_STATS.census(m0)
    assert census == sorted(census, key=lambda row: -row["seconds"])
    # the builds landed in the trace as compile-category spans
    events = json.load(open(tmp_path / "c.json"))["traceEvents"]
    spans = [e for e in events if e["cat"] == "compile"]
    assert len(spans) == cold
    for e in spans:
        assert e["name"] == "compile stream._kernel"
        assert "key" in e["args"]


def test_compile_census_fused_and_stats_block():
    import jax.numpy as jnp
    from superlu_dist_tpu.numeric.factor import make_factor_fn
    from superlu_dist_tpu.obs.compilestats import COMPILE_STATS

    plan, avals = _small_plan()
    fn = make_factor_fn(plan, "float64")
    m0 = COMPILE_STATS.marker()
    fn(jnp.asarray(avals), jnp.asarray(0.0))
    assert COMPILE_STATS.marker() - m0 == 1       # one fused program
    fn(jnp.asarray(avals), jnp.asarray(0.0))
    assert COMPILE_STATS.marker() - m0 == 1       # warm: silent
    rec = COMPILE_STATS.records[m0]
    assert rec.site == "make_factor_fn" and rec.key.startswith("fused g")
    blk = COMPILE_STATS.block(since=m0)
    assert blk["builds"] == 1 and blk["seconds"] > 0
    assert blk["census"][0]["site"] == "make_factor_fn"


def test_gssvx_fills_stats_compile_block():
    import superlu_dist_tpu as slu
    from superlu_dist_tpu.models.gallery import poisson2d

    a = poisson2d(9)   # distinct size: guarantees at least one cold build
    x, lu, stats, info = slu.gssvx(slu.Options(), a, np.ones(a.n_rows))
    assert info == 0
    assert isinstance(stats.compile, dict)
    assert {"builds", "seconds", "persistent_hits", "census"} \
        <= set(stats.compile)
    if stats.compile["builds"]:
        assert "compile" in stats.report()


# ---------------------------------------------------------------------------
# flight recorder (obs/flightrec.py)
# ---------------------------------------------------------------------------

def test_flightrec_ring_bounds_and_dump(tmp_path):
    from superlu_dist_tpu.obs import flightrec

    fr = flightrec.FlightRecorder(str(tmp_path / "fr.json"), depth=16)
    with fr.span("FACT", cat="phase"):
        for i in range(40):
            fr.complete(f"ev{i}", "dispatch", time.perf_counter(), 0.0,
                        i=i)
    path = fr.dump("unit-test", detail="ring bounds")
    assert path == str(tmp_path / "fr.json")
    doc = json.load(open(path))
    assert doc["reason"] == "unit-test"
    assert len(doc["events"]) == 16               # bounded, newest kept
    assert doc["total_events"] == 41 and doc["dropped_events"] == 25
    assert doc["events"][-1]["name"] == "FACT"    # span closed last
    assert doc["anchor"]["unix_time"] > 0
    assert "compile" in doc
    # a second dump supersedes (seq advances)
    fr.dump("again")
    assert json.load(open(path))["seq"] == 1


def test_flightrec_is_the_tracer_when_alone(tmp_path, monkeypatch):
    """Flight-only mode: get_tracer() returns the recorder (every
    instrumentation site feeds the ring) but profiling stays OFF — the
    executors must not serialize their dispatch for it."""
    from superlu_dist_tpu.obs import flightrec

    monkeypatch.setenv("SLU_TPU_FLIGHTREC", str(tmp_path / "f-%p.json"))
    flightrec._reset()
    trace._reset()
    t = trace.get_tracer()
    assert isinstance(t, flightrec.FlightRecorder)
    assert t.enabled and not t.profiling and t.path is None
    # both on: a tee that profiles (file tracer wins) and keeps the path
    monkeypatch.setenv("SLU_TPU_TRACE", str(tmp_path / "t.json"))
    flightrec._reset()
    trace._reset()
    t2 = trace.get_tracer()
    assert isinstance(t2, trace.TeeTracer)
    assert t2.profiling and t2.path == str(tmp_path / "t.json")
    with t2.span("both", cat="phase"):
        pass
    trace._reset()
    events = json.load(open(tmp_path / "t.json"))["traceEvents"]
    assert any(e["name"] == "both" for e in events)


def test_flightrec_dump_on_numeric_breakdown(tmp_path):
    """Acceptance: a run killed by an injected breakdown leaves a
    postmortem artifact with the last events and the open phase stack."""
    from superlu_dist_tpu.drivers.gssvx import gssvx
    from superlu_dist_tpu.models.gallery import poisson2d
    from superlu_dist_tpu.obs import flightrec
    from superlu_dist_tpu.utils.errors import NumericBreakdownError
    from superlu_dist_tpu.utils.options import Options, RowPerm

    fr = flightrec.FlightRecorder(str(tmp_path / "post.json"), depth=128)
    prev = flightrec.install(fr)
    trace._reset()            # recompose: the recorder becomes the tracer
    try:
        a = poisson2d(8)
        a.data = a.data.copy()
        a.data[len(a.data) // 2] = np.nan
        with pytest.raises(NumericBreakdownError) as exc:
            gssvx(Options(equil=False, row_perm=RowPerm.NOROWPERM), a,
                  np.ones(a.n_rows))
    finally:
        flightrec.install(prev)
        trace._reset()
    assert exc.value.flightrec_dump == str(tmp_path / "post.json")
    doc = json.load(open(tmp_path / "post.json"))
    assert doc["reason"] == "NumericBreakdownError"
    assert "supernode" in doc["detail"]
    assert doc["events"], "postmortem carries no events"
    names = {e["name"] for e in doc["events"]}
    assert {"EQUIL", "COLPERM"} & names            # recent phase spans
    # the error fired INSIDE the FACT phase: it is still on the stack
    stacks = [tuple(s) for st in doc["phase_stack"].values() for s in st]
    assert ("FACT", "phase") in stacks
    assert "compile" in doc and "anchor" in doc


def _mismatch_flight_worker(name, dump_path, q):
    from superlu_dist_tpu.obs import flightrec, trace as trace_mod
    fr = flightrec.FlightRecorder(dump_path, depth=64)
    flightrec.install(fr)
    trace_mod._reset()
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    from superlu_dist_tpu.utils.errors import CollectiveMismatchError
    tc = TreeComm(name, 2, 1, max_len=64, create=False)
    try:
        x = np.ones(8)
        tc.allreduce_sum_any(x)                  # matched prologue
        tc.reduce_sum_any(x)                     # DIVERGES from the owner
        q.put(("no-error", None))
    except CollectiveMismatchError as exc:
        q.put(("mismatch", exc.flightrec_dump))
    finally:
        tc.close()


@pytest.mark.skipif(not native.available(),
                    reason="native library unavailable")
def test_flightrec_dump_on_collective_mismatch_two_ranks(tmp_path,
                                                         monkeypatch):
    """Acceptance: EVERY rank of a diverged 2-rank run leaves its own
    postmortem naming the mismatch — evidence instead of a deadlock."""
    monkeypatch.setenv("SLU_TPU_VERIFY_COLLECTIVES", "1")
    from superlu_dist_tpu.obs import flightrec
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    from superlu_dist_tpu.utils.errors import CollectiveMismatchError

    owner_path = str(tmp_path / "owner.json")
    worker_path = str(tmp_path / "worker.json")
    fr = flightrec.FlightRecorder(owner_path, depth=64)
    prev = flightrec.install(fr)
    trace._reset()
    name = f"/slu_obs_frmm_{os.getpid()}"
    owner = TreeComm(name, 2, 0, max_len=64, create=True)
    ctx = mp.get_context("fork")
    q = ctx.Queue()
    p = ctx.Process(target=_mismatch_flight_worker,
                    args=(name, worker_path, q))
    p.start()
    try:
        x = np.ones(8)
        owner.allreduce_sum_any(x)
        with pytest.raises(CollectiveMismatchError) as ei:
            owner.bcast_any(x)                   # diverges from the worker
        kind, wdump = q.get(timeout=60)
        p.join(timeout=60)
        assert kind == "mismatch", kind
    finally:
        owner.close(unlink=True)
        flightrec.install(prev)
        trace._reset()
    assert ei.value.flightrec_dump == owner_path
    assert wdump == worker_path
    for path in (owner_path, worker_path):
        doc = json.load(open(path))
        assert doc["reason"] == "CollectiveMismatchError"
        assert "reduce_sum_any" in doc["detail"] \
            and "bcast_any" in doc["detail"]
        # the ring caught the matched prologue's comm legs
        assert any(e["cat"] == "comm" for e in doc["events"])
        assert doc["anchor"]["unix_time"] > 0


# ---------------------------------------------------------------------------
# metrics registry (obs/metrics.py)
# ---------------------------------------------------------------------------

def test_metrics_disabled_path_is_noop(tmp_path, monkeypatch):
    from superlu_dist_tpu.obs import metrics

    m = metrics.get_metrics()
    assert m is metrics.NULL_METRICS and not m.enabled
    assert m.inc("x", 1, op="a") is None
    m.set("g", 2.0)
    m.observe("h", 0.1, op="b")
    assert m.snapshot() == {} and m.to_prometheus() == ""
    # singleton: repeated gets allocate nothing new
    assert metrics.get_metrics() is m


def test_metrics_counters_gauges_histograms_and_exports():
    from superlu_dist_tpu.obs import metrics

    m = metrics.Metrics()
    m.inc("slu_comm_bytes_total", 64, op="bcast")
    m.inc("slu_comm_bytes_total", 64, op="bcast")
    m.inc("slu_comm_bytes_total", 8, op="reduce")
    m.set("slu_schedule_groups", 7)
    m.observe("slu_comm_seconds", 0.004, op="bcast")
    m.observe("slu_comm_seconds", 0.2, op="bcast")
    snap = m.snapshot()
    assert snap["counters"]['slu_comm_bytes_total{op="bcast"}'] == 128.0
    assert snap["gauges"]["slu_schedule_groups"] == 7.0
    h = snap["histograms"]['slu_comm_seconds{op="bcast"}']
    assert h["count"] == 2 and abs(h["sum"] - 0.204) < 1e-12
    assert h["min"] == 0.004 and h["max"] == 0.2
    # exports: JSON round-trips; Prometheus text carries samples + types
    assert json.loads(m.to_json()) == snap
    prom = m.to_prometheus()
    assert "# TYPE slu_comm_bytes_total counter" in prom
    assert 'slu_comm_bytes_total{op="bcast"} 128' in prom
    assert 'slu_comm_seconds_count{op="bcast"} 2' in prom
    assert "# TYPE slu_schedule_groups gauge" in prom


def test_metrics_env_gate_and_treecomm_latch(monkeypatch):
    from superlu_dist_tpu.obs import metrics

    monkeypatch.setenv("SLU_TPU_METRICS", "1")
    metrics._reset()
    m = metrics.get_metrics()
    assert isinstance(m, metrics.Metrics) and m.enabled
    m.inc("gate_check", 1)
    assert metrics.get_metrics() is m            # latched


@pytest.mark.skipif(not native.available(),
                    reason="native library unavailable")
def test_metrics_comm_wiring_single_rank(monkeypatch):
    from superlu_dist_tpu.obs import metrics
    from superlu_dist_tpu.parallel.treecomm import TreeComm

    monkeypatch.setenv("SLU_TPU_METRICS", "1")
    metrics._reset()
    name = f"/slu_obs_mw_{os.getpid()}"
    with TreeComm(name, 1, 0, max_len=16, create=True) as tc:
        assert tc._metrics is not None
        tc.bcast(np.ones(8))                     # 8 f64 = 64 bytes
        tc.allreduce_sum(np.ones(4))
    snap = metrics.get_metrics().snapshot()
    assert snap["counters"]['slu_comm_bytes_total{op="bcast"}'] == 64.0
    assert snap["counters"]['slu_comm_calls_total{op="allreduce"}'] == 2.0
    assert 'slu_comm_seconds{op="bcast"}' in snap["histograms"]
    # and with the knob off, TreeComm latches None (one is-None test)
    monkeypatch.delenv("SLU_TPU_METRICS")
    metrics._reset()
    name2 = f"/slu_obs_mw2_{os.getpid()}"
    with TreeComm(name2, 1, 0, max_len=16, create=True) as tc2:
        assert tc2._metrics is None
        tc2.bcast(np.ones(4))


def _metrics_rank_worker(name, q):
    os.environ["SLU_TPU_METRICS"] = "1"
    from superlu_dist_tpu.obs import metrics
    metrics._reset()
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    tc = TreeComm(name, 2, 1, max_len=64, create=False)
    try:
        m = metrics.get_metrics()
        m.inc("test_rank_contrib", 2.0)          # rank 1 contributes 2
        tc.bcast(np.arange(8.0), root=0)
        q.put((1, m.reduce(tc)))
    finally:
        tc.close()


@pytest.mark.skipif(not native.available(),
                    reason="native library unavailable")
def test_metrics_two_rank_reduce_over_treecomm(monkeypatch):
    """Cross-rank aggregation: both ranks call reduce() collectively and
    get the SAME summed/min/max table (the Stats.reduce discipline)."""
    from superlu_dist_tpu.obs import metrics
    from superlu_dist_tpu.parallel.treecomm import TreeComm

    monkeypatch.setenv("SLU_TPU_METRICS", "1")
    metrics._reset()
    name = f"/slu_obs_mr_{os.getpid()}"
    owner = TreeComm(name, 2, 0, max_len=64, create=True)
    try:
        ctx = mp.get_context("spawn")     # no fork of the jax-laden parent
        q = ctx.Queue()
        p = ctx.Process(target=_metrics_rank_worker, args=(name, q))
        p.start()
        m = metrics.get_metrics()
        m.inc("test_rank_contrib", 1.0)          # rank 0 contributes 1
        owner.bcast(np.arange(8.0), root=0)
        mine = m.reduce(owner)
        rank1, theirs = q.get(timeout=120)
        p.join(timeout=120)
        assert p.exitcode == 0
    finally:
        owner.close(unlink=True)
    contrib = mine["counter:test_rank_contrib"]
    assert contrib["sum"] == 3.0
    assert contrib["min"] == 1.0 and contrib["max"] == 2.0
    # both ranks computed the identical table
    assert theirs["counter:test_rank_contrib"] == contrib
    # the wired comm counters aggregated too (1 bcast leg per rank)
    bk = 'counter:slu_comm_calls_total{op="bcast"}'
    assert mine[bk]["sum"] >= 2.0


def test_escalation_ladder_emits_rung_metrics(monkeypatch):
    """A solve that climbs the recovery ladder counts its rung
    transitions in the registry."""
    from superlu_dist_tpu.drivers.gssvx import gssvx
    from superlu_dist_tpu.models.gallery import hilbert
    from superlu_dist_tpu.obs import metrics
    from superlu_dist_tpu.utils.options import Options

    monkeypatch.setenv("SLU_TPU_METRICS", "1")
    metrics._reset()
    a = hilbert(12)
    x, lu, stats, info = gssvx(Options(), a, np.ones(a.n_rows))
    assert info == 0
    if stats.solve_report is not None and stats.solve_report.rungs:
        snap = metrics.get_metrics().snapshot()
        rung_keys = [k for k in snap["counters"]
                     if k.startswith("slu_recovery_rungs_total")]
        assert rung_keys, snap["counters"]
        assert sum(snap["counters"][k] for k in rung_keys) \
            == len(stats.solve_report.rungs)


# ---------------------------------------------------------------------------
# bench row: compile_seconds + census + phase_seconds (acceptance fields)
# ---------------------------------------------------------------------------

def test_bench_row_carries_compile_and_phase_fields(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_NX="6",
               BENCH_REPS="1", BENCH_NO_PROBE="1", BENCH_FORCE_CPU="1",
               BENCH_DEADLINE_S="240",
               SLU_TPU_FLIGHTREC=str(tmp_path / "bench_fr.json"))
    env.pop("SLU_TPU_TRACE", None)
    r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                       env=env, cwd=REPO, stdout=subprocess.PIPE,
                       stderr=subprocess.PIPE)
    assert r.returncode == 0, r.stderr.decode()
    row = json.loads(r.stdout.decode().strip().splitlines()[-1])
    assert row["value"] is not None
    assert "compile_seconds" in row and row["compile_seconds"] >= 0
    assert isinstance(row.get("compile_census"), list)
    ph = row["phase_seconds"]
    for phase in ("prepare", "factor-compile", "factor-time"):
        assert phase in ph and ph[phase] >= 0
    assert row["flightrec"] == str(tmp_path / "bench_fr.json")


# ---------------------------------------------------------------------------
# perf-regression gate: self-seeding, pass, regression (fast --row path)
# ---------------------------------------------------------------------------

def _run_gate(history, row_dict, tmp_path):
    row_file = tmp_path / "row.json"
    row_file.write_text(json.dumps(row_dict))
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "check_perf_regress.py"),
         "--row", str(row_file), "--history", str(history)],
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE)


def test_perf_gate_seeds_then_passes_then_fails(tmp_path):
    hist = tmp_path / "hist.jsonl"
    row = {"metric": "m_test", "value": 2.0, "backend": "cpu",
           "granularity": "fused", "schedule": "dataflow",
           "blocking": [1, 2, 3], "compile_seconds": 0.5}
    # self-seeding: an empty history passes (acceptance for ci_gates)
    for i in range(3):
        r = _run_gate(hist, row, tmp_path)
        assert r.returncode == 0, r.stderr.decode()
        assert b"SEEDED" in r.stdout
    # at min_samples the gate enforces — an equal value passes
    r = _run_gate(hist, row, tmp_path)
    assert r.returncode == 0 and b"OK" in r.stdout
    # a large drop fails...
    bad = dict(row, value=0.4)
    r = _run_gate(hist, bad, tmp_path)
    assert r.returncode == 1
    assert b"REGRESSION" in r.stdout
    # ...and did NOT poison the baseline (flagged gate_fail)
    r = _run_gate(hist, row, tmp_path)
    assert r.returncode == 0, r.stderr.decode()
    # a different config key keeps its own (empty -> seeding) history
    other = dict(row, backend="tpu")
    r = _run_gate(hist, other, tmp_path)
    assert r.returncode == 0 and b"SEEDED" in r.stdout


def test_mfu_report_prints_compile_section(tmp_path):
    t = trace.Tracer(str(tmp_path / "k.json"))
    t.complete("compile stream._kernel", "compile", 0.0, 1.5,
               key="lu b4 m32 w16 u16", n_args=11, persistent_hit=False)
    t.complete("compile make_factor_fn", "compile", 2.0, 0.5,
               key="fused g7 float32", n_args=2, persistent_hit=True)
    t.close()
    r = _run_mfu(str(tmp_path / "no.jsonl"), str(tmp_path / "k.json"))
    assert r.returncode == 0, r.stderr
    out = r.stdout.decode()
    assert "compile census" in out
    assert "lu b4 m32 w16 u16" in out and "stream._kernel" in out
    assert "[disk hit]" in out
