"""Rank-failure tolerance tests (ISSUE 8 — docs/RELIABILITY.md).

The contract under test: with ``SLU_TPU_COMM_TIMEOUT_S`` armed, a rank
that DIES surfaces as a structured :class:`RankFailureError` on EVERY
survivor — naming the dead rank(s), the op, the sequence number and the
call site — within ~2x the timeout (no hang, no watchdog ``os._exit``);
a rank that is merely SLOW (stalled below/above the timeout, pid alive)
is never declared failed; and ``Options.ft`` = "shrink"/"respawn"
(parallel/recover.py) completes the solve on the survivors, resuming
the checkpoint frontier with bitwise-identical factors.
"""

import hashlib
import multiprocessing as mp
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from superlu_dist_tpu import native

pytestmark = [pytest.mark.ft,
              pytest.mark.skipif(not native.available(),
                                 reason="native library unavailable")]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TIMEOUT_S = 0.4


# ---------------------------------------------------------------------------
# spec / error-surface units
# ---------------------------------------------------------------------------

def test_parse_chaos_ft_specs():
    from superlu_dist_tpu.testing.chaos import parse_chaos_spec
    p = parse_chaos_spec("kill_rank=1@group=3,signal=term")
    assert (p.kill_rank, p.kill_group, p.signal) == (1, 3, "term")
    p = parse_chaos_spec("kill_rank=2,kill_op=4")
    assert (p.kill_rank, p.kill_op) == (2, 4) and p.comm_armed and p.armed
    p = parse_chaos_spec("stall_rank=1,secs=0.5")
    assert (p.stall_rank, p.secs) == (1, 0.5) and p.comm_armed
    assert not parse_chaos_spec("nan_supernode=3").comm_armed
    with pytest.raises(ValueError, match="unknown"):
        parse_chaos_spec("kill_rankk=1")


def test_rank_failure_error_carries_structure():
    from superlu_dist_tpu.utils.errors import (CommTimeoutError,
                                               RankFailureError,
                                               SuperLUError)
    e = RankFailureError({2, 0}, op="bcast_any", seq=7,
                         site="parallel/pgssvx.py:277", rank=1, n_ranks=3,
                         epoch=0)
    assert e.dead_ranks == [0, 2]
    for frag in ("0,2", "bcast_any", "seq 7", "pgssvx.py:277", "shrink"):
        assert frag in str(e), (frag, str(e))
    assert isinstance(e, SuperLUError)
    # the flight-recorder postmortem hook ran at construction (None =
    # recorder off, but the attribute is always stamped)
    assert hasattr(e, "flightrec_dump")
    t = CommTimeoutError("reduce_sum", 1, 0.5, 3, seq=4, site="x.py:1")
    assert t.stuck_rank == 1 and "slow, not dead" in str(t)
    assert hasattr(t, "flightrec_dump")


def test_rank_failure_dumps_flightrec(tmp_path, monkeypatch):
    """Satellite: RankFailureError construction dumps the flight ring
    (the evidence survives even when the raise dies in a worker)."""
    import json
    from superlu_dist_tpu.obs import flightrec
    from superlu_dist_tpu.utils.errors import RankFailureError
    dump = tmp_path / "flight.json"
    fr = flightrec.FlightRecorder(dump_path=str(dump))
    flightrec.install(fr)
    try:
        fr.event("pre-failure", cat="comm")
        e = RankFailureError([1], op="bcast", seq=3, site="x.py:2",
                             rank=0, n_ranks=2)
        assert e.flightrec_dump == str(dump)
        doc = json.loads(dump.read_text())
        assert "RankFailureError" in doc["reason"]
    finally:
        flightrec._reset()


def test_options_ft_validated_by_driver():
    import superlu_dist_tpu as slu
    from superlu_dist_tpu.models.gallery import poisson2d
    from superlu_dist_tpu.utils.errors import SuperLUError
    a = poisson2d(4)
    b = np.ones(a.n_rows)
    with pytest.raises(SuperLUError, match="Options.ft"):
        slu.gssvx(slu.Options(ft="shirnk"), a, b)


def test_native_timed_leg_bounds_the_wait():
    """The native timed reduce returns 1+stuck_rank within ~timeout when
    the peer never arrives, leaves the payload untouched, and the
    untimed entry is unaffected (timeout 0 = legacy)."""
    import ctypes
    lib = native._load()
    name = f"/slu_ft_unit_{os.getpid()}".encode()
    h = lib.slu_tree_attach(name, 2, 16, 0, 1)
    try:
        buf = np.arange(4.0)
        ptr = buf.ctypes.data_as(ctypes.POINTER(ctypes.c_double))
        t0 = time.monotonic()
        rc = lib.slu_tree_reduce_sum_tw(h, 0, ptr, 4, TIMEOUT_S)
        dt = time.monotonic() - t0
        assert rc == 2                        # 1 + stuck rank 1
        assert TIMEOUT_S * 0.8 < dt < TIMEOUT_S * 3
        np.testing.assert_array_equal(buf, np.arange(4.0))
        rc = lib.slu_tree_bcast_tw(h, 0, ptr, 4, TIMEOUT_S)
        assert rc == 0                        # root bcast: no waits at op 1
    finally:
        lib.slu_tree_detach(h, name, 1)


# ---------------------------------------------------------------------------
# TreeComm-level failure detection (fork workers: numpy only, no jax)
# ---------------------------------------------------------------------------

def _dying_worker(name, n_ranks, rank, die_before_op):
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    tc = TreeComm(name, n_ranks, rank, max_len=64, create=False)
    x = np.ones(4)
    for _ in range(die_before_op - 1):
        tc.allreduce_sum_any(x)
    os._exit(17)


def _surviving_worker(name, n_ranks, rank, n_ops, q, done):
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    from superlu_dist_tpu.utils.errors import RankFailureError
    tc = TreeComm(name, n_ranks, rank, max_len=64, create=False)
    x = np.ones(4)
    t0 = time.monotonic()
    try:
        for _ in range(n_ops):
            tc.allreduce_sum_any(x)
        q.put((rank, "no-error", None, None, None, 0.0))
    except RankFailureError as e:
        q.put((rank, "rank-failure", e.dead_ranks, e.op, e.site,
               time.monotonic() - t0))
    # stay alive until the peer finished its own agreement (a real
    # survivor proceeds to recovery; exiting early would legitimately
    # land this rank in the peer's dead-set)
    done.wait(timeout=30)


def test_three_rank_death_raises_on_every_survivor(monkeypatch):
    """Rank 2 dies before op 2; BOTH survivors (the main process and a
    fork worker) raise RankFailureError naming rank 2 + op + site,
    within the 2x-timeout budget."""
    monkeypatch.setenv("SLU_TPU_COMM_TIMEOUT_S", str(TIMEOUT_S))
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    from superlu_dist_tpu.utils.errors import RankFailureError

    name = f"/slu_ft3_{os.getpid()}"
    tc = TreeComm(name, 3, 0, max_len=64, create=True)
    ctx = mp.get_context("fork")
    q = ctx.Queue()
    done = ctx.Event()
    dier = ctx.Process(target=_dying_worker, args=(name, 3, 2, 2))
    surv = ctx.Process(target=_surviving_worker,
                       args=(name, 3, 1, 2, q, done))
    dier.start()
    surv.start()
    x = np.ones(4)
    try:
        assert (tc.allreduce_sum_any(x) == 3).all()
        t0 = time.monotonic()
        with pytest.raises(RankFailureError) as ei:
            tc.allreduce_sum_any(x)
        dt = time.monotonic() - t0
        done.set()
        assert ei.value.dead_ranks == [2]
        assert ei.value.op and ei.value.site
        assert dt < 2 * TIMEOUT_S + 1.0, dt
        peer = q.get(timeout=30)
        assert peer[1] == "rank-failure", peer
        assert peer[2] == [2] and peer[3] and peer[4]
        dier.join(timeout=30)
        surv.join(timeout=30)
        assert dier.exitcode == 17 and surv.exitcode == 0
    finally:
        done.set()
        tc.close(unlink=True)


def _stalling_worker(name, n_ranks, rank, q):
    # SLU_TPU_CHAOS='stall_rank=...' is inherited: the comm-chaos hook
    # sleeps before the matching public collective
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    tc = TreeComm(name, n_ranks, rank, max_len=64, create=False)
    # fresh payloads per op: contiguous f64 collectives run in place
    out1 = tc.allreduce_sum_any(np.ones(4))
    out2 = tc.allreduce_sum_any(np.ones(4))
    q.put((rank, float(out1[0]), float(out2[0])))
    tc.close()


def test_stall_is_never_declared_failure(monkeypatch):
    """A peer stalled for ~4x the timeout (pid alive) must NOT be
    declared failed: the survivor retries through several timeouts and
    the collective completes with the right value, zero false
    positives."""
    stall = 4 * TIMEOUT_S
    monkeypatch.setenv("SLU_TPU_COMM_TIMEOUT_S", str(TIMEOUT_S))
    monkeypatch.setenv("SLU_TPU_CHAOS", f"stall_rank=1,secs={stall},"
                                        "stall_op=2")
    from superlu_dist_tpu.parallel.treecomm import TreeComm

    name = f"/slu_ftstall_{os.getpid()}"
    tc = TreeComm(name, 2, 0, max_len=64, create=True)
    ctx = mp.get_context("fork")
    q = ctx.Queue()
    p = ctx.Process(target=_stalling_worker, args=(name, 2, 1, q))
    p.start()
    try:
        assert (tc.allreduce_sum_any(np.ones(4)) == 2).all()
        t0 = time.monotonic()
        # peer sleeps `stall` before entering this op
        out = tc.allreduce_sum_any(np.ones(4))
        dt = time.monotonic() - t0
        assert (out == 2).all(), out
        assert dt >= stall * 0.8, dt          # the stall really happened
        r, o1, o2 = q.get(timeout=30)
        assert (o1, o2) == (2.0, 2.0)
        p.join(timeout=30)
        assert p.exitcode == 0
    finally:
        tc.close(unlink=True)


def _sleeping_worker(name, n_ranks, rank, secs):
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    tc = TreeComm(name, n_ranks, rank, max_len=64, create=False)
    time.sleep(secs)        # never enters the collective
    tc.close()
    os._exit(0)


def test_bounded_retries_raise_comm_timeout_on_live_peer(monkeypatch):
    """With SLU_TPU_COMM_RETRIES bounded, a live-but-absent peer yields
    CommTimeoutError (the slow-not-dead verdict), never
    RankFailureError."""
    monkeypatch.setenv("SLU_TPU_COMM_TIMEOUT_S", str(TIMEOUT_S))
    monkeypatch.setenv("SLU_TPU_COMM_RETRIES", "2")
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    from superlu_dist_tpu.utils.errors import CommTimeoutError

    name = f"/slu_ftto_{os.getpid()}"
    tc = TreeComm(name, 2, 0, max_len=64, create=True)
    ctx = mp.get_context("fork")
    p = ctx.Process(target=_sleeping_worker, args=(name, 2, 1, 60.0))
    p.start()
    try:
        time.sleep(0.2)     # let the peer attach (register its pid)
        with pytest.raises(CommTimeoutError) as ei:
            tc.allreduce_sum_any(np.ones(4))
        assert ei.value.stuck_rank == 1
        assert ei.value.retries == 2
    finally:
        p.terminate()
        p.join(timeout=30)
        tc.close(unlink=True)


def test_heartbeat_and_board_roundtrip(monkeypatch):
    """Detector unit surface: heartbeat epochs advance (age gauge
    resets on movement), and a posted dead-set round-trips through the
    .ftx board to a peer attachment."""
    monkeypatch.setenv("SLU_TPU_COMM_TIMEOUT_S", str(TIMEOUT_S))
    monkeypatch.setenv("SLU_TPU_HEARTBEAT_S", "0.05")
    from superlu_dist_tpu.parallel.treecomm import TreeComm

    name = f"/slu_fthb_{os.getpid()}"
    a = TreeComm(name, 2, 0, max_len=64, create=True)
    b = TreeComm(name, 2, 1, max_len=64, create=False)
    try:
        lib = native._load()
        hb0 = lib.slu_tree_get_heartbeat(a._h, 0)
        time.sleep(0.3)
        assert lib.slu_tree_get_heartbeat(a._h, 0) > hb0
        # b observes a's heartbeat moving: age snaps back to 0
        assert b._detector.heartbeat_age(0) == 0.0
        # pid liveness: both registered, both alive
        assert b._detector.pid(0) == os.getpid()
        assert b._detector.dead_ranks() == set()
        # board: a posts a failure declaration, b reads it back
        a._detector.post_failure({1}, epoch=0)
        posted = b._detector.posted_failures(epoch=0)
        assert posted == {0: {1}}
        assert b._detector.posted_failures(epoch=3) == {}
    finally:
        b.close()
        a.close(unlink=True)


# ---------------------------------------------------------------------------
# full-driver scenarios (subprocess ranks — fresh processes, jax-laden)
# ---------------------------------------------------------------------------

_RANK_SCRIPT = r"""
import os, sys, hashlib
import numpy as np
sys.path.insert(0, {repo!r})

def main():
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    rank, n_ranks, name = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    from superlu_dist_tpu.models.gallery import poisson3d
    from superlu_dist_tpu.parallel.recover import (
        pgssvx_ft, RowBlockSource, VectorBlockSource, FT_EVENTS)
    from superlu_dist_tpu.utils.errors import RankFailureError
    from superlu_dist_tpu.utils.options import Options
    from superlu_dist_tpu.testing.chaos import HangWatchdog

    a = poisson3d(6)
    xt = np.random.default_rng(0).standard_normal(a.n_rows)
    b = a.matvec(xt)
    opts = Options(factor_dtype="float64", ckpt_every=2,
                   ckpt_dir=os.environ.get("FT_CKDIR", ""))
    lu_out = {{}}
    # the watchdog must never fire: the detector raises first (its
    # exit-3 would fail the rc==0 assertion in the parent)
    with HangWatchdog(120.0):
        try:
            x, info = pgssvx_ft(name, n_ranks, rank, opts,
                                RowBlockSource(a), VectorBlockSource(b),
                                max_len=a.n_rows, lu_out=lu_out)
        except RankFailureError as e:
            print("OUTCOME", rank, "rank-failure",
                  ",".join(map(str, e.dead_ranks)), e.op, e.site,
                  flush=True)
            return
    err = float(np.abs(x - xt).max())
    h = hashlib.sha256()
    lu = lu_out.get("lu")
    if lu is not None and getattr(lu, "numeric", None) is not None:
        for lp, up in lu.numeric.fronts:
            h.update(np.ascontiguousarray(np.asarray(lp)).tobytes())
            h.update(np.ascontiguousarray(np.asarray(up)).tobytes())
    rungs = []
    rep = lu_out.get("solve_report")
    if rep is not None:
        rungs = [r.name for r in rep.rungs]
    print("OUTCOME", rank, "solved", info, len(FT_EVENTS), err,
          h.hexdigest(), lu_out.get("recovered"), ";".join(rungs),
          flush=True)

if __name__ == "__main__":
    main()
"""


def _spawn_rank(tmp_path, name, rank, n_ranks, extra_env):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               SLU_TPU_COMM_TIMEOUT_S="1.0",
               FT_CKDIR=str(tmp_path / "ck"))
    env.pop("SLU_TPU_CHAOS", None)
    env.update(extra_env)
    script = tmp_path / f"rank{rank}.py"
    script.write_text(_RANK_SCRIPT.format(repo=REPO))
    return subprocess.Popen(
        [sys.executable, str(script), str(rank), str(n_ranks), name],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)


def _outcomes(procs, timeout=300):
    out = {}
    for rank, p in procs.items():
        o, e = p.communicate(timeout=timeout)
        lines = [ln for ln in o.splitlines() if ln.startswith("OUTCOME")]
        out[rank] = (p.returncode, lines[-1].split() if lines else None, e)
    return out


@pytest.mark.parametrize("n_ranks", [2, 3])
def test_kill_mid_factor_all_survivors_raise(tmp_path, n_ranks):
    """The acceptance shape with ft=abort: the highest rank is killed
    mid-solve (before its 4th public collective, while root factors);
    EVERY survivor raises RankFailureError naming rank+op+site, inside
    the 2x-timeout window (wall-clocked from the kill), and no
    HangWatchdog exit-3 fires."""
    victim = n_ranks - 1
    name = f"/slu_ftk{n_ranks}_{os.getpid()}"
    procs = {0: _spawn_rank(tmp_path, name, 0, n_ranks,
                            {"SLU_TPU_FT": "abort"})}
    time.sleep(0.3)
    for r in range(1, n_ranks):
        env = {"SLU_TPU_FT": "abort"}
        if r == victim:
            env["SLU_TPU_CHAOS"] = f"kill_rank={victim},kill_op=4"
        procs[r] = _spawn_rank(tmp_path, name, r, n_ranks, env)
    res = _outcomes(procs)
    rc, line, err = res[victim]
    assert rc == -signal.SIGKILL, (rc, err)
    for r in range(n_ranks):
        if r == victim:
            continue
        rc, line, err = res[r]
        assert rc == 0, (r, rc, err)
        assert line is not None and line[2] == "rank-failure", (r, line)
        assert line[3] == str(victim)         # dead set names the victim
        assert line[4] and line[5]            # op + call site populated


def test_shrink_recovery_resumes_bitwise(tmp_path):
    """ft=shrink flagship: rank 0 (the factoring root) is SIGKILLed
    after dispatch group 3 with interval checkpoints armed; the
    survivor shrinks to a solo epoch, RESUMES the durable frontier, and
    produces bitwise-identical L/U to an undisturbed run (digest
    compare), with the ft-shrink rung recorded."""
    # reference: undisturbed solo run, same options/ckpt arming
    name_ref = f"/slu_ftref_{os.getpid()}"
    ref = _spawn_rank(tmp_path, name_ref, 0, 1, {"SLU_TPU_FT": "shrink"})
    res = _outcomes({0: ref})
    rc, line, err = res[0]
    assert rc == 0 and line[2] == "solved", (rc, line, err)
    ref_digest = line[6]

    name = f"/slu_ftshrink_{os.getpid()}"
    procs = {0: _spawn_rank(
        tmp_path, name, 0, 2,
        {"SLU_TPU_FT": "shrink",
         "SLU_TPU_CHAOS": "kill_rank=0@group=3"})}
    time.sleep(0.3)
    procs[1] = _spawn_rank(tmp_path, name, 1, 2, {"SLU_TPU_FT": "shrink"})
    res = _outcomes(procs)
    assert res[0][0] == -signal.SIGKILL, res[0]
    rc, line, err = res[1]
    assert rc == 0, (rc, err)
    assert line[2] == "solved" and int(line[3]) == 0, line
    assert int(line[4]) == 1                  # one FT event
    assert float(line[5]) < 1e-8              # solution correct
    assert line[6] == ref_digest              # BITWISE identical L/U
    assert line[7] == "True"                  # lu_out["recovered"]
    assert "ft-shrink" in line[8].split(";")  # SolveReport rung


def test_respawn_recovery_completes(tmp_path):
    """ft=respawn: rank 1 dies mid-gather; rank 0 spawns a replacement
    that takes over rank 1's id in epoch 1 and the 2-rank solve
    completes with one recorded recovery."""
    name = f"/slu_ftresp_{os.getpid()}"
    procs = {0: _spawn_rank(tmp_path, name, 0, 2,
                            {"SLU_TPU_FT": "respawn"})}
    time.sleep(0.3)
    procs[1] = _spawn_rank(
        tmp_path, name, 1, 2,
        {"SLU_TPU_FT": "respawn", "SLU_TPU_CHAOS": "kill_rank=1,kill_op=4"})
    res = _outcomes(procs)
    assert res[1][0] == -signal.SIGKILL, res[1]
    rc, line, err = res[0]
    assert rc == 0, (rc, err)
    assert line[2] == "solved" and int(line[3]) == 0, line
    assert int(line[4]) == 1 and float(line[5]) < 1e-8
    assert "ft-respawn" in line[8].split(";")


def test_shrink_recovery_clean_under_verify_collectives(tmp_path):
    """The whole failure->agree->shrink->resume path runs clean with the
    SLU106 lockstep verifier ON (the digest exchange itself rides the
    bounded-wait legs; the recovery epoch gets its own .vfy domain)."""
    name = f"/slu_ftvfy_{os.getpid()}"
    base = {"SLU_TPU_FT": "shrink", "SLU_TPU_VERIFY_COLLECTIVES": "1"}
    procs = {0: _spawn_rank(
        tmp_path, name, 0, 2,
        dict(base, SLU_TPU_CHAOS="kill_rank=0@group=3"))}
    time.sleep(0.3)
    procs[1] = _spawn_rank(tmp_path, name, 1, 2, base)
    res = _outcomes(procs)
    assert res[0][0] == -signal.SIGKILL, res[0]
    rc, line, err = res[1]
    assert rc == 0, (rc, err)
    assert line[2] == "solved" and float(line[5]) < 1e-8, (line, err)
