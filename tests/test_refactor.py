"""Crash-consistent same-pattern refactorization (drivers/gssvx.py
``refactor`` + ``SolveServer.refactor`` + ``FleetRouter.refactor``):
values-only refactorization reuses the symbolic fact, FactorPlan, and
compiled programs (zero recompile, bitwise-identical to a
SamePattern_SameRowPerm driver pass), refuses drifted patterns with a
structured error, and — under the chaos specs ``kill_refactor@step=K``
and ``poison_values=S`` — always leaves the previous consistent handle
serving: an interrupted, NaN-poisoned, or BERR-rejected refactor adopts
nothing, and the fleet verb rolls every swapped replica back."""

import dataclasses
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from superlu_dist_tpu.drivers.gssvx import gssvx, refactor
from superlu_dist_tpu.models.gallery import hilbert, poisson2d
from superlu_dist_tpu.persist.serial import (load_lu, lu_meta,
                                             pattern_digest, save_lu)
from superlu_dist_tpu.serve import (FleetRouter, PatternMismatchError,
                                    RefactorRollbackError, SolveServer)
from superlu_dist_tpu.serve.fleet import FLEET_SERVER_KW
from superlu_dist_tpu.utils.errors import SuperLUError
from superlu_dist_tpu.utils.options import Fact, IterRefine, Options
from superlu_dist_tpu.utils.stats import Stats

pytestmark = pytest.mark.refactor

ROOT = os.path.join(os.path.dirname(__file__), "..")


def _drift(a, scale=2.0, shift=0.01):
    return type(a)(a.n_rows, a.n_cols, a.indptr, a.indices,
                   a.data * scale + shift)


def _same_pattern_baseline(a, a2, b, opts):
    """The ground truth a refactor must hit bitwise: an independent
    handle refreshed through the driver's SamePattern_SameRowPerm
    tier."""
    _, lu, _, info = gssvx(opts, a, b, stats=Stats())
    assert info == 0
    _, lu2, _, info2 = gssvx(
        dataclasses.replace(opts, fact=Fact.SamePattern_SameRowPerm),
        a2, b, lu=lu, stats=Stats())
    assert info2 == 0
    return lu2


# ---------------------------------------------------------------------------
# the tentpole invariant: refactor ≡ SamePattern refresh, zero recompile
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("executor", ["fused", "stream", "mega"])
@pytest.mark.parametrize("dtype", ["float64", "complex128", "df64"])
def test_refactor_bitwise_and_zero_recompile(executor, dtype):
    from superlu_dist_tpu.obs.compilestats import COMPILE_STATS
    a = poisson2d(7)
    if dtype == "complex128":
        a = type(a)(a.n_rows, a.n_cols, a.indptr, a.indices,
                    a.data.astype(np.complex128) * (1 + 0.25j))
    b = np.arange(1, a.n_rows + 1, dtype=np.float64)
    opts = Options(executor=executor, factor_dtype=dtype,
                   iter_refine=IterRefine.NOREFINE)
    a2 = _drift(a)
    base = _same_pattern_baseline(a, a2, b, opts)

    _, lu, _, info = gssvx(opts, a, b, stats=Stats())
    assert info == 0
    marker = COMPILE_STATS.marker()
    st = Stats()
    refactor(lu, a2, stats=st)
    assert np.array_equal(np.asarray(lu.solve_factored(b)),
                          np.asarray(base.solve_factored(b)))
    # the economics, asserted: no symbolic pass, no fresh compile
    assert float(st.utime.get("SYMBFACT", 0.0)) == 0.0
    blk = COMPILE_STATS.block(since=marker)
    assert float(blk["fresh_seconds"]) == 0.0, blk
    # symbolic fact + plan are the SAME objects (reuse by construction)
    assert lu.sf is not None and lu.plan is not None


def test_refactor_raw_values_array():
    """The serving verbs pass a bare CSR data array; it must land
    bitwise on the SparseCSR path."""
    a = poisson2d(7)
    b = np.ones(a.n_rows)
    opts = Options(iter_refine=IterRefine.NOREFINE)
    vals = a.data * 0.5
    a2 = type(a)(a.n_rows, a.n_cols, a.indptr, a.indices, vals)
    base = _same_pattern_baseline(a, a2, b, opts)
    _, lu, _, _ = gssvx(opts, a, b, stats=Stats())
    refactor(lu, vals)
    assert np.array_equal(np.asarray(lu.solve_factored(b)),
                          np.asarray(base.solve_factored(b)))
    with pytest.raises(PatternMismatchError):
        refactor(lu, vals[:-1])          # wrong nnz


def test_refactor_identity_latch_and_pattern_digest():
    a = poisson2d(6)
    _, lu, _, _ = gssvx(Options(), a, np.ones(a.n_rows), stats=Stats())
    dig, fp = lu.identity()
    assert dig and fp
    assert dig == pattern_digest(lu.a_sym_indptr, lu.a_sym_indices)
    assert lu.identity() == (dig, fp)    # latched, stable


def test_pattern_drift_refused_structured():
    """A different sparsity pattern must refuse with the structured
    error, not silently re-run symbolic analysis."""
    a = poisson2d(6)
    _, lu, _, _ = gssvx(Options(), a, np.ones(a.n_rows), stats=Stats())
    sf, plan = lu.sf, lu.plan
    with pytest.raises(PatternMismatchError) as ei:
        refactor(lu, hilbert(a.n_rows))
    assert ei.value.expected_digest
    assert "DOFACT" in str(ei.value)
    # nothing was touched: same symbolic/plan, handle still solves
    assert lu.sf is sf and lu.plan is plan
    assert np.isfinite(np.asarray(lu.solve_factored(
        np.ones(a.n_rows)))).all()


# ---------------------------------------------------------------------------
# rollback domains: poisoned values, BERR gate, kill -9 mid-refactor
# ---------------------------------------------------------------------------

def test_poisoned_refactor_rolls_back_adopting_nothing(monkeypatch):
    a = poisson2d(7)
    b = np.arange(1, a.n_rows + 1, dtype=np.float64)
    _, lu, _, _ = gssvx(Options(), a, b, stats=Stats())
    x_before = np.asarray(lu.solve_factored(b))
    old_numeric, old_a = lu.numeric, lu.a
    monkeypatch.setenv("SLU_TPU_CHAOS", "poison_values=1")
    with pytest.raises(RefactorRollbackError) as ei:
        refactor(lu, _drift(a))
    monkeypatch.delenv("SLU_TPU_CHAOS")
    assert ei.value.stage in ("factor", "canary")
    assert lu.numeric is old_numeric and lu.a is old_a
    assert np.array_equal(np.asarray(lu.solve_factored(b)), x_before)
    # and the handle still accepts a CLEAN refactor afterwards
    refactor(lu, _drift(a))
    assert lu.numeric is not old_numeric


def test_berr_gate_rejects_without_adoption(monkeypatch):
    monkeypatch.setenv("SLU_TPU_REFACTOR_ESCALATE", "0")
    a = poisson2d(7)
    b = np.ones(a.n_rows)
    _, lu, _, _ = gssvx(Options(), a, b, stats=Stats())
    old_numeric = lu.numeric
    with pytest.raises(RefactorRollbackError) as ei:
        refactor(lu, _drift(a), berr_max=1e-300)   # unmeetable gate
    assert ei.value.stage == "canary"
    assert ei.value.berr > ei.value.berr_target >= 0
    assert lu.numeric is old_numeric
    # a meetable gate adopts
    refactor(lu, _drift(a), berr_max=1e-8)
    assert lu.numeric is not old_numeric


def test_kill9_mid_refactor_preserves_bundle(tmp_path):
    """kill_refactor@step=0 SIGKILLs the child MID-REFACTOR; the bundle
    it was serving from must still load and solve bitwise — an
    interrupted refactor leaves the previous consistent state."""
    d = str(tmp_path / "bundle")
    a = poisson2d(6)
    b = np.ones(a.n_rows)
    _, lu, _, _ = gssvx(Options(), a, b, stats=Stats())
    save_lu(lu, d)
    x_before = np.asarray(load_lu(d).solve_factored(b))
    child = (
        "import numpy as np\n"
        "from superlu_dist_tpu.drivers.gssvx import refactor\n"
        "from superlu_dist_tpu.persist.serial import load_lu\n"
        "from superlu_dist_tpu.models.gallery import poisson2d\n"
        f"lu = load_lu({d!r})\n"
        "a = poisson2d(6)\n"
        "a2 = type(a)(a.n_rows, a.n_cols, a.indptr, a.indices,\n"
        "             a.data * 2.0)\n"
        "refactor(lu, a2)\n"
        "print('UNREACHABLE')\n")
    env = dict(os.environ, SLU_TPU_CHAOS="kill_refactor@step=0",
               JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, "-c", child], env=env, cwd=ROOT,
                       capture_output=True, timeout=300)
    assert r.returncode == -9, (r.returncode, r.stdout, r.stderr)
    assert b"UNREACHABLE" not in r.stdout
    lu2 = load_lu(d)
    assert np.array_equal(np.asarray(lu2.solve_factored(b)), x_before)
    assert lu_meta(d)["pattern_digest"] == lu.identity()[0]


# ---------------------------------------------------------------------------
# serving tiers: hot refactor with zero dropped tickets
# ---------------------------------------------------------------------------

def test_server_refactor_swaps_and_rolls_back():
    a = poisson2d(7)
    b = np.arange(1, a.n_rows + 1, dtype=np.float64)
    _, lu, _, _ = gssvx(Options(), a, b, stats=Stats())
    a2 = _drift(a)
    base = _same_pattern_baseline(a, a2, b, Options())
    srv = SolveServer(lu, max_wait_s=0.0)
    try:
        srv.refactor(a2)
        assert np.array_equal(np.asarray(srv.solve(b)),
                              np.asarray(base.solve_factored(b)))
        st = srv.stats()
        assert st["refactors"] == 1 and st["swaps"] == 1
        x_now = np.asarray(srv.solve(b))
        os.environ["SLU_TPU_CHAOS"] = "poison_values=1"
        try:
            with pytest.raises(RefactorRollbackError):
                srv.refactor(_drift(a, scale=3.0))
        finally:
            del os.environ["SLU_TPU_CHAOS"]
        # the failed refactor never reached the swap
        assert srv.stats()["swaps"] == 1
        assert np.array_equal(np.asarray(srv.solve(b)), x_now)
    finally:
        srv.close()


def test_fleet_rolling_refactor_under_traffic_and_rollback(tmp_path):
    a = poisson2d(7)
    b = a.matvec(np.ones(a.n_rows))
    _, lu, _, _ = gssvx(Options(iter_refine=IterRefine.NOREFINE), a, b,
                        stats=Stats())
    d = str(tmp_path / "k0")
    save_lu(lu, d)
    a2 = _drift(a)
    base = _same_pattern_baseline(
        a, a2, b, Options(iter_refine=IterRefine.NOREFINE))
    fleet = FleetRouter({"k0": d}, n_replicas=3, kind="thread",
                        server_kw=FLEET_SERVER_KW)
    stop = threading.Event()
    outcomes = []
    lock = threading.Lock()

    def client():
        while not stop.is_set():
            try:
                fleet.solve("k0", b, timeout=120)
                tag = "ok"
            except Exception as e:        # noqa: BLE001 — tallied
                tag = type(e).__name__
            with lock:
                outcomes.append(tag)

    th = threading.Thread(target=client)
    th.start()
    try:
        time.sleep(0.05)
        summary = fleet.refactor("k0", a2)
        time.sleep(0.05)
    finally:
        stop.set()
        th.join(30)
    try:
        # rolling refactor under live traffic dropped nothing
        assert outcomes and set(outcomes) == {"ok"}, outcomes
        assert summary["replicas_swapped"] == [0, 1, 2]
        assert np.array_equal(np.asarray(fleet.solve("k0", b)),
                              np.asarray(base.solve_factored(b)))
        x_now = np.asarray(fleet.solve("k0", b))
        # poisoned refactor: every replica keeps the adopted bundle
        os.environ["SLU_TPU_CHAOS"] = "poison_values=1"
        try:
            with pytest.raises(RefactorRollbackError) as ei:
                fleet.refactor("k0", _drift(a, scale=3.0))
        finally:
            del os.environ["SLU_TPU_CHAOS"]
        assert ei.value.stage in ("factor", "canary")
        assert np.array_equal(np.asarray(fleet.solve("k0", b)), x_now)
        st = fleet.stats()
        assert st["refactors"] == 1 and st["rollbacks"] == 1
        assert st["errors"] == 0
    finally:
        fleet.close()


@pytest.mark.slow
def test_fleet_refactor_with_kill9_replica_zero_loss(monkeypatch,
                                                     tmp_path):
    """Process replicas, a REAL kill -9 of one replica mid-stream while
    a rolling refactor lands: every accepted ticket is still delivered
    and the refactored factors serve bitwise."""
    a = poisson2d(6)
    b = a.matvec(np.ones(a.n_rows))
    _, lu, _, _ = gssvx(Options(iter_refine=IterRefine.NOREFINE), a, b,
                        stats=Stats())
    d = str(tmp_path / "k0")
    save_lu(lu, d)
    a2 = _drift(a)
    base = _same_pattern_baseline(
        a, a2, b, Options(iter_refine=IterRefine.NOREFINE))
    monkeypatch.setenv("SLU_TPU_CHAOS", "kill_replica=1@batch=1")
    fleet = FleetRouter({"k0": d}, n_replicas=3, kind="process",
                        server_kw=FLEET_SERVER_KW)
    try:
        tickets = [fleet.submit("k0", b) for _ in range(8)]
        monkeypatch.delenv("SLU_TPU_CHAOS")
        # the kill -9 fires on batch 1; the failover machinery reroutes
        # and every accepted ticket is still delivered
        xs = [t.result(300) for t in tickets]
        assert all(np.isfinite(x).all() for x in xs)
        st = fleet.stats()
        assert st["failovers"] >= 1 and 1 in st["replicas_failed"]
        # the rolling refactor then lands on the SURVIVING replicas
        summary = fleet.refactor("k0", a2)
        assert 1 not in summary["replicas_swapped"]
        tickets2 = [fleet.submit("k0", b) for _ in range(6)]
        for t in tickets2:
            assert np.array_equal(np.asarray(t.result(300)),
                                  np.asarray(base.solve_factored(b)))
        st = fleet.stats()
        assert st["errors"] == 0
        assert st["delivered"] == 14
        assert st["refactors"] == 1
    finally:
        fleet.close()


def test_refactor_requires_factored_handle():
    a = poisson2d(5)
    _, lu, _, _ = gssvx(Options(), a, np.ones(a.n_rows), stats=Stats())
    with pytest.raises(SuperLUError):
        refactor(dataclasses.replace(lu, sf=None), a)
    with pytest.raises(SuperLUError):
        refactor(dataclasses.replace(lu, plan=None), a)
