"""Zero-dependency structured span tracer — the PROFlevel≥1 substrate.

The reference's PROFlevel builds expose what every performance mystery
here has needed re-derived by hand: where the time went, per phase, per
kernel shape, per transfer (SRC/util.c:538-630 comm split; the
dgemm_mnk.dat GEMM-shape trace, SRC/pdgstrf.c:380-387).  This module is
the one sink all of that flows into: nested spans with categories
(phase / dispatch / kernel / comm / host-offload), monotonic
timestamps, and per-span attributes (supernode counts, m/w/u shapes,
bytes, dtypes).

Artifacts (env-gated by ``SLU_TPU_TRACE=<path>``):

* ``<path>``         — Chrome trace-event JSON (``{"traceEvents": [...]}``
  with "X" complete events, microsecond timestamps, events sorted by
  start time) — load it in Perfetto (https://ui.perfetto.dev) or
  ``chrome://tracing``;
* ``<path>l`` (``.json`` → ``.jsonl``, anything else gets ``.jsonl``
  appended) — the same records as line-delimited JSON, appended as each
  span CLOSES, so a crashed run still leaves every completed span on
  disk.

``%p`` in the path expands to the process id, so multi-process drivers
(parallel/pgssvx.py ranks) can share one env var without clobbering
each other's artifacts.

Disabled path (env unset): ``get_tracer()`` returns the module-level
``NULL_TRACER`` singleton whose ``span()`` hands back one reused no-op
span object — no file is opened, no string is formatted, no timestamp
is read.  Hot loops additionally guard on ``tracer.enabled`` so even
the attribute-dict construction is skipped when tracing is off.
"""

from __future__ import annotations

import atexit
import json
import os
import threading

from superlu_dist_tpu.utils.lockwatch import make_lock
import time

#: Span categories (the ``cat`` field of every record).  "verify" spans
#: come from the runtime SLU106 tier: collective-lockstep mismatches
#: (parallel/treecomm.LockstepVerifier) and unexpected-recompile events
#: (numeric/stream.RetraceSentinel).  "compile" spans come from the
#: compile census (obs/compilestats.py): one per jit build, tagged with
#: the shape-key bucket and persistent-cache hit/miss.  "request" spans
#: come from the serving tier's TicketContext (obs/slo.py, emitted by
#: serve/server.py and serve/fleet.py): one enclosing span per ticket
#: with nested per-stage children (queue_wait / coalesce / dispatch /
#: device / refine / deliver), all tagged with the ticket's trace_id so
#: scripts/trace_merge.py can join a ticket across processes.
CATEGORIES = ("phase", "dispatch", "kernel", "comm", "host-offload",
              "verify", "compile", "request")


class _NullSpan:
    """The reused no-op span: entering/exiting touches nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


NULL_SPAN = _NullSpan()


class NullTracer:
    """Disabled tracer: every operation is a constant-time no-op."""

    __slots__ = ()
    enabled = False
    profiling = False
    path = None

    def span(self, name, cat="phase", **attrs):
        return NULL_SPAN

    def complete(self, name, cat, t0, dur, **attrs):
        pass

    def flush(self):
        pass

    def close(self):
        pass


NULL_TRACER = NullTracer()


class _Span:
    """One open span; records itself on ``__exit__``."""

    __slots__ = ("_tracer", "name", "cat", "args", "_t0")

    def __init__(self, tracer, name, cat, args):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args

    def set(self, **attrs):
        """Attach attributes discovered mid-span (e.g. a result size)."""
        self.args.update(attrs)
        return self

    def __enter__(self):
        self._tracer._enter_thread()
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter_ns()
        self._tracer._record(self.name, self.cat, self._t0, t1 - self._t0,
                             self.args, depth_delta=-1)
        return False


class Tracer:
    """Collecting tracer: spans accumulate in memory (for the Chrome
    artifact) and stream to the JSONL sidecar as they close."""

    enabled = True
    profiling = True     # file tracing implies per-kernel blocking spans

    def __init__(self, path: str):
        path = path.replace("%p", str(os.getpid()))
        self.path = path
        self.jsonl_path = (path[:-5] + ".jsonl" if path.endswith(".json")
                           else path + ".jsonl")
        self._epoch_ns = time.perf_counter_ns()
        self._lock = make_lock("Tracer._lock")
        self._events = []
        self._tids = {}
        self._tls = threading.local()
        self._jsonl = None
        self._closed = False
        # wall-clock anchor: every span timestamp is monotonic, so a
        # multi-rank Perfetto merge (or a flight-recorder dump) needs one
        # absolute reference per process — unix ≈ unix_time + ts_us/1e6
        self._record("clock-anchor", "phase", self._epoch_ns, 0,
                     {"unix_time": round(time.time(), 6),
                      "perf_ns": self._epoch_ns})

    # ---- internals -----------------------------------------------------
    def _enter_thread(self):
        self._tls.depth = getattr(self._tls, "depth", 0) + 1

    def _tid(self):
        ident = threading.get_ident()
        tid = self._tids.get(ident)
        if tid is None:
            tid = self._tids.setdefault(ident, len(self._tids))
        return tid

    def _record(self, name, cat, t0_ns, dur_ns, args, depth_delta=0):
        if depth_delta:
            self._tls.depth = getattr(self._tls, "depth", 0) + depth_delta
        ev = {
            "name": str(name), "cat": str(cat), "ph": "X",
            "ts": round((t0_ns - self._epoch_ns) / 1e3, 3),   # microseconds
            "dur": round(dur_ns / 1e3, 3),
            "pid": os.getpid(), "tid": self._tid(),
            "depth": getattr(self._tls, "depth", 0),
        }
        if args:
            ev["args"] = args
        with self._lock:
            if self._closed:
                return
            self._events.append(ev)
            if self._jsonl is None:
                os.makedirs(os.path.dirname(os.path.abspath(
                    self.jsonl_path)), exist_ok=True)
                # the lock exists to serialize exactly these
                # crash-safe sidecar appends: the write IS the
                # guarded operation
                self._jsonl = open(  # slulint: disable=SLU109
                    self.jsonl_path, "w", buffering=1)
            self._jsonl.write(json.dumps(ev, default=str) + "\n")

    # ---- public API ----------------------------------------------------
    def span(self, name, cat="phase", **attrs):
        """Context manager timing a nested span.  ``attrs`` should be
        plain scalars (ints/floats/short strings) — they land in the
        record's ``args``."""
        return _Span(self, name, cat, attrs)

    def complete(self, name, cat, t0, dur, **attrs):
        """Record an already-timed span: ``t0`` is a ``time.perf_counter()``
        value (seconds), ``dur`` its duration in seconds.  For call sites
        that must time unconditionally (profiling counters) and only
        *emit* when tracing is on."""
        self._record(name, cat, int(t0 * 1e9), int(dur * 1e9), attrs)

    def flush(self):
        """Write the Chrome trace-event artifact (atomically: temp file +
        rename, so a reader never sees a torn JSON)."""
        with self._lock:
            events = sorted(self._events,
                            key=lambda e: (e["pid"], e["ts"], -e["dur"]))
            doc = {
                "traceEvents": events,
                "displayTimeUnit": "ms",
                "otherData": {"tool": "superlu_dist_tpu.obs",
                              "pid": os.getpid(),
                              "spans": len(events)},
            }
            tmp = self.path + f".tmp{os.getpid()}"
            parent = os.path.dirname(os.path.abspath(self.path))
            os.makedirs(parent, exist_ok=True)
            # atomic artifact write serialized by the same lock —
            # the flush is the guarded operation
            with open(tmp, "w") as f:  # slulint: disable=SLU109
                json.dump(doc, f, default=str)
            os.replace(tmp, self.path)

    def close(self):
        if self._closed:
            return
        self.flush()
        with self._lock:
            self._closed = True
            if self._jsonl is not None:
                self._jsonl.close()
                self._jsonl = None


class _TeeSpan:
    """One span mirrored into every child tracer."""

    __slots__ = ("_spans",)

    def __init__(self, spans):
        self._spans = spans

    def __enter__(self):
        for s in self._spans:
            s.__enter__()
        return self

    def __exit__(self, *exc):
        for s in reversed(self._spans):
            s.__exit__(*exc)
        return False

    def set(self, **attrs):
        for s in self._spans:
            s.set(**attrs)
        return self


class TeeTracer:
    """Fan-out tracer: every span/record goes to each child (the file
    tracer + the flight recorder when both are enabled)."""

    enabled = True

    def __init__(self, *tracers):
        self._tracers = [t for t in tracers if t is not None and t.enabled]

    @property
    def path(self):
        for t in self._tracers:
            if getattr(t, "path", None):
                return t.path
        return None

    @property
    def profiling(self):
        return any(getattr(t, "profiling", False) for t in self._tracers)

    def span(self, name, cat="phase", **attrs):
        return _TeeSpan([t.span(name, cat, **attrs) for t in self._tracers])

    def complete(self, name, cat, t0, dur, **attrs):
        for t in self._tracers:
            t.complete(name, cat, t0, dur, **attrs)

    def flush(self):
        for t in self._tracers:
            t.flush()

    def close(self):
        for t in self._tracers:
            t.close()


# ---- process-global tracer -------------------------------------------------

_tracer = None
_init_lock = make_lock("obs.trace._init_lock")


def get_tracer():
    """The process tracer, composed from two env gates on first use:
    ``SLU_TPU_TRACE`` (the file tracer) and ``SLU_TPU_FLIGHTREC`` (the
    ring-buffer flight recorder, obs/flightrec.py — it implements the
    tracer protocol, so every instrumentation site feeds it for free).
    Both on → a ``TeeTracer``; one on → that one; neither → the
    ``NULL_TRACER`` singleton.  Tests reconfigure via
    ``install``/``_reset``."""
    global _tracer
    t = _tracer
    if t is None:
        with _init_lock:
            if _tracer is None:
                from superlu_dist_tpu.utils.options import env_str
                path = env_str("SLU_TPU_TRACE").strip()
                file_tracer = None
                if path:
                    # init-once singleton construction: the anchor
                    # record it writes is the guarded operation
                    file_tracer = Tracer(path)  # slulint: disable=SLU109
                    atexit.register(file_tracer.close)
                from superlu_dist_tpu.obs.flightrec import get_flightrec
                # the open the call graph sees runs in a DEFERRED
                # SIGTERM handler, never under this init lock
                fr = get_flightrec()  # slulint: disable=SLU109
                if file_tracer is not None and fr.enabled:
                    _tracer = TeeTracer(file_tracer, fr)
                elif file_tracer is not None:
                    _tracer = file_tracer
                elif fr.enabled:
                    _tracer = fr
                else:
                    _tracer = NULL_TRACER
            t = _tracer
    return t


def install(tracer):
    """Install ``tracer`` as the process tracer (programmatic enable for
    tests and embedding callers); returns the previous one.  The caller
    owns flushing/closing both."""
    global _tracer
    prev = _tracer
    _tracer = tracer
    return prev


def _reset():
    """Close any active tracer and re-read ``SLU_TPU_TRACE`` on next use
    (test hygiene)."""
    global _tracer
    t = _tracer
    _tracer = None
    if t is not None and t is not NULL_TRACER:
        t.close()


def enabled() -> bool:
    return get_tracer().enabled


def span(name, cat="phase", **attrs):
    """Module-level convenience: ``with span("FACT", cat="phase"): ...``"""
    return get_tracer().span(name, cat, **attrs)


def complete(name, cat, t0, dur, **attrs):
    get_tracer().complete(name, cat, t0, dur, **attrs)
