"""SLU108 true-positive fixture: the worker thread writes self._count
under the lock, but the public stats() read skips it — a cross-thread
data race slulint must flag (and the clean twin guarded_shared.py must
not)."""
import threading


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._count = 0
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self):
        self._thread.start()

    def _loop(self):
        while not self._stop.wait(0.01):
            with self._lock:
                self._count += 1

    def stats(self):
        return self._count

    def close(self):
        self._stop.set()
        self._thread.join(1.0)
