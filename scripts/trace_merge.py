#!/usr/bin/env python
"""Merge per-process Chrome trace artifacts onto one wall clock.

Every tracer artifact (``SLU_TPU_TRACE=trace-%p.json`` — one file per
process) stamps its spans in microseconds relative to its OWN
``perf_counter`` epoch and records one ``clock-anchor`` event carrying
the epoch's absolute wall time (``args.unix_time``).  This script joins
N such artifacts on those anchors: the earliest anchor becomes the
merged timeline's zero, every other artifact's events are shifted by
its anchor's offset from that zero, and the result is ONE Chrome/
Perfetto JSON in which a router-side ``fleet-request`` span and its
replica-side ``request`` stage spans line up on the same axis — follow
the shared ``trace_id`` arg across the process tracks.

Usage::

    python scripts/trace_merge.py -o merged.json trace-123.json trace-456.json

Sub-millisecond alignment only (the anchors are wall-clock reads, not a
clock-sync protocol) — good enough to eyeball a ticket's journey, not
to time a single kernel across hosts.

Exit 0 on success; non-zero when an input is unreadable or carries no
clock anchor.
"""

import argparse
import json
import sys


def load_events(path: str) -> tuple[list, float]:
    """The artifact's events plus its anchor's absolute wall time."""
    with open(path) as f:
        doc = json.load(f)
    events = doc.get("traceEvents")
    if not isinstance(events, list) or not events:
        raise SystemExit(f"{path}: no traceEvents")
    anchors = [e for e in events if e.get("name") == "clock-anchor"]
    if not anchors:
        raise SystemExit(f"{path}: no clock-anchor event (artifact too "
                         "old, or not a superlu_dist_tpu trace)")
    a = anchors[0]
    try:
        unix0 = float(a["args"]["unix_time"]) - float(a["ts"]) / 1e6
    except (KeyError, TypeError, ValueError):
        raise SystemExit(f"{path}: malformed clock-anchor {a!r}")
    return events, unix0


def merge(paths: list) -> dict:
    loaded = [(p, *load_events(p)) for p in paths]
    base = min(unix0 for _p, _ev, unix0 in loaded)
    out = []
    for path, events, unix0 in loaded:
        shift_us = (unix0 - base) * 1e6
        for ev in events:
            ev = dict(ev)
            ev["ts"] = round(float(ev["ts"]) + shift_us, 3)
            out.append(ev)
    out.sort(key=lambda e: (e.get("pid", 0), e["ts"], -e.get("dur", 0)))
    return {
        "traceEvents": out,
        "displayTimeUnit": "ms",
        "otherData": {"tool": "superlu_dist_tpu.obs trace_merge",
                      "sources": [p for p, _e, _u in loaded],
                      "base_unix_time": round(base, 6),
                      "spans": len(out)},
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="merge per-process trace artifacts on their "
                    "clock anchors")
    ap.add_argument("inputs", nargs="+", help="tracer JSON artifacts")
    ap.add_argument("-o", "--output", required=True,
                    help="merged Chrome trace JSON path")
    args = ap.parse_args(argv)
    doc = merge(args.inputs)
    with open(args.output, "w") as f:
        json.dump(doc, f)
    pids = {e.get("pid") for e in doc["traceEvents"]}
    print(f"merged {len(args.inputs)} artifacts -> {args.output} "
          f"({doc['otherData']['spans']} spans, {len(pids)} process "
          "tracks)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
