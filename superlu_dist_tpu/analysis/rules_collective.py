"""SLU101 — collective-consistency (interprocedural since v2).

Every rank attached to a TreeComm domain must execute the same collective
sequence (treecomm.py's contract; the reference's per-supernode Bc/Rd
trees are likewise matched, TreeBcast_slu.hpp).  The deadly shapes:

* a collective call INSIDE a branch (or loop) whose condition depends on
  the caller's rank / grid coordinates — only some ranks reach it;
* a collective call AFTER a rank-conditioned early exit (`return` /
  `raise` / `break` / `continue` under a rank test, or an `assert` whose
  predicate involves the rank) earlier in the same function — some ranks
  left before reaching it;
* a collective call inside an `except` handler — exceptions raise on a
  strict subset of ranks by construction (the project-blessed pattern is
  pgssvx.bcast_result, which ships the exception THROUGH a collective
  every rank reaches).

v1 recognized these lexically: only a call spelled `*.bcast_any(...)`
inside the branch counted.  v2 closes the two indirection gaps MUST-style
dynamic tools showed matter in practice:

* *transitive* collectives — a call to any function that REACHES a
  collective through the call graph (`_ship(tc, x)` wrapping the
  `bcast_any`) is treated exactly like the collective itself, with the
  finding naming both the wrapper and the witness site it reaches;
* *dataflow rank predicates* — a branch condition is rank-dependent not
  only when it lexically names a rank, but when it uses a local the
  forward pass proved rank-tainted (`r = tc.rank; if r == 0:`) or calls
  a function whose returns are rank-derived (`if is_root(tc):`).

The scan remains per function; nested `def`s start a fresh context
(their bodies run at call time, not at definition time).
"""

from __future__ import annotations

import ast

from superlu_dist_tpu.analysis.core import Rule
from superlu_dist_tpu.analysis.dataflow import COLLECTIVE_METHODS, FnFlow

_RANK_ATTRS = frozenset({"rank", "iam", "myrow", "mycol"})
_RANK_NAMES = frozenset({"rank", "iam", "myrank", "my_rank"})


def _is_rank_expr_lexical(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in _RANK_ATTRS:
            return True
        if isinstance(sub, ast.Name) and sub.id in _RANK_NAMES:
            return True
    return False


def _has_early_exit(stmts) -> bool:
    for st in stmts:
        for sub in ast.walk(st):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                continue
            if isinstance(sub, (ast.Return, ast.Raise, ast.Break,
                                ast.Continue)):
                return True
    return False


class _FunctionScan:
    """One function body, scanned statement-by-statement in order."""

    def __init__(self, rule, path, findings, project=None, flow=None):
        self.rule = rule
        self.path = path
        self.findings = findings
        self.project = project
        self.flow = flow               # FnFlow of THIS function body
        self.diverged_at = None        # line of the earliest rank-dep. exit

    def _sub_scan(self, fn_node):
        flow = None
        if self.project is not None:
            flow = FnFlow(fn_node.body, self.path,
                          lambda c: self.project.call_target(self.path, c),
                          self.project.summaries).run()
        return _FunctionScan(self.rule, self.path, self.findings,
                             self.project, flow)

    def _is_rank_expr(self, node: ast.AST) -> bool:
        if _is_rank_expr_lexical(node):
            return True
        if self.flow is not None and self.flow.rank_tainted(node):
            return True
        return False

    def flag(self, call, why, indirect=None):
        if indirect is not None:
            via, (owner, witness) = indirect
            why = (f"call to `{via}` reaches collective `{witness}` "
                   f"(via `{owner}`); {why}")
        self.findings.append(self.rule.finding(self.path, call, why))

    def scan(self, stmts, in_rank_branch=False, in_except=False):
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._sub_scan(st).scan(st.body)
                continue
            if isinstance(st, ast.ClassDef):
                self.scan(st.body, in_rank_branch, in_except)
                continue

            rank_cond = isinstance(st, (ast.If, ast.While)) \
                and self._is_rank_expr(st.test)

            # flag the collectives this statement directly owns (for
            # compound statements that is the header expression, which
            # every rank still evaluates — so rank_cond alone does not
            # flag it; only an ENCLOSING rank branch does)
            for call, indirect in self.direct_collectives(st):
                if in_except:
                    self.flag(call,
                              "collective inside an `except` handler — "
                              "the exception raised on a subset of ranks, "
                              "so the others never reach this call",
                              indirect)
                elif in_rank_branch:
                    self.flag(call,
                              "collective under rank-dependent control "
                              "flow — only some ranks reach it", indirect)
                elif self.diverged_at is not None:
                    self.flag(call,
                              "collective after a rank-dependent early "
                              f"exit (line {self.diverged_at}) — ranks "
                              "that exited never reach this call", indirect)

            # recurse into compound statements with updated context
            if isinstance(st, (ast.If, ast.While)):
                branch = in_rank_branch or rank_cond
                self.scan(st.body, branch, in_except)
                self.scan(st.orelse, branch, in_except)
                if rank_cond and not in_rank_branch \
                        and self.diverged_at is None \
                        and (_has_early_exit(st.body)
                             or _has_early_exit(st.orelse)):
                    self.diverged_at = st.lineno
            elif isinstance(st, (ast.For, ast.AsyncFor)):
                self.scan(st.body, in_rank_branch, in_except)
                self.scan(st.orelse, in_rank_branch, in_except)
            elif isinstance(st, (ast.With, ast.AsyncWith)):
                self.scan(st.body, in_rank_branch, in_except)
            elif isinstance(st, ast.Try):
                self.scan(st.body, in_rank_branch, in_except)
                for h in st.handlers:
                    self.scan(h.body, in_rank_branch, True)
                self.scan(st.orelse, in_rank_branch, in_except)
                self.scan(st.finalbody, in_rank_branch, in_except)
            elif isinstance(st, ast.Assert) and self._is_rank_expr(st.test) \
                    and not in_rank_branch and self.diverged_at is None:
                # an assert on a rank-dependent predicate is a
                # conditional raise on a subset of ranks
                self.diverged_at = st.lineno

    def _classify(self, call: ast.Call):
        """(call, indirect-info) when `call` is collective-bearing:
        directly (attribute named like a collective) or transitively
        (resolved callee whose summary reaches a collective)."""
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in COLLECTIVE_METHODS:
            return call, None
        if self.project is not None:
            target = self.project.call_target(self.path, call)
            s = self.project.summaries.get(target) if target else None
            if s is not None and s.reaches_collective is not None:
                via = target.rsplit(".", 2)
                return call, (".".join(via[-2:]), s.reaches_collective)
        return None

    def _collective_calls(self, node: ast.AST):
        """Collective-bearing Call nodes lexically inside `node`,
        excluding nested function/class bodies (those execute in their
        own context)."""
        stack = [node]
        while stack:
            cur = stack.pop()
            for child in ast.iter_child_nodes(cur):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef, ast.Lambda)):
                    continue
                if isinstance(child, ast.Call):
                    hit = self._classify(child)
                    if hit is not None:
                        yield hit
                stack.append(child)

    def direct_collectives(self, st):
        """Collectives in `st`'s own expressions — for compound
        statements, only the header (test/iter/items), since the body is
        scanned recursively with its own context."""
        if isinstance(st, (ast.If, ast.While)):
            roots = [st.test]
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            roots = [st.iter]
        elif isinstance(st, (ast.With, ast.AsyncWith)):
            roots = [i.context_expr for i in st.items]
        elif isinstance(st, ast.Try):
            roots = []
        else:
            roots = [st]
        out = []
        for r in roots:
            if isinstance(r, ast.Call):
                hit = self._classify(r)
                if hit is not None:
                    out.append(hit)
            out.extend(self._collective_calls(r))
        return out


class CollectiveRule(Rule):
    rule_id = "SLU101"
    title = "collective-consistency"
    hint = ("make every rank reach the collective: hoist it out of the "
            "rank branch, allreduce the predicate first, or ship the "
            "root-side work through pgssvx.bcast_result (which carries "
            "exceptions to every rank)")

    def __init__(self, interprocedural: bool = True):
        # interprocedural=False restores the PR-3 lexical behavior (used
        # by the regression tests proving v2 catches what v1 missed)
        self.interprocedural = interprocedural

    def check(self, tree, source, path, project=None):
        findings = []
        proj = project if self.interprocedural else None
        flow = None
        if proj is not None:
            flow = FnFlow.for_module(proj, path, tree).run()
        # module level counts as one function body (scripts run it)
        _FunctionScan(self, path, findings, proj, flow).scan(tree.body)
        # findings inside compound headers can be discovered twice (once
        # as the header root, once in the generic walk) — dedupe by site
        seen, out = set(), []
        for f in findings:
            key = (f.line, f.col, f.message)
            if key not in seen:
                seen.add(key)
                out.append(f)
        return out
