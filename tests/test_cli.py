"""CLI driver tests (the pddrive / pdtest analog, EXAMPLE/pddrive.c:51)."""

import os

import numpy as np
import pytest

from superlu_dist_tpu.__main__ import main
from superlu_dist_tpu.io import write_matrix_market
from superlu_dist_tpu.models.gallery import poisson2d

REF = "/root/reference/EXAMPLE"


@pytest.fixture
def mtx_file(tmp_path):
    a = poisson2d(7)
    path = str(tmp_path / "p2d.mtx")
    write_matrix_market(path, a)
    return path


def test_cli_solves_generated_matrix(mtx_file, capsys):
    rc = main(["-f", mtx_file])
    out = capsys.readouterr().out
    assert rc == 0
    assert "residual" in out and "FACT" in out


def test_cli_trans_and_nrhs(mtx_file):
    assert main(["-f", mtx_file, "--trans", "--nrhs", "2", "-q"]) == 0


@pytest.mark.skipif(not os.path.exists(f"{REF}/g20.rua"),
                    reason="no fixtures")
@pytest.mark.slow
def test_cli_reference_fixture(capsys):
    rc = main(["-f", f"{REF}/g20.rua", "--colperm", "MMD"])
    assert rc == 0
    assert "residual" in capsys.readouterr().out
