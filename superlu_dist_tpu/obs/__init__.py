"""Observability subsystem — the PROFlevel analog.

One layer owns all measurement machinery:

* ``obs.trace``   — structured span tracer (``SLU_TPU_TRACE=<path>``):
  nested spans with categories (phase / dispatch / kernel / comm /
  host-offload), emitted as Chrome trace-event JSON (Perfetto-loadable)
  plus a crash-safe JSONL sidecar;
* comm telemetry  — per-op counters on the tree collectives
  (``parallel/treecomm.py`` → ``utils.stats.CommStats``), the
  PROFlevel≥1 comm split;
* kernel-shape telemetry — structured per-dispatch records from both
  factorization executors and the device solve (the dgemm_mnk.dat
  analog);
* cross-rank stat reduction — ``utils.stats.Stats.reduce`` (min/max/avg
  + load-balance factor per phase, the sum-over-ranks PStatPrint).

See docs/OBSERVABILITY.md for the artifact formats and a worked
Perfetto example.
"""

from superlu_dist_tpu.obs.trace import (      # noqa: F401
    CATEGORIES, NULL_SPAN, NULL_TRACER, NullTracer, Tracer,
    complete, enabled, get_tracer, install, span)
