"""Shared helpers for the example drivers.

Analog of EXAMPLE/dcreate_matrix.c:66,239: load a matrix (Harwell-Boeing /
Rutherford-Boeing / MatrixMarket / triples), fabricate a known solution
xtrue, build b = A·xtrue, and report ‖x−xtrue‖∞ after the solve — the
reference's examples are self-checking accuracy tests, and so are these.

Every driver accepts an optional matrix-file argument; without one it
falls back to the reference fixture (if present) or a generated 2-D
Poisson problem, so the examples always run.
"""

from __future__ import annotations

import os
import sys

import numpy as np

_REF_FIXTURE = "/root/reference/EXAMPLE/g20.rua"
_REF_FIXTURE_Z = "/root/reference/EXAMPLE/cg20.cua"


def pin_cpu_if_requested():
    """`--backend cpu` anywhere on the CLI pins the CPU backend (must run
    before any jax use; see superlu_dist_tpu/__main__.py)."""
    if "--backend" in sys.argv:
        i = sys.argv.index("--backend")
        if i + 1 < len(sys.argv) and sys.argv[i + 1] == "cpu":
            import jax
            jax.config.update("jax_platforms", "cpu")
            jax.config.update("jax_enable_x64", True)


def load_matrix(complex_: bool = False):
    """Matrix from argv[1] if given, else the reference fixture, else a
    generated Poisson problem (dcreate_matrix_postfix analog)."""
    from superlu_dist_tpu.io import read_matrix
    from superlu_dist_tpu.models.gallery import poisson2d

    args = [a for a in sys.argv[1:] if not a.startswith("--")
            and a != "cpu"]
    if args:
        return read_matrix(args[0]).tocsr(), args[0]
    fixture = _REF_FIXTURE_Z if complex_ else _REF_FIXTURE
    if os.path.exists(fixture):
        return read_matrix(fixture).tocsr(), fixture
    a = poisson2d(20)
    if complex_:
        a = type(a)(a.n_rows, a.n_cols, a.indptr, a.indices,
                    a.data.astype(np.complex128))
    return a, "poisson2d(20)"


def make_rhs(a, nrhs: int = 1, seed: int = 0):
    """xtrue + b = A·xtrue (dGenXtrue_dist / dFillRHS_dist analogs)."""
    from superlu_dist_tpu.utils.precision import gen_xtrue, fill_rhs
    xtrue = gen_xtrue(a.n_rows, nrhs, dtype=a.data.dtype, seed=seed)
    return xtrue, fill_rhs(a, xtrue)


def report(name, a, b, x, xtrue, stats):
    from superlu_dist_tpu.utils.precision import inf_norm_error
    resid = float(np.linalg.norm(np.ravel(b - a.matvec(x)))
                  / max(float(np.linalg.norm(np.ravel(b))), 1e-300))
    err = inf_norm_error(x, xtrue)
    print(f"[{name}] residual ||b-Ax||/||b|| = {resid:.3e}   "
          f"||x-xtrue||inf/||x||inf = {err:.3e}")
    stats.print()
    return resid
