"""Solve plan: the serving-side twin of the factor plan.

The factor path got an earliest-ready dataflow scheduler in PR 5
(numeric/plan.py); the triangular-solve path kept dispatching one kernel
per FACTOR group — a grouping tuned for factorization batch shapes, not
for the latency-bound sweeps that dominate a serving workload
(dataflow SpTRSV, arXiv:2406.10511; interleaved many-RHS batching,
arXiv:1909.04539).  This module builds a :class:`SolvePlan` on top of a
finished :class:`~superlu_dist_tpu.numeric.plan.FactorPlan`:

* **Cross-level batching** — the SAME `_dataflow_batches` machinery the
  factor scheduler uses (dependency = the supernode etree) regroups
  supernodes into maximal same-shape sweep batches, unconstrained by the
  factor window: the solve holds no Schur pool, so the look-ahead window
  defaults to unbounded (``SLU_TPU_SOLVE_WINDOW=0``) and whole key
  columns of the etree collapse into single dispatches.
* **Shape-key alignment** — the PR 5 `_align_shape_keys` pre-pass runs
  AGAIN on top of the factor keys (``SLU_TPU_SOLVE_ALIGN``): the solve
  executes O(w² + wu) per front where the factor executes O(w²·m), so
  the solve can afford to coalesce far more aggressively than the factor
  did.  Members promoted to a larger key get identity/zero padding when
  the solver gathers their panels (solve/device.py).
* **Bounded nrhs buckets** — a CLOSED bucket set replaces the old pure
  power-of-two rounding: power-of-two rungs up to 64, then geometric
  growth (``SLU_TPU_SOLVE_NRHS_GROWTH``) rounded to multiples of 32, up
  to ``SLU_TPU_SOLVE_NRHS_MAX``.  Any request nrhs maps to at most
  ``len(buckets)`` compiled kernel variants; wider right-hand sides are
  column-chunked at the cap (:func:`chunk_nrhs`) — the compile set is
  bounded no matter what traffic arrives, the serving analog of the
  ROADMAP item 3 closed-bucket discipline.

Schedules: ``dataflow`` (default) | ``level`` (strict level lockstep)
| ``factor`` (mirror the factor grouping 1:1 — the pre-PR-9 behavior).

The ``factor`` schedule is FORCED only on MULTI-PROCESS mesh solves
(solve/device.DeviceSolver): regrouping supernodes into dataflow sweep
batches re-stacks panels out of their factor-group arrays, and on a
multi-process mesh those stacks hold shards the local controller
cannot address — any re-gather would commit non-addressable remote
shards to one local device (a cross-host copy pjit forbids).  Keeping
the factor grouping 1:1 means every sweep kernel consumes the factor
arrays exactly as sharded.  Single-process meshes (including the
virtual CPU mesh and the shard_map SPMD tier, parallel/spmd.SpmdSolver)
have one controller addressing every shard, so they keep the dataflow
schedule and its cross-level batching wins.

Like the factor plan, everything here is host-side numpy, computed once
per factorization and reused across every subsequent solve
(the SolveInitialized discipline, pdgssvx.c:1330-1337).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from superlu_dist_tpu.numeric.plan import (
    FactorPlan, _align_shape_keys, _dataflow_batches, _level_batches)

#: nrhs values below the geometric regime get exact power-of-two rungs —
#: single-vector and small-batch solves are the latency-critical serving
#: shapes and must not pad at all.
_POW2_RUNGS = (1, 2, 4, 8, 16, 32, 64)


def nrhs_buckets(max_bucket: int, growth: float) -> tuple:
    """The closed nrhs bucket set: power-of-two up to 64, then geometric
    (factor ``growth``, rounded up to a multiple of 32), capped at
    ``max_bucket`` which is always the largest member."""
    max_bucket = max(int(max_bucket), 1)
    growth = max(float(growth), 1.01)
    sizes = {b for b in _POW2_RUNGS if b <= max_bucket}
    s = 64
    while s < max_bucket:
        s = int(np.ceil(s * growth / 32.0) * 32)
        sizes.add(min(s, max_bucket))
    sizes.add(max_bucket)
    return tuple(sorted(sizes))


def bucket_nrhs(k: int, buckets: tuple) -> int:
    """Smallest bucket >= k (k must be <= the cap — see chunk_nrhs)."""
    for b in buckets:
        if b >= k:
            return b
    raise ValueError(f"nrhs {k} exceeds the bucket cap {buckets[-1]} — "
                     "chunk_nrhs() the columns first")


def chunk_nrhs(k: int, buckets: tuple) -> list:
    """Split k right-hand-side columns into ``[(lo, hi, bucket), ...]``
    chunks: full chunks of the cap bucket, then one bucketed remainder.
    The compiled-kernel set stays bounded by the bucket set regardless
    of the request width."""
    cap = buckets[-1]
    out = []
    lo = 0
    while k - lo > cap:
        out.append((lo, lo + cap, cap))
        lo += cap
    if k - lo > 0 or not out:
        out.append((lo, k, bucket_nrhs(max(k - lo, 1), buckets)))
    return out


@dataclasses.dataclass
class SolveGroup:
    """One sweep batch: supernodes sharing a padded (W, U) solve shape.

    ``src_group``/``src_slot`` locate each member's factored panels
    inside the FACTOR plan's front arrays; ``reuse`` names the factor
    group whose front arrays can serve this batch as-is (same members,
    same order, same shape — the zero-copy fast path), or -1 when the
    solver must gather (and possibly pad) a fresh panel stack."""

    level: int
    m: int                  # padded front size (w + u)
    w: int                  # padded pivot width
    u: int                  # padded below-diagonal row count
    batch: int
    sns: np.ndarray         # supernode ids, slot order (ascending)
    ws: np.ndarray          # (batch,) real pivot widths
    src_group: np.ndarray   # (batch,) factor group of each member
    src_slot: np.ndarray    # (batch,) slot within that factor group
    reuse: int = -1         # factor group to alias, or -1 => gather


@dataclasses.dataclass
class SolvePlan:
    """Sweep schedule + nrhs bucket geometry for one factorization."""

    n: int
    sf: object                     # SymbolicFact (shared with the plan)
    groups: list                   # SolveGroups, forward-sweep order
    schedule: str                  # "dataflow" | "level" | "factor"
    window: int
    align: float
    nrhs_bucket_set: tuple
    n_factor_groups: int           # the pre-PR-9 dispatch count baseline
    critical_path: int             # longest dependent-group chain
    flops_per_rhs: float           # structural sweep flops per rhs column
    executed_flops_per_rhs: float  # shape-padded flops per PADDED column

    @property
    def mean_occupancy(self) -> float:
        return (self.sf.n_supernodes / len(self.groups)
                if self.groups else 0.0)

    def solve_flops(self, nrhs: int) -> float:
        """Structural flops of one solve with nrhs columns (the honest
        numerator for solve GFLOP/s)."""
        return self.flops_per_rhs * nrhs

    def executed_flops(self, nrhs: int) -> float:
        """Executed flops including BOTH paddings: shape padding (every
        front runs at its bucket (W, U)) and nrhs padding (every chunk
        runs at its bucket width) — the executed-vs-structural honesty
        the factor path has reported since PR 2."""
        kb = sum(b for _, _, b in chunk_nrhs(int(nrhs),
                                             self.nrhs_bucket_set))
        return self.executed_flops_per_rhs * kb

    def padding_factor(self, nrhs: int) -> float:
        return self.executed_flops(nrhs) / max(self.solve_flops(nrhs), 1.0)

    def schedule_stats(self, nrhs: int | None = None) -> dict:
        """Telemetry block (the FactorPlan.schedule_stats twin): group
        count vs the factor grouping, occupancy, critical path, shape
        padding — plus, when ``nrhs`` is given, the full nrhs-inclusive
        padding factor and the chunked bucket widths."""
        out = {
            "schedule": self.schedule,
            "n_groups": len(self.groups),
            "n_factor_groups": self.n_factor_groups,
            "occupancy": round(self.mean_occupancy, 2),
            "window": self.window,
            "align": self.align,
            "critical_path": self.critical_path,
            "nrhs_buckets": list(self.nrhs_bucket_set),
            "shape_padding": round(
                self.executed_flops_per_rhs / max(self.flops_per_rhs, 1.0),
                4),
            "reused_groups": sum(1 for g in self.groups if g.reuse >= 0),
        }
        if nrhs is not None:
            out["nrhs"] = int(nrhs)
            out["padded_nrhs"] = sum(
                b for _, _, b in chunk_nrhs(int(nrhs),
                                            self.nrhs_bucket_set))
            out["padding_factor"] = round(self.padding_factor(nrhs), 4)
        return out


def _factor_keys(plan: FactorPlan):
    """Per-supernode (W, U) padded shape keys as the factor plan
    assigned them (bucketing + PR 5 alignment already folded in)."""
    ns = plan.sf.n_supernodes
    gw = np.array([g.w for g in plan.groups], dtype=np.int64)
    gu = np.array([g.u for g in plan.groups], dtype=np.int64)
    return gw[plan.sn_group[:ns]], gu[plan.sn_group[:ns]]


def build_solve_plan(plan: FactorPlan, schedule: str | None = None,
                     window: int | None = None,
                     align: float | None = None,
                     nrhs_max: int | None = None,
                     nrhs_growth: float | None = None) -> SolvePlan:
    """Build the sweep schedule for a factor plan.  Pure numpy.

    Defaults come from the knob registry: ``SLU_TPU_SOLVE_SCHEDULE``
    (dataflow), ``SLU_TPU_SOLVE_WINDOW`` (0 = unbounded look-ahead),
    ``SLU_TPU_SOLVE_ALIGN`` (solve-side shape-key coalescing tolerance,
    <= 1 disables), ``SLU_TPU_SOLVE_NRHS_MAX`` / ``_GROWTH`` (bucket
    geometry).  ``schedule="factor"`` mirrors the factor grouping 1:1
    (alignment is then a no-op by construction — the panels are served
    from the factor fronts unchanged)."""
    from superlu_dist_tpu.utils.options import env_float, env_int, env_str
    if schedule is None:
        schedule = env_str("SLU_TPU_SOLVE_SCHEDULE")
    if schedule not in ("dataflow", "level", "factor"):
        raise ValueError(
            f"SLU_TPU_SOLVE_SCHEDULE must be 'dataflow', 'level' or "
            f"'factor', got {schedule!r}")
    if window is None:
        window = env_int("SLU_TPU_SOLVE_WINDOW")
    if align is None:
        align = env_float("SLU_TPU_SOLVE_ALIGN")
    if nrhs_max is None:
        nrhs_max = env_int("SLU_TPU_SOLVE_NRHS_MAX")
    if nrhs_growth is None:
        nrhs_growth = env_float("SLU_TPU_SOLVE_NRHS_GROWTH")
    buckets = nrhs_buckets(nrhs_max, nrhs_growth)

    sf = plan.sf
    ns = sf.n_supernodes
    widths = np.diff(sf.sn_start).astype(np.int64)
    us = np.array([len(r) for r in sf.sn_rows], dtype=np.int64)

    if schedule == "factor":
        batches = [(g.level, g.sns) for g in plan.groups]
        sn_W, sn_U = _factor_keys(plan)
    else:
        sn_W, sn_U = _factor_keys(plan)
        sn_W, sn_U = _align_shape_keys(sn_W, sn_U, float(align))
        if schedule == "dataflow":
            batches = _dataflow_batches(sf, sn_W, sn_U, int(window))
        else:
            batches = _level_batches(sf, sn_W, sn_U)

    groups: list[SolveGroup] = []
    for lvl, sns in batches:
        s0 = int(sns[0])
        W, U = int(sn_W[s0]), int(sn_U[s0])
        src_group = plan.sn_group[sns]
        src_slot = plan.sn_slot[sns]
        # zero-copy aliasing: this batch IS a factor group, same member
        # order, same padded shape — the common case whenever the solve
        # schedule reproduces the factor one (and always under "factor")
        reuse = -1
        g0 = int(src_group[0])
        fg = plan.groups[g0]
        if ((fg.w, fg.u) == (W, U) and len(fg.sns) == len(sns)
                and np.array_equal(fg.sns, sns)):
            reuse = g0
        groups.append(SolveGroup(
            level=int(lvl), m=W + U, w=W, u=U, batch=len(sns), sns=sns,
            ws=widths[sns], src_group=src_group, src_slot=src_slot,
            reuse=reuse))

    # dependent-group critical path — the serial depth of one sweep
    # (same recurrence as FactorPlan's)
    pdepth = np.zeros(ns, dtype=np.int64)
    critical_path = 0
    for grp in groups:
        d = int(pdepth[grp.sns].max(initial=0)) + 1
        critical_path = max(critical_path, d)
        pg = sf.sn_parent[grp.sns]
        valid = pg >= 0
        if valid.any():
            np.maximum.at(pdepth, pg[valid], d)

    # flops per rhs column: one triangular solve (w²) + one gemv (2wu)
    # per front per sweep, forward (L) and backward (U) — structural at
    # real (w, u), executed at the padded batch shapes
    structural = float(np.sum(2.0 * widths * widths
                              + 4.0 * widths * us))
    executed = float(sum(g.batch * (2.0 * g.w * g.w + 4.0 * g.w * g.u)
                         for g in groups))
    return SolvePlan(
        n=plan.n, sf=sf, groups=groups, schedule=schedule,
        window=int(window), align=float(align), nrhs_bucket_set=buckets,
        n_factor_groups=len(plan.groups), critical_path=critical_path,
        flops_per_rhs=structural, executed_flops_per_rhs=executed)
