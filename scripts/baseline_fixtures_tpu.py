#!/usr/bin/env python
"""Measure BASELINE.md configs 1-3 (the reference's fixture matrices) on
the real TPU backend through the full gssvx pipeline.

Configs (BASELINE.md table): g20.rua (n=400, real), big.rua (n=4960,
real), cg20.cua (n=400, complex).  On TPU the factor dtype is f32 (c64
complex) with f64 iterative refinement — the framework's GESP+IR design;
the residual reported is after refinement and must be at reference
accuracy (<=1e-10).  The grid is 1x1: one real chip is available (the
2x2-mesh versions of these configs are validated on the virtual CPU mesh
in tests/test_parallel.py and test_pgssvx.py).

Per config prints one JSON line and appends to
docs/baseline_fixtures_tpu.jsonl:
  {"config": ..., "matrix": ..., "n": ..., "factor_seconds": ...,
   "gflops": ..., "residual": ..., "refine_steps": ..., "backend": ...}

Warm timing: the factorization is run twice (same plan — the
SamePattern_SameRowPerm tier, the reference's time-stepping case) and
the warm repetition is reported, consistent with the repeated-
factorization timing used for the CPU-backend table in BASELINE.md.
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from superlu_dist_tpu.utils import tols  # noqa: E402

FIXTURES = [
    ("1", "/root/reference/EXAMPLE/g20.rua", "float32"),
    ("2", "/root/reference/EXAMPLE/big.rua", "float32"),
    ("3", "/root/reference/EXAMPLE/cg20.cua", "complex64"),
]


def main():
    import jax
    from superlu_dist_tpu.utils.jaxcache import enable_compile_cache
    enable_compile_cache()

    import superlu_dist_tpu as slu
    from superlu_dist_tpu.io import read_matrix
    from superlu_dist_tpu.utils.options import Fact

    backend = jax.default_backend()
    out_path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "baseline_fixtures_tpu.jsonl")
    for config, path, dtype in FIXTURES:
        a = read_matrix(path).tocsr()
        n = a.n_rows
        rng = np.random.default_rng(0)
        xt = rng.standard_normal(n) + (
            1j * rng.standard_normal(n)
            if np.issubdtype(a.data.dtype, np.complexfloating) else 0)
        b = a.matvec(xt)
        opts = slu.Options(factor_dtype=dtype)
        x, lu, stats, info = slu.gssvx(opts, a, b)
        # warm repetition: same pattern + row perm, cached executor
        stats2 = slu.Stats()
        x, lu, stats2, info = slu.gssvx(
            slu.Options(factor_dtype=dtype,
                        fact=Fact.SamePattern_SameRowPerm),
            a, b, lu=lu, stats=stats2)
        resid = float(np.linalg.norm(b - a.matvec(x))
                      / np.linalg.norm(b))
        fsec = stats2.utime["FACT"]
        rec = {"config": config, "matrix": os.path.basename(path), "n": n,
               "dtype": dtype, "factor_seconds": round(fsec, 5),
               "gflops": round(stats2.ops["FACT"] / max(fsec, 1e-12) / 1e9, 2),
               "residual": resid, "info": info,
               "refine_steps": stats2.refine_steps, "backend": backend}
        print(json.dumps(rec), flush=True)
        # persist each record as it is produced so a failing later config
        # cannot discard an earlier measurement
        with open(out_path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        assert info == 0 and resid < tols.RESID_GATE_TIGHT, rec


if __name__ == "__main__":
    main()
