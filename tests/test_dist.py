"""Distributed row-block format + sharded SpMV (NRformat_loc / pdgsmv
analogs) on the 8-device virtual mesh."""

import numpy as np
import pytest

from superlu_dist_tpu.models.gallery import poisson2d, random_sparse
from superlu_dist_tpu.parallel.dist import (
    DistributedCSR, distribute_rows, gather_rows, ShardedSpMV)
from superlu_dist_tpu.parallel.grid import gridinit


@pytest.mark.parametrize("nparts", [1, 3, 8])
def test_distribute_gather_roundtrip(nparts):
    a = random_sparse(57, density=0.1, seed=2)
    parts = distribute_rows(a, nparts)
    assert sum(p.m_loc for p in parts) == a.n_rows
    assert sum(p.nnz_loc for p in parts) == a.nnz
    back = gather_rows(parts)
    assert np.array_equal(back.indptr, a.indptr.astype(back.indptr.dtype))
    assert np.array_equal(back.indices, a.indices)
    np.testing.assert_array_equal(back.data, a.data)


def test_local_matvec_assembles_global():
    a = poisson2d(9)
    x = np.random.default_rng(0).standard_normal(a.n_rows)
    want = a.matvec(x)
    parts = distribute_rows(a, 4)
    got = np.concatenate([p.matvec_local(x) for p in parts])
    np.testing.assert_allclose(got, want, rtol=1e-14)


def test_gssvx_dist_and_abglobal():
    """Distributed-input and replicated-input driver entry points
    (pdgssvx NRformat_loc path / pdgssvx_ABglobal)."""
    from superlu_dist_tpu.drivers.gssvx import gssvx_dist, gssvx_ABglobal
    from superlu_dist_tpu.utils.options import Options
    a = poisson2d(8)
    xt = np.random.default_rng(3).standard_normal(a.n_rows)
    b = a.matvec(xt)
    parts = distribute_rows(a, 4)
    x, lu, stats, info = gssvx_dist(Options(), parts, b)
    assert info == 0
    np.testing.assert_allclose(x, xt, rtol=1e-8, atol=1e-8)
    x2, _, _, info2 = gssvx_ABglobal(Options(), a, b)
    assert info2 == 0
    np.testing.assert_allclose(x2, xt, rtol=1e-8, atol=1e-8)


@pytest.mark.parametrize("shape", [(4, 2), (8, 1)])
def test_sharded_spmv_matches_host(shape):
    a = poisson2d(11)
    grid = gridinit(*shape)
    spmv = ShardedSpMV(a, grid.mesh)
    x = np.random.default_rng(1).standard_normal(a.n_rows)
    np.testing.assert_allclose(spmv(x), a.matvec(x), rtol=1e-12, atol=1e-12)
    # reuse across "solves" (pdgsmv_init caching)
    x2 = np.random.default_rng(2).standard_normal(a.n_rows)
    np.testing.assert_allclose(spmv(x2), a.matvec(x2), rtol=1e-12, atol=1e-12)


def test_device_spmv_matches_host():
    """pdgsmv analog (SRC/pdgsmv.c:234): device-resident SpMV must equal
    the host CSR matvec, real and complex, 1 and k RHS."""
    from superlu_dist_tpu.parallel.dist import DeviceSpMV
    from superlu_dist_tpu.models.gallery import random_sparse
    rng = np.random.default_rng(5)
    a = random_sparse(80, density=0.07, seed=2)
    dev = DeviceSpMV(a)
    for shape in [(80,), (80, 3)]:
        x = rng.standard_normal(shape)
        np.testing.assert_allclose(dev.matvec(x), a.matvec(x),
                                   rtol=1e-13, atol=1e-13)
    x1 = rng.standard_normal(80)      # abs_matvec contract is per-column
    np.testing.assert_allclose(dev.abs_matvec(np.abs(x1)),
                               a.abs_matvec(np.abs(x1)),
                               rtol=1e-13, atol=1e-13)
    vals = a.data + 1j * rng.standard_normal(a.nnz)
    ac = type(a)(a.n_rows, a.n_cols, a.indptr, a.indices, vals)
    devc = DeviceSpMV(ac)
    xc = rng.standard_normal(80) + 1j * rng.standard_normal(80)
    np.testing.assert_allclose(devc.matvec(xc), ac.matvec(xc),
                               rtol=1e-13, atol=1e-13)
    np.testing.assert_allclose(devc.abs_matvec(np.abs(xc)),
                               ac.abs_matvec(np.abs(xc)),
                               rtol=1e-13, atol=1e-13)
