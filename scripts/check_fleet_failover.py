#!/usr/bin/env python
"""Fleet-failover gate: the serving fleet's headline robustness
guarantee, proven end to end on process replicas (the real kill -9
failure domain).

Three phases over a gallery of ≥8 distinct matrices persisted as
sha256-manifested bundles (CPU, tens of seconds):

1. **Undisturbed baseline** — 3 process replicas serve a deterministic
   mixed stream; every ticket's X is recorded.

2. **kill -9 mid-stream, zero loss** — the same fleet and stream with
   ``SLU_TPU_CHAOS=kill_replica=1@batch=2`` arming a REAL SIGKILL of
   replica 1's process before its 3rd accepted batch: the failover
   must re-route every accepted-but-undelivered ticket (failovers ≥ 1,
   reroutes ≥ 1), ZERO tickets may be lost or errored, and every
   delivered X must be **bitwise identical** to the undisturbed run —
   the idempotent-retry-token contract.

3. **Rolling deploy, zero dropped + poisoned rollback** — under live
   traffic, ``fleet.deploy`` rolls a fresh (identical) factorization
   across every replica with zero dropped/errored tickets and
   bitwise-unchanged answers; then a POISONED bundle (NaN front) must
   be rejected with ``DeployRollbackError`` — via the preflight canary
   with zero replica exposure, and via the per-replica canary (
   ``preflight=False``) with every already-swapped replica restored —
   after which the fleet still serves the original X bitwise.

Exit 0 = pass.  One gate of scripts/ci_gates.sh (the consolidated CI
entry point).  Gate contract (shared with the other gates): any
regression — a lost ticket, a drifted X, a hang, a deploy dropping
work, a poisoned bundle surviving its canary — raises/asserts, which
exits non-zero with the diagnostic on stderr.
"""

import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

N_MATRICES = 8
N_TICKETS = 32
N_REPLICAS = 3


def _bundles(tmp):
    from superlu_dist_tpu.drivers.gssvx import gssvx
    from superlu_dist_tpu.models.gallery import poisson2d
    from superlu_dist_tpu.persist.serial import save_lu
    from superlu_dist_tpu.utils.options import IterRefine, Options

    paths, mats, lus = {}, {}, {}
    for i in range(N_MATRICES):
        a = poisson2d(5 + i)            # 8 distinct systems
        x, lu, stats, info = gssvx(
            Options(iter_refine=IterRefine.NOREFINE), a,
            np.ones(a.n_rows))
        assert info == 0, f"factorization {i} failed: info={info}"
        d = os.path.join(tmp, f"m{i}")
        save_lu(lu, d)
        paths[f"m{i}"] = d
        mats[f"m{i}"] = a
        lus[f"m{i}"] = lu
    return paths, mats, lus


def _stream(fleet, mats, keys):
    rng = np.random.default_rng(7)
    tickets = []
    for j in range(N_TICKETS):
        key = keys[j % len(keys)]
        a = mats[key]
        b = a.matvec(rng.standard_normal(a.n_rows))
        tickets.append(fleet.submit(key, b))
    return [t.result(300) for t in tickets]


def _run(paths, mats, chaos=None):
    from superlu_dist_tpu.serve import FleetRouter

    if chaos:
        os.environ["SLU_TPU_CHAOS"] = chaos
    else:
        os.environ.pop("SLU_TPU_CHAOS", None)
    fleet = FleetRouter(paths, n_replicas=N_REPLICAS, kind="process")
    try:
        xs = _stream(fleet, mats, sorted(paths))
        return xs, fleet.stats()
    finally:
        fleet.close()
        os.environ.pop("SLU_TPU_CHAOS", None)


def check_kill9_zero_loss(paths, mats):
    ref, st0 = _run(paths, mats)
    assert st0["errors"] == 0 and st0["delivered"] == N_TICKETS, st0
    assert st0["failovers"] == 0, "baseline run lost a replica"
    got, st1 = _run(paths, mats, chaos="kill_replica=1@batch=2")
    assert st1["failovers"] >= 1, (
        "the kill -9 injection never fired — the gate is not "
        f"exercising failover (stats: {st1})")
    assert 1 in st1["replicas_failed"], st1["replicas_failed"]
    assert st1["errors"] == 0, (
        f"{st1['errors']} ticket(s) errored across the failover — the "
        "zero-loss contract is broken")
    assert st1["delivered"] == N_TICKETS, (
        f"only {st1['delivered']}/{N_TICKETS} tickets delivered — "
        "accepted work was LOST")
    drift = [i for i, (r, g) in enumerate(zip(ref, got))
             if not np.array_equal(r, g)]
    assert not drift, (
        f"ticket(s) {drift} are not bitwise identical to the "
        "undisturbed run — re-routing changed the arithmetic")
    print(f"  kill -9 of replica 1 mid-stream: {N_TICKETS}/{N_TICKETS} "
          f"delivered, {st1['reroutes']} re-routed, all bitwise "
          "identical to the undisturbed run")


def check_rolling_deploy(paths, mats, lus, tmp):
    import threading

    from superlu_dist_tpu.persist.serial import save_lu
    from superlu_dist_tpu.serve import DeployRollbackError, FleetRouter
    from superlu_dist_tpu.utils.errors import SuperLUError

    key = "m0"
    a = mats[key]
    good2 = os.path.join(tmp, "m0_v2")
    save_lu(lus[key], good2)            # identical refresh bundle
    lu_bad = lus[key]
    lp, up = lu_bad.numeric.fronts[0]
    lu_bad.numeric.fronts[0] = (np.asarray(lp) * np.nan, up)
    bad = os.path.join(tmp, "m0_bad")
    save_lu(lu_bad, bad)

    os.environ.pop("SLU_TPU_CHAOS", None)
    fleet = FleetRouter({key: paths[key]}, n_replicas=N_REPLICAS,
                        kind="process")
    try:
        b = a.matvec(np.ones(a.n_rows))
        ref = fleet.solve(key, b, timeout=300)
        stop = threading.Event()
        outcomes = []
        lock = threading.Lock()

        def client():
            while not stop.is_set():
                try:
                    x = fleet.solve(key, b, timeout=300)
                    tag = ("ok" if np.array_equal(x, ref)
                           else "DRIFT")
                except Exception as e:  # noqa: BLE001 — tallied
                    tag = type(e).__name__
                with lock:
                    outcomes.append(tag)

        th = threading.Thread(target=client)
        th.start()
        try:
            out = fleet.deploy(good2)
        finally:
            stop.set()
            th.join(60)
        assert not th.is_alive(), "deploy-window client hung"
        assert len(out["replicas_swapped"]) == N_REPLICAS, out
        assert outcomes and set(outcomes) == {"ok"}, (
            f"tickets dropped/errored/drifted during the rolling "
            f"deploy: {outcomes}")
        st = fleet.stats()
        assert st["deploys"] == 1 and st["errors"] == 0, st
        print(f"  rolling deploy over {N_REPLICAS} replicas: "
              f"{len(outcomes)} tickets served during the roll, zero "
              "dropped, zero drifted")

        # poisoned bundle, preflight gate: zero replica exposure
        try:
            fleet.deploy(bad)
            raise AssertionError(
                "poisoned bundle survived the preflight canary")
        except DeployRollbackError as e:
            assert e.stage == "canary" and e.rolled_back == [], e
        # poisoned bundle, per-replica gate: swapped replicas restored
        try:
            fleet.deploy(bad, preflight=False)
            raise AssertionError(
                "poisoned bundle survived the per-replica canary")
        except DeployRollbackError as e:
            assert e.stage == "canary" and e.rolled_back == [0], e
        except SuperLUError as e:       # pragma: no cover — diagnostics
            raise AssertionError(
                f"unexpected deploy failure shape: {e}")
        assert fleet.stats()["rollbacks"] == 2
        got = fleet.solve(key, b, timeout=300)
        assert np.array_equal(ref, got), (
            "the fleet does not serve the original factors bitwise "
            "after the rollback")
        print("  poisoned bundle: preflight rejected with zero "
              "exposure; per-replica canary rolled replica 0 back; "
              "original X still served bitwise")
    finally:
        fleet.close()


def main():
    with tempfile.TemporaryDirectory() as tmp:
        print(f"fleet-failover gate: building {N_MATRICES} bundles")
        paths, mats, lus = _bundles(tmp)
        print(f"fleet-failover gate: kill -9 zero-loss "
              f"({N_REPLICAS} process replicas, {N_TICKETS} tickets, "
              f"{N_MATRICES} matrices)")
        check_kill9_zero_loss(paths, mats)
        print("fleet-failover gate: rolling deploy + poisoned rollback")
        check_rolling_deploy(paths, mats, lus, tmp)
    print("fleet-failover gate: OK")


if __name__ == "__main__":
    main()
