"""Incremental slulint: a content-hash-keyed scan-result cache.

The v3 concurrency rules alone cost ~4.5 s over the tree and v4 adds
the dataflow device lattice; meanwhile the CI gates re-scan the
unchanged tree once per invocation (run_slulint.sh, test suites,
pre-commit).  This cache makes the warm whole-tree rescan sub-second:
``.slulint-cache.json`` (gitignored) stores per-file findings keyed by
each file's content sha256, plus a TREE signature over the whole
(path, sha) set and a RULE-SET signature.

Soundness: slulint is interprocedural since v2 — a changed CALLEE can
change a caller's findings — so per-file results are only valid against
the exact project they were computed in.  The tree signature encodes
that: a warm hit requires every file unchanged (then parse, call graph,
dataflow and all rules are skipped outright); any change re-scans the
whole tree and rewrites the cache.  The per-file hashes are what makes
the validity check exact, and the cache is invalidated wholesale when
the rule set or engine version changes (core.ANALYSIS_VERSION in the
rules signature) or when the scanned path set differs.

``--no-cache`` on the CLI bypasses reads AND writes (the escape hatch
for debugging the engine itself).
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile

from superlu_dist_tpu.analysis.core import ANALYSIS_VERSION, Finding

CACHE_VERSION = 1
DEFAULT_CACHE_NAME = ".slulint-cache.json"

_FIELDS = ("rule", "line", "col", "message", "hint")


def rules_signature(rules) -> str:
    """Identity of the rule set + engine semantics: rule ids plus the
    analysis version (bumped on any rule/engine change)."""
    ids = ",".join(sorted(r.rule_id for r in rules))
    blob = f"v{CACHE_VERSION}:{ANALYSIS_VERSION}:{ids}"
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def file_sha(source: str) -> str:
    return hashlib.sha256(source.encode("utf-8", "replace")).hexdigest()[:16]


def tree_signature(hashes: dict) -> str:
    blob = "\n".join(f"{p}\0{h}" for p, h in sorted(hashes.items()))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def lookup(path: str, sources: dict, rules) -> list | None:
    """Findings from a warm cache, or None on any mismatch (missing
    file, changed content, different path set, different rule set)."""
    try:
        with open(path, encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return None
    if doc.get("version") != CACHE_VERSION:
        return None
    if doc.get("rules_sig") != rules_signature(rules):
        return None
    hashes = {p: file_sha(src) for p, src in sources.items()}
    if doc.get("tree_sig") != tree_signature(hashes):
        return None
    files = doc.get("files", {})
    if set(files) != set(sources):
        return None
    out = []
    for p in sorted(files):
        if files[p].get("sha") != hashes[p]:
            return None
        for f in files[p].get("findings", ()):
            out.append(Finding(f["rule"], p, int(f["line"]), int(f["col"]),
                               f["message"], f.get("hint", "")))
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def store(path: str, sources: dict, rules, findings) -> None:
    """Write the scan result atomically (tmp + rename — a killed writer
    leaves the previous cache intact)."""
    hashes = {p: file_sha(src) for p, src in sources.items()}
    files = {p: {"sha": hashes[p], "findings": []} for p in sources}
    for f in findings:
        if f.path in files:
            files[f.path]["findings"].append(
                {k: getattr(f, k) for k in _FIELDS})
    doc = {"version": CACHE_VERSION,
           "rules_sig": rules_signature(rules),
           "tree_sig": tree_signature(hashes),
           "files": files}
    d = os.path.dirname(os.path.abspath(path)) or "."
    try:
        fd, tmp = tempfile.mkstemp(prefix=".slulint-cache.", dir=d)
        with os.fdopen(fd, "w", encoding="utf-8") as fh:
            json.dump(doc, fh)
        os.replace(tmp, path)
    except OSError:
        pass      # caching is best-effort; the scan result stands
