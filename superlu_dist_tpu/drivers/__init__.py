from superlu_dist_tpu.drivers.gssvx import gssvx, LUFactorization
