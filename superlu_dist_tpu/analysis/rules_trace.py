"""SLU102 trace-purity, SLU105 jit-cache-key hygiene, SLU107 jit-key
shape diversity.

SLU102 — host coercions inside jitted code.  ``float()``/``int()``/
``bool()``/``.item()``/``np.asarray`` on a traced value force a device
sync (or a ConcretizationError), and ``os.environ`` reads inside a
traced function bake a silent recompile axis into the program.  Flagged
lexically inside functions that are ``@jit``-decorated or wrapped by a
``jax.jit(fn)`` call in the same module, restricted to the hot
subpackages (numeric/, solve/, ops/) inside the project tree.

SLU105 — env-dependent jitted factories behind ``lru_cache``.  The
project caches kernel builders with ``functools.lru_cache`` keyed on the
factory arguments (ops/dense.py, solve/device.py, utils/jaxcache.py's
persistent-cache tier below them).  Anything else the built kernel
depends on — an ``os.environ`` read, a closure variable from an
enclosing function — is baked into the compiled program but absent from
the cache key, so two configurations silently share one kernel
(ops/dense.pivot_kernel documents exactly this contract: executors must
put the env choice IN their key).  Flagged: env reads inside an
lru_cached jit factory, loads of enclosing-function locals that are not
factory parameters, and — since v2, through the package call graph —
calls to helpers that *transitively* read env (the factory's traced
body calling ``pivot_kernel()`` three frames down is the same bug as
reading the env inline).  One idiom is exempt: a zero-argument
lru_cached env reader (``ops/dense._precision``) is a read-once latched
process constant, so baking it in without a key is sound
(analysis/dataflow.py's ``latched_env``).

SLU107 — raw (unbucketed) dimensions in jit-factory cache keys.  An
``lru_cache``d jit factory compiles one program per distinct key, so a
key axis fed a RAW size — ``len(x)``, ``x.shape[0]``, ``x.size`` —
makes the compiled-program count grow with the data.  This is exactly
the axis that produced the BENCH_r02 compile wall (119 kernels for 455
groups at n=110592, dead in `factor-compile` before one factor FLOP):
every distinct batch/index length minted a fresh kernel.  The fix is
the canonical bucket ladder (``numeric/plan.bucket_rung`` /
``stream._bucket_len``): round the size onto a rung BEFORE it enters
the key, so shapes repeat and the program set is bounded.  Flagged: a
call to an lru_cached jit factory (defined in the same module) whose
argument contains ``len()``/``.shape``/``.size`` with no bucketing
call (a name containing "bucket"/"rung"/"ladder") anywhere in the same
argument expression.  Lexical and false-negative-leaning like every
slulint rule; new intentional violations join the committed baseline
(the SLU105 policy).
"""

from __future__ import annotations

import ast

from superlu_dist_tpu.analysis.core import Rule, dotted_name, is_env_read

_COERCIONS = frozenset({"float", "int", "bool"})
_NUMPY_NAMES = frozenset({"np", "numpy", "onp"})


def _is_jit_expr(node: ast.AST) -> bool:
    """`jit` / `jax.jit` / `partial(jax.jit, ...)` as a decorator or
    callee."""
    if isinstance(node, ast.Call):
        fn = node.func
        if dotted_name(fn) in ("jit", "jax.jit"):
            return True
        if dotted_name(fn) in ("partial", "functools.partial") and node.args:
            return dotted_name(node.args[0]) in ("jit", "jax.jit")
        return False
    return dotted_name(node) in ("jit", "jax.jit")


def _jit_wrapped_names(tree: ast.AST) -> set:
    """Names of local functions passed to jax.jit(fn, ...) anywhere in
    the module (the `return jax.jit(step)` factory idiom)."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_jit_expr(node) \
                and isinstance(node, ast.Call) and node.args:
            if isinstance(node.args[0], ast.Name):
                names.add(node.args[0].id)
    return names


def _walk_own_body(fn: ast.AST, include_nested_defs: bool = True):
    """Walk a function body; nested defs/lambdas are included by default
    (they are traced as part of the jitted program when defined inside
    it)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not include_nested_defs and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


class TracePurityRule(Rule):
    rule_id = "SLU102"
    title = "trace-purity"
    hint = ("keep host coercions and env reads OUT of traced code: "
            "resolve configuration before tracing and close over the "
            "value, and return jax arrays instead of coercing — "
            "coercions force a device sync (or ConcretizationError) on "
            "every call")
    package_dirs = ("numeric", "solve", "ops")

    def check(self, tree, source, path, project=None):
        findings = []
        wrapped = _jit_wrapped_names(tree)
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            jitted = any(_is_jit_expr(d) for d in node.decorator_list) \
                or node.name in wrapped
            if not jitted:
                continue
            findings.extend(self._scan_jitted(node, path))
        return findings

    def _scan_jitted(self, fn, path):
        out = []
        for node in _walk_own_body(fn):
            env = is_env_read(node)
            if env is not None:
                out.append(self.finding(
                    path, env[1],
                    f"os.environ read inside jitted `{fn.name}` — the "
                    "value is baked in at trace time and changes silently "
                    "recompile (or worse, don't)"))
                continue
            if isinstance(node, ast.Call):
                name = dotted_name(node.func)
                if name in _COERCIONS:
                    out.append(self.finding(
                        path, node,
                        f"`{name}()` coercion inside jitted `{fn.name}` — "
                        "host sync / ConcretizationError on traced values"))
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "item":
                    out.append(self.finding(
                        path, node,
                        f"`.item()` inside jitted `{fn.name}` — forces a "
                        "blocking device-to-host transfer"))
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr in ("asarray", "array") \
                        and isinstance(node.func.value, ast.Name) \
                        and node.func.value.id in _NUMPY_NAMES:
                    out.append(self.finding(
                        path, node,
                        f"`{dotted_name(node.func)}` inside jitted "
                        f"`{fn.name}` — materializes the traced value on "
                        "the host (use jnp)"))
        return out


def _is_lru_decorator(node: ast.AST) -> bool:
    if isinstance(node, ast.Call):
        node = node.func
    return dotted_name(node) in ("lru_cache", "functools.lru_cache",
                                 "cache", "functools.cache")


def _bound_names(fn) -> set:
    """Approximate set of names bound in a function's own scope."""
    bound = set()
    a = fn.args
    for arg in (a.posonlyargs + a.args + a.kwonlyargs
                + ([a.vararg] if a.vararg else [])
                + ([a.kwarg] if a.kwarg else [])):
        bound.add(arg.arg)
    for node in _walk_own_body(fn, include_nested_defs=False):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            bound.add(node.name)
        elif isinstance(node, ast.Name) and isinstance(
                node.ctx, (ast.Store, ast.Del)):
            bound.add(node.id)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                bound.add((alias.asname or alias.name).split(".")[0])
        elif isinstance(node, ast.comprehension):
            for sub in ast.walk(node.target):
                if isinstance(sub, ast.Name):
                    bound.add(sub.id)
    return bound


class JitCacheKeyRule(Rule):
    rule_id = "SLU105"
    title = "jit-cache-key-hygiene"
    hint = ("everything a cached jitted factory bakes into the program "
            "must be a factory PARAMETER (part of the lru_cache key): "
            "resolve env/config in an uncached wrapper and pass it in, "
            "the way ops/dense.make_front_kernel passes pivot_kernel()")

    def __init__(self, interprocedural: bool = True):
        self.interprocedural = interprocedural

    def check(self, tree, source, path, project=None):
        findings = []
        proj = project if self.interprocedural else None
        self._scan(tree.body, [], path, findings, proj)
        return findings

    def _scan(self, stmts, enclosing, path, findings, project):
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(_is_lru_decorator(d) for d in st.decorator_list) \
                        and self._contains_jit(st):
                    self._check_factory(st, enclosing, path, findings,
                                        project)
                self._scan(st.body, enclosing + [st], path, findings,
                           project)
            elif isinstance(st, ast.ClassDef):
                self._scan(st.body, enclosing, path, findings, project)
            elif isinstance(st, (ast.If, ast.For, ast.AsyncFor, ast.While)):
                self._scan(st.body, enclosing, path, findings, project)
                self._scan(st.orelse, enclosing, path, findings, project)
            elif isinstance(st, (ast.With, ast.AsyncWith)):
                self._scan(st.body, enclosing, path, findings, project)
            elif isinstance(st, ast.Try):
                for block in ([st.body, st.orelse, st.finalbody]
                              + [h.body for h in st.handlers]):
                    self._scan(block, enclosing, path, findings, project)

    @staticmethod
    def _contains_jit(fn) -> bool:
        for node in _walk_own_body(fn):
            if isinstance(node, ast.Call) and _is_jit_expr(node):
                return True
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and any(_is_jit_expr(d) for d in node.decorator_list):
                return True
        return False

    def _check_factory(self, fn, enclosing, path, findings, project):
        for node in _walk_own_body(fn):
            env = is_env_read(node)
            if env is not None:
                findings.append(self.finding(
                    path, env[1],
                    f"env read inside lru_cached jit factory `{fn.name}` "
                    "— the value selects the compiled program but is not "
                    "part of the cache key"))
                continue
            # v2: transitive env reads through the call graph (the traced
            # body calling a helper that reads env frames below), minus
            # the latched-constant exemption
            if project is not None and isinstance(node, ast.Call):
                target = project.call_target(path, node)
                s = project.summaries.get(target) if target else None
                if s is not None and s.reaches_env is not None:
                    owner, witness = s.reaches_env
                    findings.append(self.finding(
                        path, node,
                        f"lru_cached jit factory `{fn.name}` calls "
                        f"`{target.rsplit('.', 2)[-1]}` which reaches an "
                        f"env read ({witness} via `{owner}`) — the value "
                        "selects the compiled program but is not part of "
                        "the cache key"))
        if not enclosing:
            return
        outer_bound = set()
        for outer in enclosing:
            outer_bound |= _bound_names(outer)
        own = _bound_names(fn)
        flagged = set()
        for node in _walk_own_body(fn):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load)\
                    and node.id in outer_bound and node.id not in own \
                    and node.id not in flagged:
                flagged.add(node.id)
                findings.append(self.finding(
                    path, node,
                    f"lru_cached jit factory `{fn.name}` closes over "
                    f"`{node.id}` from an enclosing function — it shapes "
                    "the compiled kernel but is missing from the cache "
                    "key"))


_BUCKETIZER_HINTS = ("bucket", "rung", "ladder")


def _is_bucketized(node: ast.AST) -> bool:
    """The expression routes through a bucketing helper somewhere
    (bucket_rung / _bucket_len / nrhs_buckets / ladder_rungs ...)."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            name = dotted_name(sub.func).rsplit(".", 1)[-1].lower()
            if any(h in name for h in _BUCKETIZER_HINTS):
                return True
    return False


def _raw_dim(node: ast.AST):
    """First raw-dimension read inside the expression: a len() call, a
    .shape access, or a .size access.  Returns (label, anchor) or
    None."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and dotted_name(sub.func) == "len":
            return "len(...)", sub
        if isinstance(sub, ast.Attribute) and sub.attr in ("shape", "size") \
                and isinstance(sub.ctx, ast.Load):
            return f".{sub.attr}", sub
    return None


class JitKeyShapeDiversityRule(Rule):
    rule_id = "SLU107"
    title = "jit-key-shape-diversity"
    hint = ("round raw sizes onto the canonical bucket ladder before "
            "they enter a jit-factory cache key (numeric/plan.bucket_rung"
            " / stream._bucket_len): a key axis fed len(x)/x.shape mints "
            "one compiled program per distinct value — the compile-count-"
            "grows-with-n axis that killed BENCH_r02")

    def check(self, tree, source, path, project=None):
        factories = set()
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and any(_is_lru_decorator(d)
                            for d in node.decorator_list) \
                    and JitCacheKeyRule._contains_jit(node):
                factories.add(node.name)
        if not factories:
            return []
        findings = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fname = dotted_name(node.func).rsplit(".", 1)[-1]
            if fname not in factories:
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if _is_bucketized(arg):
                    continue
                raw = _raw_dim(arg)
                if raw is not None:
                    findings.append(self.finding(
                        path, raw[1],
                        f"lru_cached jit factory `{fname}` called with a "
                        f"raw (unbucketed) dimension `{raw[0]}` — every "
                        "distinct size compiles a fresh program, so the "
                        "kernel count grows with the data instead of "
                        "staying a closed bucket set"))
        return findings
