#!/usr/bin/env python
"""SLO CI gate: serve-path p99 latency vs the bench-history baseline.

Serves ``SLO_GATE_REQUESTS`` single-ticket solves per nrhs size (from
``SLO_GATE_NRHS``, default "1,8") through a real ``SolveServer`` in a
fresh subprocess, reads the p99 off the always-on latency accounter
(obs/slo.py — the same streaming histogram the serving fleet exports),
and compares each size against the MEDIAN of prior same-configuration
rows in the bench-history DB (scripts/bench_history.py).  The
check_perf_regress.py discipline, inverted for latency (LOWER is
better):

* SELF-SEEDING — with fewer than ``SLO_GATE_MIN_SAMPLES`` comparable
  rows for a size, its fresh row is appended and the gate passes, so
  the first run on a new machine is green and later runs have a
  baseline;
* the failure threshold is ``p99 > (1 + SLO_GATE_TOL) * median``
  (default tol 1.0 — CI schedulers are noisy; a serve-path regression
  worth failing on is a multiple, not a percentage);
* a failing row is still appended, flagged ``gate_fail``, so it never
  poisons the baseline median.

Usage:  check_slo.py [--row FILE] [--history PATH]
  --row      compare an existing measurement JSON (``{"1": p99_ms,...}``
             on the last line; FILE may be '-') instead of serving
  --history  override the DB path (default: SLU_TPU_BENCH_HISTORY or
             .cache/bench_history.jsonl)

Gate contract (scripts/ci_gates.sh): exit 0 = pass/seeded, exit 1 =
regression or no measurement, diagnostics on stdout/stderr.
"""

import json
import os
import statistics
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from superlu_dist_tpu.utils.options import (          # noqa: E402
    env_float, env_int, env_str)
from bench_history import (                           # noqa: E402
    append_row, history_path, load_history, row_key)

#: history rows consulted for the baseline (most recent first)
BASELINE_WINDOW = 8

# the child: factor a small poisson2d, serve REQUESTS single-ticket
# submits per nrhs size through a SolveServer, report the accounter's
# p99 per size as one JSON line
CHILD = r"""
import json, os
import numpy as np
import superlu_dist_tpu as slu
from superlu_dist_tpu.models.gallery import poisson2d
from superlu_dist_tpu.obs import slo
from superlu_dist_tpu.serve.server import SolveServer

sizes = [int(s) for s in os.environ["_SLO_GATE_NRHS"].split(",")]
n_req = int(os.environ["_SLO_GATE_REQUESTS"])
a = poisson2d(10)
n = a.n_rows
_, lu, _, info = slu.gssvx(slu.Options(), a, np.ones(n))
assert info == 0, info
rng = np.random.default_rng(0)
acct = slo.get_accounter()
out = {}
with SolveServer(lu, max_wait_s=0.0) as srv:
    for k in sizes:
        b = rng.standard_normal((n, k))
        b = b[:, 0] if k == 1 else b
        srv.submit(b)           # warm (compile) ticket
        srv.flush()
        # window the p99 on the histogram DELTA around the measured
        # loop: the warm ticket's compile-dominated latency lands in
        # the always-on accounter too, and must not be the p99
        skey = "serve|%d" % slo.nrhs_bucket(k)
        pre = acct.snapshot().get(skey)
        for _ in range(n_req):
            t = srv.submit(b)
            srv.flush()
            x = np.asarray(t.result(60.0))
            assert np.isfinite(x).all()
        post = acct.snapshot()[skey]
        if pre is None:
            win = [post["count"], 0.0, post["buckets"]]
        else:
            win = [post["count"] - pre["count"], 0.0,
                   [c - p for c, p in zip(post["buckets"],
                                          pre["buckets"])]]
        out[str(k)] = slo.LatencyAccounter._quantile_from(win, 0.99)
print(json.dumps(out))
"""


def fail(msg: str) -> None:
    print(f"FAIL: {msg}", file=sys.stderr)
    raise SystemExit(1)


def run_serve_child(sizes: str, n_req: int) -> dict:
    """One serve run pinned to the CPU backend with telemetry knobs
    cleared (the gate measures the DISABLED-path latency the fleet
    ships with by default)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               _SLO_GATE_NRHS=sizes, _SLO_GATE_REQUESTS=str(n_req))
    for k in ("SLU_TPU_TRACE", "SLU_TPU_METRICS", "SLU_TPU_FLIGHTREC",
              "SLU_TPU_SLO_P99_MS", "SLU_TPU_SLO_TARGETS"):
        env.pop(k, None)
    r = subprocess.run([sys.executable, "-c", CHILD], env=env, cwd=REPO,
                       stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    if r.returncode != 0:
        sys.stderr.write(r.stderr.decode())
        fail(f"serve child failed (rc={r.returncode})")
    lines = [ln for ln in r.stdout.decode().strip().splitlines()
             if ln.strip()]
    if not lines:
        fail("serve child produced no measurement line")
    return json.loads(lines[-1])


def main(argv) -> int:
    row_file = None
    hist_path = None
    it = iter(argv)
    for a in it:
        if a == "--row":
            row_file = next(it, None)
        elif a == "--history":
            hist_path = next(it, None)
        else:
            print(__doc__, file=sys.stderr)
            return 2
    hist_path = hist_path or history_path()
    tol = env_float("SLO_GATE_TOL")
    min_samples = env_int("SLO_GATE_MIN_SAMPLES")
    sizes = env_str("SLO_GATE_NRHS").strip()

    if row_file:
        text = (sys.stdin.read() if row_file == "-"
                else open(row_file).read())
        lines = [ln for ln in text.strip().splitlines() if ln.strip()]
        measured = json.loads(lines[-1])
    else:
        measured = run_serve_child(sizes, env_int("SLO_GATE_REQUESTS"))

    history = load_history(hist_path)
    bad = []
    for k, p99 in sorted(measured.items(), key=lambda kv: int(kv[0])):
        if p99 is None:
            fail(f"nrhs={k}: no p99 measurement (accounter empty)")
        row = {"metric": f"serve_p99_ms_nrhs{k}", "backend": "cpu",
               "value": round(float(p99), 4)}
        key = row_key(row)
        prior = [h for h in history
                 if h.get("history_key", row_key(h)) == key
                 and h.get("value") is not None
                 and not h.get("gate_fail")]
        if len(prior) < min_samples:
            append_row(row, hist_path)
            print(f"slo gate: SEEDED nrhs={k} ({len(prior)} -> "
                  f"{len(prior) + 1} rows; enforcement starts at "
                  f"{min_samples}) — p99 {p99:.3f} ms")
            continue
        window = prior[-BASELINE_WINDOW:]
        base = statistics.median(float(h["value"]) for h in window)
        ceiling = (1.0 + tol) * base
        ok = float(p99) <= ceiling
        append_row(row, hist_path, gate_fail=not ok)
        verdict = "OK" if ok else "REGRESSION"
        print(f"slo gate: {verdict} nrhs={k} p99 {p99:.3f} ms vs median "
              f"{base:.3f} over {len(window)} rows (ceiling "
              f"{ceiling:.3f}, tol {tol:.0%})")
        if not ok:
            bad.append(k)
    if bad:
        print(f"FAIL: serve p99 latency regressed past the noise "
              f"ceiling for nrhs {', '.join(bad)}; inspect "
              f"'{sys.executable} scripts/bench_history.py list "
              "serve_p99' and recent serve-path changes",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
