"""SLU121 true-positive fixture (executable): a program whose
intermediates all stay live to the last equation — the high-water mark
is ~5x one buffer, the padded-rung-pool pattern the static peak-memory
model exists to price.  ``build()`` returns ``(jitted_fn, args)`` with
f32[256,256] buffers (256 KiB each)."""
import jax
import jax.numpy as jnp


def build():
    def widen(x):
        a = x * 2.0
        b = x * 3.0
        c = x * 4.0
        # a, b, c and x are ALL live here: nothing frees before the end
        return a + b + c + x, a, b, c

    args = (jnp.zeros((256, 256), jnp.float32),)
    return jax.jit(widen), args
