"""Phase timing / flop statistics.

Analog of ``SuperLUStat_t`` (SRC/util_dist.h:83-96) with the per-phase
``utime[]``/``ops[]`` arrays over the PhaseType enum
(SRC/superlu_enum_consts.h:65-89), and of ``PStatPrint`` (SRC/util.c:484-534)
which reports phase seconds plus factor/solve Mflops — the baseline metric
source (BASELINE.md).
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field

#: Phases, mirroring the reference's PhaseType (superlu_enum_consts.h:65-89).
PHASES = (
    "EQUIL", "ROWPERM", "COLPERM", "ETREE", "SYMBFACT", "DIST",
    "FACT", "SOLVE", "REFINE",
)


@dataclass
class Stats:
    utime: dict = field(default_factory=lambda: {p: 0.0 for p in PHASES})
    ops: dict = field(default_factory=lambda: {p: 0.0 for p in PHASES})
    tiny_pivots: int = 0          # reference: stat->TinyPivots (pdgstrf2.c:226)
    refine_steps: int = 0         # reference: stat->RefineSteps
    peak_memory_bytes: int = 0
    current_memory_bytes: int = 0
    for_lu_bytes: int = 0         # dQuerySpace_dist analog: packed L+U
    pool_bytes: int = 0           # transient Schur update pool

    @contextlib.contextmanager
    def timer(self, phase: str):
        """TIC/TOC analog (util_dist.h:135-141)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.utime[phase] = self.utime.get(phase, 0.0) + time.perf_counter() - t0

    def log_memory(self, nbytes: int):
        """Analog of log_memory (SRC/util.c:914): delta-accounting (allocs
        positive, frees negative) with a running peak."""
        self.current_memory_bytes += nbytes
        self.peak_memory_bytes = max(self.peak_memory_bytes, self.current_memory_bytes)

    def observe_memory(self, nbytes: int):
        """Replace the current gauge (the new allocation supersedes the
        previous factorization's) — keeps peak correct when one Stats is
        reused across refactorizations (the SamePattern time-stepping
        pattern)."""
        self.current_memory_bytes = nbytes
        self.peak_memory_bytes = max(self.peak_memory_bytes, nbytes)

    def gflops(self, phase: str) -> float:
        t = self.utime.get(phase, 0.0)
        return (self.ops.get(phase, 0.0) / t / 1e9) if t > 0 else 0.0

    def report(self) -> str:
        """PStatPrint analog (SRC/util.c:484-534): phase times + Mflops."""
        lines = ["**************************************************",
                 "**** Time (seconds) ****"]
        for p in PHASES:
            if self.utime.get(p, 0.0) > 0 or self.ops.get(p, 0.0) > 0:
                lines.append(f"    {p:<10s} time {self.utime.get(p, 0.0):10.4f}")
        for p in ("FACT", "SOLVE"):
            if self.ops.get(p, 0.0) > 0:
                lines.append(
                    f"    {p} flops {self.ops[p]:.6e}\tMflops {self.gflops(p) * 1e3:10.2f}")
        if self.tiny_pivots:
            lines.append(f"    tiny pivots replaced: {self.tiny_pivots}")
        if self.refine_steps:
            lines.append(f"    refinement steps: {self.refine_steps}")
        if self.for_lu_bytes:
            # dQuerySpace_dist-style report (SRC/dmemory_dist.c:73)
            lines.append(f"    L\\U storage {self.for_lu_bytes / 1e6:10.2f} MB"
                         f"\tupdate pool {self.pool_bytes / 1e6:10.2f} MB")
        if self.peak_memory_bytes:
            lines.append(
                f"    peak device memory {self.peak_memory_bytes / 1e6:10.2f} MB")
        lines.append("**************************************************")
        return "\n".join(lines)

    def print(self):
        print(self.report())
