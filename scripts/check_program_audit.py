#!/usr/bin/env python
"""Program-audit gate (slulint v4 runtime twin): every jitted program
the REAL executors build must pass the SLU111/SLU112/SLU114 IR rules.

Runs a small gallery matrix set through all three factor executors
(fused / stream / mega) and the device solve path (fused and streamed
sweeps, plain and transpose) with ``SLU_TPU_VERIFY_PROGRAMS=1`` — so
every program is traced at construction/AOT-stage time and walked for
un-donated dead buffers (SLU111), baked per-matrix constants (SLU112)
and divergent/off-mesh collective sequences (SLU114).  ANY finding
raises ProgramAuditError, which exits non-zero with the diagnostic.

Also asserts the audit actually RAN (a silently-off knob must not pass
the gate) and that donation coverage is 100% with zero baked-const
bytes — the acceptance criterion of the v4 issue: the compiled tier
stays warm-startable and peak-memory-honest by construction.

Exit 0 = pass.  One gate of scripts/ci_gates.sh (shared contract:
diagnostics on stdout/stderr, non-zero on any regression, hard
timeout).
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["SLU_TPU_VERIFY_PROGRAMS"] = "1"

import numpy as np  # noqa: E402


def _analyzed(a):
    from superlu_dist_tpu.ordering.dispatch import get_perm_c
    from superlu_dist_tpu.sparse.formats import symmetrize_pattern
    from superlu_dist_tpu.symbolic.symbfact import symbolic_factorize
    from superlu_dist_tpu.utils.options import Options

    sym = symmetrize_pattern(a)
    col_order = get_perm_c(Options(), a, sym)
    sf = symbolic_factorize(sym, col_order)
    return sf, sym.data[sf.value_perm], a.norm_max()


def check(name, a) -> int:
    from superlu_dist_tpu.numeric.factor import numeric_factorize
    from superlu_dist_tpu.numeric.plan import build_plan
    from superlu_dist_tpu.solve.device import DeviceSolver

    sf, vals, anorm = _analyzed(a)
    plan = build_plan(sf)
    rng = np.random.default_rng(7)
    rhs = rng.standard_normal((plan.n, 5))
    n_programs = 0
    for ex in ("fused", "stream", "mega"):
        fact = numeric_factorize(plan, vals, anorm, executor=ex)
        if ex == "stream":
            for fused in (True, False):
                ds = DeviceSolver(fact, fused=fused)
                ds.solve(rhs)
                ds.solve_trans(rhs)
    from superlu_dist_tpu.utils import programaudit
    aud = programaudit._AUDITOR
    assert aud is not None, "SLU_TPU_VERIFY_PROGRAMS=1 allocated no auditor"
    n_programs = len(aud.audited)
    print(f"[program-audit] {name}: {n_programs} program(s) audited clean")
    return n_programs


def main():
    import jax
    jax.config.update("jax_enable_x64", True)
    from superlu_dist_tpu.models.gallery import hilbert, poisson2d

    total = 0
    total = max(total, check("poisson2d nx=12", poisson2d(12)))
    total = max(total, check("hilbert n=48", hilbert(48)))

    from superlu_dist_tpu.obs.compilestats import COMPILE_STATS
    blk = COMPILE_STATS.audit_block()
    assert blk["programs"] == total and total > 0, \
        f"census audit block disagrees: {blk} vs {total} audited"
    assert blk["findings"] == 0, f"findings leaked past submit: {blk}"
    assert blk["donation_coverage_pct"] == 100.0, \
        f"declared-dead bytes not fully donated: {blk}"
    assert blk["baked_const_bytes"] == 0, \
        f"programs bake constants: {blk}"
    print(f"[program-audit] OK: {blk['programs']} programs, "
          f"donation coverage {blk['donation_coverage_pct']}%, "
          f"baked const bytes {blk['baked_const_bytes']}")


if __name__ == "__main__":
    main()
