"""Test-support harnesses shipped with the package (the TEST/pdtest.c
analog tier): deterministic failure-domain chaos injection lives in
:mod:`superlu_dist_tpu.testing.chaos`."""
