import numpy as np
import jax.numpy as jnp
import pytest

from superlu_dist_tpu.ops.dense import lu_nopivot, make_front_kernel


def np_lu_nopiv(a):
    a = a.copy()
    n = a.shape[0]
    for i in range(n):
        a[i + 1:, i] /= a[i, i]
        a[i + 1:, i + 1:] -= np.outer(a[i + 1:, i], a[i, i + 1:])
    return a


@pytest.mark.parametrize("n", [1, 3, 16, 17, 40, 96])
@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_lu_nopivot_matches_numpy(n, dtype):
    rng = np.random.default_rng(n)
    a = rng.standard_normal((n, n)).astype(dtype)
    if np.issubdtype(dtype, np.complexfloating):
        a = a + 1j * rng.standard_normal((n, n))
    a += np.eye(n) * (2 * n)      # diagonally dominant: no tiny pivots
    got, count = lu_nopivot(jnp.asarray(a), jnp.asarray(1e-300))
    want = np_lu_nopiv(a.copy())
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-10, atol=1e-10)
    assert count.shape == (n,) and int(count.sum()) == 0


def test_tiny_pivot_replacement():
    a = np.array([[1.0, 1.0], [1.0, 1.0]])   # second pivot exactly 0
    out, count = lu_nopivot(jnp.asarray(a), jnp.asarray(1e-8))
    # per-column flags localize the tiny pivot to column 1
    assert list(np.asarray(count)) == [0, 1]
    assert abs(np.asarray(out)[1, 1]) == pytest.approx(1e-8)


@pytest.mark.parametrize("m,w,u_real,w_real", [(24, 8, 16, 8), (32, 16, 10, 13)])
def test_partial_front_factor(m, w, u_real, w_real):
    rng = np.random.default_rng(0)
    B = 3
    fronts = np.zeros((B, m, m))
    for b in range(B):
        f = np.zeros((m, m))
        # real data: pivot block w_real, rows u_real; identity padding in
        # pivot cols [w_real, w)
        blk = rng.standard_normal((w_real + u_real, w_real + u_real))
        blk += np.eye(w_real + u_real) * 2 * (w_real + u_real)
        f[:w_real, :w_real] = blk[:w_real, :w_real]
        f[w:w + u_real, :w_real] = blk[w_real:, :w_real]
        f[:w_real, w:w + u_real] = blk[:w_real, w_real:]
        f[w:w + u_real, w:w + u_real] = blk[w_real:, w_real:]
        for k in range(w_real, w):
            f[k, k] = 1.0
        fronts[b] = f
    kern = make_front_kernel(m, w, "float64")
    out, tiny = kern(jnp.asarray(fronts), jnp.asarray(1e-300))
    out = np.asarray(out)
    assert int(tiny) == 0
    for b in range(B):
        f = fronts[b]
        # reconstruct: dense partial LU on the real (w_real+u_real) block
        blk = np.zeros((w_real + u_real, w_real + u_real))
        blk[:w_real, :w_real] = f[:w_real, :w_real]
        blk[w_real:, :w_real] = f[w:w + u_real, :w_real]
        blk[:w_real, w_real:] = f[:w_real, w:w + u_real]
        blk[w_real:, w_real:] = f[w:w + u_real, w:w + u_real]
        ref = blk.copy()
        for i in range(w_real):
            ref[i + 1:, i] /= ref[i, i]
            ref[i + 1:, i + 1:] -= np.outer(ref[i + 1:, i], ref[i, i + 1:])
        np.testing.assert_allclose(out[b][:w_real, :w_real], ref[:w_real, :w_real],
                                   rtol=1e-10, atol=1e-10)
        np.testing.assert_allclose(out[b][w:w + u_real, :w_real], ref[w_real:, :w_real],
                                   rtol=1e-10, atol=1e-10)
        np.testing.assert_allclose(out[b][:w_real, w:w + u_real], ref[:w_real, w_real:],
                                   rtol=1e-10, atol=1e-10)
        np.testing.assert_allclose(out[b][w:w + u_real, w:w + u_real],
                                   ref[w_real:, w_real:], rtol=1e-10, atol=1e-10)


@pytest.mark.parametrize("m,w", [(40, 16), (130, 120), (300, 144),
                                 (64, 31), (200, 137), (56, 56), (24, 9)])
@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.slow
def test_blocked_matches_recursive(m, w, dtype):
    """The compile-bounded blocked kernel (the unsharded default,
    _blocked_partial_factor) must agree with the recursive path on every
    output — packed LU, L21, U12, Schur, and tiny-pivot flags — including
    w not a multiple of the 128 panel block and identity-padded columns."""
    import os
    from superlu_dist_tpu.ops.dense import group_partial_factor
    rng = np.random.default_rng(m + w)
    f = rng.standard_normal((2, m, m)) + m * np.eye(m)
    if np.issubdtype(dtype, np.complexfloating):
        f = f + 1j * rng.standard_normal((2, m, m))
    f = f.astype(dtype)
    # identity-pad the last 5 pivot columns of slot 1 (ws < w case)
    f[1, :, w - 5:w] = 0
    f[1, w - 5:w, :] = 0
    for k in range(w - 5, w):
        f[1, k, k] = 1.0
    thresh = jnp.asarray(1e-300)
    old = os.environ.get("SLU_TPU_PIVOT_KERNEL")
    try:
        os.environ["SLU_TPU_PIVOT_KERNEL"] = "blocked"
        got = group_partial_factor(jnp.asarray(f), thresh, w)
        os.environ["SLU_TPU_PIVOT_KERNEL"] = "recursive"
        ref = group_partial_factor(jnp.asarray(f), thresh, w)
    finally:
        if old is None:
            os.environ.pop("SLU_TPU_PIVOT_KERNEL", None)
        else:
            os.environ["SLU_TPU_PIVOT_KERNEL"] = old
    for g, r in zip(got[:3], ref[:3]):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=1e-9, atol=1e-9)
    np.testing.assert_array_equal(np.asarray(got[3]), np.asarray(ref[3]))
