"""Solver health & recovery subsystem.

The error model (info codes / SingularMatrixError / NumericBreakdownError),
the Hager–Higham condition estimate and FERR bounds (refine/condest.py),
the SolveReport, and the automatic escalation ladder (drivers/gssvx.py) —
the GESP detect-and-repair loop the reference builds from pdgscon +
pdgsrfs + ReplaceTinyPivot accounting (PAPER.md L4/L8).
"""

import numpy as np
import pytest

from superlu_dist_tpu.drivers.gssvx import analyze, factorize_numeric, gssvx
from superlu_dist_tpu.models.gallery import (
    hilbert, poisson2d, rank_deficient_arrowhead, zero_row_col)
from superlu_dist_tpu.refine.condest import onenormest
from superlu_dist_tpu.refine.ir import (
    componentwise_berr, iterative_refinement)
from superlu_dist_tpu.utils.errors import (
    NumericBreakdownError, SingularMatrixError)
from superlu_dist_tpu.utils.options import (
    ColPerm, IterRefine, Options, RecoveryPolicy, RowPerm)
from superlu_dist_tpu.utils.stats import SolveReport


# ---------------------------------------------------------------------------
# error model: info conventions and propagation
# ---------------------------------------------------------------------------

def test_singular_matrix_error_info_is_one_based():
    err = SingularMatrixError(5)       # 0-based first zero-pivot column
    assert err.info == 6               # reference: 1-based info > 0
    assert "U(5,5)" in str(err)


def test_replace_tiny_pivot_false_propagates_info():
    """Exactly-singular A + ReplaceTinyPivot=NO: the driver returns
    info > 0 and no solution (pdgstrf.c:234-241), and a later solve on
    the poisoned handle raises with the SAME 1-based info."""
    a = zero_row_col(6, which="row")
    opts = Options(replace_tiny_pivot=False, equil=False,
                   row_perm=RowPerm.NOROWPERM, col_perm=ColPerm.NATURAL,
                   iter_refine=IterRefine.NOREFINE)
    x, lu, stats, info = gssvx(opts, a, np.ones(a.n_rows))
    assert info > 0 and x is None
    assert lu.numeric is not None and not lu.numeric.finite
    with pytest.raises(SingularMatrixError) as exc:
        lu.solve_factored(np.ones(a.n_rows))
    assert exc.value.info == info


def test_zero_column_singular_flagged():
    a = zero_row_col(6, which="col")
    opts = Options(replace_tiny_pivot=False, equil=False,
                   row_perm=RowPerm.NOROWPERM,
                   iter_refine=IterRefine.NOREFINE)
    x, lu, stats, info = gssvx(opts, a, np.ones(a.n_rows))
    assert info > 0 and x is None


# ---------------------------------------------------------------------------
# non-finite sentinels: NumericBreakdownError
# ---------------------------------------------------------------------------

def _nan_poisoned(nx=8):
    a = poisson2d(nx)
    a.data = a.data.copy()
    a.data[len(a.data) // 2] = np.nan
    return a


def test_nan_input_trips_numeric_breakdown():
    """NaN input with ReplaceTinyPivot active must trip the structured
    sentinel at factorization time — naming a supernode — instead of
    propagating NaN through the whole elimination."""
    a = _nan_poisoned()
    opts = Options(equil=False, row_perm=RowPerm.NOROWPERM)
    with pytest.raises(NumericBreakdownError) as exc:
        gssvx(opts, a, np.ones(a.n_rows))
    assert exc.value.supernode >= 0
    assert exc.value.col >= 0
    assert "supernode" in str(exc.value)


def test_sentinels_disabled_flags_instead_of_raising():
    """With sentinels off the NaN propagates (the pre-subsystem
    behavior), but the SolveReport still FLAGS the non-finite result —
    never a silent wrong answer."""
    a = _nan_poisoned()
    opts = Options(equil=False, row_perm=RowPerm.NOROWPERM,
                   iter_refine=IterRefine.NOREFINE,
                   recovery=RecoveryPolicy(enabled=False, sentinels=False,
                                           condest="never"))
    x, lu, stats, info = gssvx(opts, a, np.ones(a.n_rows))
    assert not np.all(np.isfinite(x))
    assert stats.solve_report is not None
    assert stats.solve_report.finite is False


def test_localize_nonfinite_names_earliest_supernode():
    from superlu_dist_tpu.numeric.factor import (
        localize_nonfinite, numeric_factorize)
    a = _nan_poisoned(6)
    opts = Options(equil=False, row_perm=RowPerm.NOROWPERM)
    lu, bvals, stats = analyze(opts, a)
    with pytest.raises(NumericBreakdownError):
        numeric_factorize(lu.plan, bvals, lu.anorm, replace_tiny=True)
    numeric = numeric_factorize(lu.plan, bvals, lu.anorm,
                                replace_tiny=True, check_finite=False)
    sn, col = localize_nonfinite(lu.plan, numeric.fronts)
    assert 0 <= sn and 0 <= col < a.n_rows


# ---------------------------------------------------------------------------
# condition estimation / SolveReport
# ---------------------------------------------------------------------------

def test_onenormest_never_overestimates():
    rng = np.random.default_rng(1)
    for n in (5, 23, 64):
        m = rng.standard_normal((n, n)) * np.exp(
            2 * rng.standard_normal(n))[:, None]
        true = float(np.abs(m).sum(axis=0).max())
        est = onenormest(n, lambda v: m @ v, lambda v: m.T @ v)
        assert est <= true * (1 + 1e-10)
        assert est >= 0.25 * true


def test_rcond_matches_true_condition():
    a = poisson2d(10)
    opts = Options(recovery=RecoveryPolicy(condest="always"))
    x, lu, stats, info = gssvx(opts, a, np.ones(a.n_rows))
    assert info == 0
    rep = stats.solve_report
    assert rep.rcond is not None and 0 < rep.rcond <= 1
    # equilibration is a no-op for this matrix; compare against the true
    # 1-norm condition number (the estimate may only UNDER-estimate the
    # condition, i.e. over-estimate rcond, by a modest factor)
    true_rcond = 1.0 / np.linalg.cond(a.to_dense(), 1)
    assert true_rcond <= rep.rcond <= 4 * true_rcond
    # ferr bounds the true forward error
    assert rep.ferr is not None and all(f < 1e-8 for f in rep.ferr)


def test_report_fields_well_conditioned_defaults():
    a = poisson2d(8)
    xt = np.random.default_rng(0).standard_normal(a.n_rows)
    x, lu, stats, info = gssvx(Options(), a, a.matvec(xt))
    rep = stats.solve_report
    assert isinstance(rep, SolveReport)
    assert rep.converged and rep.finite
    assert rep.berr is not None and rep.berr <= rep.target
    assert rep.rungs == [] and rep.berr_history
    assert rep.factor_dtype in ("float64", "float32")
    assert "berr" in rep.summary()
    assert "solve health" in stats.report()


# ---------------------------------------------------------------------------
# escalation ladder (acceptance criteria)
# ---------------------------------------------------------------------------

NEAR_SINGULAR = dict(n=60, delta=1e-6, seed=0)


def test_escalation_ladder_recovers_near_singular_f32():
    """Acceptance: a gallery near-singular system with f32 factors returns
    finite x with rcond populated, at least one escalation rung recorded,
    and final berr <= 10·eps(f64 working dtype)."""
    a = rank_deficient_arrowhead(**NEAR_SINGULAR)
    xt = np.random.default_rng(1).standard_normal(a.n_rows)
    b = a.matvec(xt)
    x, lu, stats, info = gssvx(Options(factor_dtype="float32"), a, b)
    assert info == 0
    rep = stats.solve_report
    assert np.all(np.isfinite(x))
    assert rep.rcond is not None and rep.rcond > 0
    assert len(rep.rungs) >= 1
    names = [r.name for r in rep.rungs]
    assert "hiprec-factors" in names or "refactor-rescale" in names
    eps = float(np.finfo(np.float64).eps)
    assert rep.berr <= 10 * eps, rep.summary()
    assert rep.converged
    # the adopted rung genuinely improved things
    adopted = [r for r in rep.rungs if r.berr_after < r.berr_before]
    assert adopted


def test_recovery_disabled_flags_stagnation():
    """Same system, recovery disabled: the solver must flag the failure
    (stagnated berr, converged=False) instead of silently returning a
    wrong answer."""
    a = rank_deficient_arrowhead(**NEAR_SINGULAR)
    xt = np.random.default_rng(1).standard_normal(a.n_rows)
    b = a.matvec(xt)
    opts = Options(factor_dtype="float32",
                   recovery=RecoveryPolicy(enabled=False))
    x, lu, stats, info = gssvx(opts, a, b)
    rep = stats.solve_report
    assert rep.rungs == []
    assert not rep.converged
    assert rep.berr > rep.target
    # diagnosis still offered on the auto tier (non-convergence gates it)
    assert rep.rcond is not None


def test_ladder_returns_escalated_handle():
    """The returned lu must be the handle the answer actually rests on:
    after a hiprec-factors rung, subsequent FACTORED-mode solves reuse
    the escalated factors and stay accurate."""
    from superlu_dist_tpu.utils.options import Fact
    a = rank_deficient_arrowhead(**NEAR_SINGULAR)
    rng = np.random.default_rng(2)
    b1 = a.matvec(rng.standard_normal(a.n_rows))
    x1, lu, stats, info = gssvx(Options(factor_dtype="float32"), a, b1)
    assert info == 0 and stats.solve_report.rungs
    assert str(lu.numeric.dtype) == "float64"    # escalated handle
    xt2 = rng.standard_normal(a.n_rows)
    b2 = a.matvec(xt2)
    x2, lu, stats2, info2 = gssvx(Options(fact=Fact.FACTORED), a, b2, lu=lu)
    assert info2 == 0
    assert np.linalg.norm(b2 - a.matvec(x2)) / np.linalg.norm(b2) < 1e-12


def test_hilbert_f32_ladder():
    """Hilbert at n=8 (kappa ~ 1.5e10): past f32+IR, inside f64."""
    a = hilbert(8)
    xt = np.ones(a.n_rows)
    b = a.matvec(xt)
    x, lu, stats, info = gssvx(Options(factor_dtype="float32"), a, b)
    assert info == 0
    rep = stats.solve_report
    assert rep.converged and rep.berr <= rep.target, rep.summary()


def test_residual_precision_rung_slu_single():
    """SLU_SINGLE's f32 residual can't see below single eps.  Against its
    OWN tier target (10·eps32) it converges — no ladder.  Against an
    explicit f64-class berr_target, the ladder's first rung escalates the
    residual to f64 on the SAME factors and reaches it."""
    a = poisson2d(10)
    xt = np.random.default_rng(3).standard_normal(a.n_rows)
    b = a.matvec(xt)
    opts = Options(iter_refine=IterRefine.SLU_SINGLE)
    x, lu, stats, info = gssvx(opts, a, b)
    assert info == 0 and stats.solve_report.converged
    assert stats.solve_report.rungs == []    # its own tier target is met

    opts = Options(iter_refine=IterRefine.SLU_SINGLE,
                   recovery=RecoveryPolicy(berr_target=1e-14))
    x, lu, stats, info = gssvx(opts, a, b)
    assert info == 0
    rep = stats.solve_report
    names = [r.name for r in rep.rungs]
    assert names and names[0] == "residual-precision"
    eps32 = float(np.finfo(np.float32).eps)
    assert rep.berr < eps32      # beyond what the f32 residual could see
    assert rep.berr <= 1e-14 and rep.converged


# ---------------------------------------------------------------------------
# shared BERR guard + IR shape normalization (satellites)
# ---------------------------------------------------------------------------

def test_componentwise_berr_guard_tiny_denominators():
    # an exactly-zero row with zero residual reports 0, not 0/0
    r = np.array([0.0, 1e-3])
    den = np.array([0.0, 1.0])
    assert componentwise_berr(r, den, nnz=10) == pytest.approx(1e-3)
    # a zero denominator with a REAL residual reports huge (the old
    # den>0 -> 1.0 rewrite understated this to 1e-30)
    assert componentwise_berr(np.array([1e-30]), np.array([0.0]),
                              nnz=10) > 1.0
    # the distributed loop shares the one implementation
    from superlu_dist_tpu.parallel import pgsrfs as mod
    assert mod.componentwise_berr is componentwise_berr


def test_ir_active_set_shape_normalization():
    """nrhs=3 with per-column convergence at different iterations and a
    solve_fn that SQUEEZES a single remaining column: the active-set
    bookkeeping must normalize shapes instead of mis-broadcasting."""
    a = poisson2d(6)
    n = a.n_rows
    d = a.to_dense()
    rng = np.random.default_rng(4)
    xt = rng.standard_normal((n, 3))
    b = a.matvec(xt)
    shapes = []

    def solve_fn(r):
        shapes.append(np.shape(r))
        dx = np.linalg.solve(d, r)
        # per-column damping => columns converge at different iterations
        k = dx.shape[1]
        dx = dx * (1.0 - np.array([0.2, 1e-4, 1e-8])[:k][None, :])
        if k == 1:
            return dx[:, 0]          # the squeezing-solver regression
        return dx

    x0 = solve_fn(b) if b.ndim > 1 else None
    x, berrs = iterative_refinement(a, b, np.asarray(x0), solve_fn)
    assert np.allclose(x, xt, atol=1e-10)
    assert berrs[-1] < 1e-14
    # the active set genuinely shrank to a single squeezed column
    assert any(s[1] == 1 for s in shapes if len(s) == 2), shapes


def test_ir_rejects_wrong_correction_shape():
    a = poisson2d(4)
    n = a.n_rows
    b = a.matvec(np.ones(n))

    def bad_solve(r):
        return np.zeros(n + 1)       # contract violation

    with pytest.raises(ValueError, match="correction solve"):
        iterative_refinement(a, b, np.zeros(n), bad_solve)


# ---------------------------------------------------------------------------
# distributed driver health report
# ---------------------------------------------------------------------------

def test_pgssvx_attaches_distributed_solve_report():
    """The distributed driver reports refinement health the same way the
    serial one does: lu_out['solve_report'] / stats.solve_report with the
    allreduced berr history (single-rank tree — the collective logic is
    identical; the multi-rank path is covered by test_treecomm.py)."""
    from superlu_dist_tpu import native
    if not native.available():
        pytest.skip("native library unavailable")
    import os
    from superlu_dist_tpu.parallel.dist import distribute_rows
    from superlu_dist_tpu.parallel.pgssvx import pgssvx
    from superlu_dist_tpu.parallel.treecomm import TreeComm

    a = poisson2d(8)
    n = a.n_rows
    xt = np.random.default_rng(0).standard_normal(n)
    b = a.matvec(xt)
    part = distribute_rows(a, 1)[0]
    name = f"/slu_rec_rep_{os.getpid()}"
    with TreeComm(name, 1, 0, max_len=n, create=True) as tc:
        lu_out = {}
        x, info = pgssvx(tc, Options(factor_dtype="float32"), part, b,
                         lu_out=lu_out)
    assert info == 0
    rep = lu_out["solve_report"]
    assert rep is not None and rep.berr is not None
    assert rep.converged and rep.finite
    assert lu_out["stats"].solve_report is rep
    assert np.linalg.norm(b - a.matvec(x)) / np.linalg.norm(b) < 1e-12


# ---------------------------------------------------------------------------
# gallery generators
# ---------------------------------------------------------------------------

def test_gallery_hilbert_values():
    a = hilbert(5)
    d = a.to_dense()
    assert d[0, 0] == 1.0 and d[2, 3] == pytest.approx(1.0 / 6.0)
    assert np.allclose(d, d.T)


def test_gallery_arrowhead_exact_singular():
    a = rank_deficient_arrowhead(20, delta=0.0)
    d = a.to_dense()
    assert np.linalg.matrix_rank(d) == 19
    np.testing.assert_allclose(d[-1, :-1], (d[1] + d[2])[:-1])


def test_gallery_zero_row_col():
    for which in ("row", "col", "both"):
        a = zero_row_col(5, k=7, which=which)
        d = a.to_dense()
        if which in ("row", "both"):
            assert not d[7].any()
        if which in ("col", "both"):
            assert not d[:, 7].any()
