"""SARIF 2.1.0 serialization for slulint findings.

``--format sarif`` on the CLI (and the ``scripts/run_slulint.sh``
passthrough) emits the Static Analysis Results Interchange Format so
findings annotate PRs in standard tooling (GitHub code scanning, IDE
SARIF viewers) without a custom adapter.  ``from_sarif`` parses the
subset ``to_sarif`` writes — the round-trip contract the test suite
pins (tests/test_program_audit.py).
"""

from __future__ import annotations

from superlu_dist_tpu.analysis.core import Finding

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")


def to_sarif(findings, rules, baselined: int = 0) -> dict:
    """One SARIF run: the slulint driver with its rule catalog, one
    result per finding (file/line/col + message, hint as a related
    message property)."""
    catalog = []
    for r in rules:
        catalog.append({
            "id": r.rule_id,
            "name": (r.title or r.rule_id).replace("-", " ").title()
                    .replace(" ", ""),
            "shortDescription": {"text": r.title or r.rule_id},
            "help": {"text": r.hint or ""},
        })
    results = []
    for f in findings:
        results.append({
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path.replace("\\", "/")},
                    "region": {"startLine": max(int(f.line), 1),
                               "startColumn": max(int(f.col), 1)},
                },
            }],
            "properties": {"hint": f.hint, "line": int(f.line),
                           "col": int(f.col)},
        })
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "slulint",
                "informationUri":
                    "docs/ANALYSIS.md",
                "rules": catalog,
            }},
            "results": results,
            "properties": {"baselined": int(baselined)},
        }],
    }


def from_sarif(doc: dict) -> list:
    """Findings back out of a ``to_sarif`` document (the round-trip
    subset: ruleId, uri, region, message text, hint property)."""
    out = []
    for run in doc.get("runs", ()):
        for res in run.get("results", ()):
            loc = (res.get("locations") or [{}])[0] \
                .get("physicalLocation", {})
            region = loc.get("region", {})
            props = res.get("properties", {})
            out.append(Finding(
                res.get("ruleId", "?"),
                loc.get("artifactLocation", {}).get("uri", "?"),
                int(props.get("line", region.get("startLine", 0))),
                int(props.get("col", region.get("startColumn", 1))),
                res.get("message", {}).get("text", ""),
                props.get("hint", "")))
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out
