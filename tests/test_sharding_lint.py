"""slulint v6 sharding & memory-flow suite (docs/ANALYSIS.md).

Per-rule fixture coverage for the source rules (SLU120 mesh/spec
hygiene against the utils/meshreg.py registry, SLU122 dispatch-loop
cross-mesh transfers over the device-taint lattice), the jaxpr rules
over real traced programs (SLU119 implicit-replication blowup through
a REAL 2-shard shard_map subprocess, SLU121 static peak-memory model
validated against XLA's own memory_analysis), the
``SLU_TPU_VERIFY_SHARDING=1`` / ``SLU_TPU_MEM_BUDGET_BYTES`` runtime
auditor (raise-before-run with flight-recorder postmortem, census
``#sharding`` notes, memoization, off-path no-state), the mega
executor's bucket-rung-naming MemoryBudgetError, and the SARIF
round-trip for the four new catalog entries.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from superlu_dist_tpu.analysis.core import analyze_sources, default_rules
from superlu_dist_tpu.analysis.program import (ProgramSpec, audit_sharding,
                                               trace_spec)
from superlu_dist_tpu.analysis import rules_sharding as rs
from superlu_dist_tpu.utils import meshreg, programaudit
from superlu_dist_tpu.utils.errors import (MemoryBudgetError,
                                           ShardingAuditError)

pytestmark = pytest.mark.shardlint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "slulint")


def _scan(name):
    path = os.path.join("tests", "fixtures", "slulint", name)
    with open(os.path.join(REPO, path)) as f:
        return analyze_sources({path: f.read()})


def _fixture_build(name, *args):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(FIXTURES, f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.build(*args)


@pytest.fixture
def fresh_sharding_auditor(monkeypatch):
    """SLU_TPU_VERIFY_SHARDING=1 with fresh auditors + clean census
    audit notes, restored afterwards."""
    from superlu_dist_tpu.obs.compilestats import COMPILE_STATS
    monkeypatch.delenv("SLU_TPU_VERIFY_PROGRAMS", raising=False)
    monkeypatch.delenv("SLU_TPU_VERIFY_DTYPES", raising=False)
    monkeypatch.delenv("SLU_TPU_MEM_BUDGET_BYTES", raising=False)
    monkeypatch.setenv("SLU_TPU_VERIFY_SHARDING", "1")
    programaudit._reset()
    with COMPILE_STATS._lock:
        saved = dict(COMPILE_STATS._audits)
        COMPILE_STATS._audits = {}
    yield
    programaudit._reset()
    with COMPILE_STATS._lock:
        COMPILE_STATS._audits = saved


# --------------------------------------------------------------------------
# utils/meshreg: the central axis registry
# --------------------------------------------------------------------------

def test_meshreg_declares_the_grid_axes():
    axes = meshreg.registered_axes()
    assert "snode" in axes and "panel" in axes
    assert meshreg.require_axis("snode") == "snode"
    with pytest.raises(meshreg.UnknownAxisError) as ei:
        meshreg.require_axis("rows")
    assert "rows" in str(ei.value) and "meshreg" in str(ei.value)


def test_process_grid_mesh_axes_come_from_the_registry():
    # parallel/grid.py routes its axis names through require_axis — a
    # registry drift would fail grid construction, not silently diverge
    from superlu_dist_tpu.parallel.grid import gridinit
    g = gridinit(1, 1)
    assert tuple(g.mesh.axis_names) == ("snode", "panel")


# --------------------------------------------------------------------------
# SLU120 mesh/spec hygiene (source)
# --------------------------------------------------------------------------

def test_slu120_fixture_flagged():
    hits = [f for f in _scan("unregistered_axis.py") if f.rule == "SLU120"]
    assert len(hits) == 6, hits
    names = [f for f in hits if "not declared in the mesh-axis registry"
             in f.message]
    # "row", "col" (Mesh), "rows" twice (in_specs + out_specs)
    assert len(names) == 4, hits
    assert any("'row'" in f.message for f in names)
    assert any("'rows'" in f.message for f in names)
    arity = [f for f in hits if "positional argument" in f.message]
    assert len(arity) == 1 and "1 spec(s)" in arity[0].message
    donated = [f for f in hits if "donated argument 1" in f.message]
    assert len(donated) == 1


def test_slu120_fixture_clean():
    assert [f for f in _scan("mesh_clean.py") if f.rule == "SLU120"] == []


def test_slu120_suppression_honored():
    src = ("from jax.sharding import PartitionSpec as P\n"
           "spec = P('bogus')  # slulint: disable=SLU120\n")
    assert [f for f in analyze_sources({"scripts/x.py": src})
            if f.rule == "SLU120"] == []


# --------------------------------------------------------------------------
# SLU122 cross-mesh transfer in dispatch loops (source)
# --------------------------------------------------------------------------

_LOOP_TRANSFER = '''\
import jax
import jax.numpy as jnp

def dispatch(xs, sharding):
    ys = []
    for x in xs:
        y = jnp.sin(x)                    # device value
        moved = jax.device_put(y, sharding)   # flagged: in-loop reshard
        resh = y.reshard(sharding)            # flagged: .reshard()
        ys.append(moved)
        ys.append(resh)
    return ys
'''

_LOOP_UPLOAD = '''\
import numpy as np
import jax

def dispatch(kern, n, sharding):
    ys = []
    for i in range(n):
        pad = np.zeros((8, 8))
        up = jax.device_put(pad, sharding)    # host upload: exempt
        ys.append(kern(up))
    committed = jax.device_put(ys[-1], sharding)  # after the loop: clean
    return ys, committed
'''


def test_slu122_flags_in_loop_device_transfers():
    hits = [f for f in analyze_sources(
        {"superlu_dist_tpu/numeric/fake.py": _LOOP_TRANSFER})
        if f.rule == "SLU122"]
    assert len(hits) == 2, hits
    assert any("`jax.device_put`" in f.message for f in hits)
    assert any("`.reshard()`" in f.message for f in hits)
    assert all("once per group" in f.message for f in hits)


def test_slu122_host_uploads_and_post_loop_transfers_exempt():
    assert [f for f in analyze_sources(
        {"superlu_dist_tpu/solve/fake.py": _LOOP_UPLOAD})
        if f.rule == "SLU122"] == []


def test_slu122_scoped_to_dispatch_packages():
    # the same pattern outside numeric//solve/ is out of scope
    assert [f for f in analyze_sources(
        {"superlu_dist_tpu/obs/fake.py": _LOOP_TRANSFER})
        if f.rule == "SLU122"] == []


# --------------------------------------------------------------------------
# SLU119 implicit replication (jaxpr) — real 2-shard shard_map programs
# --------------------------------------------------------------------------

_SHARD_CHILD = r"""
import importlib.util
import json, os, sys
sys.path.insert(0, os.environ["SLU_REPO"])
import numpy as np
import jax
from jax.sharding import Mesh
from superlu_dist_tpu.utils import programaudit
from superlu_dist_tpu.utils.errors import (MemoryBudgetError,
                                           ShardingAuditError)


def _fixture(name):
    path = os.path.join(os.environ["SLU_REPO"], "tests", "fixtures",
                        "slulint", name + ".py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


implicit_gather = _fixture("implicit_gather")
sharded_clean = _fixture("sharded_clean")

mesh = Mesh(np.array(jax.devices()[:2]), axis_names=("snode",))
out = {}

fn, args = sharded_clean.build(mesh)
stats = programaudit.maybe_audit("test.shard", "clean", fn, args,
                                 mesh_axes=("snode",))
out["clean"] = {"findings": stats["findings"],
                "peak": stats["peak_bytes_est"],
                "gathers": stats["n_gathers"]}

fn, args = implicit_gather.build(mesh)
try:
    programaudit.maybe_audit("test.shard", "gather", fn, args,
                             mesh_axes=("snode",))
    out["gather"] = {"raised": None}
except MemoryBudgetError:
    out["gather"] = {"raised": "MemoryBudgetError"}
except ShardingAuditError as e:
    out["gather"] = {"raised": "ShardingAuditError", "rules": e.rules,
                     "msg": str(e)}
print(json.dumps(out))
"""


def test_slu119_two_shard_subprocess_flags_gather_passes_sharded():
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=2",
               SLU_TPU_VERIFY_SHARDING="1",
               SLU_REPO=REPO)
    env.pop("SLU_TPU_MEM_BUDGET_BYTES", None)
    r = subprocess.run([sys.executable, "-c", _SHARD_CHILD], env=env,
                       cwd=REPO, capture_output=True, text=True,
                       timeout=300)
    assert r.returncode == 0, r.stderr
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["clean"]["findings"] == 0
    assert out["clean"]["peak"] > 0
    assert out["clean"]["gathers"] == 0
    assert out["gather"]["raised"] == "ShardingAuditError"
    assert out["gather"]["rules"] == ["SLU119"]
    assert "all_gather" in out["gather"]["msg"]
    assert "'snode'" in out["gather"]["msg"]


class _StubAval:
    def __init__(self, shape, itemsize=4):
        self.shape = shape
        self.dtype = type("dt", (), {"itemsize": itemsize})()


class _StubVar:
    def __init__(self, shape):
        self.aval = _StubAval(shape)


def _stub_jaxpr(eqns, invars=(), outvars=()):
    return type("J", (), {"eqns": list(eqns), "invars": list(invars),
                          "constvars": [], "outvars": list(outvars)})()


def test_slu119_replicated_constraint_on_mesh_flagged():
    # the fully-replicated device_put/sharding_constraint branch — CPU
    # tracing never produces it, so the duck-typed stub exercises it
    sharding = type("S", (), {"is_fully_replicated": True})()
    eqn = type("E", (), {
        "primitive": type("Pr", (), {"name": "device_put"})(),
        "params": {"devices": [sharding]},
        "invars": [_StubVar((512, 1024))],
        "outvars": [_StubVar((512, 1024))]})()
    spec = ProgramSpec(label="stub", site="test",
                       jaxpr=_stub_jaxpr([eqn]), mesh_axes=("snode",))
    findings, stats = rs.audit_resharding(spec, 1 << 20)
    assert [f.rule for f in findings] == ["SLU119"]
    assert "FULLY-REPLICATED" in findings[0].message
    assert stats["replicated_bytes"] == 512 * 1024 * 4
    # same eqn with no mesh (single-device run): priced, not flagged
    solo = ProgramSpec(label="stub", site="test",
                       jaxpr=_stub_jaxpr([eqn]), mesh_axes=())
    findings, _ = rs.audit_resharding(solo, 1 << 20)
    assert findings == []


# --------------------------------------------------------------------------
# SLU121 static peak-memory model (jaxpr)
# --------------------------------------------------------------------------

def test_slu121_blowup_vs_bounded_fixture_pair():
    fn_b, args_b = _fixture_build("mem_blowup")
    fn_c, args_c = _fixture_build("mem_bounded")
    spec_b = trace_spec(fn_b, args_b, label="blowup", site="test")
    spec_c = trace_spec(fn_c, args_c, label="bounded", site="test")
    _, stats_b = audit_sharding(spec_b, 1 << 20)
    _, stats_c = audit_sharding(spec_c, 1 << 20)
    # everything-live vs free-after-last-use: the walk must see it
    assert stats_b["peak_bytes_est"] >= 2 * stats_c["peak_bytes_est"]
    # a budget between the two verdicts splits the pair
    budget = 3 * 256 * 256 * 4
    f_b, _ = audit_sharding(spec_b, 1 << 20, budget_bytes=budget)
    f_c, _ = audit_sharding(spec_c, 1 << 20, budget_bytes=budget)
    assert [f.rule for f in f_b] == ["SLU121"]
    assert "largest buffers" in f_b[0].message
    assert f_c == []


def test_slu121_estimate_agrees_with_xla_memory_analysis():
    # acceptance: the static model within 2x of XLA's own temp+arg
    # total, where the API is available (CPU backend exposes it)
    fn, args = _fixture_build("mem_blowup")
    spec = trace_spec(fn, args, label="blowup", site="test")
    _, stats = audit_sharding(spec, 1 << 20)
    compiled = fn.lower(*args).compile()
    ma = getattr(compiled, "memory_analysis", lambda: None)()
    if ma is None or not hasattr(ma, "temp_size_in_bytes"):
        pytest.skip("compiled.memory_analysis() not available")
    # temp+arg+output: XLA fuses the elementwise chain so its "temp"
    # bytes are ~0 and the live set sits in args+outputs — the same
    # buffers the liveness walk keeps live to the end
    xla = (int(ma.temp_size_in_bytes) + int(ma.argument_size_in_bytes)
           + int(getattr(ma, "output_size_in_bytes", 0)))
    est = stats["peak_bytes_est"]
    assert xla > 0
    assert xla / 2 <= est <= xla * 2, (est, xla)


def test_slu121_counts_baked_consts():
    big = jnp.arange(1 << 16, dtype=jnp.float32)     # 256 KiB const

    def f(x):
        return jnp.sum(x) + jnp.sum(big)

    spec = trace_spec(jax.jit(f), (np.float32(1.0),),
                      label="const", site="test")
    _, stats = audit_sharding(spec, 1 << 20)
    assert stats["peak_bytes_est"] >= big.nbytes


# --------------------------------------------------------------------------
# runtime twin: SLU_TPU_VERIFY_SHARDING=1 / SLU_TPU_MEM_BUDGET_BYTES
# --------------------------------------------------------------------------

def test_budget_raises_before_run(fresh_sharding_auditor, tmp_path,
                                  monkeypatch):
    from superlu_dist_tpu.obs import flightrec
    monkeypatch.setenv("SLU_TPU_MEM_BUDGET_BYTES", str(64 * 1024))
    monkeypatch.setenv("SLU_TPU_FLIGHTREC", str(tmp_path / "fr-%p.json"))
    programaudit._reset()        # re-latch the budget
    flightrec._reset()
    fn, args = _fixture_build("mem_blowup")
    try:
        with pytest.raises(MemoryBudgetError) as ei:
            programaudit.maybe_audit("test.site", "blowup", fn, args)
        err = ei.value
        assert err.rules == ["SLU121"]
        assert err.site == "test.site" and err.program == "blowup"
        assert err.peak_bytes > err.budget_bytes == 64 * 1024
        # one except covers the whole v6 family
        assert isinstance(err, ShardingAuditError)
        # flight-recorder postmortem dumped at construction
        assert err.flightrec_dump and os.path.exists(err.flightrec_dump)
        doc = json.load(open(err.flightrec_dump))
        assert doc["reason"] == "MemoryBudgetError"
        # the failing program was NOT memoized as audited-clean
        aud = programaudit.get_sharding_auditor()
        assert ("test.site", "blowup") not in aud.audited
        assert aud.findings and aud.findings[0].rule == "SLU121"
    finally:
        flightrec._reset()


def test_budget_alone_implies_the_audit(monkeypatch):
    # a positive byte budget activates the twin without the flag
    monkeypatch.delenv("SLU_TPU_VERIFY_SHARDING", raising=False)
    monkeypatch.setenv("SLU_TPU_MEM_BUDGET_BYTES", str(1 << 30))
    programaudit._reset()
    try:
        aud = programaudit.get_sharding_auditor()
        assert aud is not None and aud.budget_bytes == 1 << 30
    finally:
        programaudit._reset()


def test_clean_program_memoized_with_census_note(fresh_sharding_auditor):
    from superlu_dist_tpu.obs.compilestats import COMPILE_STATS
    fn, args = _fixture_build("mem_bounded")
    s1 = programaudit.maybe_audit("test.site", "bounded", fn, args)
    assert s1["findings"] == 0 and s1["peak_bytes_est"] > 0
    aud = programaudit.get_sharding_auditor()
    assert ("test.site", "bounded") in aud.audited
    # memoized: a second submit returns the same stats, no re-trace
    s2 = aud.submit("test.site", "bounded", None, None)
    assert s2 is s1
    # census note lands under the #sharding-suffixed label and feeds the
    # audit_block aggregates
    assert ("test.site", "bounded#sharding") in COMPILE_STATS._audits
    blk = COMPILE_STATS.audit_block()
    assert blk["programs_sharding_audited"] == 1
    assert blk["peak_bytes_est"] == s1["peak_bytes_est"]
    assert blk["replicated_bytes"] == 0


def test_census_rows_carry_the_memory_column(fresh_sharding_auditor):
    import time
    from superlu_dist_tpu.obs.compilestats import COMPILE_STATS
    fn, args = _fixture_build("mem_bounded")
    stats = programaudit.maybe_audit("test.site", "colkey", fn, args)
    mark = COMPILE_STATS.marker()
    t0 = time.perf_counter()
    COMPILE_STATS.record("test.site", "colkey", t0, 0.01)
    rows = [r for r in COMPILE_STATS.census(since=mark)
            if r["key"] == "colkey"]
    assert rows and rows[0]["peak_bytes_est"] == stats["peak_bytes_est"]


def test_sharding_off_path_allocates_nothing(monkeypatch):
    monkeypatch.delenv("SLU_TPU_VERIFY_SHARDING", raising=False)
    monkeypatch.delenv("SLU_TPU_MEM_BUDGET_BYTES", raising=False)
    monkeypatch.delenv("SLU_TPU_VERIFY_PROGRAMS", raising=False)
    monkeypatch.delenv("SLU_TPU_VERIFY_DTYPES", raising=False)
    programaudit._reset()
    fn, args = _fixture_build("mem_blowup")    # would breach any budget
    out = programaudit.maybe_audit("test.site", "off", fn, args)
    assert out is None
    assert programaudit._SHARDING_AUDITOR is None
    assert programaudit.get_sharding_auditor() is None


# --------------------------------------------------------------------------
# mega executor: the budget error names the offending bucket RUNG
# --------------------------------------------------------------------------

def test_mega_budget_error_names_the_bucket_rung(monkeypatch):
    from superlu_dist_tpu.models.gallery import poisson2d
    from superlu_dist_tpu.numeric.factor import numeric_factorize
    from superlu_dist_tpu.numeric.plan import build_plan
    from superlu_dist_tpu.ordering.dispatch import get_perm_c
    from superlu_dist_tpu.sparse.formats import symmetrize_pattern
    from superlu_dist_tpu.symbolic.symbfact import symbolic_factorize
    from superlu_dist_tpu.utils.options import Options

    a = poisson2d(8)
    sym = symmetrize_pattern(a)
    sf = symbolic_factorize(sym, get_perm_c(Options(), a, sym))
    plan = build_plan(sf)
    vals = sym.data[sf.value_perm]

    monkeypatch.setenv("SLU_TPU_MEM_BUDGET_BYTES", "4096")
    programaudit._reset()
    try:
        with pytest.raises(MemoryBudgetError) as ei:
            numeric_factorize(plan, vals, a.norm_max(), executor="mega")
        err = ei.value
        assert err.site == "mega._kernel"
        # the label carries the padded pool rung — the axis the budget
        # verdict is actually about
        assert " P" in err.program, err.program
        assert err.peak_bytes > 4096 == err.budget_bytes
    finally:
        programaudit._reset()


# --------------------------------------------------------------------------
# catalog / SARIF plumbing
# --------------------------------------------------------------------------

def test_v6_rules_in_default_rules():
    ids = {r.rule_id for r in default_rules()}
    assert {"SLU119", "SLU120", "SLU121", "SLU122"} <= ids


def test_analysis_version_is_6():
    from superlu_dist_tpu.analysis.core import ANALYSIS_VERSION
    assert ANALYSIS_VERSION == "6"


def test_sarif_catalog_and_roundtrip_for_v6_rules():
    from superlu_dist_tpu.analysis.sarif import from_sarif, to_sarif
    findings = [f for f in _scan("unregistered_axis.py")
                if f.rule == "SLU120"]
    fn, args = _fixture_build("mem_blowup")
    spec = trace_spec(fn, args, label="blowup", site="test")
    f121, _ = audit_sharding(spec, 1 << 20, budget_bytes=4096)
    findings += f121
    assert findings
    doc = json.loads(json.dumps(to_sarif(findings, default_rules())))
    ids = {r["id"] for r in doc["runs"][0]["tool"]["driver"]["rules"]}
    assert {"SLU119", "SLU120", "SLU121", "SLU122"} <= ids
    back = from_sarif(doc)
    assert [(f.rule, f.path, f.line, f.col, f.message, f.hint)
            for f in back] == \
        [(f.rule, f.path, f.line, f.col, f.message, f.hint)
         for f in sorted(findings,
                         key=lambda f: (f.path, f.line, f.col, f.rule))]


def test_sharding_knobs_registered():
    from superlu_dist_tpu.utils.options import KNOB_REGISTRY
    assert KNOB_REGISTRY["SLU_TPU_VERIFY_SHARDING"].kind == "flag"
    assert KNOB_REGISTRY["SLU_TPU_MEM_BUDGET_BYTES"].kind == "int"
