from superlu_dist_tpu.serve.server import (   # noqa: F401
    SolveServer, SolveTicket)
from superlu_dist_tpu.utils.errors import (   # noqa: F401
    FactorCorruptError, ServeDeadlineError, ServeOverloadError,
    ServePoisonedError, ServerClosedError)
