"""Pallas fused gather/scatter kernels for the irregular factor hot spots.

The two memory-bound seams around the Schur-update GEMMs are irregular
gather/scatter round trips XLA lowers to serial scatter loops on TPU:

* the extend-add (``factor.extend_add_set``): per child-set, a
  ``pool.at[src].get`` of every child's padded ub×ub Schur block followed
  by an ``f.at[...].add`` scatter into the parent fronts — the
  multifrontal assembly traffic that bounds how wide the dataflow
  scheduler's look-ahead (``SLU_TPU_SCHED_WINDOW``) can open;
* the A-entry panel assembly (``group_step``): an ``avals`` gather and a
  front scatter-add over the host-built (slot, flat, src) index triples.

This module provides both as Pallas kernels in the spirit of
medium-granularity dataflow sparse engines (arXiv:2406.10511): the
gather, the position expansion and the accumulate run fused in one
kernel per dispatch group, with the front batch resident block-by-block
in VMEM instead of round-tripping through HBM per index triple.

Equivalence contract (tests/test_precision_ladder.py pins it): both
kernels are BITWISE-identical to the ``.at[]`` lowering —

* the extend-add accumulates child contributions in ascending child
  order via exact one-hot position matmuls (``Precision.HIGHEST`` keeps
  v·1.0 exact on the MXU) and touches only targeted positions (the
  masked ``where`` preserves untargeted bits, including -0.0), matching
  XLA's in-order scatter-add application;
* the assembly scatter targets are unique per (slot, flat) — the
  host-built maps assign every A entry its own front position — so the
  slot-sorted accumulation order cannot change the sum.

Because the two paths are bitwise-equal, every existing equivalence
gate (level↔dataflow, mega≡stream≡fused, checkpoint resume) carries
over unchanged whichever path a run takes.

Gating: ``SLU_TPU_PALLAS`` = auto (on when a TPU backend is present),
1/on, interpret (forced interpreter mode — what CI exercises on CPU),
or 0/off.  The mode is resolved in the UNCACHED executor factories and
threaded into every kernel cache key like the pivot-kernel choice
(slulint SLU102/SLU104/SLU105).  Mesh runs no longer pin the mode off:
under the shard_map SPMD tier each device runs the kernel on its local
slot shard (both kernels are bitwise twins of the ``.at[]`` lowering,
which is per-slot, so re-batching across devices preserves every bit),
and under the GSPMD stream/mega tiers the interpret lowering is plain
HLO the partitioner places like any other — interpret-mode on CPU
meshes, native Mosaic on TPU.  Index maps are cast to int32 for the
kernels — plans past the int32 pool range fall back to ``.at[]``
(``plan.check_index_width`` governs those anyway).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from superlu_dist_tpu.utils.options import env_str

#: modes the resolver returns; "on" compiles (TPU), "interpret" runs the
#: Pallas interpreter (bitwise-identical semantics, any backend)
PALLAS_MODES = ("off", "on", "interpret")

_INT32_MAX = 2 ** 31 - 1


def pallas_mode(name: str | None = None) -> str:
    """Resolve SLU_TPU_PALLAS to one of ``PALLAS_MODES``.

    auto = on iff the default backend is TPU; an explicit 1/on on a
    non-TPU backend degrades to interpret (there is no Mosaic lowering
    to run, but the fused path stays exercisable).  Resolved in the
    uncached executor factories only — the mode is part of every kernel
    cache key, never read at trace time."""
    raw = (env_str("SLU_TPU_PALLAS") if name is None or not str(name).strip()
           else str(name)).strip().lower()
    if raw in ("", "auto"):
        return "on" if jax.default_backend() == "tpu" else "off"
    if raw in ("0", "off", "false", "no"):
        return "off"
    if raw == "interpret":
        return "interpret"
    if raw in ("1", "on", "true", "yes"):
        return "on" if jax.default_backend() == "tpu" else "interpret"
    raise ValueError(f"SLU_TPU_PALLAS={raw!r} — expected auto|0|1|on|off|"
                     "interpret")


def _i32(x):
    return jnp.asarray(x).astype(jnp.int32)


# ---------------------------------------------------------------------------
# extend-add: pool gather -> one-hot position expansion -> front accumulate
# ---------------------------------------------------------------------------

def _extend_add_kernel(off_ref, slot_ref,          # SMEM (C,) scalars
                       rel_ref, pool_ref, f_ref,   # ANY
                       out_ref,                    # ANY, aliased with f
                       child_vmem, sem,            # scratch
                       *, m, ub, nc, pool_len):
    """One parent slot's extend-add: walk the child set in ascending
    child order, DMA each matching child's contiguous ub² pool slab into
    VMEM, expand it to front positions with exact one-hot matmuls, and
    accumulate — touching ONLY targeted positions (mask), which is what
    keeps the result bitwise-equal to XLA's scatter-add."""
    s = pl.program_id(0)
    out_ref[...] = f_ref[...]

    def body(c, carry):
        @pl.when((slot_ref[c] == s) & (off_ref[c] < pool_len))
        def _():
            dma = pltpu.make_async_copy(
                pool_ref.at[pl.ds(off_ref[c], ub * ub)], child_vmem, sem)
            dma.start()
            dma.wait()
            child = child_vmem[...].reshape(ub, ub)
            r = rel_ref[c]                                  # (ub,) int32
            pos = lax.broadcasted_iota(jnp.int32, (ub, m), 1)
            hit = r[:, None] == pos                         # (ub, m)
            oh = hit.astype(child.dtype)
            member = hit.any(axis=0)                        # (m,) targeted
            # rel positions are distinct (or the OOB sentinel), so every
            # one-hot contraction has at most ONE nonzero term — exact
            # at HIGHEST precision (v·1.0 reconstructs v on the MXU)
            upd = jnp.matmul(
                oh.T, jnp.matmul(child, oh,
                                 precision=lax.Precision.HIGHEST,
                                 preferred_element_type=child.dtype),
                precision=lax.Precision.HIGHEST,
                preferred_element_type=child.dtype)
            mask = member[:, None] & member[None, :]
            cur = out_ref[...].reshape(m, m)
            out_ref[...] = jnp.where(mask, cur + upd,
                                     cur).reshape(1, m * m)
        return carry

    lax.fori_loop(0, nc, body, 0)


def extend_add_set_pallas(f, pool, m, ub, child_off, child_slot, rel,
                          mode: str = "interpret"):
    """Pallas twin of ``factor.extend_add_set`` — same signature
    semantics, bitwise-identical result.  Returns None when this
    child-set cannot take the fused path (int32 index overflow) so the
    caller falls back to the ``.at[]`` lowering."""
    if int(pool.shape[0]) > _INT32_MAX or m * m > _INT32_MAX:
        return None
    batch = f.shape[0]
    nc = rel.shape[0]
    kern = functools.partial(_extend_add_kernel, m=int(m), ub=int(ub),
                             nc=int(nc), pool_len=int(pool.shape[0]))
    return pl.pallas_call(
        kern,
        grid=(batch,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),          # child_off
            pl.BlockSpec(memory_space=pltpu.SMEM),          # child_slot
            pl.BlockSpec(memory_space=pltpu.ANY),           # rel
            pl.BlockSpec(memory_space=pltpu.ANY),           # pool
            pl.BlockSpec((1, m * m), lambda s: (s, 0),
                         memory_space=pltpu.ANY),           # f block
        ],
        out_specs=pl.BlockSpec((1, m * m), lambda s: (s, 0),
                               memory_space=pltpu.ANY),
        out_shape=jax.ShapeDtypeStruct(f.shape, f.dtype),
        scratch_shapes=[pltpu.VMEM((ub * ub,), f.dtype),
                        pltpu.SemaphoreType.DMA],
        input_output_aliases={4: 0},
        interpret=(mode == "interpret"),
    )(_i32(child_off), _i32(child_slot), _i32(rel), pool, f)


# ---------------------------------------------------------------------------
# A-entry panel assembly: avals gather -> slot-sorted front scatter-add
# ---------------------------------------------------------------------------

def _assemble_kernel(bounds_ref,                   # SMEM (batch+1,)
                     flat_ref, src_ref, avals_ref, f_ref,   # ANY
                     out_ref,                      # ANY, aliased with f
                     *, m2):
    """One slot's A-entry assembly: its contiguous slot-sorted entry run
    [bounds[s], bounds[s+1]) gathers from avals and accumulates into the
    resident front block.  Targets are unique per entry (the host-built
    maps give every A entry its own front position), so the sorted order
    cannot change any floating-point sum."""
    s = pl.program_id(0)
    out_ref[...] = f_ref[...]

    def body(e, carry):
        fl = flat_ref[e]
        out_ref[0, fl] = out_ref[0, fl] + avals_ref[src_ref[e]]
        return carry

    lax.fori_loop(bounds_ref[s], bounds_ref[s + 1], body, 0)


def assemble_avals_pallas(f, avals, a_slot, a_flat, a_src,
                          mode: str = "interpret"):
    """Pallas twin of the ``group_step`` A-assembly round trip
    (``avals.at[a_src].get`` → ``f.at[(a_slot, a_flat)].add``): entries
    are slot-sorted on device (stable argsort — pure data movement, no
    arithmetic) so each grid step owns one front block's contiguous run.
    Padded entries carry the slot sentinel ``batch`` and sort past the
    last bound — the ``mode='drop'`` analog.  Returns None on int32
    overflow (caller falls back)."""
    batch, m2 = f.shape
    if m2 > _INT32_MAX or int(avals.shape[0]) > _INT32_MAX:
        return None
    order = jnp.argsort(_i32(a_slot), stable=True)
    slot_s = _i32(a_slot)[order]
    flat_s = _i32(a_flat)[order]
    src_s = _i32(a_src)[order]
    bounds = jnp.searchsorted(
        slot_s, jnp.arange(batch + 1, dtype=jnp.int32)).astype(jnp.int32)
    kern = functools.partial(_assemble_kernel, m2=int(m2))
    return pl.pallas_call(
        kern,
        grid=(batch,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),          # bounds
            pl.BlockSpec(memory_space=pltpu.ANY),           # flat sorted
            pl.BlockSpec(memory_space=pltpu.ANY),           # src sorted
            pl.BlockSpec(memory_space=pltpu.ANY),           # avals
            pl.BlockSpec((1, m2), lambda s: (s, 0),
                         memory_space=pltpu.ANY),           # f block
        ],
        out_specs=pl.BlockSpec((1, m2), lambda s: (s, 0),
                               memory_space=pltpu.ANY),
        out_shape=jax.ShapeDtypeStruct(f.shape, f.dtype),
        input_output_aliases={4: 0},
        interpret=(mode == "interpret"),
    )(bounds, flat_s, src_s, avals, f)
