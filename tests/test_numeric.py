import numpy as np
import pytest

from superlu_dist_tpu.models.gallery import poisson2d, random_sparse, convection_diffusion_2d
from superlu_dist_tpu.numeric.factor import numeric_factorize
from superlu_dist_tpu.numeric.plan import build_plan
from superlu_dist_tpu.solve.trisolve import lu_solve
from superlu_dist_tpu.sparse.formats import symmetrize_pattern
from superlu_dist_tpu.symbolic.symbfact import symbolic_factorize
from superlu_dist_tpu.ordering.dissection import geometric_nd


def factor_setup(a, order=None, relax=4, max_supernode=16, dtype="float64"):
    n = a.n_rows
    if order is None:
        order = np.arange(n)
    sym = symmetrize_pattern(a)
    sf = symbolic_factorize(sym, order, relax=relax, max_supernode=max_supernode)
    plan = build_plan(sf)
    bvals = sym.permute(sf.perm, sf.perm).data
    anorm = a.norm_max()
    fact = numeric_factorize(plan, bvals, anorm, dtype=dtype)
    m_dense = sym.permute(sf.perm, sf.perm).to_dense()
    return sf, plan, fact, m_dense


def extract_lu(sf, plan, fact):
    """Reassemble dense L (unit lower) and U from packed fronts."""
    n = sf.n
    L = np.eye(n)
    U = np.zeros((n, n))
    hosts = fact.pull_to_host()
    for s in range(sf.n_supernodes):
        grp = plan.groups[plan.sn_group[s]]
        lp, up = hosts[plan.sn_group[s]]
        lp, up = lp[plan.sn_slot[s]], up[plan.sn_slot[s]]
        fcol, lcol = int(sf.sn_start[s]), int(sf.sn_start[s + 1]) - 1
        w = lcol - fcol + 1
        u = len(sf.sn_rows[s])
        W = grp.w
        cols = np.arange(fcol, lcol + 1)
        L[np.ix_(cols, cols)] = np.tril(lp[:w, :w], -1) + np.eye(w)
        U[np.ix_(cols, cols)] = np.triu(lp[:w, :w])
        if u:
            rows = sf.sn_rows[s]
            L[np.ix_(rows, cols)] = lp[W:W + u, :w]
            U[np.ix_(cols, rows)] = up[:w, :u]
    return L, U


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_factor_reconstructs_matrix(seed):
    a = random_sparse(35, density=0.06, seed=seed)
    sf, plan, fact, m = factor_setup(a)
    L, U = extract_lu(sf, plan, fact)
    np.testing.assert_allclose(L @ U, m, atol=1e-9 * max(1, np.abs(m).max()))
    assert fact.tiny_pivots == 0


def test_factor_poisson_nd():
    a = poisson2d(9)
    sf, plan, fact, m = factor_setup(a, order=geometric_nd(a.grid_shape),
                                     relax=8, max_supernode=32)
    L, U = extract_lu(sf, plan, fact)
    np.testing.assert_allclose(L @ U, m, atol=1e-9)


def test_factor_unsymmetric_values():
    a = convection_diffusion_2d(8, beta=50.0)
    sf, plan, fact, m = factor_setup(a, order=geometric_nd(a.grid_shape))
    L, U = extract_lu(sf, plan, fact)
    np.testing.assert_allclose(L @ U, m, atol=1e-9)


@pytest.mark.parametrize("nrhs", [1, 3])
def test_solve_matches_numpy(nrhs):
    a = random_sparse(40, density=0.05, seed=5)
    sf, plan, fact, m = factor_setup(a)
    rng = np.random.default_rng(0)
    b = rng.standard_normal((40, nrhs)) if nrhs > 1 else rng.standard_normal(40)
    x = lu_solve(fact, b)
    want = np.linalg.solve(m, b)
    np.testing.assert_allclose(x, want, rtol=1e-8, atol=1e-8)


def test_complex_factor_and_solve():
    a = random_sparse(30, density=0.08, seed=9, dtype=np.complex128)
    sf, plan, fact, m = factor_setup(a, dtype="complex128")
    L, U = extract_lu_complex(sf, plan, fact)
    np.testing.assert_allclose(L @ U, m, atol=1e-9 * max(1, np.abs(m).max()))
    b = np.random.default_rng(1).standard_normal(30) + 0j
    x = lu_solve(fact, b)
    np.testing.assert_allclose(x, np.linalg.solve(m, b), rtol=1e-8, atol=1e-8)


def extract_lu_complex(sf, plan, fact):
    n = sf.n
    L = np.eye(n, dtype=np.complex128)
    U = np.zeros((n, n), dtype=np.complex128)
    hosts = fact.pull_to_host()
    for s in range(sf.n_supernodes):
        grp = plan.groups[plan.sn_group[s]]
        lp, up = hosts[plan.sn_group[s]]
        lp, up = lp[plan.sn_slot[s]], up[plan.sn_slot[s]]
        fcol, lcol = int(sf.sn_start[s]), int(sf.sn_start[s + 1]) - 1
        w = lcol - fcol + 1
        u = len(sf.sn_rows[s])
        W = grp.w
        cols = np.arange(fcol, lcol + 1)
        L[np.ix_(cols, cols)] = np.tril(lp[:w, :w], -1) + np.eye(w)
        U[np.ix_(cols, cols)] = np.triu(lp[:w, :w])
        if u:
            rows = sf.sn_rows[s]
            L[np.ix_(rows, cols)] = lp[W:W + u, :w]
            U[np.ix_(cols, rows)] = up[:w, :u]
    return L, U


def test_f32_factor_quality():
    a = poisson2d(8)
    sf, plan, fact, m = factor_setup(a, order=geometric_nd(a.grid_shape),
                                     dtype="float32")
    b = np.ones(64)
    x = lu_solve(fact, b)
    want = np.linalg.solve(m, b)
    # single-precision factors: ~1e-5 relative accuracy pre-refinement
    assert np.linalg.norm(x - want) / np.linalg.norm(want) < 1e-4


def test_index_width_guard():
    """pool_size >= 2^31 without x64 must raise the XSDK_INDEX_SIZE=64
    guidance instead of silently downcasting index maps (the n=1M bug:
    flat pool offsets wrapped negative)."""
    import dataclasses
    import jax
    from superlu_dist_tpu.numeric.factor import make_factor_fn
    from superlu_dist_tpu.numeric.stream import StreamExecutor

    a = poisson2d(6)
    sym = symmetrize_pattern(a)
    sf = symbolic_factorize(sym, np.arange(a.n_rows), relax=4,
                            max_supernode=16)
    plan = build_plan(sf)
    big = dataclasses.replace(plan, pool_size=2 ** 31)
    # x64 is ON in the suite (conftest): the guard must pass
    big.check_index_width()
    try:
        jax.config.update("jax_enable_x64", False)
        with pytest.raises(ValueError, match="XSDK_INDEX_SIZE"):
            big.check_index_width()
        with pytest.raises(ValueError, match="int32 index range"):
            StreamExecutor(big, "float32")
        with pytest.raises(ValueError, match="int32 index range"):
            make_factor_fn(big, "float32")
    finally:
        jax.config.update("jax_enable_x64", True)
