"""Iterative refinement.

Analog of pdgsrfs (SRC/pdgsrfs.c:120): classical IR with componentwise
backward error.  r = b − A·x is computed in float64 (the analog of the
reference's double-precision residual in IterRefine=SLU_DOUBLE), the
correction solves reuse the factors, and iteration stops when
berr = max_i |r|_i / (|A|·|x| + |b|)_i reaches eps, stops improving by 2×
(reference :232), or after ITMAX=20 steps (reference :126).

On TPU this is the half of the mixed-precision design that recovers f64
accuracy from f32 factors (SURVEY.md §7 hard-part 1): the factorization is
fast/low-precision on the MXU, the cheap SpMV residual is exact.
"""

from __future__ import annotations

import numpy as np

from superlu_dist_tpu.sparse.formats import SparseCSR
from superlu_dist_tpu.utils import tols

ITMAX = 20


def componentwise_berr(r: np.ndarray, den: np.ndarray, nnz: int,
                       residual_dtype=np.float64) -> float:
    """max_i |r_i| / den_i with the reference's underflow guard
    (pdgsrfs.c:225 / dgsrfs.f:214): denominators at or below
    safe1·safmin = (nnz+1)·safmin are bumped by that amount, so an
    exactly-zero row reports berr 0 instead of 0/0 while a *tiny*
    denominator is not rounded up to 1 (which understates berr).  The ONE
    implementation shared by the serial loop here and the distributed
    loop (parallel/pgsrfs.py) — the two must never drift."""
    safmin = tols.safmin(residual_dtype)
    bump = (nnz + 1) * safmin
    den = np.where(den <= bump, den + bump, den)
    return float(np.max(np.abs(r) / den))


def _normalize_correction(dx, n: int, ncols: int) -> np.ndarray:
    """Normalize a correction-solve result to (n, ncols).

    solve_fn implementations legitimately squeeze a single remaining
    column to (n,) (the host/device solvers mirror b's ndim); anything
    else that doesn't match is a real contract violation and must fail
    loudly here rather than broadcast garbage into the iterate."""
    dx = np.asarray(dx)
    if dx.ndim == 1:
        dx = dx[:, None]
    if dx.shape != (n, ncols):
        raise ValueError(
            f"correction solve returned shape {np.asarray(dx).shape}, "
            f"expected ({n}, {ncols})")
    return dx


def request_berrs(a: SparseCSR, b: np.ndarray, x: np.ndarray,
                  residual_dtype=np.float64) -> np.ndarray:
    """Per-column componentwise backward errors of x against A·x = b —
    the quality probe the serving tier's BERR gate runs on every
    micro-batch (serve/server.py, ``SLU_TPU_SERVE_BERR_MAX``).  One
    batched SpMV pair for the whole batch; columns are independent, so
    one ticket's berr never reflects a neighbor's right-hand side."""
    b2 = b[:, None] if b.ndim == 1 else b
    x2 = x[:, None] if x.ndim == 1 else x
    r = (b2 - a.matvec(x2)).astype(np.promote_types(b2.dtype,
                                                    residual_dtype))
    out = np.empty(b2.shape[1])
    for k in range(b2.shape[1]):
        den = a.abs_matvec(np.abs(x2[:, k])) + np.abs(b2[:, k])
        out[k] = componentwise_berr(r[:, k], den.real, a.nnz,
                                    residual_dtype)
    return out


def refine_ticket(a: SparseCSR, b: np.ndarray, x: np.ndarray, solve_fn,
                  berr_target: float, itmax: int = ITMAX,
                  residual_dtype=np.float64):
    """Per-ticket IR rung for the serving tier: refine ONE request's
    columns through the factored solve until its componentwise berr
    meets ``berr_target`` (or IR's own stopping rules fire), without
    touching any other ticket of the micro-batch — the per-request
    analog of the PR 1 escalation ladder's residual-precision rung.

    Returns ``(x_out, berr_before, berr_after, adopted)``.  The ladder's
    adoption discipline applies: the refined iterate is returned only
    when it strictly improved the worst column's berr; otherwise the
    original x comes back unchanged (``adopted=False``) so a
    non-converging refinement can never make a served answer worse."""
    from superlu_dist_tpu.obs.trace import get_tracer
    with get_tracer().span("refine-ticket", cat="request",
                           berr_target=berr_target) as sp:
        berr_before = float(
            request_berrs(a, b, x, residual_dtype=residual_dtype).max())
        if berr_before <= berr_target:
            sp.set(berr_before=berr_before, adopted=False)
            return x, berr_before, berr_before, False
        x_ref, _hist = iterative_refinement(
            a, b, x, solve_fn, itmax=itmax,
            residual_dtype=residual_dtype)
        x_ref = np.asarray(x_ref).astype(np.asarray(x).dtype, copy=False)
        berr_after = float(
            request_berrs(a, b, x_ref,
                          residual_dtype=residual_dtype).max())
        adopted = berr_after < berr_before
        sp.set(berr_before=berr_before, berr_after=berr_after,
               adopted=adopted, iters=len(_hist))
    if adopted:
        return x_ref, berr_before, berr_after, True
    return x, berr_before, berr_before, False


def iterative_refinement(a: SparseCSR, b: np.ndarray, x: np.ndarray,
                         solve_fn, itmax: int = ITMAX,
                         residual_dtype=np.float64):
    """Refine solve_fn-based solution x of A·x = b.

    solve_fn(r) must solve A·dx = r using the existing factorization
    (including all scalings/permutations).  residual_dtype picks the
    precision of the residual/accumulation (the reference's
    SLU_SINGLE/SLU_DOUBLE tiers).  Returns (x, berr_history).
    """
    residual_dtype = np.dtype(residual_dtype)
    b = np.asarray(b)
    squeeze = b.ndim == 1
    b2 = b[:, None] if squeeze else b
    work = np.promote_types(b.dtype, residual_dtype)
    if residual_dtype == np.float32:
        # SLU_SINGLE caps the working precision at single even for f64 input
        work = (np.complex64 if np.issubdtype(work, np.complexfloating)
                else np.float32)
    x2 = (x[:, None] if squeeze else x).astype(work, copy=True)
    eps = tols.eps(residual_dtype)
    nrhs = b2.shape[1]
    berrs = []
    # per-RHS stopping state, like the reference's outer loop over RHS
    # columns (pdgsrfs.c:126): one stagnating column must not halt others
    lstres = np.full(nrhs, np.inf)
    active = np.ones(nrhs, dtype=bool)
    for _ in range(itmax):
        # the residual is rounded to the working precision (SLU_SINGLE
        # => f32): the refinement then cannot see — and so cannot correct —
        # anything below single eps, the reference's tier semantics
        r = (b2 - a.matvec(x2)).astype(work)
        # componentwise backward error per rhs (pdgsrfs.c:213-231)
        berr = np.empty(nrhs)
        for k in range(nrhs):
            den = (a.abs_matvec(np.abs(x2[:, k]))
                   + np.abs(b2[:, k])).astype(x2.real.dtype)
            berr[k] = componentwise_berr(r[:, k], den, a.nnz, residual_dtype)
        berrs.append(berr.copy())
        active &= (berr > eps) & (berr < lstres / 2.0)
        if not active.any():
            break
        lstres = np.where(active, berr, lstres)
        dx = _normalize_correction(solve_fn(r[:, active]), len(x2),
                                   int(active.sum()))
        x2[:, active] = x2[:, active] + dx
    berrs = [float(b.max()) for b in berrs]
    return (x2[:, 0] if squeeze else x2), berrs
