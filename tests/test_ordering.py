import numpy as np
import pytest

from superlu_dist_tpu.models.gallery import poisson2d, random_sparse
from superlu_dist_tpu.ordering.etree import etree_symmetric, postorder, tree_levels
from superlu_dist_tpu.ordering.minimum_degree import minimum_degree
from superlu_dist_tpu.ordering.dissection import geometric_nd, bfs_nd
from superlu_dist_tpu.sparse.formats import symmetrize_pattern


def dense_etree(pat):
    """Brute-force etree via dense symbolic elimination: parent[j] = first
    below-diagonal nonzero of column j of the filled pattern."""
    n = pat.shape[0]
    f = pat.copy()
    parent = np.full(n, -1, dtype=np.int64)
    for j in range(n):
        below = np.flatnonzero(f[j + 1:, j]) + j + 1
        if len(below):
            p = below[0]
            parent[j] = p
            f[below, p] = True      # fill: column j merges into column p
            f[p, below] = True
    return parent


def sym_pattern(a):
    n = a.n_rows
    pat = np.zeros((n, n), dtype=bool)
    rows = np.repeat(np.arange(n), np.diff(a.indptr))
    pat[rows, a.indices] = True
    pat |= pat.T
    np.fill_diagonal(pat, True)
    return pat


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_etree_matches_dense(seed):
    a = random_sparse(30, density=0.08, seed=seed)
    s = symmetrize_pattern(a)
    parent = etree_symmetric(s.n_rows, s.indptr, s.indices)
    want = dense_etree(sym_pattern(a))
    assert np.array_equal(parent, want)


def test_postorder_valid():
    a = poisson2d(6)
    s = symmetrize_pattern(a)
    parent = etree_symmetric(s.n_rows, s.indptr, s.indices)
    post = postorder(parent)
    assert sorted(post) == list(range(len(parent)))
    seen = np.zeros(len(parent), dtype=bool)
    for j in post:
        for pj in [parent[j]]:
            pass
        # children must appear before parents
        assert not seen[j]
        seen[j] = True
        if parent[j] >= 0:
            assert not seen[parent[j]]
    lvl = tree_levels(parent)
    for j, p in enumerate(parent):
        if p >= 0:
            assert lvl[p] > lvl[j]


def fill_count(pat, order):
    """nnz(L) after eliminating in the given order (dense symbolic)."""
    n = pat.shape[0]
    f = pat[np.ix_(order, order)].copy()
    np.fill_diagonal(f, True)
    count = 0
    for j in range(n):
        below = np.flatnonzero(f[j + 1:, j]) + j + 1
        count += len(below) + 1
        if len(below):
            f[np.ix_(below, below)] = True
    return count


@pytest.mark.parametrize("maker", ["poisson", "random"])
def test_orderings_reduce_fill_and_are_perms(maker):
    if maker == "poisson":
        a = poisson2d(8)
    else:
        a = random_sparse(48, density=0.06, seed=3, pattern_symmetric=True)
    s = symmetrize_pattern(a)
    n = s.n_rows
    pat = sym_pattern(a)
    natural_fill = fill_count(pat, np.arange(n))
    md = minimum_degree(n, s.indptr, s.indices)
    assert sorted(md) == list(range(n))
    assert fill_count(pat, md) <= natural_fill
    nd = bfs_nd(n, s.indptr, s.indices, leaf_size=8)
    assert sorted(nd) == list(range(n))
    if maker == "poisson":
        geo = geometric_nd(a.grid_shape)
        assert sorted(geo) == list(range(n))
        assert fill_count(pat, geo) <= natural_fill


def test_geometric_nd_3d():
    from superlu_dist_tpu.models.gallery import poisson3d
    a = poisson3d(4)
    order = geometric_nd(a.grid_shape)
    assert sorted(order) == list(range(64))


# ---- COLAMD / MMD_ATA (reference get_perm_c.c:463-530 dispatch rows) ----

def _brute_ata_adj(a):
    n = a.n_cols
    adj = [set() for _ in range(n)]
    for r in range(a.n_rows):
        cols = set(int(j) for j in a.indices[a.indptr[r]:a.indptr[r + 1]])
        for j in cols:
            adj[j].update(cols - {j})
    return adj


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_colamd_native_matches_python_oracle(seed):
    from superlu_dist_tpu import native
    from superlu_dist_tpu.ordering.colamd import _colamd_py
    a = random_sparse(55, density=0.08, seed=seed)
    py = _colamd_py(a.n_rows, a.n_cols, a.indptr, a.indices)
    assert sorted(py) == list(range(a.n_cols))
    nat = native.colamd(a.n_rows, a.n_cols, a.indptr, a.indices)
    if nat is not None:         # native lib present: must agree exactly
        np.testing.assert_array_equal(nat, py)


def test_ata_adjacency_matches_brute_force():
    from superlu_dist_tpu.ordering.colamd import ata_adjacency
    a = random_sparse(40, density=0.1, seed=9)
    ptr, idx = ata_adjacency(a.n_rows, a.n_cols, a.indptr, a.indices)
    brute = _brute_ata_adj(a)
    for j in range(a.n_cols):
        got = sorted(idx[ptr[j]:ptr[j + 1]])
        assert got == sorted(brute[j]), j


@pytest.mark.slow
def test_colamd_mmd_ata_end_to_end():
    import superlu_dist_tpu as slu
    from superlu_dist_tpu.utils.options import ColPerm
    a = poisson2d(12)
    xt = np.random.default_rng(3).standard_normal(a.n_rows)
    b = a.matvec(xt)
    for cp in (ColPerm.COLAMD, ColPerm.MMD_ATA):
        x, lu, stats, info = slu.gssvx(slu.Options(col_perm=cp), a, b)
        assert info == 0
        r = np.linalg.norm(b - a.matvec(x)) / np.linalg.norm(b)
        assert r < 1e-12, (cp, r)


def test_colamd_dense_column_goes_last():
    # a column present in every row must be ordered last, not poison the
    # scores (the colamd dense-column rule: degree > 10·sqrt(n_rows))
    n = 400
    rng = np.random.default_rng(0)
    rows = []
    for i in range(n):
        cols = set(rng.choice(n, size=3, replace=False).tolist()) | {i, 0}
        rows.append(sorted(cols))
    indptr = np.zeros(n + 1, dtype=np.int64)
    indices = []
    for i, cs in enumerate(rows):
        indices.extend(cs)
        indptr[i + 1] = len(indices)
    from superlu_dist_tpu.ordering.colamd import colamd_order
    order = colamd_order(n, n, indptr, np.asarray(indices, dtype=np.int64))
    assert sorted(order) == list(range(n))
    assert order[-1] == 0


def test_mlnd_threaded_deterministic():
    """Parallel ND (ParMETIS-analog, get_perm_c_parmetis.c:255): subtree
    threading must not change the ordering — RNG streams derive from the
    separator-tree path, not thread timing."""
    from superlu_dist_tpu import native
    if not native.available():
        pytest.skip("native unavailable")
    a = poisson2d(30)
    sym = symmetrize_pattern(a)
    o1 = native.mlnd(a.n_rows, sym.indptr, sym.indices, nthreads=1)
    o4 = native.mlnd(a.n_rows, sym.indptr, sym.indices, nthreads=4)
    assert sorted(o1) == list(range(a.n_rows))
    np.testing.assert_array_equal(o1, o4)


def test_multilevel_nd_quality_on_irregular_graph():
    """General-graph ND (the METIS-class path) must stay competitive
    with exact minimum degree on an irregular FEM-like graph — the
    audikw-class quality gate (VERDICT r1 missing #1: a BFS level-set
    separator would explode fill here)."""
    from superlu_dist_tpu import native
    from superlu_dist_tpu.models.gallery import random_geometric_3d
    from superlu_dist_tpu.symbolic.symbfact import symbolic_factorize
    from superlu_dist_tpu.ordering.dispatch import get_perm_c
    from superlu_dist_tpu.utils.options import Options, ColPerm

    if not native.available():
        pytest.skip("native unavailable (the BFS fallback would fail the "
                    "quality gate by design)")
    a = random_geometric_3d(1500, seed=3)
    sym = symmetrize_pattern(a)

    def nnz_l(cp):
        order = get_perm_c(Options(col_perm=cp), a, sym)
        sf = symbolic_factorize(sym, order, relax=8, max_supernode=64)
        return sf.nnz_L

    nd = nnz_l(ColPerm.ND_AT_PLUS_A)
    md = nnz_l(ColPerm.MMD_AT_PLUS_A)
    nat = nnz_l(ColPerm.NATURAL)
    # ND must beat natural ordering decisively and stay within ~2x of MD
    assert nd < 0.5 * nat, (nd, nat)
    assert nd < 2.0 * md, (nd, md)
