"""slulint v2 acceptance fixture: a collective hidden behind a wrapper.

``broadcast_result`` calls ``_ship`` — whose body performs the
``bcast_any`` — from inside a rank-conditioned branch.  PR-3's lexical
SLU101 sees no collective call in the branch and stays silent; the v2
interprocedural rule resolves ``_ship`` through the call graph, sees it
reaches a collective, and flags the call site.  NOT scanned by the CI
gate (tests/ is outside the scan scope); tests/test_analysis.py runs
both rule tiers over this file to prove the v1/v2 difference.
"""


def _ship(tc, x, root):
    # fine on its own: every rank that CALLS _ship reaches the collective
    return tc.bcast_any(x, root=root)


def _ship_deeper(tc, x, root):
    # two levels of indirection — reachability, not one-step lookup
    return _ship(tc, x, root)


def broadcast_result(tc, x, root=0):
    if tc.rank == root:
        x = _ship(tc, x, root)          # v2 SLU101: wrapper reaches bcast_any
    return x


def gather_sizes(tc, sizes, root=0):
    r = tc.rank                          # rank taint through a temporary
    if r != root:
        return None                      # rank-conditioned early exit...
    return _ship_deeper(tc, sizes, root)  # ...before a transitive collective
