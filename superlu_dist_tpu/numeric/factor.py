"""Level-batched multifrontal numeric factorization on the accelerator.

The execution analog of pdgstrf (SRC/pdgstrf.c:243) — but where the
reference runs an MPI look-ahead pipeline of per-panel BLAS calls, this
walks the elimination-tree levels bottom-up and, per (level, bucket) group,
issues three scatter/gather ops and one batched dense kernel (ops.dense).
All arrays stay resident on the device; the update pool plays the role of
the reference's bigU/bigV GEMM buffers (pdgstrf.c:770-884) and the
extend-add indices the role of the dscatter_l/u index arithmetic
(SRC/dscatter.c:111-290).
"""

from __future__ import annotations

import dataclasses

import numpy as np
import jax
import jax.numpy as jnp

from superlu_dist_tpu.numeric.plan import FactorPlan
from superlu_dist_tpu.ops.dense import make_front_kernel


@dataclasses.dataclass
class NumericFactorization:
    """LU factors as packed front batches (the dLUstruct_t analog,
    superlu_ddefs.h:186-191)."""

    plan: FactorPlan
    fronts: list              # per group: (B, M, M) device array, packed LU
    tiny_pivots: int
    dtype: object
    finite: bool = True       # False => an exact zero pivot propagated
                              # (only possible with replace_tiny=False)
    host_fronts: list = None  # lazily pulled numpy copies for the host solve

    def pull_to_host(self):
        """Transfer factors to host once (the dSolveInit analog,
        SRC/pdutil.c:690 — solve-side setup cached across solves)."""
        if self.host_fronts is None:
            self.host_fronts = [np.asarray(f) for f in self.fronts]
        return self.host_fronts


def numeric_factorize(plan: FactorPlan, pattern_values: np.ndarray,
                      anorm: float, dtype="float64",
                      replace_tiny: bool = True) -> NumericFactorization:
    """Factor with values aligned to plan.pattern_indices.

    anorm: ‖A‖ for the GESP tiny-pivot threshold sqrt(eps)·‖A‖
    (reference pdgstrf2.c:218: thresh = eps·‖A‖; we use the sqrt variant of
    ReplaceTinyPivot so f32 factors retain half their digits).
    With replace_tiny=False an exact zero pivot propagates inf/nan; the
    result is flagged non-finite (the reference's info>0 singularity path,
    pdgstrf.c:234-241).
    """
    dtype = jnp.dtype(dtype)
    real_dtype = jnp.dtype(dtype).type(0).real.dtype
    eps = jnp.finfo(real_dtype).eps
    thresh = jnp.asarray(
        np.sqrt(float(eps)) * max(anorm, 1e-300) if replace_tiny else 0.0,
        dtype=real_dtype)
    avals = jnp.asarray(pattern_values, dtype=dtype)
    pool = jnp.zeros(plan.pool_size, dtype=dtype)
    fronts_out = []
    tiny_total = jnp.zeros((), jnp.int32)
    one = jnp.ones((), dtype=dtype)
    for grp in plan.groups:
        f = jnp.zeros((grp.batch, grp.m * grp.m), dtype=dtype)
        if len(grp.pad_flat):
            f = f.at[(grp.pad_slot, grp.pad_flat)].set(one)
        if len(grp.a_src):
            f = f.at[(grp.a_slot, grp.a_flat)].add(avals[grp.a_src])
        if len(grp.e_src):
            f = f.at[(grp.e_slot, grp.e_flat)].add(pool[grp.e_src])
        kern = make_front_kernel(grp.m, grp.w, str(dtype))
        packed, tiny = kern(f.reshape(grp.batch, grp.m, grp.m), thresh)
        fronts_out.append(packed)
        tiny_total = tiny_total + tiny
        if len(grp.s_dst):
            flat = packed.reshape(grp.batch, -1)
            pool = pool.at[grp.s_dst].set(flat[(grp.s_slot, grp.s_src_flat)])
    finite = True
    if not replace_tiny:
        finite = all(bool(jnp.isfinite(f).all()) for f in fronts_out)
    return NumericFactorization(plan=plan, fronts=fronts_out,
                                tiny_pivots=int(tiny_total), dtype=dtype,
                                finite=finite)


def factor_flops(plan: FactorPlan) -> float:
    """Flop count for stats (the ops[FACT] analog, SRC/util.c:513)."""
    return plan.flops
