"""Solver options.

Mirrors the reference's runtime option struct ``superlu_dist_options_t``
(SRC/superlu_defs.h:628-657) and its defaults ``set_default_options_dist``
(SRC/util.c:376-401), re-expressed for the TPU-native pipeline.  TPU-specific
knobs (factor dtype, bucket geometry) replace the CPU/GPU tuning env vars
(sp_ienv_dist, SRC/sp_ienv.c:70-123; get_cublas_nb etc., SRC/util.c:932-972).
"""

from __future__ import annotations

import dataclasses
import enum
import os


class YesNo(enum.Enum):
    NO = 0
    YES = 1


class Fact(enum.Enum):
    """Factorization reuse tiers (reference fact_t, superlu_defs.h:489-510).

    These are the reference API's main performance feature for time-stepping
    users (SURVEY.md §5 checkpoint/resume): each tier skips more of the
    pipeline on a repeated solve.
    """

    DOFACT = 0                      # factor from scratch
    SamePattern = 1                 # reuse column perm + symbolic + plan
    SamePattern_SameRowPerm = 2     # additionally reuse row perm + scalings
    FACTORED = 3                    # reuse the numeric factors (solve only)


class ColPerm(enum.Enum):
    """Fill-reducing column orderings (reference colperm_t; dispatch
    get_perm_c_dist, SRC/get_perm_c.c:463-530)."""

    NATURAL = 0
    MMD_AT_PLUS_A = 1       # minimum degree on pattern of A^T + A
    ND_AT_PLUS_A = 2        # multilevel nested dissection (METIS analog)
    METIS_AT_PLUS_A = 2     # alias: the reference default maps to our ND
    MY_PERMC = 3            # user-supplied permutation
    MMD_ATA = 4             # minimum degree on pattern of A^T A
    COLAMD = 5              # approximate column MD directly on A


class RowPerm(enum.Enum):
    """Numerical row pivoting strategy (reference rowperm_t;
    dldperm_dist, SRC/dldperm_dist.c:95)."""

    NOROWPERM = 0
    LargeDiag_MC64 = 1      # maximum-product weighted bipartite matching
    LargeDiag_AWPM = 2      # approximate-weight perfect matching (the
                            # CombBLAS HWPM analog — perm only, no scalings)
    MY_PERMR = 3


class IterRefine(enum.Enum):
    """Iterative refinement (reference IterRefine_t; pdgsrfs.c:120)."""

    NOREFINE = 0
    SLU_SINGLE = 1
    SLU_DOUBLE = 2


class Trans(enum.Enum):
    NOTRANS = 0
    TRANS = 1
    CONJ = 2


@dataclasses.dataclass
class RecoveryPolicy:
    """Solver health & recovery policy — the pdgscon/pdgsrfs repair loop
    made automatic (PAPER.md L4/L8: GESP trades pivoting stability for
    speed, then detects and repairs the damage afterwards).

    ``enabled`` drives the escalation ladder in drivers/gssvx.py: when
    iterative refinement stagnates above ``berr_target`` the driver
    escalates residual precision, retries the correction solves on
    higher-precision factors (f64 on CPU, emulated-double df64 on f32-only
    hardware), and finally refactors with diagnostics-informed re-scaling /
    re-ordering.  Every rung is recorded in the SolveReport
    (utils/stats.py) so callers see what degraded and why the answer is
    still trustworthy.

    ``sentinels`` arms the cheap isfinite reductions on factored panels
    (numeric/factor.py, numeric/stream.py) that trip NumericBreakdownError
    at the offending supernode, and the final solution check in the driver.

    ``condest`` selects when the Hager–Higham condition estimate (rcond,
    the pdgscon analog) and the normwise forward-error bound (ferr) are
    computed: "always", "never", or "auto" (only when the ladder fired or
    tiny pivots were replaced — the cases where the answer needs defending).
    """

    enabled: bool = dataclasses.field(
        default_factory=lambda: bool(_env_int("SLU_TPU_RECOVERY", 1)))
    sentinels: bool = dataclasses.field(
        default_factory=lambda: bool(_env_int("SLU_TPU_SENTINELS", 1)))
    condest: str = "auto"              # "always" | "auto" | "never"
    berr_target: float | None = None   # None => 10·eps(residual dtype)
    max_rungs: int = 3                 # ladder depth cap


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ[name])
    except (KeyError, ValueError):
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ[name])
    except (KeyError, ValueError):
        return default


@dataclasses.dataclass
class Options:
    """Runtime options (analog of superlu_dist_options_t).

    Defaults follow set_default_options_dist (SRC/util.c:376-401):
    Fact=DOFACT, Equil=YES, ColPerm=METIS_AT_PLUS_A, RowPerm=LargeDiag_MC64,
    ReplaceTinyPivot, IterRefine=DOUBLE, PrintStat=YES.  The blocking knobs
    read the sp_ienv environment tier (SRC/sp_ienv.c:70-123) at
    construction: NREL (relax), NSUP (max supernode),
    SLU_TPU_MIN_BUCKET — so `NSUP=99 python -m superlu_dist_tpu ...`
    behaves like the reference.
    """

    fact: Fact = Fact.DOFACT
    equil: bool = True
    col_perm: ColPerm = ColPerm.ND_AT_PLUS_A
    row_perm: RowPerm = RowPerm.LargeDiag_MC64
    replace_tiny_pivot: bool = True
    iter_refine: IterRefine = IterRefine.SLU_DOUBLE
    trans: Trans = Trans.NOTRANS
    # DiagInv (reference default YES-iff-LAPACK, SRC/util.c:397-401):
    # precompute inverted diagonal blocks so device solves replace
    # triangular solves with batched GEMMs — pays off for repeated /
    # many-RHS solves.  Env SLU_TPU_DIAG_INV=1 flips the default (the
    # hardware solve-ladder sweep knob).
    diag_inv: bool = dataclasses.field(
        default_factory=lambda: bool(_env_int("SLU_TPU_DIAG_INV", 0)))
    # PStatPrint analog reachable without code: SLU_TPU_STATS=1 flips the
    # default so any driver run (CLI, examples, embedding callers) prints
    # the options banner + full Stats.report (incl. the solve-health
    # line) — see docs/OBSERVABILITY.md
    print_stat: bool = dataclasses.field(
        default_factory=lambda: bool(_env_int("SLU_TPU_STATS", 0)))
    # --- symbolic / blocking tuning (sp_ienv analogs, SRC/sp_ienv.c:70-123) ---
    # NREL: amalgamate subtrees with <= relax cols
    relax: int = dataclasses.field(
        default_factory=lambda: _env_int("NREL", 20))
    # NSUP: cap supernode width.  The reference uses 128 (CPU-cache-sized);
    # the MXU wants wider panels (SURVEY.md §7 step 10).
    max_supernode: int = dataclasses.field(
        default_factory=lambda: _env_int("NSUP", 256))
    # --- TPU-native knobs -----------------------------------------------------
    factor_dtype: str | None = None   # None => float32 on TPU, float64 on CPU
    ir_dtype: str = "float64"         # residual precision for refinement
    # fill-tolerant supernode amalgamation (symbfact.amalgamate_supernodes):
    # merged-front flops may grow up to this factor per merge.  The MXU
    # wants wide pivots; the measured padding/dispatch win dwarfs the
    # ≤ tol structural-flop cost.  0 disables (reference-style zero-fill
    # supernodes + leaf relaxation only).
    amalg_tol: float = dataclasses.field(
        default_factory=lambda: _env_float("SLU_TPU_AMALG_TOL", 1.2))
    bucket_growth: float = 1.5        # geometric padding factor for front
                                      # size buckets (static-shape batching)
    min_bucket: int = dataclasses.field(   # smallest padded front dimension
        default_factory=lambda: _env_int("SLU_TPU_MIN_BUCKET", 8))
    # shard the Schur update pool across ALL mesh devices (the n≈1M
    # memory path; only meaningful with a grid) — SLU_TPU_POOL_PARTITION=1
    pool_partition: bool = dataclasses.field(
        default_factory=lambda: bool(_env_int("SLU_TPU_POOL_PARTITION", 0)))
    # distributed analysis for the multi-process tier (the reference's
    # options->ParSymbFact: ParMETIS ordering + psymbfact): ordering and
    # symbolic work/memory partition across the ranks instead of running
    # on root (parallel/panalysis.py) — SLU_TPU_PAR_SYMB_FACT=1
    par_symb_fact: bool = dataclasses.field(
        default_factory=lambda: bool(_env_int("SLU_TPU_PAR_SYMB_FACT", 0)))
    # user-supplied permutations for MY_PERMC / MY_PERMR (real dataclass
    # fields so Options(user_perm_c=...) works — the reference reads these
    # from ScalePermstruct->perm_c/perm_r when ColPerm/RowPerm say MY_*).
    # compare=False: ndarray values would make the generated __eq__ raise.
    user_perm_c: object = dataclasses.field(default=None, compare=False)
    user_perm_r: object = dataclasses.field(default=None, compare=False)
    # solver health & recovery: condition estimation, non-finite sentinels,
    # and the automatic escalation ladder (see RecoveryPolicy)
    recovery: RecoveryPolicy = dataclasses.field(
        default_factory=RecoveryPolicy)


def set_default_options() -> Options:
    """Analog of set_default_options_dist (SRC/util.c:376).  The sp_ienv
    environment tier applies to every Options() construction (see the
    class docstring), so this is a plain constructor alias."""
    return Options()


def print_options(o: Options) -> str:
    """print_options_dist analog (SRC/util.c:405-439)."""
    lines = ["**************************************************",
             ".. options:"]
    for f in dataclasses.fields(o):
        v = getattr(o, f.name)
        if f.name in ("user_perm_c", "user_perm_r"):
            # summarize, never dump an n-entry permutation into the banner
            v = None if v is None else f"<perm len={len(v)}>"
        elif f.name == "recovery":
            v = (f"enabled={v.enabled} sentinels={v.sentinels} "
                 f"condest={v.condest}")
        lines.append(f"**    {f.name:<20s} {getattr(v, 'name', v)}")
    lines.append("**************************************************")
    return "\n".join(lines)


def default_factor_dtype() -> str:
    """float32 on TPU (no fp64 MXU), float64 elsewhere."""
    try:
        import jax
        platform = jax.default_backend()
    except Exception:  # pragma: no cover - jax always present in practice
        platform = "cpu"
    if platform == "cpu" and os.environ.get("JAX_ENABLE_X64", "").lower() not in ("0", "false"):
        import jax
        if jax.config.read("jax_enable_x64"):
            return "float64"
    return "float32"
