"""Tree broadcast/reduction engine (TreeBcast_slu / TreeReduce_slu analog).

Multi-process tests: real processes coordinate through the shared-memory
segment, mirroring how the reference tests multi-node behavior by
oversubscribing ranks on one box (SURVEY.md §4, .travis_tests.sh).
Covers both topologies: flat (n <= 8) and binary (n > 8,
TreeBcast_slu.hpp:17-29).
"""

import multiprocessing as mp
import os

import numpy as np
import pytest

from superlu_dist_tpu import native

# NOTE: per-test @pytest.mark.slow below marks the multi-process fork
# tests; the faultinject tests run in the fast tier (they use spawn
# workers and small payloads) — wired into tier-1 by design so induced
# communication faults are exercised on every CI run.
pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library unavailable")


def _worker(name, n_ranks, rank, root, q):
    # import inside the child: must not inherit initialized JAX state
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    with TreeComm(name, n_ranks, rank, max_len=64,
                  create=False) as tc:
        # 1) bcast: root sends its rank-stamped payload
        buf = np.full(8, float(rank))
        tc.bcast(buf, root=root)
        bcast_ok = bool((buf == float(root)).all())
        # 2) reduce: everyone contributes rank+1
        buf2 = np.full(8, float(rank + 1))
        tc.reduce_sum(buf2, root=root)
        # 3) a second round immediately (slot-reuse path)
        buf3 = np.full(8, 1.0)
        tc.allreduce_sum(buf3, root=root)
        q.put((rank, bcast_ok, float(buf2[0]), float(buf3[0])))


def _run(n_ranks, root):
    name = f"/slu_tree_test_{os.getpid()}_{n_ranks}_{root}"
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    owner = TreeComm(name, n_ranks, 0, max_len=64, create=True)
    try:
        ctx = mp.get_context("fork")
        q = ctx.Queue()
        procs = [ctx.Process(target=_worker,
                             args=(name, n_ranks, r, root, q))
                 for r in range(1, n_ranks)]
        for p in procs:
            p.start()
        # rank 0 participates from this process
        buf = np.full(8, 0.0)
        owner.bcast(buf, root=root)
        buf2 = np.full(8, 1.0)
        owner.reduce_sum(buf2, root=root)
        buf3 = np.full(8, 1.0)
        owner.allreduce_sum(buf3, root=root)
        results = {0: (0, bool((buf == float(root)).all()),
                       float(buf2[0]), float(buf3[0]))}
        for _ in procs:
            r = q.get(timeout=60)
            results[r[0]] = r
        for p in procs:
            p.join(timeout=60)
            assert p.exitcode == 0
    finally:
        owner.close(unlink=True)
    total = n_ranks * (n_ranks + 1) / 2.0   # sum of rank+1
    for rank, (rk, bcast_ok, red, allred) in results.items():
        assert bcast_ok, f"rank {rank} bcast payload wrong"
        if rank == root:
            assert red == total, (rank, red, total)
        assert allred == float(n_ranks), (rank, allred)


@pytest.mark.slow
def test_flat_tree_6_ranks():
    _run(6, root=0)


@pytest.mark.slow
def test_flat_tree_nonzero_root():
    _run(5, root=3)


@pytest.mark.slow
def test_binary_tree_12_ranks():
    _run(12, root=0)


@pytest.mark.slow
def test_binary_tree_nonzero_root():
    _run(10, root=7)


def _obj_payload():
    return {
        "blob": b"\x00\xff analysis \x01" * 7,        # odd length, NULs
        "big_ints": np.array([2**62 + 3, -(2**55) - 1], dtype=np.int64),
        "nan_bits": np.array([np.nan, -0.0, np.inf]),
        "sf_like": {"sn_rows": [np.arange(5), np.arange(3) * 7]},
    }


def _obj_worker(name, n_ranks, rank, root, q):
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    with TreeComm(name, n_ranks, rank, max_len=16, create=False) as tc:
        got = tc.bcast_obj(_obj_payload() if rank == root else None,
                           root=root)
        ref = _obj_payload()
        ok = (got["blob"] == ref["blob"]
              and np.array_equal(got["big_ints"], ref["big_ints"])
              and np.array_equal(got["nan_bits"], ref["nan_bits"],
                                 equal_nan=True)
              and all(np.array_equal(a, b) for a, b in
                      zip(got["sf_like"]["sn_rows"],
                          ref["sf_like"]["sn_rows"])))
        q.put((rank, ok))


@pytest.mark.slow
def test_bcast_obj_bit_exact_chunked():
    """Pickled-object broadcast (the mesh tier's analysis transport):
    bytes ride the f64 slots bit-exactly — int64 beyond 2^53 and NaN
    payloads must survive, which the mantissa ride could not carry —
    and max_len=16 forces the chunked streaming path."""
    name = f"/slu_tree_obj_{os.getpid()}"
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    n_ranks, root = 4, 1
    owner = TreeComm(name, n_ranks, 0, max_len=16, create=True)
    try:
        ctx = mp.get_context("fork")
        q = ctx.Queue()
        procs = [ctx.Process(target=_obj_worker,
                             args=(name, n_ranks, r, root, q))
                 for r in range(1, n_ranks)]
        for p in procs:
            p.start()
        got = owner.bcast_obj(None, root=root)
        assert got["blob"] == _obj_payload()["blob"]
        assert np.array_equal(got["big_ints"], _obj_payload()["big_ints"])
        for _ in procs:
            rank, ok = q.get(timeout=60)
            assert ok, f"rank {rank} payload mismatch"
        for p in procs:
            p.join(timeout=60)
            assert p.exitcode == 0
    finally:
        owner.close(unlink=True)


def test_single_rank_noop():
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    name = f"/slu_tree_solo_{os.getpid()}"
    with TreeComm(name, 1, 0, max_len=16, create=True) as tc:
        b = np.arange(4.0)
        tc.bcast(b)
        tc.reduce_sum(b)
        np.testing.assert_array_equal(b, np.arange(4.0))


# ---------------------------------------------------------------------------
# Fault injection (TreeComm wrapper — drops/duplicates/reorders + timeout-
# with-retry).  These run in the FAST tier on purpose: the distributed
# refinement loop must be exercised under induced faults on every CI run.
# ---------------------------------------------------------------------------

FAULT_SPEC = "drop=0.3,dup=0.2,reorder=0.5,delay=0.0005,seed=7"


@pytest.mark.faultinject
def test_faulty_collectives_bit_exact_single_rank():
    """Aggressive chunk faults (drop+retry, duplicate, reorder) must be
    fully masked by the retransmission layer: payloads come back
    bit-exact and the fault counters prove faults were actually
    injected."""
    from superlu_dist_tpu.parallel.treecomm import (
        FaultyTreeComm, parse_fault_spec)
    name = f"/slu_tree_fault1_{os.getpid()}"
    rng = np.random.default_rng(3)
    payload = rng.standard_normal(700)          # max_len=64 -> 11 chunks
    with FaultyTreeComm(name, 1, 0, max_len=64, create=True,
                        **parse_fault_spec(FAULT_SPEC)) as tc:
        got = tc.bcast_any(payload.copy())
        np.testing.assert_array_equal(got, payload)
        got = tc.allreduce_sum_any(payload.copy())
        np.testing.assert_array_equal(got, payload)
        blob = b"\x01\x02 fault transport \xff" * 41
        assert tc.bcast_bytes(blob) == blob
        assert sum(tc.fault_counts.values()) > 0, tc.fault_counts


def test_parse_fault_spec_rejects_unknown_knob():
    from superlu_dist_tpu.parallel.treecomm import parse_fault_spec
    with pytest.raises(ValueError):
        parse_fault_spec("dorp=0.1")
    assert parse_fault_spec(" drop=0.1, seed=3 ") == {"drop": 0.1,
                                                      "seed": 3}


def _pgsrfs_fault_worker(name, n_ranks, rank, part, b_loc, q):
    # spawn-safe: constructed via the env-gated factory so the fault
    # schedule comes from SLU_TPU_FAULTS exactly as production would
    from superlu_dist_tpu.parallel.treecomm import make_treecomm
    from superlu_dist_tpu.parallel.pgsrfs import pgsrfs
    tc = make_treecomm(name, n_ranks, rank, max_len=part.n, create=False)
    try:
        stats = {}
        x = pgsrfs(tc, part, b_loc, None, None, root=0, stats_out=stats)
        q.put((rank, x, stats["iters"], stats["berr"]))
    finally:
        tc.close()


def _run_pgsrfs(a, b, x0, solve_fn, fault_spec):
    """Run the 4-rank distributed refinement, optionally under injected
    faults; returns (x, iters, berr) from the root's view."""
    from superlu_dist_tpu.parallel.dist import distribute_rows
    from superlu_dist_tpu.parallel.treecomm import make_treecomm
    from superlu_dist_tpu.parallel.pgsrfs import pgsrfs

    nranks = 4
    n = a.n_rows
    parts = distribute_rows(a, nranks)
    b_blocks = [b[p.fst_row:p.fst_row + p.m_loc] for p in parts]
    old = os.environ.pop("SLU_TPU_FAULTS", None)
    if fault_spec:
        os.environ["SLU_TPU_FAULTS"] = fault_spec
    name = f"/slu_pgsrfs_fi_{os.getpid()}_{1 if fault_spec else 0}"
    owner = make_treecomm(name, nranks, 0, max_len=n, create=True)
    try:
        ctx = mp.get_context("spawn")   # no fork of the jax-laden parent
        q = ctx.Queue()
        procs = [ctx.Process(target=_pgsrfs_fault_worker,
                             args=(name, nranks, r, parts[r],
                                   b_blocks[r], q))
                 for r in range(1, nranks)]
        for p in procs:
            p.start()
        stats = {}
        x = pgsrfs(owner, parts[0], b_blocks[0], x0, solve_fn, root=0,
                   stats_out=stats)
        others = [q.get(timeout=180) for _ in procs]
        for p in procs:
            p.join(timeout=180)
            assert p.exitcode == 0
        for rank, xr, it_r, berr_r in others:
            np.testing.assert_allclose(xr, x, rtol=0, atol=1e-12)
            assert it_r == stats["iters"]
    finally:
        owner.close(unlink=True)
        os.environ.pop("SLU_TPU_FAULTS", None)
        if old is not None:
            os.environ["SLU_TPU_FAULTS"] = old
    return x, stats["iters"], stats["berr"]


@pytest.mark.faultinject
def test_pgsrfs_converges_under_drop_and_reorder():
    """Acceptance: the distributed refinement reaches the same berr under
    the fault-injection wrapper (drop+reorder+dup) as without it, within
    +2 iterations — the faults are masked by retransmission, never
    absorbed into the numerics."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import superlu_dist_tpu as slu
    from superlu_dist_tpu.models.gallery import poisson2d
    from superlu_dist_tpu.utils.options import IterRefine

    a = poisson2d(10)
    xtrue = np.random.default_rng(0).standard_normal(a.n_rows)
    b = a.matvec(xtrue)
    # coarse f32 factors so the distributed IR has real work to do
    opts = slu.Options(iter_refine=IterRefine.NOREFINE,
                       factor_dtype="float32")
    x0, lu, _, info = slu.gssvx(opts, a, b)
    assert info == 0

    x_ref, iters_ref, berr_ref = _run_pgsrfs(a, b, x0, lu.solve_factored,
                                             fault_spec=None)
    x_flt, iters_flt, berr_flt = _run_pgsrfs(a, b, x0, lu.solve_factored,
                                             fault_spec=FAULT_SPEC)
    eps = float(np.finfo(np.float64).eps)
    assert berr_ref <= 10 * eps, berr_ref
    # same berr (retransmission is value-preserving) within +2 iterations
    np.testing.assert_allclose(berr_flt, berr_ref, rtol=1e-6, atol=1e-15)
    assert abs(iters_flt - iters_ref) <= 2, (iters_flt, iters_ref)
    np.testing.assert_allclose(x_flt, x_ref, rtol=0, atol=1e-12)
