#!/usr/bin/env python
"""Distributed-input driver — the NR_loc path of pdgssvx (the reference's
primary input format, SRC/supermatrix.h:175-188): A and B arrive as
block-row pieces (here: distribute_rows plays the role of the example
drivers' read-and-scatter, EXAMPLE/dcreate_matrix.c:239), and the solver
consumes the distributed form directly via gssvx_dist.

For the fully multi-process version of this flow (separate processes
coordinating over shared-memory tree collectives) see
superlu_dist_tpu/parallel/pgssvx.py and tests/test_pgssvx.py.

    python examples/pddrive_dist.py [matrix.rua] [--backend cpu]
"""

import sys
import os

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from examples._common import (pin_cpu_if_requested, load_matrix, make_rhs,
                              report)


def main():
    pin_cpu_if_requested()
    import superlu_dist_tpu as slu
    from superlu_dist_tpu.parallel.dist import distribute_rows

    a, src = load_matrix()
    print(f"matrix: {src}  n={a.n_rows} nnz={a.nnz}")
    xtrue, b = make_rhs(a)
    parts = distribute_rows(a, 4)        # four block-row owners
    print("block rows:", [(p.fst_row, p.m_loc, p.nnz_loc) for p in parts])
    x, lu, stats, info = slu.gssvx_dist(slu.Options(), parts, b)
    assert info == 0, f"info={info}"
    resid = report("pddrive_dist (NR_loc input)", a, b, x, xtrue, stats)
    assert resid < 1e-10
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
