import itertools

import numpy as np
import pytest

from superlu_dist_tpu.models.gallery import random_sparse
from superlu_dist_tpu.rowperm.equil import gsequ, laqgs
from superlu_dist_tpu.rowperm.matching import maximum_product_matching
from superlu_dist_tpu.sparse.formats import coo_to_csr


def test_gsequ_scaling_makes_unit_maxima():
    rng = np.random.default_rng(0)
    a = random_sparse(30, density=0.1, seed=1)
    # make badly scaled
    a = a.row_scale(10.0 ** rng.integers(-8, 8, 30)).col_scale(
        10.0 ** rng.integers(-8, 8, 30))
    r, c, rowcnd, colcnd, amax = gsequ(a)
    scaled, equed = laqgs(a, r, c, rowcnd, colcnd, amax)
    assert equed == "B"
    d = np.abs(scaled.to_dense())
    np.testing.assert_allclose(d.max(axis=1), 1.0, rtol=1e-12)  # row maxes
    assert d.max() <= 1.0 + 1e-12


def test_laqgs_no_scaling_when_well_conditioned():
    a = random_sparse(20, density=0.2, seed=2)
    r, c, rowcnd, colcnd, amax = gsequ(a)
    _, equed = laqgs(a, r, c, rowcnd, colcnd, amax)
    assert equed == "N"


def _brute_force_best_product(d):
    n = d.shape[0]
    best = -1.0
    for p in itertools.permutations(range(n)):
        prod = np.prod([np.abs(d[p[j], j]) for j in range(n)])
        best = max(best, prod)
    return best


@pytest.mark.parametrize("seed", range(5))
def test_matching_is_max_product(seed):
    n = 6
    rng = np.random.default_rng(seed)
    # dense-ish random with some zeros
    d = rng.standard_normal((n, n)) * (rng.random((n, n)) > 0.3)
    d += np.diag(rng.standard_normal(n) * 0.01 + 0.02)  # keep nonsingular
    rows, cols = np.nonzero(d)
    a = coo_to_csr(n, n, rows, cols, d[rows, cols])
    order, r, c = maximum_product_matching(a)
    assert sorted(order) == list(range(n))
    got = np.prod([np.abs(d[order[j], j]) for j in range(n)])
    want = _brute_force_best_product(d)
    np.testing.assert_allclose(got, want, rtol=1e-9)


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_matching_scalings(dtype):
    a = random_sparse(40, density=0.08, seed=3, dtype=dtype)
    order, r, c = maximum_product_matching(a)
    b = a.row_scale(r).col_scale(c).permute(perm_r=order)
    d = np.abs(b.to_dense())
    np.testing.assert_allclose(np.diag(d), 1.0, rtol=1e-10)   # matched = ±1
    assert d.max() <= 1.0 + 1e-10                             # all <= 1


def test_matching_detects_structural_singularity():
    n = 4
    rows = np.array([0, 1, 2, 3, 0])
    cols = np.array([0, 0, 0, 0, 1])   # columns 2,3 empty
    with pytest.raises(Exception):
        maximum_product_matching(coo_to_csr(n, n, rows, cols, np.ones(5)))
