"""SLU118 clean-negative fixture: thresholds minted by utils/tols.py
(eps(dtype) x factor with provenance), out-of-band literals (exact
structural constants, overflow guards), and non-relational uses of
in-band floats are all fine."""
import numpy as np

from superlu_dist_tpu.utils import tols


def gate(res):
    return res < tols.RESID_GATE           # derived threshold


def structural(k, x):
    if k > 0.5:                            # out of band: not a tolerance
        return x / max(x, 1e-30)           # out of band (underflow guard)
    return x * 1e-9                        # in band but not compared


def close(x, ref):
    np.testing.assert_allclose(x, ref, rtol=tols.DEVICE_VS_HOST_RTOL,
                               atol=tols.DEVICE_VS_HOST_ATOL)
